"""Benchmark: the north-star design-variant sweep on the flagship model.

Workload (BASELINE.md target: 10,000 VolturnUS-S variants x 200 freq bins
< 60 s on 8 chips): per variant, the FULL pipeline — traced geometry
rebuild, ballast density trim, Newton statics equilibrium with line
search, drag-linearization fixed point, batched complex 6x6 RAO solve,
response statistics — explicitly batched over the variant batch on one
chip (vmap around the fixed-point loop is pathological on XLA:TPU, and
XLA's tiny-matrix LU custom call is replaced by a lane-batched
Gauss-Jordan kernel; see raft_tpu/ops/linalg.py).

Metric: design-variants/hour/chip at 200 frequency bins.  The 8-chip
north-star target (10k x 200 bins < 60 s) equals 75,000 variants/hour/chip.

vs_baseline: the same per-variant pipeline executed the way the reference
executes it (raft/parametersweep.py:93 — serial Python per variant;
raft/raft_model.py:918-947 — Python fixed-point loop with a per-frequency
6x6 solve; raft/raft_fowt.py:1152-1266 — node-level drag linearization),
implemented in REAL numpy node-level math (actual wave kinematics,
actual relative-velocity RMS linearization, actual drag excitation — not a
synthetic stand-in), measured on this host's CPU and extrapolated.
The reference itself cannot run here (moorpy/ccblade are not installed),
so this reference-structure serial implementation is the measured stand-in;
it is GENEROUS to the baseline (statics are computed with the vectorized
kernels rather than the reference's per-member Python loops).

Prints ONE json line.
"""
import json
import os
import time

# TPU has no float64 — run the benchmark in f32/c64 (must be set before any
# raft_tpu import; accuracy-critical CPU runs keep the default x64)
os.environ.setdefault("RAFT_TPU_X64", "0")

import numpy as np

NW = int(os.environ.get("RAFT_BENCH_NW", 200))   # north-star bins
NV = int(os.environ.get("RAFT_BENCH_NV", 1024))  # variants per batch
NW2 = int(os.environ.get("RAFT_BENCH_NW2", 50))  # QTF pair-grid bins
NITER = 10        # drag-linearization iterations (VolturnUS-S setting)


def _design():
    from raft_tpu.io.designs import load_design
    return load_design("VolturnUS-S")


def _base_fowt(design):
    from raft_tpu.models.fowt import build_fowt
    w = np.arange(1, NW + 1) * 0.002 * 2 * np.pi
    return build_fowt(design, w, depth=float(design["site"]["water_depth"]))


def _aero_constants(design, base):
    """Frozen per-case aero for the sweep: calcTurbineConstants at the
    zero-offset pose from the BASE rotor (the reference evaluates the
    same constants per sweep point, raft_model.py:527-556; rotor geometry
    does not vary across the VolturnUS-S platform sweep, so one
    evaluation serves every variant).  Returns mean aero force F_env (6,),
    A_turb (6,6,nw) and B_turb (6,6,nw) incl. gyroscopic damping."""
    import jax

    if jax.default_backend() != "cpu":
        # one-time host-side build work: the BEM induction solve runs
        # eager jnp ops the axon TPU tunnel does not implement — compute
        # in a CPU subprocess and ship the small constant tensors back.
        # MUST be f64: in f32 the induction bracket test mis-signs and
        # thrust collapses ~400x (root cause of BENCH_r03's 35%-median
        # on-TPU deviation; see rotor.f64_host)
        return _aero_constants_subprocess(design)
    from raft_tpu.models.fowt import fowt_turbine_constants

    case = dict(zip(design["cases"]["keys"], design["cases"]["data"][0]))
    tc = fowt_turbine_constants(base, case, np.zeros(6))
    F_env = np.sum(np.asarray(tc["f_aero0"]), axis=1)
    A_turb = np.sum(np.asarray(tc["A_aero"]), axis=3)
    B_turb = (np.sum(np.asarray(tc["B_aero"]), axis=3)
              + np.sum(np.asarray(tc["B_gyro"]), axis=2)[:, :, None])
    return F_env, A_turb, B_turb


def _run_cpu_subprocess(body_lines, out_path, x64):
    """Run a snippet in a fresh CPU-only jax process (the axon tunnel is
    single-claim and lacks some eager ops) and return the .npz it
    writes.  Sole remaining caller: ``_aero_constants_subprocess`` (a
    TPU-has-no-f64 CONSTANT builder, not an accuracy reference) — the
    f64 accuracy-reference subprocesses died with the mixed-precision
    ladder (RAFT_TPU_PRECISION=mixed is the accuracy contract; see
    ``_accuracy_gate`` / ``_analyze_cases_metric``)."""
    import subprocess
    import sys

    here = os.path.dirname(os.path.abspath(__file__))
    code = "\n".join([
        "import os, numpy as np",
        f"import sys; sys.path.insert(0, {here!r})",
        "import jax; jax.config.update('jax_platforms', 'cpu')",
        "import bench",
    ] + body_lines)
    env = dict(os.environ, RAFT_TPU_X64="1" if x64 else "0",
               JAX_PLATFORMS="cpu", PALLAS_AXON_POOL_IPS="")
    r = subprocess.run([sys.executable, "-c", code], env=env,
                       capture_output=True, text=True, timeout=1800)
    if r.returncode != 0:
        raise RuntimeError("cpu subprocess failed:\n" + r.stderr[-500:])
    return np.load(out_path)


def _aero_constants_subprocess(design):
    import json as _json
    import tempfile

    # the child rebuilds the module-default design (NW rides the
    # RAFT_BENCH_NW env var it inherits) — guard against a caller passing
    # anything else, which would silently get constants for the wrong model
    if _json.dumps(design, sort_keys=True, default=str) != _json.dumps(
            _design(), sort_keys=True, default=str):
        raise ValueError("_aero_constants on a non-CPU backend only "
                         "supports the module-default design")
    with tempfile.TemporaryDirectory() as td:
        out = os.path.join(td, "aero.npz")
        d = _run_cpu_subprocess([
            "design = bench._design()",
            "base = bench._base_fowt(design)",
            "F_env, A_turb, B_turb = bench._aero_constants(design, base)",
            f"np.savez({out!r}, F_env=F_env, A_turb=A_turb, B_turb=B_turb)",
        ], out, x64=True)
        return d["F_env"], d["A_turb"], d["B_turb"]


def _thetas(design, base, nv, seed=7):
    """nv geometry variants sampled over the parametersweep factor range."""
    from raft_tpu.parallel.variants import volturn_grid
    thetas, _ = volturn_grid(design, factors=(0.85, 1.0, 1.15))
    n0 = len(thetas["rA0"])
    rng = np.random.default_rng(seed)
    idx = rng.integers(0, n0, nv)
    return {k: np.asarray(v)[idx] for k, v in thetas.items()}


def _want_tpu():
    """True when this process is expected to land on the TPU backend."""
    if os.environ.get("RAFT_BENCH_FORCE_CPU") == "1":
        return False
    return os.environ.get("JAX_PLATFORMS", "") not in ("cpu",)


def _tpu_probe(timeout_s=None, retries=None, backoff_s=None):
    """Probe TPU backend init in a SUBPROCESS with a hard timeout.

    The axon tunnel has a documented failure mode where a stale remote
    claim makes every in-process backend init hang forever inside
    make_c_api_client (ROUND4_NOTES.md) — so the probe must run
    out-of-process where a hang is boundable.  Retries with backoff
    because the remote lease can expire between attempts.

    Returns (ok: bool, info: dict) where ``info["attempts"]`` is a list
    of structured ``raft_tpu.obs.ProbeAttempt`` records (start/end
    timestamps, timeout used, outcome, exception class) — these land in
    the run manifest's ``probe_attempts`` so five rounds of
    ``tpu_unavailable`` are diagnosable from data, not prose."""
    import subprocess
    import sys

    from raft_tpu.obs import ProbeAttempt
    from raft_tpu.obs.manifest import _utcnow

    timeout_s = timeout_s or int(os.environ.get("RAFT_BENCH_PROBE_TIMEOUT", 240))
    retries = retries or int(os.environ.get("RAFT_BENCH_PROBE_RETRIES", 3))
    backoff_s = backoff_s or int(os.environ.get("RAFT_BENCH_PROBE_BACKOFF", 90))
    code = ("import jax, jax.numpy as jnp;"
            "d = jax.devices();"
            "y = (jnp.ones((128,128)) @ jnp.ones((128,128)))"
            ".block_until_ready();"
            "print('PROBE_OK', jax.default_backend(), len(d))")
    attempts = []
    for i in range(retries):
        if i:
            time.sleep(backoff_s)
        att = ProbeAttempt(index=i, started_at=_utcnow(),
                           timeout_s=float(timeout_s))
        try:
            r = subprocess.run([sys.executable, "-c", code],
                               capture_output=True, text=True,
                               timeout=timeout_s)
            att.finished_at = _utcnow()
            if r.returncode == 0 and "PROBE_OK" in r.stdout:
                line = next(ln for ln in r.stdout.splitlines()
                            if "PROBE_OK" in ln)
                # a silent CPU fallback must NOT pass as a hardware
                # probe: the published number would be a CPU timing
                if line.split()[1] == "cpu":
                    att.outcome = "cpu-fallback"
                    att.message = line
                    attempts.append(att.to_dict())
                    continue
                att.outcome = "ok"
                att.message = line
                attempts.append(att.to_dict())
                return True, {"attempts": attempts, "probe": line}
            att.outcome = "error"
            att.error_class = ("CalledProcessError" if r.returncode
                               else "ProbeOutputMissing")
            att.message = (r.stderr.strip().splitlines()[-1]
                           if r.stderr.strip() else f"rc={r.returncode}")
        except subprocess.TimeoutExpired:
            att.finished_at = _utcnow()
            att.outcome = "timeout"
            att.error_class = "TimeoutExpired"
            att.message = (f"no backend after {timeout_s}s "
                           "(stale-claim tunnel wedge?)")
        attempts.append(att.to_dict())
    return False, {"attempts": attempts}


def _obs_default():
    """The bench writes a run manifest on EVERY invocation: default the
    obs output directory to ./obs_runs next to this file when neither
    ``obs.configure()`` nor ``RAFT_TPU_OBS_DIR`` chose one."""
    from raft_tpu import obs
    if obs.out_dir() is None:
        obs.configure(os.path.join(
            os.path.dirname(os.path.abspath(__file__)), "obs_runs"))
    return obs


def _emit_tpu_unavailable(info, manifest=None):
    """Structured bench result when the TPU backend cannot initialize:
    diagnosable JSON (not a traceback) + the CPU-mode f32-vs-f64
    accuracy gate so the round still records a correctness signal.
    The run manifest is written here too (status ``tpu_unavailable``)
    with the structured probe-attempt records attached."""
    import subprocess
    import sys

    obs = _obs_default()
    if manifest is None:                              # direct-call safety
        manifest = obs.RunManifest.begin(kind="bench", devices=False)
    gate = None
    try:
        env = dict(os.environ, JAX_PLATFORMS="cpu", RAFT_TPU_X64="0",
                   RAFT_BENCH_GATE_ONLY="1", PALLAS_AXON_POOL_IPS="")
        r = subprocess.run([sys.executable, os.path.abspath(__file__)],
                           env=env, capture_output=True, text=True,
                           timeout=3600)
        if r.returncode in (0, 1) and r.stdout.strip():
            gate = json.loads(r.stdout.strip().splitlines()[-1])
        else:
            gate = {"error": r.stderr.strip().splitlines()[-1]
                    if r.stderr.strip() else f"rc={r.returncode}"}
    except Exception as e:                            # pragma: no cover
        gate = {"error": f"{type(e).__name__}: {e}"}
    for att in info.get("attempts", []):
        manifest.add_probe_attempt(att)
    manifest.extra["cpu_accuracy_gate"] = gate
    self_cmp = _self_compare(obs, manifest, "tpu_unavailable")
    paths = obs.finish_run(manifest, status="tpu_unavailable",
                           write_trace=False)
    result = {
        "metric": "design-variants/hour/chip (TPU backend unavailable — "
                  "no hardware number this run)",
        "value": 0.0,
        "unit": "variants/h/chip",
        "vs_baseline": 0.0,
        "ok": False,
        "reason": "tpu_unavailable",
        "probe": info,
        "cpu_accuracy_gate": gate,
        "self_compare": self_cmp,
        "manifest": paths["manifest"],
    }
    print(json.dumps(result))
    raise SystemExit(1)


def _previous_manifest(obs, current_run_id, config=None):
    """Newest previously-written COMPARABLE bench manifest in the obs
    directory (the self-compare baseline), or None on the first run.

    Comparable = status "ok" with the same bench config: a healthy run
    after a ``tpu_unavailable`` round (or after resizing via
    RAFT_BENCH_NV etc.) must not be reported as a regression against an
    incomparable baseline."""
    import glob

    d = obs.out_dir()
    if not d or not os.path.isdir(d):
        return None
    cands = [p for p in glob.glob(os.path.join(d, "bench_*.manifest.json"))
             if current_run_id not in os.path.basename(p)]
    cands.sort(key=os.path.getmtime, reverse=True)
    for p in cands:
        try:
            with open(p) as f:
                doc = json.load(f)
        except (OSError, json.JSONDecodeError):
            continue
        if doc.get("status") != "ok":
            continue
        if config is not None and doc.get("config") != config:
            continue
        return os.path.basename(p), doc
    return None


def _self_compare(obs, manifest, status):
    """Regression-sentinel hook: compare THIS run's manifest against the
    previous bench manifest in the obs dir and embed the verdict in
    ``manifest.extra["self_compare"]`` (and the printed bench JSON).
    Numeric facts at 1e-6, wall-time facts at the loose perf tolerance;
    never raises — a broken baseline must not take down the bench."""
    try:
        prev = _previous_manifest(obs, manifest.run_id,
                                  config=dict(manifest.config))
        if prev is None:
            verdict = {"baseline": None, "ok": None,
                       "note": "no comparable previous bench manifest"}
        else:
            name, prev_doc = prev
            manifest.finish(status)       # re-stamped by finish_run later
            report = obs.compare_manifests(prev_doc, manifest.to_dict())
            verdict = {"baseline": name, "ok": report["ok"],
                       "n_compared": report["n_compared"],
                       "n_regressions": len(report["regressions"]),
                       "regressions": report["regressions"][:10]}
    except Exception as e:                            # pragma: no cover
        verdict = {"baseline": None, "ok": None,
                   "note": f"self-compare failed: {type(e).__name__}: {e}"}
    manifest.extra["self_compare"] = verdict
    return verdict


def _solver_setup(nv):
    """Shared bench workload setup (design, base model, nv variant
    thetas, jitted batched solver) — ONE definition so the TPU bench and
    the CPU fallback gate always measure the same pipeline."""
    import jax

    from raft_tpu.parallel.variants import make_variant_solver

    design = _design()
    base = _base_fowt(design)
    thetas = _thetas(design, base, nv)
    F_env, A_turb, B_turb = _aero_constants(design, base)
    solver = make_variant_solver(base, Hs=6.0, Tp=12.0, ballast=True,
                                 F_env=F_env, A_turb=A_turb, B_turb=B_turb,
                                 nIter=NITER, tol=-1.0,  # full iterations
                                 newton_iters=10)
    return design, base, thetas, jax.jit(solver.batched), A_turb, B_turb


def _acc_ok(acc):
    return (isinstance(acc, dict)
            and acc["median"] <= ACC_MEDIAN_TOL
            and acc["surge_max"] <= ACC_SURGE_TOL)


def _gate_only():
    """CPU-mode accuracy gate (f32 pipeline vs the in-process
    mixed-ladder f64-refined truth) on the fixed 16-variant batch; the
    fallback correctness record when the TPU is unavailable.  Prints
    one JSON line."""
    _, _, thetas, batched, _, _ = _solver_setup(16)
    acc = _accuracy_gate(thetas, batched)
    ok = _acc_ok(acc)
    print(json.dumps({"device": "cpu", "rel_dev_f32_vs_f64": acc,
                      "ok": ok}))
    if not ok:
        raise SystemExit(1)


def main():
    import jax

    if os.environ.get("RAFT_BENCH_GATE_ONLY") == "1":
        return _gate_only()

    # environment is captured WITHOUT touching jax.devices() here — an
    # in-process backend query can hang forever on the wedged tunnel;
    # it is re-captured with device facts once the backend is known good
    obs = _obs_default()
    obs.install_jax_hooks()
    manifest = obs.RunManifest.begin(kind="bench", devices=False, config={
        "NW": NW, "NV": NV, "NW2": NW2, "NITER": NITER,
        "want_tpu": _want_tpu()})

    if _want_tpu():
        with obs.span("bench_tpu_probe"):
            ok, info = _tpu_probe()
        if not ok:
            return _emit_tpu_unavailable(info, manifest)
        for att in info.get("attempts", []):
            manifest.add_probe_attempt(att)

    status = "failed"
    try:
        with obs.span("bench_setup", nv=NV):
            design, base, thetas, batched, A_turb, B_turb = _solver_setup(NV)
        manifest.environment = obs.capture_environment()   # backend is up

        with obs.span("bench_warmup_compile", nv=NV):
            # devprof stamps the warmup-compile profile (wall seconds,
            # static-HLO FLOPs/bytes, watermark delta) into the
            # manifest and the raft_tpu_devprof_* gauges — the roofline
            # arithmetic intensity rides the bench row from here
            prof = obs.devprof.start("bench_variant_pipeline")
            lowered = batched.lower(thetas)
            out = batched(thetas)   # compile + warmup
            jax.block_until_ready(out["std"])
            devprof_facts = prof.finish(lowered=lowered)
        obs.devprof.attach(manifest, devprof_facts)
        # distinct variant batches per rep: the axon tunnel memoizes
        # repeated identical (program, inputs) executions, which would
        # fake the timing
        reps = 3
        batches = [_thetas(design, base, NV, seed=100 + r)
                   for r in range(reps)]
        with obs.span("bench_timed_reps", reps=reps, nv=NV):
            t0 = time.perf_counter()
            for r in range(reps):
                out = batched(batches[r])
                jax.block_until_ready(out["std"])
            dt = (time.perf_counter() - t0) / reps
        variants_per_hour = NV / dt * 3600.0

        with obs.span("bench_serial_baseline"):
            baseline_vph = _serial_numpy_baseline(base, A_turb, B_turb)

        with obs.span("bench_accuracy_gate"):
            acc = _accuracy_gate(thetas, batched)

        with obs.span("bench_qtf_metric", nw2=NW2):
            qtf = _qtf_metric()

        with obs.span("bench_analyze_cases"):
            ac = _analyze_cases_metric()

        dev = jax.devices()[0]
        acc_ok = _acc_ok(acc)
        # a QTF-kernel regression must be visible at the JSON level, not
        # buried in an error string (VERDICT r4 weak #5)
        qtf_ok = isinstance(qtf, dict)
        # solver-backend + executable-cache + fixed-point facts: which
        # kernel actually solved the impedance systems, and whether the
        # warm-start machinery engaged (docs/performance.md)
        from raft_tpu import _config as _cfg
        from raft_tpu.ops import linalg as _linalg
        from raft_tpu.parallel import exec_cache as _exec_cache
        solver_facts = {
            "dispatch": _linalg.last_dispatch(),
            "pallas_mode": _cfg.pallas_mode(),
            "exec_cache": {"enabled": _exec_cache.enabled(),
                           **_exec_cache.stats()},
            "fixed_point_chunks_run": int(np.asarray(out["fp_chunks"]))
            if "fp_chunks" in out else None,
        }
        manifest.extra["solver"] = solver_facts
        result = {
            "metric": f"design-variants/hour/chip ({NW}-bin VolturnUS-S "
                      f"variant pipeline incl. frozen aero "
                      f"added-mass/damping/gyro + mean-thrust statics: "
                      f"geometry+ballast+statics+dynamics, "
                      f"f32, device={dev.platform}; north-star 8-chip "
                      f"target=75000/h/chip)",
            "value": round(variants_per_hour, 1),
            "unit": "variants/h/chip",
            "vs_baseline": round(variants_per_hour / baseline_vph, 2),
            "rel_dev_f32_vs_f64": acc,
            "accuracy_gate": {"median_tol": ACC_MEDIAN_TOL,
                              "surge_max_tol": ACC_SURGE_TOL, "ok": acc_ok},
            "qtf_pairgrid": qtf,
            "qtf_ok": qtf_ok,
            "analyze_cases": ac,
            "solver": solver_facts,
            "devprof": {k: devprof_facts.get(k)
                        for k in ("compile_s", "flops", "bytes_accessed",
                                  "arithmetic_intensity")},
            "ok": acc_ok and qtf_ok,
        }
        status = "ok" if result["ok"] else "failed"
        manifest.extra["result"] = {
            "value": result["value"], "vs_baseline": result["vs_baseline"],
            "ok": result["ok"]}
        if isinstance(ac, dict):
            # per-case wall time of the flagship analyzeCases path —
            # a perf-class manifest fact (obsctl trend / self-compare)
            manifest.extra["result"]["analyze_cases_s_per_case"] = \
                ac["s_per_case"]
        result["self_compare"] = _self_compare(obs, manifest, status)
    finally:
        paths = obs.finish_run(manifest, status=status)
    result["manifest"] = paths["manifest"]
    print(json.dumps(result))
    if not result["ok"]:
        raise SystemExit(1)   # a fast-but-wrong number is not a result


#: hard accuracy thresholds: the bench FAILS (exit 1, "ok": false) if the
#: on-hardware f32 response stds deviate from the f64 truth beyond these
ACC_MEDIAN_TOL = 1e-4
ACC_SURGE_TOL = 1e-3


def _qtf_metric():
    """Single-chip throughput of the raw slender-body QTF pair kernel —
    the reference's self-identified hottest kernel (raft_model.py:980-984)
    and this framework's context-parallel axis (calc_qtf_sharded shards
    the w1-row dimension).  Times the jitted NW2-row pair-grid evaluation
    (all Pinkster terms; Kim&Yue + Hermitian completion excluded — they
    are O(nw2) and O(nw2^2) elementwise postprocessing) at 3 distinct
    headings (the axon tunnel memoizes identical executions).  Returns a
    dict for the bench JSON, or an error string — which main() surfaces
    as qtf_ok=false and a failed bench."""
    import contextlib

    import jax
    import jax.numpy as jnp

    try:
        from raft_tpu.models import qtf as qt
        from raft_tpu.models.fowt import build_fowt, fowt_pose

        design = _design()
        design["platform"]["potSecOrder"] = 1
        design["platform"]["min_freq2nd"] = float(np.round(
            0.25 / NW2, 6))
        design["platform"]["max_freq2nd"] = 0.25
        w = np.arange(1, NW + 1) * 0.002 * 2 * np.pi
        try:
            ctx = jax.default_device(jax.local_devices(backend="cpu")[0])
        except Exception:
            ctx = contextlib.nullcontext()
        with ctx:   # host-side build + concrete pose (waterline geometry)
            fowt = build_fowt(design, w,
                              depth=float(design["site"]["water_depth"]))
            pose = fowt_pose(fowt, np.zeros(6))
        nw2 = len(fowt.w1_2nd)
        rows = jnp.arange(nw2)

        fn = jax.jit(lambda r, b: qt.calc_qtf_slender_body(
            fowt, pose, b, rows=r))
        jax.block_until_ready(fn(rows, 0.0))          # compile + warmup
        betas = (0.1, 0.2, 0.3)
        t0 = time.perf_counter()
        for b in betas:
            jax.block_until_ready(fn(rows, b))
        dt = (time.perf_counter() - t0) / len(betas)
        return {"pair_entries_per_s": round(nw2 * nw2 / dt, 1),
                "nw2": nw2, "wall_s": round(dt, 4)}
    except Exception as e:                            # pragma: no cover
        return f"qtf metric failed: {type(e).__name__}: {e}"


def _f64_scope():
    """Context pieces for the in-process f64-contract sections: a
    scoped x64 enable plus a CPU device pin when the bench itself runs
    on an accelerator backend (TPU has no native f64 — the refinement
    accumulator needs a device that does).  This replaces the f64 CPU
    *subprocess* the accuracy references used to fork."""
    import contextlib

    import jax
    from jax.experimental import enable_x64

    try:
        dev = (jax.default_device(jax.local_devices(backend="cpu")[0])
               if jax.default_backend() != "cpu"
               else contextlib.nullcontext())
    except Exception:                                 # pragma: no cover
        dev = contextlib.nullcontext()
    return enable_x64(), dev


def _analyze_cases_metric():
    """Wall time per case through the flagship device-resident
    ``Model.analyzeCases`` path (coarse OC3 golden config, one case,
    cold start) — the ``analyze_cases_s_per_case`` fact ``obsctl trend``
    tracks across rounds.  Runs IN-PROCESS under a scoped x64 enable
    (the case pipeline's accuracy contract rides the precision ladder;
    the f64 CPU subprocess this used to fork is gone).  Returns a dict
    for the bench JSON, or an error string."""
    from raft_tpu.io.designs import load_design
    from raft_tpu.model import Model
    from raft_tpu.ops import linalg as _linalg

    x64_ctx, dev_ctx = _f64_scope()
    try:
        with x64_ctx, dev_ctx:
            design = load_design("OC3spar")
            design.setdefault("settings", {})
            design["settings"].update(min_freq=0.02, max_freq=0.2)
            design["cases"]["data"] = design["cases"]["data"][:1]
            m = Model(design)
            t0 = time.perf_counter()
            m.analyzeCases()
            dt = time.perf_counter() - t0
            x = (m.last_manifest.extra or {}).get(
                "host_transfers", {}).get("total", {})
    except Exception as e:                            # pragma: no cover
        return f"analyze_cases metric failed: {type(e).__name__}: {e}"
    return {"s_per_case": round(float(dt), 3), "n_cases": 1,
            "design": "OC3spar",
            "host_transfer_events": int(x.get("events", -1)),
            "host_transfer_bytes": int(x.get("bytes", -1)),
            "solver": _linalg.last_dispatch()}


def _accuracy_gate(thetas, batched):
    """On-hardware f32 accuracy vs the mixed-precision-ladder re-solve
    of the SAME fixed 16-variant batch (BASELINE's accuracy target is
    meaningless without a measured on-hardware number).

    The reference is the SAME pipeline re-built in-process at f64 under
    ``RAFT_TPU_PRECISION=mixed`` — low-width factorization with
    in-kernel f64 residual refinement and per-lane promotion
    (ops/pallas/gj_solve.py) — i.e. the on-device ladder IS the
    accuracy contract.  The f64 CPU subprocess this used to fork is
    gone."""
    import jax

    from raft_tpu import _config
    from raft_tpu.ops import linalg as _linalg

    sub = {k: np.asarray(v)[:16] for k, v in thetas.items()}
    out32 = batched(sub)
    std32 = np.asarray(out32["std"], dtype=np.float64)
    x64_ctx, dev_ctx = _f64_scope()
    _config.set_precision_mode("mixed")
    try:
        with x64_ctx, dev_ctx:
            from raft_tpu.parallel.variants import make_variant_solver

            design = _design()
            base = _base_fowt(design)
            F_env, A_turb, B_turb = _aero_constants(design, base)
            solver = make_variant_solver(
                base, Hs=6.0, Tp=12.0, ballast=True, F_env=F_env,
                A_turb=A_turb, B_turb=B_turb, nIter=NITER, tol=-1.0,
                newton_iters=10)
            out = jax.jit(solver.batched)(
                {k: np.asarray(v, np.float64) for k, v in sub.items()})
            std64 = np.asarray(out["std"], dtype=np.float64)
            ref_solver = _linalg.last_dispatch()
    except Exception as e:                            # pragma: no cover
        return f"mixed-ladder reference failed: {type(e).__name__}: {e}"
    finally:
        _config.set_precision_mode(None)
    # unit-safe masking: translations (m) and rotations (rad) are scaled
    # within their own unit group, each channel against its own batch
    # peak — a channel whose peak is itself fp noise (exact-zero response
    # by symmetry) is excluded entirely, but a genuinely responding
    # small-magnitude channel is kept
    dev = np.abs(std32 - std64) / np.maximum(np.abs(std64), 1e-12)
    mask = np.zeros_like(dev, dtype=bool)
    for grp in (slice(0, 3), slice(3, 6)):
        gscale = np.abs(std64[:, grp]).max()
        for j in range(grp.start, grp.stop):
            peak = np.abs(std64[:, j]).max()
            if peak > 1e-4 * gscale:
                mask[:, j] = np.abs(std64[:, j]) > 1e-3 * peak
    if not mask.any():
        return "accuracy gate degenerate: every channel masked as noise"
    return {
        "max": float(dev[mask].max()),
        "median": float(np.median(dev[mask])),
        "surge_max": float(dev[:, 0].max()),
        "reference": "mixed_ladder",
        "reference_solver": ref_solver,
    }


def _serial_numpy_baseline(fowt, A_turb=None, B_turb=None):
    # NOTE: the baseline times the per-variant DYNAMICS pipeline (the
    # dominant cost); the mean-thrust statics term has no per-iteration
    # cost impact and is omitted here
    """Reference-structure serial pipeline in real numpy node-level math.

    Mirrors raft_model.py:918-947: per variant, nIter drag-linearization
    passes, each doing the actual node-level relative-velocity RMS
    linearization (raft_fowt.py:1152-1266), the actual linearized drag
    excitation (:1270-1293), and a Python loop of nw complex 6x6 solves.
    Wave kinematics and strip excitation are the real formulas evaluated
    in numpy.  Statics/added-mass use the vectorized kernels once
    (generous: the reference loops members/nodes in Python there too).
    """
    from raft_tpu.models.fowt import (fowt_pose, fowt_statics,
                                      fowt_hydro_constants)
    from raft_tpu.ops.spectra import jonswap

    nw = len(fowt.w)
    w = np.asarray(fowt.w)
    k = np.asarray(fowt.k)
    dw = w[1] - w[0]
    rho = fowt.rho_water
    h = fowt.depth

    pose = fowt_pose(fowt, np.zeros(6))
    stat = fowt_statics(fowt, pose)
    hc = fowt_hydro_constants(fowt, pose)
    M = np.asarray(stat["M_struc"]) + np.asarray(hc["A_hydro_morison"])
    C = np.asarray(stat["C_struc"]) + np.asarray(stat["C_hydro"])
    A_t = np.zeros((6, 6, nw)) if A_turb is None else np.asarray(A_turb)
    B_t = np.zeros((6, 6, nw)) if B_turb is None else np.asarray(B_turb)
    from raft_tpu.models import mooring as mr
    if fowt.mooring is not None:
        C = C + np.asarray(mr.coupled_stiffness(fowt.mooring, np.zeros(6)))

    r = np.asarray(pose["r"])
    q = np.asarray(pose["q"])
    p1 = np.asarray(pose["p1"])
    p2 = np.asarray(pose["p2"])
    qMat = np.asarray(pose["qMat"])
    p1Mat = np.asarray(pose["p1Mat"])
    p2Mat = np.asarray(pose["p2Mat"])
    nd = fowt.nodes
    N = r.shape[0]
    offsets = r  # PRP at origin

    # real wave kinematics at the nodes (helpers.py:105-154 math)
    S = np.asarray(jonswap(w, 6.0, 12.0))
    zeta = np.sqrt(2.0 * S * dw).astype(complex)
    z = r[:, 2]
    kz = np.outer(z, k)
    kh = k * h
    # overflow-stable cosh/sinh ratios (same algebra as ops/waves.py):
    # cosh(kz+kh)/cosh(kh) = (e^{kz} + e^{-kz-2kh}) / (1 + e^{-2kh})
    e1 = np.exp(np.minimum(kz, 0.0))
    e2 = np.exp(-kz - 2.0 * kh[None, :])
    den = 1.0 + np.exp(-2.0 * kh)[None, :]
    c_r = (e1 + e2) / den
    s_r = (e1 - e2) / den
    wet = (z <= 0.0)[:, None]
    phase = np.exp(-1j * np.outer(r[:, 0], k))
    zn = zeta[None, :] * phase
    u = np.stack([w * zn * c_r, np.zeros_like(zn), 1j * w * zn * s_r], axis=1)
    u *= wet[:, None, :]
    ud = 1j * w[None, None, :] * u
    pDyn = np.where(wet, rho * 9.81 * zn * c_r, 0.0)

    # strip inertial excitation (raft_fowt.py:1098-1124 math)
    Imat = np.asarray(hc["Imat"])
    a_i = np.asarray(hc["a_i"])
    F_nodes = (np.einsum("nij,njw->niw", Imat, ud)
               + pDyn[:, None, :] * (a_i[:, None] * q)[:, :, None])
    F_iner = np.zeros((6, nw), complex)
    F_iner[:3] = F_nodes.sum(axis=0)
    F_iner[3:] = np.cross(offsets[:, :, None], F_nodes,
                          axisa=1, axisb=1, axisc=1).sum(axis=0)

    sub = (r[:, 2] < 0.0).astype(float)
    c_lin = np.sqrt(8.0 / np.pi) * 0.5 * rho
    a_i_q = np.asarray(nd.a_i_q) * np.asarray(nd.Cd_q)
    a_i_p1 = np.asarray(nd.a_i_p1) * np.asarray(nd.Cd_p1)
    a_i_p2 = np.asarray(nd.a_i_p2) * np.asarray(nd.Cd_p2)
    a_i_end = np.asarray(nd.a_i_end_drag) * np.asarray(nd.Cd_End)

    nmeas = 2
    t0 = time.perf_counter()
    for _ in range(nmeas):
        Xi = np.zeros((6, nw), complex)
        # NITER+1 passes, matching both the reference (nIter+1 loop,
        # raft_model.py:862/918) and the measured TPU pipeline
        for _ in range(NITER + 1):
            # node velocities from platform motion (helpers.py:66-101)
            vn = 1j * w[None, None, :] * (
                Xi[None, :3, :]
                + np.cross(np.broadcast_to(Xi[3:, :].T[:, None, :], (nw, N, 3)),
                           r[None, :, :], axisa=2, axisb=2).transpose(1, 2, 0))
            vrel = u - vn
            # real stochastic linearization (raft_fowt.py:1205-1250)
            vq = np.einsum("ncw,nc->nw", vrel, q)
            vrel_q = vq[:, None, :] * q[:, :, None]
            vrel_p = vrel - vrel_q
            vRMS_q = np.sqrt(0.5 * np.sum(np.abs(vrel_q)**2, axis=(1, 2)))
            vRMS_p = np.sqrt(0.5 * np.sum(np.abs(vrel_p)**2, axis=(1, 2)))
            Bmat = (c_lin * (vRMS_q * (a_i_q + a_i_end))[:, None, None] * qMat
                    + c_lin * (vRMS_p * a_i_p1)[:, None, None] * p1Mat
                    + c_lin * (vRMS_p * a_i_p2)[:, None, None] * p2Mat)
            Bmat *= sub[:, None, None]
            B = np.zeros((6, 6))
            B[:3, :3] = Bmat.sum(axis=0)
            mom = np.einsum("nab,nbc->nac",
                            _skew(offsets), Bmat)
            B[3:, :3] = mom.sum(axis=0)
            B[:3, 3:] = -np.einsum("nab,nbc->nac", Bmat,
                                   _skew(offsets)).sum(axis=0)
            B[3:, 3:] = -np.einsum("nab,nbc,ncd->nad", _skew(offsets), Bmat,
                                   _skew(offsets)).sum(axis=0)
            # real drag excitation (raft_fowt.py:1270-1293)
            Fd_nodes = np.einsum("nij,njw->niw", Bmat, u)
            F_drag = np.zeros((6, nw), complex)
            F_drag[:3] = Fd_nodes.sum(axis=0)
            F_drag[3:] = np.cross(offsets[:, :, None], Fd_nodes,
                                  axisa=1, axisb=1, axisc=1).sum(axis=0)
            F = F_iner + F_drag
            # the reference's per-frequency solve loop (raft_model.py:942-947)
            for iw in range(nw):
                Z = (-w[iw]**2 * (M + A_t[:, :, iw])
                     + 1j * w[iw] * (B + B_t[:, :, iw]) + C)
                Xi[:, iw] = np.linalg.solve(Z, F[:, iw])
    dt = (time.perf_counter() - t0) / nmeas
    return 3600.0 / dt


def _skew(v):
    O = np.zeros(len(v))
    return np.stack([
        np.stack([O, -v[:, 2], v[:, 1]], axis=1),
        np.stack([v[:, 2], O, -v[:, 0]], axis=1),
        np.stack([-v[:, 1], v[:, 0], O], axis=1),
    ], axis=1)


# ---------------------------------------------------------------------------
# `bench.py serve` — sustained serving throughput (open-loop arrivals)
# ---------------------------------------------------------------------------

def serve_bench(runner_factory=None, *, design="Vertical_cylinder",
                n_requests=None, rps=None, batch_cases=4, seed=2026,
                dup_ratio=None, store_dir=None, timeout_s=600.0):
    """Drive a :class:`raft_tpu.serve.SweepService` with a seeded
    OPEN-LOOP arrival process (exponential inter-arrivals at ``rps``
    requests/s, submitted on schedule whether or not earlier requests
    finished — the arrival law of independent callers, not a closed
    benchmark loop) and report sustained-serving facts:

    - ``cases_per_min`` — completed requests per wall minute;
    - ``admission_p50_s`` / ``admission_p99_s`` — latency of the
      ``submit()`` admission edge itself (the WAL-write + queue-check
      path a caller blocks on, NOT the solve);
    - ``batch_fill_ratio`` — completed / (batches x batch size): how
      well the coalescing window packs the warm program under this
      arrival rate (1.0 = every batch full);
    - ``queue_depth_p50`` / ``queue_depth_p99`` and
      ``quota_pressure`` (shed arrivals / arrivals) — the elastic
      fleet controller's input signals (serve/fleet.py), trended here
      so its scale thresholds are chosen against measured load curves
      rather than guessed.

    The facts land in a ``bench_serve`` run manifest
    (``extra["serve_bench"]``) -> trend-store row, so `obsctl trend
    --db` tracks serving throughput across rounds exactly like the
    solver metrics.  ``runner_factory`` injects a stub engine (tests);
    the default builds the real warm batch runner over ``design``.
    Knobs: ``RAFT_BENCH_SERVE_N`` (requests), ``RAFT_BENCH_SERVE_RPS``
    (arrival rate), ``RAFT_BENCH_SERVE_DUP_RATIO`` (fraction of
    arrivals repeating an earlier request — the realistic near-
    duplicate traffic shape; > 0 enables the content-addressed result
    tier on a scratch ``store_dir`` and additionally reports
    ``store_hit_ratio``, ``read_p50_ms``/``p99``,
    ``warm_start_iter_savings``, and the ground-truth
    ``store_corrupt_served_count`` — every duplicate's payload digest
    compared against the first delivery of the same request)."""
    import tempfile

    from raft_tpu import errors, obs
    from raft_tpu.serve import SweepService, soak

    n = int(n_requests if n_requests is not None
            else os.environ.get("RAFT_BENCH_SERVE_N", 48))
    rps = float(rps if rps is not None
                else os.environ.get("RAFT_BENCH_SERVE_RPS", 6.0))
    dup_ratio = float(dup_ratio if dup_ratio is not None
                      else os.environ.get("RAFT_BENCH_SERVE_DUP_RATIO",
                                          0.0))
    scratch_store = None
    if dup_ratio > 0.0 and store_dir is None:
        store_dir = scratch_store = tempfile.mkdtemp(
            prefix="raft-bench-store-")
    fowt = None
    if runner_factory is None:
        fowt = soak.build_fowt(design)
    cfg = soak.default_config(batch_cases=batch_cases, queue_max=n,
                              deadline_s=timeout_s,
                              batch_deadline_s=120.0,
                              store_dir=store_dir)
    manifest = obs.RunManifest.begin(kind="bench_serve", config={
        "design": design, "n_requests": n, "arrival_rps": rps,
        "batch_cases": batch_cases, "seed": seed,
        "dup_ratio": dup_ratio, "store": store_dir is not None,
        "stub": runner_factory is not None})
    status = "failed"
    svc = None
    try:
        svc = SweepService(fowt, cfg, runner_factory=runner_factory)
        svc.start()
        rng = np.random.default_rng(seed)
        Hs, Tp, beta = soak.case_table(n, seed=seed)
        if dup_ratio > 0.0:
            # dup-heavy arrival shape: each arrival repeats an earlier
            # request's exact physics with probability dup_ratio —
            # identical requests recur constantly across tenants in the
            # paper's workload, and they are what the result tier turns
            # into memory-speed reads / coalesced flights
            for i in range(1, n):
                if rng.random() < dup_ratio:
                    j = int(rng.integers(0, i))
                    Hs[i], Tp[i], beta[i] = Hs[j], Tp[j], beta[j]
        gaps = rng.exponential(1.0 / rps, n)
        t0 = time.monotonic()
        arrivals = t0 + np.cumsum(gaps)
        tickets = {}
        admit_s = []
        depth_samples = []
        shed = 0
        for i in range(n):
            wait = arrivals[i] - time.monotonic()
            if wait > 0:
                time.sleep(wait)
            ta = time.perf_counter()
            try:
                tickets[i] = svc.submit(Hs[i], Tp[i], beta[i])
            except errors.AdmissionRejected:
                shed += 1        # open loop: shed arrivals do not retry
            finally:
                admit_s.append(time.perf_counter() - ta)
            # queue depth AT each arrival: the distribution the fleet
            # controller's scale-up threshold cuts through
            depth_samples.append(svc.stats()["queue_depth"])
        results = {}
        deadline = time.monotonic() + timeout_s
        for i, t in tickets.items():
            results[i] = t.result(max(0.5, deadline - time.monotonic()))
        open_loop_s = time.monotonic() - t0
        summary = svc.stop()
        completed = sum(1 for r in results.values() if r.ok)
        batches = max(1, summary["batches"])
        facts = {
            "cases_per_min": round(completed / open_loop_s * 60.0, 2),
            "admission_p50_s": SweepService._percentile(admit_s, 50),
            "admission_p99_s": SweepService._percentile(admit_s, 99),
            "batch_fill_ratio": round(
                completed / (batches * cfg.batch_cases), 4),
            "arrival_rps": rps,
            "open_loop_s": round(open_loop_s, 3),
            "queue_depth_p50": SweepService._percentile(
                depth_samples, 50),
            "queue_depth_p99": SweepService._percentile(
                depth_samples, 99),
            "quota_pressure": round(shed / float(n), 4) if n else 0.0,
            "completed": completed,
            "shed": shed,
            "failed": sum(1 for r in results.values() if not r.ok),
        }
        if store_dir is not None:
            # result-tier facts + the ground-truth integrity gate: a
            # duplicate arrival's payload digest must equal the FIRST
            # delivery of the identical request — any disagreement
            # means a corrupt (or warm-start-poisoned) byte was served
            first_digest: dict[tuple, str] = {}
            corrupt_served = 0
            for i in sorted(results):
                r = results[i]
                if not r.ok:
                    continue
                key = (float(Hs[i]), float(Tp[i]), float(beta[i]))
                prior = first_digest.setdefault(key, r.digest)
                if prior != r.digest:
                    corrupt_served += 1
            facts.update({
                "dup_ratio": dup_ratio,
                "store_hit_ratio": summary.get("store_hit_ratio"),
                "read_p50_ms": summary.get("read_p50_ms"),
                "read_p99_ms": summary.get("read_p99_ms"),
                "warm_start_iter_savings": summary.get(
                    "warm_start_iter_savings"),
                "store_corrupt_served_count": corrupt_served,
                "warm_start_digest_mismatch": summary.get(
                    "warm_start_digest_mismatch", 0),
            })
        manifest.extra["serve_bench"] = facts
        manifest.extra["serve"] = summary
        status = "ok" if completed and not facts["failed"] else "failed"
        report = {"metric": "sustained serving throughput "
                            f"(open-loop {rps} req/s over {n} "
                            f"requests, batch={cfg.batch_cases})",
                  **facts, "ok": status == "ok"}
    finally:
        # the service must stop on the error path too (its own serve
        # manifest finishes, the WAL/mirror closes) — a ticket timeout
        # must not strand the worker threads behind a traceback
        if svc is not None:
            svc.stop(drain=False, timeout=5.0)
        if scratch_store is not None:
            import shutil
            shutil.rmtree(scratch_store, ignore_errors=True)
        paths = obs.finish_run(manifest, status=status)
    report["manifest"] = paths["manifest"]
    return report


def _serve_bench_main() -> int:
    report = serve_bench()
    print(json.dumps(report))
    return 0 if report["ok"] else 1


def surrogate_bench(runner_factory=None, *, design=None, n_corpus=None,
                    n_serve=None, batch_cases=4, seed=2026, steps=None,
                    tol=None, timeout_s=900.0):
    """Benchmark + ground-truth-gate the learned read tier
    (``serve/surrogate.py``) end to end, four phases over one scratch
    result store:

    1. **Corpus** — cold-solve ``n_corpus`` seeded cases through a
       store-enabled service; the phase's wall per completed case is
       the ``cold_case_s`` baseline the speedup gate compares against
       (the real batched solve path, not a microbenchmark).
    2. **Distill** — train + publish the tenant bundle from that store
       (the same :func:`raft_tpu.serve.surrogate.distill` the
       `raftserve distill` CLI runs).
    3. **Surrogate serving** — an interpolation-heavy arrival table
       (convex combinations of corpus points, plus a deliberate
       out-of-hull fraction that must escalate) against a
       surrogate-enabled service.  EVERY surrogate-served answer is
       then ALSO cold-solved (``submit(..., exact=True)``) and
       compared at the calibrated bound — the
       ``surrogate_bound_violation_served_count`` fact is measured
       against real physics, not sampled.
    4. **Quarantine drill** — a deliberately stale bundle
       (``stale_y_scale``: the corpus physics scaled 1.5x) goes live
       with ``surrogate_audit_every=1``; the first served answer's
       audit must trip, quarantine the bundle durably, and the same
       request resubmitted must come back from the exact path with a
       payload digest bit-for-bit equal to the cold solve's.

    Gates (all must hold for ``ok``): hit ratio >= 0.6 over the
    arrival table, surrogate read p50 >= 50x faster than the cold
    batched case, ZERO served bound violations, and the quarantine
    path proven live.  Facts land in a ``bench_surrogate`` manifest
    (``extra["surrogate_bench"]``) -> trend-store row gated by the
    two zero-tolerance surrogate SLO rules.  Knobs:
    ``RAFT_BENCH_SUR_DESIGN``, ``RAFT_BENCH_SUR_CORPUS``,
    ``RAFT_BENCH_SUR_SERVE``, ``RAFT_BENCH_SUR_STEPS``,
    ``RAFT_BENCH_SUR_TOL``."""
    import shutil
    import tempfile

    from raft_tpu import obs
    from raft_tpu.serve import SweepService, soak, surrogate
    from raft_tpu.serve.resultstore import ResultStore

    design = str(design if design is not None
                 else os.environ.get("RAFT_BENCH_SUR_DESIGN", "OC3spar"))
    n_corpus = int(n_corpus if n_corpus is not None
                   else os.environ.get("RAFT_BENCH_SUR_CORPUS", 48))
    n_serve = int(n_serve if n_serve is not None
                  else os.environ.get("RAFT_BENCH_SUR_SERVE", 24))
    steps = int(steps if steps is not None
                else os.environ.get("RAFT_BENCH_SUR_STEPS", 1500))
    tol = float(tol if tol is not None
                else os.environ.get("RAFT_BENCH_SUR_TOL", 0.05))
    scratch = tempfile.mkdtemp(prefix="raft-bench-surrogate-")
    store_dir = os.path.join(scratch, "store")
    sur_dir = os.path.join(scratch, "surrogate")
    fowt = None
    if runner_factory is None:
        fowt = soak.build_fowt(design)
    manifest = obs.RunManifest.begin(kind="bench_surrogate", config={
        "design": design, "n_corpus": n_corpus, "n_serve": n_serve,
        "batch_cases": batch_cases, "steps": steps, "tol": tol,
        "seed": seed, "stub": runner_factory is not None})
    status = "failed"
    svc = None

    def _mkcfg(**kw):
        return soak.default_config(
            batch_cases=batch_cases, queue_max=max(n_corpus, n_serve),
            deadline_s=timeout_s, batch_deadline_s=120.0,
            store_dir=store_dir, **kw)

    def _collect(tickets):
        out = {}
        deadline = time.monotonic() + timeout_s
        for i, t in tickets.items():
            out[i] = t.result(max(0.5, deadline - time.monotonic()))
        return out

    try:
        # -- phase 1: cold corpus (the speedup baseline) --------------
        Hs, Tp, beta = soak.case_table(n_corpus, seed=seed)
        svc = SweepService(fowt, _mkcfg(),
                           runner_factory=runner_factory)
        svc.start()
        t0 = time.monotonic()
        cold = _collect({i: svc.submit(Hs[i], Tp[i], beta[i])
                         for i in range(n_corpus)})
        cold_wall = time.monotonic() - t0
        svc.stop()
        svc = None
        n_cold = sum(1 for r in cold.values() if r.ok)
        cold_case_s = cold_wall / max(1, n_cold)

        # -- phase 2: distill + publish -------------------------------
        dist = surrogate.distill(ResultStore(store_dir), sur_dir,
                                 steps=steps, seed=seed)
        bundle = surrogate.SurrogateBundle.load(sur_dir, "default")

        # -- phase 3: interpolation-heavy arrivals, every served
        # answer ground-truth audited --------------------------------
        rng = np.random.default_rng(seed + 1)
        arrivals = []
        for k in range(n_serve):
            if k % 5 == 4:
                # the deliberate out-of-hull fraction (20%): beyond
                # the corpus Hs range — MUST escalate to the cold path
                arrivals.append((float(Hs.max() + 1.0 + rng.random()),
                                 float(Tp[k % n_corpus]),
                                 float(beta[k % n_corpus])))
            else:
                i, j = rng.integers(0, n_corpus, 2)
                lam = 0.2 + 0.6 * rng.random()
                arrivals.append((
                    float(lam * Hs[i] + (1 - lam) * Hs[j]),
                    float(lam * Tp[i] + (1 - lam) * Tp[j]),
                    float(lam * beta[i] + (1 - lam) * beta[j])))
        # the phase-4 drill point: in-hull but NEVER submitted in phase
        # 3 — the bench's own ground-truth audits cold-solve every
        # phase-3 arrival onto the exact path, so a reused arrival
        # would be answered by the exact-digest store hit and the stale
        # bundle would never get the chance to serve (and be caught)
        di, dj = rng.integers(0, n_corpus, 2)
        while dj == di:          # di == dj would collapse onto a
            dj = int(rng.integers(0, n_corpus))  # phase-1-solved point
        dlam = 0.2 + 0.6 * rng.random()
        drill = (float(dlam * Hs[di] + (1 - dlam) * Hs[dj]),
                 float(dlam * Tp[di] + (1 - dlam) * Tp[dj]),
                 float(dlam * beta[di] + (1 - dlam) * beta[dj]))
        svc = SweepService(fowt, _mkcfg(surrogate_dir=sur_dir,
                                        surrogate_tol=tol,
                                        # phase 4 proves the in-service
                                        # audit; here the BENCH audits
                                        # every answer itself
                                        surrogate_audit_every=10**6),
                           runner_factory=runner_factory)
        svc.start()
        # warm BOTH serving tiers before timing, with fresh points
        # that are never arrivals (timed hit ratio and ground-truth
        # audit set untouched): one in-hull read pays the surrogate
        # path's first-call costs, and one out-of-hull point forces
        # the batch runner build NOW — otherwise the first escalated
        # arrival kicks off that build concurrently with the timed
        # loop and, on a 1-core box, the contention lands squarely in
        # the read-latency samples
        wlam = 0.2 + 0.6 * rng.random()
        svc.submit(float(wlam * Hs[0] + (1 - wlam) * Hs[1]),
                   float(wlam * Tp[0] + (1 - wlam) * Tp[1]),
                   float(wlam * beta[0] + (1 - wlam) * beta[1])
                   ).result(timeout_s)
        svc.submit(float(Hs.max() + 3.0), float(Tp[0]),
                   float(beta[0])).result(timeout_s)
        # the surrogate read is ~100 us of pure python+numpy — a GC
        # pause inside one submit would dominate that sample, so keep
        # the collector out of the timed loops
        import gc
        tickets, lat_ms = {}, {}
        gc.collect()
        gc.disable()
        try:
            for k, (h, t, b) in enumerate(arrivals):
                ta = time.perf_counter()
                tickets[k] = svc.submit(h, t, b)
                lat_ms[k] = (time.perf_counter() - ta) * 1e3
        finally:
            gc.enable()
        results = _collect(tickets)
        served = {k: r for k, r in results.items()
                  if r.ok and r.source == "surrogate"}
        # second timed pass over the served arrivals (still no exact
        # rows in the store, so they serve from the surrogate again):
        # doubles the latency sample pool — on a 1-core box a handful
        # of samples makes the p50 a coin flip
        lat2_ms = {}
        gc.collect()
        gc.disable()
        try:
            for k in served:
                ta = time.perf_counter()
                t2 = svc.submit(*arrivals[k])
                lat2_ms[k] = (time.perf_counter() - ta) * 1e3
                t2.result(timeout_s)
        finally:
            gc.enable()
        # ground truth: cold-solve EVERY surrogate-served arrival on
        # the exact path and compare at the calibrated bound
        exact = _collect({k: svc.submit(*arrivals[k], exact=True)
                          for k in served})
        violations = 0
        for k, r in served.items():
            ok_b, _ = bundle.within_bound(r.std, r.iters, r.converged,
                                          exact[k], tol=tol)
            if not ok_b:
                violations += 1
        summary3 = svc.stop()
        svc = None
        served_ms = sorted([lat_ms[k] for k in served]
                           + list(lat2_ms.values()))
        read_p50 = SweepService._percentile(served_ms, 50)
        read_p99 = SweepService._percentile(served_ms, 99)
        hit_ratio = len(served) / max(1, n_serve)
        speedup = (cold_case_s * 1e3 / read_p50) if read_p50 else None

        # -- phase 4: stale bundle -> audit -> quarantine -> exact ----
        stale = surrogate.distill(ResultStore(store_dir), sur_dir,
                                  steps=steps, seed=seed,
                                  stale_y_scale=1.5)
        # the drill proves the AUDIT, not the serving gate: the stale
        # bundle must actually serve the drill point, so admit it even
        # when its (self-consistent) calibration lands marginally over
        # the configured tol — the audit still compares a ~50% stale
        # error against a few-percent allowance and must catch it
        stale_tol = max(tol, float(stale["bound_rel_max"]) * 1.05)
        svc = SweepService(fowt, _mkcfg(surrogate_dir=sur_dir,
                                        surrogate_tol=stale_tol,
                                        surrogate_audit_every=1,
                                        surrogate_drill=True),
                           runner_factory=runner_factory)
        svc.start()
        r_stale = svc.submit(*drill).result(timeout_s)
        stale_served = r_stale.ok and r_stale.source == "surrogate"
        deadline = time.monotonic() + timeout_s / 2
        while (svc.stats()["surrogate_quarantines"] < 1
               and time.monotonic() < deadline):
            time.sleep(0.2)
        summary4 = svc.summary()
        quarantines = summary4["surrogate_quarantines"]
        # post-quarantine: the same request must return from the exact
        # path, bit-for-bit identical to a cold solve's digest
        r_after = svc.submit(*drill).result(timeout_s)
        r_exact = svc.submit(*drill, exact=True).result(timeout_s)
        post_exact = (r_after.ok and r_after.source != "surrogate"
                      and r_after.digest == r_exact.digest)
        svc.stop()
        svc = None

        facts = {
            "cold_case_s": round(cold_case_s, 4),
            "corpus_rows": dist["corpus_rows"],
            "bound_rel_max": round(dist["bound_rel_max"], 5),
            "served": len(served),
            "escalated": n_serve - len(served),
            "audited": len(served),
            "hit_ratio": round(hit_ratio, 4),
            "read_p50_ms": read_p50,
            "read_p99_ms": read_p99,
            "speedup_vs_cold": (round(speedup, 1)
                                if speedup is not None else None),
            "surrogate_bound_violation_served_count": violations,
            "stale_served": int(stale_served),
            "quarantines": quarantines,
            "surrogate_quarantine_miss": max(
                int(stale_served and quarantines < 1),
                int(summary4["surrogate_quarantine_miss"])),
            "post_quarantine_exact": int(post_exact),
        }
        manifest.extra["surrogate_bench"] = facts
        manifest.extra["serve"] = summary3
        gates = {
            "completed": all(r.ok for r in results.values()),
            "hit_ratio": hit_ratio >= 0.6,
            "speedup": speedup is not None and speedup >= 50.0,
            "violations": violations == 0,
            "quarantine_live": bool(stale_served and quarantines >= 1),
            "post_quarantine_exact": bool(post_exact),
        }
        status = "ok" if all(gates.values()) else "failed"
        report = {"metric": "learned read tier: surrogate serving vs "
                            f"cold batched solve ({n_serve} arrivals "
                            f"over a {dist['corpus_rows']}-row corpus, "
                            f"every served answer audited)",
                  **facts, "gates": gates, "ok": status == "ok"}
    finally:
        if svc is not None:
            svc.stop(drain=False, timeout=5.0)
        shutil.rmtree(scratch, ignore_errors=True)
        paths = obs.finish_run(manifest, status=status)
    report["manifest"] = paths["manifest"]
    return report


def _surrogate_bench_main() -> int:
    report = surrogate_bench()
    print(json.dumps(report))
    return 0 if report["ok"] else 1


def optimize_bench(*, design=None, bounds=None, objective=None,
                   grid=None, nlanes=None, steps=None, method="adam",
                   lr=None, min_freq=None, max_freq=None, dfreq=None,
                   nIter=None, tol=1e-4, seed=2026):
    """Benchmark + golden-gate the differentiable co-design loop
    (``parallel/optimize.py``) against the dense forward sweep.

    Two runs over the SAME design box:

    1. **Dense forward sweep** — a ``grid^P`` θ batch through
       ``sweep_variants`` (the repo's headline forward machinery), its
       per-variant objective evaluated host-side, its argmin the
       reference optimum.
    2. **Batched descent** — ``nlanes`` simultaneous implicit-diff
       projected descents (``optimize_designs``) over the same bounds.

    The GATE: the descent's best objective must land within tolerance
    of (or beat) the dense argmin, and the best design must sit within
    one grid spacing of the dense argmin per dimension — gradients that
    lie produce a wrong optimum, so this is an end-to-end gradient
    correctness gate, not just a throughput number.

    Facts (``bench_optimize`` manifest -> trend store): descents/min,
    adjoint-solve s/step, speedup-vs-dense-sweep (wall ratio to the
    same argmin), and ``grad_nonfinite_ratio`` (SLO rule: must be 0).
    Knobs: ``RAFT_BENCH_OPT_{DESIGN,GRID,LANES,STEPS,NITER}``.

    Runs under the scoped x64 enable (``_f64_scope``): this is an
    accuracy gate like the golden ledgers, and the f32 throughput mode
    the bench pins for TPU timing loses the adjoint chain's headroom
    (catenary/statics reverse passes square ~1e9 stiffness terms)."""
    import contextlib

    import jax
    import jax.numpy as jnp

    from raft_tpu import obs
    from raft_tpu.parallel import optimize as optmod
    from raft_tpu.parallel.variants import sweep_variants
    from raft_tpu.serve.soak import build_fowt

    x64, dev = _f64_scope()
    with contextlib.ExitStack() as stack:
        stack.enter_context(x64)
        stack.enter_context(dev)
        return _optimize_bench_body(
            design, bounds, objective, grid, nlanes, steps, method, lr,
            min_freq, max_freq, dfreq, nIter, tol, seed, jax, jnp, obs,
            optmod, sweep_variants, build_fowt)


def _optimize_bench_body(design, bounds, objective, grid, nlanes, steps,
                         method, lr, min_freq, max_freq, dfreq, nIter,
                         tol, seed, jax, jnp, obs, optmod,
                         sweep_variants, build_fowt):

    # one precedence rule for EVERY knob (the serve bench's): an
    # explicit argument wins, the RAFT_BENCH_OPT_* env var is the
    # default, the literal is the fallback
    def _knob(value, env, fallback, cast):
        return cast(value if value is not None
                    else os.environ.get(env, fallback))

    design = _knob(design, "RAFT_BENCH_OPT_DESIGN", "OC3spar", str)
    min_freq = _knob(min_freq, "RAFT_BENCH_OPT_MIN_FREQ", 0.1, float)
    max_freq = _knob(max_freq, "RAFT_BENCH_OPT_MAX_FREQ", 0.9, float)
    dfreq = _knob(dfreq, "RAFT_BENCH_OPT_DFREQ", 0.2, float)
    grid = _knob(grid, "RAFT_BENCH_OPT_GRID", 5, int)
    nlanes = _knob(nlanes, "RAFT_BENCH_OPT_LANES", 4, int)
    steps = _knob(steps, "RAFT_BENCH_OPT_STEPS", 10, int)
    nIter = _knob(nIter, "RAFT_BENCH_OPT_NITER", 8, int)
    adjoint_iters = _knob(None, "RAFT_BENCH_OPT_ADJ", nIter, int)
    lr = _knob(lr, "RAFT_BENCH_OPT_LR", 0.05, float)
    if bounds is None:
        bounds = {"ballast": (0.95, 1.05), "moor_L": (0.98, 1.02)}
    objective = dict(objective or {"metric": "std", "Hs": 6.0,
                                   "Tp": 10.0})
    base = build_fowt(design, min_freq, max_freq, dfreq)
    space = optmod.DesignSpace(base, bounds)
    fn, spec = optmod.make_objective(objective)
    w = jnp.asarray(base.w)
    manifest = obs.RunManifest.begin(kind="bench_optimize", config={
        "design": design, "grid": grid, "nlanes": nlanes,
        "steps": steps, "method": method, "nw": len(base.w),
        "objective": spec["metric"],
        "names": ",".join(space.names)})
    status = "failed"
    try:
        # ----- dense forward sweep over the grid -----
        lo = np.asarray(space.lower)
        hi = np.asarray(space.upper)
        axes = [np.linspace(lo[i], hi[i], grid)
                for i in range(space.ndim)]
        gx = np.stack(np.meshgrid(*axes, indexing="ij"),
                      axis=-1).reshape(-1, space.ndim)
        thetas = jax.tree.map(
            lambda *xs: jnp.stack(xs),
            *[space.to_theta(jnp.asarray(x)) for x in gx])
        with obs.span("bench_opt_dense", nv=len(gx)):
            t0 = time.perf_counter()
            out = sweep_variants(base, thetas,
                                 ballast=("ballast" not in space.names),
                                 Hs=float(spec["Hs"]),
                                 Tp=float(spec["Tp"]),
                                 beta=float(spec["beta"]),
                                 nIter=nIter, tol=tol)
            dense_f = np.asarray(jax.vmap(lambda o: fn(o, w))(
                {k: out[k] for k in ("Xi", "std", "Xeq", "offset")}))
            dense_s = time.perf_counter() - t0
        ibest = int(np.nanargmin(dense_f))
        x_dense = gx[ibest]
        f_dense = float(dense_f[ibest])
        # ----- batched implicit-diff descent over the same box -----
        with obs.span("bench_opt_descend", nlanes=nlanes):
            t0 = time.perf_counter()
            res = optmod.optimize_designs(
                base, space, objective, nlanes=nlanes, steps=steps,
                method=method, lr=lr, seed=seed, nIter=nIter, tol=tol,
                adjoint_iters=adjoint_iters)
            descent_s = time.perf_counter() - t0
        # ----- segmented (checkpoint-chunked) descent: overhead -----
        # the same descent under checkpoint_every chunking (no store —
        # this measures the pure segmentation cost: program switches +
        # per-segment dispatch).  The wall ratio rides the trend store
        # so checkpoint cost is watched like any other perf fact, and
        # the bitwise flag is the OC3 parity pin riding along.
        ckpt_every = max(1, steps // 2)
        with obs.span("bench_opt_ckpt", nlanes=nlanes):
            t0 = time.perf_counter()
            res_seg = optmod.optimize_designs(
                base, space, objective, nlanes=nlanes, steps=steps,
                method=method, lr=lr, seed=seed, nIter=nIter, tol=tol,
                adjoint_iters=adjoint_iters,
                checkpoint_every=ckpt_every)
            seg_s = time.perf_counter() - t0
        ckpt_bitwise = bool(
            np.array_equal(np.asarray(res_seg["x"]),
                           np.asarray(res["x"]))
            and res_seg["f_best"] == res["f_best"])
        spacing = (hi - lo) / max(1, grid - 1)
        design_gap = np.abs(np.asarray(res["x_best"]) - x_dense)
        # objective tolerance: the fixed points converge to ``tol`` —
        # a few tol of relative slack separates gradient lies from
        # solver-tolerance noise
        obj_tol = max(5.0 * tol * max(abs(f_dense), 1e-12), 1e-10)
        argmin_match = bool(
            (res["f_best"] <= f_dense + obj_tol)
            and np.all(design_gap <= spacing + 1e-12))
        nonfinite_ratio = float(np.mean(res["nonfinite"]))
        facts = {
            "descents_per_min": round(nlanes / descent_s * 60.0, 3),
            "adjoint_s_per_step": round(descent_s / steps, 4),
            "speedup_vs_dense_sweep": round(dense_s / descent_s, 4),
            "dense_points": int(len(gx)),
            "dense_s": round(dense_s, 3),
            "descent_s": round(descent_s, 3),
            "f_best": float(res["f_best"]),
            "f_dense_min": f_dense,
            "objective_gap": float(res["f_best"] - f_dense),
            "design_gap_max_spacing": float(
                np.max(design_gap / np.maximum(spacing, 1e-12))),
            "grad_nonfinite_ratio": nonfinite_ratio,
            "converged_lanes": int(np.sum(res["converged"])),
            "argmin_match": int(argmin_match),
            "exec_cache": res["provenance"]["exec_cache"],
            # checkpoint-cost facts: segmented-vs-monolithic wall
            # ratio (compile-noise rides along on cold caches — trend
            # it warm) + the bitwise-parity pin
            "ckpt_overhead_ratio": round(seg_s / max(descent_s, 1e-9),
                                         4),
            "checkpoint_every": ckpt_every,
            "ckpt_segmented_bitwise": int(ckpt_bitwise),
        }
        manifest.extra["bench_optimize"] = facts
        manifest.extra["solver"] = res["provenance"]["solver"]
        status = ("ok" if argmin_match and nonfinite_ratio == 0.0
                  and ckpt_bitwise else "failed")
        report = {"metric": "differentiable co-design gate "
                            f"({design}: {grid}^{space.ndim} dense grid "
                            f"vs {nlanes}x{steps} descent)",
                  **facts,
                  "x_best": [float(v) for v in res["x_best"]],
                  "x_dense": [float(v) for v in x_dense],
                  "ok": status == "ok"}
    finally:
        paths = obs.finish_run(manifest, status=status)
    report["manifest"] = paths["manifest"]
    return report


def _optimize_bench_main() -> int:
    report = optimize_bench()
    print(json.dumps(report))
    return 0 if report["ok"] else 1


def farm_bench(*, design=None, n_turbines=None, ncases=None,
               spacing_m=None, min_freq=None, max_freq=None, dfreq=None,
               nIter=None, tol=1e-4, seed=2026, serial_sample=None,
               k_w=0.05):
    """Benchmark + parity-gate the device-resident farm axis
    (``parallel/sweep.sweep_farm`` / ``make_farm_runner``): N turbines x
    M cases solved as ONE compiled program, wake equilibrium included.

    Two measurements over the SAME layout and case table:

    1. **Farm-batched** — the warm ``make_farm_runner`` program timed
       over distinct case batches (the axon tunnel memoizes identical
       executions); metric ``turbine_cases_per_min``.
    2. **Serial baseline** — the host wake fixed point per case plus
       one jitted SINGLE-LANE solve per (turbine, case), measured on a
       sample of lanes and extrapolated (the way the reference drives
       farms: one FOWT, one case at a time).

    The GATE: every farm lane's response std must match the per-turbine
    serial path (same case solver, host wake equilibrium, per-lane
    mooring stiffness and aero damping) to solver tolerance —
    ``farm_parity_mismatch`` counts lanes beyond 1e-6 relative and the
    trend-store SLO rule pins it at 0: a fast-but-wrong farm number is
    not a result.

    Facts (``bench_farm`` manifest -> trend store): turbine_cases/min
    farm and serial, speedup, wake fixed-point iterations, parity.
    Knobs: ``RAFT_BENCH_FARM_{DESIGN,NT,NC,SPACING,NITER,SERIAL_N}``."""
    import jax

    from raft_tpu.models import mooring as mr
    from raft_tpu.models import wake as wk
    from raft_tpu.parallel import sweep as sweepmod
    from raft_tpu.serve.soak import build_fowt, case_table

    def _knob(value, env, fallback, cast):
        return cast(value if value is not None
                    else os.environ.get(env, fallback))

    design = _knob(design, "RAFT_BENCH_FARM_DESIGN", "OC3spar", str)
    nt = _knob(n_turbines, "RAFT_BENCH_FARM_NT", 4, int)
    nc = _knob(ncases, "RAFT_BENCH_FARM_NC", 64, int)
    spacing = _knob(spacing_m, "RAFT_BENCH_FARM_SPACING", 800.0, float)
    min_freq = _knob(min_freq, "RAFT_BENCH_FARM_MIN_FREQ", 0.05, float)
    max_freq = _knob(max_freq, "RAFT_BENCH_FARM_MAX_FREQ", 0.5, float)
    dfreq = _knob(dfreq, "RAFT_BENCH_FARM_DFREQ", 0.05, float)
    nIter = _knob(nIter, "RAFT_BENCH_FARM_NITER", 8, int)
    nser = _knob(serial_sample, "RAFT_BENCH_FARM_SERIAL_N", 8, int)

    obs = _obs_default()
    fowt = build_fowt(design, min_freq, max_freq, dfreq)
    # single row along +x: every downstream turbine sits in the wake
    # cone at wind_dir ~ 0, so the equilibrium is genuinely coupled
    xy = np.stack([np.arange(nt) * spacing, np.zeros(nt)], axis=1)
    Hs, Tp, beta = case_table(nc, seed=seed)
    rng = np.random.default_rng(seed)
    U_inf = 6.0 + 8.0 * rng.random(nc)
    wind_dir = rng.uniform(-15.0, 15.0, nc)

    manifest = obs.RunManifest.begin(kind="bench_farm", config={
        "design": design, "n_turbines": nt, "ncases": nc,
        "spacing_m": spacing, "nw": len(fowt.w), "nIter": nIter,
        "seed": seed})
    status = "failed"
    try:
        # the BEM induction solve behind the power/thrust curve needs
        # f64 (in f32 the bracket test mis-signs; see _aero_constants)
        # — build the curve once under the scoped x64 enable and hand
        # the plain-numpy tables to the f32 farm program
        x64_ctx, dev_ctx = _f64_scope()
        with x64_ctx, dev_ctx:
            curve = wk.power_thrust_curve(fowt)
        with obs.span("farm_bench_build", n_turbines=nt, ncases=nc):
            runner = sweepmod.make_farm_runner(
                fowt, xy, nc, nIter=nIter, tol=tol, k_w=k_w, curve=curve)
        # ----- farm-batched throughput (warm program, distinct inputs)
        reps = 3
        batches = []
        for rp in range(reps):
            h, t, b = case_table(nc, seed=seed + 1 + rp)
            r2 = np.random.default_rng(seed + 1 + rp)
            batches.append((h, t, b, 6.0 + 8.0 * r2.random(nc),
                            r2.uniform(-15.0, 15.0, nc)))
        with obs.span("farm_bench_timed", reps=reps):
            t0 = time.perf_counter()
            for arrs in batches:
                runner(*arrs)
            farm_dt = (time.perf_counter() - t0) / reps
        farm_tcpm = nt * nc / farm_dt * 60.0

        # ----- parity: farm lanes vs the serial per-turbine path -----
        out = runner(Hs, Tp, beta, U_inf, wind_dir)
        shaped = sweepmod._farm_reshape(out, nt, nc)
        std_farm = np.asarray(shaped["std"])          # (nt, nc, 6)
        wake_iters = int(np.max(np.asarray(shaped["wake_iters"])))
        curve = runner.curve
        rot = fowt.rotors[0]
        D = np.full(nt, 2.0 * rot.R_rot)
        # host wake fixed point per case — find_wake_equilibrium's exact
        # schedule (same relax/tol/termination), Model-free
        t_wake0 = time.perf_counter()
        U_t = np.zeros((nt, nc))
        for c in range(nc):
            U = np.full(nt, U_inf[c])
            Ct = wk._curve_interp(U, curve, "Ct")
            for _ in range(100):
                U_new = wk.wake_velocities(xy, D, Ct, float(U_inf[c]),
                                           float(wind_dir[c]), k_w)
                if np.max(np.abs(U_new - U)) < 1e-4:
                    U = U_new
                    break
                U = 0.5 * U + 0.5 * U_new
                Ct = wk._curve_interp(U, curve, "Ct")
            U_t[:, c] = U
        wake_host_s = time.perf_counter() - t_wake0
        r6_ref = np.array([fowt.x_ref, fowt.y_ref, 0, 0, 0, 0], float)
        C_base = (np.asarray(mr.coupled_stiffness_rotvec(fowt.mooring,
                                                         r6_ref))
                  if fowt.mooring is not None else np.zeros((6, 6)))
        B_tab = sweepmod.aero_damping_table(curve, float(rot.hubHt))
        cs = np.asarray(curve["wind_speed"])
        case = sweepmod.make_case_solver(fowt, nIter=nIter, tol=tol)
        C_b = np.broadcast_to(C_base, (nc, 6, 6))
        std_ref = np.zeros_like(std_farm)
        with obs.span("farm_bench_parity_ref", n_turbines=nt):
            for t in range(nt):
                r6_b = np.zeros((nc, 6))
                r6_b[:, :2] = xy[t]
                B_add = sweepmod._interp_along0(
                    jax.numpy.asarray(cs), jax.numpy.asarray(B_tab),
                    jax.numpy.asarray(U_t[t]))
                o = case.batched(Hs, Tp, beta, r6_b=r6_b, C_moor_b=C_b,
                                 B_add=B_add)
                std_ref[t] = np.asarray(o["std"])
        rel = (np.abs(std_farm - std_ref)
               / np.maximum(np.abs(std_ref), 1e-12))
        lane_rel = rel.max(axis=-1)                    # (nt, nc)
        # parity threshold scales with the active dtype: the farm
        # program and the serial reference order their f32 reductions
        # differently (~1e-5 roundoff); in f64 they agree to ~1e-15.
        # Real physics mistakes (wrong mooring block, unwaked lane)
        # show up at >1e-2 either way.
        from raft_tpu import _config as _cfg
        ptol = (1e-6 if np.dtype(_cfg.real_dtype()) == np.float64
                else 5e-4)
        mismatch = int(np.sum(lane_rel > ptol))

        # ----- serial baseline: one lane at a time, extrapolated -----
        jlane = jax.jit(lambda h, t, b, r6, C, B: case.batched(
            h, t, b, r6_b=r6, C_moor_b=C, B_add=B))
        C_1 = C_base[None]
        lanes = [(t, c) for t in range(nt) for c in range(nc)]
        sample = lanes[:: max(1, len(lanes) // nser)][:nser]

        def _one(t, c):
            r6_1 = np.zeros((1, 6))
            r6_1[0, :2] = xy[t]
            B_1 = sweepmod._interp_along0(
                jax.numpy.asarray(cs), jax.numpy.asarray(B_tab),
                jax.numpy.asarray(U_t[t, c:c + 1]))
            return jlane(Hs[c:c + 1], Tp[c:c + 1], beta[c:c + 1],
                         r6_1, C_1, B_1)

        jax.block_until_ready(_one(*sample[0])["std"])   # compile
        with obs.span("farm_bench_serial", sample=len(sample)):
            t0 = time.perf_counter()
            for t, c in sample:
                jax.block_until_ready(_one(t, c)["std"])
            lane_dt = (time.perf_counter() - t0) / len(sample)
        serial_s = lane_dt * nt * nc + wake_host_s
        serial_tcpm = nt * nc / serial_s * 60.0

        facts = {
            "turbine_cases_per_min": round(farm_tcpm, 2),
            "serial_turbine_cases_per_min": round(serial_tcpm, 2),
            "speedup_vs_serial": round(farm_tcpm / serial_tcpm, 3),
            "wake_iters": wake_iters,
            "n_turbines": nt,
            "ncases": nc,
            "farm_parity_mismatch": mismatch,
            "parity_max_rel": float(lane_rel.max()),
            "parity_tol": ptol,
            "wall_s": round(farm_dt, 4),
            "serial_lane_s": round(lane_dt, 5),
            "cache_state": str(runner.cache_state),
            "build_s": round(float(runner.build_s), 3),
        }
        manifest.extra["farm_bench"] = facts
        status = "ok" if mismatch == 0 else "failed"
        report = {"metric": "farm axis throughput "
                            f"({design}: {nt} turbines x {nc} cases, "
                            f"{len(fowt.w)} bins, one compiled program "
                            "incl. wake equilibrium)",
                  **facts, "ok": status == "ok"}
    finally:
        paths = obs.finish_run(manifest, status=status)
    report["manifest"] = paths["manifest"]
    return report


def _farm_bench_main() -> int:
    report = farm_bench()
    print(json.dumps(report))
    return 0 if report["ok"] else 1


if __name__ == "__main__":
    import sys as _sys
    if len(_sys.argv) > 1 and _sys.argv[1] == "serve":
        raise SystemExit(_serve_bench_main())
    if len(_sys.argv) > 1 and _sys.argv[1] == "optimize":
        raise SystemExit(_optimize_bench_main())
    if len(_sys.argv) > 1 and _sys.argv[1] == "farm":
        raise SystemExit(_farm_bench_main())
    if len(_sys.argv) > 1 and _sys.argv[1] == "surrogate":
        raise SystemExit(_surrogate_bench_main())
    main()
