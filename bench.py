"""Benchmark: batched frequency-domain RAO solves on the flagship model.

Metric: RAO frequency-bin solves per second per chip (BASELINE.json unit),
measured on a batch of VolturnUS-S load cases run through the full
drag-linearization fixed point + batched complex 6x6 solve.

vs_baseline compares against a serial reference-equivalent implementation
measured on this host: the same math with vectorized-numpy node operations
but Python loops over cases and frequency bins (the reference's structure,
raft/raft_model.py:942-947 — and generous to it, since the reference also
loops members/nodes in Python).

Prints ONE json line.
"""
import json
import os
import time

# TPU has no float64 — run the benchmark in f32/c64 (must be set before any
# raft_tpu import; accuracy-critical CPU runs keep the default x64)
os.environ.setdefault("RAFT_TPU_X64", "0")

import numpy as np


def main():
    import jax
    import jax.numpy as jnp

    from __graft_entry__ import _load_fowt
    from raft_tpu.parallel.sweep import make_case_solver

    fowt = _load_fowt()
    nw = len(fowt.w)
    NC = 256
    NITER = 10

    rng = np.random.default_rng(1)
    Hs = 4.0 + 2.0 * rng.random(NC)
    Tp = 8.0 + 6.0 * rng.random(NC)
    beta = np.zeros(NC)

    solver = make_case_solver(fowt, nIter=NITER, tol=-1.0)  # tol<0: full iterations
    batched = jax.jit(jax.vmap(solver))

    out = batched(Hs, Tp, beta)  # compile + warmup
    jax.block_until_ready(out["std"])
    reps = 3
    t0 = time.perf_counter()
    for _ in range(reps):
        out = batched(Hs, Tp, beta)
        jax.block_until_ready(out["std"])
    dt = (time.perf_counter() - t0) / reps
    # each case solves nw bins per fixed-point iteration
    bins_per_sec = NC * nw * NITER / dt

    baseline_bps = _serial_numpy_baseline(fowt, nw, NITER)

    dev = jax.devices()[0]
    result = {
        "metric": "RAO freq-bin solves/sec/chip (VolturnUS-S case sweep, "
                  f"f32, device={dev.platform})",
        "value": round(bins_per_sec, 1),
        "unit": "bins/s/chip",
        "vs_baseline": round(bins_per_sec / baseline_bps, 2),
    }
    print(json.dumps(result))


def _serial_numpy_baseline(fowt, nw, niter):
    """Reference-structure serial solve: Python loops over cases/freqs."""
    from raft_tpu.models.fowt import fowt_pose, fowt_statics, fowt_hydro_constants
    import jax

    r6 = np.zeros(6)
    pose = fowt_pose(fowt, r6)
    stat = fowt_statics(fowt, pose)
    hc = fowt_hydro_constants(fowt, pose)
    M = np.asarray(stat["M_struc"]) + np.asarray(hc["A_hydro_morison"])
    C = np.asarray(stat["C_struc"]) + np.asarray(stat["C_hydro"])
    C = C + np.eye(6) * np.abs(np.diag(C)).max() * 0.1  # keep it invertible
    w = fowt.w
    r = np.asarray(pose["r"])
    N = r.shape[0]
    ncase_meas = 2
    F = (np.ones((6, nw)) + 1j * np.ones((6, nw)))
    t0 = time.perf_counter()
    for _ in range(ncase_meas):
        Xi = np.zeros((6, nw), complex)
        for _ in range(niter):
            # node-level linearization stand-in (vectorized numpy)
            vrel = np.random.default_rng(0).random((N, 3, nw))
            vrms = np.sqrt(0.5 * np.sum(np.abs(vrel) ** 2, axis=2))
            Bn = vrms[:, :, None] * np.eye(3)[None, :, :]
            B6 = np.sum(Bn, axis=0)
            B = np.zeros((6, 6))
            B[:3, :3] = B6
            for iw in range(nw):
                Z = -w[iw] ** 2 * M + 1j * w[iw] * B + C
                Xi[:, iw] = np.linalg.solve(Z, F[:, iw])
    dt = time.perf_counter() - t0
    return ncase_meas * nw * niter / dt


if __name__ == "__main__":
    main()
