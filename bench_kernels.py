"""Microbenchmark: the batched small-system solve kernels in isolation.

bench.py times the whole variant pipeline; this harness times ONLY the
solver backends at the real hot-path shapes — the 12x12 real-embedded
impedance blocks (6x6 complex through the block embedding) at sweep-scale
batches — so a kernel regression is attributable to the kernel, not the
physics around it:

- ``jnp_gj``:   ops.linalg.gauss_jordan_solve (the unrolled XLA graph)
- ``pallas``:   ops.pallas.gj_solve.gj_solve (VMEM-resident kernel;
                interpret mode on CPU — a correctness path, not a perf
                number there)
- ``lu``:       jnp.linalg.solve (LAPACK on CPU, the LU custom call on
                accelerator backends — the pathological case on TPU)

Batch sizes default to 4096 / 65536 / 262144 (the 1024-variant x 200-bin
regime); override with RAFT_BENCH_KERNELS_B="1024,4096".  On CPU the
default shrinks to 1024/4096 (interpret-mode Pallas at 262144 systems is
a correctness exercise, not a timing).

The mixed-precision ladder rows (``pallas_f64`` / ``pallas_mixed`` /
``pallas_f32``) time the SAME kernel at the three RAFT_TPU_PRECISION
rungs on f64 inputs and report the per-solve speedup of mixed over f64
plus the promoted-lane ratio (``solve_promoted_lane_ratio`` — the
trend-store fact the DEFAULT_SLO_RULES bound so a mixed ladder that
silently mass-promotes to all-f64 gates CI).  On CPU the Pallas rows
run under interpret mode: those rows are parity records, labeled
``timing_meaningful: false`` — the compiled-path speedup claim only
comes from accelerator rounds.

Prints ONE json line (same shape as bench.py: metric/value/unit/ok) and
writes a run manifest (kind ``bench_kernels``) so ``tools/obsctl.py
trend`` charts kernel history next to the sweep manifests.
"""
import json
import os
import time

# match bench.py: f32 unless the caller opts back into x64
os.environ.setdefault("RAFT_TPU_X64", "0")

import numpy as np

N = int(os.environ.get("RAFT_BENCH_KERNELS_N", 12))   # real-embedded 2n
K = int(os.environ.get("RAFT_BENCH_KERNELS_K", 1))    # RHS columns
REPS = int(os.environ.get("RAFT_BENCH_KERNELS_REPS", 3))


def _batch_sizes(backend: str):
    env = os.environ.get("RAFT_BENCH_KERNELS_B")
    if env:
        return [int(x) for x in env.split(",") if x.strip()]
    if backend == "cpu":
        return [1024, 4096]
    return [4096, 65536, 262144]


def _systems(rng, B):
    """Well-conditioned random systems at the hot-path shape.  (The
    mixed force/moment row-scale stressor lives in tests/test_linalg.py
    — a throughput benchmark must compare kernels on systems where f32
    parity is meaningful.)"""
    A = rng.standard_normal((B, N, N)) + 5.0 * np.eye(N)
    b = rng.standard_normal((B, N, K))
    return A, b


def _time(fn, *args):
    import jax

    out = fn(*args)
    jax.block_until_ready(out)          # compile + warmup
    t0 = time.perf_counter()
    for _ in range(REPS):
        out = fn(*args)
        jax.block_until_ready(out)
    return (time.perf_counter() - t0) / REPS, out


def main():
    import jax
    import jax.numpy as jnp

    from raft_tpu import obs
    from raft_tpu.ops.linalg import gauss_jordan_solve
    from raft_tpu.ops.pallas.gj_solve import gj_solve

    if obs.out_dir() is None:
        obs.configure(os.path.join(
            os.path.dirname(os.path.abspath(__file__)), "obs_runs"))
    backend = jax.default_backend()
    x64 = bool(jax.config.jax_enable_x64)
    sizes = _batch_sizes(backend)
    manifest = obs.RunManifest.begin(kind="bench_kernels", config={
        "N": N, "K": K, "REPS": REPS, "backend": backend, "x64": x64,
        "batches": ",".join(map(str, sizes))})
    obs.record_build_info()

    backends = {
        "jnp_gj": jax.jit(gauss_jordan_solve),
        "pallas": jax.jit(gj_solve),
        "lu": jax.jit(jnp.linalg.solve),
    }
    # the accuracy gate: pallas may not be LESS accurate than the jnp
    # Gauss-Jordan it replaces, measured against the f64 LAPACK truth
    # (solver-vs-solver elementwise parity in f32 is dominated by the
    # f32 solve error itself, ~1e-4 on the worst element; the strict
    # 1e-6 interpret-mode parity gate lives in tests/test_pallas_gj.py
    # and the golden-ledger CI gate, both f64)
    acc_margin = 2.0
    rng = np.random.default_rng(17)
    rows = []
    worst_parity = 0.0
    acc_ok = True
    status = "failed"
    try:
        for B in sizes:
            A, b = _systems(rng, B)
            truth = np.linalg.solve(A, b)            # f64 LAPACK truth
            Aj = jnp.asarray(A, jnp.float64 if x64 else jnp.float32)
            bj = jnp.asarray(b, Aj.dtype)
            ref = None
            for name, fn in backends.items():
                with obs.span("bench_kernel", kernel=name, batch=B):
                    dt, out = _time(fn, Aj, bj)
                out = np.asarray(out, np.float64)
                err = np.max(np.abs(out - truth)
                             / np.maximum(np.abs(truth), 1e-12))
                row = {"kernel": name, "batch": B,
                       "systems_per_s": round(B / dt, 1),
                       "wall_s": round(dt, 6),
                       "rel_dev_vs_f64_lapack": float(err)}
                if name == "jnp_gj":
                    ref = out
                    err_gj = err
                else:
                    dev = np.max(np.abs(out - ref)
                                 / np.maximum(np.abs(ref), 1e-12))
                    row["rel_dev_vs_jnp_gj"] = float(dev)
                    if name == "pallas":
                        worst_parity = max(worst_parity, float(dev))
                        acc_ok = acc_ok and bool(
                            err <= acc_margin * err_gj + 1e-12)
                rows.append(row)
                obs.gauge(
                    "raft_kernel_systems_per_s",
                    "batched small-system solve throughput by kernel "
                    "and batch size").set(row["systems_per_s"],
                                          kernel=name, batch=str(B))

        # ---- mixed-precision ladder rows: the same Pallas kernel at
        # the three RAFT_TPU_PRECISION rungs on f64 inputs (scoped x64
        # enable — the f32-default bench still measures the ladder at
        # its real contract).  On CPU these run under interpret mode:
        # parity records, not timings (timing_meaningful=false).
        from jax.experimental import enable_x64

        from raft_tpu import _config as _cfg
        from raft_tpu.ops import precision as _prec

        timing_ok = backend != "cpu"
        ladder: dict = {}
        with enable_x64():
            Bl = sizes[-1]
            A, b = _systems(rng, Bl)
            truth = np.linalg.solve(A, b)
            Aj = jnp.asarray(A, jnp.float64)
            bj = jnp.asarray(b, jnp.float64)
            tol = _cfg.precision_tol()
            # the mixed row factorizes at the CONFIGURED width so the
            # manifest's precision_width fact matches what actually ran
            fdt = _prec.factor_dtype(_cfg.precision_width())
            fns = {
                "pallas_f64": jax.jit(lambda a, r: gj_solve(a, r)),
                "pallas_mixed": jax.jit(lambda a, r: gj_solve(
                    a, r, refine=2, precision="mixed", factor_dtype=fdt,
                    promote_tol=tol, return_stats=True)),
                "pallas_f32": jax.jit(lambda a, r: gj_solve(
                    a.astype(jnp.float32), r.astype(jnp.float32))),
            }
            for name, fn in fns.items():
                with obs.span("bench_kernel", kernel=name, batch=Bl):
                    dt, out = _time(fn, Aj, bj)
                stats = None
                if isinstance(out, tuple):
                    out, stats = out
                out = np.asarray(out, np.float64)
                err = np.max(np.abs(out - truth)
                             / np.maximum(np.abs(truth), 1e-12))
                row = {"kernel": name, "batch": Bl,
                       "systems_per_s": round(Bl / dt, 1),
                       "wall_s": round(dt, 6),
                       "rel_dev_vs_f64_lapack": float(err),
                       "timing_meaningful": timing_ok}
                if stats is not None:
                    row["promoted_lane_ratio"] = round(
                        float(np.asarray(stats["promoted"])) / Bl, 6)
                    row["promote_tol"] = tol
                    # the ladder's whole point: f64-level accuracy out
                    # of a low-width factorization
                    acc_ok = acc_ok and bool(err <= 1e-8)
                ladder[name] = row
                rows.append(row)
                obs.gauge(
                    "raft_kernel_systems_per_s",
                    "batched small-system solve throughput by kernel "
                    "and batch size").set(row["systems_per_s"],
                                          kernel=name, batch=str(Bl))
        promoted_ratio = ladder["pallas_mixed"].get("promoted_lane_ratio")
        mixed_speedup = round(ladder["pallas_f64"]["wall_s"]
                              / max(ladder["pallas_mixed"]["wall_s"],
                                    1e-12), 3)
        solver_facts = {
            "promoted_lane_ratio": promoted_ratio,
            "mixed_speedup_vs_f64": mixed_speedup,
            "precision_width": _cfg.precision_width(),
            "promote_tol": ladder["pallas_mixed"].get("promote_tol"),
            "timing_meaningful": timing_ok,
        }
        manifest.extra["solver"] = solver_facts

        best = max((r["systems_per_s"] for r in rows
                    if r["kernel"] == "pallas"), default=0.0)
        ok = acc_ok
        result = {
            "metric": f"pallas {N}x{N}+{K} real-embedded GJ solve "
                      f"throughput (backend={backend}, "
                      f"{'f64' if x64 else 'f32'}"
                      f"{', interpret' if backend == 'cpu' else ''}; "
                      f"gate: pallas error vs f64 truth <= "
                      f"{acc_margin:g}x jnp_gj error)",
            "value": best,
            "unit": "systems/s",
            "rows": rows,
            "pallas_parity_max_rel_dev": worst_parity,
            "solver": solver_facts,
            "mixed_speedup_vs_f64": mixed_speedup,
            "solve_promoted_lane_ratio": promoted_ratio,
            "ok": ok,
        }
        status = "ok" if ok else "failed"
        manifest.extra["result"] = {"value": best, "ok": ok}
        manifest.extra["rows"] = rows
    finally:
        paths = obs.finish_run(manifest, status=status, write_trace=False)
    result["manifest"] = paths["manifest"]
    print(json.dumps(result))
    if not result["ok"]:
        raise SystemExit(1)


if __name__ == "__main__":
    main()
