"""Batched wind-farm sweep — N turbines x M cases as ONE compiled program.

Builds one OC3 spar FOWT, lays four of them out in a row, and solves
every (turbine, case) lane in a single device program via
`parallel.sweep.sweep_farm`: the Gaussian-deficit wake equilibrium runs
*inside* the program (per-lane waked wind speeds feed the aero
damping), each lane solves at its turbine's position and mooring
stiffness, and the outputs come back as (n_turbines, ncases, ...)
arrays.  For a design YAML with an `array` table (e.g. the 2-FOWT
VolturnUS-S farm), `Model(design).sweep_farm(...)` does the same with
the array-mooring stiffness blocks wired in.

See docs/performance.md "Layer 8 — the farm axis" for the lane layout,
sharding rules, and cache identity; `python bench.py farm` for the
parity + throughput gate.

Usage:  python example_farm.py
"""
import numpy as np

from raft_tpu.io.designs import load_design
from raft_tpu.models.fowt import build_fowt
from raft_tpu.parallel.sweep import sweep_farm


def run_example():
    # one platform design, replicated at each layout position
    design = load_design("OC3spar")
    w = np.arange(0.05, 0.5, 0.02) * 2 * np.pi
    fowt = build_fowt(design, w,
                      depth=float(design["site"]["water_depth"]))

    # a 4-turbine row, 800 m spacing, wind blowing along the row
    layout = np.stack([np.arange(4) * 800.0, np.zeros(4)], axis=1)

    # per-case sea states + free-stream wind driving the wake coupling
    ncases = 8
    rng = np.random.default_rng(7)
    Hs = 3.0 + 3.0 * rng.random(ncases)
    Tp = 8.0 + 5.0 * rng.random(ncases)
    beta = np.zeros(ncases)
    U_inf = 7.0 + 6.0 * rng.random(ncases)
    wind_dir = rng.uniform(-10.0, 10.0, ncases)

    out = sweep_farm(fowt, layout, Hs, Tp, beta, U_inf, wind_dir,
                     nIter=8)

    std = np.asarray(out["std"])          # (4, 8, 6) motion stds
    U_wake = np.asarray(out["U_wake"])    # (4, 8) waked hub winds
    power = np.asarray(out["aero_power"])  # (4, 8) rotor power [W]
    print(f"solved {std.shape[0]} turbines x {std.shape[1]} cases in "
          f"one program; wake iters per case: "
          f"{np.asarray(out['wake_iters']).tolist()}")
    for c in (0, ncases - 1):
        losses = 100.0 * (1.0 - U_wake[:, c] / U_inf[c])
        print(f"case {c}: U_inf={U_inf[c]:5.2f} m/s, per-turbine wake "
              f"loss [%] = {np.round(losses, 2).tolist()}, "
              f"farm power = {power[:, c].sum() / 1e6:.1f} MW")
    print(f"surge std range: {std[..., 0].min():.3f} - "
          f"{std[..., 0].max():.3f} m")
    assert np.all(np.isfinite(std))
    return out


if __name__ == "__main__":
    run_example()
