"""Run raft_tpu from a design YAML — the canonical end-to-end example.

Mirror of the reference's examples/example_from_yaml.py:8-32 against this
package's API: parse the design, build the model, evaluate the unloaded
equilibrium, solve natural frequencies, analyze every load case, and
(optionally) plot the response spectra and system geometry.

Usage:  python example_from_yaml.py [plot: 1/0]   (default: plot if
matplotlib can open a figure)
"""
import sys

from raft_tpu.io.designs import load_design
from raft_tpu.model import Model


def run_example(plot_flag=False):
    # the packaged VolturnUS-S design (IEA-15MW on the UMaine semi);
    # any reference-format design YAML dict works here
    design = load_design("VolturnUS-S")

    # build all model objects from the design dict
    model = Model(design)

    # system properties and equilibrium position before loads are applied
    model.analyzeUnloaded()

    # natural frequencies and mode shapes
    fns, modes = model.solveEigen()
    print("natural frequencies [Hz]:", " ".join(f"{f:.4f}" for f in fns))

    # all load cases from design['cases']: statics -> drag-linearized
    # frequency-domain dynamics -> response statistics
    model.analyzeCases(display=1)

    import numpy as np
    case0 = model.results["case_metrics"][0][0]
    surge_std = float(case0["surge_std"])
    pitch_std = float(case0["pitch_std"])
    assert np.isfinite(surge_std) and np.isfinite(pitch_std), \
        (surge_std, pitch_std)
    print(f"case 0: surge_std={surge_std:.3f} m, "
          f"pitch_std={pitch_std:.3f} deg")

    if plot_flag:
        import matplotlib.pyplot as plt
        model.plotResponses()   # PSDs of the load cases
        model.plot()            # geometry at the latest mean offset
        plt.show()

    return model


if __name__ == "__main__":
    flag = True
    if len(sys.argv) == 2:
        flag = sys.argv[1].lower() in ("1", "t", "true", "y", "yes")
    run_example(plot_flag=flag)
