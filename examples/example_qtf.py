"""Run raft_tpu with internally computed second-order (QTF) wave loads.

Mirror of the reference's examples/example-RAFT_QTF.py: the OC4 semi with
``potSecOrder: 1`` — difference-frequency QTFs computed internally with
the slender-body approximation (Rainey equation, all Pinkster terms +
Kim&Yue corrections) on a dedicated second-order frequency grid.

Because the quadratic drag is stochastically linearized, the QTFs depend
on the sea state of each case; cases are numbered sequentially.  With
``outFolderQTF`` set, two checkpoint files per heading/case/turbine are
written and reloaded on re-runs (content-keyed cache):

* ``qtf-slender_body-total_Head#_Case#_WT#.12d`` — the QTF in WAMIT
  .12d format
* ``raos-slender_body_Head#_Case#_WT#.4`` — the RAOs used for it, in
  WAMIT .4 format

(reference behavior: raft_fowt.py:255-257, 1420-1433, 1642-1648).
"""
import sys

from raft_tpu.io.designs import load_design
from raft_tpu.model import Model


def run_example(out_folder="qtf_output", plot_flag=False):
    design = load_design("OC4semi")

    plat = design["platform"]
    plat["potSecOrder"] = 1         # internal slender-body QTF
    plat["min_freq2nd"] = 0.005     # [Hz] second-order grid start/step
    plat["max_freq2nd"] = 0.15      # [Hz] second-order grid end
    if out_folder:
        plat["outFolderQTF"] = out_folder

    model = Model(design)
    model.analyzeUnloaded()
    model.analyzeCases(display=1)

    case0 = model.results["case_metrics"][0][0]
    print(f"case 0 with 2nd-order loads: "
          f"surge_std={float(case0['surge_std']):.3f} m, "
          f"pitch_std={float(case0['pitch_std']):.3f} deg")
    if out_folder:
        print(f"QTF/.4 snapshots in {out_folder}/")

    if plot_flag:
        import matplotlib.pyplot as plt
        model.plotResponses()
        plt.show()
    return model


if __name__ == "__main__":
    out = sys.argv[1] if len(sys.argv) > 1 else "qtf_output"
    run_example(out_folder=out, plot_flag=False)
