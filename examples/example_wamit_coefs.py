"""Run raft_tpu with externally supplied potential-flow coefficients.

Mirror of the reference's examples/example-WAMIT_Coefs.py (OC4 semi with
WAMIT-format hydrodynamic data, potModMaster=1 + hydroPath).  Two paths:

* If the reference's marin_semi WAMIT files are available (pass a path,
  or the default below exists), the model loads added mass / damping
  from the `.1` file — the reference's shipped configuration
  (`/root/reference/examples/OC4semi-WAMIT_Coefs.yaml:1068-1069`).
* Otherwise it falls back to this framework's native C++ BEM solver
  (potModMaster=2): same pipeline, coefficients solved from the member
  geometry instead of read from files (cached in ``mesh_dir``).

Both `.1`-style period files and HAMS omega-format files are read
(auto-detected; override with ``platform: hydroFreqType``).
"""
import os
import sys

from raft_tpu.io.designs import load_design
from raft_tpu.model import Model

DEFAULT_WAMIT = "/root/reference/examples/OC4semi-WAMIT_Coefs/marin_semi"


def run_example(wamit_path=DEFAULT_WAMIT, plot_flag=False):
    design = load_design("OC4semi")

    if wamit_path and os.path.isfile(wamit_path + ".1"):
        # WAMIT-format coefficients from files (reference configuration:
        # potFirstOrder reuses the same loader, raft_fowt.py:640-655)
        design["platform"]["potModMaster"] = 1
        design["platform"]["potFirstOrder"] = 1
        design["platform"]["hydroPath"] = wamit_path
        print(f"using WAMIT coefficients from {wamit_path}.1")
    else:
        # no files: solve the coefficients with the native BEM instead
        design["platform"]["potModMaster"] = 2
        print("WAMIT files not found - solving with the native BEM "
              "(potModMaster=2); pass a hydro path to use files")

    model = Model(design)
    model.analyzeUnloaded()
    model.analyzeCases(display=1)

    case0 = model.results["case_metrics"][0][0]
    print(f"case 0: surge_std={float(case0['surge_std']):.3f} m, "
          f"heave_std={float(case0['heave_std']):.3f} m")

    if plot_flag:
        import matplotlib.pyplot as plt
        model.plotResponses()
        plt.show()
    return model


if __name__ == "__main__":
    path = sys.argv[1] if len(sys.argv) > 1 else DEFAULT_WAMIT
    run_example(wamit_path=path, plot_flag=False)
