"""Generate the deep-water wave Green-function kernel tables for the
native BEM core (run once; output committed as greens_table.bin).

The free-surface source potential (John's formula, infinite depth) is

  G = 1/r + 1/r' + 2k L(H,V) + 2*pi*i*k e^V J0(H),
  L(H,V) = PV int_0^inf e^{mu V} J0(mu H) / (mu - 1) dmu,

with H = k*R_horizontal >= 0 and V = k(z+z') < 0.  The gradient needs the
companion kernel M(H,V) = PV int_0^inf e^{mu V} J1(mu H)/(mu-1) dmu via

  dL/dV = 1/d + L              (d = sqrt(H^2+V^2))
  dL/dH = -(1 + V/d)/H - M.

Tabulation strategy (verified numerically in this script):
  - region 1 (H <= H_SPLIT): L and M are smooth -> tabulate raw values
    on (H uniform) x (|V| log-spaced);
  - region 2 (H > H_SPLIT): subtract the standing-wave pole residue,
    Lres = L + pi e^V Y0(H), Mres = M + pi e^V Y1(H) — the residuals
    decay algebraically and are smooth;
  - d > D_FAR: closed-form series
    L ~ -sum_n d^n/dV^n (1/d) - pi e^V Y0(H),
    M ~ -sum_n d^n/dV^n ((1+V/d)/H) - pi e^V Y1(H).

Binary layout (little-endian float64 unless noted):
  magic 'RBEMTBL1' (8 bytes)
  int32: NH1, NV, NH2
  float64: H_SPLIT, H_MAX, VLOG_MIN, VLOG_MAX
  L1[NH1*NV], M1[NH1*NV], L2[NH2*NV], M2[NH2*NV]   (H-major, V-minor)
Grids: region1 H uniform on [0, H_SPLIT]; region2 H uniform in
asinh(H) on [H_SPLIT, H_MAX]; V = -exp(u), u uniform on
[VLOG_MIN, VLOG_MAX] (natural log of |V|).
"""
import struct
import sys

import numpy as np
from scipy.integrate import quad
from scipy.special import j0, j1, y0, y1

H_SPLIT = 6.0
H_MAX = 40.0
VMIN_ABS = 1e-5
VMAX_ABS = 40.0
NH1, NH2, NV = 96, 128, 160


def kernel(H, V, order):
    """Direct PV quadrature of L (order 0) / M (order 1)."""
    bes = j0 if order == 0 else j1
    f = lambda mu: np.exp(mu * V) * bes(mu * H)
    pv, _ = quad(f, 0, 2, weight="cauchy", wvar=1.0, limit=200)
    tail, _ = quad(lambda mu: f(mu) / (mu - 1.0), 2, np.inf, limit=500)
    return pv + tail


def main(out_path):
    H1 = np.linspace(0.0, H_SPLIT, NH1)
    x2 = np.linspace(np.arcsinh(H_SPLIT), np.arcsinh(H_MAX), NH2)
    H2 = np.sinh(x2)
    u = np.linspace(np.log(VMIN_ABS), np.log(VMAX_ABS), NV)
    V = -np.exp(u)

    L1 = np.zeros((NH1, NV))
    M1 = np.zeros((NH1, NV))
    for i, h in enumerate(H1):
        for jv, v in enumerate(V):
            L1[i, jv] = kernel(h, v, 0)
            M1[i, jv] = kernel(h, v, 1) if h > 0 else 0.0
        print(f"region1 {i+1}/{NH1}", end="\r", flush=True)

    L2 = np.zeros((NH2, NV))
    M2 = np.zeros((NH2, NV))
    for i, h in enumerate(H2):
        for jv, v in enumerate(V):
            L2[i, jv] = kernel(h, v, 0) + np.pi * np.exp(v) * y0(h)
            M2[i, jv] = kernel(h, v, 1) + np.pi * np.exp(v) * y1(h)
        print(f"region2 {i+1}/{NH2}", end="\r", flush=True)
    print()

    with open(out_path, "wb") as f:
        f.write(b"RBEMTBL1")
        f.write(struct.pack("<iii", NH1, NV, NH2))
        f.write(struct.pack("<dddd", H_SPLIT, H_MAX,
                            np.log(VMIN_ABS), np.log(VMAX_ABS)))
        f.write(L1.astype("<f8").tobytes())
        f.write(M1.astype("<f8").tobytes())
        f.write(L2.astype("<f8").tobytes())
        f.write(M2.astype("<f8").tobytes())
    print(f"wrote {out_path}")


if __name__ == "__main__":
    main(sys.argv[1] if len(sys.argv) > 1 else "greens_table.bin")
