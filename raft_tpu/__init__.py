"""raft_tpu — TPU-native frequency-domain floating wind turbine framework.

A ground-up JAX/XLA re-design of the capabilities of NREL's RAFT (reference
mounted at /root/reference): strip-theory + potential-flow hydrodynamics of
member-based floating platforms, quasi-static mooring, linearized aero-servo
rotor dynamics, second-order wave loads, multi-turbine arrays, and design
optimization interfaces — with frequencies, load cases, headings, and design
variants as batched array axes sharded over TPU meshes.
"""
from raft_tpu import _config  # noqa: F401  (sets x64 before anything traces)

__version__ = "0.1.0"
