"""Global numerical configuration for raft_tpu.

The frequency-domain solves (batched complex 6x6 linear systems, hydrostatic
stiffness assembly, eigen solves) need float64 to match the CPU reference to
1e-6 (reference regression tolerances: rtol=1e-5/atol=1e-3 on PSDs,
atol~1e-10 on statics — see /root/reference tests/test_model.py,
tests/test_fowt.py).  We therefore enable JAX x64 mode at import unless the
user opts out with RAFT_TPU_X64=0 (e.g. for a pure-throughput bf16/f32 TPU
sweep where accuracy is traded for speed).
"""
import os

import jax

if os.environ.get("RAFT_TPU_X64", "1") != "0":
    jax.config.update("jax_enable_x64", True)

import jax.numpy as jnp  # noqa: E402  (after x64 flag)

#: default real/complex dtypes used when building model arrays
def real_dtype():
    return jnp.float64 if jax.config.jax_enable_x64 else jnp.float32


def complex_dtype():
    return jnp.complex128 if jax.config.jax_enable_x64 else jnp.complex64
