"""Global numerical configuration for raft_tpu.

The frequency-domain solves (batched complex 6x6 linear systems, hydrostatic
stiffness assembly, eigen solves) need float64 to match the CPU reference to
1e-6 (reference regression tolerances: rtol=1e-5/atol=1e-3 on PSDs,
atol~1e-10 on statics — see /root/reference tests/test_model.py,
tests/test_fowt.py).  We therefore enable JAX x64 mode at import unless the
user opts out with RAFT_TPU_X64=0 (e.g. for a pure-throughput bf16/f32 TPU
sweep where accuracy is traded for speed).
"""
import os

import jax

if os.environ.get("RAFT_TPU_X64", "1") != "0":
    jax.config.update("jax_enable_x64", True)

# Persistent XLA compilation cache: warm-start processes skip the XLA
# compile of any program they have compiled before (the executable-cache
# layer in parallel/exec_cache.py additionally skips trace+lower via
# jax.export).  Opt out with RAFT_TPU_JAX_CACHE=0; relocate with
# RAFT_TPU_JAX_CACHE_DIR.  Never fatal: an unwritable cache dir must not
# take down the solver.
if os.environ.get("RAFT_TPU_JAX_CACHE", "1") != "0":
    try:
        jax.config.update(
            "jax_compilation_cache_dir",
            os.environ.get("RAFT_TPU_JAX_CACHE_DIR")
            or os.path.join(os.path.expanduser("~"), ".cache", "raft_tpu",
                            "jax_cache"))
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
    # an unwritable cache dir / older jax without the knob must not
    # take down the solver at import time — the cache is an optimization
    except Exception:  # pragma: no cover  # raftlint: disable=RTL004
        pass

import jax.numpy as jnp  # noqa: E402  (after x64 flag)

#: default real/complex dtypes used when building model arrays
def real_dtype():
    return jnp.float64 if jax.config.jax_enable_x64 else jnp.float32


def complex_dtype():
    return jnp.complex128 if jax.config.jax_enable_x64 else jnp.complex64


# ---------------------------------------------------------------------------
# solve-kernel backend selection (ops/linalg.py, ops/pallas/gj_solve.py)
# ---------------------------------------------------------------------------

#: RAFT_TPU_PALLAS values: "0" never use the Pallas kernel, "1" always
#: (interpret mode on CPU — how CI exercises the identical kernel code
#: path without TPU hardware), "auto" (default) compiled Pallas on
#: accelerator backends for the qualifying small-n/large-batch shapes
#: and the pre-existing jnp paths everywhere else.
_PALLAS_MODES = ("0", "1", "auto")
_pallas_override: str | None = None


def pallas_mode() -> str:
    """Active Pallas dispatch mode ("0" | "1" | "auto").

    Programmatic override (``set_pallas_mode``) beats the
    ``RAFT_TPU_PALLAS`` environment variable; unknown values fall back
    to "auto".  Read lazily at solve-dispatch (trace) time so tests can
    flip it without re-importing."""
    if _pallas_override is not None:
        return _pallas_override
    mode = os.environ.get("RAFT_TPU_PALLAS", "auto").strip().lower()
    return mode if mode in _PALLAS_MODES else "auto"


def set_pallas_mode(mode: str | None):
    """Override the Pallas dispatch mode in-process (None clears the
    override and returns control to ``RAFT_TPU_PALLAS``)."""
    global _pallas_override
    if mode is not None and str(mode) not in _PALLAS_MODES:
        raise ValueError(f"pallas mode {mode!r} not in {_PALLAS_MODES}")
    _pallas_override = None if mode is None else str(mode)


# ---------------------------------------------------------------------------
# fused QTF pair-grid kernel (models/qtf.py, ops/pallas/qtf_pair.py)
# ---------------------------------------------------------------------------

#: RAFT_TPU_QTF_KERNEL values: "1" routes the dense (i1, i2) QTF pair
#: grid through the fused Pallas kernel (interpret mode — the CI parity
#: path, exactly like RAFT_TPU_PALLAS=1 for the solve kernel); "0"
#: forbids it; "auto" (default) keeps the doubly-vmapped XLA path until
#: the kernel's real/imag-split Mosaic port proves on hardware (the
#: body is complex-typed; see ops/pallas/qtf_pair.py).
_QTF_KERNEL_MODES = ("0", "1", "auto")
_qtf_kernel_override: str | None = None


def qtf_kernel_mode() -> str:
    """Active QTF-kernel dispatch mode ("0" | "1" | "auto")."""
    if _qtf_kernel_override is not None:
        return _qtf_kernel_override
    mode = os.environ.get("RAFT_TPU_QTF_KERNEL", "auto").strip().lower()
    return mode if mode in _QTF_KERNEL_MODES else "auto"


def set_qtf_kernel_mode(mode: str | None):
    """Override the QTF-kernel dispatch mode in-process (None clears)."""
    global _qtf_kernel_override
    if mode is not None and str(mode) not in _QTF_KERNEL_MODES:
        raise ValueError(
            f"qtf kernel mode {mode!r} not in {_QTF_KERNEL_MODES}")
    _qtf_kernel_override = None if mode is None else str(mode)


# ---------------------------------------------------------------------------
# mixed-precision solve ladder (ops/linalg.py, ops/pallas/gj_solve.py)
# ---------------------------------------------------------------------------

#: RAFT_TPU_PRECISION values: "f64" (default) solves at the ambient
#: pipeline width (f64 under the default x64 pipeline — today's exact
#: behavior); "mixed" factorizes at the low RAFT_TPU_PRECISION_WIDTH
#: (f32 default, bf16 opt-in) while the refinement residual
#: r = rhs - A x and the correction accumulate at the full input width
#: INSIDE the kernel, and lanes whose final relative residual exceeds
#: RAFT_TPU_PRECISION_TOL are re-solved at the full width in a second
#: pass over only the promoted lanes; "f32" forces the whole solve to
#: f32 (the pure-throughput rung — the pre-ladder accuracy tradeoff,
#: now explicit).  Read lazily at solve-dispatch (trace) time; the mode
#: is part of the exec-cache key (a mixed program is never served for
#: an f64 request).
_PRECISION_MODES = ("f64", "mixed", "f32")
_precision_override: str | None = None


def precision_mode() -> str:
    """Active solve-precision mode ("f64" | "mixed" | "f32");
    programmatic override beats the ``RAFT_TPU_PRECISION`` environment
    variable, unknown values fall back to "f64"."""
    if _precision_override is not None:
        return _precision_override
    mode = os.environ.get("RAFT_TPU_PRECISION", "f64").strip().lower()
    return mode if mode in _PRECISION_MODES else "f64"


def set_precision_mode(mode: str | None):
    """Override the solve-precision mode in-process (None clears)."""
    global _precision_override
    if mode is not None and str(mode) not in _PRECISION_MODES:
        raise ValueError(
            f"precision mode {mode!r} not in {_PRECISION_MODES}")
    _precision_override = None if mode is None else str(mode)


#: RAFT_TPU_PRECISION_WIDTH values: the factorization width the mixed
#: ladder drops to ("f32" default; "bf16" for pipelines already at f32
#: — bf16 shares f32's exponent range, so the equilibration floor is
#: unchanged).
_PRECISION_WIDTHS = ("f32", "bf16")
_precision_width_override: str | None = None


def precision_width() -> str:
    """Active mixed-ladder factorization width ("f32" | "bf16")."""
    if _precision_width_override is not None:
        return _precision_width_override
    w = os.environ.get("RAFT_TPU_PRECISION_WIDTH", "f32").strip().lower()
    return w if w in _PRECISION_WIDTHS else "f32"


def set_precision_width(width: str | None):
    """Override the mixed-ladder factorization width (None clears)."""
    global _precision_width_override
    if width is not None and str(width) not in _PRECISION_WIDTHS:
        raise ValueError(
            f"precision width {width!r} not in {_PRECISION_WIDTHS}")
    _precision_width_override = None if width is None else str(width)


#: default per-lane promotion tolerance: the max relative refinement
#: residual a mixed-solved lane may keep before it is re-solved at the
#: full width.  1e-9 sits three decades under the 1e-6 golden-ledger
#: contract and three above the f64 refinement noise floor (~1e-13 on
#: the equilibrated impedance blocks), so promotion fires on genuinely
#: ill-conditioned lanes, not on refinement jitter.
_PRECISION_TOL_DEFAULT = 1e-9


def precision_tol() -> float:
    """Per-lane promotion tolerance for the mixed ladder
    (``RAFT_TPU_PRECISION_TOL``, default 1e-9); non-numeric values fall
    back to the default."""
    raw = os.environ.get("RAFT_TPU_PRECISION_TOL", "")
    try:
        return float(raw) if raw.strip() else _PRECISION_TOL_DEFAULT
    except ValueError:
        return _PRECISION_TOL_DEFAULT


# ---------------------------------------------------------------------------
# solver-health telemetry placement (model.py dynamics/statics hot path)
# ---------------------------------------------------------------------------

#: RAFT_TPU_TELEMETRY values: "fast" (default) computes the dynamics
#: solve residual and the impedance condition estimate ON DEVICE inside
#: the batched solve program (jnp SVD / einsum, a handful of scalar
#: pulls per case); "full" restores the host-side telemetry — the whole
#: (nw, 6N, 6N) impedance stack is pulled to host and run through
#: ``np.linalg.cond`` / ``np.einsum`` (opt-in: it parks a large
#: device→host transfer plus a host SVD on the critical path).
_TELEMETRY_MODES = ("fast", "full")
_telemetry_override: str | None = None


def telemetry_mode() -> str:
    """Active telemetry placement ("fast" | "full"); programmatic
    override beats the ``RAFT_TPU_TELEMETRY`` environment variable,
    unknown values fall back to "fast"."""
    if _telemetry_override is not None:
        return _telemetry_override
    mode = os.environ.get("RAFT_TPU_TELEMETRY", "fast").strip().lower()
    return mode if mode in _TELEMETRY_MODES else "fast"


def set_telemetry_mode(mode: str | None):
    """Override the telemetry placement in-process (None clears)."""
    global _telemetry_override
    if mode is not None and str(mode) not in _TELEMETRY_MODES:
        raise ValueError(
            f"telemetry mode {mode!r} not in {_TELEMETRY_MODES}")
    _telemetry_override = None if mode is None else str(mode)


# ---------------------------------------------------------------------------
# statics Newton backend (model.py:_solve_statics_impl)
# ---------------------------------------------------------------------------

#: RAFT_TPU_STATICS values: "device" (default) runs the damped-Newton
#: equilibrium as one jitted ``lax.while_loop`` with the 5-alpha line
#: search evaluated in a single vmapped call and exactly one host sync
#: at convergence; "host" restores the Python-driven loop (one
#: device→host pull per Newton iteration plus a serial line search) —
#: kept as the parity reference for tests and as an escape hatch.
_STATICS_MODES = ("device", "host")
_statics_override: str | None = None


def statics_mode() -> str:
    """Active statics Newton backend ("device" | "host")."""
    if _statics_override is not None:
        return _statics_override
    mode = os.environ.get("RAFT_TPU_STATICS", "device").strip().lower()
    return mode if mode in _STATICS_MODES else "device"


def set_statics_mode(mode: str | None):
    """Override the statics backend in-process (None clears)."""
    global _statics_override
    if mode is not None and str(mode) not in _STATICS_MODES:
        raise ValueError(f"statics mode {mode!r} not in {_STATICS_MODES}")
    _statics_override = None if mode is None else str(mode)


def statics_warm() -> bool:
    """Ambient default for statics Newton warm-start seeding in
    ``Model.analyzeCases`` (``RAFT_TPU_STATICS_WARM=1``).  Opt-in:
    seeding changes iteration counts (and the accepted pose at
    solver-tolerance level), so the golden-ledger gates run unseeded."""
    return os.environ.get("RAFT_TPU_STATICS_WARM", "0").strip().lower() \
        in ("1", "on", "true")


# ---------------------------------------------------------------------------
# on-device probe channel (obs/probes.py — live in-flight telemetry)
# ---------------------------------------------------------------------------

#: RAFT_TPU_PROBES values: "off" — probes are trace-time no-ops (the
#: compiled programs are bit-identical to the pre-probe stack);
#: "sampled" (default) — coarse sites stream through jax.debug.callback
#: (statics Newton counts, drag fixed-point residuals per iteration,
#: sweep chunk residuals, per-lane finite flags); "full" — adds the
#: high-rate sites tagged level="full".  Read at TRACE time: functions
#: traced under one mode keep their instrumentation until retraced.
_PROBE_MODES = ("off", "sampled", "full")
_probes_override: str | None = None


def probes_mode() -> str:
    """Active probe mode ("off" | "sampled" | "full"); programmatic
    override beats the ``RAFT_TPU_PROBES`` environment variable,
    unknown values fall back to "sampled"."""
    if _probes_override is not None:
        return _probes_override
    mode = os.environ.get("RAFT_TPU_PROBES", "sampled").strip().lower()
    if mode in ("0", "false"):
        mode = "off"
    return mode if mode in _PROBE_MODES else "sampled"


def set_probes_mode(mode: str | None):
    """Override the probe mode in-process (None clears).  Only affects
    functions traced AFTER the change."""
    global _probes_override
    if mode is not None and str(mode) not in _PROBE_MODES:
        raise ValueError(f"probes mode {mode!r} not in {_PROBE_MODES}")
    _probes_override = None if mode is None else str(mode)


# ---------------------------------------------------------------------------
# automatic recovery (recovery.py ladder + model.py case quarantine)
# ---------------------------------------------------------------------------

#: RAFT_TPU_RECOVERY values: "1" (default) — typed solver failures walk
#: the degradation ladder and unrecoverable cases are quarantined so the
#: rest of the sweep completes; "0" — pre-recovery behavior: the first
#: typed failure propagates out of analyzeCases/sweep_cases unchanged.
_RECOVERY_MODES = ("0", "1")
_recovery_override: str | None = None


def recovery_mode() -> str:
    """Active recovery mode ("0" | "1"); programmatic override beats
    the ``RAFT_TPU_RECOVERY`` environment variable."""
    if _recovery_override is not None:
        return _recovery_override
    mode = os.environ.get("RAFT_TPU_RECOVERY", "1").strip().lower()
    if mode in ("off", "false"):
        mode = "0"
    return mode if mode in _RECOVERY_MODES else "1"


def set_recovery_mode(mode: str | None):
    """Override the recovery mode in-process (None clears)."""
    global _recovery_override
    if mode is not None and str(mode) not in _RECOVERY_MODES:
        raise ValueError(f"recovery mode {mode!r} not in {_RECOVERY_MODES}")
    _recovery_override = None if mode is None else str(mode)


# ---------------------------------------------------------------------------
# batched solve-health telemetry (parallel/sweep.py, parallel/optimize.py)
# ---------------------------------------------------------------------------

#: RAFT_TPU_HEALTH values: "0" (default) — batched programs are compiled
#: without the health block and the exec-cache keys stay byte-identical
#: to pre-health builds; "1" — solve_batched and the optimize summary
#: additionally report per-lane relative residuals, a conditioning proxy
#: and nonfinite-lane counts (the exec-cache key forks on ``health``).
_HEALTH_MODES = ("0", "1")
_health_override: str | None = None


def health_mode() -> str:
    """Active solve-health mode ("0" | "1"); programmatic override beats
    the ``RAFT_TPU_HEALTH`` environment variable."""
    if _health_override is not None:
        return _health_override
    mode = os.environ.get("RAFT_TPU_HEALTH", "0").strip().lower()
    if mode in ("off", "false"):
        mode = "0"
    if mode in ("on", "true"):
        mode = "1"
    return mode if mode in _HEALTH_MODES else "0"


def set_health_mode(mode: str | None):
    """Override the solve-health mode in-process (None clears)."""
    global _health_override
    if mode is not None and str(mode) not in _HEALTH_MODES:
        raise ValueError(f"health mode {mode!r} not in {_HEALTH_MODES}")
    _health_override = None if mode is None else str(mode)


def health_enabled() -> bool:
    """True when batched solve-health telemetry is on."""
    return health_mode() == "1"
