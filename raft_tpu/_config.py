"""Global numerical configuration for raft_tpu.

The frequency-domain solves (batched complex 6x6 linear systems, hydrostatic
stiffness assembly, eigen solves) need float64 to match the CPU reference to
1e-6 (reference regression tolerances: rtol=1e-5/atol=1e-3 on PSDs,
atol~1e-10 on statics — see /root/reference tests/test_model.py,
tests/test_fowt.py).  We therefore enable JAX x64 mode at import unless the
user opts out with RAFT_TPU_X64=0 (e.g. for a pure-throughput bf16/f32 TPU
sweep where accuracy is traded for speed).
"""
import os

import jax

if os.environ.get("RAFT_TPU_X64", "1") != "0":
    jax.config.update("jax_enable_x64", True)

# Persistent XLA compilation cache: warm-start processes skip the XLA
# compile of any program they have compiled before (the executable-cache
# layer in parallel/exec_cache.py additionally skips trace+lower via
# jax.export).  Opt out with RAFT_TPU_JAX_CACHE=0; relocate with
# RAFT_TPU_JAX_CACHE_DIR.  Never fatal: an unwritable cache dir must not
# take down the solver.
if os.environ.get("RAFT_TPU_JAX_CACHE", "1") != "0":
    try:
        jax.config.update(
            "jax_compilation_cache_dir",
            os.environ.get("RAFT_TPU_JAX_CACHE_DIR")
            or os.path.join(os.path.expanduser("~"), ".cache", "raft_tpu",
                            "jax_cache"))
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
    except Exception:                                 # pragma: no cover
        pass

import jax.numpy as jnp  # noqa: E402  (after x64 flag)

#: default real/complex dtypes used when building model arrays
def real_dtype():
    return jnp.float64 if jax.config.jax_enable_x64 else jnp.float32


def complex_dtype():
    return jnp.complex128 if jax.config.jax_enable_x64 else jnp.complex64


# ---------------------------------------------------------------------------
# solve-kernel backend selection (ops/linalg.py, ops/pallas/gj_solve.py)
# ---------------------------------------------------------------------------

#: RAFT_TPU_PALLAS values: "0" never use the Pallas kernel, "1" always
#: (interpret mode on CPU — how CI exercises the identical kernel code
#: path without TPU hardware), "auto" (default) compiled Pallas on
#: accelerator backends for the qualifying small-n/large-batch shapes
#: and the pre-existing jnp paths everywhere else.
_PALLAS_MODES = ("0", "1", "auto")
_pallas_override: str | None = None


def pallas_mode() -> str:
    """Active Pallas dispatch mode ("0" | "1" | "auto").

    Programmatic override (``set_pallas_mode``) beats the
    ``RAFT_TPU_PALLAS`` environment variable; unknown values fall back
    to "auto".  Read lazily at solve-dispatch (trace) time so tests can
    flip it without re-importing."""
    if _pallas_override is not None:
        return _pallas_override
    mode = os.environ.get("RAFT_TPU_PALLAS", "auto").strip().lower()
    return mode if mode in _PALLAS_MODES else "auto"


def set_pallas_mode(mode: str | None):
    """Override the Pallas dispatch mode in-process (None clears the
    override and returns control to ``RAFT_TPU_PALLAS``)."""
    global _pallas_override
    if mode is not None and str(mode) not in _PALLAS_MODES:
        raise ValueError(f"pallas mode {mode!r} not in {_PALLAS_MODES}")
    _pallas_override = None if mode is None else str(mode)
