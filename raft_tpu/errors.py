"""Typed error taxonomy for the case-execution layer.

Every failure the solver stack can diagnose gets a typed exception that
carries *structured* context (case index, phase, FOWT index, active
solver configuration) instead of a bare ``Exception``/``RuntimeError``
with the facts baked into the message string.  The recovery layer
(:mod:`raft_tpu.recovery`) keys its degradation ladder off these types,
the per-case quarantine in ``Model.analyzeCases`` serializes their
:meth:`RaftError.context` into the run manifest and result ledger
(``extra["failed_cases"]``), and tests can assert on the class rather
than regex-matching messages.

Back-compat: callers that caught the old builtin classes keep working —
:class:`NonFiniteResult` is a ``FloatingPointError`` *and* a
``ValueError`` (the two builtins it replaces in ``model.py`` and
``io/wamit.py``), :class:`StaticsDivergence`/:class:`DynamicsSingular`/
:class:`EigenFailure` are ``RuntimeError``\\ s, and
:class:`ModelConfigError` is a ``ValueError``.
"""
from __future__ import annotations


class RaftError(Exception):
    """Base of the raft_tpu error taxonomy.

    ``context`` keyword arguments are retained verbatim on the instance
    (``err.ctx``) and rendered into the message; :meth:`context` returns
    the JSON-able record the quarantine/manifest layers persist.
    """

    #: phase tag the recovery ladder dispatches on; subclasses override
    phase = "unknown"

    def __init__(self, message: str = "", **context):
        self.ctx = dict(context)
        self.injected = bool(self.ctx.pop("injected", False))
        super().__init__(message)

    def __str__(self):
        base = super().__str__()
        facts = ", ".join(f"{k}={v}" for k, v in sorted(self.ctx.items()))
        inj = " [injected]" if self.injected else ""
        return f"{base}{inj}" + (f" ({facts})" if facts else "")

    def context(self) -> dict:
        """JSON-able structured record of this failure.  Non-finite
        floats become the strings ``"nan"``/``"inf"`` — ``json.dump``
        would otherwise emit bare ``NaN`` literals (invalid strict
        JSON) into the run manifest for exactly the failed runs the
        record documents."""
        import math

        out = {"error": type(self).__name__, "phase": self.phase,
               "message": Exception.__str__(self),
               "injected": self.injected}
        for k, v in self.ctx.items():
            if isinstance(v, float) and not math.isfinite(v):
                v = "nan" if math.isnan(v) else (
                    "inf" if v > 0 else "-inf")
            out[str(k)] = v if isinstance(v, (bool, int, float, str,
                                              type(None))) else str(v)
        return out


class StaticsDivergence(RaftError, RuntimeError):
    """The mean-offset Newton produced a non-finite pose or diverged."""

    phase = "statics"


class DynamicsSingular(RaftError, RuntimeError):
    """The frequency-domain impedance system is singular or otherwise
    unsolvable (near-singular factor, solve blow-up)."""

    phase = "dynamics"


class NonFiniteResult(RaftError, FloatingPointError, ValueError):
    """A solver output or parsed input carries NaN/Inf.

    Subclasses both ``FloatingPointError`` (the old ``solveDynamics``
    sanitizer raise) and ``ValueError`` (the old ``io.wamit``
    corrupt-file raise) so pre-taxonomy ``except`` clauses keep
    working.
    """

    phase = "dynamics"


class KernelFailure(RaftError, RuntimeError):
    """A solve kernel (Pallas / XLA program) failed to trace, compile,
    or execute — the ladder's cue to degrade Pallas -> jnp -> host."""

    phase = "dynamics"


class CacheCorruption(RaftError, RuntimeError):
    """A persisted artifact (executable cache entry, QTF snapshot)
    failed its integrity check.  The caches recover by delete-and-miss;
    this type surfaces only when a caller opts into strict mode."""

    phase = "cache"


class JournalCorrupt(CacheCorruption):
    """A write-ahead/resume journal record failed to parse or verify
    (torn tail, bit rot, schema drift).  Replay treats corruption as a
    skip-and-count miss by default — this type surfaces only when a
    caller opts into strict scanning (``serve.journal.replay(...,
    strict=True)``), and inherits :class:`CacheCorruption` so existing
    integrity handling keeps working."""

    phase = "journal"


class ResultStoreCorrupt(CacheCorruption):
    """A persistent result-store entry (:mod:`raft_tpu.serve.resultstore`)
    failed an integrity check — size/sha256 sidecar mismatch, a torn or
    unparseable payload, a key/payload digest disagreement (a *stale*
    entry answering for the wrong request), or a payload whose recorded
    result digest no longer matches its own metrics.  The store recovers
    by delete-and-miss (the request re-solves; the corruption is counted
    in ``raft_tpu_serve_result_store_corrupt_total``); this type
    surfaces only when a caller opts into strict reads."""

    phase = "cache"


class StorageExhausted(RaftError, OSError):
    """A persistence tier (WAL, result store, checkpoint store, exec
    cache) hit *proven* resource exhaustion — an ``ENOSPC`` write
    failure, or a configured ``disk_budget_bytes`` ceiling.  Raised only
    from write paths whose failure the caller can shed gracefully: the
    service degradation ladder drops checkpointing first, then the
    result-store write-through, while admission and delivery stay alive
    on a full disk (``docs/robustness.md`` "Preemption & storage").
    ``OSError`` base keeps pre-taxonomy filesystem handling working."""

    phase = "storage"


class WarmStartRejected(RaftError, RuntimeError):
    """A neighbor-seeded (warm-started) solve tripped the divergence
    guard — the seeded iteration failed to converge, went non-finite,
    or regressed past the cold-start bound — and the service fell back
    to a cold start, quarantining the offending neighbor seed.  This is
    a *degradation signal* recorded per occurrence (event + counter +
    summary fact), never a caller-visible failure: the fallback result
    is always delivered, bit-identical to a cold start."""

    phase = "serve"


class EigenFailure(RaftError, RuntimeError):
    """The eigen solve produced unusable system matrices or
    non-positive eigenvalues."""

    phase = "eigen"


class MooringSingular(RaftError, RuntimeError):
    """A mooring tension Jacobian / stiffness evaluation is singular —
    degraded to NaN tension channels by the case loop."""

    phase = "outputs"


class ModelConfigError(RaftError, ValueError):
    """The model/design configuration cannot be analyzed as requested
    (not recoverable by the ladder — the input itself is wrong)."""

    phase = "setup"


class PartitionRuleError(RaftError, ValueError):
    """The partition layer cannot place a pytree on the mesh as asked —
    an unmatched leaf, a mesh/axes shape mismatch, or a mesh wanting
    more devices than exist (not recoverable by the ladder: the sharding
    request itself is wrong; see parallel/partition.py)."""

    phase = "setup"


class AdmissionRejected(RaftError, RuntimeError):
    """The serving layer (:mod:`raft_tpu.serve`) refused a request at
    admission — queue depth or deadline pressure beyond the configured
    watermarks, or the service is in its ``reject`` degradation mode.

    Carries ``retry_after_s`` (the load-shed hint: the caller's earliest
    useful resubmission time, estimated from queue depth and the
    observed batch cadence) as an attribute and in :meth:`context`.
    Deliberately NOT recoverable by the in-process ladder: backpressure
    only works if the rejection reaches the caller."""

    phase = "admission"

    def __init__(self, message: str = "", retry_after_s: float = 0.0,
                 **context):
        self.retry_after_s = float(retry_after_s)
        super().__init__(message, retry_after_s=self.retry_after_s,
                         **context)


class ReplicaLagExceeded(RaftError, RuntimeError):
    """The write-ahead-journal mirror (:mod:`raft_tpu.serve.replica`)
    fell further behind the primary than the configured record budget —
    a *degradation signal*, not a crash: the service keeps serving (and
    the mirror keeps catching up), but a failover while this condition
    holds could lose the lagging tail.  Surfaces as a typed raise only
    from :meth:`WalMirror.check` (strict callers: health gates, tests);
    the serving loop folds it into the degradation ladder instead."""

    phase = "replication"


class DeadlineExceeded(RaftError, TimeoutError):
    """A request (or the batch carrying it) overran its deadline — the
    serving watchdog's abandon signal and the typed failure a
    quarantined-for-hanging request reports.  ``TimeoutError`` base
    keeps pre-taxonomy timeout handling working."""

    phase = "serve"


class FaultInjected(RaftError, RuntimeError):
    """Raised by :mod:`raft_tpu.testing.faults` for ``raise@...`` specs
    at sites without a more specific mapped type."""

    phase = "injected"


#: failure types the degradation ladder may retry (everything a solver
#: can plausibly survive by changing backend/precision/damping);
#: configuration errors and cache corruption are excluded on purpose
RECOVERABLE = (StaticsDivergence, DynamicsSingular, NonFiniteResult,
               KernelFailure, FaultInjected)
