"""ctypes wrapper for the native BEM core (native/bem/bem.cpp).

The in-process equivalent of the reference's pyHAMS path (reference:
raft_fowt.py:596-650 writes mesh files, spawns the HAMS Fortran solver and
reads WAMIT files back): here the panel mesh goes straight to the C++
solver and the coefficients come back as arrays, which `solve_bem_fowt`
packs into the same `BEMData` the WAMIT readers produce — so potModMaster=2
works without precomputed coefficient files.

The shared library is built on demand with the checked-in Makefile (g++ +
system LAPACK); the wave-kernel tables ship as greens_table.bin.
"""
from __future__ import annotations

import ctypes as ct
import os
import subprocess

import numpy as np

from raft_tpu import errors

_NATIVE_DIR = os.path.join(os.path.dirname(os.path.dirname(
    os.path.dirname(os.path.abspath(__file__)))), "native", "bem")
_LIB_PATH = os.path.join(_NATIVE_DIR, "libraftbem.so")
_TABLE_PATH = os.path.join(_NATIVE_DIR, "greens_table.bin")

_lib = None
_load_error = None


def available() -> bool:
    """True when the native core can be (built and) loaded.  On failure
    the underlying build/load error is kept in ``load_error()`` so callers
    can surface the real diagnostic instead of a generic hint."""
    global _load_error
    try:
        _load()
        return True
    except subprocess.CalledProcessError as e:
        _load_error = (e.stderr or b"").decode(errors="replace")[-2000:]
        return False
    # building/ctypes-loading a C core fails in arbitrary ways (missing
    # toolchain, ABI drift, bad ELF); ANY of them just means "native
    # core unavailable" — captured verbatim for load_error(), and the
    # caller raises the typed KernelFailure with it
    except Exception as e:  # raftlint: disable=RTL004
        _load_error = str(e)
        return False


def load_error():
    """The captured reason the native core failed to build/load."""
    return _load_error


def _load():
    global _lib
    if _lib is not None:
        return _lib
    if not os.path.isfile(_LIB_PATH):
        subprocess.run(["make"], cwd=_NATIVE_DIR, check=True,
                       capture_output=True)
    if not os.path.isfile(_TABLE_PATH):
        raise FileNotFoundError(
            f"{_TABLE_PATH} missing — run native/bem/make_tables.py")
    lib = ct.CDLL(_LIB_PATH)
    lib.raft_bem_load_tables.argtypes = [ct.c_char_p]
    lib.raft_bem_load_tables.restype = ct.c_int
    lib.raft_bem_solve2.argtypes = [
        ct.POINTER(ct.c_double), ct.c_int,          # verts
        ct.POINTER(ct.c_int32), ct.c_int, ct.c_int,  # panels, nbody
        ct.POINTER(ct.c_double), ct.c_int,          # omegas
        ct.POINTER(ct.c_double), ct.c_int,          # betas
        ct.c_double, ct.c_double, ct.c_double,      # rho, g, depth
        ct.POINTER(ct.c_double), ct.POINTER(ct.c_double),
        ct.POINTER(ct.c_double), ct.POINTER(ct.c_double)]
    lib.raft_bem_solve2.restype = ct.c_int
    if lib.raft_bem_load_tables(_TABLE_PATH.encode()) != 0:
        # IS a RuntimeError — pre-taxonomy catchers keep working
        raise errors.KernelFailure(
            f"failed to load Green-function tables from {_TABLE_PATH}",
            kernel="bem_native")
    _lib = lib
    return lib


def solve_radiation_diffraction(mesh, omegas, betas_deg, rho=1025.0,
                                g=9.81, depth=0.0):
    """Run the native solver on a PanelMesh.

    Returns (A (nw,6,6), B (nw,6,6), X (nw,nbeta,6) complex) about the
    origin (PRP), per unit wave amplitude.  ``depth`` > 0 selects the
    finite-depth Green function (John's eigenfunction series; the solver
    switches itself to the deep-water kernel above k0*h ~ 25 where the
    two agree to machine precision); 0 means deep water.
    """
    lib = _load()
    verts = np.ascontiguousarray(mesh.verts, dtype=np.float64)
    panels = np.ascontiguousarray(mesh.panels, dtype=np.int32)
    omegas = np.ascontiguousarray(np.atleast_1d(omegas), dtype=np.float64)
    betas = np.ascontiguousarray(np.deg2rad(np.atleast_1d(betas_deg)),
                                 dtype=np.float64)
    nw, nb = len(omegas), len(betas)
    A = np.zeros((nw, 6, 6))
    B = np.zeros((nw, 6, 6))
    Xre = np.zeros((nw, nb, 6))
    Xim = np.zeros((nw, nb, 6))

    def p(a, t=ct.c_double):
        return a.ctypes.data_as(ct.POINTER(t))

    rc = lib.raft_bem_solve2(
        p(verts), len(verts), p(panels, ct.c_int32), len(panels),
        int(getattr(mesh, "nbody", len(panels))),
        p(omegas), nw, p(betas), nb, float(rho), float(g), float(depth),
        p(A), p(B), p(Xre), p(Xim))
    if rc != 0:
        raise errors.KernelFailure(f"raft_bem_solve failed (rc={rc})",
                                   kernel="bem_native", rc=int(rc))
    return A, B, Xre + 1j * Xim


def solve_bem_fowt(fowt, headings=None, dz=None, da=None, w_bem=None,
                   mesh_dir=None, max_freqs=48, dw_bem=None):
    """Mesh a FOWT's potMod members, run the native BEM core, and return a
    `BEMData` on the model frequency grid — the in-process replacement for
    the reference's calcBEM/pyHAMS round trip (reference:
    raft_fowt.py:568-717).

    - BEM frequencies default to a decimated model grid (the reference's
      coarser dw_BEM grid + interpolation, raft_fowt.py:121-122, 678-683),
      capped at ``max_freqs`` solves.
    - ``mesh_dir`` (reference's meshDir) acts as a coefficient cache: if
      WAMIT `.1/.3` files exist there they are loaded instead of re-solving,
      and fresh solves are written back in WAMIT format (the reference's
      HAMS output directory plays the same role, raft_fowt.py:652).
    - X is conjugated from the solver's e^{-i w t} convention into the
      WAMIT/e^{+i w t} convention the framework uses throughout (calibrated
      against the strip-theory excitation path in tests/test_bem_native.py).
    """
    import os as _os
    from raft_tpu.io.mesh import mesh_fowt_members, write_pnl
    from raft_tpu.io import wamit as _wamit

    import hashlib

    rho, g = fowt.rho_water, fowt.g
    if headings is None:
        headings = np.arange(0.0, 360.0, 30.0)
    headings = np.asarray(headings, float)


    mesh = None
    key = None
    if mesh_dir is not None:
        # cache key over geometry + discretization + solve settings so a
        # changed design cannot silently reload stale coefficients
        from raft_tpu.io.mesh import mesh_fowt_members as _mesh_members
        mesh = _mesh_members(fowt, dz_max=dz or 3.0, da_max=da or 2.0)
        h = hashlib.sha256()
        h.update(np.ascontiguousarray(mesh.verts).tobytes())
        h.update(np.ascontiguousarray(mesh.panels).tobytes())
        h.update(np.asarray(fowt.w, float).tobytes())
        # the BEM grid is part of the key: a custom w_bem (preprocess_BEM)
        # must not reload coefficients solved on a different grid
        h.update(np.asarray(w_bem if w_bem is not None else [], float)
                 .tobytes())
        h.update(np.array([max_freqs,
                           -1.0 if dw_bem is None else float(dw_bem)],
                          float).tobytes())
        h.update(headings.tobytes())
        h.update(np.array([rho, g, fowt.depth, mesh.nbody]).tobytes())
        # physics-version token: cached coefficients solved by an older
        # kernel (e.g. deep-water-only) must not be silently reloaded
        h.update(b"raftbem-v2-finite-depth")
        key = h.hexdigest()
        key_path = _os.path.join(mesh_dir, "cache_key.txt")
        if (_os.path.isfile(_os.path.join(mesh_dir, "Output.1"))
                and _os.path.isfile(key_path)
                and open(key_path).read().strip() == key):
            return _wamit.load_bem(_os.path.join(mesh_dir, "Output"),
                                   fowt.w, rho=rho, g=g)
        if _os.path.isfile(key_path):
            # a stale key means geometry/grid/solver-version changed —
            # including key-scheme upgrades, which invalidate every older
            # cache; say so instead of silently re-solving everything
            # (warn, not print: stdout stays machine-parseable for the
            # bench's one-JSON-line contract).  Only when the STORED key
            # actually differs: a matching key with the coefficient
            # files themselves missing (partial cache wipe) is a plain
            # re-solve, not a key change
            try:
                stored_key = open(key_path).read().strip()
            except OSError:
                stored_key = None
            if stored_key != key:
                import warnings
                warnings.warn(
                    f"raft_tpu bem: cache key changed in '{mesh_dir}' "
                    "(geometry, BEM grid, or solver/key version) — "
                    "re-solving and refreshing the cache")

    if w_bem is None:
        # BEM grid: ``dw_bem`` (the reference's min_freq_BEM step,
        # raft_fowt.py:121-122) or the decimated model grid; either way
        # the max_freqs cost cap applies
        if dw_bem is not None:
            dw = float(dw_bem)
        else:
            dw = float(fowt.w[0]) if len(fowt.w) < 2 \
                else float(fowt.w[1] - fowt.w[0])
        w_bem = np.arange(dw, fowt.w[-1] + 0.5 * dw, dw)
        while len(w_bem) > max_freqs:
            w_bem = w_bem[::2]
        if len(w_bem) == 0 or w_bem[-1] < fowt.w[-1]:
            w_bem = np.r_[w_bem, fowt.w[-1]]
    w_bem = np.asarray(w_bem, float)

    if mesh is None:
        mesh = mesh_fowt_members(fowt, dz_max=dz or 3.0, da_max=da or 2.0)
    # finite-depth Green function below k0*h ~ 25, deep-water kernel above
    # (the solver switches per frequency; see native/bem/bem.cpp)
    A, B, X = solve_radiation_diffraction(mesh, w_bem, headings, rho, g,
                                          depth=float(fowt.depth))
    X = np.conj(X)

    # reorder to the WAMIT reader's layout: (6,6,nf) and (nh,6,nf)
    A_t = np.moveaxis(A, 0, -1)
    B_t = np.moveaxis(B, 0, -1)
    X_t = np.moveaxis(X, 0, -1)        # (nbeta,6,nf)

    if mesh_dir is not None:
        _os.makedirs(mesh_dir, exist_ok=True)
        write_pnl(mesh, mesh_dir)        # body panels only (no lid)
        _wamit.write_wamit1(_os.path.join(mesh_dir, "Output.1"),
                            w_bem, A_t, B_t, rho=rho)
        _wamit.write_wamit3(_os.path.join(mesh_dir, "Output.3"),
                            w_bem, headings, X_t, rho=rho, g=g)
        with open(_os.path.join(mesh_dir, "cache_key.txt"), "w") as f:
            f.write(key)
        return _wamit.load_bem(_os.path.join(mesh_dir, "Output"),
                               fowt.w, rho=rho, g=g)

    # pack a BEMData directly (same steps as load_bem: zero-frequency pad,
    # model-grid interpolation, wave-frame rotation)
    from raft_tpu.io.wamit import BEMData, _interp_freq, rotate_to_wave_frame
    A_m = _interp_freq(fowt.w, w_bem, A_t, A_t[..., 0])
    B_m = _interp_freq(fowt.w, w_bem, B_t, np.zeros((6, 6)))
    X_m = _interp_freq(fowt.w, w_bem, X_t, np.zeros_like(X_t[..., 0]))
    return BEMData(A_BEM=A_m, B_BEM=B_m,
                   X_BEM=rotate_to_wave_frame(X_m, headings),
                   headings=headings)
