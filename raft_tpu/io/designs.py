"""Shared design-YAML resolution for driver entry points, bench, tests.

One canonical lookup for named reference designs so bench.py,
__graft_entry__ and tests all load the SAME yaml (they previously kept
three hand-rolled fallback copies that could silently diverge)."""
import os

#: search roots, in priority order: the reference checkout (parity tests
#: pin against its copies when present), a designs/ directory next to the
#: repo root (user overrides in a source checkout), then the yamls
#: vendored as package data (raft_tpu/designs — works for wheel installs)
_SEARCH_DIRS = (
    "/root/reference/designs",
    os.path.join(os.path.dirname(os.path.dirname(
        os.path.dirname(os.path.abspath(__file__)))), "designs"),
    os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "designs"),
)


def design_path(name: str) -> str:
    """Absolute path of the named design yaml (e.g. 'VolturnUS-S')."""
    fname = name if name.endswith((".yaml", ".yml")) else name + ".yaml"
    for root in _SEARCH_DIRS:
        path = os.path.join(root, fname)
        if os.path.isfile(path):
            return path
    raise FileNotFoundError(
        f"design '{fname}' not found in {list(_SEARCH_DIRS)}")


def load_design(name: str) -> dict:
    """Load the named design yaml into a dict."""
    import yaml
    with open(design_path(name)) as f:
        return yaml.safe_load(f)
