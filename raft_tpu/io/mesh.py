"""Panel meshing for potential-flow members + HAMS/WAMIT mesh writers.

Equivalent of the reference's mesh sidecar (reference: raft/member2pnl.py):
axisymmetric members are revolved into quad panels with the same
discretization policy — ``dz_max`` longitudinal panel height, ``da_max``
azimuthal width with power-of-two azimuth doubling as radius grows,
waterline clipping, and radial end-cap fill (member2pnl.py:73-278) — then
written as a HAMS ``HullMesh.pnl`` (member2pnl.py:280-310) or WAMIT
``.gdf`` (member2pnl.py:496-546).

The mesh feeds the native BEM core (raft_tpu/io/bem_native.py) and can be
exported for external solvers, mirroring how the reference feeds pyHAMS
(raft_fowt.py:607-650).
"""
from __future__ import annotations

import os
from dataclasses import dataclass

import numpy as np

from raft_tpu.errors import ModelConfigError


@dataclass
class PanelMesh:
    """Quad panel mesh: vertices (N,3) and panels (M,4) vertex indices.

    Triangles repeat the last index.  Panel vertex order gives outward
    normals (into the fluid) via the right-hand rule.

    ``n_body``: the first n_body panels are the wetted body surface; any
    panels after them are interior-waterplane LID panels used by the BEM
    core's irregular-frequency removal (extended boundary condition).
    Negative means all panels are body panels.
    """

    verts: np.ndarray
    panels: np.ndarray
    n_body: int = -1

    @property
    def nbody(self):
        return self.npanels if self.n_body < 0 else self.n_body

    @property
    def npanels(self):
        return len(self.panels)

    def panel_geometry(self):
        """(centroids (M,3), normals (M,3) unit OUTWARD, areas (M,)).

        Quads are split into two triangles; the normal is the area-weighted
        mean (flat-panel approximation, same as low-order BEM codes).  The
        stored vertex order replicates the reference generator's (so .pnl
        and .gdf exports are bit-compatible); its right-hand-rule normal
        points outward (into the fluid), verified on the cylinder test."""
        v = self.verts[self.panels]          # (M, 4, 3)
        a, b, c, d = v[:, 0], v[:, 1], v[:, 2], v[:, 3]
        n1 = 0.5 * np.cross(b - a, c - a)
        n2 = 0.5 * np.cross(c - a, d - a)
        n = n1 + n2
        area = np.linalg.norm(n, axis=1)
        area1 = np.linalg.norm(n1, axis=1)
        area2 = np.linalg.norm(n2, axis=1)
        cen1 = (a + b + c) / 3.0
        cen2 = (a + c + d) / 3.0
        w = np.where(area1 + area2 > 0, area1 + area2, 1.0)[:, None]
        cen = (cen1 * area1[:, None] + cen2 * area2[:, None]) / w
        nrm = n / np.where(area > 0, area, 1.0)[:, None]
        return cen, nrm, area

    def volume_centroid(self):
        """Displaced volume and center of buoyancy by the divergence
        theorem over the wetted surface (the z=0 lid contributes zero)."""
        cen, nrm, area = self.panel_geometry()
        anz = area * nrm[:, 2]
        V = np.sum(anz * cen[:, 2])
        if V <= 0:
            return 0.0, np.zeros(3)
        rb = np.zeros(3)
        rb[0] = np.sum(anz * cen[:, 2] * cen[:, 0]) / V
        rb[1] = np.sum(anz * cen[:, 2] * cen[:, 1]) / V
        rb[2] = 0.5 * np.sum(anz * cen[:, 2] ** 2) / V
        return V, rb


class _MeshBuilder:
    """Node-deduplicating accumulator (reference: member2pnl.py:8-71)."""

    def __init__(self):
        self.nodes = []
        self.index = {}
        self.panels = []

    def add_panel(self, X, Y, Z):
        Z = np.asarray(Z, float)
        if np.all(Z > 0.0):       # fully above water: skip
            return
        Z = np.minimum(Z, 0.0)    # clip to the waterline
        ids = []
        for i in range(4):
            key = (round(float(X[i]), 6), round(float(Y[i]), 6),
                   round(float(Z[i]), 6))
            j = self.index.get(key)
            if j is None:
                j = len(self.nodes)
                self.nodes.append([key[0], key[1], key[2]])
                self.index[key] = j
            if j in ids:
                continue          # degenerate edge -> triangle
            ids.append(j)
        if len(ids) < 3:
            return                # fully degenerate panel
        if len(ids) == 3:
            ids.append(ids[-1])
        self.panels.append(ids)

    def mesh(self) -> PanelMesh:
        return PanelMesh(np.asarray(self.nodes, float),
                         np.asarray(self.panels, int))


def _radius_profile(stations, radii, dz_max, da_max):
    """Discretize the (station, radius) profile with slope-weighted panel
    sizes and radial end fills (reference: member2pnl.py:113-165)."""
    r_rp = [radii[0]]
    z_rp = [stations[0]]
    for i_s in range(1, len(radii)):
        dr_s = radii[i_s] - radii[i_s - 1]
        dz_s = stations[i_s] - stations[i_s - 1]
        if dr_s == 0:
            cos_m, sin_m, dz_ps = 1.0, 0.0, dz_max
        elif dz_s == 0:
            cos_m, sin_m, dz_ps = 0.0, np.sign(dr_s), 0.6 * da_max
        else:
            m = dr_s / dz_s
            dz_ps = (np.arctan(np.abs(m)) * 2 / np.pi * 0.6 * da_max
                     + np.arctan(abs(1 / m)) * 2 / np.pi * dz_max)
            h = np.sqrt(dr_s**2 + dz_s**2)
            cos_m, sin_m = dz_s / h, dr_s / h
        seg = np.sqrt(dr_s**2 + dz_s**2)
        n_z = max(int(np.ceil(seg / dz_ps)), 1)
        d_l = seg / n_z
        for i_z in range(1, n_z + 1):
            r_rp.append(radii[i_s - 1] + sin_m * i_z * d_l)
            z_rp.append(stations[i_s - 1] + cos_m * i_z * d_l)

    # radial fill of end B then end A (caps)
    if radii[-1] > 0:
        n_r = int(np.ceil(radii[-1] / (0.6 * da_max)))
        dr = radii[-1] / n_r
        for i_r in range(n_r):
            r_rp.append(radii[-1] - (1 + i_r) * dr)
            z_rp.append(stations[-1])
    if radii[0] > 0:
        n_r = int(np.ceil(radii[0] / (0.6 * da_max)))
        dr = radii[0] / n_r
        for i_r in range(n_r):
            r_rp.insert(0, radii[0] - (1 + i_r) * dr)
            z_rp.insert(0, stations[0])
    return r_rp, z_rp


def mesh_member(stations, diameters, rA, rB, dz_max=0.0, da_max=0.0,
                builder: _MeshBuilder = None) -> _MeshBuilder:
    """Mesh one axisymmetric member into quad panels (reference:
    member2pnl.py:73-278 meshMember).

    ``stations`` are axial positions from end A (any monotonic scale whose
    span equals the member length), ``diameters`` the matching outer
    diameters.  The revolved profile is rotated by the member incline
    (Z1Y2Z3 Euler, reference :246-259) and translated to ``rA``; panels
    fully above the waterline are dropped, straddling ones clipped.
    """
    if builder is None:
        builder = _MeshBuilder()
    stations = np.asarray(stations, float)
    radii = 0.5 * np.asarray(diameters, float)
    rA = np.asarray(rA, float)
    rB = np.asarray(rB, float)

    if dz_max == 0:
        dz_max = stations[-1] / 20
    if da_max == 0:
        da_max = np.max(radii) / 8

    r_rp, z_rp = _radius_profile(stations, radii, dz_max, da_max)

    # member orientation (reference :246-259)
    rAB = rB - rA
    beta = np.arctan2(rAB[1], rAB[0])
    phi = np.arctan2(np.sqrt(rAB[0]**2 + rAB[1]**2), rAB[2])
    s1, c1 = np.sin(beta), np.cos(beta)
    s2, c2 = np.sin(phi), np.cos(phi)
    R = np.array([[c1 * c2, -s1, c1 * s2],
                  [c2 * s1, c1, s1 * s2],
                  [-s2, 0.0, c2]])

    def emit(xs, ys, zs):
        nodes = R @ np.array([xs, ys, zs]) + rA[:, None]
        builder.add_panel(nodes[0], nodes[1], nodes[2])

    naz = 8
    for i_rp in range(len(z_rp) - 1):
        r1, r2 = r_rp[i_rp], r_rp[i_rp + 1]
        z1, z2 = z_rp[i_rp], z_rp[i_rp + 1]
        # azimuthal refinement doubling/halving (reference :186-192)
        while (r1 * 2 * np.pi / naz >= da_max / 2
               and r2 * 2 * np.pi / naz >= da_max / 2):
            naz = int(2 * naz)
        while (r1 * 2 * np.pi / naz < da_max / 2
               and r2 * 2 * np.pi / naz < da_max / 2 and naz > 8):
            naz = int(naz / 2)

        inc = (r1 * 2 * np.pi / naz < da_max / 2
               and r2 * 2 * np.pi / naz >= da_max / 2)
        dec = (r1 * 2 * np.pi / naz >= da_max / 2
               and r2 * 2 * np.pi / naz < da_max / 2)
        if inc:       # transition row: double the azimuth count on row 2
            for ia in range(1, int(naz / 2) + 1):
                th1 = (ia - 1) * 4 * np.pi / naz
                th2 = (ia - 0.5) * 4 * np.pi / naz
                th3 = ia * 4 * np.pi / naz
                emit([(r1 * np.cos(th1) + r1 * np.cos(th3)) / 2,
                      r2 * np.cos(th2), r2 * np.cos(th1), r1 * np.cos(th1)],
                     [(r1 * np.sin(th1) + r1 * np.sin(th3)) / 2,
                      r2 * np.sin(th2), r2 * np.sin(th1), r1 * np.sin(th1)],
                     [z1, z2, z2, z1])
                emit([r1 * np.cos(th3), r2 * np.cos(th3), r2 * np.cos(th2),
                      (r1 * np.cos(th1) + r1 * np.cos(th3)) / 2],
                     [r1 * np.sin(th3), r2 * np.sin(th3), r2 * np.sin(th2),
                      (r1 * np.sin(th1) + r1 * np.sin(th3)) / 2],
                     [z1, z2, z2, z1])
        elif dec:     # transition row: halve the azimuth count on row 2
            for ia in range(1, int(naz / 2) + 1):
                th1 = (ia - 1) * 4 * np.pi / naz
                th2 = (ia - 0.5) * 4 * np.pi / naz
                th3 = ia * 4 * np.pi / naz
                emit([r1 * np.cos(th2), r2 * (np.cos(th1) + np.cos(th3)) / 2,
                      r2 * np.cos(th1), r1 * np.cos(th1)],
                     [r1 * np.sin(th2), r2 * (np.sin(th1) + np.sin(th3)) / 2,
                      r2 * np.sin(th1), r1 * np.sin(th1)],
                     [z1, z2, z2, z1])
                emit([r1 * np.cos(th3), r2 * np.cos(th3),
                      r2 * (np.cos(th1) + np.cos(th3)) / 2, r1 * np.cos(th2)],
                     [r1 * np.sin(th3), r2 * np.sin(th3),
                      r2 * (np.sin(th1) + np.sin(th3)) / 2, r1 * np.sin(th2)],
                     [z1, z2, z2, z1])
        else:
            for ia in range(1, naz + 1):
                th1 = (ia - 1) * 2 * np.pi / naz
                th2 = ia * 2 * np.pi / naz
                emit([r1 * np.cos(th2), r2 * np.cos(th2), r2 * np.cos(th1),
                      r1 * np.cos(th1)],
                     [r1 * np.sin(th2), r2 * np.sin(th2), r2 * np.sin(th1),
                      r1 * np.sin(th1)],
                     [z1, z2, z2, z1])
    return builder


def lid_disk(builder: _MeshBuilder, cx, cy, R, da_max, z_lid):
    """Interior-waterplane lid panels: concentric ring quads over the disk
    of radius R centered at (cx, cy), at depth ``z_lid`` (slightly below
    z=0 so the wave-kernel tables stay in range).  Used by the BEM core's
    irregular-frequency removal — not part of the wetted body surface."""
    n_r = max(int(np.ceil(R / (0.6 * da_max))), 2)
    radii = np.linspace(R, 0.0, n_r + 1)
    naz = 8
    for i in range(n_r):
        r1, r2 = radii[i], radii[i + 1]
        while r1 * 2 * np.pi / naz >= da_max and naz < 256:
            naz *= 2
        for ia in range(naz):
            th1 = ia * 2 * np.pi / naz
            th2 = (ia + 1) * 2 * np.pi / naz
            builder.add_panel(
                [cx + r1 * np.cos(th2), cx + r2 * np.cos(th2),
                 cx + r2 * np.cos(th1), cx + r1 * np.cos(th1)],
                [cy + r1 * np.sin(th2), cy + r2 * np.sin(th2),
                 cy + r2 * np.sin(th1), cy + r1 * np.sin(th1)],
                [z_lid] * 4)


def mesh_fowt_members(fowt, dz_max=3.0, da_max=2.0, lid=True,
                      all_members=False) -> PanelMesh:
    """One combined mesh of all potMod members of a FOWTModel (reference:
    raft_fowt.py:607-614 meshes each potMod member into one shared list).

    Member positions are taken at the zero-offset pose (heading patterns
    already baked into rA0/rB0 at build).  ``all_members=True`` meshes
    every platform member regardless of its potMod flag (for validating
    the native solver on designs whose run configuration is strip-only)."""
    builder = _MeshBuilder()
    any_pot = False
    piercing = []
    for m in fowt.members[:fowt.nplatmems] if all_members else fowt.members:
        if not all_members and not m.potMod:
            continue
        if not m.circular:
            raise NotImplementedError(
                "panel meshing supports circular members only (the "
                "reference mesher has the same limitation, member2pnl.py)")
        any_pot = True
        rA, rB = np.asarray(m.rA0, float), np.asarray(m.rB0, float)
        mesh_member(m.stations, m.d, rA, rB,
                    dz_max=dz_max, da_max=da_max, builder=builder)
        # surface-piercing vertical members get an interior lid at z=0
        if rA[2] < 0.0 < rB[2] and abs(rA[0] - rB[0]) < 1e-9 \
                and abs(rA[1] - rB[1]) < 1e-9:
            st = np.asarray(m.stations, float)
            dd = np.atleast_1d(np.asarray(m.d, float))
            if dd.ndim == 0 or len(dd) == 1:
                dwl = float(dd.flat[0])
            else:
                z_st = rA[2] + (st - st[0]) / (st[-1] - st[0]) * (rB[2] - rA[2])
                dwl = float(np.interp(0.0, z_st, dd))
            piercing.append((rA[0], rA[1], 0.5 * dwl))
    if not any_pot:
        # IS a ValueError — pre-taxonomy catchers keep working
        raise ModelConfigError("FOWT has no potMod members to mesh")
    n_body = len(builder.panels)
    if lid:
        for cx, cy, R in piercing:
            lid_disk(builder, cx, cy, R, da_max, z_lid=-0.01 * da_max)
    mesh = builder.mesh()
    mesh.n_body = n_body
    return mesh


# --------------------------------------------------------------------------
# writers
# --------------------------------------------------------------------------

def write_pnl(mesh: PanelMesh, out_dir: str, body_only: bool = True):
    """HAMS HullMesh.pnl writer (reference: member2pnl.py:280-310).

    By default only the wetted BODY panels are written — interior-
    waterplane lid panels (our BEM core's irregular-frequency treatment)
    are not hull surface and would corrupt an external HAMS run."""
    os.makedirs(out_dir, exist_ok=True)
    path = os.path.join(out_dir, "HullMesh.pnl")
    npan = mesh.nbody if body_only else mesh.npanels
    with open(path, "w") as f:
        f.write("    --------------Hull Mesh File---------------\n\n")
        f.write("    # Number of Panels, Nodes, X-Symmetry and Y-Symmetry\n")
        f.write(f"         {npan}         {len(mesh.verts)}"
                "         0         0\n\n")
        f.write("    #Start Definition of Node Coordinates     "
                "! node_number   x   y   z\n")
        for i, nd in enumerate(mesh.verts):
            f.write(f"{i+1:>5}{nd[0]:18.3f}{nd[1]:18.3f}{nd[2]:18.3f}\n")
        f.write("   #End Definition of Node Coordinates\n\n")
        f.write("   #Start Definition of Node Relations   ! panel_number  "
                "number_of_vertices   Vertex1_ID   Vertex2_ID   Vertex3_ID  "
                " (Vertex4_ID)\n")
        for i, p in enumerate(mesh.panels[:npan]):
            ids = list(p)
            if ids[3] == ids[2]:        # triangle
                row = [i + 1, 3] + [j + 1 for j in ids[:3]]
            else:
                row = [i + 1, 4] + [j + 1 for j in ids]
            f.write("".join(f"{v:>8}" for v in row) + "\n")
        f.write("   #End Definition of Node Relations\n\n")
        f.write("    --------------End Hull Mesh File---------------\n")
    return path


def write_gdf(mesh: PanelMesh, path: str, ulen=1.0, g=9.80665):
    """WAMIT .gdf writer (reference: member2pnl.py:496-546): panel
    vertices listed explicitly, no symmetry."""
    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    with open(path, "w") as f:
        f.write("gdf mesh written by raft_tpu\n")
        f.write(f"{ulen:>10.4f}{g:>10.4f}\n")
        f.write("0  0\n")
        f.write(f"{mesh.npanels}\n")
        for p in mesh.panels:
            for j in p:
                v = mesh.verts[j]
                f.write(f"{v[0]:>14.5f}{v[1]:>14.5f}{v[2]:>14.5f}\n")
    return path
