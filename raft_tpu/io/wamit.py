"""WAMIT-format hydrodynamic coefficient I/O and the potential-flow
excitation kernel.

TPU-first equivalent of the reference's pyHAMS read-back + readHydro path
(reference: raft/raft_fowt.py:640-768).  The reference shells out to the
Fortran HAMS solver and reads its WAMIT-format output files through
`pyhams.read_wamit1/read_wamit3`; here the readers are self-contained numpy
(file parsing is host-side build work), and the per-case excitation
assembly — heading interpolation with wraparound, rotation from the
wave-relative frame back to global, and the array-position phase offset
(reference: raft_fowt.py:1039-1093) — is pure jnp so it can sit inside the
jitted/vmapped case pipeline.

File conventions (WAMIT v7 manual, as used by HAMS):
  .1 : PER i j Abar [Bbar]     added mass/damping, nondimensional
       PER < 0 -> zero frequency (infinite period): Abar only
       PER = 0 -> infinite frequency (zero period): Abar only
  .3 : PER head(deg) i MOD PHA Re Im    excitation per heading, nondim
Dimensionalization: A = rho*Abar, B = rho*w*Bbar (the reference's read-back
receives already-w-scaled damping from pyhams and multiplies by rho only;
pyhams read_wamit1 returns B*w internally, so our reader does the same),
X = rho*g*(Re + i*Im).
"""
from __future__ import annotations

import os
import warnings
from dataclasses import dataclass
from typing import Optional

import numpy as np
import jax.numpy as jnp


def _screen_finite(name, path, **arrays):
    """Raise with an actionable message if any parsed coefficient array
    carries NaN/Inf (reference guards its HAMS read-back the same way,
    raft_fowt.py:708-714) — a corrupt file must not propagate silently.

    The raise is the typed :class:`raft_tpu.errors.NonFiniteResult`
    (still a ``ValueError``, so pre-taxonomy callers keep working) with
    the file/field facts as structured context."""
    from raft_tpu.errors import NonFiniteResult

    for label, arr in arrays.items():
        if arr is None:
            continue
        bad = ~np.isfinite(np.asarray(arr))
        if bad.any():
            raise NonFiniteResult(
                f"{name} file '{path}': {int(bad.sum())} non-finite "
                f"value(s) in {label} — the file is corrupt or truncated; "
                f"re-run the BEM solver or delete the cached output",
                file=str(path), field=str(label), n_bad=int(bad.sum()))


def _detect_freq_convention(col1_in_file_order):
    """'period' (WAMIT standard: column 1 descends in file order — long
    periods first) vs 'omega' (HAMS/pyhams Wamit_format output with
    Output_frequency_type 3: column 1 is rad/s, ASCENDING in file order —
    e.g. the reference's shipped raft/data/cylinder Buoy.* files).  The
    reference reads both through pyhams; a single sequence check
    disambiguates every shipped file."""
    seen = set()
    vals = []
    for v in col1_in_file_order:          # first-seen unique positives:
        if v > 0 and v not in seen:       # multi-heading/multi-ij files
            seen.add(v)                   # repeat col-1 within a block
            vals.append(v)
    if len(vals) < 2:
        warnings.warn(
            "WAMIT/HAMS file has fewer than 2 unique positive column-1 "
            "values — the period-vs-omega convention cannot be detected "
            "from ordering; assuming WAMIT periods.  A single-frequency "
            "HAMS omega-format file would be misread (frequency axis "
            "warped): pass freq='omega' or set platform hydroFreqType.")
        return "period"
    if all(b > a for a, b in zip(vals, vals[1:])):
        return "omega"
    return "period"


def read_wamit1(path, freq="auto"):
    """Parse a WAMIT `.1` added-mass/damping file.

    ``freq``: 'period' (WAMIT: column 1 is the wave period), 'omega'
    (HAMS Wamit_format: column 1 is rad/s ascending), or 'auto' (detect
    from the file ordering).  4-column special rows are ALWAYS periods
    per the WAMIT convention regardless of ``freq`` (PER<0 rows are
    zero-frequency, PER=0 infinite-frequency — raft_fowt.py:644-646).

    Returns dict(w (nf,) ascending rad/s, A (6,6,nf), B (6,6,nf),
    A0 (6,6) zero-frequency added mass or None, Ainf (6,6) or None).
    A/B are nondimensional (Abar, w*Bbar not yet applied — see load_bem).
    """
    rows = []
    special = []
    order = []
    with open(path) as f:
        for line in f:
            parts = line.split()
            if not parts:
                continue
            T = float(parts[0])
            i, j = int(parts[1]) - 1, int(parts[2]) - 1
            if len(parts) == 4:
                special.append((T, i, j, float(parts[3])))
            else:
                rows.append((T, i, j, float(parts[3]), float(parts[4])))
                order.append(T)

    if freq == "auto":
        freq = _detect_freq_convention(order)
    zero, inf = {}, {}
    for T, i, j, v in special:
        # special rows are ALWAYS periods per the WAMIT convention
        # (PER < 0 = zero frequency, PER = 0 = infinite frequency; quoted
        # verbatim by the reference at raft_fowt.py:644-646 and relied on
        # by pyhams' TFlag read-back) — irrespective of whether the
        # finite-frequency rows carry periods or rad/s
        (zero if T < 0 else inf)[(i, j)] = v

    if freq == "omega":
        omegas = sorted({r[0] for r in rows})
        idx = {o: n for n, o in enumerate(omegas)}
        w = np.array(omegas)
    else:
        periods = sorted({r[0] for r in rows}, reverse=True)
        idx = {T: n for n, T in enumerate(periods)}
        w = 2.0 * np.pi / np.array(periods)
    nf = len(idx)
    A = np.zeros((6, 6, nf))
    B = np.zeros((6, 6, nf))
    for T, i, j, a, b in rows:
        A[i, j, idx[T]] = a
        B[i, j, idx[T]] = b

    def mat(d):
        if not d:
            return None
        M = np.zeros((6, 6))
        for (i, j), v in d.items():
            M[i, j] = v
        return M

    out = dict(w=w, A=A, B=B, A0=mat(zero), Ainf=mat(inf))
    _screen_finite("WAMIT .1", path, **out)
    return out


def read_wamit3(path, freq="auto"):
    """Parse a WAMIT `.3` excitation file (``freq`` as in read_wamit1).

    Returns dict(w (nf,) ascending rad/s, headings (nh,) deg sorted
    ascending in [0,360), X (nh,6,nf) complex nondimensional).
    """
    rows = []
    order = []
    with open(path) as f:
        for line in f:
            parts = line.split()
            if not parts:
                continue
            T = float(parts[0])
            head = float(parts[1])
            i = int(parts[2]) - 1
            re, im = float(parts[5]), float(parts[6])
            rows.append((T, head, i, re, im))
            order.append(T)

    if freq == "auto":
        freq = _detect_freq_convention(order)
    if freq == "omega":
        keys = sorted({r[0] for r in rows})
        w = np.array(keys)
    else:
        keys = sorted({r[0] for r in rows}, reverse=True)
        w = 2.0 * np.pi / np.array(keys)
    heads_raw = sorted({r[1] for r in rows})
    tidx = {T: n for n, T in enumerate(keys)}
    hidx = {h: n for n, h in enumerate(heads_raw)}
    X = np.zeros((len(heads_raw), 6, len(keys)), dtype=complex)
    for T, head, i, re, im in rows:
        X[hidx[head], i, tidx[T]] = re + 1j * im

    # normalize headings to [0,360) and re-sort (reference: raft_fowt.py:669-676)
    headings = np.asarray(heads_raw) % 360.0
    order = np.argsort(headings)
    _screen_finite("WAMIT .3", path, w=w, X=X, headings=np.asarray(heads_raw))
    return dict(w=w, headings=headings[order], X=X[order])


@dataclass
class BEMData:
    """Potential-flow coefficients interpolated onto the model frequency
    grid (numpy, built once per design).

    X_BEM is stored in the WAVE-RELATIVE frame per BEM heading (surge along
    the incident wave direction), exactly as the reference stores it for
    accurate magnitude interpolation between headings
    (reference: raft_fowt.py:692-706).
    """

    A_BEM: np.ndarray            # (6,6,nw) dimensional added mass
    B_BEM: np.ndarray            # (6,6,nw) dimensional radiation damping
    X_BEM: np.ndarray            # (nh,6,nw) complex excitation coeffs, wave frame
    headings: np.ndarray         # (nh,) deg in [0,360), ascending


def _interp_freq(w_model, w_data, Y, Y_at_zero):
    """Linear interp of Y (..., nf) from w_data to w_model with a
    zero-frequency pad (reference: raft_fowt.py:678-683).  Clamps above the
    data range (the reference's interp1d would raise there instead)."""
    w_ext = np.concatenate([[0.0], w_data])
    Y_ext = np.concatenate([Y_at_zero[..., None], Y], axis=-1)
    shape = Y.shape[:-1]
    out = np.empty(shape + (len(w_model),), dtype=Y.dtype)
    for idx in np.ndindex(shape):
        if np.iscomplexobj(Y):
            out[idx] = (np.interp(w_model, w_ext, Y_ext[idx].real)
                        + 1j * np.interp(w_model, w_ext, Y_ext[idx].imag))
        else:
            out[idx] = np.interp(w_model, w_ext, Y_ext[idx])
    return out


def rotate_to_wave_frame(X_global, headings):
    """Rotate global-frame excitation (nh,6,nf) so surge/sway (and
    roll/pitch) are relative to each incident wave heading (reference:
    raft_fowt.py:692-706).  Shared by the WAMIT reader and the native BEM
    packer so the frame convention cannot diverge."""
    X = np.zeros_like(X_global)
    for ih, hd in enumerate(np.atleast_1d(headings)):
        c, s = np.cos(np.deg2rad(hd)), np.sin(np.deg2rad(hd))
        Xg = X_global[ih]
        X[ih, 0] = c * Xg[0] + s * Xg[1]
        X[ih, 1] = -s * Xg[0] + c * Xg[1]
        X[ih, 2] = Xg[2]
        X[ih, 3] = c * Xg[3] + s * Xg[4]
        X[ih, 4] = -s * Xg[3] + c * Xg[4]
        X[ih, 5] = Xg[5]
    return X


def load_bem(hydro_path: str, w_model, rho: float = 1025.0,
             g: float = 9.81, freq: str = "auto") -> BEMData:
    """Read `hydro_path`.1/.3 and interpolate onto the model grid
    (reference: raft_fowt.py:663-768).

    ``freq``: 'period' (WAMIT), 'omega' (HAMS Wamit_format), or 'auto'
    (detect from file ordering; see read_wamit1).  Exposed through the
    design dict as ``platform: hydroFreqType`` for files the detection
    cannot disambiguate (e.g. a WAMIT run with periods listed ascending).

    A missing `.3` file yields zero excitation with a single 0-degree
    heading (the strip-theory excitation path still applies) — the
    reference would raise instead.
    """
    path = hydro_path
    if not os.path.isfile(path + ".1"):
        raise FileNotFoundError(f"WAMIT file {hydro_path}.1 not found")

    w_model = np.asarray(w_model, float)
    if freq == "auto":
        # resolve the convention ONCE from the .1 and reuse it for the .3
        # so the pair can never land on inconsistent axes; warn when the
        # ambiguous case fires (a legal WAMIT run can list periods
        # ascending — set platform: hydroFreqType to override)
        with open(path + ".1") as f:
            col1 = [float(ln.split()[0]) for ln in f if ln.split()]
        freq = _detect_freq_convention(col1)
        if freq == "omega":
            warnings.warn(
                f"'{hydro_path}.1': column 1 ascends in file order — "
                "reading as HAMS omega [rad/s] format.  If this is a "
                "WAMIT period file with ascending PER input, set "
                "platform: hydroFreqType: period.", stacklevel=2)
    d1 = read_wamit1(path + ".1", freq=freq)
    A0 = d1["A0"] if d1["A0"] is not None else d1["A"][:, :, 0]
    A_BEM = rho * _interp_freq(w_model, d1["w"], d1["A"], A0)
    # above the data range, use the file's infinite-frequency limit when
    # provided (PER=0 rows) instead of flat-clamping the last sample
    if d1["Ainf"] is not None:
        above = w_model > d1["w"][-1]
        if np.any(above):
            A_BEM[:, :, above] = rho * d1["Ainf"][:, :, None]
    # pyhams' read_wamit1 returns damping already scaled by w; our reader
    # keeps the file's raw Bbar, so apply the WAMIT w*Bbar dimensionalization
    B_dim = d1["B"] * d1["w"][None, None, :]
    B_BEM = rho * _interp_freq(w_model, d1["w"], B_dim, np.zeros((6, 6)))

    if os.path.isfile(path + ".3"):
        d3 = read_wamit3(path + ".3", freq=freq)
        X_dim = rho * g * d3["X"]
        X_BEM_global = _interp_freq(w_model, d3["w"], X_dim,
                                    np.zeros_like(X_dim[..., 0]))
        headings = d3["headings"]
        X_BEM = rotate_to_wave_frame(X_BEM_global, headings)
    else:
        headings = np.array([0.0])
        X_BEM = np.zeros((1, 6, len(w_model)), dtype=complex)

    return BEMData(A_BEM=A_BEM, B_BEM=B_BEM, X_BEM=X_BEM, headings=headings)


def bem_coeffs(bem: Optional[BEMData], nw: int):
    """(A_BEM, B_BEM) as jnp arrays for the linear system assembly; zeros
    when no potential-flow data is loaded.  Shared by Model.solveDynamics
    and the vmapped sweep solver so the two stay in sync."""
    if bem is None:
        z = jnp.zeros((6, 6, nw))
        return z, z
    return jnp.asarray(bem.A_BEM), jnp.asarray(bem.B_BEM)


def bem_excitation(bem: BEMData, beta_rad, zeta, k, x_ref=0.0, y_ref=0.0,
                   heading_adjust=0.0):
    """Potential-flow excitation for one heading's sea state — pure jnp
    (reference: raft_fowt.py:1039-1093).

    beta_rad: scalar global wave heading [rad] (traceable);
    zeta: (nw,) complex wave amplitudes; k: (nw,) wave numbers.
    Returns F_BEM (6,nw) complex.
    """
    beta_rad = jnp.asarray(beta_rad)
    zeta = jnp.asarray(zeta)
    k = jnp.asarray(k)
    heads = np.asarray(bem.headings, float)

    # periodic extension for wraparound interpolation
    # (reference: raft_fowt.py:1053-1074)
    heads_ext = np.concatenate([[heads[-1] - 360.0], heads, [heads[0] + 360.0]])
    X = np.asarray(bem.X_BEM)
    X_ext = jnp.asarray(np.concatenate([X[-1:], X, X[:1]], axis=0))

    beta_deg = (jnp.rad2deg(beta_rad) - heading_adjust) % 360.0
    i2 = jnp.clip(jnp.searchsorted(jnp.asarray(heads_ext), beta_deg),
                  1, len(heads_ext) - 1)
    i1 = i2 - 1
    h1 = jnp.asarray(heads_ext)[i1]
    h2 = jnp.asarray(heads_ext)[i2]
    f2 = jnp.where(h2 > h1, (beta_deg - h1) / jnp.where(h2 > h1, h2 - h1, 1.0), 0.0)
    X_prime = X_ext[i1] * (1.0 - f2) + X_ext[i2] * f2          # (6,nw)

    # rotate back to the global frame (reference: raft_fowt.py:1082-1090)
    c, s = jnp.cos(beta_rad), jnp.sin(beta_rad)
    Xg = jnp.stack([
        X_prime[0] * c - X_prime[1] * s,
        X_prime[0] * s + X_prime[1] * c,
        X_prime[2],
        X_prime[3] * c - X_prime[4] * s,
        X_prime[3] * s + X_prime[4] * c,
        X_prime[5],
    ])

    # array-position phase offset from the GLOBAL wave heading
    # (reference: raft_fowt.py:1043-1045 uses case['wave_heading'], not the
    # heading_adjust-shifted interpolation angle)
    phase = jnp.exp(-1j * k * (x_ref * c + y_ref * s))
    return Xg * zeta[None, :] * phase[None, :]


# --------------------------------------------------------------------------
# WAMIT-format writers (.1/.3) — used by the native BEM path to cache its
# coefficients in the same files the reference writes for OpenFAST export
# (reference: raft_fowt.py:568-571 docstring; pyHAMS output conventions)
# --------------------------------------------------------------------------

def write_wamit1(path, w, A, B, rho=1025.0):
    """Write a WAMIT `.1` file from dimensional A/B (6,6,nf) on ascending
    frequency grid w; entries are nondimensionalized by rho (Abar) and
    rho*w (Bbar)."""
    with open(path, "w") as f:
        for n in range(len(w)):
            T = 2.0 * np.pi / w[n]
            for i in range(6):
                for j in range(6):
                    Abar = A[i, j, n] / rho
                    Bbar = B[i, j, n] / (rho * w[n])
                    f.write(f"{T:14.6e} {i+1:d} {j+1:d} "
                            f"{Abar:14.6e} {Bbar:14.6e}\n")
    return path


def write_wamit3(path, w, headings, X, rho=1025.0, g=9.81):
    """Write a WAMIT `.3` file from dimensional GLOBAL-frame excitation
    X (nh,6,nf) complex; nondimensionalized by rho*g."""
    with open(path, "w") as f:
        for n in range(len(w)):
            T = 2.0 * np.pi / w[n]
            for ih, hd in enumerate(headings):
                for i in range(6):
                    Xn = X[ih, i, n] / (rho * g)
                    mod, pha = np.abs(Xn), np.angle(Xn, deg=True)
                    f.write(f"{T:14.6e} {hd:10.3f} {i+1:d} "
                            f"{mod:14.6e} {pha:10.3f} "
                            f"{Xn.real:14.6e} {Xn.imag:14.6e}\n")
    return path
