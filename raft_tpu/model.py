"""System-level model: statics equilibrium, eigen, dynamic RAO solve, cases.

TPU-first equivalent of the reference Model class (reference:
raft/raft_model.py).  Host-side Python orchestrates per-case setup; the hot
paths are pure-jnp:

- `solveStatics` (reference :479-849): damped-Newton equilibrium on the
  6N-DOF pose with the linearized-hydrostatics + constant-forcing scheme
  (statics_mod=0 / forcing_mod=0, the reference's hard-coded modes), with
  mooring reactions/stiffness from the differentiable catenary.  The
  Newton itself is a device-resident `lax.while_loop` (all line-search
  alphas in one vmapped evaluation, ONE host sync at convergence);
  `RAFT_TPU_STATICS=host` keeps the Python-loop reference backend.
- `solveDynamics` (reference :852-1146): the drag-linearization fixed point
  as a `lax.while_loop` whose inner step solves ALL frequencies in one
  batched complex 6x6 `jnp.linalg.solve` (the reference's per-frequency
  loop at raft_model.py:942-947 collapsed), then ONE heading-batched
  system solve over the `(nWaves, 6N, nw)` excitation stack — the
  reference's per-heading loop at raft_model.py:1042-1083 collapsed,
  with solver telemetry computed on device (`RAFT_TPU_TELEMETRY`).
  Host pulls happen only at sanctioned counted exit points
  (`obs.transfers`; see docs/performance.md for the per-case budget).
- `solveEigen` (reference :391-476) with the same DOF-claiming mode sort.
- `analyzeCases`/`saveTurbineOutputs` (reference :244-388 and
  raft_fowt.py:1821-2109): statistics of each response channel.

Pose conventions replicated from the reference case flow: statics matrices,
strip added mass, and turbine constants are evaluated at the ZERO-offset
pose; wave excitation and drag linearization at the mean-offset pose;
mooring stiffness at the mean-offset pose (see raft_model.py:527-556 where
calcStatics/calcTurbineConstants/calcHydroConstants run before the Newton
solve, and :885 where excitation runs after it).
"""
from __future__ import annotations

import copy
import time
from functools import partial

import numpy as np
import jax
import jax.numpy as jnp

from raft_tpu.models import mooring as mr
from raft_tpu.models.fowt import (
    FOWTModel, build_fowt, build_seastate, fowt_pose, fowt_statics,
    fowt_hydro_constants, fowt_hydro_excitation, fowt_drag_precompute,
    fowt_hydro_linearization_pre,
    fowt_drag_excitation, fowt_current_loads, fowt_turbine_constants,
    fowt_bem_excitation,
)
from raft_tpu.models.rotor import calc_aero
from raft_tpu.models import qtf as qt
from raft_tpu.ops.spectra import get_psd, get_rao, get_rms
from raft_tpu.ops.linalg import impedance_solve, inv_complex
from raft_tpu.ops.transforms import transform_force, translate_matrix_6to6
from raft_tpu.models.member import member_inertia
from raft_tpu.utils.dicttools import get_from_dict
from raft_tpu import _config, errors, obs, recovery
from raft_tpu.testing import faults
from raft_tpu.utils.profiling import get_logger, temp_verbosity

RAD2DEG = 180.0 / np.pi

_LOG = get_logger("model")


@jax.jit
def _apply_zinv_j(Zinv, F_wave):
    """Batched system RAO solve: apply the factored inverse impedance to
    one heading's excitation, (nw,6N,6N) x (6N,nw) -> (6N,nw).  Kept as
    the single-heading reference kernel (parity tests); the case
    pipeline itself runs the heading-batched ``_dyn_solve_batched``."""
    Xi_h = jnp.einsum("wij,wj->wi", Zinv, jnp.moveaxis(F_wave, -1, 0))
    return jnp.moveaxis(Xi_h, 0, -1)


def _dyn_solve_core(Zinv, Z_sys, F_all):
    """Heading-batched system RAO solve + solve-health residual, one
    device program: apply the factored inverse impedance to EVERY
    heading's excitation at once ((nw,6N,6N) x (nH,6N,nw) -> (nH,6N,nw))
    and compute the per-heading relative residual |Z Xi - F|/|F| of the
    factor-once Zinv reuse on device — two scalars per heading cross the
    host boundary instead of the full response stack."""
    Xi = jnp.einsum("wij,hjw->hiw", Zinv, F_all)
    R = jnp.einsum("wij,hjw->hiw", Z_sys, Xi) - F_all
    num = jnp.sqrt(jnp.sum(jnp.abs(R) ** 2, axis=(1, 2)))
    den = jnp.sqrt(jnp.sum(jnp.abs(F_all) ** 2, axis=(1, 2)))
    return Xi, num / (den + 1e-300)


def _cond_core(Z_sys):
    """Device-side conditioning telemetry of the impedance stack:
    (all-finite flag, max cond, median cond over frequencies).  A
    non-finite stack short-circuits to an identity so the SVD cannot
    blow up — the caller skips recording when the flag is False and the
    solve path downstream raises its clearer non-finite diagnostic."""
    finite = jnp.all(jnp.isfinite(Z_sys.real) & jnp.isfinite(Z_sys.imag))
    eye = jnp.eye(Z_sys.shape[-1], dtype=Z_sys.dtype)
    safe = jnp.where(finite, Z_sys, eye)
    c = jnp.linalg.cond(safe)
    return finite, jnp.max(c), jnp.median(c)


#: lazily-built jitted instances (donation is decided by the active
#: backend, which must not be queried at import time); the dynamics
#: solve is additionally keyed by the mesh topology — a mesh with a
#: ``freq`` axis gets its own program with the statics->dynamics
#: resharding constraints baked in (parallel/partition.py)
_DYN_JITS: dict = {}


def _dyn_solve_jit(mesh=None):
    from raft_tpu.parallel import partition
    if not partition.has_freq_axis(mesh):
        # only a freq axis changes this program — a batch-only mesh
        # shares the single-device entry instead of recompiling it
        mesh = None
    # the compiled wrapper closes over the Mesh OBJECT, so the key must
    # carry device identity, not just the axis topology — a same-shape
    # mesh over different chips is a different program placement
    key = ("solve", partition.mesh_key(mesh),
           None if mesh is None
           else tuple(d.id for d in mesh.devices.ravel()))
    if key not in _DYN_JITS:
        donate = (2,) if jax.default_backend() != "cpu" else ()
        core = _dyn_solve_core
        if mesh is not None:
            core = partition.sharded_dynamics_core(core, mesh)
        _DYN_JITS[key] = jax.jit(core, donate_argnums=donate)
    return _DYN_JITS[key]


def _cond_jit():
    if "cond" not in _DYN_JITS:
        _DYN_JITS["cond"] = jax.jit(_cond_core)
    return _DYN_JITS["cond"]


class Model:
    """Single- or (later) multi-FOWT frequency-domain model.

    Mirrors the reference API: Model(design) -> analyzeUnloaded() ->
    analyzeCases() with results in `model.results`.
    """

    def __init__(self, design: dict):
        design = copy.deepcopy(design)
        design.setdefault("settings", {})
        s = design["settings"]
        min_freq = float(get_from_dict(s, "min_freq", default=0.01, dtype=float))
        max_freq = float(get_from_dict(s, "max_freq", default=1.00, dtype=float))
        self.XiStart = float(get_from_dict(s, "XiStart", default=0.1, dtype=float))
        self.nIter = int(get_from_dict(s, "nIter", default=15, dtype=int))
        self.w = np.arange(min_freq, max_freq + 0.5 * min_freq, min_freq) * 2 * np.pi
        self.nw = len(self.w)
        self.depth = float(get_from_dict(design["site"], "water_depth", dtype=float))

        self.arr_ms = None
        self._arr_xf = None
        self._K_array = None
        if "array" in design:
            # ----- array/farm mode (reference: raft_model.py:67-141) -----
            if "turbine" in design and "turbines" not in design:
                design["turbines"] = [design["turbine"]]
            if "platform" in design and "platforms" not in design:
                design["platforms"] = [design["platform"]]
            if "mooring" in design and "moorings" not in design:
                design["moorings"] = [design["mooring"]]
            fowtInfo = [dict(zip(design["array"]["keys"], row))
                        for row in design["array"]["data"]]
            self.nFOWT = len(fowtInfo)
            if "array_mooring" in design:
                from raft_tpu.models import mooring_array as ma
                if not design["array_mooring"].get("file"):
                    # IS a ValueError — pre-taxonomy catchers keep working
                    raise errors.ModelConfigError(
                        "'array_mooring' requires a MoorDyn-style input "
                        "file as 'file'")
                self.arr_ms = ma.parse_moordyn(
                    design["array_mooring"]["file"], nbodies=self.nFOWT,
                    depth=self.depth)
            self.fowtList = []
            for info in fowtInfo:
                design_i = {"site": design["site"]}
                if info["turbineID"] != 0:
                    design_i["turbine"] = design["turbines"][info["turbineID"] - 1]
                design_i["platform"] = design["platforms"][info["platformID"] - 1]
                if info["mooringID"] != 0:
                    design_i["mooring"] = design["moorings"][info["mooringID"] - 1]
                self.fowtList.append(build_fowt(
                    design_i, self.w, depth=self.depth,
                    x_ref=float(info["x_location"]),
                    y_ref=float(info["y_location"]),
                    heading_adjust=float(info["heading_adjust"])))
        else:
            self.fowtList = [build_fowt(design, self.w, depth=self.depth)]
            self.nFOWT = 1
        self.nDOF = 6 * self.nFOWT
        # 0: no current on mooring lines; 1: uniform case current included
        # in the line-drag wrench (reference: raft_model.py:162-163)
        self.mooring_currentMod = int(get_from_dict(
            design.get("mooring") or {}, "currentMod", dtype=int, default=0))
        # QTF output folder: internal-QTF runs drop .12d/.4 snapshots here
        # and reload them as a checkpoint cache (reference:
        # raft_fowt.py:255-257, 1420-1433, 1642-1648)
        plat = design.get("platform") or (design.get("platforms") or [{}])[0]
        self.outFolderQTF = plat.get("outFolderQTF")
        self._iCase = None
        #: named device mesh for the batched dynamics solve (None =
        #: single-device).  Defaults to the ambient ``RAFT_TPU_MESH``
        #: topology (e.g. "freq=8") so existing entry points — the
        #: golden gate, analyzeCases scripts — run through the
        #: partitioned path with zero API changes; ``set_mesh``
        #: overrides programmatically.
        from raft_tpu.parallel import partition as _partition
        self.mesh = _partition.ambient_mesh()
        #: RunManifest of the most recent analyzeCases invocation
        self.last_manifest = None
        #: result ledger (raft_tpu.ledger/v1) of the most recent
        #: analyzeCases invocation — the regression sentinel's input
        self.last_ledger = None
        # per-case solver facts (Newton/drag iterations, residuals,
        # condition numbers) accumulated for the ledger
        self._case_records = {}
        self._dyn_cost_recorded = False
        self.design = design
        self.results = {}
        # per-fowt case state (filled by solveStatics/solveDynamics)
        self._state = [dict() for _ in self.fowtList]

    def set_mesh(self, mesh):
        """Run the heading-batched dynamics solve on ``mesh`` (a named
        :class:`jax.sharding.Mesh`; a ``freq`` axis shards the
        frequency-bin dimension of the impedance/excitation stacks —
        see ``parallel/partition.py``).  ``None`` restores the
        single-device program; already-compiled topologies stay cached.
        """
        self.mesh = mesh

    @staticmethod
    def _case_for_fowt(case, i):
        """Per-FOWT view of a case row: farm cases may give per-turbine
        lists for the wind parameters (reference: raft_model.py:515-519,
        536-547)."""
        if not case:
            return case
        case_i = dict(case)
        for key in ("wind_speed", "wind_heading", "turbulence"):
            v = case.get(key)
            if isinstance(v, (list, tuple, np.ndarray)):
                if i >= len(v):
                    raise errors.ModelConfigError(
                        f"case list for '{key}' has {len(v)} entries but "
                        f"FOWT {i+1} exists — per-turbine lists must match "
                        "the number of turbines (reference: "
                        "raft_model.py:517-519)", key=key, fowt=i)
                case_i[key] = v[i]
        return case_i

    # ------------------------------------------------------------------
    # statics
    # ------------------------------------------------------------------

    def _case_constants(self, fowt: FOWTModel, case, state):
        """Statics + constant forcing at the zero-offset pose (reference:
        raft_model.py:521-556)."""
        X0 = np.array([fowt.x_ref, fowt.y_ref, 0, 0, 0, 0], float)
        pose0 = fowt_pose(fowt, X0)
        stat = fowt_statics(fowt, pose0)
        state["pose0"] = pose0
        state["statics"] = stat
        state["K_hydrostatic"] = np.asarray(stat["C_struc"] + stat["C_hydro"])
        state["F_undisplaced"] = np.asarray(stat["W_struc"] + stat["W_hydro"])

        F_env = np.zeros(6)
        if case:
            # statics-time constants use the PREVIOUS case's inflow
            # heading for the hub->PRP transfer offset (reference
            # statefulness: setPosition at raft_model.py:527 runs before
            # calcTurbineConstants refreshes the heading; see
            # fowt_turbine_constants docstring)
            stale = state.get("_stored_heading", [0.0] * len(fowt.rotors))
            tc = fowt_turbine_constants(fowt, case, X0,
                                        transfer_heading=stale)
            # the stored heading only advances for rotors whose calcAero
            # actually ran (operating, aeroServoMod>0, speed>0) — a parked
            # or zero-wind case leaves the reference rotor's heading (and
            # hence the next case's stale hub transfer) untouched
            status = str(get_from_dict(case, "turbine_status", shape=0,
                                       dtype=str, default="operating"))
            new_heads = list(stale)
            for k, rot in enumerate(fowt.rotors):
                spd = float(get_from_dict(
                    case, "current_speed" if rot.hubHt < 0 else "wind_speed",
                    shape=0, default=1.0 if rot.hubHt < 0 else 10.0))
                if status == "operating" and rot.aeroServoMod > 0 and spd > 0:
                    new_heads[k] = np.radians(float(get_from_dict(
                        case, "current_heading" if rot.hubHt < 0
                        else "wind_heading", shape=0, default=0.0)))
            state["_stored_heading"] = new_heads
            state["turbine"] = tc
            # cavitation check for operating submerged rotors (reference:
            # raft_fowt.py:826-827 -> raft_rotor.py:639-696)
            status = str(case.get("turbine_status", "operating"))
            cav = []
            for rot in fowt.rotors:
                if rot.hubHt < 0 and status == "operating" and \
                        float(get_from_dict(case, "current_speed", shape=0,
                                            default=0.0)) > 0:
                    from raft_tpu.models.rotor import calc_cavitation
                    cav.append(calc_cavitation(rot, case))
            if cav:
                state["cavitation"] = cav
            else:
                state.pop("cavitation", None)
            hc = fowt_hydro_constants(fowt, pose0)
            state["hydro0"] = hc
            cur_speed = float(get_from_dict(case, "current_speed", shape=0, default=0.0))
            cur_head = float(get_from_dict(case, "current_heading", shape=0, default=0))
            D_hydro = fowt_current_loads(fowt, pose0, cur_speed, cur_head)
            state["D_hydro"] = np.asarray(D_hydro)
            F_env = np.asarray(jnp.sum(tc["f_aero0"], axis=1)) + np.asarray(D_hydro)
            # current on the mooring lines (reference passes the case
            # current to MoorPy, raft_model.py:559-578).  Simple-topology
            # systems model it the MoorPy way — current-loaded line
            # profiles (tilted-plane catenary, line_forces) whose fairlead
            # tensions transmit the drag to the body — so the wrench,
            # stiffness, and tension stats all see the loaded lines.
            # General (free-point) topologies keep the lumped chord
            # approximation on F_env.
            state["moor_current"] = None
            if (self.mooring_currentMod > 0 and cur_speed > 0
                    and fowt.mooring is not None):
                U = cur_speed * np.array([np.cos(np.deg2rad(cur_head)),
                                          np.sin(np.deg2rad(cur_head)), 0.0])
                if mr._is_general(fowt.mooring):
                    X0 = np.array([fowt.x_ref, fowt.y_ref, 0, 0, 0, 0], float)
                    F_env = F_env + np.asarray(
                        mr.current_wrench(fowt.mooring, X0, U))
                else:
                    state["moor_current"] = U
            if "F_meandrift" in state:
                F_env = F_env + state["F_meandrift"]
        else:
            state["turbine"] = None
            state["hydro0"] = fowt_hydro_constants(fowt, pose0)
            state["D_hydro"] = np.zeros(6)
            state["moor_current"] = None
        state["F_env_constant"] = F_env

    def _statics_eval_raw(self):
        """Un-jitted (net force, tangent stiffness, free points)
        evaluation closure, built ONCE per Model — the shared body of
        both the jitted per-call evaluator (host Newton) and the
        device-resident ``lax.while_loop`` Newton (which vmaps it over
        the line-search alphas).  The per-case constants (F0, K_hs) are
        traced arguments, not baked-in constants."""
        if getattr(self, "_eval_FK_raw", None) is not None:
            return self._eval_FK_raw
        N = self.nFOWT
        refs = np.concatenate([
            [f.x_ref, f.y_ref, 0, 0, 0, 0] for f in self.fowtList])
        moors = [f.mooring for f in self.fowtList]
        _is_general_moor = [m is not None and mr._is_general(m)
                            for m in moors]
        arr = self.arr_ms
        if arr is not None:
            from raft_tpu.models import mooring_array as ma

        def eval_FK(X, xf, F0s, K_hss, Ucur):
            Fs, Kblocks = [], []
            for i in range(N):
                s = slice(6 * i, 6 * i + 6)
                Xi0 = X[s] - refs[s]
                F = F0s[i] - K_hss[i] @ Xi0
                K = K_hss[i]
                if moors[i] is not None:
                    # general topologies: solve free points once per
                    # evaluation, share across wrench + stiffness.  Simple
                    # topologies see the case current through the loaded
                    # line profiles (zero current reduces to the plain
                    # vertical-plane catenary).
                    cur = None if _is_general_moor[i] else Ucur[i]
                    xf_i = mr.free_points(moors[i], X[s])
                    F = F + mr.body_wrench(moors[i], X[s], xf=xf_i,
                                           current=cur)
                    K = K + mr.coupled_stiffness(moors[i], X[s], xf=xf_i,
                                                 current=cur)
                Fs.append(F)
                Kblocks.append(K)
            Fv = jnp.concatenate(Fs)
            Km = jnp.zeros((6 * N, 6 * N), dtype=_config.real_dtype())
            for i in range(N):
                Km = Km.at[6 * i:6 * i + 6, 6 * i:6 * i + 6].set(Kblocks[i])
            if arr is not None:
                Xb = X.reshape(N, 6)
                xf = ma.solve_free_points(arr, Xb, xf0=xf)
                Fv = Fv + ma.body_wrenches(arr, Xb, xf).reshape(-1)
                Km = Km + ma.coupled_stiffness(arr, Xb, xf)
            return Fv, Km, xf

        self._eval_FK_raw = eval_FK
        return self._eval_FK_raw

    def _statics_eval_fn(self):
        """Jitted per-call wrapper of :meth:`_statics_eval_raw` (the
        host-loop Newton and the band-forensics replay call it once per
        evaluation)."""
        if getattr(self, "_eval_FK_j", None) is None:
            self._eval_FK_j = jax.jit(self._statics_eval_raw())
        return self._eval_FK_j

    #: line-search candidates of the damped Newton (both backends)
    _NEWTON_ALPHAS = (1.0, 0.5, 0.25, 0.125, 0.0625)
    _NEWTON_MAX_ITERS = 50

    def _statics_newton_fn(self):
        """Device-resident damped Newton: one jitted ``lax.while_loop``
        whose body evaluates ALL line-search alphas in a single vmapped
        ``eval_FK`` call, merit-selects and clips on device, and carries
        X/F/K/xf device-resident across iterations — the host syncs
        exactly once, at convergence (the sanctioned
        ``obs.transfers.device_get`` in ``_solve_statics_impl``).

        Algorithmically identical to the host loop in
        ``_statics_newton_host`` (same candidate order, same
        first-sufficient-wins selection, same full-step fallback, same
        |dX| < tol convergence test), so iteration counts and accepted
        poses match bit-for-bit-ish — the golden-ledger gate holds the
        rewrite to 1e-6 including the integer ``statics_iters``.

        Built once per Model; traced once and reused across cases (the
        per-case constants are arguments).  Input buffers are donated on
        accelerator backends so the pose/free-point carries reuse device
        memory (CPU has no donation — donating there only warns)."""
        if getattr(self, "_newton_j", None) is not None:
            return self._newton_j
        eval_FK = self._statics_eval_raw()
        alphas = jnp.asarray(np.array(self._NEWTON_ALPHAS))
        max_iters = self._NEWTON_MAX_ITERS

        def newton(X0, xf0, F0s, K_hss, Ucur, db, tol):
            F0, K0, xf1 = eval_FK(X0, xf0, F0s, K_hss, Ucur)

            def body(carry):
                X, F, K, xf, it, done = carry
                # guard zero-stiffness diagonals like the reference
                # (raft_model.py:713-715)
                kdiag = jnp.diagonal(K)
                kfix = jnp.where(kdiag == 0.0, jnp.mean(kdiag), kdiag)
                Kg = K + jnp.diag(kfix - kdiag)
                dX = jnp.clip(jnp.linalg.solve(Kg, F), -db, db)
                merit0 = jnp.sum(F ** 2)
                Fa, Ka, xfa = jax.vmap(
                    lambda a: eval_FK(X + a * dX, xf, F0s, K_hss, Ucur)
                )(alphas)
                merits = jnp.sum(Fa ** 2, axis=1)
                # first sufficient candidate wins (argmax of a boolean
                # vector is the first True); no candidate improving the
                # residual -> full clipped step, i.e. candidate 0 (a=1)
                suff = jnp.isfinite(merits) & (merits < merit0)
                idx = jnp.where(jnp.any(suff), jnp.argmax(suff), 0)
                X = X + jnp.where(jnp.any(suff), alphas[idx], 1.0) * dX
                # convergence on the UNDAMPED Newton step (see the host
                # loop): checked on this iteration's dX, applied next
                conv = jnp.all(jnp.abs(dX) < tol)
                return (X, Fa[idx], Ka[idx], xfa[idx], it + 1, conv)

            def cond(carry):
                return (carry[4] < max_iters) & (~carry[5])

            X, F, _K, xf, it, _ = jax.lax.while_loop(
                cond, body,
                (X0, F0, K0, xf1, jnp.zeros((), jnp.int32), False))
            res = jnp.sqrt(jnp.sum(F ** 2))
            # on-device probe: the Newton trip count/residual stream to
            # the host DURING execution (RAFT_TPU_PROBES knob; its own
            # budget — the sanctioned device_get below is untouched)
            obs.probes.probe("statics_newton", iters=it, residual=res)
            return X, xf, it, res

        donate = (0, 1) if jax.default_backend() != "cpu" else ()
        self._newton_j = jax.jit(newton, donate_argnums=donate)
        return self._newton_j

    def _case_label(self) -> str:
        """Metrics label for the current case ("unloaded" outside the
        analyzeCases loop)."""
        return "unloaded" if self._iCase is None else str(self._iCase)

    def solveStatics(self, case, display=0):
        """Mean-offset equilibrium over all 6N system DOFs (reference:
        raft_model.py:479-849).  In array mode the shared mooring's free
        points are re-equilibrated every Newton iteration and its coupled
        stiffness couples the FOWT blocks."""
        with temp_verbosity(display), \
                obs.span("solveStatics", case=self._case_label()) as sp:
            return self._solve_statics_impl(case, sp)

    def _solve_statics_impl(self, case, sp):
        N = self.nFOWT
        for i, fowt in enumerate(self.fowtList):
            self._case_constants(fowt, self._case_for_fowt(case, i),
                                 self._state[i])

        K_hs = [st["K_hydrostatic"] for st in self._state]
        F0 = [st["F_undisplaced"] + st["F_env_constant"] for st in self._state]
        refs = np.concatenate([
            [f.x_ref, f.y_ref, 0, 0, 0, 0] for f in self.fowtList])
        arr = self.arr_ms
        if arr is not None:
            from raft_tpu.models import mooring_array as ma

        X = refs.copy()
        xf = self._arr_xf
        if arr is not None and xf is None:
            xf = arr.r0[arr.attach == -2]

        F0s = jnp.asarray(np.stack(F0))
        K_hss = jnp.asarray(np.stack(K_hs))
        # the degradation ladder's damped retry shrinks the Newton step
        # clip (recovery.override("clip_scale")); 1.0 outside a retry
        db = np.tile(np.array([30, 30, 5, 0.1, 0.1, 0.1]), N) \
            * float(recovery.current("clip_scale", 1.0))
        tol = np.tile(np.array([0.05, 0.05, 0.05, 5e-3, 5e-3, 5e-3]) * 1e-3, N)
        xf_arg = (jnp.zeros((0, 3), dtype=_config.real_dtype())
                  if xf is None else jnp.asarray(xf))
        # damped Newton with a backtracking line search on |F|^2 — the
        # same scheme as parallel.variants.statics_newton (one statics
        # doctrine for the Model path and the sweep path), extended to
        # 6N DOFs with the array free points re-solved per evaluation.
        # The reference's plain clip-step loop can oscillate on
        # pathological designs (raft_model.py:677-767 band-aids).
        Ucur = jnp.asarray(np.stack([
            st.get("moor_current") if st.get("moor_current") is not None
            else np.zeros(3) for st in self._state]))

        def run_newton(Xstart, xf0):
            if _config.statics_mode() == "host":
                return self._statics_newton_host(
                    np.asarray(Xstart, float).copy(), xf0, F0s, K_hss,
                    Ucur, db, tol)
            # device-resident lax.while_loop Newton: exactly ONE host
            # sync per statics solve, through the sanctioned counted
            # exit point.  X0/xf0 buffers are donated on accelerator
            # backends — copy them so a guarded cold RE-solve (warm
            # start rejected) never re-passes a donated buffer.
            newton = self._statics_newton_fn()
            # jnp.array (copy=True by default) = an on-device copy, no
            # host round-trip — the one-sync-per-solve budget holds
            Xd, xfd, itd, resd = newton(jnp.array(Xstart),
                                        jnp.array(xf0), F0s,
                                        K_hss, Ucur, jnp.asarray(db),
                                        jnp.asarray(tol))
            Xh, xf_np, n_it, res = obs.transfers.device_get(
                (Xd, xfd, itd, resd), what="statics_newton",
                phase="statics")
            return (np.asarray(Xh, float), jnp.asarray(xf_np),
                    int(n_it), float(res))

        # ----- statics Newton warm start (opt-in): seed from the
        # previous case's converged pose instead of the reference
        # position.  Guarded exactly like the serve tier's neighbor
        # seeds: a seeded solve that fails to converge (or goes
        # non-finite) triggers a counted cold re-solve from the
        # reference start — seeding can cost one extra solve, never a
        # wrong equilibrium.
        seed = getattr(self, "_statics_seed", None)
        seeded = (bool(getattr(self, "_statics_warm", False))
                  and self._iCase is not None and seed is not None
                  and np.shape(seed) == np.shape(X)
                  and bool(np.all(np.isfinite(seed))))
        xf0 = xf_arg
        X, xf_arg, n_iters, residual = run_newton(
            np.asarray(seed, float) if seeded else X, xf0)
        if seeded:
            ok = (bool(np.all(np.isfinite(X))) and np.isfinite(residual)
                  and n_iters < self._NEWTON_MAX_ITERS)
            outcome = "seeded" if ok else "rejected"
            if not ok:
                obs.events.emit("statics_warm_rejected",
                                case=self._iCase, iters=n_iters)
                X, xf_arg, n_iters, residual = run_newton(refs.copy(),
                                                          xf0)
            counts = getattr(self, "_statics_warm_counts", None)
            if counts is not None:
                counts[outcome] = counts.get(outcome, 0) + 1
            obs.counter(
                "raft_tpu_statics_warm_total",
                "statics Newton warm-start outcomes in analyzeCases "
                "(seeded = previous-case pose accepted; rejected = "
                "guarded cold re-solve)").inc(outcome=outcome)
        # fault-injection seam + divergence screen: a Newton that walked
        # the pose into NaN/Inf (or an injected statics fault) surfaces
        # as a typed StaticsDivergence the degradation ladder can act on
        if faults.maybe_raise("statics", case=self._iCase) == "nan":
            X = np.full_like(np.asarray(X, float), np.nan)
        if not np.all(np.isfinite(X)) or not np.isfinite(residual):
            raise errors.StaticsDivergence(
                "statics Newton produced a non-finite pose",
                case=self._iCase, iters=n_iters, residual=residual,
                backend=_config.statics_mode())
        if getattr(self, "_statics_warm", False) \
                and n_iters < self._NEWTON_MAX_ITERS:
            # converged pose becomes the next case's seed (DLC-shaped
            # case tables walk the operating point smoothly)
            self._statics_seed = np.asarray(X, float).copy()
        case_lbl = self._case_label()
        sp.set(newton_iters=n_iters, residual_norm=residual)
        obs.histogram(
            "raft_statics_newton_iterations",
            "damped-Newton iterations to mean-offset equilibrium",
            buckets=obs.ITER_BUCKETS).observe(n_iters, case=case_lbl)
        obs.gauge(
            "raft_statics_residual_norm",
            "|F| at the accepted statics equilibrium [N]",
            ).set(residual, case=case_lbl)
        rec = self._case_records.setdefault(case_lbl, {})
        rec["statics_iters"] = n_iters
        rec["statics_residual"] = residual

        # mooring properties at the FINAL pose (one more free-point solve
        # so xf corresponds to X, not the previous Newton iterate)
        if arr is not None:
            Xb = X.reshape(N, 6)
            xf = ma.solve_free_points(arr, Xb, xf0=xf_arg)
            self._arr_xf = np.asarray(xf)
            # rotation-vector flavor for the same reason as the
            # single-body dynamics C_moor below (MoorPy analytic parity)
            self._K_array = np.asarray(
                ma.coupled_stiffness_rotvec(arr, Xb, xf))
        else:
            self._arr_xf = None
            self._K_array = None
        for i, fowt in enumerate(self.fowtList):
            s = slice(6 * i, 6 * i + 6)
            state = self._state[i]
            state["r6"] = X[s]
            state["Xi0"] = X[s] - refs[s]
            # NOTE: the reference does NOT re-evaluate turbine constants
            # at the solved pose — the "update values based on offsets"
            # block (raft_model.py:798-850, incl. the
            # calcTurbineConstants(ptfm_pitch=Xi0[4]) loop) sits inside a
            # triple-quoted TODO string and never executes.  Dynamics and
            # outputs therefore use the statics-time constants: zero
            # pose, current-case heading, stale-heading hub transfer
            # (state["turbine"]).
            if fowt.mooring is not None:
                # MoorPy-parity analytic stiffness at the equilibrium pose
                # — the reference's dynamics/eigen C_moor is
                # getCoupledStiffnessA from setPosition (raft_fowt.py:287),
                # whose Taylor-series assembly is the ROTATION-VECTOR
                # linearization, not the Euler-angle jacobian.  At loaded
                # poses (several degrees mean pitch/yaw) the two differ by
                # the Euler-rate factor on the roll/pitch columns — the
                # round-4 operating-case wave-band residual (0.3-1.8% stds)
                # closed to ~1e-5 when this switched to rotvec (round 5).
                # Only the TENSION statistics use the FD variant.
                cur = state.get("moor_current")
                state["C_moor"] = np.asarray(
                    mr.coupled_stiffness_rotvec(fowt.mooring, X[s],
                                                current=cur))
                state["F_moor0"] = np.asarray(
                    mr.body_wrench(fowt.mooring, X[s], current=cur))
            else:
                state["C_moor"] = np.zeros((6, 6))
                state["F_moor0"] = np.zeros(6)
        if case and "iCase" in case:
            self.results.setdefault("mean_offsets", []).append(X.copy())
        _LOG.info("Found mean offsets: %s", X - refs)
        return X

    def _statics_newton_host(self, X, xf_arg, F0s, K_hss, Ucur, db, tol):
        """Host-driven damped Newton (the ``RAFT_TPU_STATICS=host``
        escape hatch and the parity reference for the device
        ``lax.while_loop`` backend): a Python loop with one device→host
        sync and a SERIAL 5-alpha line search per iteration.  Returns
        ``(X, xf_arg, n_iters, residual)``."""
        eval_FK_j = self._statics_eval_fn()
        alphas = np.array(self._NEWTON_ALPHAS)
        Fj, Kj, xf_arg = eval_FK_j(jnp.asarray(X), xf_arg, F0s, K_hss, Ucur)
        for it in range(self._NEWTON_MAX_ITERS):
            F, K = np.asarray(Fj), np.asarray(Kj).copy()
            # guard zero-stiffness diagonals like the reference (:713-715)
            kmean = np.mean(np.diag(K))
            for i in range(len(F)):
                if K[i, i] == 0:
                    K[i, i] = kmean
            dX = np.linalg.solve(K, F)
            dX = np.clip(dX, -db, db)
            merit0 = float(np.sum(F**2))
            best = None
            full_step = None
            for a in alphas:
                Fa, Ka, xfa = eval_FK_j(jnp.asarray(X + a * dX), xf_arg,
                                        F0s, K_hss, Ucur)
                if a == 1.0:
                    full_step = (Fa, Ka, xfa)
                merit_a = float(np.sum(np.asarray(Fa)**2))
                if np.isfinite(merit_a) and (best is None
                                             or merit_a < best[0]):
                    best = (merit_a, a, Fa, Ka, xfa)
                if merit_a < merit0:     # first sufficient candidate wins
                    break
            if best is not None and best[0] < merit0:
                _, a, Fj, Kj, xf_arg = best
                X = X + a * dX
            else:
                # no candidate improves the residual: take the full
                # clipped step once (reference behavior), reusing the
                # a=1.0 candidate's evaluation
                X = X + dX
                Fj, Kj, xf_arg = full_step
            # convergence on the UNDAMPED Newton step (the reference's
            # |dX| < tol criterion) — a heavily damped accepted step can
            # be small while the residual is still far from equilibrium
            if np.all(np.abs(dX) < tol):
                break
        residual = float(np.sqrt(np.sum(np.asarray(Fj) ** 2)))
        return X, xf_arg, it + 1, residual

    # ------------------------------------------------------------------
    # eigen
    # ------------------------------------------------------------------

    def solveEigen(self, display=0):
        with temp_verbosity(display), \
                obs.span("solveEigen", case=self._case_label()) as sp:
            fns, modes = self._solve_eigen_impl()
            sp.set(fn_min_hz=float(np.min(fns)), fn_max_hz=float(np.max(fns)))
            g = obs.gauge("raft_eigen_fn_hz",
                          "undamped natural frequency per system DOF [Hz]")
            for idof, fn in enumerate(np.asarray(fns)):
                g.set(float(fn), dof=str(idof))
            _LOG.info("natural frequencies [Hz]: %s", np.array2string(
                np.asarray(fns), precision=4))
        return fns, modes

    def _solve_eigen_impl(self):
        nDOF = self.nDOF
        M_tot = np.zeros((nDOF, nDOF))
        C_tot = np.zeros((nDOF, nDOF))
        for i, fowt in enumerate(self.fowtList):
            s = slice(6 * i, 6 * i + 6)
            state = self._state[i]
            stat = state["statics"]
            hc = state.get("hydro0") or fowt_hydro_constants(fowt, state["pose0"])
            M_tot[s, s] = (np.asarray(stat["M_struc"])
                           + np.asarray(hc["A_hydro_morison"]))
            C_tot[s, s] = (np.asarray(stat["C_struc"])
                           + np.asarray(stat["C_hydro"]) + state["C_moor"])
            C_tot[6 * i + 5, 6 * i + 5] += fowt.yawstiff
        if self._K_array is not None:
            C_tot += self._K_array

        for i in range(nDOF):
            if M_tot[i, i] < 1.0 or C_tot[i, i] < 1.0:
                raise errors.EigenFailure(
                    "small/negative diagonal in system matrices",
                    dof=i, M_ii=float(M_tot[i, i]), C_ii=float(C_tot[i, i]))

        eigenvals, eigenvectors = np.linalg.eig(np.linalg.solve(M_tot, C_tot))
        if any(eigenvals <= 0.0):
            raise errors.EigenFailure(
                "zero or negative system eigenvalues detected",
                n_nonpositive=int(np.sum(eigenvals <= 0.0)))

        # DOF-claiming sort (reference: raft_model.py:441-456)
        ind_list = []
        for i in range(nDOF - 1, -1, -1):
            vec = np.abs(eigenvectors[i, :]).copy()
            for _ in range(nDOF):
                ind = int(np.argmax(vec))
                if ind in ind_list:
                    vec[ind] = 0.0
                else:
                    ind_list.append(ind)
                    break
        ind_list.reverse()
        fns = np.sqrt(eigenvals[ind_list]) / 2.0 / np.pi
        modes = eigenvectors[:, ind_list]
        self.results["eigen"] = {"frequencies": fns, "modes": modes}
        return fns, modes

    # ------------------------------------------------------------------
    # dynamics
    # ------------------------------------------------------------------

    def solveDynamics(self, case, tol=0.01, display=0):
        """Iterative drag linearization per FOWT + block system RAO solve
        (reference: raft_model.py:852-1146).  Each FOWT's drag fixed point
        converges on its own 6x6 impedance (matching the reference, which
        excludes the array-level mooring stiffness from the linearization
        loop); the block-diagonal system impedance plus the shared-mooring
        stiffness then yields the coupled response per heading."""
        with temp_verbosity(display), \
                obs.span("solveDynamics", case=self._case_label()) as sp:
            return self._solve_dynamics_impl(case, tol, display, sp)

    def _record_dyn_residual(self, ih, rel):
        """Record one heading's system-solve relative residual
        ||Z Xi - F|| / ||F||, computed on device by ``_dyn_solve_core``
        (in both telemetry modes) — a health check on the factor-once
        Zinv reuse."""
        rel = float(rel)
        obs.gauge(
            "raft_dynamics_solve_residual",
            "relative residual |Z Xi - F|/|F| of the system RAO solve",
            ).set(rel, case=self._case_label(), heading=str(ih))
        rec = self._case_records.setdefault(self._case_label(), {})
        rec.setdefault("dyn_solve_residual", []).append(rel)
        return rel

    def _solve_dynamics_impl(self, case, tol, display, sp):
        N = self.nFOWT
        nw = self.nw
        for i in range(N):
            with obs.span("fowt_linearize", fowt=i,
                          case=self._case_label()):
                self._fowt_linearize(i, self._case_for_fowt(case, i),
                                     tol=tol, display=display)

        # ----- system assembly — ON DEVICE (reference :1021-1031); the
        # converged per-FOWT impedances never leave the device between
        # the drag fixed point and the factored solve -----
        if N == 1:
            Z_sys = jnp.moveaxis(jnp.asarray(self._state[0]["Z"]), -1, 0)
        else:
            Z_sys = jnp.zeros((nw, 6 * N, 6 * N),
                              dtype=_config.complex_dtype())
            for i in range(N):
                s = slice(6 * i, 6 * i + 6)
                Z_sys = Z_sys.at[:, s, s].set(
                    jnp.moveaxis(jnp.asarray(self._state[i]["Z"]), -1, 0))
        if self._K_array is not None:
            Z_sys = Z_sys + jnp.asarray(self._K_array)[None, :, :]
        # factor once, reuse across headings and 2nd-order re-solves
        # (the reference's Zinv, raft_model.py:1038-1040)
        Zinv = inv_complex(Z_sys)

        # solver-health telemetry: conditioning of the complex system
        # across the frequency axis (a resonance-adjacent near-singular
        # impedance shows up here long before the response goes bad).
        # Default ("fast"): the SVD runs ON DEVICE and three scalars
        # cross to host; RAFT_TPU_TELEMETRY=full restores the host
        # np.linalg.cond over the pulled stack (a counted, sanctioned
        # transfer).  A non-finite stack records nothing — telemetry
        # must not preempt the clearer non-finite diagnostic the solve
        # path raises downstream
        if _config.telemetry_mode() == "full":
            Z_host = obs.transfers.device_get(
                Z_sys, what="impedance_stack", phase="dynamics")
            finite = bool(np.all(np.isfinite(Z_host)))
            if finite:
                cond = np.linalg.cond(Z_host)
                cond_max = float(cond.max())
                cond_med = float(np.median(cond))
        else:
            finite, cond_max, cond_med = obs.transfers.device_get(
                _cond_jit()(Z_sys), what="cond_estimate", phase="dynamics")
            finite = bool(finite)
        if finite:
            cond_max, cond_med = float(cond_max), float(cond_med)
            sp.set(cond_max=cond_max, cond_median=cond_med)
            obs.gauge(
                "raft_dynamics_condition_number",
                "max condition number of the 6Nx6N impedance over "
                "frequencies").set(cond_max, case=self._case_label())
            self._case_records.setdefault(self._case_label(), {})[
                "cond_max"] = cond_max

        nWaves = self._state[0]["seastate"]["nWaves"]

        # ----- heading-batched excitation assembly (device) -----
        # linearized drag excitation for ALL headings in one batched
        # call per FOWT (fowt_drag_excitation is rank-polymorphic over
        # the leading heading axis); the potSecOrder==2 second-order
        # forces stay host-side QTF math, exactly as before
        for i, fowt in enumerate(self.fowtList):
            st = self._state[i]
            st["F_drag"] = fowt_drag_excitation(
                fowt, st["pose_eq"], st["Bmat"],
                st["excitation"]["u"][:nWaves])
            if fowt.potSecOrder == 2:
                qd = fowt.qtf_data
                for ih in range(1, nWaves):
                    st["Fhydro_2nd_mean"][ih], f2h = (np.asarray(a) for a in
                        qt.hydro_force_2nd(qd.qtf, qd.heads_rad, qd.w,
                                           st["seastate"]["beta"][ih],
                                           st["seastate"]["S"][ih], self.w))
                    st["Fhydro_2nd"][ih] = f2h

        def assemble_F():
            """(nWaves, 6N, nw) excitation stack, device-resident."""
            if N == 1:
                st = self._state[0]
                return (jnp.asarray(st["F_BEM"])[:nWaves]
                        + jnp.asarray(st["excitation"]["F_hydro_iner"])[:nWaves]
                        + st["F_drag"]
                        + jnp.asarray(st["Fhydro_2nd"])).astype(
                    _config.complex_dtype())
            F_all = jnp.zeros((nWaves, 6 * N, nw),
                              dtype=_config.complex_dtype())
            for i in range(N):
                st = self._state[i]
                s = slice(6 * i, 6 * i + 6)
                F_all = F_all.at[:, s, :].set(
                    jnp.asarray(st["F_BEM"])[:nWaves]
                    + jnp.asarray(st["excitation"]["F_hydro_iner"])[:nWaves]
                    + st["F_drag"]
                    + jnp.asarray(st["Fhydro_2nd"]))
            return F_all

        F_all = assemble_F()
        if not self._dyn_cost_recorded:
            # static HLO cost analysis of the heading-batched dynamics
            # solve (a trace, not an XLA compile) — once per
            # analyzeCases run, folded into the metrics registry and
            # thence the run manifest
            self._dyn_cost_recorded = True
            obs.device.cost_analysis(_dyn_solve_jit(self.mesh), Zinv,
                                     Z_sys, F_all,
                                     kernel="dynamics_system_solve")
        # ONE batched solve over every heading; the per-heading solve
        # residuals come back as nWaves scalars in the same pull
        Xi_d, rel_d = _dyn_solve_jit(self.mesh)(Zinv, Z_sys, F_all)
        rel = obs.transfers.device_get(rel_d, what="solve_residual",
                                       phase="dynamics")
        rel2 = None

        # internal-QTF secondary headings: QTF from each heading's
        # first-order RAOs, then ONE batched re-solve with the 2nd-order
        # forces included (reference: raft_model.py:1066-1083) — the
        # factored Zinv is reused on device, never re-pulled to host
        if nWaves > 1 and any(f.potSecOrder == 1 for f in self.fowtList):
            Xi_first = obs.transfers.device_get(
                Xi_d, what="first_order_rao", phase="dynamics")
            for ih in range(1, nWaves):
                for i, fowt in enumerate(self.fowtList):
                    if fowt.potSecOrder != 1:
                        continue
                    s = slice(6 * i, 6 * i + 6)
                    st = self._state[i]
                    RAO_h = np.asarray(get_rao(
                        Xi_first[ih, s, :], st["seastate"]["zeta"][ih]))
                    qtf_h = np.asarray(qt.calc_qtf_slender_body(
                        fowt, st["pose_eq"], st["seastate"]["beta"][ih],
                        Xi0=RAO_h, M_struc=st["statics"]["M_struc"]))[:, :, None, :]
                    st["Fhydro_2nd_mean"][ih], f2h = (np.asarray(a) for a in
                        qt.hydro_force_2nd(qtf_h,
                                           np.array([st["seastate"]["beta"][ih]]),
                                           fowt.w1_2nd, st["seastate"]["beta"][ih],
                                           st["seastate"]["S"][ih], self.w))
                    st["Fhydro_2nd"][ih] = f2h
            Xi2_d, rel2_d = _dyn_solve_jit(self.mesh)(Zinv, Z_sys,
                                                      assemble_F())
            # heading 0's converged first-order solution is kept; the
            # secondary headings take the re-solved response
            Xi_d = jnp.concatenate([Xi_d[:1], Xi2_d[1:]], axis=0)
            rel2 = obs.transfers.device_get(
                rel2_d, what="solve_residual", phase="dynamics")
        # residual cadence matches the old per-heading loop: first-order
        # solve, then (when present) that heading's re-solve
        for ih in range(nWaves):
            self._record_dyn_residual(ih, rel[ih])
            if rel2 is not None and ih > 0:
                self._record_dyn_residual(ih, rel2[ih])

        # ----- final write-back: the ONE response pull per case -----
        Xi_np = obs.transfers.device_get(Xi_d, what="response",
                                         phase="dynamics")
        Xi_sys = np.zeros((nWaves + 1, 6 * N, nw),
                          dtype=complex)  # raftlint: disable=RTL003 host-side result mirror stays complex128
        Xi_sys[:nWaves] = np.asarray(Xi_np)

        for i, fowt in enumerate(self.fowtList):
            s = slice(6 * i, 6 * i + 6)
            st = self._state[i]
            st["Xi"] = Xi_sys[:, s, :]
            if fowt.potSecOrder > 0:
                # mean drift feeds the statics re-solve (reference :548-554)
                st["F_meandrift"] = st["Fhydro_2nd_mean"].sum(axis=0)
        # sanitize the solved response before it reaches any consumer
        # (reference guards the same way, raft_model.py:956-957) — a NaN
        # here means diverged drag linearization or corrupt coefficients
        bad = ~np.isfinite(np.asarray(Xi_sys))
        if bad.any():
            raise errors.NonFiniteResult(
                f"solveDynamics produced {int(bad.sum())} non-finite "
                "response value(s); check BEM/QTF input files and "
                "drag-linearization convergence",
                case=self._iCase, n_bad=int(bad.sum()),
                nWaves=int(nWaves))
        self.Xi = Xi_sys
        self.results["response"] = {}
        return Xi_sys

    def _fowt_linearize(self, ifowt, case, tol=0.01, display=0):
        """Per-FOWT drag-linearization fixed point producing the converged
        6x6 impedance (reference: raft_model.py:877-1013)."""
        fowt = self.fowtList[ifowt]
        state = self._state[ifowt]
        # the ladder's damped restart doubles the iteration budget and
        # strengthens the under-relaxation (recovery.override); the
        # defaults reproduce the reference 0.2/0.8 scheme bitwise
        nIter = self.nIter * int(recovery.current("fp_iter_mult", 1)) + 1
        keep, relax = recovery.relax_weights(
            recovery.current("fp_relax", 0.8))
        w = jnp.asarray(self.w)
        nw = self.nw

        seastate = build_seastate(fowt, case)
        nWaves = seastate["nWaves"]
        pose_eq = fowt_pose(fowt, state["r6"])
        state["pose_eq"] = pose_eq
        state["seastate"] = seastate
        hc0 = state["hydro0"]

        exc = fowt_hydro_excitation(fowt, pose_eq, seastate, hc0)
        state["excitation"] = exc

        tc = state["turbine"]
        stat = state["statics"]
        if fowt.nrotors > 0 and tc is not None:
            M_turb = jnp.sum(tc["A_aero"], axis=3)
            B_turb = jnp.sum(tc["B_aero"], axis=3)
            B_gyro = jnp.sum(tc["B_gyro"], axis=2)
        else:
            M_turb = jnp.zeros((6, 6, nw), dtype=_config.real_dtype())
            B_turb = jnp.zeros((6, 6, nw), dtype=_config.real_dtype())
            B_gyro = jnp.zeros((6, 6), dtype=_config.real_dtype())

        # potential-flow coefficients (reference: raft_model.py:911-914 —
        # A_BEM/B_BEM always enter the linear system once loaded; F_BEM per
        # the potMod guard inside fowt_bem_excitation)
        from raft_tpu.io.wamit import bem_coeffs
        A_BEM, B_BEM = bem_coeffs(fowt.bem, nw)
        F_BEM = fowt_bem_excitation(fowt, seastate)   # (nH,6,nw)
        state["F_BEM"] = F_BEM

        M_lin = M_turb + jnp.asarray(stat["M_struc"])[:, :, None] \
            + jnp.asarray(hc0["A_hydro_morison"])[:, :, None] + A_BEM
        B_lin = B_turb + B_gyro[:, :, None] + B_BEM
        C_lin = (jnp.asarray(stat["C_struc"]) + jnp.asarray(state["C_moor"])
                 + jnp.asarray(stat["C_hydro"]))
        # NOTE: the additional platform yaw stiffness (OC3 crowfoot
        # surrogate) deliberately does NOT enter the dynamics impedance —
        # the reference's C_lin is C_struc + C_moor(analytic) + C_hydro
        # only (raft_model.py:913); yawstiff appears in the eigen solve
        # (raft_model.py:418) and the statics.

        u0 = exc["u"][0]

        # ----- second-order forces (reference: raft_model.py:901-904) -----
        Fhydro_2nd = np.zeros((nWaves, 6, nw))
        Fhydro_2nd_mean = np.zeros((nWaves, 6))
        if fowt.potSecOrder == 2:
            qd = fowt.qtf_data
            Fhydro_2nd_mean[0], f2 = (np.asarray(a) for a in qt.hydro_force_2nd(
                qd.qtf, qd.heads_rad, qd.w, seastate["beta"][0],
                seastate["S"][0], self.w))
            Fhydro_2nd[0] = f2

        F_lin = F_BEM[0] + exc["F_hydro_iner"][0] + Fhydro_2nd[0]   # (6, nw)

        drag_pre = fowt_drag_precompute(fowt, pose_eq, u0)

        def run_fixed_point(F_lin, Xi_init=None):
            """Drag-linearization fixed point: lax.while_loop around one
            batched complex solve over all frequencies.  ``Xi_init`` warm-
            starts the iteration (used by the potSecOrder==1 re-solve,
            matching the reference's counter-only reset at
            raft_model.py:966-989)."""

            def iteration(carry):
                XiLast, Xi, Z, Bmat, ii, done = carry
                B_drag, Bmat = fowt_hydro_linearization_pre(
                    fowt, pose_eq, drag_pre, XiLast)
                F_drag = fowt_drag_excitation(fowt, pose_eq, Bmat, u0)
                B_tot = B_lin + B_drag[:, :, None]
                Zn = (-w[None, None, :] ** 2 * M_lin
                      + 1j * w[None, None, :] * B_tot
                      + C_lin[:, :, None]).astype(
                          _config.complex_dtype())
                # batched complex 6x6 solve over all frequencies at once
                # (real block embedding keeps this TPU-compatible); the
                # converged Zn itself is still carried out of the loop —
                # the system assembly needs it — so only the solve goes
                # through the fused dispatch (XLA CSEs the assembly)
                Xin = impedance_solve(w, M_lin, B_tot, C_lin,
                                      F_lin + F_drag)
                tolCheck = jnp.abs(Xin - XiLast) / (jnp.abs(Xin) + tol)
                conv = jnp.all(tolCheck < tol)
                # per-iteration residual streamed live off the device
                # (trace-time no-op under RAFT_TPU_PROBES=off)
                obs.probes.probe("drag_fixed_point", it=ii,
                                 residual=jnp.max(tolCheck))
                XiNext = jnp.where(conv, XiLast,
                                   keep * XiLast + relax * Xin)
                return (XiNext, Xin, Zn, Bmat, ii + 1, done | conv)

            def cond(carry):
                _, _, _, _, ii, done = carry
                return (ii < nIter) & (~done)

            if Xi_init is None:
                Xi0c = jnp.zeros(
                    (6, nw), dtype=_config.complex_dtype()) + self.XiStart
            else:
                Xi0c = jnp.asarray(Xi_init)
            Z0 = jnp.zeros((6, 6, nw), dtype=_config.complex_dtype())
            Bmat0 = jnp.zeros((fowt.nodes.n, 3, 3),
                              dtype=_config.real_dtype())
            if jax.default_backend() != "cpu":
                # donate the warm-start buffer so the Xi carry reuses
                # device memory (CPU has no donation — it would only
                # warn); the while_loop traces per call either way
                fp = jax.jit(
                    lambda x0: jax.lax.while_loop(
                        cond, iteration, (x0, x0, Z0, Bmat0, 0, False)),
                    donate_argnums=0)
                return fp(Xi0c)
            return jax.lax.while_loop(cond, iteration,
                                      (Xi0c, Xi0c, Z0, Bmat0, 0, False))

        def run_fixed_point_guarded(F_lin, Xi_init=None):
            """Trace/compile/execute failures of the solve kernel become
            typed KernelFailures the degradation ladder can step down
            (Pallas -> jnp -> damped restart)."""
            try:
                return run_fixed_point(F_lin, Xi_init=Xi_init)
            except errors.RaftError:
                raise
            except (FloatingPointError, RuntimeError) as e:
                from raft_tpu.ops import linalg as _linalg
                raise errors.KernelFailure(
                    "drag fixed-point solve kernel failed",
                    case=self._iCase, fowt=ifowt,
                    dispatch=_linalg.last_dispatch().get("backend"),
                ) from e

        carry = run_fixed_point_guarded(jnp.asarray(F_lin))

        if fowt.potSecOrder == 1:
            # internal QTF from the drag-converged first-order RAOs, then
            # re-converge with the 2nd-order forces included (reference:
            # raft_model.py:966-989)
            Xi1 = np.asarray(obs.transfers.device_get(
                carry[1], what="first_order_rao", phase="dynamics"))
            RAO = np.asarray(get_rao(Xi1, seastate["zeta"][0]))
            # outFolderQTF: drop .4 RAO + .12d QTF snapshots and reload the
            # QTF as a checkpoint when inputs are unchanged (reference
            # writes the same files, raft_fowt.py:1420-1433/1642-1648; the
            # content-hash reload is the resume half the reference lacks)
            qtf4 = None
            cache_path = key = None
            if self.outFolderQTF is not None:
                import hashlib
                import os as _os
                _os.makedirs(self.outFolderQTF, exist_ok=True)
                beta0 = float(seastate["beta"][0])
                tag = f"Head{int(round(np.rad2deg(beta0)))}"
                if self._iCase is not None:
                    tag += f"_Case{self._iCase + 1}"
                tag += f"_WT{ifowt}"
                qt.write_rao_4(
                    _os.path.join(self.outFolderQTF,
                                  f"raos-slender_body_{tag}.4"),
                    self.w, beta0, RAO)
                h = hashlib.sha256()
                for a in (state["r6"], [beta0], RAO,
                          stat["M_struc"], fowt.w1_2nd):
                    h.update(np.ascontiguousarray(
                        np.asarray(a, dtype=complex)).tobytes())  # raftlint: disable=RTL003 digest canonicalization is width-pinned by contract
                # fold the DIRECT QTF inputs into the key too — the RAO is
                # not a perfect proxy for every QTF-affecting quantity (a
                # geometry edit could leave the first-order response
                # numerically unchanged): node fields, depth, rho/g, and
                # the per-member MCF flags (ADVICE r2)
                import dataclasses as _dc
                for fld in sorted(f.name for f in _dc.fields(fowt.nodes)):
                    val = getattr(fowt.nodes, fld)
                    h.update(fld.encode())
                    if val is not None:
                        h.update(np.ascontiguousarray(np.asarray(
                            val, dtype=float)).tobytes())
                h.update(np.asarray(
                    [fowt.depth, fowt.rho_water, fowt.g]).tobytes())
                h.update(np.asarray(
                    [bool(getattr(m, "MCF", False)) for m in fowt.members],
                    dtype=bool).tobytes())
                # member end positions pin geometry the per-node scalars
                # can't (a member relocated/re-oriented with unchanged
                # discretization would otherwise collide)
                for m in fowt.members:
                    h.update(np.ascontiguousarray(np.asarray(
                        [m.rA0, m.rB0], dtype=float)).tobytes())
                key = h.hexdigest()
                cache_path = _os.path.join(
                    self.outFolderQTF,
                    f"qtf-slender_body-total_{tag}.12d")
                key_path = cache_path + ".key"
                if (_os.path.isfile(cache_path)
                        and _os.path.isfile(key_path)
                        and open(key_path).read().strip() == key):
                    qd = qt.read_qtf_12d(cache_path, rho=fowt.rho_water,
                                         g=fowt.g)
                    if (len(qd.w) == len(fowt.w1_2nd)
                            and np.allclose(qd.w, fowt.w1_2nd, rtol=1e-6)):
                        qtf4 = qd.qtf
            if qtf4 is None:
                with obs.span("calcQTF_slenderBody", fowt=ifowt,
                              case=self._case_label()):
                    qtf_local = qt.calc_qtf_slender_body(
                        fowt, pose_eq, seastate["beta"][0], Xi0=RAO,
                        M_struc=stat["M_struc"])
                qtf4 = np.asarray(qtf_local)[:, :, None, :]
                if cache_path is not None:
                    qt.write_qtf_12d(cache_path, qtf4, fowt.w1_2nd,
                                     [float(seastate["beta"][0])],
                                     rho=fowt.rho_water, g=fowt.g)
                    with open(cache_path + ".key", "w") as f:
                        f.write(key)
            heads = np.array([seastate["beta"][0]])
            Fhydro_2nd_mean[0], f2 = (np.asarray(a) for a in qt.hydro_force_2nd(
                qtf4, heads, fowt.w1_2nd, seastate["beta"][0],
                seastate["S"][0], self.w))
            Fhydro_2nd[0] = f2
            F_lin = F_lin + Fhydro_2nd[0]
            carry = run_fixed_point_guarded(jnp.asarray(F_lin), Xi_init=Xi1)
            state["qtf"] = qtf4

        XiLast, Xi1, Z, Bmat, niter, converged = carry

        # ----- solver-health metrics: the fixed point's convergence -----
        # one sanctioned pull for the whole carry summary (iteration
        # count, convergence flag, last two iterates); the converged
        # impedance Z and drag matrix Bmat STAY on device for the
        # system assembly / heading-batched drag excitation
        n_it, conv, Xi1_np, XiLast_np = obs.transfers.device_get(
            (niter, converged, Xi1, XiLast), what="drag_fixed_point",
            phase="dynamics")
        n_it = int(n_it)
        conv = bool(conv)
        Xi1_np, XiLast_np = np.asarray(Xi1_np), np.asarray(XiLast_np)
        residual = float(np.max(np.abs(Xi1_np - XiLast_np)
                                / (np.abs(Xi1_np) + tol)))
        lbl = dict(fowt=ifowt, case=self._case_label())
        obs.histogram(
            "raft_fixed_point_iterations",
            "drag-linearization fixed-point iterations per load case",
            buckets=obs.ITER_BUCKETS).observe(n_it, **lbl)
        obs.gauge(
            "raft_fixed_point_last_iterations",
            "iterations of the most recent drag fixed point",
            ).set(n_it, **lbl)
        obs.gauge(
            "raft_fixed_point_residual",
            "final relative update of the drag fixed point "
            "(|Xi_n - Xi_{n-1}| / (|Xi_n| + tol), max over DOF x freq)",
            ).set(residual, **lbl)
        if not conv:
            obs.counter(
                "raft_fixed_point_nonconverged_total",
                "drag fixed points that hit nIter without converging",
                ).inc(1, **lbl)
        cur = obs.current_span()
        if cur is not None:
            cur.set(iterations=n_it, residual=residual, converged=conv)
        rec = self._case_records.setdefault(self._case_label(), {})
        rec[f"fowt{ifowt}"] = {"drag_iters": n_it,
                               "drag_residual": residual,
                               "drag_converged": conv}

        state["Fhydro_2nd"] = Fhydro_2nd
        state["Fhydro_2nd_mean"] = Fhydro_2nd_mean
        # fault-injection seam: nan@dynamics poisons the converged
        # impedance so the non-finite sanitizer (and thence the
        # ladder/quarantine) sees a realistic corrupt-solve signature
        if faults.maybe_raise("dynamics", case=self._iCase,
                              fowt=ifowt) == "nan":
            Z = Z * jnp.nan
        # the converged impedance stays a DEVICE array: the dynamics
        # system assembly and the heading-batched solve consume it
        # without a host round-trip (state["F_drag"] is filled there)
        state["Z"] = Z
        state["Bmat"] = Bmat

    # ------------------------------------------------------------------
    # case loop
    # ------------------------------------------------------------------

    def analyzeUnloaded(self, ballast=0, heave_tol=1.0):
        """Unloaded equilibrium, optionally preceded by ballast trim
        (reference: raft_model.py:184-241; ballast==1 walks fill levels,
        ballast==2 shifts fill densities uniformly)."""
        if self.nFOWT > 1:
            raise errors.ModelConfigError(
                "analyzeUnloaded only works for a single FOWT (reference: "
                "raft_model.py:191-192)", nFOWT=self.nFOWT)
        fowt = self.fowtList[0]
        if ballast == 1:
            self.adjustBallast(fowt, heave_tol=heave_tol)
        elif ballast == 2:
            self.adjustBallastDensity(fowt)
        self.results.setdefault("properties", {})
        self.solveStatics(None)
        self.results["properties"]["offset_unloaded"] = self._state[0]["Xi0"]
        # unloaded mooring reaction/stiffness snapshots for calcOutputs
        # (the reference's self.C_moor0/F_moor0, raft_model.py:230-233)
        self.C_moor0 = self._state[0]["C_moor"].copy()
        self.F_moor0 = self._state[0]["F_moor0"].copy()

    # ------------------------------------------------------------------
    # ballast trim
    # ------------------------------------------------------------------

    def _heave_imbalance(self, fowt):
        """(sumFz, heave, stat): net vertical force at the undisplaced pose
        and the linearized heave offset (reference: raft_model.py:1448-1453)."""
        ref = np.array([fowt.x_ref, fowt.y_ref, 0, 0, 0, 0], float)
        pose0 = fowt_pose(fowt, ref)
        stat = fowt_statics(fowt, pose0)
        Fz_moor = 0.0
        if fowt.mooring is not None:
            Fz_moor = float(np.asarray(mr.body_wrench(fowt.mooring, ref))[2])
        m = float(np.asarray(stat["M_struc"])[0, 0])
        V = float(np.asarray(stat["V"]))
        AWP = float(np.asarray(stat["AWP"]))
        sumFz = -m * fowt.g + V * fowt.rho_water * fowt.g + Fz_moor
        heave = sumFz / (fowt.rho_water * fowt.g * AWP)
        return sumFz, heave, stat

    @staticmethod
    def _section_fill_volume(geom, j, l_fill):
        """Ballast volume of member section j filled to ``l_fill``, using
        the reference's convention of interpolating the inner frustum over
        the FULL member length (raft_model.py:1484-1492)."""
        l = geom.l
        if geom.circular:
            dAi = float(geom.d[j] - 2 * geom.t[j])
            dBi = float(geom.d[j + 1] - 2 * geom.t[j + 1])
            dBf = (dBi - dAi) * (l_fill / l) + dAi
            return np.pi / 12.0 * l_fill * (dAi**2 + dAi * dBf + dBf**2)
        slAi = np.asarray(geom.d[j]) - 2 * geom.t[j]
        slBi = np.asarray(geom.d[j + 1]) - 2 * geom.t[j + 1]
        slBf = (slBi - slAi) * (l_fill / l) + slAi
        A1 = slAi[0] * slAi[1]
        A2 = slBf[0] * slBf[1]
        return l_fill / 3.0 * (A1 + A2 + np.sqrt(max(A1 * A2, 0.0)))

    def _member_groups(self, fowt):
        """Platform members grouped by repeated-heading pattern (one yaml
        member entry per group, recorded at build time), mirroring the
        reference's one-member-per-heading-group adjustment
        (raft_model.py:1464-1467 keyed off member.headings)."""
        if fowt.platmem_groups is not None:
            return fowt.platmem_groups
        return [[i] for i in range(fowt.nplatmems)]

    def adjustBallast(self, fowt, heave_tol=1.0, display=0):
        """Walk ballast fill levels member-by-member until the linearized
        unloaded heave is within ``heave_tol`` (reference:
        raft_model.py:1434-1566).  The reference's 1 cm stepping loop is
        replaced by an exact bisection to the same rounded (2-decimal)
        fill level."""
        with temp_verbosity(int(display)):
            return self._adjust_ballast_impl(fowt, heave_tol)

    def _adjust_ballast_impl(self, fowt, heave_tol):
        sumFz, heave, _ = self._heave_imbalance(fowt)
        dmass = sumFz / fowt.g
        _LOG.info(" initial heave imbalance %.3f m", heave)
        for group in self._member_groups(fowt):
            geom0 = fowt.members[group[0]]
            rho_fills = np.atleast_1d(np.asarray(geom0.rho_fill, float))
            for j, rho_b in enumerate(rho_fills):
                if rho_b <= 0:
                    continue
                dvol = dmass / rho_b
                mdvol = dvol / len(group)
                l = geom0.l
                l_fill0 = float(np.atleast_1d(geom0.l_fill)[j])
                V0 = self._section_fill_volume(geom0, j, l_fill0)
                Vtarget = V0 + mdvol
                Vmax = self._section_fill_volume(geom0, j, l)
                if Vtarget >= Vmax:
                    l_new = l
                elif Vtarget <= 0.0:
                    l_new = 0.0
                else:
                    lo, hi = 0.0, l
                    for _ in range(60):
                        mid = 0.5 * (lo + hi)
                        if self._section_fill_volume(geom0, j, mid) < Vtarget:
                            lo = mid
                        else:
                            hi = mid
                    l_new = 0.5 * (lo + hi)
                l_new = round(l_new, 2)
                for imem in group:
                    fowt.members[imem].l_fill = np.asarray(
                        np.atleast_1d(fowt.members[imem].l_fill), float)
                    fowt.members[imem].l_fill[j] = l_new
                sumFz, heave, _ = self._heave_imbalance(fowt)
                _LOG.info(" member %s section %d: l_fill -> %.2f m, "
                          "heave %.3f m", geom0.name, j, l_new, heave)
                if abs(heave) < heave_tol:
                    return heave
                dmass = sumFz / fowt.g
        return heave

    def adjustBallastDensity(self, fowt, display=0):
        """Uniform ballast-density shift to zero the unloaded heave —
        closed form (reference: raft_model.py:1569-1624)."""
        from raft_tpu.models.member import member_inertia
        ref = np.array([fowt.x_ref, fowt.y_ref, 0, 0, 0, 0], float)
        pose0 = fowt_pose(fowt, ref)
        # zero fill levels wherever the fill density is zero (:1576-1583)
        for geom in fowt.members:
            lf = np.asarray(np.atleast_1d(geom.l_fill), float)
            rf = np.atleast_1d(np.asarray(geom.rho_fill, float))
            geom.l_fill = np.where(rf == 0.0, 0.0, lf)
        sumFz, heave, _ = self._heave_imbalance(fowt)
        ballast_volume = 0.0
        for imem, geom in enumerate(fowt.members):
            mi = member_inertia(geom, pose0["members"][imem],
                               rPRP=ref[:3])
            ballast_volume += float(np.sum(np.asarray(mi["vfill"])))
        if ballast_volume <= 0:
            raise errors.ModelConfigError(
                "adjustBallastDensity needs a platform with ballast volume")
        delta_rho_fill = sumFz / fowt.g / ballast_volume
        for geom in fowt.members:
            lf = np.atleast_1d(np.asarray(geom.l_fill, float))
            rf = np.asarray(np.atleast_1d(np.asarray(geom.rho_fill, float)))
            geom.rho_fill = np.where(lf > 0.0, rf + delta_rho_fill, rf)
        if display:
            with temp_verbosity(max(int(display), 1)):
                _, heave_new, _ = self._heave_imbalance(fowt)
                _LOG.info(" ballast density shifted %+.3f kg/m3; "
                          "heave %.3f -> %.3f m", delta_rho_fill, heave,
                          heave_new)
        return delta_rho_fill

    def make_service(self, config=None, coarse_stride: int = 2,
                     **config_kw):
        """An always-on sweep service over this model's (single) FOWT —
        the serving-loop entry point of ROADMAP item 1.

        Builds a :class:`raft_tpu.serve.SweepService` whose warm batch
        runner closes over the device-resident FOWT state, handing the
        service a frequency-decimated sibling (every
        ``coarse_stride``-th bin) as the ``coarse`` degradation rung.
        Keyword arguments construct the :class:`ServeConfig` when
        ``config`` is not given.  The caller starts/stops it::

            with model.make_service(batch_cases=8) as svc:
                ticket = svc.submit(Hs, Tp, heading_rad)
                result = ticket.result()

        Farm models (``nFOWT > 1``) are not servable — the batched
        case solver is single-FOWT (see parallel/sweep.py)."""
        from raft_tpu.models.fowt import build_fowt
        from raft_tpu.serve import ServeConfig, SweepService

        if self.nFOWT != 1:
            raise errors.ModelConfigError(
                "make_service needs a single-FOWT model",
                nFOWT=self.nFOWT)
        degraded = None
        if coarse_stride and int(coarse_stride) > 1:
            w_coarse = np.asarray(self.w)[::int(coarse_stride)]
            degraded = {"coarse": build_fowt(
                self.design, w_coarse, depth=self.depth)}
        return SweepService(self.fowtList[0],
                            config or ServeConfig(**config_kw),
                            degraded_fowts=degraded)

    def sweep_farm(self, cases=None, mesh=None, **kw):
        """Batched farm sweep: every turbine x every case of this array
        model in ONE compiled program (:func:`raft_tpu.parallel.sweep.
        sweep_farm`), wake-coupled through the device-resident Gaussian
        wake equilibrium.

        ``cases``: optional dict of per-case arrays (``Hs``, ``Tp``,
        ``beta`` [rad], ``U_inf``, ``wind_dir`` [deg]); default = this
        design's ``cases`` table (wave_height/wave_period/wave_heading/
        wind_speed/wind_heading columns).  ``mesh`` defaults to the
        model's ambient mesh.  Remaining ``kw`` passes through to the
        farm solver (``k_w``, ``aero``, ``nIter``, ...).

        The batched program replicates ``fowtList[0]`` at every layout
        position — a HOMOGENEOUS farm.  Heterogeneous arrays (mixed
        platform/turbine IDs, per-turbine heading_adjust) keep their
        per-turbine geometry only on the serial ``analyzeCases`` path; a
        warning is emitted when this approximation is in play.  Array
        mooring enters at the statics boundary: when ``solveStatics``
        has populated ``_K_array``, its per-turbine 6x6 diagonal blocks
        are added to the base platform's own-mooring stiffness (the
        turbine-coupling OFF-diagonal blocks are dropped — the batched
        lanes are independent solves; docs/performance.md Layer 8).

        Returns the :func:`~raft_tpu.parallel.sweep.sweep_farm` output
        dict of (n_turbines, ncases, ...) arrays, also stored as
        ``self.results["farm"]`` summary facts."""
        import warnings

        from raft_tpu.models import mooring as mr
        from raft_tpu.parallel import sweep as _sweep

        fowt = self.fowtList[0]
        n = self.nFOWT
        arr = self.design.get("array")
        if arr:
            rows = [dict(zip(arr["keys"], r)) for r in arr["data"]]
            hetero = {(r.get("turbineID", 1), r.get("platformID", 1),
                       r.get("mooringID", 1),
                       float(r.get("heading_adjust", 0.0)))
                      for r in rows}
            if len(hetero) > 1:
                warnings.warn(
                    "sweep_farm replicates the first FOWT at every "
                    "layout position — this array mixes platform/"
                    "turbine/mooring IDs or heading adjustments, which "
                    "only the serial analyzeCases path preserves",
                    stacklevel=2)
        xy = np.array([[f.x_ref, f.y_ref] for f in self.fowtList])

        # mooring stiffness at the statics boundary: own mooring at the
        # BASE reference position (translation-invariant under a move of
        # platform + anchors together) plus the array-mooring diagonal
        # block when solveStatics has solved the shared-line network
        r6_ref = np.array([fowt.x_ref, fowt.y_ref, 0, 0, 0, 0], float)
        C_base = (np.asarray(mr.coupled_stiffness_rotvec(fowt.mooring,
                                                         r6_ref))
                  if fowt.mooring is not None else np.zeros((6, 6)))
        C_moor_t = np.broadcast_to(C_base, (n, 6, 6)).copy()
        if self._K_array is not None:
            Kb = np.asarray(self._K_array).reshape(n, 6, n, 6)
            for i in range(n):
                C_moor_t[i] += Kb[i, :, i, :]
        elif self.arr_ms is not None:
            warnings.warn(
                "array_mooring present but statics not solved — run "
                "solveStatics first so sweep_farm can include the "
                "shared-line stiffness blocks", stacklevel=2)

        if cases is None:
            ctab = self.design.get("cases")
            if not ctab:
                raise errors.ModelConfigError(
                    "sweep_farm needs a cases= dict or a design 'cases' "
                    "table")
            rows = [dict(zip(ctab["keys"], r)) for r in ctab["data"]]
            def _ws(r):
                v = r.get("wind_speed", 10.0)
                return float(np.max(v)) if np.ndim(v) > 0 else float(v)
            def _wd(r):
                v = r.get("wind_heading", 0.0)
                return float(np.mean(v)) if np.ndim(v) > 0 else float(v)
            cases = {
                "Hs": [float(r.get("wave_height", 0.0)) for r in rows],
                "Tp": [float(r.get("wave_period", 10.0)) for r in rows],
                "beta": [np.deg2rad(float(r.get("wave_heading", 0.0)))
                         for r in rows],
                "U_inf": [_ws(r) for r in rows],
                "wind_dir": [_wd(r) for r in rows]}
        mesh = self.mesh if mesh is None else mesh
        kw.setdefault("nIter", self.nIter)
        kw.setdefault("XiStart", self.XiStart)
        out = _sweep.sweep_farm(
            fowt, xy, cases["Hs"], cases["Tp"], cases["beta"],
            cases["U_inf"], cases.get("wind_dir"), mesh=mesh,
            C_moor_t=C_moor_t, **kw)
        self.results["farm"] = {
            "n_turbines": n, "ncases": int(np.asarray(cases["Hs"]).size),
            "std": np.asarray(out["std"]),
            "U_wake": np.asarray(out["U_wake"]),
            "aero_power": np.asarray(out["aero_power"]),
            "wake_iters": np.asarray(out["wake_iters"])}
        return out

    def analyzeCases(self, display=0, RAO_plot=False, resume=False,
                     warm_statics=None):
        """Statics + dynamics + output statistics per load case.  Records
        nested spans (statics/dynamics/QTF/outputs phases), solver-health
        metrics, and a :class:`raft_tpu.obs.RunManifest` — kept on
        ``self.last_manifest`` and written to ``obs.out_dir()`` (the
        ``RAFT_TPU_OBS_DIR`` env var) when configured.

        Fault tolerance (docs/robustness.md): typed solver failures walk
        the degradation ladder; a case the ladder cannot save is
        quarantined — a structured record lands in ``self.failed_cases``,
        the manifest, and the ledger ``extra["failed_cases"]`` while the
        remaining cases still run.  Completed cases are journaled (keyed
        by the model content digest) so ``resume=True`` after a crash or
        preemption re-runs only the missing/failed cases.  Set
        ``RAFT_TPU_RECOVERY=0`` to restore fail-fast behavior.

        ``warm_statics`` (default: the ``RAFT_TPU_STATICS_WARM`` env
        knob, off) seeds each case's statics Newton from the previous
        case's converged pose — fewer iterations on DLC-shaped case
        tables — with the serve-tier guard: a seeded solve that does
        not converge triggers a counted cold re-solve.  Opt-in because
        seeding shifts iteration counts (and poses at solver-tolerance
        level), which the golden ledgers pin exactly."""
        obs.install_jax_hooks()
        obs.device.jit_cache_delta(scope="analyzeCases")   # baseline
        from raft_tpu.parallel import partition
        nCases = len(self.design["cases"]["data"])
        manifest = obs.RunManifest.begin(kind="analyzeCases", config={
            "nCases": nCases, "nFOWT": self.nFOWT, "nw": self.nw,
            "nDOF": self.nDOF, "nIter": self.nIter,
            "depth": self.depth,
            "mesh": partition.mesh_facts(self.mesh)})
        # run-scoped process identity: a scrape during this run carries
        # pid/hostname/run_id on the build-info series
        obs.record_build_info(run_id=manifest.run_id)
        self.last_manifest = manifest
        self._case_records = {}
        self._dyn_cost_recorded = False
        #: structured quarantine records of this run's unrecoverable cases
        self.failed_cases = []
        self._recovery_attempts = []
        self._resumed_cases = []
        #: statics warm-start state (satellite of ROADMAP item 5): the
        #: previous case's converged pose seeds the next case's Newton
        self._statics_warm = bool(_config.statics_warm()
                                  if warm_statics is None
                                  else warm_statics)
        self._statics_seed = None
        self._statics_warm_counts = {}
        transfers0 = obs.transfers.snapshot()
        status = "failed"
        try:
            with temp_verbosity(display), \
                    obs.span("analyzeCases", nCases=nCases,
                             nFOWT=self.nFOWT):
                self._analyze_cases_impl(nCases, display, resume=resume)
            status = "ok"
        finally:
            # a later direct solveDynamics call must not write its QTF
            # snapshot under the last case's tag
            self._iCase = None
            ledger = None
            # host-transfer accounting for THIS run (per-phase pull
            # counts/bytes through the sanctioned exit points), folded
            # into the manifest and — on success — the ledger extra
            xfers = obs.transfers.delta(transfers0,
                                        obs.transfers.snapshot())
            xfers["per_case"] = {
                ph: round(rec["events"] / max(nCases, 1), 3)
                for ph, rec in xfers["phases"].items()}
            manifest.extra["host_transfers"] = xfers
            manifest.extra["failed_cases"] = list(self.failed_cases)
            # solve-backend + precision-ladder facts of the most recent
            # dispatch (trace time): which kernel solved the impedance
            # systems and at what widths (RAFT_TPU_PRECISION)
            from raft_tpu.ops import linalg as _linalg
            manifest.extra["solver"] = _linalg.last_dispatch()
            if self._recovery_attempts:
                manifest.extra["recovery"] = {
                    "attempts": [a.to_dict()
                                 for a in self._recovery_attempts]}
            if self._resumed_cases:
                manifest.extra["resumed_cases"] = list(self._resumed_cases)
            if self._statics_warm:
                manifest.extra["statics_warm"] = {
                    "seeded": self._statics_warm_counts.get("seeded", 0),
                    "rejected": self._statics_warm_counts.get(
                        "rejected", 0)}
            self._statics_warm = False
            self._statics_seed = None
            if status == "ok":
                obs.device.collect(manifest, scope="analyzeCases")
                ledger = obs.ledger_from_model(
                    self, run_id=manifest.run_id)
                ledger["extra"] = {"host_transfers": xfers,
                                   "failed_cases": list(self.failed_cases)}
                self.last_ledger = ledger
            # drain pending probe callbacks (unordered jax.debug
            # effects) BEFORE the flight recorder closes — on async
            # backends the final case's samples may still be in flight
            try:
                jax.effects_barrier()
            except Exception:  # pragma: no cover  # raftlint: disable=RTL004
                pass
            with temp_verbosity(display):
                paths = obs.finish_run(manifest, status=status,
                                       ledger=ledger)
                if paths["manifest"]:
                    _LOG.info("run manifest: %s  trace: %s  ledger: %s",
                              paths["manifest"], paths["trace"],
                              paths["ledger"])
        return self.results

    # ---- cross-case carry state (resume/retry bookkeeping) ----------

    def _snapshot_carry(self) -> dict:
        """Copy of the state one case hands the next: the stale-heading
        hub-transfer quirk, any pending mean-drift forcing, and the
        array free-point warm start.  Restored before a ladder retry of
        statics (so the retry sees the same stale heading the first
        attempt did) and journaled after each case (so a resumed run
        reproduces a continuous run)."""
        return {
            "stored_heading": [
                None if st.get("_stored_heading") is None
                else list(st["_stored_heading"]) for st in self._state],
            "F_meandrift": [
                None if "F_meandrift" not in st
                else np.array(st["F_meandrift"], float)
                for st in self._state],
            "arr_xf": (None if self._arr_xf is None
                       else np.array(self._arr_xf, float)),
        }

    def _restore_carry(self, carry: dict):
        for st, heads, fmd in zip(self._state, carry["stored_heading"],
                                  carry["F_meandrift"]):
            if heads is None:
                st.pop("_stored_heading", None)
            else:
                st["_stored_heading"] = list(heads)
            if fmd is None:
                st.pop("F_meandrift", None)
            else:
                st["F_meandrift"] = np.array(fmd, float)
        self._arr_xf = (None if carry["arr_xf"] is None
                        else np.array(carry["arr_xf"], float))

    def _case_journal(self):
        """Journal for this model's case table, or None when journaling
        is disabled (``RAFT_TPU_JOURNAL=0``)."""
        if not recovery.journal_enabled():
            return None
        try:
            return recovery.CaseJournal.for_model(self)
        # an unwritable/corrupt journal dir must never take down
        # analyzeCases — journaling is an optional resilience feature
        except Exception as e:  # pragma: no cover  # raftlint: disable=RTL004
            _LOG.warning("case journal unavailable: %s", e)
            return None

    def _analyze_cases_impl(self, nCases, display, resume=False):
        self.results["properties"] = {}
        self.results["case_metrics"] = {}
        self.results["mean_offsets"] = []
        journal = self._case_journal()
        quarantine = recovery.enabled()
        last_err = None

        for iCase in range(nCases):
            case = dict(zip(self.design["cases"]["keys"],
                            self.design["cases"]["data"][iCase]))
            case["iCase"] = iCase
            self._iCase = iCase
            if resume and journal is not None:
                entry = journal.load_case(iCase)
                if entry is not None:
                    self._resume_case(iCase, entry)
                    continue
            self.results["case_metrics"][iCase] = {}
            carry0 = self._snapshot_carry()
            # per-case progress on the flight recorder: a tailed (or
            # killed) run shows exactly how far it got, as it happens
            obs.events.emit("case_start", case=iCase, n_cases=nCases)
            t_case = time.perf_counter()
            ok = False
            try:
                with faults.context(case=iCase):
                    self._run_one_case(iCase, case, display, carry0)
                ok = True
            except errors.RECOVERABLE as e:
                if not quarantine:
                    raise
                last_err = e
                self._quarantine_case(iCase, e)
            finally:
                obs.events.emit(
                    "case_end", case=iCase, n_cases=nCases, ok=ok,
                    s=round(time.perf_counter() - t_case, 3))
                # keep the mean-offset list aligned with the case index
                # (a failed case may have appended 0 or 1 entries)
                offs = self.results["mean_offsets"]
                del offs[iCase + 1:]
                while len(offs) < iCase + 1:
                    offs.append(np.full(self.nDOF, np.nan))
            if ok and journal is not None:
                journal.store_case(iCase, {
                    "case_metrics": self.results["case_metrics"][iCase],
                    "mean_offset": np.array(
                        self.results["mean_offsets"][iCase], float),
                    "case_record": self._case_records.get(str(iCase), {}),
                    "carry": self._snapshot_carry(),
                })
        if self.failed_cases and len(self.failed_cases) == nCases:
            # nothing survived: surface the failure instead of returning
            # an all-quarantined result set
            raise last_err
        return self.results

    def _run_one_case(self, iCase, case, display, carry0):
        """One load case end to end: statics and dynamics through the
        degradation ladder, optional mean-drift statics re-solve, output
        statistics, and the (guarded) array tension statistics."""

        def statics_fn():
            # a retry must see the same cross-case carry the first
            # attempt did (the stale-heading quirk advances inside
            # _case_constants)
            self._restore_carry(carry0)
            return self.solveStatics(case, display=display)

        recovery.run_ladder(
            "statics", str(iCase), statics_fn, recovery.statics_ladder(),
            recorder=self._recovery_attempts.append)
        recovery.run_ladder(
            "dynamics", str(iCase),
            lambda: self.solveDynamics(case, display=display),
            recovery.dynamics_ladder(),
            recorder=self._recovery_attempts.append)
        # re-solve the operating point with mean wave drift included,
        # then clear it so it can't leak into the next case (reference:
        # raft_model.py:296-303)
        if any(f.potSecOrder > 0 for f in self.fowtList):
            self.results["mean_offsets"].pop()   # superseded by re-solve
            recovery.run_ladder(
                "statics", str(iCase),
                lambda: self.solveStatics(case, display=display),
                recovery.statics_ladder(),
                recorder=self._recovery_attempts.append)
            for state in self._state:
                state.pop("F_meandrift", None)
        for i, fowt in enumerate(self.fowtList):
            self.results["case_metrics"][iCase][i] = {}
            with obs.span("saveTurbineOutputs", fowt=i, case=str(iCase)):
                self.saveTurbineOutputs(
                    self.results["case_metrics"][iCase][i], i, case)
            if display > 0:
                self._print_stats_table(iCase, i)

        if self.arr_ms is not None:
            self.results["case_metrics"][iCase]["array_mooring"] = \
                self._array_tension_stats(iCase)

    def _quarantine_case(self, iCase, err: errors.RaftError):
        """Record an unrecoverable case and keep the run alive: a
        structured failure record replaces the case metrics and is
        surfaced through the manifest and ledger extras."""
        rec = {"case": int(iCase), **err.context()}
        self.failed_cases.append(rec)
        self.results["case_metrics"][iCase] = {"failed": rec}
        self._case_records.pop(str(iCase), None)
        # a failed case's mean-offset slot is ALWAYS the NaN marker —
        # a case that passed statics but died in dynamics must not
        # leave its partial equilibrium looking like a converged result
        offs = self.results["mean_offsets"]
        if len(offs) > iCase:
            offs[iCase] = np.full(self.nDOF, np.nan)
        # a completed case never hands F_meandrift to its successor (the
        # clean flow pops it after the mean-drift statics re-solve) — a
        # case quarantined mid-dynamics must not either, or the next
        # case's statics would see the failed case's drift forcing and
        # converge to a different equilibrium than a clean run.  The
        # advanced _stored_heading is deliberately KEPT: the clean flow
        # advances it in _case_constants regardless of how the case ends.
        for state in self._state:
            state.pop("F_meandrift", None)
        obs.counter(
            "raft_tpu_cases_failed_total",
            "load cases quarantined by analyzeCases after the "
            "degradation ladder was exhausted, by phase").inc(
            1.0, phase=rec.get("phase", "unknown"))
        obs.events.emit(
            "quarantine", case=int(iCase),
            phase=rec.get("phase", "unknown"),
            error=rec.get("error", type(err).__name__))
        cur = obs.current_span()
        if cur is not None:
            cur.set(failed_cases=len(self.failed_cases))
        _LOG.error("case %d quarantined: %s", iCase, err)

    def _resume_case(self, iCase, entry):
        """Restore one journaled case: results, ledger record, and the
        cross-case carry — the solve phases are skipped entirely (no
        solveStatics/solveDynamics spans for this case)."""
        with obs.span("case_resumed", case=str(iCase)):
            self.results["case_metrics"][iCase] = entry["case_metrics"]
            offs = self.results["mean_offsets"]
            del offs[iCase:]
            while len(offs) < iCase:
                offs.append(np.full(self.nDOF, np.nan))
            offs.append(np.array(entry["mean_offset"], float))
            if entry.get("case_record"):
                self._case_records[str(iCase)] = entry["case_record"]
            self._restore_carry(entry["carry"])
        self._resumed_cases.append(int(iCase))
        obs.events.emit("case_end", case=int(iCase), ok=True,
                        resumed=True, s=0.0,
                        n_cases=len(self.design["cases"]["data"]))
        obs.counter(
            "raft_tpu_cases_resumed_total",
            "load cases restored from the per-case journal instead of "
            "re-solved").inc(1.0)
        _LOG.info("case %d restored from journal (resume)", iCase)

    def _array_tension_stats(self, iCase) -> dict:
        """Array-level mooring tension statistics through the coupled
        tension Jacobian (reference: raft_model.py:345-388), degraded to
        NaN-filled channels when the Jacobian is singular/non-finite —
        a bad tension linearization must not take down the case loop."""
        from raft_tpu.models import mooring_array as ma
        dw = self.w[1] - self.w[0]
        nT = 2 * len(self.arr_ms.iA)
        Xb = np.stack([self._state[i]["r6"]
                       for i in range(self.nFOWT)])
        xf = self._arr_xf
        try:
            J = np.asarray(ma.tension_jacobian(self.arr_ms, Xb, xf))
            T0 = np.asarray(ma.tensions(self.arr_ms, Xb, xf))
            if not (np.all(np.isfinite(J)) and np.all(np.isfinite(T0))):
                raise errors.MooringSingular(
                    "array tension Jacobian/tensions non-finite",
                    case=iCase)
            T_amps = np.einsum("tj,hjw->htw", J, self.Xi)
            nT = len(T0)
            TRMS = np.array([float(get_rms(T_amps[:, iT, :]))
                             for iT in range(nT)])
            return {
                "Tmoor_avg": T0,
                "Tmoor_std": TRMS,
                "Tmoor_max": T0 + 3 * TRMS,
                "Tmoor_min": T0 - 3 * TRMS,
                "Tmoor_PSD": np.stack(
                    [np.asarray(get_psd(T_amps[:, iT, :], dw,
                                        source_axis=0))
                     for iT in range(nT)]),
            }
        except (errors.MooringSingular, np.linalg.LinAlgError,
                FloatingPointError) as e:
            _LOG.warning(
                "case %d: array mooring tension statistics degraded to "
                "NaN (%s) — singular/non-finite tension Jacobian", iCase, e)
            obs.counter(
                "raft_tpu_tension_stats_degraded_total",
                "array tension-statistics blocks degraded to NaN "
                "channels by a singular tension Jacobian").inc(1.0)
            nan_t = np.full(nT, np.nan)
            return {
                "Tmoor_avg": nan_t, "Tmoor_std": nan_t.copy(),
                "Tmoor_max": nan_t.copy(), "Tmoor_min": nan_t.copy(),
                "Tmoor_PSD": np.full((nT, self.nw), np.nan),
            }

    # ------------------------------------------------------------------
    # outputs
    # ------------------------------------------------------------------

    def saveTurbineOutputs(self, results, ifowt, case):
        """Per-case response statistics (reference: raft_fowt.py:1821-2109)."""
        fowt = self.fowtList[ifowt]
        state = self._state[ifowt]
        Xi = state["Xi"]          # (nWaves+1, 6, nw)
        Xi0 = state["Xi0"]
        dw = self.w[1] - self.w[0]

        chans = ["surge", "sway", "heave", "roll", "pitch", "yaw"]
        for idof, ch in enumerate(chans):
            sig = Xi[:, idof, :]
            mean = Xi0[idof]
            if idof >= 3:
                sig = sig * RAD2DEG
                mean = mean * RAD2DEG
            std = float(get_rms(sig))
            results[f"{ch}_avg"] = mean
            results[f"{ch}_std"] = std
            results[f"{ch}_max"] = mean + 3 * std
            results[f"{ch}_min"] = mean - 3 * std
            results[f"{ch}_PSD"] = np.asarray(get_psd(sig, dw, source_axis=0))
            results[f"{ch}_RA"] = np.asarray(sig)

        # first-heading RAO magnitude/phase summaries per DOF — the
        # compact response fingerprint the result ledger digests
        # (rotational DOFs kept in rad/m, matching get_rao's output)
        RAO0 = np.asarray(get_rao(Xi[0], state["seastate"]["zeta"][0]))
        mag = np.abs(RAO0)
        for idof, ch in enumerate(chans):
            ipk = int(np.argmax(mag[idof]))
            results[f"{ch}_RAO_mag_max"] = float(mag[idof, ipk])
            results[f"{ch}_RAO_mag_mean"] = float(mag[idof].mean())
            # phase of a symmetry-zero channel is fp noise — pin it
            results[f"{ch}_RAO_phase_peak"] = (
                float(np.angle(RAO0[idof, ipk]))
                if mag[idof, ipk] > 1e-12 else 0.0)
            results[f"{ch}_RAO_w_peak"] = float(self.w[ipk])

        # mooring tensions through the tension Jacobian (reference :1877-1898)
        moor = fowt.mooring
        if moor is not None:
            r6 = state["r6"]
            # MoorPy-parity FD Jacobian (see coupled_stiffness_fd): the
            # reference's Tmoor stats use getCoupledStiffness(tensions=True)
            cur = state.get("moor_current")
            J = np.asarray(mr.tension_jacobian_fd(moor, r6, current=cur))
            T0 = np.asarray(mr.tensions(moor, r6, current=cur))
            nT = len(T0)
            T_amps = np.einsum("tj,hjw->htw", J, Xi)
            results["Tmoor_avg"] = T0
            TRMS = np.array([float(get_rms(T_amps[:, iT, :])) for iT in range(nT)])
            results["Tmoor_std"] = TRMS
            results["Tmoor_max"] = T0 + 3 * TRMS
            results["Tmoor_min"] = T0 - 3 * TRMS
            results["Tmoor_PSD"] = np.stack(
                [np.asarray(get_psd(T_amps[:, iT, :], dw, source_axis=0))
                 for iT in range(nT)])

        # nacelle acceleration + tower base bending (reference :1900-1971)
        nrot = fowt.nrotors
        XiHub = np.zeros((Xi.shape[0], nrot, self.nw),
                         dtype=complex)  # raftlint: disable=RTL003 host-side result mirror stays complex128
        for key in ("AxRNA", "Mbase"):
            results[f"{key}_avg"] = np.zeros(nrot)
            results[f"{key}_std"] = np.zeros(nrot)
            results[f"{key}_max"] = np.zeros(nrot)
            results[f"{key}_min"] = np.zeros(nrot)
            results[f"{key}_PSD"] = np.zeros((self.nw, nrot))

        stat = state["statics"]
        tc = state.get("turbine")
        for ir, rot in enumerate(fowt.rotors):
            XiHub[:, ir, :] = Xi[:, 0, :] + rot.r_rel[2] * Xi[:, 4, :]
            a_std = float(get_rms(XiHub[:, ir, :] * self.w**2))
            results["AxRNA_std"][ir] = a_std
            results["AxRNA_PSD"][:, ir] = np.asarray(
                get_psd(XiHub[:, ir, :] * self.w**2, dw, source_axis=0))
            results["AxRNA_avg"][ir] = abs(np.sin(Xi0[4]) * 9.81)
            results["AxRNA_max"][ir] = results["AxRNA_avg"][ir] + 3 * a_std
            results["AxRNA_min"][ir] = results["AxRNA_avg"][ir] - 3 * a_std

            # tower-base bending moment
            mtow = float(stat["mtower"][ir]) if stat["mtower"] else 0.0
            if mtow > 0:
                rCGt = np.asarray(stat["rCG_tow"][ir])
                m_turb = mtow + rot.mRNA
                zCGt = (rCGt[2] * mtow + rot.r_rel[2] * rot.mRNA) / m_turb
                tower_geom = fowt.members[fowt.nplatmems + ir]
                tower_pose = state["pose_eq"]["members"][fowt.nplatmems + ir]
                zBase = float(tower_pose["rA"][2])
                hArm = zCGt - zBase
                aCG = -self.w**2 * (Xi[:, 0, :] + zCGt * Xi[:, 4, :])
                tower_M = np.asarray(member_inertia(tower_geom, tower_pose,
                                                    rPRP=state["r6"][:3])["M_struc"])
                ICGt = (np.asarray(translate_matrix_6to6(
                    jnp.asarray(tower_M), jnp.array([0, 0, -zCGt])))[4, 4]
                    + rot.mRNA * (rot.r_rel[2] - zCGt) ** 2 + rot.IrRNA)
                M_I = -m_turb * aCG * hArm - ICGt * (-self.w**2 * Xi[:, 4, :])
                M_w = m_turb * fowt.g * hArm * Xi[:, 4, :]
                if tc is not None:
                    A00 = np.asarray(tc["A_aero"][0, 0, :, ir])
                    B00 = np.asarray(tc["B_aero"][0, 0, :, ir])
                else:
                    A00 = B00 = np.zeros(self.nw)
                M_X = -(-self.w**2 * A00 + 1j * self.w * B00) \
                    * (rot.r_rel[2] - zBase) ** 2 * Xi[:, 4, :]
                dyn = M_I + M_w + M_X
                f_aero0_ir = np.asarray(tc["f_aero0"][:, ir]) if tc is not None else np.zeros(6)
                results["Mbase_avg"][ir] = (
                    m_turb * fowt.g * hArm * np.sin(Xi0[4])
                    + np.asarray(transform_force(jnp.asarray(f_aero0_ir),
                                                 offset=jnp.array([0, 0, -hArm])))[4])
                results["Mbase_std"][ir] = float(get_rms(dyn))
                results["Mbase_PSD"][:, ir] = np.asarray(get_psd(dyn, dw, source_axis=0))
                results["Mbase_max"][ir] = results["Mbase_avg"][ir] + 3 * results["Mbase_std"][ir]
                results["Mbase_min"][ir] = results["Mbase_avg"][ir] - 3 * results["Mbase_std"][ir]

        results["wave_PSD"] = np.asarray(
            get_psd(state["seastate"]["zeta"], dw, source_axis=0))

        # cavitation check results for submerged rotors (reference:
        # raft_fowt.py:2047-2049)
        if "cavitation" in state:
            results["cavitation"] = state["cavitation"]

        # rotor control channels (reference :1976-2045)
        for key in ("omega", "torque", "power", "bPitch"):
            results[f"{key}_avg"] = np.zeros(nrot)
            results[f"{key}_std"] = np.zeros(nrot)
            if key != "power":
                results[f"{key}_PSD"] = np.zeros((self.nw, nrot))
        results["omega_max"] = np.zeros(nrot)
        results["omega_min"] = np.zeros(nrot)

        for ir, rot in enumerate(fowt.rotors):
            current = rot.hubHt < 0
            speed = float(get_from_dict(case, "current_speed", shape=0, default=1.0)) \
                if current else float(get_from_dict(case, "wind_speed", shape=0, default=10.0))
            if rot.aeroServoMod > 1 and speed > 0.0:
                # the reference's control transfer function comes from the
                # STATICS-TIME calcAero (zero pose) — the equilibrium
                # update loop is dead code (see solveStatics note)
                X0r = np.array([fowt.x_ref, fowt.y_ref, 0, 0, 0, 0], float)
                aero = calc_aero(rot, self.w, case, r6=X0r, current=current)
                C = np.asarray(aero["C"])
                V_w = np.asarray(aero["V_w"])
                kp_beta = -np.interp(speed, rot.Uhub_ops, rot.kp_0)
                ki_beta = -np.interp(speed, rot.Uhub_ops, rot.ki_0)
                kp_tau = rot.kp_tau * (kp_beta == 0)
                ki_tau = rot.ki_tau * (ki_beta == 0)
                nh = Xi.shape[0]
                phi_w = np.zeros((nh, self.nw),
                                 dtype=complex)  # raftlint: disable=RTL003 host-side result mirror stays complex128
                for ih in range(nh - 1):
                    phi_w[ih] = C * XiHub[ih, ir, :]
                phi_w[-1] = C * (XiHub[-1, ir, :] - V_w / (1j * self.w))
                omega_w = 1j * self.w * phi_w
                torque_w = (1j * self.w * kp_tau + ki_tau) * phi_w
                bPitch_w = (1j * self.w * kp_beta + ki_beta) * phi_w

                results["omega_avg"][ir] = float(aero["op"]["Omega_rpm"])
                results["omega_std"][ir] = float(get_rms(omega_w)) / 0.1047
                results["omega_max"][ir] = results["omega_avg"][ir] + 2 * results["omega_std"][ir]
                results["omega_min"][ir] = results["omega_avg"][ir] - 2 * results["omega_std"][ir]
                results["omega_PSD"][:, ir] = (1 / 0.1047) ** 2 * np.asarray(
                    get_psd(omega_w, dw, source_axis=0))
                results["torque_avg"][ir] = float(aero["loads"]["Q"]) / rot.Ng
                results["torque_std"][ir] = float(get_rms(torque_w))
                results["torque_PSD"][:, ir] = np.asarray(get_psd(torque_w, dw, source_axis=0))
                results["power_avg"][ir] = float(aero["loads"]["P"])
                results["bPitch_avg"][ir] = float(aero["op"]["pitch_deg"])
                results["bPitch_std"][ir] = float(get_rms(bPitch_w)) * RAD2DEG
                results["bPitch_PSD"][:, ir] = RAD2DEG**2 * np.asarray(
                    get_psd(bPitch_w, dw, source_axis=0))
                results["wind_PSD"] = np.asarray(get_psd(V_w, dw))

    def preprocess_BEM(self, dw=0.05, wMax=3.0, mesh_dir=None,
                       headings=None, dz=None, da=None):
        """Re-run the native BEM core at a custom frequency resolution and
        write WAMIT-format .1/.3 coefficient files plus the panel mesh
        (reference: raft_model.py:1310-1330 preprocess_HAMS, which re-runs
        pyHAMS to export coefficients for OpenFAST).  One output directory
        per FOWT (``mesh_dir`` gets a ``_WT{i}`` suffix for i>0).
        Returns the list of per-FOWT BEMData."""
        from raft_tpu.io.bem_native import available, load_error, solve_bem_fowt

        if not available():
            # IS a RuntimeError — pre-taxonomy catchers keep working
            raise errors.KernelFailure(
                f"native BEM core unavailable: {load_error()}",
                kernel="bem_native")
        w_bem = np.arange(dw, wMax + 0.5 * dw, dw)
        out = []
        for i, fowt in enumerate(self.fowtList):
            d = mesh_dir if (mesh_dir is None or i == 0) \
                else f"{mesh_dir}_WT{i}"
            out.append(solve_bem_fowt(fowt, headings=headings, dz=dz, da=da,
                                      w_bem=w_bem, mesh_dir=d,
                                      max_freqs=len(w_bem)))
        return out

    def calcOutputs(self):
        """Fill results['properties'] (reference: raft_model.py:1150-1189)."""
        if self.nFOWT > 1:
            # the reference only fills properties for single-FOWT models
            # (raft_model.py:1153)
            return self.results
        fowt = self.fowtList[0]
        state = self._state[0]
        stat = state["statics"]
        props = self.results.setdefault("properties", {})
        props["tower mass"] = np.asarray([np.asarray(m) for m in stat["mtower"]])
        props["tower CG"] = np.asarray([np.asarray(c) for c in stat["rCG_tow"]])
        props["substructure mass"] = float(stat["m_sub"])
        props["substructure CG"] = np.asarray(stat["rCG_sub"])
        props["shell mass"] = float(stat["m_shell"])
        props["total mass"] = float(stat["m"])
        props["total CG"] = np.asarray(stat["rCG"])
        # ballast masses grouped by unique fill density (reference:
        # raft_fowt.py:505-516)
        mball = np.concatenate([np.atleast_1d(np.asarray(m, float))
                                for m in stat["mballast"]]) \
            if stat["mballast"] else np.zeros(0)
        pball = np.concatenate([np.atleast_1d(np.asarray(p, float))
                                for p in stat["pballast"]]) \
            if stat["pballast"] else np.zeros(0)
        pb = []
        for p in pball:
            if p != 0 and p not in pb:
                pb.append(p)
        props["ballast densities"] = np.asarray(pb)
        props["ballast mass"] = np.asarray(
            [mball[pball == p].sum() for p in pb])
        props["roll inertia at subCG"] = float(stat["Ixx_sub"])
        props["pitch inertia at subCG"] = float(stat["Iyy_sub"])
        props["yaw inertia at subCG"] = float(stat["Izz_sub"])
        props["buoyancy (pgV)"] = fowt.rho_water * fowt.g * float(stat["V"])
        props["center of buoyancy"] = np.asarray(stat["rCB"])
        props["C hydrostatic"] = np.asarray(stat["C_hydro"])
        C_moor0 = getattr(self, "C_moor0", state["C_moor"])
        props["C system"] = np.asarray(
            stat["C_struc"] + stat["C_hydro"]) + C_moor0
        props["F_lines0"] = getattr(self, "F_moor0", state["F_moor0"])
        props["C_lines0"] = C_moor0
        hc = state.get("hydro0")
        A_morison = np.asarray(hc["A_hydro_morison"]) if hc is not None \
            else np.zeros((6, 6))
        props["A matrix"] = A_morison
        # added mass at the highest BEM frequency, matching the reference's
        # fowt.A_BEM[:,:,-1] convention (raft_model.py:1185)
        from raft_tpu.io.wamit import bem_coeffs
        A_BEM, _ = bem_coeffs(fowt.bem, self.nw)
        props["M support structure"] = np.asarray(stat["M_struc_sub"])
        props["A support structure"] = A_morison + np.asarray(A_BEM[:, :, -1])
        props["C support structure"] = np.asarray(
            stat["C_struc_sub"] + stat["C_hydro"]) + C_moor0
        return self.results

    # ------------------------------------------------------------------
    # observability: stats table, PSD export, plots
    # ------------------------------------------------------------------

    def _print_stats_table(self, iCase, ifowt):
        """Response-statistics table (reference: raft_model.py:315-341),
        emitted at INFO level through the raft_tpu logger — visible with
        ``display>0`` (a per-call ``temp_verbosity`` override) or an
        ambient ``set_verbosity(1)``."""
        m = self.results["case_metrics"][iCase][ifowt]
        fowt = self.fowtList[ifowt]
        lines = [
            f"---------------- FOWT {ifowt+1} Case {iCase+1} "
            "Statistics ----------------",
            "Response channel     Average     RMS         Maximum     "
            "Minimum",
        ]
        for ch, unit in (("surge", "m"), ("sway", "m"), ("heave", "m"),
                         ("roll", "deg"), ("pitch", "deg"), ("yaw", "deg")):
            lines.append(
                f"{(ch + ' (' + unit + ')').ljust(19)}"
                f"{m[ch + '_avg']:10.2e}  {m[ch + '_std']:10.2e}  "
                f"{m[ch + '_max']:10.2e}  {m[ch + '_min']:10.2e}")
        for ir in range(fowt.nrotors):
            lines.append(
                f"nacelle acc (m/s2) {m['AxRNA_avg'][ir]:10.2e}  "
                f"{m['AxRNA_std'][ir]:10.2e}  {m['AxRNA_max'][ir]:10.2e}  "
                f"{m['AxRNA_min'][ir]:10.2e}")
            lines.append(
                f"tower bending (Nm) {m['Mbase_avg'][ir]:10.2e}  "
                f"{m['Mbase_std'][ir]:10.2e}  {m['Mbase_max'][ir]:10.2e}  "
                f"{m['Mbase_min'][ir]:10.2e}")
            if m["omega_avg"][ir] != 0.0:
                lines.append(
                    f"rotor speed (RPM)  {m['omega_avg'][ir]:10.2e}  "
                    f"{m['omega_std'][ir]:10.2e}  "
                    f"{m['omega_max'][ir]:10.2e}  "
                    f"{m['omega_min'][ir]:10.2e}")
                lines.append(
                    f"blade pitch (deg)  {m['bPitch_avg'][ir]:10.2e}  "
                    f"{m['bPitch_std'][ir]:10.2e}")
                lines.append(f"rotor power        {m['power_avg'][ir]:10.2e}")
        lines.append(
            "-----------------------------------------------------------")
        _LOG.info("%s", "\n".join(lines))

    def saveResponses(self, out_path):
        """Per-case per-FOWT PSD text export (reference:
        raft_model.py:1231-1261)."""
        from raft_tpu.plot import save_responses
        return save_responses(self, out_path)

    def plotResponses(self, cases=None, ifowt=0):
        from raft_tpu.plot import plot_responses
        return plot_responses(self, cases=cases, ifowt=ifowt)

    def plot(self, ax=None, color=None, station_plot=None):
        """3D wireframe of the system (reference: raft_model.py:1333-1431)."""
        from raft_tpu.plot import plot_model
        return plot_model(self, ax=ax, color=color, plot2d=False,
                          station_plot=station_plot)

    def plot2d(self, ax=None, color=None, Xuvec=(1, 0, 0), Yuvec=(0, 0, 1)):
        from raft_tpu.plot import plot_model
        return plot_model(self, ax=ax, color=color, plot2d=True,
                          Xuvec=Xuvec, Yuvec=Yuvec)

    # ------------------------------------------------------------------
    # wake coupling (FLORIS-equivalent, reference: raft_model.py:1674-2022)
    # ------------------------------------------------------------------

    def powerThrustCurve(self, speeds=None, ifowt=0):
        """Cp/Ct/power/pitch tables vs wind speed from the BEM rotor
        (reference: raft_model.py:1674-1750)."""
        from raft_tpu.models.wake import power_thrust_curve
        return power_thrust_curve(self, speeds=speeds, ifowt=ifowt)

    def findWakeEquilibrium(self, case, k_w=0.05, **kw):
        """Farm wake fixed point with the built-in Gaussian-deficit model
        (reference: raft_model.py:1852-1994 florisFindEquilibrium).  The
        returned case carries per-turbine wind speeds for analyzeCases."""
        from raft_tpu.models.wake import find_wake_equilibrium
        return find_wake_equilibrium(self, case, k_w=k_w, **kw)

    def calcAEP(self, wind_rose, **kw):
        """Wind-rose AEP with wake losses (reference:
        raft_model.py:1996-2022 florisCalcAEP)."""
        from raft_tpu.models.wake import calc_aep
        return calc_aep(self, wind_rose, **kw)

    def florisCoupling(self, config, turbconfig, path):
        """Drive a FLORIS interface from this model (reference:
        raft_model.py:1753-1850); requires the optional floris package —
        see raft_tpu.models.wake.floris_coupling."""
        from raft_tpu.models.wake import floris_coupling
        return floris_coupling(self, config, turbconfig, path)

    def adjustWISDEM(self, old_wisdem_file, new_wisdem_file):
        """Write an adjusted WISDEM geometry yaml with ballast volumes
        updated from this model's trimmed fill levels (reference:
        raft_model.py:1627-1672 adjustWISDEM — same member matching rule:
        a WISDEM member maps to the RAFT member whose bottom-node z
        matches its joint1 z to 5 significant characters and whose first
        outer diameter matches; only the first ballast entry's volume is
        updated, assuming a constant-diameter member).  Deviation: the
        reference's member loop breaks unconditionally after the FIRST
        RAFT member (raft_model.py:1665), so only one member could ever
        match; here every member is considered."""
        try:                        # the reference uses ruamel to preserve
            import ruamel.yaml as ry     # format; fall back to plain yaml
            reader = ry.YAML(typ="safe", pure=True)
            with open(old_wisdem_file, encoding="utf-8") as f:
                wisdem = reader.load(f)
            dump = ry.YAML()
            dump.default_flow_style = None

            def _write(data, f):
                dump.dump(data, f)
        except ImportError:
            import yaml as _yaml
            with open(old_wisdem_file, encoding="utf-8") as f:
                wisdem = _yaml.safe_load(f)

            def _write(data, f):
                _yaml.safe_dump(data, f, sort_keys=False,
                                default_flow_style=None)

        fowt = self.fowtList[0]
        plat = wisdem["components"]["floating_platform"]
        joints = {j["name"]: j for j in plat["joints"]}
        for wm in plat["members"]:
            if not wm.get("internal_structure", {}).get("ballasts"):
                continue
            joint = joints.get(wm.get("joint1"))
            if joint is None:
                continue
            for m in fowt.members:
                rA = np.asarray(m.rA0, float)
                d0 = float(np.atleast_1d(m.d)[0]) if np.ndim(m.d) else float(m.d)
                if (str(joint["location"][2])[0:5] == str(rA[2])[0:5]
                        and wm["outer_shape"]["outer_diameter"]["values"][0]
                        == d0):
                    t0 = float(np.atleast_1d(m.t)[0])
                    area = np.pi * ((d0 - 2.0 * t0) / 2.0) ** 2
                    lf = float(np.atleast_1d(m.l_fill)[0])
                    wm["internal_structure"]["ballasts"][0]["volume"] = \
                        float(area * lf)
                    break
        with open(new_wisdem_file, "w", encoding="utf-8") as f:
            _write(wisdem, f)
        return wisdem


def run_raft(design_or_path, plots=0, ballast=False, station_plot=[]):
    """Convenience entry point (reference: raft_model.py:2024-2061).

    Farm designs (nFOWT > 1) take the reference's runRAFTFarm path
    (raft_model.py:2065-2095): analyzeUnloaded and calcOutputs are
    skipped — both are single-FOWT-only in the reference too — and the
    case analysis runs directly."""
    import yaml

    if isinstance(design_or_path, str):
        with open(design_or_path) as f:
            design = yaml.safe_load(f)
    else:
        design = design_or_path
    model = Model(design)
    if model.nFOWT > 1:
        model.analyzeCases(display=1 if plots else 0)
    else:
        model.analyzeUnloaded(ballast=1 if ballast else 0)
        model.analyzeCases(display=1 if plots else 0)
        model.calcOutputs()
    if plots:
        model.plot(station_plot=station_plot)
        model.plotResponses()
    return model
