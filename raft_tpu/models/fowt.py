"""Single-platform assembly: statics, strip-theory hydro, aero constants.

TPU-first re-design of the reference FOWT class (reference: raft/raft_fowt.py).
The reference loops `for mem in memberList: for il in range(mem.ns):` inside
every hydro method; here all members' strip nodes are CONCATENATED into one
flat node axis at build time (`NodeSet`), so every hydro quantity — added
mass, Froude-Krylov excitation, drag linearization, current loads — is one
batched jnp expression over (heading, node, frequency) with submergence
masks, ready for vmap over cases and sharding over designs.

Build-time (numpy): `build_fowt(design, w, ...)` parses the design dict into
a `FOWTModel` of MemberGeometry/RotorModel/MooringSystem plus static
per-node scalars (drag areas, volumes, coefficients; reference formulas at
raft_fowt.py:1197-1243, raft_member.py:922-953).

Pose/trace-time (jnp): `fowt_pose` evaluates member poses and stacks node
positions/orientations; the `fowt_*` kernels mirror the reference methods:

  calcStatics            -> fowt_statics            (raft_fowt.py:291-566)
  calcHydroConstants     -> fowt_hydro_constants    (raft_fowt.py:848-880)
  calcHydroExcitation    -> fowt_hydro_excitation   (raft_fowt.py:972-1149)
  calcHydroLinearization -> fowt_hydro_linearization(raft_fowt.py:1152-1266)
  calcDragExcitation     -> fowt_drag_excitation    (raft_fowt.py:1270-1293)
  calcCurrentLoads       -> fowt_current_loads      (raft_fowt.py:1297-1382)
  calcTurbineConstants   -> fowt_turbine_constants  (raft_fowt.py:773-845)
"""
from __future__ import annotations

import os
from dataclasses import dataclass, field
from typing import List, Optional

import numpy as np
import jax.numpy as jnp

from raft_tpu.models.member import (
    MemberGeometry, build_member_geometry, member_pose, member_inertia,
    member_hydrostatics,
)
from raft_tpu.models.rotor import RotorModel, build_rotor, calc_aero, rotor_pose
from raft_tpu.models import mooring as mr
from raft_tpu.ops.transforms import (
    translate_force_3to6, translate_matrix_3to6, translate_matrix_6to6,
    rotate_matrix_6, transform_force, skew,
)
from raft_tpu.ops.waves import wave_number, wave_kinematics, kinematics_from_motion
from raft_tpu.ops.spectra import jonswap, get_rms
from raft_tpu.utils.dicttools import get_from_dict


@dataclass
class NodeSet:
    """Static per-node scalars, all members concatenated (numpy, built once).

    Dynamic quantities (positions, submergence, kinematics) are computed in
    jnp from the pose.  Shapes (N,) unless noted.
    """

    member_index: np.ndarray     # which member each node belongs to
    frac: np.ndarray             # position along member axis / length
    dls: np.ndarray
    # drag areas per unit Cd (reference: raft_fowt.py:1200-1202, 1235-1238)
    a_i_q: np.ndarray
    a_i_p1: np.ndarray
    a_i_p2: np.ndarray
    a_i_end_drag: np.ndarray     # |end area| for drag
    # added-mass volumes/areas (reference: raft_member.py:925-949)
    v_side: np.ndarray           # pre-submergence-scaling side volume
    v_end: np.ndarray
    a_i: np.ndarray              # signed axial pressure area
    # coefficients interpolated to nodes
    Cd_q: np.ndarray
    Cd_p1: np.ndarray
    Cd_p2: np.ndarray
    Cd_End: np.ndarray
    Ca_p1: np.ndarray
    Ca_p2: np.ndarray
    Ca_End: np.ndarray
    circ: np.ndarray             # bool per node
    potMod: np.ndarray           # bool per node (True -> no strip hydro)
    MCF: np.ndarray = None       # bool per node: MacCamy-Fuchs member
    R: np.ndarray = None         # node radius ds/2 (circular; 0 for rect)

    @property
    def n(self):
        return len(self.frac)


@dataclass
class FOWTModel:
    """Static description of one floating wind turbine (build output)."""

    members: List[MemberGeometry]
    member_types: List[int]
    member_names: List[str]
    rotors: List[RotorModel]
    mooring: Optional[mr.MooringSystem]
    nodes: NodeSet
    w: np.ndarray
    k: np.ndarray
    depth: float
    rho_water: float
    g: float
    shearExp_water: float
    yawstiff: float
    x_ref: float
    y_ref: float
    heading_adjust: float
    nplatmems: int
    ntowers: int
    potModMaster: int
    #: platform members grouped by repeated-heading pattern (one yaml
    #: member entry -> one group), for ballast trim (reference keys the
    #: adjustment off member.headings, raft_model.py:1464-1467)
    platmem_groups: Optional[List[List[int]]] = None
    potSecOrder: int = 0
    potFirstOrder: int = 0
    bem: Optional[object] = None   # io.wamit.BEMData when potential-flow files loaded
    w1_2nd: Optional[np.ndarray] = None   # 2nd-order QTF frequency grid (potSecOrder==1)
    k1_2nd: Optional[np.ndarray] = None
    qtf_data: Optional[object] = None     # models.qtf.QTFData (potSecOrder==2)

    @property
    def potMod_any(self) -> bool:
        """True when any member is modeled with potential flow (the
        reference's self.potMod flag, raft_fowt.py:209-210)."""
        return any(m.potMod for m in self.members)

    @property
    def nw(self):
        return len(self.w)

    @property
    def nrotors(self):
        return len(self.rotors)


def build_fowt(design: dict, w, depth=600.0, x_ref=0.0, y_ref=0.0,
               heading_adjust=0.0, geometry_only=False) -> FOWTModel:
    """Parse a design dict into a FOWTModel (reference: raft_fowt.py:22-257).

    ``geometry_only`` skips the (potentially expensive) potential-flow
    coefficient load/solve and second-order setup — for callers that only
    need member geometry (e.g. the variant-sweep base build)."""
    design = dict(design)
    site = design["site"]
    rho_water = float(get_from_dict(site, "rho_water", default=1025.0))
    g = float(get_from_dict(site, "g", default=9.81))
    shearExp_water = float(get_from_dict(site, "shearExp_water", default=0.12))

    platform = design["platform"]
    potModMaster = int(get_from_dict(platform, "potModMaster", dtype=int, default=0))
    dlsMax = float(get_from_dict(platform, "dlsMax", default=5.0))

    members: List[MemberGeometry] = []
    member_types: List[int] = []
    member_names: List[str] = []
    nplatmems = 0
    platmem_groups: List[List[int]] = []
    for mi in platform["members"]:
        mi = dict(mi)
        if potModMaster in (1,):
            mi["potMod"] = False
        elif potModMaster in (2, 3):
            mi["potMod"] = True
        mi.setdefault("dlsMax", dlsMax)
        headings = get_from_dict(mi, "heading", shape=-1, default=0.0)
        platmem_groups.append(list(range(
            nplatmems, nplatmems + len(np.atleast_1d(headings)))))
        for h in (np.atleast_1d(headings)):
            members.append(build_member_geometry(mi, heading=float(h) + heading_adjust))
            member_types.append(int(mi.get("type", 2)))
            member_names.append(str(mi.get("name", "")))
            nplatmems += 1

    rotors: List[RotorModel] = []
    ntowers = 0
    if "turbine" in design and design["turbine"] is not None:
        turbine = dict(design["turbine"])
        nrotors = int(get_from_dict(turbine, "nrotors", dtype=int, shape=0, default=1))
        turbine["nrotors"] = nrotors
        turbine["rho_air"] = float(get_from_dict(site, "rho_air", shape=0, default=1.225))
        turbine["mu_air"] = float(get_from_dict(site, "mu_air", shape=0, default=1.81e-5))
        turbine["shearExp_air"] = float(get_from_dict(site, "shearExp_air", shape=0, default=0.12))
        turbine["rho_water"] = rho_water
        turbine["mu_water"] = float(get_from_dict(site, "mu_water", shape=0, default=1.0e-3))
        turbine["shearExp_water"] = shearExp_water
        tower = turbine.get("tower")
        if tower is not None:
            towers = [tower] if isinstance(tower, dict) else list(tower)
            ntowers = len(towers)
            for mem in towers:
                mem = dict(mem)
                mem.setdefault("dlsMax", dlsMax)
                members.append(build_member_geometry(mem))
                member_types.append(int(mem.get("type", 1)))
                member_names.append(str(mem.get("name", "tower")))
        nac = turbine.get("nacelle")
        if nac is not None:
            nacs = [nac] if isinstance(nac, dict) else list(nac)
            for mem in nacs:
                mem = dict(mem)
                mem.setdefault("dlsMax", dlsMax)
                members.append(build_member_geometry(mem))
                member_types.append(int(mem.get("type", 1)))
                member_names.append("nacelle")
        for ir in range(nrotors):
            rotors.append(build_rotor(turbine, w, ir))

        # fully-submerged rotors get per-element blade members for added
        # mass / buoyancy / inertial excitation (reference:
        # raft_rotor.py:369-373 creates bladeMemberList when
        # r3[2] + R_rot < 0; raft_fowt.py:384-444, 873-880 consume it).
        # Appended last so platform/tower member indexing is unchanged.
        from raft_tpu.models.rotor import blade_member_dicts
        for rot in rotors:
            if rot.hubHt + rot.R_rot < 0:
                for bm in blade_member_dicts(rot):
                    bm.setdefault("dlsMax", dlsMax)
                    members.append(build_member_geometry(bm))
                    member_types.append(3)
                    member_names.append("blade")

    moor = None
    if design.get("mooring"):
        moor = mr.parse_mooring(design["mooring"], rho=rho_water, g=g,
                                trans=(x_ref, y_ref), rot=heading_adjust)

    yawstiff = float(platform.get("yaw_stiffness", 0.0))

    w = np.asarray(w, float)
    k = np.asarray(wave_number(w, depth))

    nodes = _build_nodeset(members)

    # potential-flow coefficient files (reference: raft_fowt.py:222-227 for
    # potFirstOrder==1; :654-655 reuses the same path for potModMaster==3)
    potFirstOrder = int(get_from_dict(platform, "potFirstOrder", dtype=int, default=0))
    bem = None
    if (not geometry_only) and (potFirstOrder == 1 or potModMaster == 3):
        if "hydroPath" not in platform:
            raise ValueError("potFirstOrder==1/potModMaster==3 require "
                             "'hydroPath' in the platform input")
        from raft_tpu.io.wamit import load_bem
        bem = load_bem(platform["hydroPath"], w, rho=rho_water, g=g,
                       freq=str(platform.get("hydroFreqType", "auto")))
    # second-order hydro setup (reference: raft_fowt.py:231-252)
    potSecOrder = int(get_from_dict(platform, "potSecOrder", dtype=int, default=0))
    if geometry_only:
        potSecOrder = 0
    w1_2nd = k1_2nd = qtf_data = None
    if potSecOrder == 1:
        if "min_freq2nd" not in platform or "max_freq2nd" not in platform:
            raise ValueError("potSecOrder==1 requires min_freq2nd and "
                             "max_freq2nd in the platform input")
        f_min2 = float(platform["min_freq2nd"])
        f_max2 = float(platform["max_freq2nd"])
        f_df2 = float(platform.get("df_freq2nd", f_min2))
        w1_2nd = np.arange(f_min2, f_max2 + 0.5 * f_min2, f_df2) * 2 * np.pi
        k1_2nd = np.asarray(wave_number(w1_2nd, depth))
    elif potSecOrder == 2:
        if "hydroPath" not in platform:
            raise ValueError("potSecOrder==2 requires hydroPath in the "
                             "platform input")
        from raft_tpu.models.qtf import read_qtf_12d
        qpath = platform["hydroPath"] + ".12d"
        if not os.path.isfile(qpath):
            raise FileNotFoundError(f"QTF file {qpath} not found")
        qtf_data = read_qtf_12d(qpath, rho=rho_water, g=g)

    if (not geometry_only) and bem is None and any(m.potMod for m in members):
        # potMod members get no strip-theory hydro — run the native C++ BEM
        # core on their panel mesh (the reference's pyHAMS/HAMS step,
        # raft_fowt.py:568-650; here in-process, see native/bem/bem.cpp).
        # The mesh/solve happens lazily on a FOWTModel stub because the
        # solver needs the frequency grid and fluid properties.
        from raft_tpu.io import bem_native
        if not bem_native.available():
            raise NotImplementedError(
                "members with potMod=True need either precomputed WAMIT "
                "coefficients (potFirstOrder: 1 + hydroPath / potModMaster:"
                " 3) or the native BEM core, which failed to build/load: "
                f"{bem_native.load_error()}")
        dz_BEM = float(get_from_dict(platform, "dz_BEM", default=3.0))
        da_BEM = float(get_from_dict(platform, "da_BEM", default=2.0))
        # the reference's BEM grid control: min_freq_BEM [Hz] is both the
        # lowest BEM frequency and the grid step (raft_fowt.py:121-122);
        # grid construction (and its max_freqs cost cap) lives in
        # solve_bem_fowt
        mf_bem = get_from_dict(platform, "min_freq_BEM", default=0.0)
        dw_bem = 2.0 * np.pi * float(mf_bem) if mf_bem else None
        _stub = FOWTModel(
            members=members, member_types=member_types,
            member_names=member_names, rotors=[], mooring=None, nodes=nodes,
            w=w, k=k, depth=float(depth), rho_water=rho_water, g=g,
            shearExp_water=shearExp_water, yawstiff=yawstiff,
            x_ref=float(x_ref), y_ref=float(y_ref),
            heading_adjust=float(heading_adjust), nplatmems=nplatmems,
            ntowers=ntowers, potModMaster=potModMaster)
        bem = bem_native.solve_bem_fowt(
            _stub, dz=dz_BEM, da=da_BEM, dw_bem=dw_bem,
            mesh_dir=platform.get("meshDir"))

    return FOWTModel(
        members=members, member_types=member_types, member_names=member_names,
        rotors=rotors, mooring=moor, nodes=nodes,
        w=w, k=k, depth=float(depth), rho_water=rho_water, g=g,
        shearExp_water=shearExp_water, yawstiff=yawstiff,
        x_ref=float(x_ref), y_ref=float(y_ref),
        heading_adjust=float(heading_adjust),
        nplatmems=nplatmems, ntowers=ntowers,
        platmem_groups=platmem_groups, potModMaster=potModMaster,
        potSecOrder=potSecOrder,
        potFirstOrder=potFirstOrder,
        bem=bem, w1_2nd=w1_2nd, k1_2nd=k1_2nd, qtf_data=qtf_data,
    )


def member_node_cols(m: MemberGeometry):
    """Per-node derived areas/volumes for one member, from its strip arrays
    (reference: raft_fowt.py:1200-1202, raft_member.py:925-949).

    Written with jnp so it works both at build time (numpy leaves) and
    inside a traced design-variant pipeline where ds/drs/dls are functions
    of the variant parameters (parallel/variants.py)."""
    ds, drs, dls = m.ds, m.drs, m.dls
    if m.circular:
        a_i_q = np.pi * ds * dls
        a_i_p1 = ds * dls
        a_i_p2 = ds * dls
        a_end_drag = jnp.abs(np.pi * ds * drs)
        v_side = 0.25 * np.pi * ds**2 * dls
        v_end = np.pi / 12.0 * jnp.abs((ds + drs) ** 3 - (ds - drs) ** 3)
        a_i = np.pi * ds * drs
    else:
        # NOTE: a_i_q uses ds[:,0] twice, replicating the reference
        # (raft_fowt.py:1200: 2*(ds[il,0]+ds[il,0])*dls)
        a_i_q = 2 * (ds[:, 0] + ds[:, 0]) * dls
        a_i_p1 = ds[:, 0] * dls
        a_i_p2 = ds[:, 1] * dls
        a_end = ((ds[:, 0] + drs[:, 0]) * (ds[:, 1] + drs[:, 1])
                 - (ds[:, 0] - drs[:, 0]) * (ds[:, 1] - drs[:, 1]))
        a_end_drag = jnp.abs(a_end)
        v_side = ds[:, 0] * ds[:, 1] * dls
        dmean_p = jnp.mean(ds + drs, axis=1)
        dmean_m = jnp.mean(ds - drs, axis=1)
        v_end = np.pi / 12.0 * (dmean_p**3 - dmean_m**3)
        a_i = a_end
    R = 0.5 * ds if m.circular else 0.0 * ds[:, 0]
    return dict(frac=m.ls / m.l, dls=dls, a_i_q=a_i_q, a_i_p1=a_i_p1,
                a_i_p2=a_i_p2, a_i_end_drag=a_end_drag, v_side=v_side,
                v_end=v_end, a_i=a_i, R=R)


def _build_nodeset(members: List[MemberGeometry]) -> NodeSet:
    cols = {k: [] for k in ("member_index", "frac", "dls", "a_i_q", "a_i_p1",
                            "a_i_p2", "a_i_end_drag", "v_side", "v_end", "a_i",
                            "Cd_q", "Cd_p1", "Cd_p2", "Cd_End",
                            "Ca_p1", "Ca_p2", "Ca_End", "circ", "potMod",
                            "MCF", "R")}
    for im, m in enumerate(members):
        ns = m.ns
        circ = m.circular
        derived = member_node_cols(m)
        cols["member_index"].append(np.full(ns, im))
        cols["MCF"].append(np.full(ns, bool(m.MCF), dtype=bool))
        for key in ("frac", "dls", "a_i_q", "a_i_p1", "a_i_p2",
                    "a_i_end_drag", "v_side", "v_end", "a_i", "R"):
            cols[key].append(np.asarray(derived[key]))
        cols["Cd_q"].append(m.Cd_q_n)
        cols["Cd_p1"].append(m.Cd_p1_n)
        cols["Cd_p2"].append(m.Cd_p2_n)
        cols["Cd_End"].append(m.Cd_End_n)
        cols["Ca_p1"].append(m.Ca_p1_n)
        cols["Ca_p2"].append(m.Ca_p2_n)
        cols["Ca_End"].append(m.Ca_End_n)
        cols["circ"].append(np.full(ns, circ, dtype=bool))
        cols["potMod"].append(np.full(ns, m.potMod, dtype=bool))
    return NodeSet(**{k: np.concatenate(v) for k, v in cols.items()})


# --------------------------------------------------------------------------
# pose
# --------------------------------------------------------------------------

def fowt_pose(fowt: FOWTModel, r6):
    """Member poses + stacked node arrays for the given platform pose.

    Returns dict with 'members' (list of member pose dicts) and stacked
    'r' (N,3), 'q','p1','p2' (N,3), 'qMat','p1Mat','p2Mat' (N,3,3).
    """
    r6 = jnp.asarray(r6, float)
    mposes = [member_pose(m, r6) for m in fowt.members]
    counts = [m.ns for m in fowt.members]
    r = jnp.concatenate([p["r"] for p in mposes])
    q = jnp.concatenate([jnp.tile(p["q"], (n, 1)) for p, n in zip(mposes, counts)])
    p1 = jnp.concatenate([jnp.tile(p["p1"], (n, 1)) for p, n in zip(mposes, counts)])
    p2 = jnp.concatenate([jnp.tile(p["p2"], (n, 1)) for p, n in zip(mposes, counts)])
    qMat = q[:, :, None] * q[:, None, :]
    p1Mat = p1[:, :, None] * p1[:, None, :]
    p2Mat = p2[:, :, None] * p2[:, None, :]
    return dict(r6=r6, members=mposes, r=r, q=q, p1=p1, p2=p2,
                qMat=qMat, p1Mat=p1Mat, p2Mat=p2Mat)


# --------------------------------------------------------------------------
# statics
# --------------------------------------------------------------------------

def fowt_statics(fowt: FOWTModel, pose, l_fill=None, rho_fill=None):
    """Mass/hydrostatic matrices and weight/buoyancy vectors about the PRP
    (reference: raft_fowt.py:291-566).

    ``l_fill``/``rho_fill``: optional per-member override lists for ballast
    trim (traced values allowed).
    """
    g = fowt.g
    r6 = pose["r6"]
    rPRP = r6[:3]

    W_struc = jnp.zeros(6)
    M_struc = jnp.zeros((6, 6))
    M_struc_sub = jnp.zeros((6, 6))
    W_hydro = jnp.zeros(6)
    C_hydro = jnp.zeros((6, 6))
    m_center_sum = jnp.zeros(3)
    m_sub_sum = jnp.zeros(3)
    m_sub = 0.0
    m_shell_sub = 0.0
    VTOT = 0.0
    AWP_TOT = 0.0
    IWPx_TOT = 0.0
    IWPy_TOT = 0.0
    Sum_V_rCB = jnp.zeros(3)
    Sum_AWP_rWP = jnp.zeros(2)
    mtower = []
    rCG_tow = []
    mballast = []
    pballast = []

    for i, (m, mtype, mname) in enumerate(zip(fowt.members, fowt.member_types,
                                              fowt.member_names)):
        mpose = pose["members"][i]
        # nacelles and underwater-rotor blade members contribute buoyancy
        # only — their inertia lives in mRNA/IxRNA/IrRNA (reference:
        # raft_fowt.py:447-464 nacelles, :402-405 blade members)
        if mname not in ("nacelle", "blade"):
            lf = None if l_fill is None else l_fill[i]
            rf = None if rho_fill is None else rho_fill[i]
            inert = member_inertia(m, mpose, rPRP=rPRP, l_fill=lf, rho_fill=rf)
            mass, center = inert["mass"], inert["center"]
            W_struc = W_struc + translate_force_3to6(
                jnp.array([0.0, 0.0, -g]) * mass, center)
            M_struc = M_struc + inert["M_struc"]
            m_center_sum = m_center_sum + center * mass
            if mtype <= 1:
                mtower.append(mass)
                rCG_tow.append(center)
            else:
                m_sub = m_sub + mass
                M_struc_sub = M_struc_sub + inert["M_struc"]
                m_sub_sum = m_sub_sum + center * mass
                m_shell_sub = m_shell_sub + inert["mshell"]
                mballast.append(inert["mfill"])
                pballast.append(inert["pfill"])

        hs = member_hydrostatics(m, mpose, rPRP=rPRP, rho=fowt.rho_water, g=g)
        W_hydro = W_hydro + hs["Fvec"]
        C_hydro = C_hydro + hs["Cmat"]
        VTOT = VTOT + hs["V_UW"]
        AWP_TOT = AWP_TOT + hs["AWP"]
        IWPx_TOT = IWPx_TOT + hs["IWP"] + hs["AWP"] * hs["yWP"] ** 2
        IWPy_TOT = IWPy_TOT + hs["IWP"] + hs["AWP"] * hs["xWP"] ** 2
        Sum_V_rCB = Sum_V_rCB + hs["r_center"] * hs["V_UW"]
        Sum_AWP_rWP = Sum_AWP_rWP + jnp.stack([hs["xWP"], hs["yWP"]]) * hs["AWP"]

    # RNA inertia contributions (reference :467-480)
    for rot in fowt.rotors:
        rpose = rotor_pose(rot, r6)
        Mmat = jnp.diag(jnp.array([rot.mRNA, rot.mRNA, rot.mRNA,
                                   rot.IxRNA, rot.IrRNA, rot.IrRNA]))
        Mmat = rotate_matrix_6(Mmat, rpose["R_q"])
        r_RRP_rel = rpose["R_ptfm"] @ jnp.asarray(rot.r_rel)
        r_CG_rel = r_RRP_rel + rpose["q"] * rot.xCG_RNA
        W_struc = W_struc + translate_force_3to6(
            jnp.array([0.0, 0.0, -g * rot.mRNA]), r_CG_rel)
        M_struc = M_struc + translate_matrix_6to6(Mmat, r_CG_rel)
        m_center_sum = m_center_sum + r_CG_rel * rot.mRNA

    m_all = M_struc[0, 0]
    rCG = m_center_sum / m_all
    rCG_sub = m_sub_sum / jnp.where(m_sub == 0.0, 1.0, m_sub)

    C_struc = jnp.zeros((6, 6))
    C_struc = C_struc.at[3, 3].set(-m_all * g * rCG[2])
    C_struc = C_struc.at[4, 4].set(-m_all * g * rCG[2])
    C_struc_sub = jnp.zeros((6, 6))
    C_struc_sub = C_struc_sub.at[3, 3].set(-m_sub * g * rCG_sub[2])
    C_struc_sub = C_struc_sub.at[4, 4].set(-m_sub * g * rCG_sub[2])

    rCB = Sum_V_rCB / jnp.where(VTOT == 0.0, 1.0, VTOT)
    zMeta = jnp.where(VTOT == 0.0, 0.0,
                      rCB[2] + IWPx_TOT / jnp.where(VTOT == 0.0, 1.0, VTOT))

    M_sub_cm = translate_matrix_6to6(M_struc_sub, -rCG_sub)
    M_all_cm = translate_matrix_6to6(M_struc, -rCG)

    return dict(
        W_struc=W_struc, M_struc=M_struc, C_struc=C_struc,
        W_hydro=W_hydro, C_hydro=C_hydro,
        M_struc_sub=M_struc_sub, C_struc_sub=C_struc_sub,
        m=m_all, m_sub=m_sub, m_shell=m_shell_sub,
        rCG=rCG, rCG_sub=rCG_sub, rCB=rCB, V=VTOT, AWP=AWP_TOT,
        rM=jnp.array([rCB[0], rCB[1], 0.0]) + jnp.array([0.0, 0.0, 1.0]) * zMeta,
        mtower=mtower, rCG_tow=rCG_tow, mballast=mballast, pballast=pballast,
        Ixx=M_all_cm[3, 3], Iyy=M_all_cm[4, 4], Izz=M_all_cm[5, 5],
        Ixx_sub=M_sub_cm[3, 3], Iyy_sub=M_sub_cm[4, 4], Izz_sub=M_sub_cm[5, 5],
    )


# --------------------------------------------------------------------------
# strip-theory hydro constants (stacked nodes)
# --------------------------------------------------------------------------

def fowt_hydro_constants(fowt: FOWTModel, pose):
    """Added mass (6,6) about the PRP plus per-node Amat/Imat/a_i
    (reference: raft_fowt.py:848-880 over raft_member.py:877-1050)."""
    nd = fowt.nodes
    rho = fowt.rho_water
    r = pose["r"]
    submerged = r[:, 2] < 0.0
    active = submerged & jnp.asarray(~nd.potMod)

    dls = jnp.asarray(nd.dls)
    z = r[:, 2]
    dls_safe = jnp.where(dls == 0.0, 1.0, dls)
    scale = jnp.where(z + 0.5 * dls > 0.0, (0.5 * dls - z) / dls_safe, 1.0)
    v_side = jnp.asarray(nd.v_side) * scale
    v_end = jnp.asarray(nd.v_end)

    Ca_p1 = jnp.asarray(nd.Ca_p1)
    Ca_p2 = jnp.asarray(nd.Ca_p2)
    Ca_End = jnp.asarray(nd.Ca_End)
    p1Mat, p2Mat, qMat = pose["p1Mat"], pose["p2Mat"], pose["qMat"]

    Amat = ((rho * v_side * Ca_p1)[:, None, None] * p1Mat
            + (rho * v_side * Ca_p2)[:, None, None] * p2Mat
            + (rho * v_end * Ca_End)[:, None, None] * qMat)
    Imat = ((rho * v_side * (1.0 + Ca_p1))[:, None, None] * p1Mat
            + (rho * v_side * (1.0 + Ca_p2))[:, None, None] * p2Mat
            + (rho * v_end * Ca_End)[:, None, None] * qMat)
    mask = active.astype(float)
    Amat = Amat * mask[:, None, None]
    Imat = Imat * mask[:, None, None]
    a_i = jnp.asarray(nd.a_i) * mask

    # MacCamy-Fuchs: frequency-dependent complex inertial coefficient for
    # flagged circular members (reference: raft_member.py:1053-1088 — Cm =
    # 4i/(pi (kR)^2 H1'(kR)) with a cosine ramp blending to the Morison Cm
    # for long waves; applied to the transverse terms only)
    if nd.MCF is not None and bool(np.any(np.asarray(nd.MCF))):
        from raft_tpu.ops.special import hankel1p_all
        k = jnp.asarray(fowt.k)                       # (nw,)
        R = jnp.asarray(nd.R)                         # (N,)
        R_safe = jnp.where(R > 0, R, 1.0)
        kR = k[None, :] * R_safe[:, None]             # (N, nw)
        Hp1 = hankel1p_all(kR, 1)[1]
        Cm = 4j / (jnp.pi * kR**2 * Hp1)
        Tr = jnp.pi / 5.0 / R_safe                    # (N,)
        ramp = jnp.where(k[None, :] < Tr[:, None],
                         0.5 * (1.0 - jnp.cos(jnp.pi * k[None, :] / Tr[:, None])),
                         1.0)
        ramp = jnp.where(k[None, :] <= 0.0, 0.0, ramp)
        mcf = jnp.asarray(nd.MCF)[:, None]
        Cm_p1 = jnp.where(mcf, Cm * ramp + (1.0 + Ca_p1[:, None]) * (1 - ramp),
                          (1.0 + Ca_p1[:, None]).astype(complex))
        Cm_p2 = jnp.where(mcf, Cm * ramp + (1.0 + Ca_p2[:, None]) * (1 - ramp),
                          (1.0 + Ca_p2[:, None]).astype(complex))
        Imat = ((rho * v_side)[:, None, None, None]
                * (Cm_p1[:, None, None, :] * p1Mat[:, :, :, None]
                   + Cm_p2[:, None, None, :] * p2Mat[:, :, :, None])
                + ((rho * v_end * Ca_End)[:, None, None]
                   * qMat)[:, :, :, None].astype(complex))
        Imat = Imat * mask[:, None, None, None]

    offsets = r - pose["r6"][:3]
    A_hydro = jnp.sum(translate_matrix_3to6(Amat, offsets), axis=0)
    return dict(A_hydro_morison=A_hydro, Amat=Amat, Imat=Imat, a_i=a_i,
                active=active)


# --------------------------------------------------------------------------
# sea states & excitation
# --------------------------------------------------------------------------

def build_seastate(fowt: FOWTModel, case: dict):
    """Host-side sea-state setup from a case dict (reference:
    raft_fowt.py:977-1014).  Returns dict(beta (nH,), S (nH,nw),
    zeta (nH,nw) complex)."""
    wh = case.get("wave_heading", 0.0)
    nWaves = 1 if np.isscalar(wh) else len(wh)
    heading = np.atleast_1d(np.asarray(
        get_from_dict(case, "wave_heading", shape=nWaves, dtype=float, default=0), float))
    spectrum = get_from_dict(case, "wave_spectrum", shape=nWaves, dtype=str,
                             default="JONSWAP")
    spectrum = [spectrum] * nWaves if isinstance(spectrum, str) else list(np.atleast_1d(spectrum))
    # wind-only case rows carry no wave keys: default to a still sea state
    period = np.atleast_1d(np.asarray(get_from_dict(case, "wave_period", shape=nWaves, dtype=float, default=0), float))
    height = np.atleast_1d(np.asarray(get_from_dict(case, "wave_height", shape=nWaves, dtype=float, default=0), float))
    for ih in range(nWaves):
        if spectrum[ih] == "JONSWAP" and height[ih] <= 0.0:
            spectrum[ih] = "still"
        elif spectrum[ih] == "JONSWAP" and period[ih] <= 0.0:
            raise ValueError(
                f"case specifies wave_height={height[ih]} but no positive "
                "wave_period — set both (or neither, for a still sea state)")
    gamma = np.atleast_1d(np.asarray(get_from_dict(case, "wave_gamma", shape=nWaves, dtype=float, default=0), float))

    w = fowt.w
    dw = w[1] - w[0]
    S = np.zeros((nWaves, len(w)))
    zeta = np.zeros((nWaves, len(w)), dtype=complex)
    for ih in range(nWaves):
        sp = spectrum[ih]
        if sp == "unit":
            S[ih, :] = 1.0
        elif sp == "constant":
            S[ih, :] = height[ih]
        elif sp == "JONSWAP":
            S[ih, :] = np.asarray(jonswap(w, height[ih], period[ih],
                                          gamma=(gamma[ih] if gamma[ih] else None)))
        elif sp in ("none", "still"):
            S[ih, :] = 0.0
        else:
            raise ValueError(f"unknown wave spectrum '{sp}'")
        zeta[ih, :] = np.sqrt(2.0 * S[ih, :] * dw)
    return dict(beta=np.deg2rad(heading), S=S, zeta=zeta, nWaves=nWaves)


def fowt_bem_excitation(fowt: FOWTModel, seastate):
    """Potential-flow wave excitation per heading, (nH,6,nw) complex
    (reference: raft_fowt.py:1034-1093).  Zero when no BEM data applies —
    the reference computes F_BEM only when a member is potential-flow
    modeled or potModMaster is 2/3 (raft_fowt.py:1040)."""
    import jax

    beta = jnp.atleast_1d(jnp.asarray(seastate["beta"]))
    nH = beta.shape[0]
    nw = fowt.nw
    if fowt.bem is None or not (fowt.potMod_any or fowt.potModMaster in (2, 3)):
        return jnp.zeros((nH, 6, nw), dtype=complex)
    from raft_tpu.io.wamit import bem_excitation
    zeta = jnp.asarray(seastate["zeta"]).reshape(nH, nw)
    k = jnp.asarray(fowt.k)

    def one(beta_h, zeta_h):
        return bem_excitation(fowt.bem, beta_h, zeta_h, k,
                              x_ref=fowt.x_ref, y_ref=fowt.y_ref,
                              heading_adjust=fowt.heading_adjust)

    return jax.vmap(one)(beta, zeta)


def fowt_hydro_excitation(fowt: FOWTModel, pose, seastate, hydro_consts):
    """Wave kinematics at all nodes + strip-theory inertial excitation
    (reference: raft_fowt.py:972-1149, strip part).  Returns dict with
    u, ud (nH,N,3,nw), pDyn (nH,N,nw), F_hydro_iner (nH,6,nw)."""
    r = pose["r"]
    w = jnp.asarray(fowt.w)
    k = jnp.asarray(fowt.k)
    beta = jnp.asarray(seastate["beta"])
    zeta = jnp.asarray(seastate["zeta"])

    submerged = (r[:, 2] < 0.0)

    def per_heading(zeta_h, beta_h):
        u, ud, pDyn = wave_kinematics(zeta_h, beta_h, w, k, fowt.depth, r,
                                      rho=fowt.rho_water, g=fowt.g)
        # wave_kinematics zeroes z>0 nodes; the reference additionally
        # excludes z==0 exactly (strict z<0)
        m3 = submerged[:, None, None].astype(float)
        return u * m3, ud * m3, pDyn * submerged[:, None].astype(float)

    import jax
    u, ud, pDyn = jax.vmap(per_heading)(zeta, beta)

    # inertial excitation: F = Imat @ ud + pDyn * a_i * q   per node
    # (Imat is (N,3,3,nw) complex when MacCamy-Fuchs members are present)
    Imat = hydro_consts["Imat"].astype(complex)
    a_i = hydro_consts["a_i"]
    q = pose["q"]
    if Imat.ndim == 4:
        F_I = jnp.einsum("nijw,hnjw->hniw", Imat, ud)
    else:
        F_I = jnp.einsum("nij,hnjw->hniw", Imat, ud)
    F_nodes = (F_I
               + pDyn[:, :, None, :] * (a_i[:, None] * q)[None, :, :, None])
    offsets = r - pose["r6"][:3]
    F_hydro_iner = jnp.sum(_wrench_about_origin(F_nodes, offsets, node_axis=1),
                           axis=1)
    return dict(u=u, ud=ud, pDyn=pDyn, F_hydro_iner=F_hydro_iner)


def _wrench_about_origin(F_nodes, offsets, node_axis=-3):
    """Stack per-node 3-forces with their moments r x F into 6-wrenches.

    F_nodes: (..., N, 3, nw); offsets: (..., N, 3), both right-aligned so
    either may carry extra leading batch/heading axes.  Returns
    (..., N, 6, nw).  ``node_axis`` is kept for call-site readability but
    the layout is fixed to the (-3, -2, -1) = (node, component, freq)
    convention."""
    if node_axis not in (-3, F_nodes.ndim - 3):
        raise ValueError("_wrench_about_origin uses the fixed (node, "
                         "component, freq) = (-3, -2, -1) layout; got "
                         f"node_axis={node_axis} for ndim={F_nodes.ndim}")
    rx = offsets[..., None]                       # (..., N, 3, 1)
    def comp(i):
        return F_nodes[..., i, :]
    def rcomp(i):
        return rx[..., i, :]
    m0 = rcomp(1) * comp(2) - rcomp(2) * comp(1)
    m1 = rcomp(2) * comp(0) - rcomp(0) * comp(2)
    m2 = rcomp(0) * comp(1) - rcomp(1) * comp(0)
    mom = jnp.stack([m0, m1, m2], axis=-2)
    return jnp.concatenate([F_nodes, mom], axis=-2)


# --------------------------------------------------------------------------
# drag linearization & excitation
# --------------------------------------------------------------------------

def fowt_hydro_linearization(fowt: FOWTModel, pose, Xi, u0):
    """Stochastic linearization of quadratic drag about response Xi
    (reference: raft_fowt.py:1152-1266).  u0: (N,3,nw) wave velocity for
    the FIRST heading.  Returns (B_hydro_drag (6,6), Bmat (N,3,3))."""
    nd = fowt.nodes
    rho = fowt.rho_water
    r = pose["r"]
    w = jnp.asarray(fowt.w)
    offsets = r - pose["r6"][:3]
    _, vnode, _ = kinematics_from_motion(offsets, Xi, w)   # (N,3,nw)

    submerged = (r[:, 2] < 0.0)
    q, p1, p2 = pose["q"], pose["p1"], pose["p2"]

    vrel = u0 - vnode
    vrel_q = jnp.sum(vrel * q[:, :, None], axis=1)[:, None, :] * q[:, :, None]
    vrel_p = vrel - vrel_q
    vrel_p1 = jnp.sum(vrel * p1[:, :, None], axis=1)[:, None, :] * p1[:, :, None]
    vrel_p2 = jnp.sum(vrel * p2[:, :, None], axis=1)[:, None, :] * p2[:, :, None]

    vRMS_q = get_rms(vrel_q, axis=(1, 2))
    vRMS_p = get_rms(vrel_p, axis=(1, 2))
    vRMS_p1c = get_rms(vrel_p1, axis=(1, 2))
    vRMS_p2c = get_rms(vrel_p2, axis=(1, 2))
    circ = jnp.asarray(nd.circ)
    vRMS_p1 = jnp.where(circ, vRMS_p, vRMS_p1c)
    vRMS_p2 = jnp.where(circ, vRMS_p, vRMS_p2c)

    c = jnp.sqrt(8.0 / jnp.pi) * 0.5 * rho
    Bq = c * vRMS_q * jnp.asarray(nd.a_i_q) * jnp.asarray(nd.Cd_q)
    Bp1 = c * vRMS_p1 * jnp.asarray(nd.a_i_p1) * jnp.asarray(nd.Cd_p1)
    Bp2 = c * vRMS_p2 * jnp.asarray(nd.a_i_p2) * jnp.asarray(nd.Cd_p2)
    Bend = c * vRMS_q * jnp.asarray(nd.a_i_end_drag) * jnp.asarray(nd.Cd_End)

    Bmat = (Bq[:, None, None] * pose["qMat"]
            + Bp1[:, None, None] * pose["p1Mat"]
            + Bp2[:, None, None] * pose["p2Mat"]
            + Bend[:, None, None] * pose["qMat"])
    Bmat = Bmat * submerged[:, None, None].astype(float)
    B_hydro_drag = jnp.sum(translate_matrix_3to6(Bmat, offsets), axis=0)
    return B_hydro_drag, Bmat


def fowt_drag_precompute(fowt: FOWTModel, pose, u0):
    """Xi-independent pieces of the stochastic drag linearization.

    The node velocity is affine in the 6 platform motions
    (vnode = i w T_n Xi, T_n = [I | H(r_n)] with H the reference's
    alternator matrix, H(r) th = th x r), so every RMS integral in
    `fowt_hydro_linearization` splits into a wave-only energy (constant
    across the fixed-point iterations), a cross term linear in Xi, and a
    quadratic form in the motion spectrum.  Precomputing the constants
    removes all (node,3,nw) temporaries from the iteration loop — the
    dominant HBM traffic of the variant pipeline on TPU (measured ~90% of
    the per-iteration cost at 1024 variants x 200 bins).

    Returns a dict consumed by `fowt_hydro_linearization_pre`.
    """
    r = pose["r"]
    offsets = r - pose["r6"][..., None, :3]
    q, p1, p2 = pose["q"], pose["p1"], pose["p2"]

    eye = jnp.broadcast_to(jnp.eye(3), offsets.shape[:-1] + (3, 3))
    # ops.transforms.skew follows the reference's H-matrix convention
    # (skew(r) @ th == th x r), so the rotational block enters with +
    # (all shapes carry an optional leading batch: this function and its
    # consumers are rank-polymorphic so the variant sweep can run them on
    # explicitly batched arrays — vmap around the fixed-point loop
    # compiles ~300x slower on TPU than a manually batched loop body)
    T = jnp.concatenate([eye, skew(offsets)], axis=-1)      # (...,N,3,6)

    def proj(vec):
        s = jnp.einsum("...nc,...ncw->...nw", vec, u0)      # scalar projection
        g = jnp.einsum("...nc,...ncj->...nj", vec, T)       # motion row
        A = jnp.sum(jnp.abs(s) ** 2, axis=-1)               # wave energy
        return s, g, A

    s_q, g_q, A_q = proj(q)
    s_p1, g_p1, A_p1 = proj(p1)
    s_p2, g_p2, A_p2 = proj(p2)

    u_P = u0 - q[..., :, None] * s_q[..., None, :]          # perp wave vel
    K = T - q[..., :, None] * g_q[..., None, :]             # (...,N,3,6)
    A_P = jnp.sum(jnp.abs(u_P) ** 2, axis=(-2, -1))

    # effective drag areas per node (traced for design variants, where the
    # node set itself is theta-dependent — the iteration step must not
    # reach back into a shared base FOWTModel for them)
    nd = fowt.nodes
    a_q_eff = (jnp.asarray(nd.a_i_q) * jnp.asarray(nd.Cd_q)
               + jnp.asarray(nd.a_i_end_drag) * jnp.asarray(nd.Cd_End))
    a_p1_eff = jnp.asarray(nd.a_i_p1) * jnp.asarray(nd.Cd_p1)
    a_p2_eff = jnp.asarray(nd.a_i_p2) * jnp.asarray(nd.Cd_p2)

    return dict(T=T, s_q=s_q, g_q=g_q, A_q=A_q,
                s_p1=s_p1, g_p1=g_p1, A_p1=A_p1,
                s_p2=s_p2, g_p2=g_p2, A_p2=A_p2,
                u_P=u_P, K=K, A_P=A_P,
                a_q_eff=a_q_eff, a_p1_eff=a_p1_eff, a_p2_eff=a_p2_eff,
                circ=jnp.asarray(nd.circ))


def fowt_hydro_linearization_pre(fowt: FOWTModel, pose, pre, Xi):
    """Drag linearization about Xi using `fowt_drag_precompute` constants.

    Algebraically identical to `fowt_hydro_linearization` (same vRMS per
    node, same B matrices; validated to ~1e-12 in
    tests/test_drag_linearization.py) but with per-iteration cost reduced to three
    (N,nw)x(6,nw) contractions, one (N,3,nw)x(6,nw) contraction, and
    node-local algebra."""
    rho = fowt.rho_water
    r = pose["r"]
    w = jnp.asarray(fowt.w)
    offsets = r - pose["r6"][..., None, :3]
    submerged = (r[..., 2] < 0.0)

    iwXi = (1j * w) * jnp.asarray(Xi)                       # (...,6,nw)
    # motion spectrum quadratic form: M[j,k] = sum_w w^2 Re(Xi_j Xi_k*)
    M_re = jnp.real(jnp.einsum("...jw,...kw->...jk", iwXi, jnp.conj(iwXi)))

    def rms_scalar(s, g, A):
        b = jnp.real(jnp.einsum("...jw,...nw->...nj", iwXi, jnp.conj(s)))
        cross = jnp.sum(g * b, axis=-1)
        quad = jnp.einsum("...nj,...jk,...nk->...n", g, M_re, g)
        return jnp.sqrt(jnp.maximum(0.5 * (A - 2.0 * cross + quad), 0.0))

    vRMS_q = rms_scalar(pre["s_q"], pre["g_q"], pre["A_q"])
    vRMS_p1c = rms_scalar(pre["s_p1"], pre["g_p1"], pre["A_p1"])
    vRMS_p2c = rms_scalar(pre["s_p2"], pre["g_p2"], pre["A_p2"])

    K = pre["K"]
    D = jnp.real(jnp.einsum("...jw,...ncw->...ncj", iwXi,
                            jnp.conj(pre["u_P"])))
    cross_P = jnp.sum(K * D, axis=(-2, -1))
    quad_P = jnp.einsum("...ncj,...jk,...nck->...n", K, M_re, K)
    vRMS_p = jnp.sqrt(jnp.maximum(
        0.5 * (pre["A_P"] - 2.0 * cross_P + quad_P), 0.0))

    circ = pre["circ"]
    vRMS_p1 = jnp.where(circ, vRMS_p, vRMS_p1c)
    vRMS_p2 = jnp.where(circ, vRMS_p, vRMS_p2c)

    c = jnp.sqrt(8.0 / jnp.pi) * 0.5 * rho
    # a_q_eff folds the axial and end-drag areas together (both multiply
    # vRMS_q and qMat); node constants come from `pre` so design variants'
    # traced node sets flow through (see fowt_drag_precompute)
    Bq_end = c * vRMS_q * pre["a_q_eff"]
    Bp1 = c * vRMS_p1 * pre["a_p1_eff"]
    Bp2 = c * vRMS_p2 * pre["a_p2_eff"]

    Bmat = (Bq_end[..., None, None] * pose["qMat"]
            + Bp1[..., None, None] * pose["p1Mat"]
            + Bp2[..., None, None] * pose["p2Mat"])
    Bmat = Bmat * submerged[..., None, None].astype(float)
    B_hydro_drag = jnp.sum(translate_matrix_3to6(Bmat, offsets), axis=-3)
    return B_hydro_drag, Bmat


def fowt_drag_excitation(fowt: FOWTModel, pose, Bmat, u_h):
    """Linearized drag excitation for one heading's wave velocities u_h
    (...,N,3,nw) (reference: raft_fowt.py:1270-1293).  Rank-polymorphic
    over an optional leading batch axis (see fowt_drag_precompute)."""
    F_nodes = jnp.einsum("...nij,...njw->...niw", Bmat.astype(complex), u_h)
    offsets = (pose["r"] - pose["r6"][..., None, :3])
    return jnp.sum(_wrench_about_origin(F_nodes, offsets, node_axis=-3),
                   axis=-3)


def fowt_current_loads(fowt: FOWTModel, pose, speed, heading_deg):
    """Mean current drag about the PRP (reference: raft_fowt.py:1297-1382)."""
    nd = fowt.nodes
    rho = fowt.rho_water
    r = pose["r"]
    submerged = (r[:, 2] < 0.0)

    # reference z for the current profile: submerged rotor hub depth if any
    # (reference: raft_fowt.py:1311-1314)
    Zref = 0.0
    for rot in fowt.rotors:
        if rot.hubHt < 0:
            Zref = rot.hubHt
    v = speed * (((fowt.depth) - jnp.abs(r[:, 2])) / (fowt.depth + Zref)) ** fowt.shearExp_water
    h = jnp.deg2rad(heading_deg)
    vcur = jnp.stack([v * jnp.cos(h), v * jnp.sin(h), jnp.zeros_like(v)], axis=-1)

    q, p1, p2 = pose["q"], pose["p1"], pose["p2"]
    vq = jnp.sum(vcur * q, axis=1)[:, None] * q
    vp = vcur - vq
    vp1 = jnp.sum(vcur * p1, axis=1)[:, None] * p1
    vp2 = jnp.sum(vcur * p2, axis=1)[:, None] * p2
    circ = jnp.asarray(nd.circ)
    nq = jnp.linalg.norm(vq, axis=1)
    np_ = jnp.linalg.norm(vp, axis=1)
    np1 = jnp.where(circ, np_, jnp.linalg.norm(vp1, axis=1))
    np2 = jnp.where(circ, np_, jnp.linalg.norm(vp2, axis=1))

    Dq = 0.5 * rho * jnp.asarray(nd.a_i_q) * jnp.asarray(nd.Cd_q)
    Dp1 = 0.5 * rho * jnp.asarray(nd.a_i_p1) * jnp.asarray(nd.Cd_p1)
    Dp2 = 0.5 * rho * jnp.asarray(nd.a_i_p2) * jnp.asarray(nd.Cd_p2)
    Dend = 0.5 * rho * jnp.asarray(nd.a_i_end_drag) * jnp.asarray(nd.Cd_End)
    D = (Dq[:, None] * nq[:, None] * vq + Dp1[:, None] * np1[:, None] * vp1
         + Dp2[:, None] * np2[:, None] * vp2 + Dend[:, None] * nq[:, None] * vq)
    D = D * submerged[:, None].astype(float)
    offsets = r - pose["r6"][:3]
    return jnp.sum(translate_force_3to6(D, offsets), axis=0)


# --------------------------------------------------------------------------
# turbine constants
# --------------------------------------------------------------------------

def fowt_turbine_constants(fowt: FOWTModel, case: dict, r6,
                           transfer_heading=None):
    """Aero-servo matrices/forces about the PRP + gyroscopic damping
    (reference: raft_fowt.py:773-845).

    ``transfer_heading`` (rad, per-rotor list or scalar) replicates a
    reference statefulness quirk: the hub->PRP transfer offset r_hub_rel
    is only refreshed by Rotor.setPosition, NOT by calcAero's setYaw
    (raft_rotor.py:376-460 vs :795-800), so the statics-time constants of
    case i transfer moments with the hub position of the PREVIOUS case's
    inflow heading (zero pose).  Pass the stale heading here to reproduce
    that; None uses the current case heading (the post-statics
    equilibrium update behaves that way because setPosition has run by
    then)."""
    nw = fowt.nw
    nrot = fowt.nrotors
    A_aero = jnp.zeros((6, 6, nw, nrot))
    B_aero = jnp.zeros((6, 6, nw, nrot))
    f_aero = jnp.zeros((6, nw, nrot), dtype=complex)
    f_aero0 = jnp.zeros((6, nrot))
    B_gyro = jnp.zeros((6, 6, nrot))

    status = str(get_from_dict(case, "turbine_status", shape=0, dtype=str,
                               default="operating"))
    if status != "operating":
        return dict(A_aero=A_aero, B_aero=B_aero, f_aero=f_aero,
                    f_aero0=f_aero0, B_gyro=B_gyro)

    for ir, rot in enumerate(fowt.rotors):
        current = rot.hubHt < 0
        speed = float(get_from_dict(case, "current_speed", shape=0, default=1.0)) \
            if current else float(get_from_dict(case, "wind_speed", shape=0, default=10.0))
        if rot.aeroServoMod > 0 and speed > 0.0:
            out = calc_aero(rot, fowt.w, case, r6=r6, current=current)
            pose_r = out["pose"]
            if transfer_heading is None:
                r_hub_rel = pose_r["r_hub"] - jnp.asarray(r6)[:3]
            else:
                th = (transfer_heading[ir]
                      if np.ndim(transfer_heading) else transfer_heading)
                pose_t = rotor_pose(
                    rot, r6, inflow_heading=float(th),
                    turbine_heading=np.radians(float(get_from_dict(
                        case, "turbine_heading", shape=0, default=0.0))),
                    yaw_command=np.radians(float(get_from_dict(
                        case, "yaw_misalign", shape=0, default=0.0))))
                r_hub_rel = pose_t["r_hub"] - jnp.asarray(r6)[:3]
            a = jnp.moveaxis(out["a"], -1, 0)   # (nw,6,6)
            b = jnp.moveaxis(out["b"], -1, 0)
            A_aero = A_aero.at[:, :, :, ir].set(
                jnp.moveaxis(translate_matrix_6to6(a, r_hub_rel), 0, -1))
            B_aero = B_aero.at[:, :, :, ir].set(
                jnp.moveaxis(translate_matrix_6to6(b, r_hub_rel), 0, -1))
            f_aero0 = f_aero0.at[:, ir].set(
                transform_force(out["f0"], offset=r_hub_rel))
            f_h = jnp.moveaxis(out["f"], -1, 0)  # (nw,6)
            f_aero = f_aero.at[:, :, ir].set(
                jnp.moveaxis(transform_force(f_h, offset=r_hub_rel), 0, -1))
            # gyroscopic damping (reference :829-840)
            Omega_rpm = jnp.interp(jnp.asarray(speed, float),
                                   jnp.asarray(rot.Uhub_ops),
                                   jnp.asarray(rot.Omega_rpm_ops))
            IO = rot.I_drivetrain * pose_r["q"] * Omega_rpm * 2 * jnp.pi / 60.0
            B_gyro = B_gyro.at[3:, 3:, ir].set(skew(IO))
    return dict(A_aero=A_aero, B_aero=B_aero, f_aero=f_aero, f_aero0=f_aero0,
                B_gyro=B_gyro)
