"""IEC 61400-1 extreme wind condition models (reference: raft/pyIECWind.py).

The reference vendors a pyIECWind_extreme class whose sigma-models
(NTM/ETM/EWM) feed the Kaimal rotor-averaged spectrum in the main path
(raft_rotor.py:1186-1193 -> our models/rotor.turbulence_sigma) and whose
transient gust models (EOG/EDC/ECD/EWS) generate deterministic wind time
histories and uniform-wind `.wnd` files for aeroelastic codes.  This
module provides the full surface, implemented directly from the IEC
61400-1 Ed.3 formulas (all are closed-form): turbulence classes, extreme
operating gust, extreme direction change, extreme coherent gust with
direction change, extreme wind shear, the hub-height time histories, and
the AeroDyn/InflowWind uniform `.wnd` writer.

Everything is plain numpy — these are offline design-load-case tools, not
part of the jitted response path.
"""
from __future__ import annotations

import os

import numpy as np

#: IEC 61400-1 Table 1 reference speeds by turbine class
_V_REF = {"I": 50.0, "II": 42.5, "III": 37.5, "IV": 30.0}
#: turbulence intensity by turbulence class
_I_REF = {"A+": 0.18, "A": 0.16, "B": 0.14, "C": 0.12}


class IECWindExtreme:
    """IEC extreme-condition generator (reference: pyIECWind.py:8-419).

    Attributes mirror the reference's knobs: turbine/turbulence class,
    hub height ``z_hub`` [m], rotor diameter ``D`` [m], transient time
    resolution ``dt`` [s], analysis time ``T`` [s], and output folder for
    ``.wnd`` files.
    """

    def __init__(self, turbine_class="I", turbulence_class="B",
                 z_hub=90.0, D=126.0, dt=0.05, T=30.0, outdir="."):
        self.Turbine_Class = turbine_class
        self.Turbulence_Class = turbulence_class
        self.z_hub = float(z_hub)
        self.D = float(D)
        self.dt = float(dt)
        self.T = float(T)
        self.TStart = 0.0
        self.outdir = outdir
        self.setup()

    # ------------------------------------------------------------------
    def setup(self):
        """Class constants (IEC 61400-1 §6.2-6.3; pyIECWind.py:25-52)."""
        self.V_ref = _V_REF[self.Turbine_Class]
        self.V_ave = 0.2 * self.V_ref
        self.I_ref = _I_REF[self.Turbulence_Class]
        # longitudinal turbulence scale parameter Lambda_1 (§6.3)
        self.Sigma_1 = 42.0 if self.z_hub >= 60.0 else 0.7 * self.z_hub

    # ----- sigma models ------------------------------------------------
    def NTM(self, V_hub):
        """Normal turbulence sigma (IEC eq. 11)."""
        return self.I_ref * (0.75 * V_hub + 5.6)

    def ETM(self, V_hub):
        """Extreme turbulence sigma (IEC eq. 19)."""
        c = 2.0
        return c * self.I_ref * (0.072 * (self.V_ave / c + 3.0)
                                 * (V_hub / c - 4.0) + 10.0)

    def EWM(self, V_hub):
        """Extreme wind speed model (IEC §6.3.2.1): returns
        (sigma, Ve50, Ve1, V50, V1) — turbulent sigma, the steady
        50-year / 1-year extreme speeds, and the turbulent-model 50-year /
        1-year means (same 5-tuple as the reference, pyIECWind.py:66-77)."""
        sigma = 0.11 * V_hub
        Ve50 = 1.4 * self.V_ref
        Ve1 = 0.8 * Ve50
        V50 = self.V_ref
        V1 = 0.8 * V50
        return sigma, Ve50, Ve1, V50, V1

    # ----- transient gust models ---------------------------------------
    def _tgrid(self, T):
        return np.arange(0.0, T + 0.5 * self.dt, self.dt)

    def EOG(self, V_hub):
        """Extreme operating gust (IEC §6.3.2.2, eq. 16-17): returns
        (t, V(t)) hub-height history over the 10.5 s gust."""
        V_hub = float(V_hub)
        sigma_1 = self.NTM(V_hub)
        _, _, Ve1, _, _ = self.EWM(V_hub)
        V_gust = min(1.35 * (Ve1 - V_hub),
                     3.3 * sigma_1 / (1.0 + 0.1 * self.D / self.Sigma_1))
        T = 10.5
        t = self._tgrid(T)
        V = V_hub - 0.37 * V_gust * np.sin(3 * np.pi * t / T) \
            * (1.0 - np.cos(2 * np.pi * t / T))
        self.V_gust = V_gust
        return t, V

    def EDC(self, V_hub):
        """Extreme direction change (IEC §6.3.2.4, eq. 21-22): returns
        (t, theta(t) [deg]) over the 6 s transient."""
        V_hub = float(V_hub)
        sigma_1 = self.NTM(V_hub)
        # NOTE deliberate deviation: IEC 61400-1 Ed.3 eq. 21 uses
        # 1 + 0.1*(D/Lambda_1); the reference (pyIECWind.py:156) types
        # 0.01 instead.  We keep the standard's 0.1 (pinned by
        # tests/test_iecwind.py::test_edc_uses_iec_coefficient).
        theta_e = np.degrees(4.0 * np.arctan(
            sigma_1 / (V_hub * (1.0 + 0.1 * self.D / self.Sigma_1))))
        T = 6.0
        t = self._tgrid(T)
        theta = 0.5 * theta_e * (1.0 - np.cos(np.pi * t / T))
        self.theta_e = theta_e
        return t, theta

    def ECD(self, V_hub):
        """Extreme coherent gust with direction change (IEC §6.3.2.5,
        eq. 23-26): returns (t, V(t), theta(t) [deg]) over 10 s."""
        V_hub = float(V_hub)
        V_cg = 15.0
        theta_cg = 180.0 if V_hub < 4.0 else 720.0 / V_hub
        T = 10.0
        t = self._tgrid(T)
        ramp = 0.5 * (1.0 - np.cos(np.pi * t / T))
        V = V_hub + V_cg * ramp
        theta = theta_cg * ramp
        self.V_cg, self.theta_cg = V_cg, theta_cg
        return t, V, theta

    def EWS(self, V_hub, mode="vertical", sign=+1.0):
        """Extreme wind shear (IEC §6.3.2.6, eq. 27-28): returns
        (t, shear(t)) — the transient LINEAR shear across the rotor disc
        [1/s-less, expressed as delta-V across D] for the vertical or
        horizontal variant."""
        if mode not in ("vertical", "horizontal"):
            raise ValueError("mode must be 'vertical' or 'horizontal'")
        V_hub = float(V_hub)
        sigma_1 = self.NTM(V_hub)
        beta, T = 6.4, 12.0
        t = self._tgrid(T)
        # IEC gives the same transient amplitude for EWS-V and EWS-H
        # (eq. 27 vs 28); the mode selects which shear column the .wnd
        # writer fills (see execute)
        amp = (2.5 + 0.2 * beta * sigma_1 * (self.D / self.Sigma_1) ** 0.25)
        shear = sign * amp * (1.0 - np.cos(2 * np.pi * t / T))
        return t, shear

    # ----- uniform-wind file output ------------------------------------
    def write_wnd(self, fname, t, V=None, theta=None, shear_v=None,
                  shear_h=None, pwr_shear=0.2):
        """Write an AeroDyn/InflowWind uniform wind file
        (reference: pyIECWind.py:373-403).  Columns: time, wind speed,
        direction [deg], vertical speed, horizontal shear, power-law
        shear, linear vertical shear, gust speed.

        ``shear_v``/``shear_h`` are the NORMALIZED (dimensionless) shear
        columns InflowWind expects — delta-V across the rotor divided by
        the wind-speed column.  ``pwr_shear`` fills the power-law
        vertical-shear column (the reference writes alpha=0.2 for its
        transient conditions, pyIECWind.py:149)."""
        t = np.asarray(t, float)
        n = len(t)

        def col(x, default):
            return np.full(n, default) if x is None \
                else np.broadcast_to(np.asarray(x, float), (n,))

        V = col(V, 0.0)
        theta = col(theta, 0.0)
        sv = col(shear_v, 0.0)
        sh = col(shear_h, 0.0)
        os.makedirs(self.outdir, exist_ok=True)
        path = os.path.join(self.outdir, fname)
        with open(path, "w") as f:
            f.write("! Uniform wind file generated by raft_tpu "
                    "(IEC 61400-1 extreme condition)\n")
            f.write("! Time  WindSpeed  WindDir  VertSpeed  HorizShear  "
                    "PwrLawVertShear  LinVertShear  GustSpeed\n")
            for i in range(n):
                f.write(f"{t[i]:10.3f} {V[i]:10.4f} {theta[i]:10.4f} "
                        f"{0.0:10.4f} {sh[i]:10.4f} {pwr_shear:10.4f} "
                        f"{sv[i]:10.4f} {0.0:10.4f}\n")
        self.fpath = path
        return path

    # ----- dispatcher ---------------------------------------------------
    def execute(self, condition, V_hub, mode="vertical"):
        """Dispatch by IEC condition tag (reference: pyIECWind.py:405-419).
        'NTM'/'ETM' -> sigma; 'EWM50'/'EWM1' -> (sigma, Ve); transient
        tags ('EOG','EDC','ECD','EWS') -> time histories + a .wnd file.
        ``mode`` selects the EWS variant (vertical/horizontal shear
        column in the .wnd file)."""
        if condition == "NTM":
            return self.NTM(V_hub)
        if condition == "ETM":
            return self.ETM(V_hub)
        if condition in ("EWM", "EWM50"):
            sigma, Ve50, _, _, _ = self.EWM(V_hub)
            return sigma, Ve50
        if condition == "EWM1":
            sigma, _, Ve1, _, _ = self.EWM(V_hub)
            return sigma, Ve1
        if condition == "EOG":
            t, V = self.EOG(V_hub)
            self.write_wnd(f"EOG_U{V_hub:.1f}.wnd", t, V=V)
            return t, V
        if condition == "EDC":
            t, th = self.EDC(V_hub)
            self.write_wnd(f"EDC_U{V_hub:.1f}.wnd", t,
                           V=np.full(len(t), float(V_hub)), theta=th)
            return t, th
        if condition == "ECD":
            t, V, th = self.ECD(V_hub)
            self.write_wnd(f"ECD_U{V_hub:.1f}.wnd", t, V=V, theta=th)
            return t, V, th
        if condition == "EWS":
            t, sh = self.EWS(V_hub, mode=mode)
            # InflowWind shear columns are normalized by the wind-speed
            # column — divide the dimensional transient by V_hub before
            # writing (reference: pyIECWind.py:302-303).
            sh_wnd = sh / float(V_hub)
            cols = ({"shear_v": sh_wnd} if mode == "vertical"
                    else {"shear_h": sh_wnd})
            self.write_wnd(f"EWS{mode[0].upper()}_U{V_hub:.1f}.wnd", t,
                           V=np.full(len(t), float(V_hub)), **cols)
            return t, sh
        raise ValueError(f"unknown IEC condition '{condition}'")
