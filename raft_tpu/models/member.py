"""Strip-theory member physics: geometry preprocessing + batched jnp kernels.

TPU-first re-design of the reference Member class (reference:
raft/raft_member.py).  The reference is an object whose methods loop over
sub-members and strip nodes in Python; here the design dictionary is parsed
ONCE into a static `MemberGeometry` of numpy arrays (strip discretization,
per-node coefficients, resolved cap geometry), and the physics —
inertia (raft_member.py:307-707), hydrostatics (:712-874), strip-theory
added mass / Froude-Krylov coefficients (:877-1050) — are pure vectorized
jnp kernels over the section/node axes.  Every per-section `if` in the
reference (submerged / crossing / dry, tapered / straight) becomes a mask,
so the kernels are jit/vmap-safe and differentiable w.r.t. pose and (for
design sweeps) geometry arrays.

Intentional deviations from the reference, for correctness:
- zero-length (repeated-station) sections contribute nothing; the reference
  re-adds the previous section's rotated MoI tensor at the origin in that
  case (stale-variable behavior at raft_member.py:420-426 + 538-547).  No
  shipped design has zero-length sections.
- rectangular top-end caps use the sane assignment order (the reference has
  a use-before-assignment at raft_member.py:629-632).
"""
from __future__ import annotations

from dataclasses import dataclass, field

import numpy as np
import jax.numpy as jnp

from raft_tpu.ops.geometry import (
    frustum_vcv_circ,
    frustum_vcv_rect,
    frustum_moi_circ,
    frustum_moi_rect,
)
from raft_tpu.ops.transforms import (
    rotation_matrix,
    translate_force_3to6,
    translate_matrix_3to6,
    translate_matrix_6to6,
    vec_vec_trans,
)
from raft_tpu.utils.dicttools import get_from_dict

_CAP_BOTTOM, _CAP_TOP, _CAP_MIDDLE = 0, 1, 2


@dataclass
class MemberGeometry:
    """Static (per-design) description of one member, all numpy.

    Everything here is resolved from the YAML member dict at model-build
    time: strip discretization (reference: raft_member.py:169-220), station
    scaling (:82), ballast levels (:110-135), cap geometry (:553-700
    resolved ahead of time), and per-node hydro coefficients interpolated
    onto strip nodes (:916-919 done once instead of per call).
    """

    name: str
    shape: str                  # 'circular' | 'rectangular'
    potMod: bool
    MCF: bool
    gamma: float                # twist [deg] (incl. heading for vertical members)
    rA0: np.ndarray             # (3,) end A relative to PRP, after heading rotation
    rB0: np.ndarray             # (3,)
    l: float
    stations: np.ndarray        # (n,) positions along axis, 0..l
    d: np.ndarray               # (n,) diameters  or (n,2) side lengths
    t: np.ndarray               # (n,) shell thickness
    rho_shell: float
    l_fill: np.ndarray          # (n-1,) ballast fill length per section [m]
    rho_fill: np.ndarray        # (n-1,) ballast density per section
    # strip discretization
    ns: int
    ls: np.ndarray              # (ns,) node positions along axis
    dls: np.ndarray             # (ns,) lumped strip lengths
    ds: np.ndarray              # (ns,) or (ns,2) strip mean diameter / sides
    drs: np.ndarray             # (ns,) or (ns,2) radius (half-side) change over strip
    # per-node coefficients (pre-interpolated over stations)
    Cd_q_n: np.ndarray
    Cd_p1_n: np.ndarray
    Cd_p2_n: np.ndarray
    Cd_End_n: np.ndarray
    Ca_q_n: np.ndarray
    Ca_p1_n: np.ndarray
    Ca_p2_n: np.ndarray
    Ca_End_n: np.ndarray
    # resolved caps/bulkheads: arrays over caps (possibly empty)
    cap_kind: np.ndarray = field(default_factory=lambda: np.zeros(0, int))
    cap_L: np.ndarray = field(default_factory=lambda: np.zeros(0))
    cap_h: np.ndarray = field(default_factory=lambda: np.zeros(0))
    cap_dA: np.ndarray = field(default_factory=lambda: np.zeros(0))
    cap_dB: np.ndarray = field(default_factory=lambda: np.zeros(0))
    cap_dAi: np.ndarray = field(default_factory=lambda: np.zeros(0))
    cap_dBi: np.ndarray = field(default_factory=lambda: np.zeros(0))

    @property
    def circular(self) -> bool:
        return self.shape == "circular"


def build_member_geometry(mi: dict, heading: float = 0.0) -> MemberGeometry:
    """Parse one YAML member dict into a MemberGeometry (reference:
    raft_member.py:16-242)."""
    name = str(mi.get("name", ""))
    mtype = int(mi.get("type", 0))
    rA0 = np.array(mi["rA"], dtype=float)
    rB0 = np.array(mi["rB"], dtype=float)
    if (rA0[2] == 0 or rB0[2] == 0) and mtype != 3:
        raise ValueError("Members cannot start or end on the waterplane")
    if rB0[2] < rA0[2]:
        rA0, rB0 = rB0.copy(), rA0.copy()

    shape_str = str(mi["shape"])
    potMod = bool(get_from_dict(mi, "potMod", dtype=bool, default=False))
    MCF = bool(get_from_dict(mi, "MCF", dtype=bool, default=False))
    gamma = float(get_from_dict(mi, "gamma", default=0.0))

    rAB = rB0 - rA0
    l = float(np.linalg.norm(rAB))

    if heading != 0.0:
        c, s = np.cos(np.deg2rad(heading)), np.sin(np.deg2rad(heading))
        rotMat = np.array([[c, -s, 0.0], [s, c, 0.0], [0.0, 0.0, 1.0]])
        rA0 = rotMat @ rA0
        rB0 = rotMat @ rB0
        if rAB[0] == 0.0 and rAB[1] == 0.0:  # vertical: heading becomes twist
            gamma += heading

    st = np.array(mi["stations"], dtype=float)
    n = len(st)
    if n < 2:
        raise ValueError("At least two station entries must be provided")
    if sorted(st.tolist()) != st.tolist():
        raise ValueError(f"Member {name}: station list not ascending")
    stations = (st - st[0]) / (st[-1] - st[0]) * l

    if shape_str[0].lower() == "c":
        shape = "circular"
        d = np.asarray(get_from_dict(mi, "d", shape=n), dtype=float)
        gamma = 0.0
    elif shape_str[0].lower() == "r":
        shape = "rectangular"
        d = np.asarray(get_from_dict(mi, "d", shape=[n, 2]), dtype=float)
    else:
        raise ValueError("shape must be circular or rectangular")

    if MCF and shape != "circular":
        MCF = False

    t = np.asarray(get_from_dict(mi, "t", shape=n), dtype=float)
    rho_shell = float(get_from_dict(mi, "rho_shell", shape=0, default=8500.0))

    st_fill = np.asarray(get_from_dict(mi, "l_fill", shape=n - 1, default=0), dtype=float)
    for i in range(n - 1):
        if st_fill[i] < 0:
            raise ValueError(f"Member {name}: negative ballast level in section {i+1}")
        if st_fill[i] > st[i + 1] - st[i]:
            raise ValueError(f"Member {name}: ballast exceeds section {i+1} length")
    l_fill = st_fill / (st[-1] - st[0]) * l

    rho_fill = get_from_dict(mi, "rho_fill", shape=-1, default=1025)
    if np.isscalar(rho_fill):
        rho_fill = np.zeros(n - 1) + rho_fill
    else:
        rho_fill = np.asarray(rho_fill, dtype=float)
        if len(rho_fill) != n - 1:
            raise ValueError(f"Member {name}: rho_fill must have {n-1} entries")

    # drag / added-mass coefficients at stations
    Cd_q = np.asarray(get_from_dict(mi, "Cd_q", shape=n, default=0.0), float)
    Cd_p1 = np.asarray(get_from_dict(mi, "Cd", shape=n, default=0.6, index=0), float)
    Cd_p2 = np.asarray(get_from_dict(mi, "Cd", shape=n, default=0.6, index=1), float)
    Cd_End = np.asarray(get_from_dict(mi, "CdEnd", shape=n, default=0.6), float)
    Ca_q = np.asarray(get_from_dict(mi, "Ca_q", shape=n, default=0.0), float)
    Ca_p1 = np.asarray(get_from_dict(mi, "Ca", shape=n, default=0.97, index=0), float)
    Ca_p2 = np.asarray(get_from_dict(mi, "Ca", shape=n, default=0.97, index=1), float)
    Ca_End = np.asarray(get_from_dict(mi, "CaEnd", shape=n, default=0.6), float)

    # ----- strip discretization (reference: raft_member.py:169-216) -----
    dorsl = [d[i] for i in range(n)]  # per-station diameter or side pair
    dlsMax = float(np.atleast_1d(get_from_dict(mi, "dlsMax", shape=-1, default=5))[0])

    ls = [0.0]
    dls = [0.0]
    ds = [0.5 * dorsl[0]]
    drs = [0.5 * dorsl[0]]
    for i in range(1, n):
        lstrip = stations[i] - stations[i - 1]
        if lstrip > 0.0:
            nseg = int(np.ceil(lstrip / dlsMax))
            dlstrip = lstrip / nseg
            m = 0.5 * (dorsl[i] - dorsl[i - 1]) / lstrip
            ls += [stations[i - 1] + dlstrip * (0.5 + j) for j in range(nseg)]
            dls += [dlstrip] * nseg
            ds += [dorsl[i - 1] + dlstrip * 2 * m * (0.5 + j) for j in range(nseg)]
            drs += [dlstrip * m] * nseg
        else:  # flat transition: single zero-length strip
            ls += [stations[i - 1]]
            dls += [0.0]
            ds += [0.5 * (dorsl[i - 1] + dorsl[i])]
            drs += [0.5 * (dorsl[i] - dorsl[i - 1])]
    # end-B strip
    ls += [stations[-1]]
    dls += [0.0]
    ds += [0.5 * dorsl[-1]]
    drs += [-0.5 * dorsl[-1]]

    ls = np.array(ls, float)
    dls = np.array(dls, float)
    ds = np.array(ds, float)
    drs = np.array(drs, float)
    ns = len(ls)

    geom = MemberGeometry(
        name=name, shape=shape, potMod=potMod, MCF=MCF, gamma=gamma,
        rA0=rA0, rB0=rB0, l=l, stations=stations, d=d, t=t,
        rho_shell=rho_shell, l_fill=l_fill, rho_fill=rho_fill,
        ns=ns, ls=ls, dls=dls, ds=ds, drs=drs,
        Cd_q_n=np.interp(ls, stations, Cd_q),
        Cd_p1_n=np.interp(ls, stations, Cd_p1),
        Cd_p2_n=np.interp(ls, stations, Cd_p2),
        Cd_End_n=np.interp(ls, stations, Cd_End),
        Ca_q_n=np.interp(ls, stations, Ca_q),
        Ca_p1_n=np.interp(ls, stations, Ca_p1),
        Ca_p2_n=np.interp(ls, stations, Ca_p2),
        Ca_End_n=np.interp(ls, stations, Ca_End),
    )
    _resolve_caps(geom, mi, st)
    return geom


def _resolve_caps(geom: MemberGeometry, mi: dict, st_raw: np.ndarray) -> None:
    """Resolve end cap / bulkhead diameters ahead of time (reference:
    raft_member.py:553-700, geometry-only part).  Rectangular caps store
    side pairs in cap_dA..cap_dBi with shape (ncap, 2)."""
    cap_st_raw = get_from_dict(mi, "cap_stations", shape=-1, default=[])
    cap_st_raw = np.atleast_1d(np.asarray(cap_st_raw, float))
    ncap = len(cap_st_raw)
    if ncap == 0:
        return
    cap_t = np.atleast_1d(np.asarray(get_from_dict(mi, "cap_t", shape=ncap), float))
    if geom.circular:
        cap_d_in = np.atleast_1d(np.asarray(
            get_from_dict(mi, "cap_d_in", shape=ncap, default=np.zeros(ncap)), float))
        d_in = geom.d - 2 * geom.t  # inner diameter profile at stations
    else:
        cap_d_in = np.asarray(
            get_from_dict(mi, "cap_d_in", shape=[ncap, 2], default=np.zeros([ncap, 2])), float)
        d_in = geom.d - 2 * geom.t[:, None]
    cap_L = (cap_st_raw - st_raw[0]) / (st_raw[-1] - st_raw[0]) * geom.l

    stations = geom.stations

    def interp_d(x):
        if geom.circular:
            return np.interp(x, stations, d_in)
        return np.stack([np.interp(x, stations, d_in[:, k]) for k in range(2)], -1)

    kinds, dAs, dBs, dAis, dBis = [], [], [], [], []
    for i in range(ncap):
        L, h, hole = cap_L[i], cap_t[i], cap_d_in[i]
        if L == stations[0]:
            kind = _CAP_BOTTOM
            dA = d_in[0]
            dB = interp_d(L + h)
            dAi = hole
            dBi = dB * _safe_ratio(dAi, dA)
        elif L == stations[-1]:
            kind = _CAP_TOP
            dA = interp_d(L - h)
            dB = d_in[-1]
            dBi = hole
            dAi = dA * _safe_ratio(dBi, dB)
        elif (stations[0] < L < stations[0] + h) or (stations[-1] - h < L < stations[-1]):
            raise ValueError(f"Member {geom.name}: cap at {L} overlaps member end")
        elif i < ncap - 1 and cap_L[i] == cap_L[i + 1]:
            # step discontinuity (duplicated cap station): an end cap
            # going DOWN from the lower segment.  NOTE the reference
            # indexes the per-station inner-diameter array by the CAP
            # index here (raft_member.py:584 `dB = d[i]`) — valid only
            # when caps align 1:1 with stations; replicated verbatim.
            kind = _CAP_MIDDLE        # positioned like a middle bulkhead
            dA = interp_d(L - h)
            dB = d_in[i]
            dBi = hole
            dAi = dA * _safe_ratio(dBi, dB)
        elif i > 0 and cap_L[i] == cap_L[i - 1]:
            # step discontinuity: the matching end cap going UP from the
            # upper segment (reference raft_member.py:588-592, same
            # cap-index quirk)
            kind = _CAP_MIDDLE
            dA = d_in[i]
            dB = interp_d(L + h)
            dAi = hole
            dBi = dB * _safe_ratio(dAi, dA)
        else:
            kind = _CAP_MIDDLE
            dA = interp_d(L - h / 2)
            dB = interp_d(L + h / 2)
            dM = interp_d(L)
            dAi = dA * _safe_ratio(hole, dM)
            dBi = dB * _safe_ratio(hole, dM)
        kinds.append(kind)
        dAs.append(dA)
        dBs.append(dB)
        dAis.append(dAi)
        dBis.append(dBi)

    geom.cap_kind = np.array(kinds, int)
    geom.cap_L = cap_L
    geom.cap_h = cap_t
    geom.cap_dA = np.array(dAs, float)
    geom.cap_dB = np.array(dBs, float)
    geom.cap_dAi = np.array(dAis, float)
    geom.cap_dBi = np.array(dBis, float)


def _safe_ratio(a, b):
    b = np.asarray(b, float)
    return np.asarray(a, float) / np.where(b == 0.0, 1.0, b) * (b != 0.0)


# --------------------------------------------------------------------------
# pose
# --------------------------------------------------------------------------

def member_pose(geom: MemberGeometry, r6=None):
    """Member pose under a 6-DOF platform displacement (reference:
    raft_member.py:245-304).  Returns a dict of jnp arrays: rA, rB, q, p1,
    p2, R, r (ns,3), qMat, p1Mat, p2Mat.
    """
    if r6 is None:
        r6 = jnp.zeros(6)
    r6 = jnp.asarray(r6, float)
    rA0 = jnp.asarray(geom.rA0)
    rB0 = jnp.asarray(geom.rB0)
    rAB0 = rB0 - rA0
    q0 = rAB0 / jnp.linalg.norm(rAB0)

    beta = jnp.arctan2(q0[1], q0[0])
    phi = jnp.arctan2(jnp.sqrt(q0[0] ** 2 + q0[1] ** 2), q0[2])
    s1, c1 = jnp.sin(beta), jnp.cos(beta)
    s2, c2 = jnp.sin(phi), jnp.cos(phi)
    s3, c3 = jnp.sin(jnp.deg2rad(geom.gamma)), jnp.cos(jnp.deg2rad(geom.gamma))
    # Z1Y2Z3 Euler rotation (reference: raft_member.py:272-274)
    R0 = jnp.array([
        [c1 * c2 * c3 - s1 * s3, -c3 * s1 - c1 * c2 * s3, c1 * s2],
        [c1 * s3 + c2 * c3 * s1, c1 * c3 - c2 * s1 * s3, s1 * s2],
        [-c3 * s2, s2 * s3, c2],
    ])
    p1_0 = R0 @ jnp.array([1.0, 0.0, 0.0])

    R_platform = rotation_matrix(r6[3], r6[4], r6[5])
    R = R_platform @ R0
    q = R_platform @ q0
    p1 = R_platform @ p1_0
    p2 = jnp.cross(q, p1)

    rA = r6[:3] + R_platform @ rA0
    rB = r6[:3] + R_platform @ rB0
    rAB = rB - rA
    frac = jnp.asarray(geom.ls) / geom.l
    r = rA + frac[:, None] * rAB

    return dict(
        rA=rA, rB=rB, q=q, p1=p1, p2=p2, R=R, r=r,
        qMat=vec_vec_trans(q), p1Mat=vec_vec_trans(p1), p2Mat=vec_vec_trans(p2),
    )


# --------------------------------------------------------------------------
# inertia
# --------------------------------------------------------------------------

def member_inertia(geom: MemberGeometry, pose, rPRP=jnp.zeros(3),
                   l_fill=None, rho_fill=None):
    """Mass properties about the PRP (reference: raft_member.py:307-707).

    Returns dict(mass, center, mshell, mfill, pfill, M_struc) where mfill /
    pfill are per-section arrays.  ``l_fill``/``rho_fill`` may override the
    geometry's static ballast (used by the ballast-trim adjusters) — they
    are traced values, so ballast trim can run inside jit.
    """
    st = jnp.asarray(geom.stations)
    lsec = st[1:] - st[:-1]
    valid = lsec > 0.0
    lsafe = jnp.where(valid, lsec, 1.0)
    l_fill = jnp.asarray(geom.l_fill if l_fill is None else l_fill, float)
    rho_fill = jnp.asarray(geom.rho_fill if rho_fill is None else rho_fill, float)
    rho_shell = geom.rho_shell

    if geom.circular:
        dA, dB = jnp.asarray(geom.d[:-1]), jnp.asarray(geom.d[1:])
        dAi = dA - 2 * jnp.asarray(geom.t[:-1])
        dBi = dB - 2 * jnp.asarray(geom.t[1:])
        V_outer, hco = frustum_vcv_circ(dA, dB, lsec)
        V_inner, hci = frustum_vcv_circ(dAi, dBi, lsec)
        dBi_fill = (dBi - dAi) * (l_fill / lsafe) + dAi
        v_fill, hc_fill = frustum_vcv_circ(dAi, dBi_fill, l_fill)
        IxxO, IzzO = frustum_moi_circ(dA, dB, lsec, rho_shell)
        IxxI, IzzI = frustum_moi_circ(dAi, dBi, lsec, rho_shell)
        IxxF, IzzF = frustum_moi_circ(dAi, dBi_fill, l_fill, rho_fill)
        IyyO, IyyI, IyyF = IxxO, IxxI, IxxF
    else:
        slA, slB = jnp.asarray(geom.d[:-1]), jnp.asarray(geom.d[1:])
        slAi = slA - 2 * jnp.asarray(geom.t[:-1, None])
        slBi = slB - 2 * jnp.asarray(geom.t[1:, None])
        V_outer, hco = frustum_vcv_rect(slA, slB, lsec)
        V_inner, hci = frustum_vcv_rect(slAi, slBi, lsec)
        slBi_fill = (slBi - slAi) * (l_fill / lsafe)[:, None] + slAi
        v_fill, hc_fill = frustum_vcv_rect(slAi, slBi_fill, l_fill)
        IxxO, IyyO, IzzO = frustum_moi_rect(slA, slB, lsec, rho_shell)
        IxxI, IyyI, IzzI = frustum_moi_rect(slAi, slBi, lsec, rho_shell)
        IxxF, IyyF, IzzF = frustum_moi_rect(slAi, slBi_fill, l_fill, rho_fill)

    v_shell = V_outer - V_inner
    m_shell = v_shell * rho_shell
    vs_safe = jnp.where(v_shell != 0.0, v_shell, 1.0)
    hc_shell = (hco * V_outer - hci * V_inner) / vs_safe
    m_fill = v_fill * rho_fill
    mass_s = m_shell + m_fill
    mass_safe = jnp.where(mass_s != 0.0, mass_s, 1.0)
    hc = (hc_fill * m_fill + hc_shell * m_shell) / mass_safe

    # transverse MoI about section CG via parallel axis (reference :473-476)
    Ixx = (IxxO - IxxI) + IxxF - mass_s * hc**2
    Iyy = (IyyO - IyyI) + IyyF - mass_s * hc**2
    Izz = (IzzO - IzzI) + IzzF

    # zero out invalid (zero-length) sections — EXCEPT the local MoI:
    # the reference's l==0 branch (raft_member.py:420-426) zeroes
    # mass/center but not the loop-carried Ixx/Iyy/Izz, so the PREVIOUS
    # segment's local inertia tensor is added a second time with zero
    # mass and center=0, i.e. untranslated about the PRP
    # (raft_member.py:539-548).  Replicated verbatim for parity: on
    # OC4semi's stepped offset columns this phantom term is ~1.6e7 (Ixx)
    # / 3.0e7 (Izz) kg-m^2 per column and is visible in the example's
    # regression data.
    mass_s = jnp.where(valid, mass_s, 0.0)
    m_shell = jnp.where(valid, m_shell, 0.0)
    m_fill = jnp.where(valid, m_fill, 0.0)
    v_fill = jnp.where(valid, v_fill, 0.0)
    pfill = jnp.where(valid, rho_fill, 0.0)
    # LIMITATION (documented, advisor round 3): this shift-by-one
    # replication matches the reference's loop-carried variable only for
    # a SINGLE zero-length segment.  For two consecutive duplicated
    # stations the second invalid segment picks up the first invalid
    # segment's ~0 value, whereas the reference would re-add the last
    # valid segment's inertia again.  No shipped design has consecutive
    # duplicated stations; a forward-fill over invalid entries would be
    # needed if one ever does.
    Ixx = jnp.where(valid, Ixx, jnp.concatenate([jnp.zeros(1), Ixx[:-1]]))
    Iyy = jnp.where(valid, Iyy, jnp.concatenate([jnp.zeros(1), Iyy[:-1]]))
    Izz = jnp.where(valid, Izz, jnp.concatenate([jnp.zeros(1), Izz[:-1]]))

    center = pose["rA"] + pose["q"][None, :] * (st[:-1] + hc)[:, None] - rPRP
    center = jnp.where(valid[:, None], center, 0.0)

    R = pose["R"]
    M_struc = _assemble_inertia(mass_s, Ixx, Iyy, Izz, R, center)

    # ----- caps / bulkheads -----
    mshell_total = jnp.sum(m_shell)
    mass_center = jnp.sum(mass_s[:, None] * center, axis=0)
    if len(geom.cap_kind):
        h = jnp.asarray(geom.cap_h)
        rho_cap = rho_shell
        if geom.circular:
            V_o, hco_c = frustum_vcv_circ(geom.cap_dA, geom.cap_dB, h)
            V_i, hci_c = frustum_vcv_circ(geom.cap_dAi, geom.cap_dBi, h)
            IxxOc, IzzOc = frustum_moi_circ(geom.cap_dA, geom.cap_dB, h, rho_cap)
            IxxIc, IzzIc = frustum_moi_circ(geom.cap_dAi, geom.cap_dBi, h, rho_cap)
            IyyOc, IyyIc = IxxOc, IxxIc
        else:
            V_o, hco_c = frustum_vcv_rect(geom.cap_dA, geom.cap_dB, h)
            V_i, hci_c = frustum_vcv_rect(geom.cap_dAi, geom.cap_dBi, h)
            IxxOc, IyyOc, IzzOc = frustum_moi_rect(geom.cap_dA, geom.cap_dB, h, rho_cap)
            IxxIc, IyyIc, IzzIc = frustum_moi_rect(geom.cap_dAi, geom.cap_dBi, h, rho_cap)
        v_cap = V_o - V_i
        m_cap = v_cap * rho_cap
        vc_safe = jnp.where(v_cap != 0.0, v_cap, 1.0)
        hc_cap = (hco_c * V_o - hci_c * V_i) / vc_safe
        Ixx_c = (IxxOc - IxxIc) - m_cap * hc_cap**2
        Iyy_c = (IyyOc - IyyIc) - m_cap * hc_cap**2
        Izz_c = IzzOc - IzzIc

        kind = jnp.asarray(geom.cap_kind)
        # CG offset from the cap station along q (reference :676-681)
        off = jnp.where(kind == _CAP_BOTTOM, hc_cap,
                        jnp.where(kind == _CAP_TOP, -(h - hc_cap), -(h / 2 - hc_cap)))
        center_cap = pose["rA"] + pose["q"][None, :] * (jnp.asarray(geom.cap_L) + off)[:, None] - rPRP
        M_struc = M_struc + _assemble_inertia(m_cap, Ixx_c, Iyy_c, Izz_c, R, center_cap)
        mshell_total = mshell_total + jnp.sum(m_cap)
        mass_center = mass_center + jnp.sum(m_cap[:, None] * center_cap, axis=0)

    mass = M_struc[0, 0]
    center_total = mass_center / jnp.where(mass != 0.0, mass, 1.0)
    return dict(mass=mass, center=center_total, mshell=mshell_total,
                mfill=m_fill, pfill=pfill, vfill=v_fill, M_struc=M_struc)


def _assemble_inertia(mass, Ixx, Iyy, Izz, R, center):
    """Per-section local mass matrix (diag mass + rotated MoI about its CG)
    translated to the PRP and summed (reference: raft_member.py:537-547)."""
    nsec = mass.shape[0]
    I_loc = jnp.zeros((nsec, 3, 3))
    I_loc = I_loc.at[:, 0, 0].set(Ixx).at[:, 1, 1].set(Iyy).at[:, 2, 2].set(Izz)
    I_rot = R @ I_loc @ R.T   # broadcast over sections
    Mmat = jnp.zeros((nsec, 6, 6))
    for k in range(3):
        Mmat = Mmat.at[:, k, k].set(mass)
    Mmat = Mmat.at[:, 3:, 3:].set(I_rot)
    return jnp.sum(translate_matrix_6to6(Mmat, center), axis=0)


# --------------------------------------------------------------------------
# hydrostatics
# --------------------------------------------------------------------------

def member_hydrostatics(geom: MemberGeometry, pose, rPRP=jnp.zeros(3),
                        rho=1025.0, g=9.81):
    """Buoyancy wrench, hydrostatic stiffness, displaced volume, CB, and
    waterplane properties (reference: raft_member.py:712-874).

    Vectorized over sections with the reference's three cases as masks:
    crossing the waterplane (rA_z*rB_z <= 0), fully submerged, dry.  The
    waterplane outputs (AWP/IWP/xWP/yWP) take the *last* crossing section's
    values, matching the reference's loop-overwrite semantics.
    """
    st = jnp.asarray(geom.stations)
    q = pose["q"]
    rHS_ref = jnp.array([rPRP[0], rPRP[1], 0.0])
    rA_s = pose["rA"] + q[None, :] * st[:-1, None] - rHS_ref   # (nsec,3)
    rB_s = pose["rA"] + q[None, :] * st[1:, None] - rHS_ref
    zA, zB = rA_s[:, 2], rB_s[:, 2]

    cross = zA * zB <= 0.0
    submerged = (~cross) & (zA <= 0.0) & (zB <= 0.0)

    beta = jnp.arctan2(q[1], q[0])
    phi = jnp.arctan2(jnp.sqrt(q[0] ** 2 + q[1] ** 2), q[2])
    cosPhi, sinPhi, tanPhi = jnp.cos(phi), jnp.sin(phi), jnp.tan(phi)
    cosBeta, sinBeta = jnp.cos(beta), jnp.sin(beta)
    cosPhi_safe = jnp.where(cosPhi == 0.0, 1.0, cosPhi)

    dz = jnp.where(zB - zA == 0.0, 1.0, zB - zA)
    xWP_s = rA_s[:, 0] + (0.0 - zA) * (rB_s[:, 0] - rA_s[:, 0]) / dz
    yWP_s = rA_s[:, 1] + (0.0 - zA) * (rB_s[:, 1] - rA_s[:, 1]) / dz

    if geom.circular:
        d = jnp.asarray(geom.d)
        # NOTE: the reference interpolates the waterplane diameter with the
        # upper/lower values swapped (raft_member.py:769) — replicated for
        # parity; exact for untapered sections.
        dWP = d[1:] + (0.0 - zA) * (d[:-1] - d[1:]) / dz
        AWP_s = (jnp.pi / 4) * dWP**2
        IWP_s = (jnp.pi / 64) * dWP**4
        IxWP_s, IyWP_s = IWP_s, IWP_s
    else:
        sl = jnp.asarray(geom.d)
        slWP = sl[1:] + (0.0 - zA)[:, None] * (sl[:-1] - sl[1:]) / dz[:, None]
        AWP_s = slWP[:, 0] * slWP[:, 1]
        IWP_s = jnp.zeros_like(AWP_s)  # reference leaves IWP at 0 for rect
        IxWP_l = (1.0 / 12.0) * slWP[:, 0] * slWP[:, 1] ** 3
        IyWP_l = (1.0 / 12.0) * slWP[:, 0] ** 3 * slWP[:, 1]
        # rotate the local waterplane inertia tensor into global axes
        R = pose["R"]
        nsec = AWP_s.shape[0]
        Iloc = jnp.zeros((nsec, 3, 3))
        Iloc = Iloc.at[:, 0, 0].set(IxWP_l).at[:, 1, 1].set(IyWP_l)
        Irot = R @ Iloc @ R.T
        IxWP_s = Irot[:, 0, 0]
        IyWP_s = Irot[:, 1, 1]

    LWP = jnp.abs(zA / cosPhi_safe)

    if geom.circular:
        V_cr, hc_cr = frustum_vcv_circ(jnp.asarray(geom.d[:-1]), dWP, LWP)
        V_sub, hc_sub = frustum_vcv_circ(jnp.asarray(geom.d[:-1]), jnp.asarray(geom.d[1:]), st[1:] - st[:-1])
    else:
        V_cr, hc_cr = frustum_vcv_rect(jnp.asarray(geom.d[:-1]), slWP, LWP)
        V_sub, hc_sub = frustum_vcv_rect(jnp.asarray(geom.d[:-1]), jnp.asarray(geom.d[1:]), st[1:] - st[:-1])

    r_center_cr = rA_s + q[None, :] * hc_cr[:, None]
    r_center_sub = rA_s + q[None, :] * hc_sub[:, None]

    # ---- crossing-section contributions ----
    Fz_cr = rho * g * V_cr
    if geom.circular:
        M_incline = -rho * g * jnp.pi * (dWP**2 / 32.0 * (2.0 + tanPhi**2)
                                         + 0.5 * (zA / cosPhi_safe) ** 2) * sinPhi
    else:
        M_incline = jnp.zeros_like(Fz_cr)
    Mx_cr = M_incline * (-sinBeta)
    My_cr = M_incline * (cosBeta)

    cr = cross.astype(float)
    Fvec = jnp.zeros(6)
    Fvec = Fvec.at[2].add(jnp.sum(cr * Fz_cr))
    Fvec = Fvec.at[3].add(jnp.sum(cr * (Mx_cr + Fz_cr * rA_s[:, 1])))
    Fvec = Fvec.at[4].add(jnp.sum(cr * (My_cr - Fz_cr * rA_s[:, 0])))

    Cmat = jnp.zeros((6, 6))
    c22 = rho * g * AWP_s / cosPhi_safe
    Cmat = Cmat.at[2, 2].add(jnp.sum(cr * c22))
    Cmat = Cmat.at[2, 3].add(jnp.sum(cr * rho * g * (-AWP_s * yWP_s)))
    Cmat = Cmat.at[2, 4].add(jnp.sum(cr * rho * g * (AWP_s * xWP_s)))
    Cmat = Cmat.at[3, 2].add(jnp.sum(cr * rho * g * (-AWP_s * yWP_s)))
    Cmat = Cmat.at[3, 3].add(jnp.sum(cr * rho * g * (IxWP_s + AWP_s * yWP_s**2)))
    Cmat = Cmat.at[3, 4].add(jnp.sum(cr * rho * g * (AWP_s * xWP_s * yWP_s)))
    Cmat = Cmat.at[4, 2].add(jnp.sum(cr * rho * g * (AWP_s * xWP_s)))
    Cmat = Cmat.at[4, 3].add(jnp.sum(cr * rho * g * (AWP_s * xWP_s * yWP_s)))
    Cmat = Cmat.at[4, 4].add(jnp.sum(cr * rho * g * (IyWP_s + AWP_s * xWP_s**2)))
    Cmat = Cmat.at[3, 3].add(jnp.sum(cr * rho * g * V_cr * r_center_cr[:, 2]))
    Cmat = Cmat.at[4, 4].add(jnp.sum(cr * rho * g * V_cr * r_center_cr[:, 2]))

    # ---- fully-submerged contributions ----
    sub = submerged.astype(float)
    Fsub = translate_force_3to6(
        jnp.stack([jnp.zeros_like(V_sub), jnp.zeros_like(V_sub), rho * g * V_sub], -1),
        r_center_sub)
    Fvec = Fvec + jnp.sum(sub[:, None] * Fsub, axis=0)
    Cmat = Cmat.at[3, 3].add(jnp.sum(sub * rho * g * V_sub * r_center_sub[:, 2]))
    Cmat = Cmat.at[4, 4].add(jnp.sum(sub * rho * g * V_sub * r_center_sub[:, 2]))

    V_UW = jnp.sum(cr * V_cr + sub * V_sub)
    r_centerV = jnp.sum((cr * V_cr)[:, None] * r_center_cr
                        + (sub * V_sub)[:, None] * r_center_sub, axis=0)
    r_center = jnp.where(V_UW > 0, r_centerV / jnp.where(V_UW > 0, V_UW, 1.0), 0.0)

    # last crossing section wins the waterplane scalars
    nsec = zA.shape[0]
    idxs = jnp.arange(nsec)
    last_cross = jnp.max(jnp.where(cross, idxs, -1))
    any_cross = last_cross >= 0
    sel = jnp.clip(last_cross, 0, nsec - 1)
    AWP = jnp.where(any_cross, AWP_s[sel], 0.0)
    IWP = jnp.where(any_cross, IWP_s[sel], 0.0)
    xWP = jnp.where(any_cross, xWP_s[sel], 0.0)
    yWP = jnp.where(any_cross, yWP_s[sel], 0.0)

    return dict(Fvec=Fvec, Cmat=Cmat, V_UW=V_UW, r_center=r_center,
                AWP=AWP, IWP=IWP, xWP=xWP, yWP=yWP)


# --------------------------------------------------------------------------
# strip-theory added mass & inertial-excitation coefficients
# --------------------------------------------------------------------------

def _node_volumes(geom: MemberGeometry, r_nodes):
    """Per-node side volume (with partial-submergence scaling) and end
    volume/area terms (reference: raft_member.py:922-949)."""
    dls = jnp.asarray(geom.dls)
    if geom.circular:
        ds = jnp.asarray(geom.ds)
        drs = jnp.asarray(geom.drs)
        v_side = 0.25 * jnp.pi * ds**2 * dls
        v_end = jnp.pi / 12.0 * jnp.abs((ds + drs) ** 3 - (ds - drs) ** 3)
        a_i = jnp.pi * ds * drs
    else:
        ds = jnp.asarray(geom.ds)
        drs = jnp.asarray(geom.drs)
        v_side = ds[:, 0] * ds[:, 1] * dls
        dmean_p = jnp.mean(ds + drs, axis=1)
        dmean_m = jnp.mean(ds - drs, axis=1)
        v_end = jnp.pi / 12.0 * (dmean_p**3 - dmean_m**3)
        a_i = ((ds[:, 0] + drs[:, 0]) * (ds[:, 1] + drs[:, 1])
               - (ds[:, 0] - drs[:, 0]) * (ds[:, 1] - drs[:, 1]))
    # partial submergence: if the strip pokes out of the water, scale volume
    z = r_nodes[:, 2]
    dls_safe = jnp.where(dls == 0.0, 1.0, dls)
    scale = jnp.where(z + 0.5 * dls > 0.0, (0.5 * dls - z) / dls_safe, 1.0)
    v_side = v_side * scale
    return v_side, v_end, a_i


def member_hydro_constants(geom: MemberGeometry, pose, r_ref=jnp.zeros(3),
                           rho=1025.0):
    """Strip-theory added mass and Froude-Krylov/inertial-excitation
    matrices (reference: raft_member.py:877-1050, non-MCF path).

    Returns dict with per-node Amat, Imat (ns,3,3), a_i (ns,), plus the
    6x6 A_hydro and I_hydro accumulated about ``r_ref``.
    """
    r = pose["r"]
    submerged = r[:, 2] < 0.0
    active = submerged & (not geom.potMod)

    v_side, v_end, a_i = _node_volumes(geom, r)

    Ca_p1 = jnp.asarray(geom.Ca_p1_n)
    Ca_p2 = jnp.asarray(geom.Ca_p2_n)
    Ca_End = jnp.asarray(geom.Ca_End_n)

    p1Mat, p2Mat, qMat = pose["p1Mat"], pose["p2Mat"], pose["qMat"]
    Amat = (rho * v_side * Ca_p1)[:, None, None] * p1Mat \
        + (rho * v_side * Ca_p2)[:, None, None] * p2Mat \
        + (rho * v_end * Ca_End)[:, None, None] * qMat
    # Froude-Krylov Cm = 1 + Ca on the sides; end term has no +1 because
    # dynamic pressure is handled separately (reference :1014-1044)
    Imat = (rho * v_side * (1.0 + Ca_p1))[:, None, None] * p1Mat \
        + (rho * v_side * (1.0 + Ca_p2))[:, None, None] * p2Mat \
        + (rho * v_end * Ca_End)[:, None, None] * qMat

    mask = active[:, None, None].astype(float)
    Amat = Amat * mask
    Imat = Imat * mask
    a_i = a_i * active.astype(float)

    offsets = r - jnp.asarray(r_ref)[None, :3]
    A_hydro = jnp.sum(translate_matrix_3to6(Amat, offsets), axis=0)
    I_hydro = jnp.sum(translate_matrix_3to6(Imat, offsets), axis=0)
    return dict(Amat=Amat, Imat=Imat, a_i=a_i, A_hydro=A_hydro, I_hydro=I_hydro)
