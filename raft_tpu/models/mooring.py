"""Quasi-static catenary mooring as differentiable jnp kernels.

TPU-first replacement for the MoorPy subset the reference uses
(reference: raft/raft_fowt.py:166-189, 275-288 and raft/raft_model.py:
801-803 — System.parseYAML, Body.setPosition, solveEquilibrium,
getCoupledStiffnessA, Body.getForces(lines_only=True),
getCoupledStiffness(..., tensions=True), getTensions).

Design: a mooring system is a static `MooringSystem` of numpy arrays
(anchor positions, body-frame fairlead positions, per-line unstretched
length / axial stiffness / wet weight).  The fairlead force comes from the
classic two-segment analytic catenary (elastic, frictionless seabed) solved
with a FIXED-iteration Newton in jnp — shape-stable, vmapped over lines,
and forward/reverse differentiable, so the 6x6 coupled stiffness and the
line-tension Jacobian are exact `jax.jacfwd`s of the wrench instead of the
reference's hand-coded analytic derivatives.  All lines solve in parallel;
systems batch over design variants.

The catenary formulation follows the standard quasi-static equations
(Jonkman 2007, MAP/MoorPy lineage): given horizontal span XF, vertical
span ZF (fairlead above anchor), unstretched length L, axial stiffness EA,
and submerged weight/length w, find fairlead force components (H, V):

  no seabed contact (V >= wL):
    XF = (H/w)[asinh(V/H) - asinh((V-wL)/H)] + HL/EA
    ZF = (H/w)[sqrt(1+(V/H)^2) - sqrt(1+((V-wL)/H)^2)] + (VL - wL^2/2)/EA
  partial seabed contact (V < wL), frictionless:
    XF = (L - V/w) + (H/w) asinh(V/H) + HL/EA
    ZF = (H/w)[sqrt(1+(V/H)^2) - 1] + V^2/(2 EA w)
"""
from __future__ import annotations

from dataclasses import dataclass

import numpy as np
import jax
import jax.numpy as jnp

from raft_tpu.ops.transforms import rotation_matrix, translate_force_3to6

_G = 9.81
_RHO = 1025.0
_NEWTON_ITERS = 40


@dataclass
class MooringSystem:
    """Static description of one body's mooring (numpy, built at parse time)."""

    depth: float
    rAnchor: np.ndarray      # (nl,3) anchor positions, global
    rFair0: np.ndarray       # (nl,3) fairlead positions in the body frame
    L: np.ndarray            # (nl,) unstretched lengths
    EA: np.ndarray           # (nl,) axial stiffness
    w: np.ndarray            # (nl,) submerged weight per length [N/m]
    d_vol: np.ndarray        # (nl,) volume-equivalent diameter
    m_lin: np.ndarray        # (nl,) mass per length
    Cd_t: np.ndarray         # (nl,) transverse drag coefficient
    Cd_a: np.ndarray         # (nl,) tangential drag coefficient
    rho: float = _RHO        # water density (for line current drag)

    @property
    def n_lines(self) -> int:
        return len(self.L)


def parse_mooring(moor: dict, rho: float = _RHO, g: float = _G,
                  trans=(0.0, 0.0), rot: float = 0.0):
    """Build a mooring system from the design['mooring'] YAML dict
    (schema per reference designs/*.yaml: water_depth, points with
    type fixed|vessel|free, lines endA/endB, line_types).

    Simple anchor->fairlead topologies build the vectorized
    `MooringSystem`.  Topologies with FREE intermediate points or
    multi-segment composite lines build a single-body
    `mooring_array.ArrayMooring` (same differentiable catenary, plus a
    free-point equilibrium solve) — the MoorPy-general path the reference
    gets from System.parseYAML (raft_fowt.py:166-189).

    ``trans``/``rot`` apply the reference's array-placement transform
    (reference: raft_fowt.py:185): rotate the whole system about z by
    ``rot`` degrees, then translate anchors in x,y.  Fairleads stay in the
    body frame (the body itself carries the placement).
    """
    depth = float(moor["water_depth"])
    types = {lt["name"]: lt for lt in moor["line_types"]}
    points = {p["name"]: p for p in moor["points"]}

    c, s = np.cos(np.deg2rad(rot)), np.sin(np.deg2rad(rot))
    Rz = np.array([[c, -s, 0.0], [s, c, 0.0], [0.0, 0.0, 1.0]])

    def ptype(p):
        t = p["type"].lower()
        if t.startswith("vessel") or t.startswith("body") \
                or t.startswith("coupled"):
            return "vessel"
        if t.startswith("free") or t.startswith("connect"):
            return "free"
        return "fixed"

    simple = all(
        {ptype(points[ln["endA"]]), ptype(points[ln["endB"]])}
        == {"fixed", "vessel"}
        for ln in moor["lines"])

    def line_props(ln):
        lt = types[ln["type"]]
        d = float(lt["diameter"])
        m = float(lt["mass_density"])
        return dict(L=float(ln["length"]), EA=float(lt["stiffness"]),
                    w=(m - rho * np.pi / 4 * d**2) * g, d=d, m=m,
                    Cd_t=float(lt.get("transverse_drag", 0.0)),
                    Cd_a=float(lt.get("tangential_drag", 0.0)))

    if simple:
        rAnchor, rFair0 = [], []
        L, EA, w, d_vol, m_lin, Cd_t, Cd_a = [], [], [], [], [], [], []
        for ln in moor["lines"]:
            pA, pB = points[ln["endA"]], points[ln["endB"]]
            if ptype(pA) == "vessel":
                pA, pB = pB, pA
            anchor = Rz @ np.array(pA["location"], float)
            anchor[0] += trans[0]
            anchor[1] += trans[1]
            fair = Rz @ np.array(pB["location"], float)
            rAnchor.append(anchor)
            rFair0.append(fair)
            lp = line_props(ln)
            L.append(lp["L"])
            EA.append(lp["EA"])
            w.append(lp["w"])
            d_vol.append(lp["d"])
            m_lin.append(lp["m"])
            Cd_t.append(lp["Cd_t"])
            Cd_a.append(lp["Cd_a"])

        return MooringSystem(
            depth=depth,
            rAnchor=np.array(rAnchor), rFair0=np.array(rFair0),
            L=np.array(L), EA=np.array(EA), w=np.array(w),
            d_vol=np.array(d_vol), m_lin=np.array(m_lin),
            Cd_t=np.array(Cd_t), Cd_a=np.array(Cd_a), rho=rho,
        )

    # ----- general topology: single-body ArrayMooring -----
    from raft_tpu.models import mooring_array as ma

    names = list(points.keys())
    attach, r0, pmass, pvol = [], [], [], []
    for name in names:
        p = points[name]
        t = ptype(p)
        loc = np.array(p["location"], float)
        if t == "vessel":
            attach.append(0)
            r0.append(Rz @ loc)          # body frame (placement on body)
        else:
            attach.append(ma.ATTACH_FIXED if t == "fixed" else ma.ATTACH_FREE)
            loc = Rz @ loc
            loc[0] += trans[0]
            loc[1] += trans[1]
            r0.append(loc)
        pmass.append(float(p.get("mass", 0.0)))
        pvol.append(float(p.get("volume", 0.0)))
    attach = np.array(attach)
    r0 = np.array(r0)
    free_idx = np.full(len(names), -1)
    free_idx[attach == ma.ATTACH_FREE] = np.arange(
        (attach == ma.ATTACH_FREE).sum())
    name2row = {n: i for i, n in enumerate(names)}

    iA, iB, L, EA, w = [], [], [], [], []
    d_vol, Cd_t, Cd_a = [], [], []
    for ln in moor["lines"]:
        lp = line_props(ln)
        iA.append(name2row[ln["endA"]])
        iB.append(name2row[ln["endB"]])
        L.append(lp["L"])
        EA.append(lp["EA"])
        w.append(lp["w"])
        d_vol.append(lp["d"])
        Cd_t.append(lp["Cd_t"])
        Cd_a.append(lp["Cd_a"])
    iA, iB = np.array(iA), np.array(iB)

    def on_seabed(ipt):
        return (attach[ipt] == ma.ATTACH_FIXED) & (r0[ipt, 2] <= -depth + 1.0)

    return ma.ArrayMooring(
        depth=depth, nbodies=1,
        attach=attach, r0=r0, pmass=np.array(pmass), pvol=np.array(pvol),
        free_idx=free_idx,
        iA=iA, iB=iB, L=np.array(L), EA=np.array(EA), w=np.array(w),
        contact_ok=on_seabed(iA) | on_seabed(iB), g=g, rho=rho,
        d_vol=np.array(d_vol), Cd_t=np.array(Cd_t), Cd_a=np.array(Cd_a),
    )


# --------------------------------------------------------------------------
# catenary kernel
# --------------------------------------------------------------------------

def _profile_spans(H, V, L, EA, w, contact_allowed=True):
    """(XF, ZF) reached by a line with fairlead force (H, V); both seabed
    branches evaluated and selected by mask (elementwise).

    ``contact_allowed`` gates the seabed-contact branch: it is only valid
    when the lower (anchor) end actually rests on the seabed.  For lines
    suspended between elevated points (shared farm lines, line segments
    between free junction points) pass False — the suspended-catenary
    formulas remain valid for a negative anchor-end vertical force
    (line sagging below the lower attachment)."""
    H = jnp.maximum(H, 1e-8)
    Va = V - w * L  # vertical force at anchor end (suspended case)
    s1 = jnp.sqrt(1.0 + (V / H) ** 2)
    s2 = jnp.sqrt(1.0 + (Va / H) ** 2)
    # fully suspended
    XF_s = (H / w) * (jnp.arcsinh(V / H) - jnp.arcsinh(Va / H)) + H * L / EA
    ZF_s = (H / w) * (s1 - s2) + (V * L - 0.5 * w * L**2) / EA
    # partial seabed contact (frictionless): length L - V/w on the bottom
    LB = L - V / w
    XF_c = LB + (H / w) * jnp.arcsinh(V / H) + H * L / EA
    ZF_c = (H / w) * (s1 - 1.0) + V**2 / (2.0 * EA * w)
    contact = (V < w * L) & contact_allowed
    return jnp.where(contact, XF_c, XF_s), jnp.where(contact, ZF_c, ZF_s)


def catenary_solve(XF, ZF, L, EA, w, contact_allowed=True):
    """Solve one line's fairlead force (H, V) from its spans.  Elementwise
    over any batch shape; fixed ``_NEWTON_ITERS`` damped-Newton iterations
    (shape-stable under jit/vmap, differentiable by unrolled iteration —
    converged Newton reproduces the implicit-function derivative).

    Returns dict(H, V, Va, Ha, TA, TB) — fairlead/anchor force components
    and tension magnitudes.
    """
    XF, ZF = jnp.asarray(XF, float), jnp.asarray(ZF, float)
    L, EA, w = jnp.asarray(L, float), jnp.asarray(EA, float), jnp.asarray(w, float)

    # standard initial guess (Jonkman 2007 quasi-static lineage)
    slack = L**2 - ZF**2
    XF_safe = jnp.where(XF > 0, XF, 1.0)
    lam = jnp.where(
        L**2 > XF**2 + ZF**2,
        jnp.sqrt(jnp.maximum(3.0 * (slack / XF_safe**2 - 1.0), 1e-8)),
        0.2,
    )
    H0 = jnp.maximum(jnp.abs(0.5 * w * XF / lam), 1e3)
    V0 = 0.5 * w * (ZF / jnp.tanh(lam) + L)

    contact_allowed = jnp.asarray(contact_allowed)

    def resid(x):
        Xc, Zc = _profile_spans(x[..., 0], x[..., 1], L, EA, w,
                                contact_allowed)
        return jnp.stack([Xc - XF, Zc - ZF], axis=-1)

    def newton_step(x, _):
        r = resid(x)
        # elementwise 2x2 Jacobian via jvp along the two coordinate
        # directions (exact, cheap, batch-shaped)
        e0 = jnp.zeros_like(x).at[..., 0].set(1.0)
        e1 = jnp.zeros_like(x).at[..., 1].set(1.0)
        _, dr_dH = jax.jvp(resid, (x,), (e0,))
        _, dr_dV = jax.jvp(resid, (x,), (e1,))
        det = dr_dH[..., 0] * dr_dV[..., 1] - dr_dV[..., 0] * dr_dH[..., 1]
        det = jnp.where(jnp.abs(det) < 1e-30, 1e-30, det)
        dH = (-r[..., 0] * dr_dV[..., 1] + r[..., 1] * dr_dV[..., 0]) / det
        dV = (-dr_dH[..., 0] * r[..., 1] + dr_dH[..., 1] * r[..., 0]) / det
        # damp: keep H positive
        Hn = x[..., 0] + dH
        Hn = jnp.where(Hn <= 0.0, 0.1 * x[..., 0], Hn)
        Vn = x[..., 1] + dV
        return jnp.stack([Hn, Vn], axis=-1), None

    x0 = jnp.stack([H0, V0], axis=-1)
    x, _ = jax.lax.scan(newton_step, x0, None, length=_NEWTON_ITERS)
    H, V = jnp.maximum(x[..., 0], 1e-8), x[..., 1]

    contact = (V < w * L) & contact_allowed
    Va = jnp.where(contact, 0.0, V - w * L)
    Ha = jnp.where(contact, H, H)  # frictionless seabed: H unchanged
    TB = jnp.sqrt(H**2 + V**2)
    TA = jnp.sqrt(Ha**2 + Va**2)
    return dict(H=H, V=V, Ha=Ha, Va=Va, TA=TA, TB=TB)


# --------------------------------------------------------------------------
# body-level quantities
# --------------------------------------------------------------------------

def fairlead_positions(sys_: MooringSystem, r6):
    """Global fairlead positions for body pose r6 (full Euler rotation,
    matching the reference's MoorPy Body.setPosition)."""
    r6 = jnp.asarray(r6, float)
    R = rotation_matrix(r6[3], r6[4], r6[5])
    return r6[:3] + jnp.asarray(sys_.rFair0) @ R.T


def _safe_norm(x, axis=-1):
    """|x| with a zero-safe gradient (d|x|/dx = 0 at x = 0 instead of NaN,
    needed because the current-drag decomposition vanishes identically
    when U is parallel/perpendicular to the chord)."""
    return jnp.sqrt(jnp.sum(x * x, axis=axis) + 1e-30)


def line_forces(sys_: MooringSystem, r6, current=None, rF=None):
    """Per-line force on the body at each fairlead, (nl,3) global, plus the
    solve products (tensions).

    ``current``: optional uniform current velocity (3,).  When given, each
    line solves in the plane of its EFFECTIVE weight vector — submerged
    weight plus chord-direction current drag per unit length — the
    quasi-static current model of MoorPy's currentMod=1 (the reference
    passes case currents to MoorPy, raft_model.py:559-578, and its
    tension statistics FD re-equilibrates the current-loaded lines at
    every perturbed pose).  The catenary itself is unchanged; only the
    solve plane tilts and the weight becomes |w_vec|.

    ``rF`` overrides the fairlead positions (used by the rotation-vector
    stiffness linearization, which perturbs the orientation directly
    rather than through the Euler angles in r6)."""
    if rF is None:
        rF = fairlead_positions(sys_, r6)
    rA = jnp.asarray(sys_.rAnchor)
    L = jnp.asarray(sys_.L)
    EA = jnp.asarray(sys_.EA)
    w = jnp.asarray(sys_.w)
    if current is None:
        dxy = rF[:, :2] - rA[:, :2]
        XF = jnp.linalg.norm(dxy, axis=1)
        ZF = rF[:, 2] - rA[:, 2]
        sol = catenary_solve(XF, ZF, L, EA, w)
        XF_safe = jnp.where(XF > 0, XF, 1.0)[:, None]
        dir_h = dxy / XF_safe
        F = jnp.concatenate([-sol["H"][:, None] * dir_h,
                             -sol["V"][:, None]], axis=1)
        return F, rF, sol

    from raft_tpu.models.mooring_array import chord_drag_per_length
    U = jnp.asarray(current, float)
    dr = rF - rA                                     # (nl,3) anchor->fairlead
    f_drag = chord_drag_per_length(dr, U, sys_.d_vol, sys_.Cd_t,
                                   sys_.Cd_a, sys_.rho)   # (nl,3) N/m
    w_vec = f_drag + w[:, None] * jnp.array([0.0, 0.0, -1.0])
    # the tilted-plane construction assumes the effective weight points
    # broadly DOWN; net-buoyant lines (w <= 0, e.g. the FOCTT model-scale
    # chain at -483 N/m) would get a flipped frame and lose the signed-
    # weight catenary semantics — they stay on the plain vertical-plane
    # solve (current tilt unsupported for buoyant lines, documented)
    sinking = (w > 0.0)
    w_eff = jnp.where(sinking, _safe_norm(w_vec), w)   # (nl,) signed
    zt = jnp.where(sinking[:, None],
                   -w_vec / _safe_norm(w_vec)[:, None],
                   jnp.array([0.0, 0.0, 1.0]))         # tilted "up"
    ZF = jnp.sum(dr * zt, axis=1)
    xvec = dr - ZF[:, None] * zt
    XF = _safe_norm(xvec)
    xt = xvec / jnp.where(XF > 0, XF, 1.0)[:, None]
    sol = catenary_solve(XF, ZF, L, EA, w_eff)
    F = -sol["H"][:, None] * xt - sol["V"][:, None] * zt
    # buoyant lines solve in the plain frame (no drag in the profile);
    # their current drag still loads the body as the lumped half-line
    # wrench (same doctrine as current_wrenches on the general path)
    F = F + jnp.where(sinking[:, None], 0.0,
                      0.5 * L[:, None] * f_drag)
    return F, rF, sol


def _is_general(sys_) -> bool:
    """True for the general (free-point / multi-segment) single-body
    system built by parse_mooring on non-simple topologies."""
    return hasattr(sys_, "attach")


def free_points(sys_, r6, xf0=None):
    """Equilibrium free-point positions for a general system (None for the
    simple topology).  Callers evaluating several mooring quantities at one
    pose should solve this ONCE and pass it via the ``xf=`` arguments below
    instead of paying a cold Newton solve per quantity."""
    if not _is_general(sys_):
        return None
    from raft_tpu.models import mooring_array as ma
    return ma.solve_free_points(sys_, jnp.asarray(r6, float)[None, :],
                                xf0=xf0)


def body_wrench(sys_, r6, xf=None, current=None):
    """Net 6-DOF mooring wrench on the body about its reference point
    (equivalent of Body.getForces(lines_only=True)).  ``current`` engages
    the current-loaded line profiles on the simple path (see
    line_forces); general topologies model current by the lumped chord
    approximation in current_wrench instead."""
    if _is_general(sys_):
        from raft_tpu.models import mooring_array as ma
        Xb = jnp.asarray(r6, float)[None, :]
        if xf is None:
            xf = ma.solve_free_points(sys_, Xb)
        return ma.body_wrenches(sys_, Xb, xf)[0]
    F, rF, _ = line_forces(sys_, r6, current=current)
    r6 = jnp.asarray(r6, float)
    return jnp.sum(translate_force_3to6(F, rF - r6[:3]), axis=0)


def coupled_stiffness(sys_, r6, xf=None, current=None):
    """6x6 mooring stiffness -dF/dx about the body pose as the exact
    EULER-ANGLE jacobian of the wrench, by forward-mode autodiff through
    the catenary Newton solve (free points eliminated by the
    implicit-function theorem on the general path).

    This is the consistent jacobian for Newton statics on the Euler pose
    vector.  For the reference's dynamics/eigen C_moor
    (getCoupledStiffnessA) use :func:`coupled_stiffness_rotvec` — MoorPy's
    analytic assembly is the ROTATION-VECTOR linearization, which differs
    from this jacobian at loaded poses (the two coincide at zero
    angles)."""
    if _is_general(sys_):
        from raft_tpu.models import mooring_array as ma
        Xb = jnp.asarray(r6, float)[None, :]
        if xf is None:
            xf = ma.solve_free_points(sys_, Xb)
        return ma.coupled_stiffness(sys_, Xb, xf)
    return -jax.jacfwd(lambda x: body_wrench(sys_, x, current=current))(
        jnp.asarray(r6, float))


def coupled_stiffness_rotvec(sys_, r6, xf=None, current=None):
    """MoorPy-parity ANALYTIC coupled stiffness: the exact ROTATION-VECTOR
    linearization of the mooring wrench about the pose.

    MoorPy's getCoupledStiffnessA (the reference's dynamics/eigen C_moor,
    raft_fowt.py:287) assembles Body.getStiffnessA from a Taylor series in
    an infinitesimal GLOBAL-AXIS rotation vector: dr_fairlead = dtheta x r
    plus the geometric force term d(r x F).  That is the exact derivative
    with respect to a rotation-vector perturbation of the CURRENT
    orientation — NOT with respect to the Euler angles in r6.  At a loaded
    equilibrium with nonzero mean pitch theta the two differ by the
    Euler-rate matrix E(theta) (K_euler[:,3:] = K_rotvec[:,3:] @ E, with
    E - I entries of order sin(theta) in the roll/pitch columns; the yaw
    column is exact because Rz is the outermost rotation), which is
    exactly the sub-1% rotational-coupling difference class isolated by
    the round-4 operating-case forensics.  Implemented not by hand-porting
    MoorPy's formulas but by autodiffing the same wrench under the
    rotation-vector parameterization R(delta) @ R0 — identical to MoorPy's
    series to first order, with no sign/term transcription risk.

    Limitation: the general (free-point) topology path does NOT model
    line current — a non-None ``current`` is dropped there (the
    mooring_array stiffness has no current-loaded line profiles; only
    the simple-topology catenary does) and a UserWarning is emitted so
    the approximation is visible instead of silent."""
    if _is_general(sys_):
        if current is not None:
            import warnings
            warnings.warn(
                "coupled_stiffness_rotvec: 'current' is ignored on "
                "general (free-point) mooring topologies — the stiffness "
                "is evaluated with unloaded line profiles (current only "
                "enters general topologies through the lumped "
                "current_wrench on F_env)", stacklevel=2)
        from raft_tpu.models import mooring_array as ma
        Xb = jnp.asarray(r6, float)[None, :]
        if xf is None:
            xf = ma.solve_free_points(sys_, Xb)
        return ma.coupled_stiffness_rotvec(sys_, Xb, xf)
    r6 = jnp.asarray(r6, float)
    R0 = rotation_matrix(r6[3], r6[4], r6[5])
    rfair_rel0 = jnp.asarray(sys_.rFair0) @ R0.T   # body->global, base pose

    def wrench(delta):
        # rotation_matrix's differential at the identity is the skew of
        # the rotation vector for every Euler convention, so this is the
        # exact rotation-vector derivative
        dR = rotation_matrix(delta[3], delta[4], delta[5])
        base = r6[:3] + delta[:3]
        rF = base + rfair_rel0 @ dR.T
        F, rFo, _ = line_forces(sys_, r6, current=current, rF=rF)
        return jnp.sum(translate_force_3to6(F, rFo - base), axis=0)

    return -jax.jacfwd(wrench)(jnp.zeros(6))


def tensions(sys_, r6, xf=None, current=None):
    """Line end tensions, shape (2*nl,): all anchor-end tensions first,
    then all fairlead-end tensions ([TA_1..TA_n, TB_1..TB_n]), matching
    MoorPy's getTensions ordering used by the reference."""
    if _is_general(sys_):
        from raft_tpu.models import mooring_array as ma
        Xb = jnp.asarray(r6, float)[None, :]
        if xf is None:
            xf = ma.solve_free_points(sys_, Xb)
        return ma.tensions(sys_, Xb, xf)
    _, _, sol = line_forces(sys_, r6, current=current)
    return jnp.concatenate([sol["TA"], sol["TB"]])


def current_wrench(sys_, r6, U, rho: float = _RHO, xf=None):
    """Uniform-current drag on the mooring lines, lumped to the body —
    chord-direction approximation of MoorPy's currentMod=1 (the reference
    passes case currents to MoorPy, raft_model.py:559-578).  Half of each
    line's drag loads the fairlead, the anchor half sheds to ground."""
    if _is_general(sys_):
        from raft_tpu.models import mooring_array as ma
        Xb = jnp.asarray(r6, float)[None, :]
        if xf is None:
            xf = ma.solve_free_points(sys_, Xb)
        return ma.current_wrenches(sys_, Xb, xf, U)[0]
    from raft_tpu.models.mooring_array import chord_drag
    r6 = jnp.asarray(r6, float)
    rF = fairlead_positions(sys_, r6)
    F_line = chord_drag(sys_.rAnchor, rF, U, sys_.L, sys_.d_vol,
                        sys_.Cd_t, sys_.Cd_a, rho)
    return jnp.sum(translate_force_3to6(0.5 * F_line, rF - r6[:3]), axis=0)


def coupled_stiffness_fd(sys_, r6, dx=0.1, dth=0.1, tensions_too=False):
    """MoorPy-parity coupled stiffness (and optionally tension Jacobian)
    by CENTRAL finite differences with MoorPy's default perturbations
    (System.getCoupledStiffness: dx=0.1 m, dth=0.1 rad), free DOFs
    re-equilibrated at every perturbed pose.

    The reference uses this FD variant ONLY for the tension statistics
    (raft_fowt.py:1881 getCoupledStiffness(tensions=True) -> J_moor);
    its statics Newton AND the dynamics/eigen C_moor use the analytic
    getCoupledStiffnessA (raft_fowt.py:287 via setPosition — the
    model-level FD block at raft_model.py:798-850 is dead code inside a
    TODO string).  So: `coupled_stiffness_rotvec` (MoorPy's analytic
    flavor) for dynamics/eigen, `coupled_stiffness` (Euler AD) for the
    statics Newton jacobian, and `tension_jacobian_fd` for Tmoor stats.
    The FD truncation error (notably the 0.1 rad rotational step) is a
    few percent on rotation-coupled tension sensitivities at loaded
    offsets, so the exact-AD Jacobian does NOT reproduce the reference's
    Tmoor_std."""
    r6 = np.asarray(r6, float)
    dX = np.array([dx, dx, dx, dth, dth, dth])
    K = np.zeros((6, 6))
    J = None
    for i in range(6):
        Xp = r6.copy(); Xp[i] += dX[i]
        Xm = r6.copy(); Xm[i] -= dX[i]
        # fresh free-point solves at each perturbed pose = MoorPy's
        # internal re-equilibration of free DOFs
        Fp = np.asarray(body_wrench(sys_, Xp))
        Fm = np.asarray(body_wrench(sys_, Xm))
        K[:, i] = -0.5 * (Fp - Fm) / dX[i]
        if tensions_too:
            Tp = np.asarray(tensions(sys_, Xp))
            Tm = np.asarray(tensions(sys_, Xm))
            if J is None:
                J = np.zeros((len(Tp), 6))
            J[:, i] = 0.5 * (Tp - Tm) / dX[i]
    if tensions_too:
        return K, J
    return K


def tension_jacobian_fd(sys_, r6, dx=0.1, dth=0.1, current=None):
    """MoorPy-parity FD tension Jacobian (getCoupledStiffness(...,
    tensions=True) J_moor) — see :func:`coupled_stiffness_fd`.  Computes
    only the tensions (no wrench evaluations), with one free-point solve
    shared per perturbed pose.  ``current`` re-solves the CURRENT-LOADED
    line profiles at every perturbed pose, matching MoorPy's FD under
    ms.currentMod=1 (without it the loaded-case Tmoor_std carried a
    3-5e-2 band vs the reference pickles; see tests/test_model_oc3.py)."""
    r6 = np.asarray(r6, float)
    dX = np.array([dx, dx, dx, dth, dth, dth])
    J = None
    for i in range(6):
        Xp = r6.copy(); Xp[i] += dX[i]
        Xm = r6.copy(); Xm[i] -= dX[i]
        Tp = np.asarray(tensions(sys_, Xp, xf=free_points(sys_, Xp),
                                 current=current))
        Tm = np.asarray(tensions(sys_, Xm, xf=free_points(sys_, Xm),
                                 current=current))
        if J is None:
            J = np.zeros((len(Tp), 6))
        J[:, i] = 0.5 * (Tp - Tm) / dX[i]
    return J


def tension_jacobian(sys_, r6, xf=None):
    """d(tensions)/d(pose): (2*nl, 6), the J_moor of the reference's
    getCoupledStiffness(..., tensions=True)."""
    if _is_general(sys_):
        from raft_tpu.models import mooring_array as ma
        Xb = jnp.asarray(r6, float)[None, :]
        if xf is None:
            xf = ma.solve_free_points(sys_, Xb)
        return ma.tension_jacobian(sys_, Xb, xf)
    return jax.jacfwd(lambda x: tensions(sys_, x))(jnp.asarray(r6, float))
