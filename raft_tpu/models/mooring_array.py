"""Array-level (multi-body) quasi-static mooring with shared lines.

TPU-first replacement for the array-level MoorPy ``System`` the reference
builds for farms (reference: raft/raft_model.py:83-100 — ``mp.System`` +
``addBody`` per FOWT + ``load(MoorDyn file)``; used at raft_model.py:
600-606 for equilibrium forces, :1029-1031 for the coupled stiffness added
to the block impedance, and :345-388 for tension statistics).

Capability set (the subset the reference exercises):

- points: FIXED anchors (global coords), FREE junction points (clump
  weights / multi-segment line junctions, positions solved to static
  equilibrium), and BODY-attached fairleads on any number of bodies
  (body-frame coords).
- lines: the same differentiable elastic catenary as ``models.mooring``,
  generalized to arbitrary end elevations.  The seabed-contact branch is
  only enabled for lines whose lower end is a fixed anchor on the seabed
  (static per-line mask) — suspended shared lines between elevated points
  use the pure-catenary branch, which is valid for a negative lower-end
  vertical force (line sagging below the attachment).

Everything is jnp and differentiable end-to-end:

- free-point equilibrium is a fixed-iteration damped Newton (jacfwd
  Jacobian) — shape-stable under jit;
- the coupled body stiffness eliminates the free-point DOFs by the
  implicit-function theorem (Schur complement), i.e. the exact equivalent
  of MoorPy's ``getCoupledStiffnessA`` finite differencing:
      K = -( dFb/dXb - dFb/dxf (dg/dxf)^-1 dg/dXb )     with g(xf; Xb)=0
- tension Jacobians for the farm tension statistics get the same implicit
  correction (equivalent of ``getCoupledStiffness(..., tensions=True)``).
"""
from __future__ import annotations

import re
from dataclasses import dataclass

import numpy as np
import jax
import jax.numpy as jnp

from raft_tpu.models.mooring import catenary_solve
from raft_tpu.ops.transforms import rotation_matrix, translate_force_3to6

_G = 9.81
_RHO = 1025.0

ATTACH_FIXED = -1
ATTACH_FREE = -2


@dataclass
class ArrayMooring:
    """Static description of a multi-body mooring system (numpy)."""

    depth: float
    nbodies: int
    # points
    attach: np.ndarray      # (npt,) ATTACH_FIXED | ATTACH_FREE | body index
    r0: np.ndarray          # (npt,3) body-frame (body pts) or global coords
    pmass: np.ndarray       # (npt,) point mass [kg]
    pvol: np.ndarray        # (npt,) point displaced volume [m^3]
    free_idx: np.ndarray    # (npt,) row into the free-point vector, -1 else
    # lines
    iA: np.ndarray          # (nl,) endpoint A point index
    iB: np.ndarray          # (nl,) endpoint B point index
    L: np.ndarray           # (nl,) unstretched length
    EA: np.ndarray          # (nl,) axial stiffness
    w: np.ndarray           # (nl,) submerged weight per length [N/m]
    contact_ok: np.ndarray  # (nl,) bool: lower end is a seabed anchor
    g: float = _G
    rho: float = _RHO
    d_vol: np.ndarray = None   # (nl,) volume-equivalent line diameter
    Cd_t: np.ndarray = None    # (nl,) transverse drag coefficient
    Cd_a: np.ndarray = None    # (nl,) tangential (axial) drag coefficient

    @property
    def n_free(self) -> int:
        return int((self.attach == ATTACH_FREE).sum())

    @property
    def n_lines(self) -> int:
        return len(self.L)


# --------------------------------------------------------------------------
# MoorDyn-format parsing (reference loads the same file through MoorPy's
# System.load; schema per tests/test_data/shared_mooring_volturnus.dat)
# --------------------------------------------------------------------------

_BODY_RE = re.compile(r"^(?:turbine|body|vessel|coupled)(\d*)$", re.I)


def parse_moordyn(path: str, nbodies: int, depth: float | None = None,
                  rho: float = _RHO, g: float = _G) -> ArrayMooring:
    """Parse the sections of a MoorDyn v2 input file that define a
    quasi-static system: LINE TYPES, POINTS, LINES, and the WtrDpth option.

    Body attachments named ``Turbine<i>``/``Body<i>`` map to body ``i-1``;
    their coordinates are body-frame (MoorPy attaches them as relative
    coordinates to the pre-created FOWT bodies, reference
    raft_model.py:93-97)."""
    sections: dict[str, list[str]] = {}
    current = None
    with open(path) as f:
        for raw in f:
            line = raw.strip()
            if not line:
                continue
            if line.startswith("---"):
                name = line.strip("- ").upper()
                current = name
                sections[current] = []
            elif current is not None:
                sections[current].append(line)

    def section(key, n_header=2):
        for name, rows in sections.items():
            if key in name:
                return rows[n_header:]  # drop column-name/units header rows
        return []

    # line types
    types = {}
    for row in section("LINE TYPES"):
        c = row.split()
        d, m, EA = float(c[1]), float(c[2]), float(c[3])
        w_wet = (m - rho * np.pi / 4.0 * d**2) * g
        types[c[0]] = dict(d=d, m=m, EA=EA, w=w_wet,
                           Cd=float(c[6]) if len(c) > 6 else 0.0,
                           CdAx=float(c[8]) if len(c) > 8 else 0.0)

    # options (water depth)
    for row in section("OPTIONS", n_header=0):
        c = row.split()
        if len(c) >= 2 and c[1].lower() in ("wtrdpth", "depth", "wtrdepth"):
            depth = float(c[0])
    if depth is None:
        raise ValueError("water depth not found in MoorDyn file or args")

    # points
    ids, attach, r0, pmass, pvol = [], [], [], [], []
    for row in section("POINTS"):
        c = row.split()
        ids.append(int(c[0]))
        a = c[1].lower()
        if a in ("fixed", "fix", "anchor"):
            attach.append(ATTACH_FIXED)
        elif a in ("free", "connect"):
            attach.append(ATTACH_FREE)
        else:
            mm = _BODY_RE.match(a)
            if not mm:
                raise ValueError(f"unknown point attachment {c[1]!r}")
            attach.append(int(mm.group(1) or 1) - 1)
        r0.append([float(c[2]), float(c[3]), float(c[4])])
        pmass.append(float(c[5]))
        pvol.append(float(c[6]))
    ids = np.array(ids)
    attach = np.array(attach)
    r0 = np.array(r0)
    if attach.size and attach.max() >= nbodies:
        raise ValueError(
            f"MoorDyn file references body {attach.max()+1} but the array "
            f"has only {nbodies} FOWTs")

    id2row = {pid: i for i, pid in enumerate(ids)}
    free_idx = np.full(len(ids), -1)
    free_idx[attach == ATTACH_FREE] = np.arange((attach == ATTACH_FREE).sum())

    # lines
    iA, iB, L, EA, w = [], [], [], [], []
    d_vol, Cd_t, Cd_a = [], [], []
    for row in section("LINES"):
        c = row.split()
        lt = types[c[1]]
        iA.append(id2row[int(c[2])])
        iB.append(id2row[int(c[3])])
        L.append(float(c[4]))
        EA.append(lt["EA"])
        w.append(lt["w"])
        d_vol.append(lt["d"])
        Cd_t.append(lt["Cd"])
        Cd_a.append(lt["CdAx"])
    iA, iB = np.array(iA), np.array(iB)

    # seabed contact only for lines whose lower end is a fixed anchor on
    # the seabed (static: anchors don't move, other points sit well above)
    def on_seabed(ipt):
        return (attach[ipt] == ATTACH_FIXED) & (r0[ipt, 2] <= -depth + 1.0)

    contact_ok = on_seabed(iA) | on_seabed(iB)

    return ArrayMooring(
        depth=float(depth), nbodies=nbodies,
        attach=attach, r0=r0, pmass=np.array(pmass), pvol=np.array(pvol),
        free_idx=free_idx,
        iA=iA, iB=iB, L=np.array(L), EA=np.array(EA), w=np.array(w),
        contact_ok=contact_ok, g=g, rho=rho,
        d_vol=np.array(d_vol), Cd_t=np.array(Cd_t), Cd_a=np.array(Cd_a),
    )


# --------------------------------------------------------------------------
# kinematics & forces
# --------------------------------------------------------------------------

def point_positions(ms: ArrayMooring, Xb, xf, delta=None):
    """Global point positions. Xb: (nb,6) body poses; xf: (nf,3) free
    point positions.  ``delta`` ((nb,6), optional) perturbs each body by
    a translation delta[:, :3] and a left-composed rotation
    R(delta[:, 3:]) @ R0 — the rotation-vector parameterization used by
    the MoorPy-parity analytic stiffness (coupled_stiffness_rotvec)."""
    Xb = jnp.asarray(Xb, float)
    xf = jnp.asarray(xf, float)
    r0 = jnp.asarray(ms.r0)

    R = jax.vmap(lambda x: rotation_matrix(x[3], x[4], x[5]))(Xb)  # (nb,3,3)
    base = Xb[:, :3]
    if delta is not None:
        delta = jnp.asarray(delta, float)
        dR = jax.vmap(lambda d: rotation_matrix(d[3], d[4], d[5]))(delta)
        R = jnp.einsum("bij,bjk->bik", dR, R)
        base = base + delta[:, :3]
    bidx = jnp.clip(jnp.asarray(ms.attach), 0, ms.nbodies - 1)
    body_pos = base[bidx] + jnp.einsum("pij,pj->pi", R[bidx], r0)
    fidx = jnp.clip(jnp.asarray(ms.free_idx), 0, max(ms.n_free - 1, 0))
    free_pos = xf[fidx] if ms.n_free else jnp.zeros_like(r0)

    attach = jnp.asarray(ms.attach)
    pts = jnp.where((attach >= 0)[:, None], body_pos,
                    jnp.where((attach == ATTACH_FREE)[:, None], free_pos, r0))
    return pts


def line_end_forces(ms: ArrayMooring, pts):
    """Per-line forces exerted BY the line ON its two endpoints, plus end
    tensions.  Returns (FA, FB, TA, TB) with F* (nl,3) and T* oriented so
    TA belongs to endpoint A of the file's line definition (matching
    MoorPy's per-line TA/TB)."""
    rA = pts[jnp.asarray(ms.iA)]
    rB = pts[jnp.asarray(ms.iB)]
    flip = rA[:, 2] > rB[:, 2]          # A above B -> A is the upper end
    rLow = jnp.where(flip[:, None], rB, rA)
    rUp = jnp.where(flip[:, None], rA, rB)

    dxy = rUp[:, :2] - rLow[:, :2]
    XF = jnp.linalg.norm(dxy, axis=1)
    ZF = rUp[:, 2] - rLow[:, 2]
    sol = catenary_solve(XF, ZF, jnp.asarray(ms.L), jnp.asarray(ms.EA),
                         jnp.asarray(ms.w),
                         contact_allowed=jnp.asarray(ms.contact_ok))

    dir_h = dxy / jnp.where(XF > 1e-8, XF, 1.0)[:, None]
    # upper end: line pulls down-and-toward-lower; lower end: toward upper
    F_up = jnp.concatenate([-sol["H"][:, None] * dir_h, -sol["V"][:, None]],
                           axis=1)
    F_low = jnp.concatenate([sol["Ha"][:, None] * dir_h, sol["Va"][:, None]],
                            axis=1)
    FA = jnp.where(flip[:, None], F_up, F_low)
    FB = jnp.where(flip[:, None], F_low, F_up)
    TA = jnp.where(flip, sol["TB"], sol["TA"])
    TB = jnp.where(flip, sol["TA"], sol["TB"])
    return FA, FB, TA, TB


def _point_forces(ms: ArrayMooring, pts):
    """Net line force on every point, (npt,3)."""
    FA, FB, _, _ = line_end_forces(ms, pts)
    F = jnp.zeros_like(pts)
    F = F.at[jnp.asarray(ms.iA)].add(FA)
    F = F.at[jnp.asarray(ms.iB)].add(FB)
    return F


_KBOT_POINT = 1e5   # [N/m] seabed normal-contact stiffness for free points


def free_net_force(ms: ArrayMooring, Xb, xf, delta=None):
    """Equilibrium residual of the free points: line forces + weight +
    buoyancy + seabed normal contact (linear penalty below z = -depth,
    the MoorDyn kbot analog), (nf,3)."""
    pts = point_positions(ms, Xb, xf, delta=delta)
    F = _point_forces(ms, pts)
    Wz = (-jnp.asarray(ms.pmass) * ms.g
          + jnp.asarray(ms.pvol) * ms.rho * ms.g)
    F = F.at[:, 2].add(Wz)
    F = F.at[:, 2].add(_KBOT_POINT * jnp.maximum(-ms.depth - pts[:, 2], 0.0))
    return F[np.where(ms.attach == ATTACH_FREE)[0]]


def solve_free_points(ms: ArrayMooring, Xb, xf0=None, iters: int = 40,
                      step_max: float = 30.0):
    """Damped-Newton equilibrium of the free points (fixed iterations,
    jit/vmap-safe).  The MoorPy analog is System.solveEquilibrium over the
    free-point DOFs (called from the reference's eval_func_equil,
    raft_model.py:600-606)."""
    if ms.n_free == 0:
        return jnp.zeros((0, 3))
    if xf0 is None:
        xf0 = ms.r0[ms.attach == ATTACH_FREE]
    x0 = jnp.asarray(xf0, float).reshape(-1)

    def resid(x):
        return free_net_force(ms, Xb, x.reshape(-1, 3)).reshape(-1)

    def step(x, _):
        r = resid(x)
        J = jax.jacfwd(resid)(x)
        J = J + 1e-6 * jnp.eye(J.shape[0])
        dx = jnp.linalg.solve(J, -r)
        dx = jnp.clip(dx, -step_max, step_max)
        return x + dx, None

    x, _ = jax.lax.scan(step, x0, None, length=iters)
    return x.reshape(-1, 3)


def chord_drag_per_length(chord, U, d, Cd_t, Cd_a, rho):
    """Uniform-current drag per unit length on lines with the given chord
    vectors (nl,3) -> (nl,3) N/m: transverse 0.5 rho Cd_t d |Un| Un plus
    tangential 0.5 rho Cd_a (pi d) |Ut| Ut.  The single constitutive law
    shared by the lumped wrench (chord_drag) and the tilted-plane
    current-loaded catenary (mooring.line_forces).  Norms are zero-safe so
    autodiff through vanishing components stays finite."""
    U = jnp.asarray(U, float)
    chord = jnp.asarray(chord)
    cn = jnp.sqrt(jnp.sum(chord * chord, axis=1, keepdims=True) + 1e-30)
    t = chord / cn
    Ut = jnp.sum(U[None, :] * t, axis=1, keepdims=True) * t
    Un = U[None, :] - Ut
    nUn = jnp.sqrt(jnp.sum(Un * Un, axis=1, keepdims=True) + 1e-30)
    nUt = jnp.sqrt(jnp.sum(Ut * Ut, axis=1, keepdims=True) + 1e-30)
    return (0.5 * rho * jnp.asarray(d))[:, None] * (
        jnp.asarray(Cd_t)[:, None] * nUn * Un
        + np.pi * jnp.asarray(Cd_a)[:, None] * nUt * Ut)


def chord_drag(rA, rB, U, L, d, Cd_t, Cd_a, rho):
    """Per-line uniform-current drag on the straight chord rA->rB, (nl,3),
    integrated over the unstretched length (chord_drag_per_length * L).
    Shared by the single-body and array mooring paths."""
    f = chord_drag_per_length(jnp.asarray(rB) - jnp.asarray(rA), U,
                              d, Cd_t, Cd_a, rho)
    return jnp.asarray(L)[:, None] * f


def current_wrenches(ms: ArrayMooring, Xb, xf, U):
    """Uniform-current drag on the mooring lines, lumped to the attached
    bodies, (nb,6).

    Quasi-static approximation of MoorPy's currentMod=1 (the reference
    passes case currents to MoorPy at raft_model.py:559-578): drag is
    evaluated on each line's straight CHORD direction — transverse
    0.5 rho Cd_t d |Un| Un and tangential 0.5 rho Cd_a (pi d) |Ut| Ut per
    unit length over the unstretched length — and half of each line's
    total is lumped to each endpoint.  Free/fixed endpoints shed their
    share to the seabed/junction, body endpoints load the body."""
    if ms.Cd_t is None:
        return jnp.zeros((ms.nbodies, 6))
    Xb = jnp.asarray(Xb, float)
    pts = point_positions(ms, Xb, xf)
    rA = pts[jnp.asarray(ms.iA)]
    rB = pts[jnp.asarray(ms.iB)]
    F_line = chord_drag(rA, rB, U, ms.L, ms.d_vol, ms.Cd_t, ms.Cd_a, ms.rho)
    Fp = jnp.zeros_like(pts)
    Fp = Fp.at[jnp.asarray(ms.iA)].add(0.5 * F_line)
    Fp = Fp.at[jnp.asarray(ms.iB)].add(0.5 * F_line)
    attach = jnp.asarray(ms.attach)

    def wrench(b):
        mask = (attach == b).astype(float)[:, None]
        offs = pts - Xb[b, :3]
        return jnp.sum(translate_force_3to6(Fp * mask, offs), axis=0)

    return jnp.stack([wrench(b) for b in range(ms.nbodies)])


def body_wrenches(ms: ArrayMooring, Xb, xf, delta=None):
    """6-DOF mooring wrench on each body about its pose reference point,
    (nb,6) (equivalent of per-body Body.getForces(lines_only=True)).
    ``delta`` perturbs the body poses per point_positions (the moment
    reference point translates with the body)."""
    Xb = jnp.asarray(Xb, float)
    pts = point_positions(ms, Xb, xf, delta=delta)
    base = Xb[:, :3]
    if delta is not None:
        base = base + jnp.asarray(delta, float)[:, :3]
    F = _point_forces(ms, pts)
    attach = jnp.asarray(ms.attach)

    def wrench(b):
        mask = (attach == b).astype(float)[:, None]
        offs = pts - base[b]
        return jnp.sum(translate_force_3to6(F * mask, offs), axis=0)

    return jnp.stack([wrench(b) for b in range(ms.nbodies)])


# --------------------------------------------------------------------------
# equilibrium-coupled quantities (implicit-function / Schur complement)
# --------------------------------------------------------------------------

def _implicit_sensitivity(g, xb_arg, xf_flat, n_free):
    """d(xf)/d(xb) at equilibrium: -(dg/dxf)^-1 (dg/dxb).  The single
    regularized free-point elimination behind every equilibrium-coupled
    quantity (both stiffness flavors and the tension Jacobian), so
    regularization/solve changes cannot drift between them."""
    nf3 = n_free * 3
    dg_dxf = jax.jacfwd(lambda xf: g(xb_arg, xf))(xf_flat)
    dg_dxb = jax.jacfwd(lambda xb: g(xb, xf_flat))(xb_arg)
    return -jnp.linalg.solve(dg_dxf + 1e-9 * jnp.eye(nf3), dg_dxb)


def _implicit_dxf_dXb(ms: ArrayMooring, Xb_flat, xf_eq):
    """d(xf)/d(Xb) at equilibrium for the Euler pose parameterization."""

    def g(xb, xf):
        return free_net_force(ms, xb.reshape(-1, 6), xf.reshape(-1, 3)
                              ).reshape(-1)

    xf_flat = jnp.asarray(xf_eq, float).reshape(-1)
    return _implicit_sensitivity(g, Xb_flat, xf_flat, ms.n_free)


def _schur_coupled(fb, g, xb_arg, xf_flat, n_free):
    """-d(fb)/d(xb) at equilibrium with the free points eliminated by the
    implicit-function theorem (MoorPy's analytic Schur complement over
    free DOFs) — the single elimination shared by BOTH body
    parameterizations (Euler pose vector and rotation-vector delta), so
    regularization/solve changes cannot drift between the two flavors."""
    dfb_dxb = jax.jacfwd(lambda xb: fb(xb, xf_flat))(xb_arg)
    if n_free == 0:
        return -dfb_dxb
    dxf_dxb = _implicit_sensitivity(g, xb_arg, xf_flat, n_free)
    dfb_dxf = jax.jacfwd(lambda xf: fb(xb_arg, xf))(xf_flat)
    return -(dfb_dxb + dfb_dxf @ dxf_dxb)


def coupled_stiffness(ms: ArrayMooring, Xb, xf_eq):
    """(6nb,6nb) coupled mooring stiffness about the body poses with the
    free points eliminated — equivalent of MoorPy's
    getCoupledStiffnessA(lines_only=True) (reference raft_model.py:
    1029-1031), but by exact autodiff instead of finite differences."""
    Xb_flat = jnp.asarray(Xb, float).reshape(-1)
    xf_flat = jnp.asarray(xf_eq, float).reshape(-1)

    def fb(xb, xf):
        return body_wrenches(ms, xb.reshape(-1, 6), xf.reshape(-1, 3)
                             ).reshape(-1)

    def g(xb, xf):
        return free_net_force(ms, xb.reshape(-1, 6), xf.reshape(-1, 3)
                              ).reshape(-1)

    return _schur_coupled(fb, g, Xb_flat, xf_flat, ms.n_free)


def coupled_stiffness_rotvec(ms: ArrayMooring, Xb, xf_eq):
    """(6nb,6nb) MoorPy-parity analytic coupled stiffness: the exact
    ROTATION-VECTOR linearization of the body wrenches (free points
    eliminated by the shared Schur complement).  See
    mooring.coupled_stiffness_rotvec for why this differs from the
    Euler-angle jacobian at loaded poses."""
    Xb = jnp.asarray(Xb, float)
    xf_flat = jnp.asarray(xf_eq, float).reshape(-1)
    d0 = jnp.zeros(Xb.size)

    def fb(d, xf):
        return body_wrenches(ms, Xb, xf.reshape(-1, 3),
                             delta=d.reshape(-1, 6)).reshape(-1)

    def g(d, xf):
        return free_net_force(ms, Xb, xf.reshape(-1, 3),
                              delta=d.reshape(-1, 6)).reshape(-1)

    return _schur_coupled(fb, g, d0, xf_flat, ms.n_free)


def tensions(ms: ArrayMooring, Xb, xf):
    """Line end tensions, (2*nl,): [TA_1..TA_n, TB_1..TB_n] (MoorPy
    getTensions ordering used by the farm statistics at
    raft_model.py:360-363)."""
    pts = point_positions(ms, jnp.asarray(Xb, float), xf)
    _, _, TA, TB = line_end_forces(ms, pts)
    return jnp.concatenate([TA, TB])


def tension_jacobian(ms: ArrayMooring, Xb, xf_eq):
    """d(tensions)/d(body poses) with the implicit free-point correction,
    (2nl, 6nb) — the J_moor of getCoupledStiffness(..., tensions=True)."""
    Xb_flat = jnp.asarray(Xb, float).reshape(-1)
    xf_flat = jnp.asarray(xf_eq, float).reshape(-1)

    def T(xb, xf):
        return tensions(ms, xb.reshape(-1, 6), xf.reshape(-1, 3))

    dT_dxb = jax.jacfwd(lambda xb: T(xb, xf_flat))(Xb_flat)
    if ms.n_free == 0:
        return dT_dxb
    dT_dxf = jax.jacfwd(lambda xf: T(Xb_flat, xf))(xf_flat)
    dxf_dxb = _implicit_dxf_dXb(ms, Xb_flat, xf_eq)
    return dT_dxb + dT_dxf @ dxf_dxb
