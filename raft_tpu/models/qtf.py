"""Second-order difference-frequency hydrodynamics: the QTF engine.

TPU-first re-design of the reference's hottest kernel (reference:
raft/raft_fowt.py:1385-1648 calcQTF_slenderBody, :1651-1725 readQTF/
writeQTF, :1728-1818 calcHydroForce_2ndOrd).  The reference evaluates the
slender-body QTF in a quadruple Python loop (member x node x freq-pair
upper triangle); here all strip nodes are stacked on one axis (the same
NodeSet layout as the first-order hydro) and the (w1, w2) pair grid is a
dense double-vmap of a pure pair kernel over precomputed per-frequency
node fields — one fused XLA program whose FLOPs land on the MXU as batched
(N,3,3)x(N,3) contractions.  The lower triangle is masked out and filled
by Hermitian symmetry afterwards, exactly as the reference does.

Force components per pair, following Rainey's slender-body equation plus
Pinkster's terms (names match the reference):
  F_rotN   rotation of first-order inertial loads (Pinkster IV)
  F_2ndPot second-order incident-wave potential
  F_conv   convective acceleration
  F_axdv   Rainey axial-divergence acceleration
  F_nabla  body motion within the first-order wave field
  F_rslb   Rainey body-rotation terms
  F_eta    relative wave elevation at the waterline intersection

Physics deviations from the reference:
- a consistent all-radians heading convention (the reference mixes
  deg/rad at beta != 0) and the reference's grad[2][1]=du/dy index quirk
  is NOT replicated (we use the symmetric dv/dz) — both documented in
  ops/waves.py and inert at beta=0, the only heading the reference's QTF
  examples exercise;
- the Kim & Yue second-order diffraction correction for MCF members
  (reference: raft_fowt.py:1636 -> raft_member.py:1090-1205) is applied
  on the dense pair grid via `kim_yue_correction`.
"""
from __future__ import annotations
from dataclasses import dataclass

import numpy as np
import jax
import jax.numpy as jnp

from raft_tpu import _config
from raft_tpu.ops.waves import (
    wave_kinematics, kinematics_from_motion, wave_vel_gradient,
    wave_pres1st_gradient, wave_pot_2nd_order, wave_number,
)
from raft_tpu.ops.transforms import skew


def _use_qtf_kernel() -> bool:
    """Whether the dense pair grid routes through the fused Pallas
    kernel (ops/pallas/qtf_pair.py), per RAFT_TPU_QTF_KERNEL: "1"
    forces it (interpret mode — the CI parity path, the same pattern
    RAFT_TPU_PALLAS=1 uses for the solve kernel), "0"/"auto" keep the
    doubly-vmapped XLA path (the kernel's complex-typed body awaits its
    real/imag-split Mosaic port before "auto" can prefer it on
    hardware)."""
    return _config.qtf_kernel_mode() == "1"


@dataclass
class QTFData:
    """A QTF matrix on its own (coarse) frequency grid.

    qtf has shape (nw2, nw2, nh, 6), dimensional [N/m^2-ish per unit
    amplitude pair], Hermitian in the two frequency axes.
    """

    heads_rad: np.ndarray
    w: np.ndarray
    qtf: np.ndarray


# --------------------------------------------------------------------------
# .12d file I/O  (reference: raft_fowt.py:1651-1725)
# --------------------------------------------------------------------------

def read_qtf_12d(path: str, rho: float = 1025.0, g: float = 9.81,
                 ULEN: float = 1.0) -> QTFData:
    """Read a WAMIT .12d difference-frequency QTF file.

    Columns: T1 T2 head1 head2 DOF |F| phase Re Im, periods in seconds.
    Only unidirectional QTFs (head1 == head2) are supported, as in the
    reference (raft_fowt.py:1668-1669).  The file holds one triangle; the
    other is filled by Hermitian symmetry.
    """
    data = np.loadtxt(path)
    w12 = 2.0 * np.pi / data[:, 0:2]
    if not np.allclose(data[:, 2], data[:, 3]):
        raise ValueError("only unidirectional QTFs are supported")
    heads = np.sort(np.unique(data[:, 2]))
    w1 = np.unique(w12[:, 0])
    w2 = np.unique(w12[:, 1])
    if not (len(w1) == len(w2) and np.allclose(w1, w2)):
        raise ValueError("both frequency columns must contain the same values")

    qtf = np.zeros([len(w1), len(w2), len(heads), 6],
                   dtype=complex)  # raftlint: disable=RTL003 host-side .12d I/O stays numpy complex128
    for row, (ww1, ww2) in zip(data, w12):
        i1 = int(np.argmin(np.abs(w1 - ww1)))
        i2 = int(np.argmin(np.abs(w2 - ww2)))
        ih = int(np.argmin(np.abs(heads - row[2])))
        idof = int(round(row[4])) - 1
        factor = rho * g * ULEN * (ULEN if idof >= 3 else 1.0)
        val = factor * (row[7] + 1j * row[8])
        qtf[i1, i2, ih, idof] = val
        if i1 != i2:
            qtf[i2, i1, ih, idof] = np.conj(val)
    nbad = int((~np.isfinite(qtf)).sum())
    if nbad:
        raise ValueError(
            f"QTF .12d file '{path}': {nbad} non-finite value(s) — the "
            f"file is corrupt or truncated; delete it (and its .key "
            f"checkpoint) and re-run the QTF computation")
    return QTFData(heads_rad=np.deg2rad(heads), w=w1, qtf=qtf)


def write_qtf_12d(path: str, qtf, w, heads_rad, rho: float = 1025.0,
                  g: float = 9.81) -> None:
    """Write the upper triangle of a (nw,nw,nh,6) QTF in .12d format
    (reference: raft_fowt.py:1703-1725).

    Row assembly is vectorized (the quadruple Python loop it replaces
    executed O(nh*6*nw^2) interpreted iterations — minutes at the dense
    pair grids) and the file is emitted through numpy's C formatter;
    the ``% .8e`` / ``%d`` row format is byte-identical to the previous
    per-value f-strings, ih-major / DOF / upper-triangle row order
    preserved."""
    w = np.asarray(w)
    qtf = np.asarray(qtf)
    heads = np.atleast_1d(heads_rad)
    ULEN = 1.0
    nh = len(heads)
    i1, i2 = np.triu_indices(len(w))
    F = np.moveaxis(qtf[i1, i2, :, :], 0, -1) / (rho * g * ULEN)
    rows = np.empty((nh, 6, i1.size, 9), float)
    rows[..., 0] = 2.0 * np.pi / w[i1]
    rows[..., 1] = 2.0 * np.pi / w[i2]
    rows[..., 2] = np.rad2deg(heads)[:, None, None]
    rows[..., 3] = rows[..., 2]
    rows[..., 4] = (np.arange(6) + 1.0)[None, :, None]
    rows[..., 5] = np.abs(F)
    rows[..., 6] = np.angle(F)
    rows[..., 7] = F.real
    rows[..., 8] = F.imag
    with open(path, "w") as f:
        np.savetxt(f, rows.reshape(-1, 9),
                   fmt="% .8e % .8e % .8e % .8e %d % .8e % .8e % .8e % .8e")


def write_rao_4(path, w, beta_rad, Xi) -> None:
    """Write first-order RAOs in WAMIT .4 format (reference:
    raft_fowt.py:1420-1433): period, heading, DOF, |X|, phase, Re, Im —
    the RAO snapshot the reference drops next to its QTF files so a run
    can be audited/resumed."""
    Xi = np.asarray(Xi)
    w = np.asarray(w)
    beta = float(np.rad2deg(beta_rad))
    with open(path, "w") as f:
        for idof in range(Xi.shape[0]):
            for w1, x in zip(w, Xi[idof, :]):
                f.write(f"{2*np.pi/w1: 8.4e} {beta: 8.4e} {idof+1} "
                        f"{np.abs(x): 8.4e} {np.angle(x): 8.4e} "
                        f"{x.real: 8.4e} {x.imag: 8.4e}\n")


# --------------------------------------------------------------------------
# Kim & Yue analytical 2nd-order diffraction correction
# (reference: raft_member.py:1090-1205, applied at raft_fowt.py:1636)
# --------------------------------------------------------------------------

def kim_yue_correction(fowt, pose, beta, Nm: int = 10):
    """Sum of the Kim & Yue (1989/1990) bottom-mounted-cylinder
    difference-frequency corrections over the MCF-flagged surface-piercing
    members, on the dense (i1,i2) QTF pair grid.  Returns (nw2,nw2,6)
    complex (zero when no member is flagged).

    Faithful to the reference, including its quirks: the real part only is
    kept (diffraction share, avoiding double counting with the Rainey
    terms, :1148/:1196), the segment phase uses the waterline intersection
    point rwl (:1199 — not the segment midpoint), end nodes reuse ds as the
    radius (:1173-1179), and the whole force is conjugated where k1 < k2
    (:1202-1203)."""
    from raft_tpu.ops.special import hankel1p_all

    w2 = np.asarray(fowt.w1_2nd)
    k2g = np.asarray(fowt.k1_2nd)
    nw2 = len(w2)
    h = fowt.depth
    rho, g = fowt.rho_water, fowt.g

    members = [(im, m) for im, m in enumerate(fowt.members)
               if getattr(m, "MCF", False)
               and float(m.rA0[2]) * float(m.rB0[2]) < 0]
    if not members:
        return jnp.zeros((nw2, nw2, 6), dtype=_config.complex_dtype())

    k1 = jnp.asarray(k2g)[:, None]     # (nw2,1) broadcast over pairs
    k2 = jnp.asarray(k2g)[None, :]
    w1 = jnp.asarray(w2)[:, None]
    wv2 = jnp.asarray(w2)[None, :]
    cosB, sinB = np.cos(beta), np.sin(beta)
    rPRP = pose["r6"][:3]

    def _recip(z):
        """1/z with overflow-safe zero for huge |z| (high-order Hankel
        magnitudes saturate the dtype; the physical limit of 1/(H'H') is
        exactly 0 there)."""
        r = 1.0 / z
        ok = jnp.isfinite(jnp.real(r)) & jnp.isfinite(jnp.imag(r))
        return jnp.where(ok, r, 0.0)

    def omega_sum(Hp, weights):
        """sum_n weights_n * Omega_n where Omega_n = 1/(Hp_{n+1} conj(Hp_n))
        - 1/(Hp_n conj(Hp_{n+1})) on the (nw2, nw2) pair grid; Hp is the
        (Nm+2, nw2) derivative table on the k grid, weights a per-n list of
        grids or a scalar (reference: raft_member.py:1102-1109)."""
        tot = 0.0
        for n in range(Nm + 1):
            a1 = Hp[n + 1][:, None] * jnp.conj(Hp[n][None, :])
            a2 = Hp[n][:, None] * jnp.conj(Hp[n + 1][None, :])
            wn = weights[n] if isinstance(weights, (list, tuple)) else weights
            tot = tot + wn * (_recip(a1) - _recip(a2))
        return tot

    def sinh_over_coshcosh(a, b, c):
        """sinh(a) / (cosh(b) cosh(c)), overflow-stable for |a| <= b + c
        (same exp-ratio algebra as ops/waves.py's depth ratios)."""
        num = jnp.exp(a - b - c) - jnp.exp(-a - b - c)
        den = (1.0 + jnp.exp(-2.0 * b)) * (1.0 + jnp.exp(-2.0 * c))
        return 2.0 * num / den

    def inv_coshcosh(b, c):
        return 4.0 * jnp.exp(-(b + c)) / (
            (1.0 + jnp.exp(-2.0 * b)) * (1.0 + jnp.exp(-2.0 * c)))

    # Hankel derivative tables cached by radius (uniform columns share one)
    _hp_cache: dict = {}

    def hp_table(R):
        key = round(float(R), 12)
        if key not in _hp_cache:
            _hp_cache[key] = hankel1p_all(jnp.asarray(k2g) * R, Nm + 1)
        return _hp_cache[key]

    F = jnp.zeros((nw2, nw2, 6), dtype=_config.complex_dtype())
    for im, m in members:
        mpose = pose["members"][im]
        rA = np.asarray(mpose["rA"])
        rB = np.asarray(mpose["rB"])
        rm = np.asarray(mpose["r"])
        p1 = np.asarray(mpose["p1"])
        p2 = np.asarray(mpose["p2"])
        ds = np.asarray(m.ds)
        dls = np.asarray(m.dls)

        # wave-aligned transverse force direction (:1128-1131)
        bvec = np.array([cosB, sinB, 0.0])
        pf = np.dot(bvec, p1) * p1 + np.dot(bvec, p2) * p2
        pf = pf / np.linalg.norm(pf)
        pf = jnp.asarray(pf)

        # waterline intersection and radius (:1136-1139)
        rwl = rA + (rB - rA) * (0.0 - rA[2]) / (rB[2] - rA[2])
        order = np.argsort(rm[:, 2])
        Rwl = float(np.interp(0.0, rm[order, 2], 0.5 * ds[order]))
        phase = jnp.exp(-1j * ((k1 - k2) * (cosB * rwl[0] + sinB * rwl[1])))

        # ---- waterline relative-elevation term (:1134-1149) ----
        k1R, k2R = k1 * Rwl, k2 * Rwl
        Fwl = -rho * g * Rwl * 2j / jnp.pi / (k1R * k2R) * omega_sum(
            hp_table(Rwl), 1.0)
        Fwl = jnp.real(Fwl) * phase                           # (nw2,nw2)
        off_wl = jnp.asarray(rwl) - rPRP
        F = F + Fwl[:, :, None] * jnp.concatenate(
            [pf, jnp.cross(off_wl, pf)])[None, None, :]

        # ---- Bernoulli quadratic-velocity depth integral (:1155-1200) ----
        for il in range(len(rm) - 1):
            z1 = float(rm[il, 2])
            if z1 > 0:
                continue
            z2 = min(float(rm[il + 1, 2]), 0.0)
            R1 = ds[il] / 2.0 if dls[il] != 0 else ds[il]
            R2 = ds[il + 1] / 2.0 if dls[il + 1] != 0 else ds[il]
            R = 0.5 * (R1 + R2)
            k1R, k2R = k1 * R, k2 * R

            diag = (w1 == wv2)
            kp = k1 + k2
            km_safe = jnp.where(diag, 1.0, k1 - k2)
            k1h, k2h = k1R * (h / R), k2R * (h / R)
            # Im/Ip pre-divided by cosh(k1h)cosh(k2h) with the
            # overflow-stable exp-ratio algebra (the raw sinh/cosh of the
            # reference overflow for (k1+k2)h beyond the dtype range)
            icc = inv_coshcosh(k1h, k2h)
            sp2 = sinh_over_coshcosh(kp * (z2 + h), k1h, k2h) / (k1h + k2h)
            sp1 = sinh_over_coshcosh(kp * (z1 + h), k1h, k2h) / (k1h + k2h)
            sm2 = jnp.where(
                diag, (z2 + h) / h * icc,
                sinh_over_coshcosh(km_safe * (z2 + h), k1h, k2h)
                / jnp.where(diag, 1.0, k1h - k2h))
            sm1 = jnp.where(
                diag, (z1 + h) / h * icc,
                sinh_over_coshcosh(km_safe * (z1 + h), k1h, k2h)
                / jnp.where(diag, 1.0, k1h - k2h))
            Im_cc = 0.5 * (sp2 - sm2 - sp1 + sm1)
            Ip_cc = 0.5 * (sp2 + sm2 - sp1 - sm1)

            t1 = jnp.sqrt(k1h * jnp.tanh(k1h))
            t2 = jnp.sqrt(k2h * jnp.tanh(k2h))
            pref = k1h * k2h / t1 / t2
            weights = [pref * (Im_cc + Ip_cc * n * (n + 1) / k1R / k2R)
                       for n in range(Nm + 1)]
            dF = (rho * g * R * 2j / jnp.pi / (k1R * k2R)
                  * omega_sum(hp_table(R), weights))
            rmid = 0.5 * (rm[il] + rm[il + 1])
            dF = jnp.real(dF) * phase
            off = jnp.asarray(rmid) - rPRP
            F = F + dF[:, :, None] * jnp.concatenate(
                [pf, jnp.cross(off, pf)])[None, None, :]

    # conjugate where k1 < k2 (:1202-1203)
    conj_mask = (k1 < k2)
    F = jnp.where(conj_mask[:, :, None], jnp.conj(F), F)
    return F


# --------------------------------------------------------------------------
# slender-body QTF  (reference: raft_fowt.py:1385-1648)
# --------------------------------------------------------------------------

def calc_qtf_slender_body(fowt, pose, beta, Xi0=None, M_struc=None,
                          rows=None):
    """Slender-body QTF for one wave heading, (nw2, nw2, 6) complex.

    Parameters
    ----------
    fowt : FOWTModel with w1_2nd/k1_2nd set (potSecOrder==1 grid)
    pose : fowt_pose output at the mean-offset position (concrete values;
        the waterline-crossing node selection is host-side geometry)
    beta : wave heading [rad]
    Xi0 : (6, nw) motion RAOs on the MODEL grid, or None for a fixed body
    M_struc : (6,6) structural mass matrix for the Pinkster-IV term
    rows : optional (nr,) array of w1-row indices.  When given, only those
        rows of the RAW pair grid are computed and returned (nr, nw2, 6) —
        no Kim&Yue correction and no Hermitian completion — so callers can
        shard the row axis over a device mesh (`calc_qtf_sharded`).
    """
    w2 = jnp.asarray(fowt.w1_2nd)
    k2 = jnp.asarray(fowt.k1_2nd)
    nw2 = len(fowt.w1_2nd)
    h = fowt.depth
    rho, g = fowt.rho_water, fowt.g

    # ---- resample RAOs to the 2nd-order grid (reference :1415-1417) ----
    if Xi0 is None:
        Xi = jnp.zeros((6, nw2), dtype=_config.complex_dtype())
    else:
        wm = jnp.asarray(fowt.w)
        Xi = jax.vmap(lambda row: jnp.interp(w2, wm, row.real, left=0.0, right=0.0)
                      + 1j * jnp.interp(w2, wm, row.imag, left=0.0, right=0.0))(
            jnp.asarray(Xi0))

    # ---- first-order inertial loads for Pinkster IV (reference :1437-1440)
    if M_struc is None:
        M_struc = jnp.zeros((6, 6), dtype=_config.real_dtype())
    M_struc = jnp.asarray(M_struc)
    F1st = jnp.concatenate([
        M_struc[0, 0] * (-w2**2 * Xi[0:3, :]),
        M_struc[3:, 3:] @ (-w2**2 * Xi[3:, :]),
    ])

    # ---- stacked node fields on the 2nd-order grid ----
    nd = fowt.nodes
    r = jnp.asarray(pose["r"])                   # (N,3) global positions
    rPRP = pose["r6"][:3]
    offsets = r - rPRP
    q, p1, p2 = pose["q"], pose["p1"], pose["p2"]
    qMat, p1Mat, p2Mat = pose["qMat"], pose["p1Mat"], pose["p2Mat"]
    Ca_p1 = jnp.asarray(nd.Ca_p1)
    Ca_p2 = jnp.asarray(nd.Ca_p2)
    Ca_End = jnp.asarray(nd.Ca_End)

    # per-node volumes with partial-submergence scaling (reference :1533-1539)
    dls = jnp.asarray(nd.dls)
    z = r[:, 2]
    dls_safe = jnp.where(dls == 0.0, 1.0, dls)
    scale = jnp.where(z + 0.5 * dls > 0.0, (0.5 * dls - z) / dls_safe, 1.0)
    v_i = jnp.asarray(nd.v_side) * scale
    v_end = jnp.asarray(nd.v_end)
    a_i = jnp.asarray(nd.a_i)
    submerged = (z < 0.0)                        # strict, reference :1522-1523

    ones = jnp.ones(nw2, dtype=_config.complex_dtype())
    u_n, _, _ = wave_kinematics(ones, beta, w2, k2, h, r, rho=rho, g=g)  # (N,3,nw2)
    dr_n, nodeV, _ = kinematics_from_motion(offsets, Xi, w2)             # (N,3,nw2)
    grad_u = wave_vel_gradient(w2, k2, beta, h, r[:, None, :])           # (N,nw2,3,3)
    grad_p = wave_pres1st_gradient(k2, beta, h, r[:, None, :], rho=rho, g=g)  # (N,nw2,3)
    # relative axial velocity (reference :1484)
    nodeV_ax = jnp.einsum("ncw,nc->nw", u_n - nodeV, q)

    # inertial projection matrices per node
    Minert = ((1.0 + Ca_p1)[:, None, None] * p1Mat
              + (1.0 + Ca_p2)[:, None, None] * p2Mat)
    CaMat = (Ca_p1[:, None, None] * p1Mat + Ca_p2[:, None, None] * p2Mat)
    ptMat = p1Mat + p2Mat

    # ---- waterline-crossing members (host-side geometry selection;
    #      reference :1487-1497, 1603-1626).  All per-member frequency
    #      fields are precomputed here so the pair kernel only indexes. ----
    r_np = np.asarray(r)
    mem_idx = np.asarray(nd.member_index)
    wl_members = []
    for im, m in enumerate(fowt.members):
        sel = np.where(mem_idx == im)[0]
        rm = r_np[sel]
        if len(rm) == 0 or rm[0, 2] * rm[-1, 2] >= 0:
            continue
        r_int = rm[0] + (rm[-1] - rm[0]) * (0.0 - rm[0, 2]) / (rm[-1, 2] - rm[0, 2])
        below = np.where(rm[:, 2] < 0)[0]
        i_wl = below[-1]
        if m.circular:
            d_wl = (0.5 * (m.ds[i_wl] + m.ds[i_wl + 1])
                    if i_wl != len(m.ds) - 1 else m.ds[i_wl])
            a_wl_area = 0.25 * np.pi * d_wl**2
        else:
            if i_wl != len(m.ds) - 1:
                d1 = 0.5 * (m.ds[i_wl, 0] + m.ds[i_wl + 1, 0])
                d2w = 0.5 * (m.ds[i_wl, 1] + m.ds[i_wl + 1, 1])
            else:
                d1, d2w = m.ds[i_wl, 0], m.ds[i_wl, 1]
            a_wl_area = d1 * d2w
        # global node index whose Ca the reference's loop leaks into the
        # waterline term: the last node that passed the submerged guard
        # (raft_fowt.py:1527-1529 'continue' on r[il,2]>=0, used at :1613)
        last = int(sel[below[-1]])
        # frequency fields at the intersection point (unit wave amplitude;
        # rho=g=1 so the "pressure" output is the wave elevation)
        _, udw, eta = wave_kinematics(ones, beta, w2, k2, h,
                                      jnp.asarray(r_int), rho=1.0, g=1.0)
        drw, _, aw = kinematics_from_motion(jnp.asarray(r_int) - rPRP, Xi, w2)
        eta_r = eta - drw[2, :]
        pm1, pm2 = p1[last], p2[last]
        # g projected along p1/p2 per frequency (reference :1506-1509)
        g_e1 = -g * (jnp.cross(Xi[3:, :], pm1[:, None].astype(_config.complex_dtype()),
                               axisa=0, axisb=0, axisc=0)[2][None, :] * pm1[:, None]
                     + jnp.cross(Xi[3:, :], pm2[:, None].astype(_config.complex_dtype()),
                                 axisa=0, axisb=0, axisc=0)[2][None, :] * pm2[:, None])
        wl_members.append(dict(
            r_int=jnp.asarray(r_int), a=a_wl_area, last=last,
            udw=udw, aw=aw, eta_r=eta_r, g_e1=g_e1))

    # ---- pair kernel over the dense (i1,i2) grid ----
    idx = jnp.arange(nw2, dtype=jnp.int32)

    def pair(i1, i2):
        w1, wv2 = w2[i1], w2[i2]
        kk1, kk2 = k2[i1], k2[i2]
        Xi1, Xi2 = Xi[:, i1], Xi[:, i2]
        u1, u2 = u_n[:, :, i1], u_n[:, :, i2]
        gu1, gu2 = grad_u[:, i1], grad_u[:, i2]              # (N,3,3)
        gdu1, gdu2 = 1j * w1 * gu1, 1j * wv2 * gu2
        dr1, dr2 = dr_n[:, :, i1], dr_n[:, :, i2]
        nv1, nv2 = nodeV[:, :, i1], nodeV[:, :, i2]
        nax1, nax2 = nodeV_ax[:, i1], nodeV_ax[:, i2]
        gp1, gp2 = grad_p[:, i1], grad_p[:, i2]

        # Pinkster IV (reference :1449-1456)
        F_rotN = jnp.concatenate([
            0.25 * (jnp.cross(Xi1[3:], jnp.conj(F1st[0:3, i2]))
                    + jnp.cross(jnp.conj(Xi2[3:]), F1st[0:3, i1])),
            0.25 * (jnp.cross(Xi1[3:], jnp.conj(F1st[3:, i2]))
                    + jnp.cross(jnp.conj(Xi2[3:]), F1st[3:, i1])),
        ])

        # 2nd-order potential (reference :1541-1544)
        acc_2p, p_2nd = wave_pot_2nd_order(w1, wv2, kk1, kk2, beta, beta, h, r,
                                           g=g, rho=rho)
        f_2ndPot = (rho * v_i)[:, None] * jnp.einsum("nij,nj->ni", Minert.astype(_config.complex_dtype()), acc_2p)

        # convective acceleration (reference :1546-1548)
        conv_acc = 0.25 * (jnp.einsum("nij,nj->ni", gu1, jnp.conj(u2))
                           + jnp.einsum("nij,nj->ni", jnp.conj(gu2), u1))
        f_conv = (rho * v_i)[:, None] * jnp.einsum("nij,nj->ni", Minert.astype(_config.complex_dtype()), conv_acc)

        # Rainey axial divergence (reference :1550-1551, helpers.py:228-251)
        dwdz1 = jnp.einsum("nij,nj,ni->n", gu1, q.astype(_config.complex_dtype()), q.astype(_config.complex_dtype()))
        dwdz2 = jnp.einsum("nij,nj,ni->n", gu2, q.astype(_config.complex_dtype()), q.astype(_config.complex_dtype()))
        def transverse(vec):
            return vec - jnp.einsum("nc,nc->n", vec, q.astype(_config.complex_dtype()))[:, None] * q
        u1t, u2t = transverse(u1), transverse(u2)
        nv1t, nv2t = transverse(nv1), transverse(nv2)
        axdv = 0.25 * (dwdz1[:, None] * jnp.conj(u2t - nv2t)
                       + jnp.conj(dwdz2)[:, None] * (u1t - nv1t))
        axdv = transverse(axdv)
        f_axdv = (rho * v_i)[:, None] * jnp.einsum("nij,nj->ni", CaMat.astype(_config.complex_dtype()), axdv)

        # body motion in the 1st-order field (reference :1553-1555)
        acc_nabla = 0.25 * (jnp.einsum("nij,nj->ni", gdu1, jnp.conj(dr2))
                            + jnp.einsum("nij,nj->ni", jnp.conj(gdu2), dr1))
        f_nabla = (rho * v_i)[:, None] * jnp.einsum("nij,nj->ni", Minert.astype(_config.complex_dtype()), acc_nabla)

        # Rainey body-rotation terms (reference :1557-1576)
        OM1 = -skew(1j * w1 * Xi1[3:])
        OM2 = -skew(1j * wv2 * Xi2[3:])
        f_rslb = -0.25 * 2.0 * jnp.einsum(
            "nij,nj->ni", CaMat.astype(_config.complex_dtype()),
            (OM1 @ jnp.conj(nax2[:, None] * q).T).T
            + (jnp.conj(OM2) @ (nax1[:, None] * q).T).T)
        f_rslb = (rho * v_i)[:, None] * f_rslb

        u1a = u1 - nv1
        u2a = u2 - nv2
        V1 = gu1 + OM1[None, :, :]
        V2 = gu2 + OM2[None, :, :]
        aux = 0.25 * (jnp.einsum("nij,nj->ni", V1,
                                 jnp.conj(jnp.einsum("nij,nj->ni", CaMat.astype(_config.complex_dtype()), u2a)))
                      + jnp.einsum("nij,nj->ni", jnp.conj(V2),
                                   jnp.einsum("nij,nj->ni", CaMat.astype(_config.complex_dtype()), u1a)))
        aux = aux - jnp.einsum("nij,nj->ni", qMat.astype(_config.complex_dtype()), aux)
        f_rslb = f_rslb + (rho * v_i)[:, None] * aux

        u1at = u1a - jnp.einsum("nij,nj->ni", qMat.astype(_config.complex_dtype()), u1a)
        u2at = u2a - jnp.einsum("nij,nj->ni", qMat.astype(_config.complex_dtype()), u2a)
        aux2 = 0.25 * (jnp.einsum("nij,nj->ni", CaMat.astype(_config.complex_dtype()),
                                  jnp.einsum("nij,nj->ni", V1, jnp.conj(u2at)))
                       + jnp.einsum("nij,nj->ni", CaMat.astype(_config.complex_dtype()),
                                    jnp.einsum("nij,nj->ni", jnp.conj(V2), u1at)))
        f_rslb = f_rslb - (rho * v_i)[:, None] * aux2

        # axial/end effects (reference :1578-1601)
        f_2ndPot = f_2ndPot + a_i[:, None] * p_2nd[:, None] * q
        f_2ndPot = f_2ndPot + (rho * v_end * Ca_End)[:, None] * jnp.einsum(
            "nij,nj->ni", qMat.astype(_config.complex_dtype()), acc_2p)
        f_conv = f_conv + (rho * v_end * Ca_End)[:, None] * jnp.einsum(
            "nij,nj->ni", qMat.astype(_config.complex_dtype()), conv_acc)
        f_nabla = f_nabla + (rho * v_end * Ca_End)[:, None] * jnp.einsum(
            "nij,nj->ni", qMat.astype(_config.complex_dtype()), acc_nabla)
        p_nabla = 0.25 * (jnp.einsum("nc,nc->n", gp1, jnp.conj(dr2))
                          + jnp.einsum("nc,nc->n", jnp.conj(gp2), dr1))
        f_nabla = f_nabla + (a_i * p_nabla)[:, None] * q
        p_drop = -2.0 * 0.25 * 0.5 * rho * jnp.einsum(
            "nc,nc->n",
            jnp.einsum("nij,nj->ni", ptMat.astype(_config.complex_dtype()), u1 - nv1),
            jnp.conj(jnp.einsum("nij,nj->ni", CaMat.astype(_config.complex_dtype()), u2 - nv2)))
        f_conv = f_conv + (a_i[:, None] * p_drop[:, None]) * q

        # wrench about the PRP, masked to submerged nodes
        f_side = (f_2ndPot + f_conv + f_axdv + f_nabla + f_rslb) \
            * submerged[:, None].astype(float)
        mom = jnp.cross(offsets.astype(_config.complex_dtype()), f_side)
        F_side = jnp.concatenate([jnp.sum(f_side, axis=0), jnp.sum(mom, axis=0)])

        # waterline relative-elevation term per crossing member
        # (reference :1603-1631; all fields precomputed outside the kernel)
        F_eta = jnp.zeros(6, dtype=_config.complex_dtype())
        for wm in wl_members:
            last = wm["last"]
            aA = wm["a"]
            # reference quirk: Ca at the waterline is the LAST node's value
            # (loop-leaked variable, raft_fowt.py:1527-1529 used at :1613)
            Minert_wl = Minert[last].astype(_config.complex_dtype())
            CaMat_wl = CaMat[last].astype(_config.complex_dtype())
            udw, aw, eta_r, g_e1 = wm["udw"], wm["aw"], wm["eta_r"], wm["g_e1"]
            f_eta = 0.25 * (udw[:, i1] * jnp.conj(eta_r[i2])
                            + jnp.conj(udw[:, i2]) * eta_r[i1])
            f_eta = rho * aA * (Minert_wl @ f_eta)
            a_eta = 0.25 * (aw[:, i1] * jnp.conj(eta_r[i2])
                            + jnp.conj(aw[:, i2]) * eta_r[i1])
            f_eta = f_eta - rho * aA * (CaMat_wl @ a_eta)
            f_eta = f_eta - 0.25 * rho * aA * (g_e1[:, i1] * jnp.conj(eta_r[i2])
                                               + jnp.conj(g_e1[:, i2]) * eta_r[i1])
            off = (wm["r_int"] - rPRP).astype(_config.complex_dtype())
            F_eta = F_eta + jnp.concatenate([f_eta, jnp.cross(off, f_eta)])

        return F_rotN + F_side + F_eta

    if rows is not None:
        return jax.vmap(jax.vmap(pair, in_axes=(None, 0)),
                        in_axes=(0, None))(jnp.asarray(rows), idx)

    if _use_qtf_kernel():
        # fused Pallas pair-grid kernel: same precomputed fields, the
        # (i1, i2) product tiled with w2 on the lane axis and the whole
        # per-pair force assembly VMEM-resident (ops/pallas/qtf_pair.py)
        from raft_tpu.ops.pallas.qtf_pair import qtf_pair_grid

        wl = None
        if wl_members:
            rdt = _config.real_dtype()
            wl = dict(
                c=jnp.stack([jnp.stack([m["udw"], m["aw"], m["g_e1"]])
                             for m in wl_members]),
                eta=jnp.stack([m["eta_r"] for m in wl_members]),
                mats=jnp.stack([jnp.stack([Minert[m["last"]],
                                           CaMat[m["last"]]])
                                for m in wl_members]),
                geo=jnp.stack([jnp.concatenate([
                    jnp.asarray([m["a"]], rdt),
                    jnp.asarray(m["r_int"] - rPRP, rdt)])
                    for m in wl_members]),
            )
        fields = dict(
            w2=w2, k2=k2, Xi=Xi, F1st=F1st,
            u=u_n, dr=dr_n, nv=nodeV, nax=nodeV_ax,
            gu=jnp.moveaxis(grad_u, 1, -1),      # (N,3,3,nw2) lane-last
            gp=jnp.moveaxis(grad_p, 1, -1),      # (N,3,nw2) lane-last
            q=q, offsets=offsets, pos=r,
            Minert=Minert, CaMat=CaMat, ptMat=ptMat, qMat=qMat,
            nodescal=jnp.stack(
                [v_i, v_end * Ca_End, a_i,
                 submerged.astype(_config.real_dtype())], axis=1),
            wl=wl)
        Q = qtf_pair_grid(fields, beta, h, rho, g)
    else:
        Q = jax.vmap(jax.vmap(pair, in_axes=(None, 0)),
                     in_axes=(0, None))(idx, idx)

    # Kim & Yue analytical 2nd-order diffraction correction for MCF
    # members (reference: raft_fowt.py:1636 -> raft_member.py:1090-1205)
    Q = Q + kim_yue_correction(fowt, pose, beta)

    # keep only the upper triangle (w2 >= w1), then Hermitian-complete
    # (reference :1638-1640)
    upper = (w2[None, :] >= w2[:, None]).astype(float)
    Q = Q * upper[:, :, None]
    eye = jnp.eye(nw2)[:, :, None]
    return Q + jnp.conj(jnp.swapaxes(Q, 0, 1)) - eye * jnp.conj(Q)


def calc_qtf_sharded(fowt, pose, beta, Xi0=None, M_struc=None, mesh=None,
                     axis_name="qtf_rows"):
    """QTF pair grid sharded over a device mesh — the framework's
    context-parallel axis (SURVEY §5.7: the reference handles the
    2nd-order grid's cost by decimation; here the (w1, w2) pair grid —
    the "sequence" dimension of this workload — is sharded by w1-row
    across devices, with the Hermitian completion as the only cross-
    device exchange).

    Returns the same (nw2, nw2, 6) Hermitian-completed QTF as
    `calc_qtf_slender_body` (validated to ~1e-12 on an 8-device virtual
    CPU mesh in tests/test_qtf.py)."""
    if mesh is None:
        return calc_qtf_slender_body(fowt, pose, beta, Xi0=Xi0,
                                     M_struc=M_struc)
    from jax.sharding import NamedSharding, PartitionSpec as P

    nw2 = len(fowt.w1_2nd)
    ndev = int(np.prod(list(mesh.shape.values())))
    npad = (-nw2) % ndev
    # pad with wrapped rows (discarded after the gather)
    rows_all = jnp.asarray(np.arange(nw2 + npad) % nw2)
    rows_sh = jax.device_put(rows_all, NamedSharding(mesh, P(axis_name)))

    fn = jax.jit(lambda r: calc_qtf_slender_body(
        fowt, pose, beta, Xi0=Xi0, M_struc=M_struc, rows=r))
    Q = fn(rows_sh)[:nw2]

    Q = Q + kim_yue_correction(fowt, pose, beta)
    w2 = jnp.asarray(fowt.w1_2nd)
    upper = (w2[None, :] >= w2[:, None]).astype(float)
    Q = Q * upper[:, :, None]
    eye = jnp.eye(nw2)[:, :, None]
    return Q + jnp.conj(jnp.swapaxes(Q, 0, 1)) - eye * jnp.conj(Q)


# --------------------------------------------------------------------------
# 2nd-order force from QTF + spectrum  (reference: raft_fowt.py:1728-1818)
# --------------------------------------------------------------------------

def hydro_force_2nd(qtf, heads_rad, w2, beta, S0, w, interp_mode="qtf"):
    """Mean drift + slowly-varying difference-frequency force amplitudes.

    qtf: (nw2, nw2, nh, 6) Hermitian; heads_rad (nh,); w2 (nw2,) QTF grid;
    beta: case wave heading [rad]; S0: (nw,) wave spectrum on the model
    grid w (nw,).  Returns (f_mean (6,), f (6, nw) real amplitudes).
    """
    qtf = jnp.asarray(qtf)
    heads = np.atleast_1d(np.asarray(heads_rad, float))
    w2 = jnp.asarray(w2)
    w = jnp.asarray(w)
    S0 = jnp.asarray(S0)
    nw = len(w)
    dw = w[1] - w[0]

    # heading interpolation with clamping (reference :1747-1757)
    if len(heads) == 1:
        Qh = qtf[:, :, 0, :]
    else:
        b = float(np.clip(beta, heads[0], heads[-1]))
        i2 = int(np.clip(np.searchsorted(heads, b), 1, len(heads) - 1))
        f2 = (b - heads[i2 - 1]) / (heads[i2] - heads[i2 - 1])
        Qh = qtf[:, :, i2 - 1, :] * (1 - f2) + qtf[:, :, i2, :] * f2

    def interp2(Qd):
        """separable bilinear (nw2,nw2)->(nw,nw) with zero fill outside."""
        def i1d(row):
            return (jnp.interp(w, w2, row.real, left=0.0, right=0.0)
                    + 1j * jnp.interp(w, w2, row.imag, left=0.0, right=0.0))
        Qc = jax.vmap(i1d, in_axes=0)(Qd)          # interp along axis 1
        return jax.vmap(i1d, in_axes=1, out_axes=1)(Qc)  # then axis 0

    jj = jnp.arange(nw, dtype=jnp.int32)
    i2idx = jj[None, :] + jj[:, None]              # [imu, j] -> j + imu
    valid = (i2idx < nw)
    i2c = jnp.clip(i2idx, 0, nw - 1)

    if interp_mode == "qtf":
        # interpolate the QTF to the model grid, then sum off-diagonals
        # (reference :1786-1804, the default mode)
        def per_dof(Qd):
            Qi = interp2(Qd)
            Qdiag = Qi[jj[None, :], i2c] * valid    # (imu, j)
            Smu = S0[i2c] * valid
            ssum = jnp.sum(S0[None, :] * Smu * jnp.abs(Qdiag) ** 2, axis=1)
            fi = 4.0 * jnp.sqrt(ssum) * dw
            fi = fi.at[0].set(0.0)
            fmean = 2.0 * jnp.sum(S0 * jnp.real(jnp.diagonal(Qi))) * dw
            return fmean, fi
    elif interp_mode == "spectrum":
        # force spectrum on the QTF grid, then interpolate (reference
        # :1760-1784)
        nw2n = len(np.asarray(w2))
        S2 = (jnp.interp(w2, w, S0, left=0.0, right=0.0))
        j2 = jnp.arange(nw2n, dtype=jnp.int32)
        i2idx2 = j2[None, :] + j2[:, None]
        valid2 = (i2idx2 < nw2n)
        i2c2 = jnp.clip(i2idx2, 0, nw2n - 1)
        dw2 = w2[1] - w2[0]
        mu = w2 - w2[0]

        def per_dof(Qd):
            Qdiag = Qd[j2[None, :], i2c2] * valid2
            Smu = S2[i2c2] * valid2
            Sf = 8.0 * jnp.sum(S2[None, :] * Smu * jnp.abs(Qdiag) ** 2, axis=1) * dw2
            Sf = Sf.at[0].set(0.0)
            Sf_i = jnp.interp(w - w[0], mu, Sf, left=0.0, right=0.0)
            fi = jnp.sqrt(2.0 * Sf_i * dw)
            fmean = 2.0 * jnp.sum(S2 * jnp.real(jnp.diagonal(Qd))) * dw2
            return fmean, fi
    else:
        raise ValueError(f"unknown interp_mode '{interp_mode}'")

    fmean, f = jax.vmap(per_dof, in_axes=2, out_axes=0)(Qh)

    # shift by one frequency: difference frequencies start at 0, the model
    # grid starts at dw (reference :1806-1810)
    f = jnp.concatenate([f[:, 1:],
                         jnp.zeros((6, 1), dtype=f.dtype)], axis=1)
    return fmean, f
