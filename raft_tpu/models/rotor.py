"""Rotor aero-servo layer: differentiable BEM + control linearization.

TPU-first replacement for the reference Rotor class and its CCBlade
(Fortran) dependency (reference: raft/raft_rotor.py).  Structure:

- `build_rotor(turbine, w, ir)` parses the turbine dict ONCE (numpy):
  blade geometry resampled to `nr` elements (raft_rotor.py:309-320),
  airfoil polars interpolated spanwise by relative thickness with PCHIP
  (raft_rotor.py:250-296), then each element's cl/cd(alpha) fitted with the
  same smoothing-spline family CCBlade's CCAirfoil uses and converted to
  piecewise-cubic coefficient tables evaluable in jnp.
- `bem_evaluate(...)` is a pure-jnp blade-element-momentum solve of Ning
  (2014)'s single-residual formulation (the algorithm inside CCBlade's
  Fortran `inductionfactors`): bracketed bisection (non-differentiated) +
  Newton polish (differentiable), vmapped over blade elements and azimuth
  sectors.  Hub loads integrate over the curved blade path.  Derivatives
  dT/d(U, Omega, pitch) come from `jax.jacfwd` instead of CCBlade's
  hand-coded adjoints (raft_rotor.py:726, 753-764).
- `calc_aero(...)` reproduces the aero-servo linearization
  (raft_rotor.py:788-1005): aeroServoMod 1 (thrust-damping only) and 2
  (closed-loop H_QT transfer function with gain-scheduled pitch PI, torque
  PI, and floating feedback), rotated to global frame.
- `kaimal_spectra(...)` is the IEC 61400-1 Kaimal model with rotor
  averaging via Struve/Bessel kernels (raft_rotor.py:1125-1223), using the
  numerically-stable difference functions from raft_tpu.ops.special.
"""
from __future__ import annotations

import contextlib
import functools
from dataclasses import dataclass, field

import numpy as np
import jax
import jax.numpy as jnp

from raft_tpu.ops.special import struve_bessel_diff_1, struve_bessel_diff_m2
from raft_tpu.ops.transforms import rotation_matrix, rotate_matrix_3, rotate_matrix_6
from raft_tpu.utils.dicttools import get_from_dict

# the reference's (approximate) conversion constants — kept bit-identical
# for parity (raft_rotor.py:31-32)
_RAD2DEG = 57.2958
_RPM2RADPS = 0.1047
_RPM2RS = np.pi / 30.0   # exact, used inside the BEM like CCBlade does

_N_BISECT = 60
_N_NEWTON = 3
_EPS_PHI = 1e-6


def _tree_cast(tree, from_to):
    """Cast every jax/numpy float array leaf per the dtype map."""
    def cast(x):
        if isinstance(x, (jax.Array, np.ndarray)) and x.dtype in from_to:
            return jnp.asarray(x, from_to[x.dtype])
        return x
    return jax.tree.map(cast, tree)


_DOWN = {np.dtype(np.float64): np.float32,
         np.dtype(np.complex128): np.complex64}
_UP = {np.dtype(np.float32): np.float64,
       np.dtype(np.complex64): np.complex128}


def f64_host(fn):
    """Run a BEM/aero entry point in float64 on the host CPU regardless of
    the global x64 mode, casting inputs up and results down.

    The induction residual's bracket test needs ~1e-12 cancellation
    resolution at the phi -> 0+ endpoint (two ~1e12-magnitude terms nearly
    cancel); in f32 the sign flips, the bisection falls into the
    propeller-brake bracket [pi/2, pi] for every element, and rotor thrust
    collapses ~400x (measured, round 4).  Rather than chase f32 robustness
    of a fundamentally ill-conditioned bracket test, the aero-servo stage —
    a tiny host-side once-per-case computation producing (6,6,nw) tensors —
    always runs in f64 on CPU, the way the reference runs CCBlade in f64
    numpy (raft_rotor.py:726), and only the resulting constants travel to
    the accelerator in the working precision.
    """
    # jax.enable_x64 is the public context manager on recent jax; older
    # releases only have the jax.experimental spelling
    _enable_x64 = getattr(jax, "enable_x64", None)
    if _enable_x64 is None:                      # pragma: no cover
        from jax.experimental import enable_x64 as _enable_x64

    @functools.wraps(fn)
    def wrapped(*args, **kwargs):
        if jax.config.jax_enable_x64:
            return fn(*args, **kwargs)
        try:
            ctx = jax.default_device(jax.local_devices(backend="cpu")[0])
        # no cpu backend registered (backend probing has no typed
        # error across jax versions): stay put on the default device
        except Exception:  # raftlint: disable=RTL004
            ctx = contextlib.nullcontext()
        with _enable_x64(), ctx:
            args, kwargs = _tree_cast((args, kwargs), _UP)
            out = fn(*args, **kwargs)
        return _tree_cast(out, _DOWN)
    return wrapped


@dataclass
class RotorModel:
    """Static description of one rotor (numpy arrays + flags)."""

    # RNA / drivetrain
    r_rel: np.ndarray
    overhang: float
    xCG_RNA: float
    mRNA: float
    IxRNA: float
    IrRNA: float
    speed_gain: float
    nBlades: int
    yaw_mode: int
    azimuths: np.ndarray
    shaft_tilt: float      # [rad]
    shaft_toe: float       # [rad]
    aeroServoMod: int
    I_drivetrain: float
    # blade/BEM geometry
    Rhub: float
    Rtip: float
    R_rot: float
    precone: float         # [deg]
    blade_r: np.ndarray
    chord: np.ndarray
    theta_deg: np.ndarray
    precurve: np.ndarray
    presweep: np.ndarray
    precurveTip: float
    presweepTip: float
    nSector: int
    rho: float
    mu: float
    shearExp: float
    hubHt: float
    # operating schedule (incl. parked extension)
    Uhub_ops: np.ndarray
    Omega_rpm_ops: np.ndarray
    pitch_deg_ops: np.ndarray
    # control gains
    kp_0: np.ndarray
    ki_0: np.ndarray
    k_float: float
    kp_tau: float
    ki_tau: float
    Ng: float
    # per-element polar piecewise-cubics: breakpoints (nr, nbp) and
    # coefficients (nr, nbp-1, 4) highest-power-first
    cl_bp: np.ndarray = field(default=None, repr=False)
    cl_c: np.ndarray = field(default=None, repr=False)
    cd_bp: np.ndarray = field(default=None, repr=False)
    cd_c: np.ndarray = field(default=None, repr=False)
    cpmin_bp: np.ndarray = field(default=None, repr=False)
    cpmin_c: np.ndarray = field(default=None, repr=False)
    # spanwise airfoil info (underwater blade members, cavitation)
    Ca_interp: np.ndarray = field(default=None, repr=False)
    r_thick_interp: np.ndarray = field(default=None, repr=False)
    aoa_grid: np.ndarray = field(default=None, repr=False)
    # rotor axis unit vector in the platform frame at build (tilt+toe
    # applied, zero nacelle yaw) — the reference's q_rel (raft_rotor.py:100)
    q_rel0: np.ndarray = field(default=None, repr=False)


# --------------------------------------------------------------------------
# build
# --------------------------------------------------------------------------

def _ppoly_from_smoothing_spline(x, y, s):
    """Fit the same bivariate smoothing spline CCAirfoil uses (duplicated
    Reynolds column, kx=3/ky=1) and convert the alpha dependence to
    piecewise-cubic (breakpoints, coeffs highest-power-first)."""
    from scipy.interpolate import RectBivariateSpline

    Re = np.array([1e1, 1e15])
    yy = np.c_[y, y]
    kx = min(len(x) - 1, 3)
    spl = RectBivariateSpline(x, Re, yy, kx=kx, ky=1, s=s)
    tx = spl.get_knots()[0]
    bp = np.unique(tx)
    Re0 = 1e7
    nseg = len(bp) - 1
    c = np.zeros((nseg, 4))
    x0 = bp[:-1]
    h = np.diff(bp)
    c[:, 3] = spl.ev(x0, Re0)
    c[:, 2] = spl.ev(x0, Re0, dx=1)
    c[:, 1] = spl.ev(x0, Re0, dx=2) / 2.0
    # cubic term from the change in second derivative across the segment
    # (FITPACK can't evaluate dx=3 for kx=3)
    c[:, 0] = (spl.ev(bp[1:], Re0, dx=2) - spl.ev(x0, Re0, dx=2)) / (6.0 * h)
    return bp, c


def build_rotor(turbine: dict, w, ir: int = 0) -> RotorModel:
    """Parse a turbine dict into a RotorModel (reference:
    raft_rotor.py:37-373)."""
    from scipy.interpolate import PchipInterpolator

    nrot = turbine.get("nrotors", 1)
    turbine = dict(turbine)
    turbine.setdefault("nrotors", nrot)

    if "rRNA" in turbine:
        r_rel = np.asarray(get_from_dict(turbine, "rRNA", shape=[nrot, 3]))[ir].astype(float)
    else:
        r_rel = np.array([0.0, 0.0, 100.0])
    overhang = float(np.atleast_1d(get_from_dict(turbine, "overhang", shape=nrot))[ir])
    xCG_RNA = float(np.atleast_1d(get_from_dict(turbine, "xCG_RNA", shape=nrot))[ir])
    mRNA = float(np.atleast_1d(get_from_dict(turbine, "mRNA", shape=nrot))[ir])
    IxRNA = float(np.atleast_1d(get_from_dict(turbine, "IxRNA", shape=nrot))[ir])
    IrRNA = float(np.atleast_1d(get_from_dict(turbine, "IrRNA", shape=nrot))[ir])
    speed_gain = float(np.atleast_1d(get_from_dict(turbine, "speed_gain", shape=nrot, default=1.0))[ir])
    nBlades = int(np.atleast_1d(get_from_dict(turbine, "nBlades", shape=nrot, dtype=int))[ir])
    yaw_mode = int(np.atleast_1d(get_from_dict(turbine, "yaw_mode", shape=nrot, dtype=int, default=0))[ir])
    azimuths = np.atleast_1d(np.asarray(
        get_from_dict(turbine, "headings", shape=-1,
                      default=list(np.arange(nBlades) * 360.0 / nBlades)), float))
    Rhub = float(np.atleast_1d(get_from_dict(turbine, "Rhub", shape=nrot))[ir])
    precone = float(np.atleast_1d(get_from_dict(turbine, "precone", shape=nrot))[ir])
    shaft_tilt = float(np.atleast_1d(get_from_dict(turbine, "shaft_tilt", shape=nrot))[ir]) * np.pi / 180
    shaft_toe = float(np.atleast_1d(get_from_dict(turbine, "shaft_toe", shape=nrot, default=0))[ir]) * np.pi / 180
    aeroServoMod = int(np.atleast_1d(get_from_dict(turbine, "aeroServoMod", shape=nrot, default=1))[ir])
    I_drivetrain = float(np.atleast_1d(get_from_dict(turbine, "I_drivetrain", shape=nrot))[ir])

    # initial axis/hub height (reference :99-112)
    q_rel = rotation_matrix_np(0.0, shaft_tilt, shaft_toe) @ np.array([1.0, 0.0, 0.0])
    if "hHub" in turbine:
        hHub = float(np.atleast_1d(get_from_dict(turbine, "hHub", shape=nrot))[ir])
        r_rel[2] = hHub - q_rel[2] * overhang
    hubHt = r_rel[2] + q_rel[2] * overhang

    blade = turbine["blade"]
    if isinstance(blade, dict):
        blade = [blade] * nrot
    wt_ops = turbine["wt_ops"]
    if isinstance(wt_ops, dict):
        wt_ops = [wt_ops] * nrot
    bl = blade[ir]
    Rtip = float(bl["Rtip"])

    Uhub = np.asarray(get_from_dict(wt_ops[ir], "v", shape=-1), float)
    Omega_rpm = np.asarray(get_from_dict(wt_ops[ir], "omega_op", shape=-1), float)
    pitch_deg = np.asarray(get_from_dict(wt_ops[ir], "pitch_op", shape=-1), float)
    # parked extension (reference :157-159)
    Uhub = np.r_[Uhub, Uhub.max() * 1.4, 100.0]
    Omega_rpm = np.r_[Omega_rpm, 0.0, 0.0]
    pitch_deg = np.r_[pitch_deg, 90.0, 90.0]

    # fluid properties by initial hub position (reference :323-330)
    underwater = (r_rel[2] + q_rel[2] * overhang) < 0
    if underwater:
        rho = float(turbine["rho_water"]); mu = float(turbine["mu_water"])
        shearExp = float(turbine["shearExp_water"])
    else:
        rho = float(turbine["rho_air"]); mu = float(turbine["mu_air"])
        shearExp = float(turbine["shearExp_air"])

    # ----- airfoil polar database (reference :179-296) -----
    station_airfoil = [b for [a, b] in bl["airfoils"]]
    station_position = np.array([a for [a, b] in bl["airfoils"]], float)
    n_aoa = 200
    aoa = np.unique(np.hstack([np.linspace(-180, -30, int(n_aoa / 4 + 1)),
                               np.linspace(-30, 30, int(n_aoa / 2)),
                               np.linspace(30, 180, int(n_aoa / 4 + 1))]))
    afs = turbine["airfoils"]
    names = [a["name"] for a in afs]
    thick = np.array([a["relative_thickness"] for a in afs], float)
    Ca_af = np.array([a.get("added_mass_coeff", [0.5, 1.0]) for a in afs], float)
    tables = {}
    for a in afs:
        # airfoils may differ in column count (5th cpmin column optional,
        # e.g. FOCTT_example.yaml) but each table must be internally
        # consistent — silently truncating would zero cpmin and disable
        # the cavitation check for that airfoil
        rows = [np.asarray(row, float) for row in a["data"]]
        ncols = {len(row) for row in rows}
        if len(ncols) != 1:
            raise ValueError(
                f"airfoil '{a.get('name')}' polar rows have inconsistent "
                f"column counts {sorted(ncols)}")
        ncol = ncols.pop()
        tab = np.stack(rows)
        cl = np.interp(aoa, tab[:, 0], tab[:, 1])
        cd = np.interp(aoa, tab[:, 0], tab[:, 2])
        cpm = np.interp(aoa, tab[:, 0], tab[:, 4]) if ncol > 4 else np.zeros_like(aoa)
        # enforce +-pi continuity as the reference does (:228-239)
        cl[0] = cl[-1]; cd[0] = cd[-1]; cpm[0] = cpm[-1]
        tables[a["name"]] = (cl, cd, cpm)

    nSector = int(get_from_dict(bl, "nSector", default=4))
    nr = int(get_from_dict(bl, "nr", default=20))
    grid = np.linspace(0.0, 1.0, nr, endpoint=False) + 0.5 / nr

    st_thick = np.array([thick[names.index(s)] for s in station_airfoil])
    st_Ca = np.array([Ca_af[names.index(s)] for s in station_airfoil])
    st_cl = np.array([tables[s][0] for s in station_airfoil])
    st_cd = np.array([tables[s][1] for s in station_airfoil])
    st_cpm = np.array([tables[s][2] for s in station_airfoil])

    if not np.all(st_thick == np.flip(np.sort(st_thick))):
        raise NotImplementedError("non-monotonic spanwise airfoil thickness")
    r_thick_interp = PchipInterpolator(station_position, st_thick)(grid)
    Ca_interp = PchipInterpolator(station_position, st_Ca)(grid)
    r_thick_unique, idx = np.unique(st_thick, return_index=True)
    cl_interp = np.flip(PchipInterpolator(r_thick_unique, st_cl[idx])(np.flip(r_thick_interp)), axis=0)
    cd_interp = np.flip(PchipInterpolator(r_thick_unique, st_cd[idx])(np.flip(r_thick_interp)), axis=0)
    cpm_interp = np.flip(PchipInterpolator(r_thick_unique, st_cpm[idx])(np.flip(r_thick_interp)), axis=0)

    # per-element smoothing-spline piecewise cubics (CCAirfoil equivalent:
    # RectBivariateSpline with s=0.1 on cl, s=0.001 on cd)
    aoa_rad = np.radians(aoa)
    cl_bps, cl_cs, cd_bps, cd_cs, cp_bps, cp_cs = [], [], [], [], [], []
    for i in range(nr):
        bp, c = _ppoly_from_smoothing_spline(aoa_rad, cl_interp[i], s=0.1)
        cl_bps.append(bp); cl_cs.append(c)
        bp, c = _ppoly_from_smoothing_spline(aoa_rad, cd_interp[i], s=0.001)
        cd_bps.append(bp); cd_cs.append(c)
        bp, c = _ppoly_from_smoothing_spline(aoa_rad, cpm_interp[i], s=0.1)
        cp_bps.append(bp); cp_cs.append(c)
    cl_bp, cl_c = _pad_ppoly(cl_bps, cl_cs)
    cd_bp, cd_c = _pad_ppoly(cd_bps, cd_cs)
    cp_bp, cp_c = _pad_ppoly(cp_bps, cp_cs)

    # blade element geometry (reference :309-320).  NOTE the reference's
    # element grid spans [Rhub, LAST GEOMETRY RADIUS] (raft_rotor.py:139
    # `rtip = geometry[-1][0]`, :312-315), NOT [Rhub, Rtip]: for IEA15MW
    # the geometry table ends at 116.94 m while Rtip=120.97 m, and CCBlade
    # still uses Rtip for the Prandtl tip loss and the hub/tip-padded
    # integration.  Replicating this (previously we spanned to Rtip) was
    # worth ~2.4% on thrust.
    gt = np.array(bl["geometry"], float)
    rtip_geom = float(gt[-1, 0])
    dr = (rtip_geom - Rhub) / nr
    blade_r = np.linspace(Rhub, rtip_geom, nr, endpoint=False) + dr / 2
    chord = np.interp(blade_r, gt[:, 0], gt[:, 1])
    theta = np.interp(blade_r, gt[:, 0], gt[:, 2])
    precurve = np.interp(blade_r, gt[:, 0], gt[:, 3])
    presweep = np.interp(blade_r, gt[:, 0], gt[:, 4])

    # control gains (reference :770-784)
    pc = turbine["pitch_control"]
    pc_angles = np.array(pc["GS_Angles"]) * _RAD2DEG
    kp_0 = np.interp(pitch_deg, pc_angles, pc["GS_Kp"], left=0, right=0)
    ki_0 = np.interp(pitch_deg, pc_angles, pc["GS_Ki"], left=0, right=0)
    k_float = -pc["Fl_Kp"]
    kp_tau = -turbine["torque_control"]["VS_KP"]
    ki_tau = -turbine["torque_control"]["VS_KI"]
    Ng = turbine["gear_ratio"]

    cone_r = np.radians(precone)
    R_rot = Rtip * np.cos(cone_r) + float(bl["precurveTip"]) * np.sin(cone_r)

    return RotorModel(
        r_rel=r_rel, overhang=overhang, xCG_RNA=xCG_RNA, mRNA=mRNA,
        IxRNA=IxRNA, IrRNA=IrRNA, speed_gain=speed_gain, nBlades=nBlades,
        yaw_mode=yaw_mode, azimuths=azimuths, shaft_tilt=shaft_tilt,
        shaft_toe=shaft_toe, aeroServoMod=aeroServoMod,
        I_drivetrain=I_drivetrain,
        Rhub=Rhub, Rtip=Rtip, R_rot=R_rot, precone=precone,
        blade_r=blade_r, chord=chord, theta_deg=theta,
        precurve=precurve, presweep=presweep,
        precurveTip=float(bl["precurveTip"]), presweepTip=float(bl["presweepTip"]),
        nSector=nSector, rho=rho, mu=mu, shearExp=shearExp, hubHt=hubHt,
        Uhub_ops=Uhub, Omega_rpm_ops=Omega_rpm, pitch_deg_ops=pitch_deg,
        kp_0=kp_0, ki_0=ki_0, k_float=k_float, kp_tau=kp_tau, ki_tau=ki_tau,
        Ng=float(Ng),
        cl_bp=cl_bp, cl_c=cl_c, cd_bp=cd_bp, cd_c=cd_c,
        cpmin_bp=cp_bp, cpmin_c=cp_c,
        Ca_interp=Ca_interp, r_thick_interp=r_thick_interp, aoa_grid=aoa_rad,
        q_rel0=q_rel,
    )


def _pad_ppoly(bps, cs):
    """Pad ragged per-element piecewise-cubic tables to a common segment
    count (repeating the last breakpoint; padded segments are never
    selected by searchsorted)."""
    nmax = max(len(b) for b in bps)
    bp = np.stack([np.pad(b, (0, nmax - len(b)), mode="edge") for b in bps])
    cc = np.stack([np.pad(c, ((0, nmax - 1 - len(c)), (0, 0)), mode="edge") for c in cs])
    return bp, cc


def rotation_matrix_np(x3, x2, x1):
    import numpy as _np
    s1, c1 = _np.sin(x1), _np.cos(x1)
    s2, c2 = _np.sin(x2), _np.cos(x2)
    s3, c3 = _np.sin(x3), _np.cos(x3)
    return _np.array([
        [c1 * c2, c1 * s2 * s3 - c3 * s1, s1 * s3 + c1 * c3 * s2],
        [c2 * s1, c1 * c3 + s1 * s2 * s3, c3 * s1 * s2 - c1 * s3],
        [-s2, c2 * s3, c2 * c3]])


# --------------------------------------------------------------------------
# polar evaluation (piecewise cubic, batched over elements)
# --------------------------------------------------------------------------

def _ppoly_eval(bp, c, x):
    """bp: (nr, nbp), c: (nr, nbp-1, 4), x: (nr,) -> (nr,)"""
    x = jnp.clip(x, bp[:, 0], bp[:, -1])
    idx = jnp.clip(jax.vmap(jnp.searchsorted)(bp, x) - 1, 0, bp.shape[1] - 2)
    t = x - jnp.take_along_axis(bp, idx[:, None], axis=1)[:, 0]
    ci = jnp.take_along_axis(c, idx[:, None, None], axis=1)[:, 0, :]
    return ((ci[:, 0] * t + ci[:, 1]) * t + ci[:, 2]) * t + ci[:, 3]


# --------------------------------------------------------------------------
# BEM core (Ning 2014 single-residual formulation)
# --------------------------------------------------------------------------

def _define_curvature(r, precurve, presweep, precone_rad):
    """Azimuthal-frame coordinates and local cone angle of the blade axis
    (CCBlade's definecurvature)."""
    x_az = -r * jnp.sin(precone_rad) + precurve * jnp.cos(precone_rad)
    z_az = r * jnp.cos(precone_rad) + precurve * jnp.sin(precone_rad)
    y_az = presweep
    dx = x_az[1:] - x_az[:-1]
    dz = z_az[1:] - z_az[:-1]
    seg = jnp.arctan2(-dx, dz)
    cone = jnp.concatenate([seg[:1], 0.5 * (seg[1:] + seg[:-1]), seg[-1:]])
    ds = jnp.sqrt((x_az[1:] - x_az[:-1]) ** 2 + (y_az[1:] - y_az[:-1]) ** 2
                  + (z_az[1:] - z_az[:-1]) ** 2)
    s = jnp.concatenate([jnp.zeros(1), jnp.cumsum(ds)])
    return x_az, y_az, z_az, cone, s


def _wind_components(rot: RotorModel, Uinf, Omega_rs, azimuth_rad, tilt, yaw):
    """Axial/tangential velocity at each element (CCBlade windcomponents)."""
    r = jnp.asarray(rot.blade_r)
    precone = jnp.radians(rot.precone)
    x_az, y_az, z_az, cone, _ = _define_curvature(
        r, jnp.asarray(rot.precurve), jnp.asarray(rot.presweep), precone)
    sy, cy = jnp.sin(yaw), jnp.cos(yaw)
    st, ct = jnp.sin(tilt), jnp.cos(tilt)
    sa, ca = jnp.sin(azimuth_rad), jnp.cos(azimuth_rad)
    sc, cc = jnp.sin(cone), jnp.cos(cone)

    height = (y_az * sa + z_az * ca) * ct - x_az * st
    V = Uinf * (1.0 + height / rot.hubHt) ** rot.shearExp
    Vwind_x = V * ((cy * st * ca + sy * sa) * sc + cy * ct * cc)
    Vwind_y = V * (cy * st * sa - sy * ca)
    Vrot_x = -Omega_rs * y_az * sc
    Vrot_y = Omega_rs * z_az
    return Vwind_x + Vrot_x, Vwind_y + Vrot_y


def _induction_residual(rot, phi, alpha_off, Vx, Vy):
    """Ning (2014) residual + induction factors at inflow angle phi.

    All element arrays (nr,).  Returns (R, a, ap, cn, ct)."""
    sphi, cphi = jnp.sin(phi), jnp.cos(phi)
    alpha = phi - alpha_off
    cl = _ppoly_eval(jnp.asarray(rot.cl_bp), jnp.asarray(rot.cl_c), alpha)
    cd = _ppoly_eval(jnp.asarray(rot.cd_bp), jnp.asarray(rot.cd_c), alpha)
    cn = cl * cphi + cd * sphi
    ct = cl * sphi - cd * cphi

    r = jnp.asarray(rot.blade_r)
    B = rot.nBlades
    sigma_p = B / (2.0 * jnp.pi) * jnp.asarray(rot.chord) / r
    asphi = jnp.maximum(jnp.abs(sphi), 1e-9)
    ftip = B / 2.0 * (rot.Rtip - r) / (r * asphi)
    Ftip = 2.0 / jnp.pi * jnp.arccos(jnp.clip(jnp.exp(-ftip), -1.0, 1.0))
    fhub = B / 2.0 * (r - rot.Rhub) / (rot.Rhub * asphi)
    Fhub = 2.0 / jnp.pi * jnp.arccos(jnp.clip(jnp.exp(-fhub), -1.0, 1.0))
    F = jnp.maximum(Ftip * Fhub, 1e-9)

    def _signed_floor(x, floor):
        s = jnp.where(x < 0, -1.0, 1.0)
        return s * jnp.maximum(jnp.abs(x), floor)

    sphi_safe = _signed_floor(sphi, 1e-12)
    cphi_safe = _signed_floor(cphi, 1e-12)
    k = sigma_p * cn / (4.0 * F * sphi_safe * sphi_safe)
    kp = sigma_p * ct / (4.0 * F * sphi_safe * cphi_safe)

    # axial induction: momentum region / Buhl empirical region (phi>0)
    g1 = 2.0 * F * k - (10.0 / 9.0 - F)
    g2 = jnp.maximum(2.0 * F * k - (4.0 / 3.0 - F) * F, 1e-12)
    g3 = 2.0 * F * k - (25.0 / 9.0 - 2.0 * F)
    g3_safe = jnp.where(jnp.abs(g3) < 1e-6, 1.0, g3)
    a_buhl = jnp.where(jnp.abs(g3) < 1e-6,
                       1.0 - 1.0 / (2.0 * jnp.sqrt(g2)),
                       (g1 - jnp.sqrt(g2)) / g3_safe)
    # momentum solution: guard k == -1 (pole) with a signed floor
    a_mom = k / _signed_floor(1.0 + k, 1e-12)
    a_pos = jnp.where(k <= 2.0 / 3.0, a_mom, a_buhl)
    # propeller-brake region (phi<0)
    a_neg = jnp.where(k > 1.0, k / _signed_floor(k - 1.0, 1e-12), 0.0)
    a = jnp.where(phi > 0, a_pos, a_neg)

    ap = kp / _signed_floor(1.0 - kp, 1e-12)

    Vx_safe = _signed_floor(Vx, 1e-9)
    Vy_safe = _signed_floor(Vy, 1e-9)
    lam = Vy_safe / Vx_safe
    one_m_a = _signed_floor(1.0 - a, 1e-12)
    R_pos = sphi / one_m_a - cphi / lam * (1.0 - kp)
    R_neg = sphi * (1.0 - k) - cphi / lam * (1.0 - kp)
    R = jnp.where(phi > 0, R_pos, R_neg)
    return R, a, ap, cn, ct


def _solve_phi(rot, alpha_off, Vx, Vy):
    """Bracketed bisection (CCBlade's interval strategy) + Newton polish."""
    def res(phi):
        return _induction_residual(rot, phi, alpha_off, Vx, Vy)[0]

    eps = _EPS_PHI
    lo1, hi1 = jnp.full_like(Vx, eps), jnp.full_like(Vx, jnp.pi / 2)
    lo2, hi2 = jnp.full_like(Vx, -jnp.pi / 4), jnp.full_like(Vx, -eps)
    lo3, hi3 = jnp.full_like(Vx, jnp.pi / 2), jnp.full_like(Vx, jnp.pi - eps)
    r1lo, r1hi = res(lo1), res(hi1)
    r2lo, r2hi = res(lo2), res(hi2)
    use1 = r1lo * r1hi <= 0.0
    use2 = (~use1) & (r2lo * r2hi <= 0.0)
    lo = jnp.where(use1, lo1, jnp.where(use2, lo2, lo3))
    hi = jnp.where(use1, hi1, jnp.where(use2, hi2, hi3))

    def body(_, state):
        lo, hi, rlo = state
        mid = 0.5 * (lo + hi)
        rmid = res(mid)
        go_lo = rlo * rmid <= 0.0
        lo_n = jnp.where(go_lo, lo, mid)
        hi_n = jnp.where(go_lo, mid, hi)
        rlo_n = jnp.where(go_lo, rlo, rmid)
        return lo_n, hi_n, rlo_n

    lo_f, hi_f, _ = jax.lax.fori_loop(
        0, _N_BISECT, body,
        (jax.lax.stop_gradient(lo), jax.lax.stop_gradient(hi),
         jax.lax.stop_gradient(res(lo))))
    phi = 0.5 * (lo_f + hi_f)

    # Newton polish (differentiable; restores implicit-function gradients)
    for _ in range(_N_NEWTON):
        r, dr = jax.jvp(res, (phi,), (jnp.ones_like(phi),))
        dr_safe = jnp.where(jnp.abs(dr) < 1e-14, 1e-14, dr)
        step = jnp.clip(r / dr_safe, -0.05, 0.05)
        phi = phi - step
    return phi


def _distributed_loads(rot: RotorModel, Uinf, Omega_rpm, pitch_deg, azimuth_deg,
                       tilt, yaw):
    """Np, Tp (N/m) along the blade at one azimuth, plus W and alpha."""
    Omega_rs = Omega_rpm * _RPM2RS
    az = jnp.radians(azimuth_deg)
    Vx, Vy = _wind_components(rot, Uinf, Omega_rs, az, tilt, yaw)
    alpha_off = jnp.radians(jnp.asarray(rot.theta_deg) + pitch_deg)
    phi = _solve_phi(rot, alpha_off, Vx, Vy)
    _, a, ap, cn, ct = _induction_residual(rot, phi, alpha_off, Vx, Vy)
    W2 = (Vx * (1.0 - a)) ** 2 + (Vy * (1.0 + ap)) ** 2
    chord = jnp.asarray(rot.chord)
    Np = cn * 0.5 * rot.rho * W2 * chord
    Tp = ct * 0.5 * rot.rho * W2 * chord
    return Np, Tp, jnp.sqrt(W2), phi - alpha_off


def _hub_loads_one_azimuth(rot: RotorModel, Np, Tp, azimuth_deg):
    """Integrate one blade's distributed loads (with hub/tip zero padding)
    along the curved path and express force/moment in the hub frame,
    using CCBlade's exact (somewhat ad-hoc) component conventions.

    CCBlade does NOT form a coherent p x f cross product for the moments.
    Its azimuth-frame components, identified by exhaustive fit against the
    reference's IEA15MW_true_calcAero pickles (machine-precision match,
    8e-16 over the full 30-case speed x heading envelope):
      F   = trapz over s of (Np cos(cone), -Tp, Np sin(cone))
      M_x = trapz(Tp * z_az, s)          (shaft torque)
      M_y = trapz(Np * z_az, s)          (flap bending: raw normal load
                                          times height — no cone
                                          projection, no x_az arm)
      M_z = 0                            (no in-plane moment component)
    so the hub-frame My/Mz both come from rotating the flap bending
    moment by the azimuth angle."""
    r = jnp.asarray(rot.blade_r)
    rfull = jnp.concatenate([jnp.array([rot.Rhub]), r, jnp.array([rot.Rtip])])
    curve = jnp.concatenate([jnp.zeros(1), jnp.asarray(rot.precurve),
                             jnp.array([rot.precurveTip])])
    sweep = jnp.concatenate([jnp.zeros(1), jnp.asarray(rot.presweep),
                             jnp.array([rot.presweepTip])])
    Npf = jnp.concatenate([jnp.zeros(1), Np, jnp.zeros(1)])
    Tpf = jnp.concatenate([jnp.zeros(1), Tp, jnp.zeros(1)])
    x_az, y_az, z_az, cone, s = _define_curvature(rfull, curve, sweep,
                                                  jnp.radians(rot.precone))
    # force per unit path length in the azimuthal frame
    f = jnp.stack([Npf * jnp.cos(cone), -Tpf, Npf * jnp.sin(cone)], axis=-1)
    F_az = jnp.trapezoid(f, s, axis=0)
    M_az = jnp.stack([jnp.trapezoid(Tpf * z_az, s),
                      jnp.trapezoid(Npf * z_az, s),
                      jnp.zeros(())])
    # azimuthal -> hub frame: rotation about x by the azimuth angle
    psi = jnp.radians(azimuth_deg)
    cpsi, spsi = jnp.cos(psi), jnp.sin(psi)
    Rx = jnp.array([[1.0, 0.0, 0.0],
                    [0.0, cpsi, spsi],
                    [0.0, -spsi, cpsi]])
    return Rx @ F_az, Rx @ M_az


@f64_host
def bem_evaluate(rot: RotorModel, Uinf, Omega_rpm, pitch_deg,
                 tilt=0.0, yaw=0.0):
    """Azimuth-averaged hub loads: dict(T, Y, Z, Q, My, Mz, P).

    Equivalent of ccblade.evaluate (reference use: raft_rotor.py:726)
    with nSector azimuthal sectors.  Fully differentiable w.r.t.
    (Uinf, Omega_rpm, pitch_deg).

    Sign convention: Y and Mz are negated from the internal azimuthal
    integration to land on CCBlade's reported hub loads (CCBlade's y/z
    component conventions are left-handed relative to the right-handed
    azimuth frame used here; see _hub_loads_one_azimuth for the exact
    per-component integrands).  Validated against the reference's
    IEA15MW_true_calcAero pickles at MACHINE PRECISION (8e-16) on all six
    channels across the full 30-case (speed x heading) yaw_mode-0
    envelope (tests/test_rotor.py::test_hub_loads_full_envelope_parity).
    """
    azimuths = jnp.linspace(0.0, 360.0, rot.nSector, endpoint=False)

    def one(azimuth):
        Np, Tp, _, _ = _distributed_loads(rot, Uinf, Omega_rpm, pitch_deg,
                                          azimuth, tilt, yaw)
        return _hub_loads_one_azimuth(rot, Np, Tp, azimuth)

    F, M = jax.vmap(one)(azimuths)
    F = rot.nBlades * jnp.mean(F, axis=0)
    M = rot.nBlades * jnp.mean(M, axis=0)
    Omega_rs = Omega_rpm * _RPM2RS
    return dict(T=F[0], Y=-F[1], Z=F[2], Q=M[0], My=M[1], Mz=-M[2],
                P=M[0] * Omega_rs)


@f64_host
def bem_thrust_torque_derivs(rot: RotorModel, Uinf, Omega_rpm, pitch_deg,
                             tilt=0.0, yaw=0.0):
    """(T, Q) and their Jacobian w.r.t. (Uinf, Omega_rpm, pitch_deg) by
    forward-mode autodiff (replaces CCBlade's hand-coded derivatives,
    reference: raft_rotor.py:753-764)."""
    def tq(x):
        out = bem_evaluate(rot, x[0], x[1], x[2], tilt, yaw)
        return jnp.stack([out["T"], out["Q"]])

    x = jnp.stack([jnp.asarray(Uinf, float), jnp.asarray(Omega_rpm, float),
                   jnp.asarray(pitch_deg, float)])
    TQ = tq(x)
    J = jax.jacfwd(tq)(x)
    return TQ, J


# --------------------------------------------------------------------------
# IEC Kaimal rotor-averaged spectrum
# --------------------------------------------------------------------------

_IEC_VREF = {"I": 50.0, "II": 42.5, "III": 37.5, "IV": 30.0}
_IEC_IREF = {"A+": 0.18, "A": 0.16, "B": 0.14, "C": 0.12}


def turbulence_sigma(turbulence, speed, turbine_class="I",
                     turbulence_class="B"):
    """sigma_1 from the IEC 61400-1 models (host-side; reference:
    raft/pyIECWind.py NTM/ETM/EWM + raft_rotor.py:1147-1193).

    ``turbulence`` is a float TI (NTM with I_ref=TI) or a string like
    'IB_NTM' (class+category+model)."""
    if isinstance(turbulence, str):
        cls = ""
        for ch in turbulence:
            if ch in ("I", "V"):
                cls += ch
            else:
                break
        if not cls:
            I_ref = float(turbulence)
            model = "NTM"
            V_ave = _IEC_VREF[turbine_class] * 0.2
        else:
            categ = turbulence[len(cls)]
            model = turbulence.split("_")[1]
            I_ref = _IEC_IREF[categ]
            V_ave = _IEC_VREF[cls] * 0.2
    else:
        I_ref = float(turbulence)
        model = "NTM"
        V_ave = _IEC_VREF[turbine_class] * 0.2

    if model == "NTM":
        return I_ref * (0.75 * speed + 5.6)
    if model == "ETM":
        c = 2.0
        return c * I_ref * (0.072 * (V_ave / c + 3) * (speed / c - 4) + 10)
    if model == "EWM":
        return 0.11 * speed
    raise ValueError(f"unknown turbulence model {model}")


def kaimal_spectra(w, speed, HH, R, sigma_1):
    """IEC Kaimal spectra U,V,W plus rotor-averaged Rot spectrum
    [(m/s)^2/(rad/s)] (reference: raft_rotor.py:1195-1223), computed with
    numerically-stable Struve-Bessel differences (the reference's naive
    scipy difference collapses for 2*R*kappa over ~38)."""
    w = jnp.asarray(w, float)
    f = w / (2.0 * jnp.pi)
    L_1 = jnp.where(HH <= 60.0, 0.7 * HH, 42.0)
    sigma_u, L_u = sigma_1, 8.1 * L_1
    sigma_v, L_v = 0.8 * sigma_1, 2.7 * L_1
    sigma_w, L_w = 0.5 * sigma_1, 0.66 * L_1
    U = (4 * L_u / speed) * sigma_u**2 / (1 + 6 * f * L_u / speed) ** (5.0 / 3.0)
    V = (4 * L_v / speed) * sigma_v**2 / (1 + 6 * f * L_v / speed) ** (5.0 / 3.0)
    W = (4 * L_w / speed) * sigma_w**2 / (1 + 6 * f * L_w / speed) ** (5.0 / 3.0)
    kappa = 12.0 * jnp.sqrt((f / speed) ** 2 + (0.12 / L_u) ** 2)
    x = 2.0 * R * kappa
    d1 = struve_bessel_diff_1(x)
    dm2 = struve_bessel_diff_m2(x)
    Rk = R * kappa
    Rot = (2.0 * U / Rk**3) * (d1 - 2.0 / jnp.pi + Rk * (-2.0 * dm2 + 1.0))
    Rot = jnp.where(jnp.isfinite(Rot), Rot, 0.0)
    return U, V, W, Rot


# --------------------------------------------------------------------------
# pose / yaw
# --------------------------------------------------------------------------

def rotor_pose(rot: RotorModel, r6=None, inflow_heading=0.0,
               turbine_heading=0.0, yaw_command=0.0):
    """Rotor orientation under a platform pose and yaw mode (reference:
    raft_rotor.py:376-460).  Returns dict(R_ptfm, R_q, q, r_hub, yaw).
    Angles in radians."""
    if r6 is None:
        r6 = jnp.zeros(6)
    r6 = jnp.asarray(r6, float)
    R_ptfm = rotation_matrix(r6[3], r6[4], r6[5])
    platform_heading = r6[5]
    if rot.yaw_mode == 0:
        yaw = inflow_heading - platform_heading + yaw_command
    elif rot.yaw_mode == 1:
        yaw = turbine_heading - platform_heading
    elif rot.yaw_mode == 2:
        yaw = yaw_command
    elif rot.yaw_mode == 3:
        yaw = yaw_command - platform_heading
    else:
        raise ValueError("yaw_mode must be 0..3")
    R_q_rel = rotation_matrix(0.0, rot.shaft_tilt, rot.shaft_toe + yaw)
    # NOTE: the reference composes R_q = R_q_rel @ R_ptfm (raft_rotor.py:454);
    # replicated verbatim for parity.
    R_q = R_q_rel @ R_ptfm
    q_rel = R_q_rel @ jnp.array([1.0, 0.0, 0.0])
    q = R_ptfm @ q_rel
    r_RRP_rel = R_ptfm @ jnp.asarray(rot.r_rel)
    r_hub_rel = r_RRP_rel + q * rot.overhang
    r_hub = r6[:3] + r_hub_rel
    return dict(R_ptfm=R_ptfm, R_q=R_q, q=q, q_rel=q_rel, r_hub=r_hub, yaw=yaw)


# --------------------------------------------------------------------------
# aero-servo linearization
# --------------------------------------------------------------------------

@f64_host
def calc_aero(rot: RotorModel, w, case: dict, r6=None, current=False):
    """Mean loads + frequency-domain aero matrices (reference:
    raft_rotor.py:788-1005).

    Returns dict(f0 (6,), f (6,nw) complex, a (6,6,nw), b (6,6,nw),
    C (nw,) control transfer fn, pose info, operating point).
    """
    w = jnp.asarray(w, float)
    nw = w.shape[0]
    if current:
        speed = float(get_from_dict(case, "current_speed", shape=0, default=1.0))
        heading = float(get_from_dict(case, "current_heading", shape=0, default=0.0))
        turb = case.get("current_turbulence", 0.0)
    else:
        speed = float(get_from_dict(case, "wind_speed", shape=0, default=10.0))
        heading = float(get_from_dict(case, "wind_heading", shape=0, default=0.0))
        turb = case.get("turbulence", 0.0)

    inflow_heading = np.radians(heading)
    turbine_heading = np.radians(float(get_from_dict(case, "turbine_heading", shape=0, default=0.0)))
    yaw_command = np.radians(float(get_from_dict(case, "yaw_misalign", shape=0, default=0.0)))

    pose = rotor_pose(rot, r6, inflow_heading=inflow_heading,
                      turbine_heading=turbine_heading, yaw_command=yaw_command)
    q = pose["q"]
    yaw_misalign = jnp.arctan2(q[1], q[0]) - inflow_heading
    turbine_tilt = jnp.arctan2(q[2], jnp.hypot(q[0], q[1]))

    # operating point (reference :714-718)
    Uhub = speed * rot.speed_gain
    Omega_rpm = jnp.interp(Uhub, jnp.asarray(rot.Uhub_ops), jnp.asarray(rot.Omega_rpm_ops))
    pitch_deg = jnp.interp(Uhub, jnp.asarray(rot.Uhub_ops), jnp.asarray(rot.pitch_deg_ops))

    loads = bem_evaluate(rot, Uhub, Omega_rpm, pitch_deg,
                         tilt=turbine_tilt, yaw=yaw_misalign)
    TQ, J = bem_thrust_torque_derivs(rot, Uhub, Omega_rpm, pitch_deg,
                                     tilt=turbine_tilt, yaw=yaw_misalign)
    dT_dU = J[0, 0]
    dT_dOm = J[0, 1] / _RPM2RADPS
    dT_dPi = J[0, 2] * _RAD2DEG
    dQ_dU = J[1, 0]
    dQ_dOm = J[1, 1] / _RPM2RADPS
    dQ_dPi = J[1, 2] * _RAD2DEG

    R_q = pose["R_q"]
    f0 = jnp.concatenate([
        R_q @ jnp.stack([loads["T"], loads["Y"], loads["Z"]]),
        R_q @ jnp.stack([loads["My"], loads["Q"], loads["Mz"]]),
    ])

    # rotor-averaged turbulence spectrum -> wave-like amplitudes
    HH = jnp.abs(pose["r_hub"][2])
    sigma_1 = turbulence_sigma(turb, speed)
    _, _, _, S_rot = kaimal_spectra(w, speed, HH, rot.R_rot, sigma_1)
    V_w = jnp.sqrt(S_rot).astype(complex)

    a = jnp.zeros((6, 6, nw))
    b = jnp.zeros((6, 6, nw))
    fvec = jnp.zeros((6, nw), dtype=complex)
    C = jnp.zeros(nw, dtype=complex)

    if rot.aeroServoMod == 1:
        b_inflow = jnp.zeros((6, 6, nw)).at[0, 0, :].set(dT_dU)
        a = rotate_matrix_6(jnp.moveaxis(a, -1, 0), R_q)
        a = jnp.moveaxis(a, 0, -1)
        b = rotate_matrix_6(jnp.moveaxis(b_inflow, -1, 0), R_q)
        b = jnp.moveaxis(b, 0, -1)
        f_inflow = dT_dU * V_w
        fvec = fvec.at[:3, :].set(R_q.astype(complex)
                                  @ jnp.stack([f_inflow,
                                               jnp.zeros_like(f_inflow),
                                               jnp.zeros_like(f_inflow)]))
    elif rot.aeroServoMod == 2:
        kp_beta = -jnp.interp(jnp.asarray(speed, float), jnp.asarray(rot.Uhub_ops), jnp.asarray(rot.kp_0))
        ki_beta = -jnp.interp(jnp.asarray(speed, float), jnp.asarray(rot.Uhub_ops), jnp.asarray(rot.ki_0))
        kp_tau = rot.kp_tau * (kp_beta == 0)
        ki_tau = rot.ki_tau * (ki_beta == 0)
        zhub = pose["r_hub"][2]

        D = (rot.I_drivetrain * w**2
             + (dQ_dOm + kp_beta * dQ_dPi - rot.Ng * kp_tau) * 1j * w
             + ki_beta * dQ_dPi - rot.Ng * ki_tau)
        C = 1j * w * (dQ_dU - rot.k_float * dQ_dPi / zhub) / D
        H_QT = ((dT_dOm + kp_beta * dT_dPi) * 1j * w + ki_beta * dT_dPi) / D
        f2 = (dT_dU - H_QT * dQ_dU) * V_w
        b2 = jnp.real(dT_dU - rot.k_float * dT_dPi
                      - H_QT * (dQ_dU - rot.k_float * dQ_dPi))
        a2 = jnp.real((dT_dU - rot.k_float * dT_dPi
                       - H_QT * (dQ_dU - rot.k_float * dQ_dPi)) / (1j * w))

        diag_a = jnp.zeros((nw, 3, 3)).at[:, 0, 0].set(a2)
        diag_b = jnp.zeros((nw, 3, 3)).at[:, 0, 0].set(b2)
        a = a.at[:3, :3, :].set(jnp.moveaxis(rotate_matrix_3(diag_a, R_q), 0, -1))
        b = b.at[:3, :3, :].set(jnp.moveaxis(rotate_matrix_3(diag_b, R_q), 0, -1))
        fvec = fvec.at[:3, :].set(R_q.astype(complex)
                                  @ jnp.stack([f2, jnp.zeros_like(f2),
                                               jnp.zeros_like(f2)]))
    # aeroServoMod == 0: all zeros

    return dict(f0=f0, f=fvec, a=a, b=b, C=C, pose=pose, V_w=V_w,
                loads=loads, op=dict(U=Uhub, Omega_rpm=Omega_rpm,
                                     pitch_deg=pitch_deg),
                derivs=dict(dT_dU=dT_dU, dT_dOm=dT_dOm, dT_dPi=dT_dPi,
                            dQ_dU=dQ_dU, dQ_dOm=dQ_dOm, dQ_dPi=dQ_dPi))


# --------------------------------------------------------------------------
# underwater rotors (MHK): blade members + cavitation
# --------------------------------------------------------------------------

def _rodrigues_np(az_deg, axis):
    """Rotation matrix about ``axis`` by the blade azimuth angle
    (reference: raft_rotor.py:565-583 getBladeMemberPositions)."""
    c = np.cos(np.deg2rad(az_deg))
    s = np.sin(np.deg2rad(az_deg))
    a = np.asarray(axis, float)
    return np.array([
        [c + a[0]**2*(1-c), a[0]*a[1]*(1-c) - a[2]*s, a[0]*a[2]*(1-c) + a[1]*s],
        [a[1]*a[0]*(1-c) + a[2]*s, c + a[1]**2*(1-c), a[1]*a[2]*(1-c) - a[0]*s],
        [a[2]*a[0]*(1-c) - a[1]*s, a[2]*a[1]*(1-c) + a[0]*s, c + a[2]**2*(1-c)]])


def blade_member_dicts(rot: RotorModel):
    """Rectangular member dicts for each blade element of a submerged rotor,
    one set per blade at its build azimuth, positioned in the PLATFORM frame
    (reference: raft_rotor.py:522-562 bladeGeometry2Member creates them
    hub-relative and rotates per azimuth at use time, raft_fowt.py:384-444;
    here the azimuth rotation is baked in at build so the members flow
    through the same stacked-node strip kernels as everything else).

    Each element becomes a rect member of chord x equivalent-area thickness
    with the blade twist as gamma and the airfoil's added-mass coefficient
    pair; Cd = 0 (drag handled by the rotor aero model).  The last element
    is skipped, replicating the reference's ``range(len(blade_r)-1)``.
    """
    q = np.asarray(rot.q_rel0, float)
    # 90-degree z-rotation of the rotor axis: the azimuth-zero blade
    # direction (reference: raft_rotor.py:530 airfoil_zero_heading)
    dir0 = np.array([[0.0, -1.0, 0.0], [1.0, 0.0, 0.0], [0.0, 0.0, 1.0]]) @ q
    r_hub_rel = np.asarray(rot.r_rel, float) + q * rot.overhang
    dr = float(rot.blade_r[1] - rot.blade_r[0])
    mems = []
    for az in np.atleast_1d(rot.azimuths):
        R = _rodrigues_np(float(az), q)
        for i in range(len(rot.blade_r) - 1):
            chord = float(rot.chord[i])
            rect_thick = (np.pi / 4.0) * chord * float(rot.r_thick_interp[i])
            rA = r_hub_rel + R @ (dir0 * (rot.blade_r[i] - dr / 2.0))
            rB = r_hub_rel + R @ (dir0 * (rot.blade_r[i] + dr / 2.0))
            mems.append(dict(
                name="blade", type=3, rA=rA, rB=rB, shape="rect",
                stations=[0, 1],
                d=[[chord, rect_thick], [chord, rect_thick]],
                gamma=float(rot.theta_deg[i]), potMod=False,
                Cd=0.0, Ca=list(np.atleast_1d(rot.Ca_interp[i])),
                CdEnd=0.0, CaEnd=0.0, t=0.01, rho_shell=1850.0))
    return mems


@f64_host
def calc_cavitation(rot: RotorModel, case: dict, clearance_margin=1.0,
                    Patm=101325.0, Pvap=2500.0, error_on_cavitation=False,
                    display=0):
    """Cavitation check for a submerged rotor (reference:
    raft_rotor.py:639-696 calcCavitation).

    For each blade (azimuth) and element: run the BEM at the case current
    speed to get the relative velocity W and angle of attack, look up the
    airfoil's minimum pressure coefficient, and compare the critical
    cavitation number sigma_crit = (Patm + rho*g*|z| - Pvap)/(0.5*rho*W^2)
    against sigma_l = -cpmin.  Returns cav_check (nBlades, nr-ish):
    negative entries cavitate.
    """
    if rot.hubHt >= 0:
        raise ValueError("Hub depth must be below the water surface to "
                         "calculate cavitation")
    Uhub = float(get_from_dict(case, "current_speed", shape=0, default=0.0)) \
        * rot.speed_gain
    Omega_rpm = float(np.interp(Uhub, rot.Uhub_ops, rot.Omega_rpm_ops))
    pitch_deg = float(np.interp(Uhub, rot.Uhub_ops, rot.pitch_deg_ops))

    q = np.asarray(rot.q_rel0, float)
    dir0 = np.array([[0.0, -1.0, 0.0], [1.0, 0.0, 0.0], [0.0, 0.0, 1.0]]) @ q
    azimuths = np.atleast_1d(rot.azimuths)
    cav = np.zeros((len(azimuths), len(rot.blade_r)))
    for a, az in enumerate(azimuths):
        # tilt seen by the BEM is -shaft_tilt (q[2] = -sin(shaft_tilt))
        _, _, W, alpha = _distributed_loads(
            rot, Uhub, Omega_rpm, pitch_deg, float(az),
            -rot.shaft_tilt, 0.0)
        cpmin = _ppoly_eval(jnp.asarray(rot.cpmin_bp),
                            jnp.asarray(rot.cpmin_c), alpha)
        # node depths at the zero-offset pose
        R = _rodrigues_np(float(az), q)
        z = rot.hubHt + (np.asarray(rot.blade_r)[:, None]
                         * (R @ dir0)[None, :])[:, 2] * clearance_margin
        W = np.asarray(W)
        sigma_crit = (Patm + rot.rho * 9.81 * np.abs(z) - Pvap) \
            / np.maximum(0.5 * rot.rho * W**2, 1e-9)
        cav[a, :] = sigma_crit + np.asarray(cpmin)
    if np.any(cav < 0.0):
        if error_on_cavitation:
            raise ValueError("Cavitation occurred at a blade node")
        from raft_tpu.utils.profiling import get_logger
        get_logger("rotor").warning(
            "Cavitation check found a blade node with cavitation occurring")
    return cav
