"""Pure-JAX surrogate MLP over the served case space.

The learned read tier (:mod:`raft_tpu.serve.surrogate`) distills the
result store's corpus — every cold solve the service ever persisted —
into a tiny per-tenant MLP mapping ``(Hs, Tp, beta)`` to the served
response summary: the six per-DOF response ``std`` channels, an
iteration-count proxy, and a converged logit.  This module is the
*network only*: parameter init, the normalized forward pass, and the
optax fit loop.  Bundling, calibration, hull checks, and every serving
decision live in the serve layer — the net knows nothing about
tenants, stores, or bounds.

Design constraints, in order:

- **pure JAX, no new deps** — optax is already a dependency of the
  co-design descents (:mod:`raft_tpu.parallel.optimize`);
- **npz-serializable params** — the parameter set is a flat
  ``{name: np.ndarray}`` dict (layer weights plus the input/output
  normalization constants), so a bundle is one ``np.savez`` away and
  its digest is a hash over deterministic bytes;
- **self-contained forward** — normalization constants ride inside the
  params, so ``predict(params, X)`` is the whole inference story: a
  caller cannot forget to normalize.

Output layout (:data:`OUT_CHANNELS` wide): columns ``0..5`` are the
per-DOF response std (surge..yaw), column 6 the iters proxy, column 7
the converged logit (sigmoid > 0.5 ⇒ converged).
"""
from __future__ import annotations

import numpy as np

from raft_tpu import errors

#: input features per example: (Hs [m], Tp [s], beta [rad])
IN_FEATURES = 3
#: what the first layer actually sees: (Hs, Tp, sin beta, cos beta).
#: beta is periodic and the global-frame response channels vary with
#: it through |cos|/|sin| projections — fed raw, the net treats
#: beta=0.1 and beta=2*pi-0.1 as opposite ends of the support and
#: wastes its capacity faking the wrap; the embedding makes the
#: periodicity structural
NET_FEATURES = 4
#: output channels: 6 per-DOF std + iters proxy + converged logit
OUT_CHANNELS = 8
#: floor on normalization scales — a constant column (e.g. every
#: corpus case converged) must not divide by ~0
_SCALE_FLOOR = 1e-8


def init_params(sizes, seed: int = 0) -> dict:
    """Fresh parameter dict for layer widths ``sizes`` (e.g.
    ``[4, 32, 32, 8]`` — the first width is :data:`NET_FEATURES`),
    Glorot-scaled, deterministically seeded.  Normalization constants
    start at identity (mu=0, sd=1)."""
    sizes = [int(s) for s in sizes]
    if len(sizes) < 2 or sizes[0] != NET_FEATURES \
            or sizes[-1] != OUT_CHANNELS or any(s < 1 for s in sizes):
        raise errors.ModelConfigError(
            "surrogate net sizes must run 4 -> ... -> 8 with positive "
            "widths", sizes=str(sizes))
    rng = np.random.default_rng(int(seed))
    params = {"layers": np.asarray(len(sizes) - 1, dtype=np.int64)}
    for i, (m, n) in enumerate(zip(sizes[:-1], sizes[1:])):
        scale = np.sqrt(2.0 / (m + n))
        params[f"W{i}"] = (rng.standard_normal((m, n)) * scale).astype(
            np.float64)
        params[f"b{i}"] = np.zeros(n, dtype=np.float64)
    params["x_mu"] = np.zeros(NET_FEATURES, dtype=np.float64)
    params["x_sd"] = np.ones(NET_FEATURES, dtype=np.float64)
    params["y_mu"] = np.zeros(OUT_CHANNELS, dtype=np.float64)
    params["y_sd"] = np.ones(OUT_CHANNELS, dtype=np.float64)
    return params


def _nlayers(params: dict) -> int:
    try:
        return int(np.asarray(params["layers"]))
    except (KeyError, TypeError, ValueError) as e:
        raise errors.ModelConfigError(
            "surrogate params carry no layer count", field="layers"
        ) from e


def _features(X, xp):
    """Raw ``(N, 3)`` inputs -> the ``(N, 4)`` net features
    (Hs, Tp, sin beta, cos beta); ``xp`` is numpy or jax.numpy."""
    X = xp.asarray(X)
    return xp.concatenate(
        [X[:, :2], xp.sin(X[:, 2:3]), xp.cos(X[:, 2:3])], axis=1)


def forward(params: dict, X):
    """Batched forward pass: ``X (N, 3)`` raw inputs -> ``(N, 8)`` raw
    outputs (periodic beta embedding + normalization applied
    internally on both sides).  Traceable — the serve layer jits it
    once per bundle."""
    import jax.numpy as jnp

    L = _nlayers(params)
    h = (_features(X, jnp) - params["x_mu"]) / params["x_sd"]
    for i in range(L):
        h = h @ params[f"W{i}"] + params[f"b{i}"]
        if i < L - 1:
            h = jnp.tanh(h)
    return h * params["y_sd"] + params["y_mu"]


def forward_np(params: dict, X) -> np.ndarray:
    """:func:`forward` in pure NumPy — the serving hot path.  One
    ``(1, 3)`` row through this tiny MLP is ~15 us of float64 matmuls;
    the jitted XLA twin pays several times the net's whole FLOP cost
    in per-call dispatch overhead alone.  Training stays on JAX; the
    two agree to ~1 ulp (same float64 ops, same order), and the
    conformal calibration evaluates THIS function so the served bounds
    are calibrated against the exact forward that serves."""
    L = _nlayers(params)
    h = (_features(np.asarray(X, dtype=np.float64), np)
         - params["x_mu"]) / params["x_sd"]
    for i in range(L):
        h = h @ params[f"W{i}"] + params[f"b{i}"]
        if i < L - 1:
            np.tanh(h, out=h)
    return h * params["y_sd"] + params["y_mu"]


def fit(X, Y, *, hidden=(32, 32), steps: int = 1500, lr: float = 5e-3,
        seed: int = 0) -> tuple[dict, dict]:
    """Train the net on corpus arrays ``X (N, 3)`` / ``Y (N, 8)`` with
    full-batch Adam (the corpora are thousands of rows, not millions).

    Returns ``(params, info)``: npz-ready ``params`` (weights + the
    normalization constants fitted from THIS data) and an ``info`` dict
    with the loss trajectory endpoints and step count.  Deterministic
    for fixed inputs/seed."""
    import jax
    import jax.numpy as jnp
    import optax

    X = np.asarray(X, dtype=np.float64)
    Y = np.asarray(Y, dtype=np.float64)
    if X.ndim != 2 or X.shape[1] != IN_FEATURES or Y.ndim != 2 \
            or Y.shape[1] != OUT_CHANNELS or X.shape[0] != Y.shape[0]:
        raise errors.ModelConfigError(
            "surrogate corpus must be X (N, 3) / Y (N, 8)",
            x_shape=str(X.shape), y_shape=str(Y.shape))
    if X.shape[0] < 2:
        raise errors.ModelConfigError(
            "surrogate corpus too small to fit", rows=X.shape[0])
    if int(steps) < 1 or float(lr) <= 0.0:
        raise errors.ModelConfigError(
            "surrogate fit needs steps >= 1 and lr > 0",
            steps=int(steps), lr=float(lr))

    params = init_params([NET_FEATURES, *hidden, OUT_CHANNELS],
                         seed=seed)
    feats = np.asarray(_features(X, np))
    params["x_mu"] = feats.mean(axis=0)
    params["x_sd"] = np.maximum(feats.std(axis=0), _SCALE_FLOOR)
    params["y_mu"] = Y.mean(axis=0)
    params["y_sd"] = np.maximum(Y.std(axis=0), _SCALE_FLOOR)
    frozen = {k: params[k] for k in
              ("layers", "x_mu", "x_sd", "y_mu", "y_sd")}
    train = {k: jnp.asarray(v) for k, v in params.items()
             if k not in frozen}
    Xj, Yj = jnp.asarray(X), jnp.asarray(Y)

    def loss_fn(tp):
        pred = forward({**frozen, **tp}, Xj)
        # normalized-space MSE: every channel counts equally regardless
        # of its physical units
        err = (pred - Yj) / frozen["y_sd"]
        return jnp.mean(err * err)

    opt = optax.adam(float(lr))
    state = opt.init(train)

    @jax.jit
    def step(tp, st):
        val, grads = jax.value_and_grad(loss_fn)(tp)
        upd, st = opt.update(grads, st, tp)
        return optax.apply_updates(tp, upd), st, val

    loss0 = loss_last = None
    for _ in range(int(steps)):
        train, state, val = step(train, state)
        loss_last = float(val)
        if loss0 is None:
            loss0 = loss_last
    params = {**frozen,
              **{k: np.asarray(v, dtype=np.float64)
                 for k, v in train.items()}}
    return params, {"steps": int(steps), "loss_first": loss0,
                    "loss_last": loss_last,
                    "hidden": [int(h) for h in hidden],
                    "rows": int(X.shape[0])}


def predict_row(params: dict, Hs: float, Tp: float, beta: float):
    """Single-case convenience wrapper around :func:`forward` — one
    ``(8,)`` numpy row (std[6], iters proxy, converged logit)."""
    out = forward(params, np.asarray(
        [[float(Hs), float(Tp), float(beta)]], dtype=np.float64))
    return np.asarray(out)[0]
