"""Wake coupling for farms: Gaussian-deficit model, equilibrium, AEP.

Equivalent of the reference's FLORIS coupling surface (reference:
raft_model.py:1674-2022 — powerThrustCurve, florisCoupling,
florisFindEquilibrium, florisCalcAEP).  The reference shells out to the
optional FLORIS package; here the wake physics is built in — the
Bastankhah & Porte-Agel (2014) Gaussian self-similar deficit with
linear wake expansion and root-sum-square superposition, the same model
family FLORIS's default gauss velocity model implements — so farms get
wake-coupled operating points and AEP with zero extra dependencies.

All functions are plain numpy (host-side orchestration, like the
reference's FLORIS loop); the per-turbine aero evaluations inside the
fixed point reuse the jitted BEM rotor model.
"""
from __future__ import annotations

import numpy as np


def gaussian_deficit(x_d, y_d, Ct, k_w=0.05):
    """Normalized velocity deficit at (x_d, y_d) rotor diameters
    downstream/crosswind of a turbine with thrust coefficient Ct.
    (Fully nondimensional in the diameter — positions enter in D units.)

    Bastankhah & Porte-Agel (2014): sigma/D = k_w x/D + 0.25 sqrt(beta),
    beta = (1 + sqrt(1-Ct)) / (2 sqrt(1-Ct));
    dU/U = (1 - sqrt(1 - Ct/(8 (sigma/D)^2))) exp(-y^2/(2 sigma^2)).
    """
    Ct = np.clip(Ct, 0.0, 0.96)
    sq = np.sqrt(1.0 - Ct)
    beta = 0.5 * (1.0 + sq) / sq
    sigma_D = k_w * np.maximum(x_d, 0.1) + 0.25 * np.sqrt(beta)
    rad = 1.0 - Ct / (8.0 * sigma_D**2)
    C = 1.0 - np.sqrt(np.clip(rad, 0.0, 1.0))
    dU = C * np.exp(-y_d**2 / (2.0 * sigma_D**2))
    return np.where(x_d > 0.05, dU, 0.0)


def wake_velocities(xy, D, Ct, U_inf, wind_dir_deg=0.0, k_w=0.05):
    """Effective hub-height wind speed at each turbine of a farm.

    xy: (n,2) turbine positions [m]; D: rotor diameter(s); Ct: (n,) thrust
    coefficients; wind_dir_deg: direction the wind FLOWS TOWARD (x-axis at
    0).  Root-sum-square deficit superposition.
    """
    xy = np.asarray(xy, float)
    n = len(xy)
    D = np.broadcast_to(np.asarray(D, float), (n,))
    Ct = np.asarray(Ct, float)
    th = np.deg2rad(wind_dir_deg)
    R = np.array([[np.cos(th), np.sin(th)], [-np.sin(th), np.cos(th)]])
    xy_w = xy @ R.T          # downwind/crosswind frame
    U = np.full(n, float(U_inf))
    for i in range(n):       # receiving turbine
        ssq = 0.0
        for j in range(n):   # wake source
            if i == j:
                continue
            dx = (xy_w[i, 0] - xy_w[j, 0]) / D[j]
            dy = (xy_w[i, 1] - xy_w[j, 1]) / D[j]
            ssq += gaussian_deficit(dx, dy, Ct[j], k_w) ** 2
        U[i] = U_inf * (1.0 - np.sqrt(ssq))
    return U


def power_thrust_curve(model, speeds=None, ifowt=0, cut_in=3.0,
                       cut_out=25.0):
    """Cp/Ct/power/thrust/pitch schedule vs wind speed (reference:
    raft_model.py:1674-1750 powerThrustCurve).

    Evaluates the BEM rotor at each operating point; returns a dict of
    arrays keyed like the FLORIS turbine yaml the reference writes.
    Speeds outside [cut_in, cut_out] are PARKED — zero power/thrust/Cp/Ct
    and zero rotor speed, like the reference's 'parked' case switch
    (raft_model.py:1705-1708); np.interp clamping of the operating
    schedule would otherwise report near-rated loads at storm speeds.
    """
    from raft_tpu.models.rotor import bem_evaluate

    fowt = model.fowtList[ifowt]
    rot = fowt.rotors[0]
    if speeds is None:
        speeds = np.arange(3.0, 25.5, 1.0)
    speeds = np.asarray(speeds, float)
    rho = rot.rho
    A = np.pi * rot.R_rot**2
    P = np.zeros_like(speeds)
    T = np.zeros_like(speeds)
    pitch = np.zeros_like(speeds)
    omega = np.zeros_like(speeds)
    for i, U in enumerate(speeds):
        if not (cut_in <= U <= cut_out):
            continue                    # parked: all-zero row
        Uh = U * rot.speed_gain
        om = float(np.interp(Uh, rot.Uhub_ops, rot.Omega_rpm_ops))
        pi_deg = float(np.interp(Uh, rot.Uhub_ops, rot.pitch_deg_ops))
        # tilt seen by the BEM is -shaft_tilt (q[2] = -sin(shaft_tilt);
        # same convention calc_aero derives from the pose)
        loads = bem_evaluate(rot, Uh, om, pi_deg, tilt=-rot.shaft_tilt)
        P[i] = float(loads["P"])
        T[i] = float(loads["T"])
        pitch[i] = pi_deg
        omega[i] = om
    Cp = P / (0.5 * rho * A * speeds**3)
    Ct = np.clip(T / (0.5 * rho * A * speeds**2), 0.0, 2.0)
    return dict(wind_speed=speeds, power=P, thrust=T, Cp=Cp, Ct=Ct,
                pitch_deg=pitch, omega_rpm=omega, rotor_area=A)


def _curve_interp(U, curve, key, outside=0.0):
    """Interpolate a power/thrust-curve channel at speeds U, returning
    `outside` beyond the curve's speed range (below cut-in / above
    cut-out the turbine is parked: zero power and thrust, not the
    clamped endpoint value np.interp would give)."""
    U = np.asarray(U, float)
    xs = curve["wind_speed"]
    vals = np.interp(U, xs, curve[key])
    return np.where((U < xs[0]) | (U > xs[-1]), outside, vals)


def _farm_curves(model, curve=None):
    """One power/thrust curve per FOWT, computed once per distinct rotor
    object (heterogeneous farms of design variants get their own curves).
    `curve` may be a single curve dict (applied to all) or a list."""
    if isinstance(curve, dict):
        return [curve] * model.nFOWT
    if curve is not None:
        return list(curve)
    cache = {}
    out = []
    for i, f in enumerate(model.fowtList):
        key = id(f.rotors[0])
        if key not in cache:
            cache[key] = power_thrust_curve(model, ifowt=i)
        out.append(cache[key])
    return out


def find_wake_equilibrium(model, case, k_w=0.05, max_iter=100, tol=1e-4,
                          relax=0.5, curve=None):
    """Farm wake fixed point (reference: raft_model.py:1852-1994
    florisFindEquilibrium): wake model -> per-turbine wind speeds ->
    thrust coefficients -> wake model, with under-relaxation.

    `case['wind_speed']` may be a scalar free-stream speed or a
    per-turbine list (as produced by this function itself / accepted by
    Model._case_for_fowt) — a list is reduced to its maximum, the
    free-stream value unaffected by wakes.

    Returns dict(U (n,), Ct (n,), power (n,), case with per-turbine
    wind_speed list ready for Model.analyzeCases).
    """
    n = model.nFOWT
    ws = case.get("wind_speed", 10.0)
    U_inf = float(np.max(ws)) if np.ndim(ws) > 0 else float(ws)
    wh = np.atleast_1d(np.asarray(case.get("wind_heading", 0.0), float))
    # circular mean (arithmetic mean of e.g. [350, 10] deg is wrong)
    wind_dir = float(np.rad2deg(np.arctan2(
        np.mean(np.sin(np.deg2rad(wh))), np.mean(np.cos(np.deg2rad(wh))))))
    xy = np.array([[f.x_ref, f.y_ref] for f in model.fowtList])
    rots = [f.rotors[0] for f in model.fowtList]
    D = np.array([2.0 * r.R_rot for r in rots])

    curves = _farm_curves(model, curve)

    U = np.full(n, U_inf)
    Ct = np.array([float(_curve_interp(U[i], curves[i], "Ct"))
                   for i in range(n)])
    for it in range(max_iter):
        U_new = wake_velocities(xy, D, Ct, U_inf, wind_dir, k_w)
        if np.max(np.abs(U_new - U)) < tol:
            U = U_new
            break
        U = relax * U + (1.0 - relax) * U_new
        Ct = np.array([float(_curve_interp(U[i], curves[i], "Ct"))
                       for i in range(n)])
    power = np.array([float(_curve_interp(U[i], curves[i], "power"))
                      for i in range(n)])
    case_out = dict(case)
    case_out["wind_speed"] = list(U)
    return dict(U=U, Ct=Ct, power=power, case=case_out, iterations=it + 1)


def calc_aep(model, wind_rose, k_w=0.05, availability=1.0):
    """Wind-rose AEP [Wh] with wake losses (reference:
    raft_model.py:1996-2022 florisCalcAEP).

    wind_rose: iterable of (speed [m/s], direction [deg], probability);
    probabilities should sum to ~1.
    """
    curves = _farm_curves(model)
    hours = 8760.0
    aep = 0.0
    per_state = []
    for speed, wd, prob in wind_rose:
        eq = find_wake_equilibrium(
            model, dict(wind_speed=speed, wind_heading=wd),
            k_w=k_w, curve=curves)
        farm_p = float(np.sum(eq["power"]))
        per_state.append(dict(speed=speed, dir=wd, prob=prob,
                              farm_power=farm_p, U=eq["U"]))
        aep += prob * farm_p * hours
    return dict(AEP=aep * availability, states=per_state)


# --------------------------------------------------------------------------
# FLORIS interop (optional dependency; reference: raft_model.py:1753-1850)
# --------------------------------------------------------------------------

def floris_available() -> bool:
    """True when the optional FLORIS package can be imported."""
    try:
        import floris  # noqa: F401
        return True
    except ImportError:
        return False


def floris_turbine_dict(model, ifowt, turb_template, uhubs=None):
    """Per-turbine FLORIS turbine-library dict from the BEM power/thrust
    curve (the body of the reference's florisCoupling turbine loop,
    raft_model.py:1806-1846): hub height, rotor diameter, air density,
    power/thrust tables from powerThrustCurve, and the floating tilt
    table (mean platform pitch schedule) for the Empirical Gaussian wake
    deflection model.  ``turb_template`` is the base turbine yaml dict to
    update; pure data — no floris import needed.

    DEVIATION (docs/quirks.md #23): the floating tilt table here is the
    small-angle linearization atan2(thrust*zhub, C55) about the reference
    pose, whereas the reference runs a full solveStatics per wind speed
    and records the equilibrium pitch Xi0[4] (raft_model.py:1722).  The
    linearization drops the aero pitch moment about the PRP
    (overhang/hub-moment), mooring nonlinearity at the offset position,
    and mean drag — adequate for the Empirical Gaussian deflection input
    (degree-level agreement), but pass explicit equilibrium pitches via a
    statics sweep if exact reference tilt parity is needed."""
    fowt = model.fowtList[ifowt]
    rot = fowt.rotors[0]
    if uhubs is None:
        # the reference's grid: 3..24.5 step 0.5 plus 25.02 and 50
        uhubs = list(np.arange(3.0, 25.0, 0.5)) + [25.02, 50.0]
    uhubs = np.asarray(uhubs, float)
    curve = power_thrust_curve(model, speeds=uhubs, ifowt=ifowt)
    # mean platform pitch at each operating point: thrust at hub height
    # against the pitch hydrostatic+mooring stiffness about the FOWT's
    # reference position (anchors are laid out about (x_ref, y_ref) —
    # evaluating the mooring at the origin would solve km-scale spans)
    from raft_tpu.models import mooring as mr
    ref6 = np.array([fowt.x_ref, fowt.y_ref, 0.0, 0.0, 0.0, 0.0])
    st = model._state[ifowt].get("statics")
    if st is None:
        from raft_tpu.models.fowt import fowt_pose, fowt_statics
        pose0 = fowt_pose(fowt, ref6)
        st = fowt_statics(fowt, pose0)
    C55 = float(np.asarray(st["C_struc"] + st["C_hydro"])[4, 4])
    if fowt.mooring is not None:
        C55 += float(np.asarray(
            mr.coupled_stiffness(fowt.mooring, ref6))[4, 4])
    # true hub height (reference raft_model.py:1812 writes hHub):
    # r_rel[2] is the RNA reference z = hHub - q_rel[2]*overhang
    zhub = float(rot.hubHt)
    tilt = np.degrees(np.arctan2(curve["thrust"] * zhub, C55))

    out = dict(turb_template)
    out["hub_height"] = float(zhub)
    out["rotor_diameter"] = float(2.0 * rot.R_rot)
    out["ref_density_cp_ct"] = float(rot.rho)
    out["turbine_type"] = f"turb{ifowt}_floating"
    # Cp/Ct already carry the floating mean tilt; FLORIS must not re-tilt
    out["floating_correct_cp_ct_for_tilt"] = False
    # FLORIS v3 power_thrust_table semantics (matching the reference,
    # raft_model.py:1837-1839): 'power' is the power COEFFICIENT Cp,
    # 'thrust' the thrust coefficient Ct — FLORIS dimensionalizes with
    # 0.5 rho A U^3 itself
    ptt = dict(out.get("power_thrust_table") or {})
    ptt["power"] = np.asarray(curve["Cp"]).tolist()
    ptt["thrust"] = np.asarray(curve["Ct"]).tolist()
    ptt["wind_speed"] = uhubs.tolist()
    out["power_thrust_table"] = ptt
    ftt = dict(out.get("floating_tilt_table") or {})
    ftt["wind_speeds"] = uhubs.tolist()
    ftt["tilt"] = tilt.tolist()
    out["floating_tilt_table"] = ftt
    return out


def floris_coupling(model, config, turbconfig, path):
    """Set up a FLORIS interface from this model (reference
    florisCoupling, raft_model.py:1753-1850): write one turbine yaml per
    unique (turbine, platform, mooring, heading) combination into
    ``path`` and reinitialize FLORIS with the farm layout and those
    turbine types.  Requires the optional ``floris`` package — without
    it, raise ImportError pointing at the built-in Gaussian wake
    (find_wake_equilibrium / calc_aep), which needs no dependencies.

    config: floris farm config yaml path; turbconfig: list of turbine
    yaml paths indexed by turbineID; path: output turbine-library dir.
    Returns the FlorisInterface; also stored as ``model.fi``.
    """
    try:
        from floris.tools import FlorisInterface
    except ImportError as e:
        raise ImportError(
            "floris is not installed — use the built-in wake coupling "
            "(raft_tpu.models.wake.find_wake_equilibrium / calc_aep), "
            "or pip install floris for FLORIS-driven wakes") from e
    import os

    import yaml

    fi = FlorisInterface(config)
    site = model.design.get("site", {})
    fi.reinitialize(air_density=site.get("rho_air", 1.225),
                    wind_shear=site.get("shearExp", 0.12))
    arr = model.design.get("array")
    if arr:
        rows = [dict(zip(arr["keys"], r)) for r in arr["data"]]
    else:
        rows = [dict(turbineID=1, platformID=1, mooringID=1,
                     heading_adjust=0.0, x_location=f.x_ref,
                     y_location=f.y_ref) for f in model.fowtList]
    fi.reinitialize(layout_x=[r["x_location"] for r in rows],
                    layout_y=[r["y_location"] for r in rows])

    turblist, unique = [], []
    for i, r in enumerate(rows):
        key = [r.get("turbineID", 1), r.get("platformID", 1),
               r.get("mooringID", 1), r.get("heading_adjust", 0.0)]
        if key in unique:
            ID = unique.index(key)
        else:
            unique.append(key)
            ID = len(unique) - 1
            with open(turbconfig[r.get("turbineID", 1) - 1]) as f:
                template = yaml.safe_load(f)
            td = floris_turbine_dict(model, i, template)
            td["turbine_type"] = f"turb{ID}_floating"
            with open(os.path.join(path, f"turb{ID}.yaml"), "w") as f:
                yaml.dump(td, f, sort_keys=False, default_flow_style=None)
        turblist.append(f"turb{ID}.yaml")
    fi.reinitialize(turbine_type=turblist, turbine_library_path=path)
    model.fi = fi
    model.turblist = turblist
    return fi
