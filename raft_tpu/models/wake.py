"""Wake coupling for farms: Gaussian-deficit model, equilibrium, AEP.

Equivalent of the reference's FLORIS coupling surface (reference:
raft_model.py:1674-2022 — powerThrustCurve, florisCoupling,
florisFindEquilibrium, florisCalcAEP).  The reference shells out to the
optional FLORIS package; here the wake physics is built in — the
Bastankhah & Porte-Agel (2014) Gaussian self-similar deficit with
linear wake expansion and root-sum-square superposition, the same model
family FLORIS's default gauss velocity model implements — so farms get
wake-coupled operating points and AEP with zero extra dependencies.

The host functions are plain numpy (host-side orchestration, like the
reference's FLORIS loop); the per-turbine aero evaluations behind the
power/thrust curve reuse the jitted BEM rotor model.  The ``*_jnp``
twins at the bottom are the device-resident port the batched farm sweep
(:func:`raft_tpu.parallel.sweep.sweep_farm`) traces into its single
compiled program — same deficit model, same fixed-point schedule,
shape-stable ``lax.while_loop`` instead of the host Python loop.
"""
from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp

#: the reference clips Ct at 0.96 before dividing by sqrt(1 - Ct); the
#: guard below additionally floors (1 - Ct) at this value so a jnp port
#: of the same expression has no Ct -> 1 singularity on the UNTAKEN
#: branch (jax.grad evaluates both sides of a where/clip — a NaN there
#: poisons the gradient even when the clipped forward value is fine)
CT_MAX = 0.96
_ONE_MINUS_CT_MIN = 1.0 - CT_MAX


def gaussian_deficit(x_d, y_d, Ct, k_w=0.05):
    """Normalized velocity deficit at (x_d, y_d) rotor diameters
    downstream/crosswind of a turbine with thrust coefficient Ct.
    (Fully nondimensional in the diameter — positions enter in D units.)

    Bastankhah & Porte-Agel (2014): sigma/D = k_w x/D + 0.25 sqrt(beta),
    beta = (1 + sqrt(1-Ct)) / (2 sqrt(1-Ct));
    dU/U = (1 - sqrt(1 - Ct/(8 (sigma/D)^2))) exp(-y^2/(2 sigma^2)).

    Ct -> 1 guard: Ct is clipped at :data:`CT_MAX` AND (1 - Ct) is
    floored at (1 - CT_MAX) before the square root — bitwise identical
    for every in-range Ct, finite (value and gradient) for any raw
    Ct >= 1 a thrust curve or an optimizer step might produce.
    """
    Ct = np.clip(Ct, 0.0, CT_MAX)
    sq = np.sqrt(np.maximum(1.0 - Ct, _ONE_MINUS_CT_MIN))
    beta = 0.5 * (1.0 + sq) / sq
    sigma_D = k_w * np.maximum(x_d, 0.1) + 0.25 * np.sqrt(beta)
    rad = 1.0 - Ct / (8.0 * sigma_D**2)
    C = 1.0 - np.sqrt(np.clip(rad, 0.0, 1.0))
    dU = C * np.exp(-y_d**2 / (2.0 * sigma_D**2))
    return np.where(x_d > 0.05, dU, 0.0)


def _wake_frame(xy, wind_dir_deg):
    """Rotate farm coordinates into the downwind/crosswind frame."""
    th = np.deg2rad(wind_dir_deg)
    R = np.array([[np.cos(th), np.sin(th)], [-np.sin(th), np.cos(th)]])
    return np.asarray(xy, float) @ R.T


def wake_velocities(xy, D, Ct, U_inf, wind_dir_deg=0.0, k_w=0.05):
    """Effective hub-height wind speed at each turbine of a farm.

    xy: (n,2) turbine positions [m]; D: rotor diameter(s); Ct: (n,) thrust
    coefficients; wind_dir_deg: direction the wind FLOWS TOWARD (x-axis at
    0).  Root-sum-square deficit superposition.

    One broadcast over the full (receiver i, source j) pair matrix —
    the O(n^2) Python double loop this replaces is parity-pinned in
    tests/test_wake.py (the loop sums sources in index order; the
    pairwise summation here agrees to float64 roundoff).
    """
    xy = np.asarray(xy, float)
    n = len(xy)
    D = np.broadcast_to(np.asarray(D, float), (n,))
    Ct = np.asarray(Ct, float)
    xy_w = _wake_frame(xy, wind_dir_deg)   # downwind/crosswind frame
    # pair matrices, receiver i on rows / source j on columns,
    # normalized by the SOURCE diameter (self-deficit masked below;
    # gaussian_deficit is zero at x_d = 0 anyway, the mask keeps the
    # diagonal exactly 0.0 regardless of the near-wake cutoff)
    dx = (xy_w[:, 0][:, None] - xy_w[None, :, 0]) / D[None, :]
    dy = (xy_w[:, 1][:, None] - xy_w[None, :, 1]) / D[None, :]
    dU = gaussian_deficit(dx, dy, Ct[None, :], k_w)
    np.fill_diagonal(dU, 0.0)
    ssq = np.sum(dU**2, axis=1)
    return U_inf * (1.0 - np.sqrt(ssq))


def power_thrust_curve(model, speeds=None, ifowt=0, cut_in=3.0,
                       cut_out=25.0):
    """Cp/Ct/power/thrust/pitch schedule vs wind speed (reference:
    raft_model.py:1674-1750 powerThrustCurve).

    Evaluates the BEM rotor at each operating point; returns a dict of
    arrays keyed like the FLORIS turbine yaml the reference writes.
    Speeds outside [cut_in, cut_out] are PARKED — zero power/thrust/Cp/Ct
    and zero rotor speed, like the reference's 'parked' case switch
    (raft_model.py:1705-1708); np.interp clamping of the operating
    schedule would otherwise report near-rated loads at storm speeds.

    ``model`` may be a Model (rotor taken from ``fowtList[ifowt]``) or a
    bare FOWT (so the farm sweep path can build a curve without a Model).
    The operating-schedule lookups are batched (one np.interp per
    channel over all speeds) and a single jitted BEM closure is traced
    once and reused across speeds — the rotor geometry setup no longer
    repeats per operating point.
    """
    import jax as _jax

    from raft_tpu.models.rotor import bem_evaluate

    fowt = model.fowtList[ifowt] if hasattr(model, "fowtList") else model
    rot = fowt.rotors[0]
    if speeds is None:
        speeds = np.arange(3.0, 25.5, 1.0)
    speeds = np.asarray(speeds, float)
    rho = rot.rho
    A = np.pi * rot.R_rot**2
    P = np.zeros_like(speeds)
    T = np.zeros_like(speeds)
    pitch = np.zeros_like(speeds)
    omega = np.zeros_like(speeds)
    op = (speeds >= cut_in) & (speeds <= cut_out)   # else parked: zero row
    Uh_all = speeds * rot.speed_gain
    om_all = np.interp(Uh_all, rot.Uhub_ops, rot.Omega_rpm_ops)
    pi_all = np.interp(Uh_all, rot.Uhub_ops, rot.pitch_deg_ops)

    # tilt seen by the BEM is -shaft_tilt (q[2] = -sin(shaft_tilt);
    # same convention calc_aero derives from the pose)
    @_jax.jit
    def _bem(U, om, pi_deg):
        out = bem_evaluate(rot, U, om, pi_deg, tilt=-rot.shaft_tilt)
        return out["P"], out["T"]

    for i in np.flatnonzero(op):
        p_i, t_i = _bem(Uh_all[i], om_all[i], pi_all[i])
        P[i] = float(p_i)
        T[i] = float(t_i)
    pitch[op] = pi_all[op]
    omega[op] = om_all[op]
    Cp = P / (0.5 * rho * A * speeds**3)
    Ct = np.clip(T / (0.5 * rho * A * speeds**2), 0.0, 2.0)
    return dict(wind_speed=speeds, power=P, thrust=T, Cp=Cp, Ct=Ct,
                pitch_deg=pitch, omega_rpm=omega, rotor_area=A)


def _curve_interp(U, curve, key, outside=0.0):
    """Interpolate a power/thrust-curve channel at speeds U, returning
    `outside` beyond the curve's speed range (below cut-in / above
    cut-out the turbine is parked: zero power and thrust, not the
    clamped endpoint value np.interp would give)."""
    U = np.asarray(U, float)
    xs = curve["wind_speed"]
    vals = np.interp(U, xs, curve[key])
    return np.where((U < xs[0]) | (U > xs[-1]), outside, vals)


def _farm_curves(model, curve=None):
    """One power/thrust curve per FOWT, computed once per distinct rotor
    object (heterogeneous farms of design variants get their own curves).
    `curve` may be a single curve dict (applied to all) or a list."""
    if isinstance(curve, dict):
        return [curve] * model.nFOWT
    if curve is not None:
        return list(curve)
    cache = {}
    out = []
    for i, f in enumerate(model.fowtList):
        key = id(f.rotors[0])
        if key not in cache:
            cache[key] = power_thrust_curve(model, ifowt=i)
        out.append(cache[key])
    return out


def find_wake_equilibrium(model, case, k_w=0.05, max_iter=100, tol=1e-4,
                          relax=0.5, curve=None):
    """Farm wake fixed point (reference: raft_model.py:1852-1994
    florisFindEquilibrium): wake model -> per-turbine wind speeds ->
    thrust coefficients -> wake model, with under-relaxation.

    `case['wind_speed']` may be a scalar free-stream speed or a
    per-turbine list (as produced by this function itself / accepted by
    Model._case_for_fowt) — a list is reduced to its maximum, the
    free-stream value unaffected by wakes.

    Returns dict(U (n,), Ct (n,), power (n,), case with per-turbine
    wind_speed list ready for Model.analyzeCases).
    """
    n = model.nFOWT
    ws = case.get("wind_speed", 10.0)
    U_inf = float(np.max(ws)) if np.ndim(ws) > 0 else float(ws)
    wh = np.atleast_1d(np.asarray(case.get("wind_heading", 0.0), float))
    # circular mean (arithmetic mean of e.g. [350, 10] deg is wrong)
    wind_dir = float(np.rad2deg(np.arctan2(
        np.mean(np.sin(np.deg2rad(wh))), np.mean(np.cos(np.deg2rad(wh))))))
    xy = np.array([[f.x_ref, f.y_ref] for f in model.fowtList])
    rots = [f.rotors[0] for f in model.fowtList]
    D = np.array([2.0 * r.R_rot for r in rots])

    curves = _farm_curves(model, curve)

    U = np.full(n, U_inf)
    Ct = np.array([float(_curve_interp(U[i], curves[i], "Ct"))
                   for i in range(n)])
    for it in range(max_iter):
        U_new = wake_velocities(xy, D, Ct, U_inf, wind_dir, k_w)
        if np.max(np.abs(U_new - U)) < tol:
            U = U_new
            break
        U = relax * U + (1.0 - relax) * U_new
        Ct = np.array([float(_curve_interp(U[i], curves[i], "Ct"))
                       for i in range(n)])
    power = np.array([float(_curve_interp(U[i], curves[i], "power"))
                      for i in range(n)])
    case_out = dict(case)
    case_out["wind_speed"] = list(U)
    return dict(U=U, Ct=Ct, power=power, case=case_out, iterations=it + 1)


def calc_aep(model, wind_rose, k_w=0.05, availability=1.0):
    """Wind-rose AEP [Wh] with wake losses (reference:
    raft_model.py:1996-2022 florisCalcAEP).

    wind_rose: iterable of (speed [m/s], direction [deg], probability);
    probabilities should sum to ~1.
    """
    curves = _farm_curves(model)
    hours = 8760.0
    aep = 0.0
    per_state = []
    for speed, wd, prob in wind_rose:
        eq = find_wake_equilibrium(
            model, dict(wind_speed=speed, wind_heading=wd),
            k_w=k_w, curve=curves)
        farm_p = float(np.sum(eq["power"]))
        per_state.append(dict(speed=speed, dir=wd, prob=prob,
                              farm_power=farm_p, U=eq["U"]))
        aep += prob * farm_p * hours
    return dict(AEP=aep * availability, states=per_state)


# --------------------------------------------------------------------------
# Device-resident port (jnp) — traced into the batched farm program
# --------------------------------------------------------------------------
# Same Bastankhah deficit, same fixed-point schedule as the host
# functions above, but expressed as shape-stable jax so sweep_farm can
# fold the wake equilibrium into its ONE compiled program.  The
# wake<->rotor coupling enters through the (wind_speed, Ct, power)
# curve tables produced by power_thrust_curve — i.e. the jitted BEM
# evaluation, sampled once per rotor design, exactly as the host loop
# consumes it via _curve_interp.

def gaussian_deficit_jnp(x_d, y_d, Ct, k_w=0.05):
    """jnp twin of :func:`gaussian_deficit` — identical math, plus
    double-where guards so jax.grad stays finite at Ct -> 1 (the clip
    boundary) and at rad <= 0 (sqrt(0) has an infinite derivative and
    clip alone still differentiates the sqrt at 0)."""
    Ct = jnp.clip(Ct, 0.0, CT_MAX)
    sq = jnp.sqrt(jnp.maximum(1.0 - Ct, _ONE_MINUS_CT_MIN))
    beta = 0.5 * (1.0 + sq) / sq
    sigma_D = k_w * jnp.maximum(x_d, 0.1) + 0.25 * jnp.sqrt(beta)
    rad = 1.0 - Ct / (8.0 * sigma_D**2)
    rad_pos = rad > 0.0
    C = 1.0 - jnp.where(rad_pos,
                        jnp.sqrt(jnp.where(rad_pos, rad, 1.0)),
                        jnp.where(rad > 1.0, 1.0, 0.0))
    # rad > 1 cannot occur (Ct >= 0, sigma_D > 0) but keeps the clip
    # semantics of the host exact: clip(rad, 0, 1) -> sqrt
    dU = C * jnp.exp(-y_d**2 / (2.0 * sigma_D**2))
    return jnp.where(x_d > 0.05, dU, 0.0)


def wake_velocities_jnp(xy_w, D, Ct, U_inf, k_w=0.05):
    """jnp twin of :func:`wake_velocities`, already in the wake frame.

    xy_w: (n,2) positions rotated into the downwind/crosswind frame
    (rotation is a case-level constant — do it once outside the fixed
    point); D: (n,); Ct: (n,); U_inf scalar.  Returns (n,) speeds.
    """
    dx = (xy_w[:, 0][:, None] - xy_w[None, :, 0]) / D[None, :]
    dy = (xy_w[:, 1][:, None] - xy_w[None, :, 1]) / D[None, :]
    dU = gaussian_deficit_jnp(dx, dy, Ct[None, :], k_w)
    n = xy_w.shape[0]
    dU = dU * (1.0 - jnp.eye(n, dtype=dU.dtype))   # no self-deficit
    ssq = jnp.sum(dU**2, axis=1)
    return U_inf * (1.0 - jnp.sqrt(ssq))


def _curve_interp_jnp(U, xs, vals, outside=0.0):
    """jnp twin of :func:`_curve_interp` — clamped interp with the
    parked-outside-range override."""
    out = jnp.interp(U, xs, vals)
    return jnp.where((U < xs[0]) | (U > xs[-1]), outside, out)


def wake_equilibrium_jnp(xy, D, curve_speed, curve_Ct, curve_power,
                         U_inf, wind_dir_deg, k_w=0.05, max_iter=100,
                         tol=1e-4, relax=0.5):
    """Device-resident farm wake fixed point — the jnp mirror of
    :func:`find_wake_equilibrium`'s iteration, for ONE (U_inf, wind
    direction) state of a homogeneous farm (one curve table shared by
    all turbines).

    The host loop breaks as soon as max|U_new - U| < tol, keeping
    U = U_new and NOT re-interpolating Ct on the break iteration; the
    while_loop state machine below reproduces that exactly, so the two
    paths agree bitwise-modulo-interp-roundoff at any iteration count.

    Returns dict(U (n,), Ct (n,), power (n,), iterations scalar int).
    """
    xy = jnp.asarray(xy)
    n = xy.shape[0]
    D = jnp.broadcast_to(jnp.asarray(D), (n,))
    th = jnp.deg2rad(wind_dir_deg)
    R = jnp.stack([jnp.stack([jnp.cos(th), jnp.sin(th)]),
                   jnp.stack([-jnp.sin(th), jnp.cos(th)])])
    xy_w = xy @ R.T

    def interp_ct(U):
        return _curve_interp_jnp(U, curve_speed, curve_Ct)

    U0 = jnp.broadcast_to(jnp.asarray(U_inf, dtype=xy_w.dtype), (n,))
    Ct0 = interp_ct(U0)

    def cond(state):
        U, Ct, it, done = state
        return (~done) & (it < max_iter)

    def body(state):
        U, Ct, it, _ = state
        U_new = wake_velocities_jnp(xy_w, D, Ct, U_inf, k_w)
        conv = jnp.max(jnp.abs(U_new - U)) < tol
        U2 = jnp.where(conv, U_new, relax * U + (1.0 - relax) * U_new)
        Ct2 = jnp.where(conv, Ct, interp_ct(U2))
        return (U2, Ct2, it + 1, conv)

    U, Ct, it, _ = jax.lax.while_loop(
        cond, body, (U0, Ct0, jnp.asarray(0), jnp.asarray(False)))
    power = _curve_interp_jnp(U, curve_speed, curve_power)
    return dict(U=U, Ct=Ct, power=power, iterations=it)


def wake_equilibria_jnp(xy, D, curve_speed, curve_Ct, curve_power,
                        U_inf, wind_dir_deg, k_w=0.05, max_iter=100,
                        tol=1e-4, relax=0.5):
    """vmap of :func:`wake_equilibrium_jnp` over a case axis.

    U_inf, wind_dir_deg: (ncases,).  Returns dict with U/Ct/power of
    shape (ncases, n_turbines) and iterations (ncases,).
    """
    def one(ui, wd):
        return wake_equilibrium_jnp(
            xy, D, curve_speed, curve_Ct, curve_power, ui, wd,
            k_w=k_w, max_iter=max_iter, tol=tol, relax=relax)

    return jax.vmap(one)(jnp.asarray(U_inf), jnp.asarray(wind_dir_deg))


# --------------------------------------------------------------------------
# FLORIS interop (optional dependency; reference: raft_model.py:1753-1850)
# --------------------------------------------------------------------------

def floris_available() -> bool:
    """True when the optional FLORIS package can be imported."""
    try:
        import floris  # noqa: F401
        return True
    except ImportError:
        return False


def floris_turbine_dict(model, ifowt, turb_template, uhubs=None):
    """Per-turbine FLORIS turbine-library dict from the BEM power/thrust
    curve (the body of the reference's florisCoupling turbine loop,
    raft_model.py:1806-1846): hub height, rotor diameter, air density,
    power/thrust tables from powerThrustCurve, and the floating tilt
    table (mean platform pitch schedule) for the Empirical Gaussian wake
    deflection model.  ``turb_template`` is the base turbine yaml dict to
    update; pure data — no floris import needed.

    DEVIATION (docs/quirks.md #23): the floating tilt table here is the
    small-angle linearization atan2(thrust*zhub, C55) about the reference
    pose, whereas the reference runs a full solveStatics per wind speed
    and records the equilibrium pitch Xi0[4] (raft_model.py:1722).  The
    linearization drops the aero pitch moment about the PRP
    (overhang/hub-moment), mooring nonlinearity at the offset position,
    and mean drag — adequate for the Empirical Gaussian deflection input
    (degree-level agreement), but pass explicit equilibrium pitches via a
    statics sweep if exact reference tilt parity is needed."""
    fowt = model.fowtList[ifowt]
    rot = fowt.rotors[0]
    if uhubs is None:
        # the reference's grid: 3..24.5 step 0.5 plus 25.02 and 50
        uhubs = list(np.arange(3.0, 25.0, 0.5)) + [25.02, 50.0]
    uhubs = np.asarray(uhubs, float)
    curve = power_thrust_curve(model, speeds=uhubs, ifowt=ifowt)
    # mean platform pitch at each operating point: thrust at hub height
    # against the pitch hydrostatic+mooring stiffness about the FOWT's
    # reference position (anchors are laid out about (x_ref, y_ref) —
    # evaluating the mooring at the origin would solve km-scale spans)
    from raft_tpu.models import mooring as mr
    ref6 = np.array([fowt.x_ref, fowt.y_ref, 0.0, 0.0, 0.0, 0.0])
    st = model._state[ifowt].get("statics")
    if st is None:
        from raft_tpu.models.fowt import fowt_pose, fowt_statics
        pose0 = fowt_pose(fowt, ref6)
        st = fowt_statics(fowt, pose0)
    C55 = float(np.asarray(st["C_struc"] + st["C_hydro"])[4, 4])
    if fowt.mooring is not None:
        C55 += float(np.asarray(
            mr.coupled_stiffness(fowt.mooring, ref6))[4, 4])
    # true hub height (reference raft_model.py:1812 writes hHub):
    # r_rel[2] is the RNA reference z = hHub - q_rel[2]*overhang
    zhub = float(rot.hubHt)
    tilt = np.degrees(np.arctan2(curve["thrust"] * zhub, C55))

    out = dict(turb_template)
    out["hub_height"] = float(zhub)
    out["rotor_diameter"] = float(2.0 * rot.R_rot)
    out["ref_density_cp_ct"] = float(rot.rho)
    out["turbine_type"] = f"turb{ifowt}_floating"
    # Cp/Ct already carry the floating mean tilt; FLORIS must not re-tilt
    out["floating_correct_cp_ct_for_tilt"] = False
    # FLORIS v3 power_thrust_table semantics (matching the reference,
    # raft_model.py:1837-1839): 'power' is the power COEFFICIENT Cp,
    # 'thrust' the thrust coefficient Ct — FLORIS dimensionalizes with
    # 0.5 rho A U^3 itself
    ptt = dict(out.get("power_thrust_table") or {})
    ptt["power"] = np.asarray(curve["Cp"]).tolist()
    ptt["thrust"] = np.asarray(curve["Ct"]).tolist()
    ptt["wind_speed"] = uhubs.tolist()
    out["power_thrust_table"] = ptt
    ftt = dict(out.get("floating_tilt_table") or {})
    ftt["wind_speeds"] = uhubs.tolist()
    ftt["tilt"] = tilt.tolist()
    out["floating_tilt_table"] = ftt
    return out


def floris_coupling(model, config, turbconfig, path):
    """Set up a FLORIS interface from this model (reference
    florisCoupling, raft_model.py:1753-1850): write one turbine yaml per
    unique (turbine, platform, mooring, heading) combination into
    ``path`` and reinitialize FLORIS with the farm layout and those
    turbine types.  Requires the optional ``floris`` package — without
    it, raise ImportError pointing at the built-in Gaussian wake
    (find_wake_equilibrium / calc_aep), which needs no dependencies.

    config: floris farm config yaml path; turbconfig: list of turbine
    yaml paths indexed by turbineID; path: output turbine-library dir.
    Returns the FlorisInterface; also stored as ``model.fi``.
    """
    try:
        from floris.tools import FlorisInterface
    except ImportError as e:
        raise ImportError(
            "floris is not installed — use the built-in wake coupling "
            "(raft_tpu.models.wake.find_wake_equilibrium / calc_aep), "
            "or pip install floris for FLORIS-driven wakes") from e
    import os

    import yaml

    fi = FlorisInterface(config)
    site = model.design.get("site", {})
    fi.reinitialize(air_density=site.get("rho_air", 1.225),
                    wind_shear=site.get("shearExp", 0.12))
    arr = model.design.get("array")
    if arr:
        rows = [dict(zip(arr["keys"], r)) for r in arr["data"]]
    else:
        rows = [dict(turbineID=1, platformID=1, mooringID=1,
                     heading_adjust=0.0, x_location=f.x_ref,
                     y_location=f.y_ref) for f in model.fowtList]
    fi.reinitialize(layout_x=[r["x_location"] for r in rows],
                    layout_y=[r["y_location"] for r in rows])

    turblist, unique = [], []
    for i, r in enumerate(rows):
        key = [r.get("turbineID", 1), r.get("platformID", 1),
               r.get("mooringID", 1), r.get("heading_adjust", 0.0)]
        if key in unique:
            ID = unique.index(key)
        else:
            unique.append(key)
            ID = len(unique) - 1
            with open(turbconfig[r.get("turbineID", 1) - 1]) as f:
                template = yaml.safe_load(f)
            td = floris_turbine_dict(model, i, template)
            td["turbine_type"] = f"turb{ID}_floating"
            with open(os.path.join(path, f"turb{ID}.yaml"), "w") as f:
                yaml.dump(td, f, sort_keys=False, default_flow_style=None)
        turblist.append(f"turb{ID}.yaml")
    fi.reinitialize(turbine_type=turblist, turbine_library_path=path)
    model.fi = fi
    model.turblist = turblist
    return fi
