"""Wake coupling for farms: Gaussian-deficit model, equilibrium, AEP.

Equivalent of the reference's FLORIS coupling surface (reference:
raft_model.py:1674-2022 — powerThrustCurve, florisCoupling,
florisFindEquilibrium, florisCalcAEP).  The reference shells out to the
optional FLORIS package; here the wake physics is built in — the
Bastankhah & Porte-Agel (2014) Gaussian self-similar deficit with
linear wake expansion and root-sum-square superposition, the same model
family FLORIS's default gauss velocity model implements — so farms get
wake-coupled operating points and AEP with zero extra dependencies.

All functions are plain numpy (host-side orchestration, like the
reference's FLORIS loop); the per-turbine aero evaluations inside the
fixed point reuse the jitted BEM rotor model.
"""
from __future__ import annotations

import numpy as np


def gaussian_deficit(x_d, y_d, Ct, k_w=0.05):
    """Normalized velocity deficit at (x_d, y_d) rotor diameters
    downstream/crosswind of a turbine with thrust coefficient Ct.
    (Fully nondimensional in the diameter — positions enter in D units.)

    Bastankhah & Porte-Agel (2014): sigma/D = k_w x/D + 0.25 sqrt(beta),
    beta = (1 + sqrt(1-Ct)) / (2 sqrt(1-Ct));
    dU/U = (1 - sqrt(1 - Ct/(8 (sigma/D)^2))) exp(-y^2/(2 sigma^2)).
    """
    Ct = np.clip(Ct, 0.0, 0.96)
    sq = np.sqrt(1.0 - Ct)
    beta = 0.5 * (1.0 + sq) / sq
    sigma_D = k_w * np.maximum(x_d, 0.1) + 0.25 * np.sqrt(beta)
    rad = 1.0 - Ct / (8.0 * sigma_D**2)
    C = 1.0 - np.sqrt(np.clip(rad, 0.0, 1.0))
    dU = C * np.exp(-y_d**2 / (2.0 * sigma_D**2))
    return np.where(x_d > 0.05, dU, 0.0)


def wake_velocities(xy, D, Ct, U_inf, wind_dir_deg=0.0, k_w=0.05):
    """Effective hub-height wind speed at each turbine of a farm.

    xy: (n,2) turbine positions [m]; D: rotor diameter(s); Ct: (n,) thrust
    coefficients; wind_dir_deg: direction the wind FLOWS TOWARD (x-axis at
    0).  Root-sum-square deficit superposition.
    """
    xy = np.asarray(xy, float)
    n = len(xy)
    D = np.broadcast_to(np.asarray(D, float), (n,))
    Ct = np.asarray(Ct, float)
    th = np.deg2rad(wind_dir_deg)
    R = np.array([[np.cos(th), np.sin(th)], [-np.sin(th), np.cos(th)]])
    xy_w = xy @ R.T          # downwind/crosswind frame
    U = np.full(n, float(U_inf))
    for i in range(n):       # receiving turbine
        ssq = 0.0
        for j in range(n):   # wake source
            if i == j:
                continue
            dx = (xy_w[i, 0] - xy_w[j, 0]) / D[j]
            dy = (xy_w[i, 1] - xy_w[j, 1]) / D[j]
            ssq += gaussian_deficit(dx, dy, Ct[j], k_w) ** 2
        U[i] = U_inf * (1.0 - np.sqrt(ssq))
    return U


def power_thrust_curve(model, speeds=None, ifowt=0):
    """Cp/Ct/power/thrust/pitch schedule vs wind speed (reference:
    raft_model.py:1674-1750 powerThrustCurve).

    Evaluates the BEM rotor at each operating point; returns a dict of
    arrays keyed like the FLORIS turbine yaml the reference writes.
    """
    from raft_tpu.models.rotor import bem_evaluate

    fowt = model.fowtList[ifowt]
    rot = fowt.rotors[0]
    if speeds is None:
        speeds = np.arange(3.0, 25.5, 1.0)
    speeds = np.asarray(speeds, float)
    rho = rot.rho
    A = np.pi * rot.R_rot**2
    P = np.zeros_like(speeds)
    T = np.zeros_like(speeds)
    pitch = np.zeros_like(speeds)
    omega = np.zeros_like(speeds)
    for i, U in enumerate(speeds):
        Uh = U * rot.speed_gain
        om = float(np.interp(Uh, rot.Uhub_ops, rot.Omega_rpm_ops))
        pi_deg = float(np.interp(Uh, rot.Uhub_ops, rot.pitch_deg_ops))
        # tilt seen by the BEM is -shaft_tilt (q[2] = -sin(shaft_tilt);
        # same convention calc_aero derives from the pose)
        loads = bem_evaluate(rot, Uh, om, pi_deg, tilt=-rot.shaft_tilt)
        P[i] = float(loads["P"])
        T[i] = float(loads["T"])
        pitch[i] = pi_deg
        omega[i] = om
    Cp = P / (0.5 * rho * A * speeds**3)
    Ct = np.clip(T / (0.5 * rho * A * speeds**2), 0.0, 2.0)
    return dict(wind_speed=speeds, power=P, thrust=T, Cp=Cp, Ct=Ct,
                pitch_deg=pitch, omega_rpm=omega, rotor_area=A)


def _curve_interp(U, curve, key, outside=0.0):
    """Interpolate a power/thrust-curve channel at speeds U, returning
    `outside` beyond the curve's speed range (below cut-in / above
    cut-out the turbine is parked: zero power and thrust, not the
    clamped endpoint value np.interp would give)."""
    U = np.asarray(U, float)
    xs = curve["wind_speed"]
    vals = np.interp(U, xs, curve[key])
    return np.where((U < xs[0]) | (U > xs[-1]), outside, vals)


def _farm_curves(model, curve=None):
    """One power/thrust curve per FOWT, computed once per distinct rotor
    object (heterogeneous farms of design variants get their own curves).
    `curve` may be a single curve dict (applied to all) or a list."""
    if isinstance(curve, dict):
        return [curve] * model.nFOWT
    if curve is not None:
        return list(curve)
    cache = {}
    out = []
    for i, f in enumerate(model.fowtList):
        key = id(f.rotors[0])
        if key not in cache:
            cache[key] = power_thrust_curve(model, ifowt=i)
        out.append(cache[key])
    return out


def find_wake_equilibrium(model, case, k_w=0.05, max_iter=100, tol=1e-4,
                          relax=0.5, curve=None):
    """Farm wake fixed point (reference: raft_model.py:1852-1994
    florisFindEquilibrium): wake model -> per-turbine wind speeds ->
    thrust coefficients -> wake model, with under-relaxation.

    `case['wind_speed']` may be a scalar free-stream speed or a
    per-turbine list (as produced by this function itself / accepted by
    Model._case_for_fowt) — a list is reduced to its maximum, the
    free-stream value unaffected by wakes.

    Returns dict(U (n,), Ct (n,), power (n,), case with per-turbine
    wind_speed list ready for Model.analyzeCases).
    """
    n = model.nFOWT
    ws = case.get("wind_speed", 10.0)
    U_inf = float(np.max(ws)) if np.ndim(ws) > 0 else float(ws)
    wh = np.atleast_1d(np.asarray(case.get("wind_heading", 0.0), float))
    # circular mean (arithmetic mean of e.g. [350, 10] deg is wrong)
    wind_dir = float(np.rad2deg(np.arctan2(
        np.mean(np.sin(np.deg2rad(wh))), np.mean(np.cos(np.deg2rad(wh))))))
    xy = np.array([[f.x_ref, f.y_ref] for f in model.fowtList])
    rots = [f.rotors[0] for f in model.fowtList]
    D = np.array([2.0 * r.R_rot for r in rots])

    curves = _farm_curves(model, curve)

    U = np.full(n, U_inf)
    Ct = np.array([float(_curve_interp(U[i], curves[i], "Ct"))
                   for i in range(n)])
    for it in range(max_iter):
        U_new = wake_velocities(xy, D, Ct, U_inf, wind_dir, k_w)
        if np.max(np.abs(U_new - U)) < tol:
            U = U_new
            break
        U = relax * U + (1.0 - relax) * U_new
        Ct = np.array([float(_curve_interp(U[i], curves[i], "Ct"))
                       for i in range(n)])
    power = np.array([float(_curve_interp(U[i], curves[i], "power"))
                      for i in range(n)])
    case_out = dict(case)
    case_out["wind_speed"] = list(U)
    return dict(U=U, Ct=Ct, power=power, case=case_out, iterations=it + 1)


def calc_aep(model, wind_rose, k_w=0.05, availability=1.0):
    """Wind-rose AEP [Wh] with wake losses (reference:
    raft_model.py:1996-2022 florisCalcAEP).

    wind_rose: iterable of (speed [m/s], direction [deg], probability);
    probabilities should sum to ~1.
    """
    curves = _farm_curves(model)
    hours = 8760.0
    aep = 0.0
    per_state = []
    for speed, wd, prob in wind_rose:
        eq = find_wake_equilibrium(
            model, dict(wind_speed=speed, wind_heading=wd),
            k_w=k_w, curve=curves)
        farm_p = float(np.sum(eq["power"]))
        per_state.append(dict(speed=speed, dir=wd, prob=prob,
                              farm_power=farm_p, U=eq["U"]))
        aep += prob * farm_p * hours
    return dict(AEP=aep * availability, states=per_state)
