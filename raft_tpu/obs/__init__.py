"""raft_tpu.obs — observability: tracing, metrics, manifests, ledgers.

Nine pillars (see docs/observability.md):

- :mod:`raft_tpu.obs.tracing` — nested wall-time spans with attributes,
  Chrome-trace/Perfetto JSON export, and the name -> (total, calls)
  aggregate behind ``utils.profiling.timing_report()``.
- :mod:`raft_tpu.obs.metrics` — process-wide counters/gauges/histograms
  (drag fixed-point iterations and residuals, dynamics condition
  numbers, JAX compile events) with JSON and Prometheus text exports.
- :mod:`raft_tpu.obs.manifest` — ``RunManifest``: one structured JSON
  record per ``analyzeCases`` / ``sweep_cases`` / ``bench.py`` run.
- :mod:`raft_tpu.obs.ledger` — content-addressed physics-result
  digests (RAO summaries, eigenfrequencies, mean offsets, solver
  iteration counts) diffable across runs: the regression sentinel's
  ground truth, driven by the ``tools/obsctl.py`` CLI.
- :mod:`raft_tpu.obs.device` — per-device memory stats, live-array
  accounting, jit cache hit/miss deltas, static HLO cost analysis.
- :mod:`raft_tpu.obs.transfers` — host-transfer accounting: counted
  sanctioned ``device_get`` exit points, per-phase budgets, and a
  transfer-guard wrapper that traps unsanctioned device→host pulls.
- :mod:`raft_tpu.obs.events` — the flight recorder: a crash-safe,
  append-only JSONL stream of span/case/probe/recovery/quarantine
  events flushed *as they happen*, replayable after a kill.
- :mod:`raft_tpu.obs.probes` — the sanctioned on-device instrumentation
  channel (``jax.debug.callback``) streaming solver health out of
  jitted code during execution, on its own counted budget.
- :mod:`raft_tpu.obs.trendstore` — persistent SQLite run history every
  finished manifest is appended to, with declarative SLO rules
  (``obsctl slo``) gating CI and the future serving loop.

File output is opt-in: call ``configure(out_dir=...)`` or set the
``RAFT_TPU_OBS_DIR`` environment variable, and every instrumented entry
point writes ``<kind>_<run_id>.manifest.json`` plus
``<kind>_<run_id>.trace.json`` (and, for ledger-emitting entry points,
``<kind>_<run_id>.ledger.json``) there — and, live, a
``status="running"`` manifest stub at ``begin`` (atomically replaced at
finish; a killed run stays discoverable), the flight recorder's
``<kind>_<run_id>.events.jsonl`` stream, and a ``trend.sqlite``
run-history append at finish.  ``configure(...,
max_runs=N)`` (or ``RAFT_TPU_OBS_MAX_RUNS``) bounds the directory: after
each write the oldest runs' artifact sets are pruned so at most N
runs remain (the trend store is deliberately exempt — it IS the long
history).  Without an output directory, spans/metrics still record
in-process (``Model.last_manifest``, ``timing_report()``,
``obs.snapshot()``) and nothing touches the filesystem.

This package never imports jax at module scope — bench.py must be able
to import it before deciding which backend to initialize.
"""
from __future__ import annotations

import os

from raft_tpu.obs.tracing import (                              # noqa: F401
    span, current_span, spans, aggregate, reset as reset_tracing,
    chrome_trace, export_chrome_trace, dropped_spans,
    TraceContext, TRACE_HEADER,
)
from raft_tpu.obs.metrics import (                              # noqa: F401
    REGISTRY, counter, gauge, histogram, snapshot, to_prometheus,
    install_jax_hooks, sample_jit_cache, record_build_info, ITER_BUCKETS,
    record_solve_dispatch, record_exec_cache_event, record_solve_health,
    record_devprof,
)
from raft_tpu.obs.manifest import (                             # noqa: F401
    SCHEMA, RunManifest, ProbeAttempt, capture_environment,
    validate_manifest, git_sha, collapse_probe_attempts,
)
from raft_tpu.obs.ledger import (                               # noqa: F401
    LEDGER_SCHEMA, ledger_from_model, ledger_from_sweep, write_ledger,
    load_ledger, validate_ledger, diff_ledgers, format_diff,
    compare_manifests,
)
from raft_tpu.obs import device  # noqa: F401
from raft_tpu.obs import devprof  # noqa: F401
from raft_tpu.obs import transfers  # noqa: F401
from raft_tpu.obs import events  # noqa: F401
from raft_tpu.obs import probes  # noqa: F401
from raft_tpu.obs import trendstore  # noqa: F401
from raft_tpu.obs import tracing as _tracing_mod

# stream span open/close into the flight recorder whenever one is
# active (a cheap no-op check per span otherwise)
_tracing_mod.set_sink(events._tracing_sink)

_OUT_DIR: str | None = None
_MAX_RUNS: int | None = None


def configure(out_dir: str | None, max_runs: int | None = None):
    """Set (or clear, with None) the observability output directory —
    overrides the ``RAFT_TPU_OBS_DIR`` environment variable.

    ``max_runs`` bounds artifact growth: after every ``finish_run``
    write, only the newest ``max_runs`` runs' ``*.manifest.json`` /
    ``*.trace.json`` / ``*.ledger.json`` triples are kept (falls back
    to the ``RAFT_TPU_OBS_MAX_RUNS`` env var; None/0 = unbounded).
    """
    global _OUT_DIR, _MAX_RUNS
    _OUT_DIR = out_dir
    _MAX_RUNS = int(max_runs) if max_runs else None


def out_dir() -> str | None:
    """Active output directory, or None when file output is disabled."""
    return _OUT_DIR or os.environ.get("RAFT_TPU_OBS_DIR") or None


def max_runs() -> int | None:
    """Active retention bound (runs kept on disk), or None (unbounded)."""
    if _MAX_RUNS:
        return _MAX_RUNS
    try:
        n = int(os.environ.get("RAFT_TPU_OBS_MAX_RUNS", "0"))
    except ValueError:
        return None
    return n or None


#: artifact suffixes that make up one run's on-disk record (the event
#: file may additionally carry rotated ``.events.jsonl.N`` siblings —
#: prune_runs removes those by prefix)
_RUN_SUFFIXES = (".manifest.json", ".trace.json", ".ledger.json",
                 ".events.jsonl")


def _is_running_stub(path: str) -> bool:
    """True when ``path`` is a ``status="running"`` manifest — an
    in-flight (or killed) run whose forensic record retention must
    never delete out from under it."""
    import json as _json
    try:
        with open(path) as f:
            return _json.load(f).get("status") == "running"
    except (OSError, ValueError):
        return False


def prune_runs(directory: str, keep: int) -> list[str]:
    """Delete the oldest runs' artifact sets from ``directory`` so at
    most ``keep`` runs (identified by their ``*.manifest.json``) remain.
    ``status="running"`` stubs are exempt: an active run writes its
    stub at begin (the oldest mtime in the directory by construction),
    and a killed run's stub+events pair IS the crash-safety record.
    Returns the removed paths."""
    try:
        manifests = [f for f in os.listdir(directory)
                     if f.endswith(".manifest.json")
                     and not _is_running_stub(os.path.join(directory, f))]
    except OSError:
        return []
    if keep <= 0 or len(manifests) <= keep:
        return []
    def _mtime(f):
        try:
            return os.path.getmtime(os.path.join(directory, f))
        except OSError:
            return 0.0
    manifests.sort(key=_mtime)
    removed = []
    for f in manifests[:len(manifests) - keep]:
        stem = f[:-len(".manifest.json")]
        victims = [stem + suffix for suffix in _RUN_SUFFIXES]
        # rotated flight-recorder generations (stem.events.jsonl.N)
        try:
            victims += [n for n in os.listdir(directory)
                        if n.startswith(stem + ".events.jsonl.")]
        except OSError:                              # pragma: no cover
            pass
        for name in victims:
            path = os.path.join(directory, name)
            try:
                os.remove(path)
                removed.append(path)
            except OSError:
                pass
    return removed


def begin_run(manifest: RunManifest) -> dict:
    """Crash-safety + live-telemetry hook ``RunManifest.begin`` fires.

    When an output directory is configured this (a) atomically writes a
    ``status="running"`` manifest stub — so a killed run leaves a
    discoverable record that ``finish_run`` later replaces — and (b)
    starts the flight recorder on ``<kind>_<run_id>.events.jsonl``,
    registering the event file in ``manifest.extra["events"]``.
    Returns ``{"manifest": path|None, "events": path|None}``; never
    raises (telemetry must not take down the run it documents)."""
    paths = {"manifest": None, "events": None}
    try:
        d = out_dir()
        if not d:
            return paths
        stem = f"{manifest.kind}_{manifest.run_id}"
        paths["manifest"] = manifest.write(
            os.path.join(d, stem + ".manifest.json"))
        if events.enabled():
            rec = events.start(os.path.join(d, stem + ".events.jsonl"),
                               run_id=manifest.run_id,
                               kind=manifest.kind)
            if rec is not None:
                manifest.extra["events"] = {"schema": events.SCHEMA,
                                            "path": rec.path}
                paths["events"] = rec.path
    except Exception:  # pragma: no cover  # raftlint: disable=RTL004
        pass
    return paths


def finish_run(manifest: RunManifest, status: str = "ok",
               write_trace: bool = True, ledger: dict = None) -> dict:
    """Finish ``manifest`` and, when an output directory is configured,
    write the manifest JSON (atomically replacing the ``running`` stub
    ``begin_run`` left, plus the Chrome trace and, when given, the
    result ledger), close the run's flight recorder, append the run to
    the trend store, and apply the ``max_runs`` retention bound.
    Returns ``{"manifest": path|None, "trace": path|None,
    "ledger": path|None, "events": path|None, "trend": path|None}``."""
    manifest.finish(status)
    paths = {"manifest": None, "trace": None, "ledger": None,
             "events": None, "trend": None}
    paths["events"] = events.finish(manifest.run_id, status=status)
    d = out_dir()
    if d:
        stem = f"{manifest.kind}_{manifest.run_id}"
        paths["manifest"] = manifest.write(
            os.path.join(d, stem + ".manifest.json"))
        if write_trace:
            paths["trace"] = export_chrome_trace(
                os.path.join(d, stem + ".trace.json"))
        if ledger is not None:
            paths["ledger"] = write_ledger(
                ledger, os.path.join(d, stem + ".ledger.json"))
    paths["trend"] = trendstore.append_manifest(manifest.to_dict())
    if d:
        keep = max_runs()
        if keep:
            prune_runs(d, keep)
    return paths


def reset_all():
    """Reset every in-process observability pillar in one call: span
    buffer + aggregate, metrics registry, jit-cache delta baselines,
    host-transfer accounting, AND the configured output
    directory/retention.  Built for test
    isolation (the autouse conftest fixture); a long-running service
    that calls it between logical runs must call ``configure(...)``
    again afterwards or artifact output silently stops."""
    reset_tracing()
    REGISTRY.reset()
    device.reset_jit_cache_baseline()
    transfers.reset()
    events.stop_all()
    configure(None)
