"""raft_tpu.obs — observability: span tracing, metrics, run manifests.

Three pillars (see docs/observability.md):

- :mod:`raft_tpu.obs.tracing` — nested wall-time spans with attributes,
  Chrome-trace/Perfetto JSON export, and the name -> (total, calls)
  aggregate behind ``utils.profiling.timing_report()``.
- :mod:`raft_tpu.obs.metrics` — process-wide counters/gauges/histograms
  (drag fixed-point iterations and residuals, dynamics condition
  numbers, JAX compile events) with JSON and Prometheus text exports.
- :mod:`raft_tpu.obs.manifest` — ``RunManifest``: one structured JSON
  record per ``analyzeCases`` / ``sweep_cases`` / ``bench.py`` run.

File output is opt-in: call ``configure(out_dir=...)`` or set the
``RAFT_TPU_OBS_DIR`` environment variable, and every instrumented entry
point writes ``<kind>_<run_id>.manifest.json`` plus
``<kind>_<run_id>.trace.json`` there.  Without it, spans/metrics still
record in-process (``Model.last_manifest``, ``timing_report()``,
``obs.snapshot()``) and nothing touches the filesystem.

This package never imports jax at module scope — bench.py must be able
to import it before deciding which backend to initialize.
"""
from __future__ import annotations

import os

from raft_tpu.obs.tracing import (                              # noqa: F401
    span, current_span, spans, aggregate, reset as reset_tracing,
    chrome_trace, export_chrome_trace, dropped_spans,
)
from raft_tpu.obs.metrics import (                              # noqa: F401
    REGISTRY, counter, gauge, histogram, snapshot, to_prometheus,
    install_jax_hooks, sample_jit_cache, ITER_BUCKETS,
)
from raft_tpu.obs.manifest import (                             # noqa: F401
    SCHEMA, RunManifest, ProbeAttempt, capture_environment,
    validate_manifest, git_sha,
)

_OUT_DIR: str | None = None


def configure(out_dir: str | None):
    """Set (or clear, with None) the observability output directory —
    overrides the ``RAFT_TPU_OBS_DIR`` environment variable."""
    global _OUT_DIR
    _OUT_DIR = out_dir


def out_dir() -> str | None:
    """Active output directory, or None when file output is disabled."""
    return _OUT_DIR or os.environ.get("RAFT_TPU_OBS_DIR") or None


def finish_run(manifest: RunManifest, status: str = "ok",
               write_trace: bool = True) -> dict:
    """Finish ``manifest`` and, when an output directory is configured,
    write the manifest JSON (and the Chrome trace).  Returns
    ``{"manifest": path|None, "trace": path|None}``."""
    manifest.finish(status)
    paths = {"manifest": None, "trace": None}
    d = out_dir()
    if d:
        stem = f"{manifest.kind}_{manifest.run_id}"
        paths["manifest"] = manifest.write(
            os.path.join(d, stem + ".manifest.json"))
        if write_trace:
            paths["trace"] = export_chrome_trace(
                os.path.join(d, stem + ".trace.json"))
    return paths
