"""Device & compiler telemetry: what the hardware and XLA actually did.

Four probes, all cheap and all optional (every JAX API touched here is
guarded — a missing API degrades to an absent field, never an error):

- :func:`device_memory` — per-device ``memory_stats()`` (bytes in use,
  peak bytes, limit; TPU/GPU backends only — CPU devices report none).
- :func:`live_arrays_summary` — ``jax.live_arrays()`` count and total
  bytes: the host-visible picture of what is pinned on devices.
- :func:`jit_cache_delta` — jit cache hit/miss counters as a DELTA
  since the previous sample, so a recompile storm inside one run is a
  nonzero ``misses`` where steady state is 0 (the absolute counters in
  ``metrics.sample_jit_cache`` are process-cumulative).
- :func:`cost_analysis` — static HLO cost analysis of a jitted
  function (FLOPs / bytes-accessed estimates via
  ``Lowered.cost_analysis()``; no XLA compile is triggered).

:func:`collect` runs the first three, folds everything into Prometheus
gauges (``raft_device_memory_bytes``, ``raft_live_arrays``,
``raft_jit_cache_delta``) and returns one JSON-able dict that the
instrumented entry points attach to ``RunManifest.extra
["device_telemetry"]``.

This module never imports jax at module scope (same contract as the
rest of ``raft_tpu.obs``).
"""
from __future__ import annotations

import threading

_LOCK = threading.Lock()
_LAST_CACHE: dict = {}     # previous jit cache sample, for deltas


def _gauge(name, help):
    from raft_tpu.obs import metrics as _metrics
    return _metrics.gauge(name, help)


def device_memory() -> list[dict]:
    """Per-local-device memory stats: ``[{device, platform, stats}]``
    where ``stats`` is the backend's ``memory_stats()`` dict or None
    (CPU).  Byte-valued stats are exported as
    ``raft_device_memory_bytes{device,stat}`` gauges."""
    try:
        import jax
        devices = jax.local_devices()
    except Exception:
        return []
    out = []
    g = _gauge("raft_device_memory_bytes",
               "per-device allocator stats (bytes_in_use, "
               "peak_bytes_in_use, bytes_limit) from memory_stats()")
    for d in devices:
        try:
            stats = d.memory_stats()
        except Exception:
            stats = None
        rec = {"device": str(d), "platform": getattr(d, "platform", None),
               "stats": dict(stats) if stats else None}
        if stats:
            for k in ("bytes_in_use", "peak_bytes_in_use", "bytes_limit",
                      "largest_alloc_size"):
                if k in stats:
                    g.set(float(stats[k]), device=str(d), stat=k)
        out.append(rec)
    return out


def live_arrays_summary() -> dict | None:
    """{count, total_bytes} over ``jax.live_arrays()`` — what Python
    still holds on devices; a leak across cases shows up as growth."""
    try:
        import jax
        arrs = jax.live_arrays()
    except Exception:
        return None
    total = 0
    for a in arrs:
        try:
            total += int(a.nbytes)
        except Exception:
            pass
    summary = {"count": len(arrs), "total_bytes": total}
    _gauge("raft_live_arrays",
           "count of live jax arrays on devices").set(len(arrs))
    _gauge("raft_live_arrays_bytes",
           "total bytes of live jax arrays on devices").set(total)
    return summary


def jit_cache_delta(scope: str = "run") -> dict | None:
    """Jit cache hit/miss counts since the previous sample for
    ``scope`` (None when this JAX build exposes no cache-info hook).
    A steady-state run has ``misses == 0``; nonzero misses between two
    samples is a retrace/recompile storm made visible."""
    from raft_tpu.obs import metrics as _metrics

    stats = _metrics.sample_jit_cache()
    if stats is None:
        return None
    with _LOCK:
        prev = _LAST_CACHE.get(scope)
        _LAST_CACHE[scope] = dict(stats)
    if prev is None:
        delta = {"hits": None, "misses": None, "first_sample": True,
                 **{f"total_{k}": v for k, v in stats.items()}}
        return delta
    delta = {"hits": stats["hits"] - prev["hits"],
             "misses": stats["misses"] - prev["misses"],
             **{f"total_{k}": v for k, v in stats.items()}}
    g = _gauge("raft_jit_cache_delta",
               "jit cache hit/miss delta since the previous sample "
               "(misses > 0 at steady state = recompile storm)")
    g.set(delta["hits"], kind="hits", scope=scope)
    g.set(delta["misses"], kind="misses", scope=scope)
    return delta


def reset_jit_cache_baseline():
    """Forget previous jit-cache samples (test isolation)."""
    with _LOCK:
        _LAST_CACHE.clear()


def cost_analysis(target, *args, kernel: str = "kernel",
                  **kwargs) -> dict | None:
    """Static HLO cost analysis: {flops, bytes_accessed, ...} estimates
    via ``Lowered.cost_analysis()`` — a trace, not an XLA compile.

    ``target`` is either a jitted function (lowered here at ``*args``)
    or an already-lowered ``jax.stages.Lowered`` (args ignored).
    Exported as ``raft_hlo_flops{kernel}`` /
    ``raft_hlo_bytes_accessed{kernel}`` gauges.  None when the API (or
    the lowering) is unavailable."""
    try:
        lowered = (target if hasattr(target, "cost_analysis")
                   else target.lower(*args, **kwargs))
        costs = lowered.cost_analysis()
        if isinstance(costs, (list, tuple)):   # per-partition list
            costs = costs[0] if costs else None
    except Exception:
        return None
    if not isinstance(costs, dict):
        return None
    out = {"kernel": kernel}
    for k in ("flops", "bytes accessed", "transcendentals",
              "optimal_seconds"):
        if k in costs:
            out[k.replace(" ", "_")] = float(costs[k])
    if "flops" in out:
        _gauge("raft_hlo_flops",
               "static HLO cost analysis: estimated FLOPs per call"
               ).set(out["flops"], kernel=kernel)
    if "bytes_accessed" in out:
        _gauge("raft_hlo_bytes_accessed",
               "static HLO cost analysis: estimated bytes accessed "
               "per call").set(out["bytes_accessed"], kernel=kernel)
    return out


def collect(manifest=None, scope: str = "run") -> dict:
    """One-call telemetry sample: device memory + live arrays + jit
    cache delta, folded into the metrics registry and (when given)
    ``manifest.extra["device_telemetry"]``."""
    telemetry = {
        "devices": device_memory(),
        "live_arrays": live_arrays_summary(),
        "jit_cache": jit_cache_delta(scope=scope),
    }
    if manifest is not None:
        manifest.extra["device_telemetry"] = telemetry
    return telemetry
