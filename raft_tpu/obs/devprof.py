"""Program-level device profiling: what each compiled program costs.

PR 16 made requests observable; this module makes the *programs* they
run observable.  Every AOT compile site (the exec-cache misses in
``parallel/sweep.py`` and ``parallel/optimize.py``, plus ``bench.py``)
wraps its lower→compile step in :func:`start` / :meth:`Prof.finish`
and gets back one JSON-able facts dict per kernel:

- ``compile_s`` — wall seconds spent inside XLA compilation,
- ``flops`` / ``bytes_accessed`` / ``optimal_seconds`` — static HLO
  cost analysis via :func:`raft_tpu.obs.device.cost_analysis`,
- ``arithmetic_intensity`` — flops / bytes_accessed (roofline x-axis),
- ``argument_bytes`` / ``output_bytes`` / ``temp_bytes`` /
  ``code_bytes`` — the compiled program's ``memory_analysis()``,
- ``peak_bytes_before`` / ``peak_bytes_after`` / ``peak_bytes_delta``
  — device allocator watermark movement across the compile (None on
  CPU, whose allocator reports no stats).

The facts ride three sinks: the run manifest
(``extra["devprof"][kernel]``), the exec-cache meta sidecar (so warm
hits recover the original compile's facts without recompiling), and —
via :func:`raft_tpu.obs.metrics.record_devprof` — Prometheus gauges
and the trend store (``devprof_*`` facts, consumed by ``obsctl
regress``).

Every probe is guarded: a JAX build without ``memory_analysis`` or
``cost_analysis`` degrades to absent fields, never an error.  This
module never imports jax at module scope (the ``raft_tpu.obs``
contract).
"""
from __future__ import annotations

import time


def peak_bytes() -> int | None:
    """Sum of per-device ``peak_bytes_in_use`` allocator watermarks, or
    None when no local device reports memory stats (CPU)."""
    try:
        import jax
        devices = jax.local_devices()
    except Exception:
        return None
    total, seen = 0, False
    for d in devices:
        try:
            stats = d.memory_stats()
        except Exception:
            stats = None
        if stats and "peak_bytes_in_use" in stats:
            total += int(stats["peak_bytes_in_use"])
            seen = True
    return total if seen else None


def memory_analysis(compiled) -> dict | None:
    """Buffer sizes of a compiled program: {argument_bytes,
    output_bytes, temp_bytes, code_bytes} via ``memory_analysis()``
    (None when this JAX build or backend exposes none)."""
    try:
        ma = compiled.memory_analysis()
    except Exception:
        return None
    if ma is None:
        return None
    out = {}
    for attr, key in (("argument_size_in_bytes", "argument_bytes"),
                      ("output_size_in_bytes", "output_bytes"),
                      ("temp_size_in_bytes", "temp_bytes"),
                      ("generated_code_size_in_bytes", "code_bytes")):
        val = getattr(ma, attr, None)
        if val is not None:
            try:
                out[key] = int(val)
            except (TypeError, ValueError):
                pass
    return out or None


class Prof:
    """One lower→compile measurement; create via :func:`start`."""

    def __init__(self, kernel: str):
        self.kernel = kernel
        self._t0 = time.perf_counter()
        self._peak0 = peak_bytes()

    def finish(self, lowered=None, compiled=None) -> dict:
        """Close the measurement and return the facts dict.  ``lowered``
        feeds static cost analysis; ``compiled`` feeds buffer sizes."""
        compile_s = time.perf_counter() - self._t0
        facts: dict = {"kernel": self.kernel,
                       "compile_s": round(compile_s, 6)}
        if lowered is not None:
            from raft_tpu.obs import device as _device
            costs = _device.cost_analysis(lowered, kernel=self.kernel)
            if costs:
                for k in ("flops", "bytes_accessed", "transcendentals",
                          "optimal_seconds"):
                    if k in costs:
                        facts[k] = costs[k]
                if facts.get("flops") and facts.get("bytes_accessed"):
                    facts["arithmetic_intensity"] = (
                        facts["flops"] / facts["bytes_accessed"])
        if compiled is not None:
            ma = memory_analysis(compiled)
            if ma:
                facts.update(ma)
        peak1 = peak_bytes()
        if self._peak0 is not None:
            facts["peak_bytes_before"] = self._peak0
        if peak1 is not None:
            facts["peak_bytes_after"] = peak1
        if self._peak0 is not None and peak1 is not None:
            facts["peak_bytes_delta"] = peak1 - self._peak0
        from raft_tpu.obs import metrics as _metrics
        _metrics.record_devprof(facts)
        return facts


def start(kernel: str) -> Prof:
    """Begin profiling one compile; call ``.finish(...)`` after it."""
    return Prof(kernel)


def tree_bytes(tree) -> int:
    """Total ``nbytes`` over the array leaves of a pytree (fallback
    argument/output sizing when ``memory_analysis`` is unavailable)."""
    try:
        import jax
        leaves = jax.tree_util.tree_leaves(tree)
    except Exception:
        return 0
    total = 0
    for leaf in leaves:
        try:
            total += int(leaf.nbytes)
        except (AttributeError, TypeError):
            pass
    return total


def attach(manifest, facts: dict | None):
    """Fold one kernel's facts into ``manifest.extra["devprof"]``
    (keyed by kernel name; None facts are a no-op)."""
    if manifest is None or not facts:
        return
    kernel = facts.get("kernel", "kernel")
    manifest.extra.setdefault("devprof", {})[kernel] = dict(facts)
