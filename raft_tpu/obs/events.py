"""Flight recorder: a crash-safe, append-only JSONL event stream.

Everything else in ``raft_tpu.obs`` is post-hoc — manifests, ledgers,
and span traces materialize when a run *finishes*, so a multi-hour sweep
is a black box while it runs and a killed process leaves no forensic
record.  The flight recorder closes that gap: every span open/close,
probe sample, recovery-ladder transition, quarantine decision,
exec-cache event, and per-case completion is appended to a run-scoped
JSONL file *as it happens* and flushed line-by-line, so the file is
valid (modulo at most one torn final line, which :func:`read` ignores)
at every instant — including the instant a SIGKILL lands.

Schema ``raft_tpu.events/v1``: one JSON object per line, every line
carrying ``seq`` (monotonic per file), ``t`` (unix epoch seconds) and
``type``.  The first line is a ``begin`` record with the run identity
(``run_id``, ``kind``, ``pid``, ``hostname``, ``schema``); a clean
shutdown appends an ``end`` record — its *absence* is how a reader
detects a killed run.  Event types emitted by the instrumented stack:

========== =============================================================
type        emitted by
========== =============================================================
begin/end   recorder lifecycle (``start`` / ``finish``)
span_open   ``obs.span`` entry (name, ts, depth, parent, attrs)
span_close  ``obs.span`` exit — the full span event, replayable into
            the identical Chrome trace via :func:`to_chrome_trace`
case_start  ``Model.analyzeCases`` per-case loop
case_end    ditto (``ok``/``resumed`` flags, wall seconds)
quarantine  per-case / per-lane quarantine decisions
recovery    every degradation-ladder transition (``recovery.py``)
probe       on-device probe samples (``obs.probes``)
probe_attempt  bench TPU-probe attempts (``RunManifest``)
exec_cache  executable-cache hit/miss/store/error events
========== =============================================================

File output follows the rest of the obs layer: a recorder starts only
when an output directory is configured (``obs.begin_run`` registers the
event file in the run manifest under ``extra["events"]``), and
``RAFT_TPU_EVENTS=0`` disables it outright.  Files rotate by size
(``RAFT_TPU_EVENTS_MAX_BYTES``, default 16 MiB; the newest rotated
generations are kept as ``<file>.1``, ``<file>.2``, ... up to
``RAFT_TPU_EVENTS_KEEP``) — each rotation opens with a fresh ``begin``
record carrying an incremented ``part``.

Like the rest of ``raft_tpu.obs``, this module never imports jax, and
no recorder failure may ever take down the solve it is watching: every
emit path degrades to a silent no-op on I/O trouble.

The crash-safe file discipline itself (flush-per-line append, torn-tail
skip on read, size rotation) lives in :mod:`raft_tpu.obs.journalio` —
one tested codec shared with the serving layer's write-ahead request
journal (:mod:`raft_tpu.serve.journal`); this module owns only the
event *schema* on top of it.
"""
from __future__ import annotations

import os
import socket
import threading
import time

from raft_tpu.obs import journalio

SCHEMA = "raft_tpu.events/v1"

_LOCK = threading.Lock()
#: stack of active recorders (innermost last) — nested runs each keep
#: their own file; `emit` routes to the innermost
_STACK: list["FlightRecorder"] = []


def enabled() -> bool:
    """Flight recording active (when an output path is available)?
    ``RAFT_TPU_EVENTS=0`` disables it."""
    return os.environ.get("RAFT_TPU_EVENTS", "1").strip() != "0"


def max_bytes() -> int:
    try:
        return int(os.environ.get("RAFT_TPU_EVENTS_MAX_BYTES",
                                  str(16 << 20)))
    except ValueError:
        return 16 << 20


def keep_rotations() -> int:
    try:
        return max(0, int(os.environ.get("RAFT_TPU_EVENTS_KEEP", "2")))
    except ValueError:
        return 2


def _jsonable(v):
    """Best-effort JSON-safe conversion (numpy scalars -> numbers,
    small arrays -> lists, everything else -> str)."""
    if v is None or isinstance(v, (bool, int, float, str)):
        return v
    if isinstance(v, dict):
        return {str(k): _jsonable(x) for k, x in v.items()}
    if isinstance(v, (list, tuple)):
        return [_jsonable(x) for x in v]
    try:
        import numpy as np
        if isinstance(v, np.generic):
            return v.item()
        if isinstance(v, np.ndarray) and v.size <= 64:
            return v.tolist()
    except ImportError:                          # pragma: no cover
        pass
    try:
        return float(v)
    except (TypeError, ValueError):
        return str(v)


class FlightRecorder:
    """One run's append-only event file.

    Every :meth:`emit` serializes one line, writes it and flushes the
    stream, so the OS has the bytes even if the process is killed the
    next instant.  All methods are thread-safe and exception-silent —
    the recorder is telemetry, never a failure mode.
    """

    def __init__(self, path: str, run_id: str, kind: str):
        self.path = str(path)
        self.run_id = str(run_id)
        self.kind = str(kind)
        self.seq = 0
        self._lock = threading.Lock()
        # the shared crash-safe codec owns open/flush/rotate; this
        # recorder owns the schema (seq numbering, begin/end records)
        self._writer = journalio.JsonlWriter(
            self.path, max_bytes=max_bytes(), keep=keep_rotations(),
            header=self._begin_record)

    # -- file lifecycle ----------------------------------------------

    @property
    def part(self) -> int:
        return self._writer.part if self._writer is not None else 0

    def _begin_record(self, part: int) -> dict:
        rec = {"seq": self.seq, "t": round(time.time(), 6),
               "type": "begin", "schema": SCHEMA, "run_id": self.run_id,
               "kind": self.kind, "pid": os.getpid(),
               "hostname": socket.gethostname(), "part": int(part)}
        self.seq += 1
        return rec

    def close(self, status: str = "ok"):
        """Append the ``end`` record and close the file (idempotent)."""
        with self._lock:
            if self._writer is None or self._writer.closed:
                return
            try:
                self._emit_locked("end", status=str(status))
            except OSError:                      # pragma: no cover
                pass
            self._writer.close()

    @property
    def closed(self) -> bool:
        return self._writer is None or self._writer.closed

    # -- emission ----------------------------------------------------

    def _emit_locked(self, type_: str, **fields):
        rec = {"seq": self.seq, "t": round(time.time(), 6),
               "type": str(type_)}
        for k, v in fields.items():
            rec[k] = _jsonable(v)
        # assign this record's seq BEFORE the write: a size rotation
        # inside write() opens a fresh part whose begin header must
        # number itself after this record
        self.seq += 1
        self._writer.write(rec)

    def emit(self, type_: str, **fields):
        try:
            with self._lock:
                if self.closed:
                    return
                # the knobs stay env-dynamic (tests shrink them mid-run)
                self._writer.max_bytes = max_bytes()
                self._writer.keep = keep_rotations()
                self._emit_locked(type_, **fields)
        # a full disk / closed stream must never take down the run the
        # recorder is documenting (obs contract)
        except Exception:  # pragma: no cover  # raftlint: disable=RTL004
            pass


# ---------------------------------------------------------------------------
# module-level recorder stack (what the instrumented stack talks to)
# ---------------------------------------------------------------------------

def start(path: str, run_id: str, kind: str) -> FlightRecorder | None:
    """Open a recorder and make it the active event sink.  Returns the
    recorder, or None when recording is disabled or the open failed."""
    if not enabled():
        return None
    try:
        rec = FlightRecorder(path, run_id=run_id, kind=kind)
    except OSError:
        return None
    with _LOCK:
        _STACK.append(rec)
    return rec


def active() -> FlightRecorder | None:
    """The innermost active recorder, or None."""
    with _LOCK:
        return _STACK[-1] if _STACK else None


def emit(type_: str, **fields):
    """Append one event to the innermost active recorder (no-op when
    none is active) — the one call every instrumented site makes."""
    rec = active()
    if rec is not None:
        rec.emit(type_, **fields)


def finish(run_id: str, status: str = "ok") -> str | None:
    """Close and deactivate the recorder owned by ``run_id`` (no-op
    when that run never started one).  Returns the closed file's path,
    or None."""
    with _LOCK:
        rec = next((r for r in _STACK if r.run_id == str(run_id)), None)
        if rec is not None:
            _STACK.remove(rec)
    if rec is None:
        return None
    rec.close(status=status)
    return rec.path


def stop_all():
    """Close every active recorder without an ``end`` status ceremony
    (test isolation / ``obs.reset_all``)."""
    with _LOCK:
        recs = list(_STACK)
        del _STACK[:]
    for rec in recs:
        rec.close(status="aborted")


def _tracing_sink(kind: str, event: dict):
    """Span open/close hook installed on ``obs.tracing`` — forwards
    every span event into the active recorder."""
    if active() is not None:
        emit(kind, **event)


# ---------------------------------------------------------------------------
# replay: the read half of the recorder
# ---------------------------------------------------------------------------

def read(path: str) -> list[dict]:
    """Parse one event file, tolerating the torn final line a hard kill
    can leave (any unparseable line is skipped, never fatal) — the
    shared :func:`raft_tpu.obs.journalio.read` codec."""
    return journalio.read(path)


def read_incremental(path: str, offset: int = 0) -> tuple[list[dict], int]:
    """Parse only the COMPLETE lines at byte ``offset`` and beyond;
    returns ``(events, new_offset)``.  A torn final line (mid-write or
    mid-kill) is left unconsumed for the next call — the follow loop's
    building block (``obsctl tail -f``) that avoids re-parsing a
    multi-MiB stream twice a second.  A ``new_offset`` smaller than the
    file is normal (torn tail); a file smaller than ``offset`` means
    the recorder rotated — re-enter at 0."""
    return journalio.read_incremental(path, offset)


def validate(events: list[dict]) -> list[str]:
    """Structural check of a parsed event stream; [] == valid.  A
    stream without an ``end`` record is still *valid* — that is the
    killed-run signature ``progress`` reports — but seq gaps,
    a missing/alien header, or untyped records are problems."""
    problems = []
    if not events:
        return ["no events"]
    head = events[0]
    if head.get("type") != "begin":
        problems.append("first event is not 'begin'")
    elif head.get("schema") != SCHEMA:
        problems.append(f"schema is {head.get('schema')!r}, "
                        f"expected {SCHEMA}")
    prev_seq = None
    for i, e in enumerate(events):
        if "type" not in e or "seq" not in e or "t" not in e:
            problems.append(f"events[{i}] missing seq/t/type")
            continue
        if prev_seq is not None and e["seq"] != prev_seq + 1:
            problems.append(
                f"events[{i}] seq {e['seq']} != {prev_seq + 1} "
                "(gap or reorder)")
        prev_seq = e["seq"]
    return problems


def to_chrome_trace(events: list[dict]) -> dict:
    """Replay the ``span_close`` records into the same Chrome Trace
    Event Format object ``tracing.chrome_trace()`` would have produced
    in-process (pid taken from the ``begin`` header) — the span tree of
    a killed run, reconstructed from disk."""
    pid = os.getpid()
    for e in events:
        if e.get("type") == "begin" and e.get("pid") is not None:
            pid = int(e["pid"])
            break
    out = []
    for e in events:
        if e.get("type") != "span_close":
            continue
        out.append({
            "name": e.get("name"),
            "cat": "raft_tpu",
            "ph": "X",
            "ts": float(e.get("ts", 0.0)) * 1e6,
            "dur": float(e.get("dur", 0.0)) * 1e6,
            "pid": pid,
            "tid": e.get("tid"),
            "args": e.get("attrs") or {},
        })
    return {"traceEvents": out, "displayTimeUnit": "ms"}


def progress(events: list[dict], state: dict = None) -> dict:
    """Per-case progress reconstructed from the stream — what
    ``obsctl tail`` renders and the ``serve`` endpoint exports.

    Returns ``{run_id, kind, status, n_cases, done, failed, resumed,
    in_flight, avg_case_s, eta_s, probes, recoveries, quarantined,
    last_t}``;
    ``status`` is ``running`` until an ``end`` record appears (a killed
    run therefore reads ``running`` forever — exactly the forensic
    signal the manifest stub carries too).

    Incremental folding: pass a previous call's return value as
    ``state`` and only the NEWLY appended events — the follow loop's
    O(new) path (accumulators ride under the private ``"_"`` key;
    strip it before serializing the dict for users)."""
    if state is not None:
        info = state
        acc = info["_"]
    else:
        info = {"run_id": None, "kind": None, "status": "running",
                "n_cases": None, "done": 0, "failed": 0, "resumed": 0,
                "in_flight": None, "avg_case_s": None, "eta_s": None,
                "probes": 0, "recoveries": 0, "quarantined": 0,
                "last_t": None}
        acc = info["_"] = {"durations": [], "started": {}}
    durations = acc["durations"]
    started = acc["started"]
    for e in events:
        t = e.get("type")
        info["last_t"] = e.get("t", info["last_t"])
        if t == "begin":
            info["run_id"] = e.get("run_id")
            info["kind"] = e.get("kind")
        elif t == "end":
            info["status"] = e.get("status", "ok")
            info["in_flight"] = None
        elif t == "case_start":
            started[e.get("case")] = e.get("t")
            info["in_flight"] = e.get("case")
            if e.get("n_cases") is not None:
                info["n_cases"] = int(e["n_cases"])
        elif t == "case_end":
            case = e.get("case")
            info["done"] += 1
            if e.get("n_cases") is not None:
                info["n_cases"] = int(e["n_cases"])
            if e.get("resumed"):
                # journal restores are ~free — folding their s=0.0 into
                # the average would wreck the ETA of the solved cases
                info["resumed"] += 1
            else:
                if not e.get("ok", True):
                    info["failed"] += 1
                if isinstance(e.get("s"), (int, float)):
                    durations.append(float(e["s"]))
                elif case in started and e.get("t") is not None:
                    durations.append(float(e["t"]) - float(started[case]))
            if info["in_flight"] == case:
                info["in_flight"] = None
        elif t == "quarantine":
            info["quarantined"] += 1
        elif t == "probe":
            info["probes"] += 1
        elif t == "recovery":
            info["recoveries"] += 1
    info["eta_s"] = None                  # recomputed on every fold
    if durations:
        info["avg_case_s"] = sum(durations) / len(durations)
        if info["n_cases"]:
            remaining = max(0, info["n_cases"] - info["done"])
            if info["status"] == "running" and remaining:
                info["eta_s"] = info["avg_case_s"] * remaining
    return info


def public_progress(info: dict) -> dict:
    """``progress()`` output without the private ``"_"`` accumulators —
    what goes into JSON responses and rendered summaries."""
    return {k: v for k, v in info.items() if k != "_"}
