"""Crash-safe JSONL codec shared by every append-only journal.

Two subsystems keep forensic/durability records as line-flushed JSONL:
the flight recorder (:mod:`raft_tpu.obs.events`) and the serving
layer's write-ahead request journal (:mod:`raft_tpu.serve.journal`).
Both need the same discipline, extracted here once:

- **flush-per-line writes** — every record is serialized, written, and
  flushed in one step, so the OS has the bytes even if the process is
  SIGKILLed the next instant; a hard kill leaves at most one torn
  final line;
- **torn-tail-tolerant reads** — :func:`read` skips any unparseable
  line (the torn tail of a killed writer, or mid-file bit rot) instead
  of raising into a recovery path, and :func:`read_incremental` leaves
  an incomplete final line unconsumed for the next poll;
- **size rotation** — when a part exceeds ``max_bytes`` the file
  rotates to ``<path>.1``, ``<path>.2``, ... keeping the newest
  ``keep`` generations.

Corrupt-entry accounting is shared too: every journal flavor counts
skipped/unreadable entries in the single
``raft_tpu_journal_corrupt_total{kind}`` counter (``kind="case"`` for
the per-case resume pickles, ``kind="serve"`` for the write-ahead
request journal, ``kind="events"`` when a reader opts in), so one
dashboard row watches every durability surface.

Like the rest of ``raft_tpu.obs`` this module never imports jax, and a
writer failure must never take down the run it documents — callers
decide whether to swallow (telemetry) or count-and-continue (WAL).
"""
from __future__ import annotations

import json
import os
import threading


def _default(v):
    return str(v)


def dumps(doc: dict) -> str:
    """The one serialization every journal line uses (compact
    separators, non-JSON-able values stringified)."""
    return json.dumps(doc, separators=(",", ":"), default=_default)


def fsync_write(path: str, data: bytes):
    """The one crash-safe whole-file write every persistence tier uses:
    per-writer tmp name (concurrent same-path puts from sibling
    replicas/threads must not truncate each other mid-commit) ->
    write -> flush -> fsync -> atomic rename.  Raises on I/O trouble
    (the caller owns its degradation: count-and-miss, typed
    ``StorageExhausted`` on proven ENOSPC, ...); the tmp file is
    unlinked on any failure.  raftlint RTL007 statically pins every
    persistence module's write path onto this helper."""
    tmp = f"{path}.tmp.{os.getpid()}.{threading.get_ident()}"
    try:
        with open(tmp, "wb") as f:
            f.write(data)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)
    except BaseException:
        try:
            os.unlink(tmp)
        except OSError:
            pass
        raise


def dir_bytes(path: str) -> int:
    """Total payload bytes under ``path`` (non-recursive — every
    journal/store tier is directory-flat), 0 when unreadable.  Feeds
    the per-component ``raft_tpu_disk_bytes`` gauges."""
    total = 0
    try:
        with os.scandir(path) as entries:
            for e in entries:
                try:
                    if e.is_file(follow_symlinks=False):
                        total += e.stat(follow_symlinks=False).st_size
                except OSError:
                    continue
    except OSError:
        return 0
    return total


def count_corrupt(kind: str, n: int = 1):
    """Count torn/corrupt journal entries in the shared
    ``raft_tpu_journal_corrupt_total{kind}`` counter (never raises —
    corruption accounting must not become a second failure)."""
    if n <= 0:
        return
    try:
        from raft_tpu import obs
        obs.counter(
            "raft_tpu_journal_corrupt_total",
            "torn/corrupt journal entries treated as misses on read, "
            "by journal kind").inc(float(n), kind=str(kind))
    except Exception:                                 # pragma: no cover
        pass


class JsonlWriter:
    """One append-only, line-flushed JSONL file with size rotation.

    Not thread-safe on its own — callers that emit from several threads
    hold their own lock around :meth:`write` (the flight recorder and
    the serve journal both do).  ``header`` (optional) is called as
    ``header(part)`` after every fresh open — including the first — and
    its returned dict (if any) becomes the part's first record, so a
    rotated generation is self-describing.

    Replication hooks (optional, both guarded — a hook failure must
    never take down the journal it observes):

    - ``post_flush(writer)`` runs after every record's write+flush,
      while the caller's lock (if any) is still held — the seam the
      serve WAL mirror uses to ship the fresh bytes (or queue a
      catch-up) to peer stores *before* the write is acknowledged;
    - ``post_rotate(writer, sealed_part)`` runs after a size rotation
      sealed a part (now at ``<path>.1``), with the sealed generation's
      part index — the seam that ships whole sealed parts.
    """

    def __init__(self, path: str, *, max_bytes: int = None,
                 keep: int = 2, header=None, post_flush=None,
                 post_rotate=None):
        self.path = str(path)
        self.max_bytes = max_bytes
        self.keep = max(0, int(keep))
        self.part = 0
        self._header = header
        self.post_flush = post_flush
        self.post_rotate = post_rotate
        self._fh = None
        os.makedirs(os.path.dirname(os.path.abspath(self.path)),
                    exist_ok=True)
        self._open_fresh()

    # -- file lifecycle ----------------------------------------------

    def _open_fresh(self):
        self._fh = open(self.path, "a", encoding="utf-8")
        if self._header is not None:
            doc = self._header(self.part)
            if doc:
                self.write(dict(doc), rotate=False)

    def write(self, doc: dict, rotate: bool = True):
        """Serialize one record, write it, flush — then rotate if the
        part outgrew ``max_bytes``.  Raises on I/O trouble; the caller
        chooses its own degradation (the obs layer swallows, the WAL
        counts and keeps serving)."""
        self._fh.write(dumps(doc) + "\n")
        self._fh.flush()
        if self.post_flush is not None:
            # notification only — the mirror counts its own errors; a
            # broken hook must never become a failed WAL write
            try:
                self.post_flush(self)
            except Exception:                        # pragma: no cover
                pass
        if rotate and self.max_bytes is not None \
                and self._fh.tell() > self.max_bytes:
            self.rotate()

    def rotate(self):
        """Close the current part and open a fresh one, shifting the
        closed part to ``<path>.1`` (older generations shuffle up;
        anything past ``keep`` is dropped)."""
        try:
            self._fh.close()
        except OSError:                              # pragma: no cover
            pass
        if self.keep <= 0:
            try:
                os.remove(self.path)
            except OSError:                          # pragma: no cover
                pass
        else:
            for i in range(self.keep - 1, 0, -1):
                src, dst = f"{self.path}.{i}", f"{self.path}.{i + 1}"
                if os.path.exists(src):
                    try:
                        os.replace(src, dst)
                    except OSError:                  # pragma: no cover
                        pass
            try:
                os.replace(self.path, self.path + ".1")
            except OSError:                          # pragma: no cover
                pass
        self.part += 1
        # the rotate hook fires BEFORE the fresh part opens (and writes
        # its header): a mirror must shuffle its peer generations while
        # the sealed bytes still name the live path, or the header ship
        # would overwrite the peer's un-sealed copy
        if self.post_rotate is not None:
            try:
                self.post_rotate(self, self.part - 1)
            except Exception:                        # pragma: no cover
                pass
        self._open_fresh()

    def tell(self) -> int:
        return self._fh.tell()

    def close(self):
        """Close the stream (idempotent; no end-record ceremony — that
        is the owning journal's schema, not the codec's)."""
        if self._fh is None:
            return
        try:
            self._fh.close()
        except OSError:                              # pragma: no cover
            pass
        self._fh = None

    @property
    def closed(self) -> bool:
        return self._fh is None

    # -- fault seam (testing/faults.py: torn@journal) ----------------

    def tear_tail(self, nbytes: int = 7):
        """Truncate the file mid-record — what a crash between
        ``write`` and ``flush`` of the NEXT record looks like.  Driven
        only by the ``torn@journal`` fault action; readers must skip
        the torn line."""
        try:
            self._fh.flush()
            end = self._fh.tell()
            self._fh.close()
            with open(self.path, "ab") as f:
                f.truncate(max(0, end - int(nbytes)))
            self._fh = open(self.path, "a", encoding="utf-8")
        except OSError:                              # pragma: no cover
            pass


_READ_LOCK = threading.Lock()  # corrupt counting only; reads are pure


def read(path: str, kind: str = None) -> list[dict]:
    """Parse one JSONL file, tolerating the torn final line a hard
    kill can leave (any unparseable line is skipped, never fatal).
    When ``kind`` is given, skipped lines are counted in
    ``raft_tpu_journal_corrupt_total{kind}``."""
    return read_counted(path, kind)[0]


def read_counted(path: str, kind: str = None) -> tuple[list[dict], int]:
    """:func:`read` plus the number of skipped (torn/corrupt) lines —
    the replay paths that must *account* for corruption, not just
    survive it."""
    out = []
    bad = 0
    try:
        with open(path, encoding="utf-8") as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                try:
                    doc = json.loads(line)
                except json.JSONDecodeError:
                    bad += 1
                    continue
                if isinstance(doc, dict):
                    out.append(doc)
                else:
                    bad += 1
    except OSError:
        return [], 0
    if kind is not None and bad:
        with _READ_LOCK:
            count_corrupt(kind, bad)
    return out, bad


def read_incremental(path: str, offset: int = 0) -> tuple[list[dict], int]:
    """Parse only the COMPLETE lines at byte ``offset`` and beyond;
    returns ``(records, new_offset)``.  A torn final line (mid-write or
    mid-kill) is left unconsumed for the next call — the follow loop's
    O(new) building block.  A ``new_offset`` smaller than the file is
    normal (torn tail); a file smaller than ``offset`` means the writer
    rotated — re-enter at 0."""
    try:
        with open(path, "rb") as f:
            f.seek(int(offset))
            data = f.read()
    except OSError:
        return [], offset
    end = data.rfind(b"\n")
    if end < 0:
        return [], offset
    out = []
    for raw in data[:end].split(b"\n"):
        if not raw.strip():
            continue
        try:
            doc = json.loads(raw.decode("utf-8"))
        except (json.JSONDecodeError, UnicodeDecodeError):
            continue
        if isinstance(doc, dict):
            out.append(doc)
    return out, int(offset) + end + 1
