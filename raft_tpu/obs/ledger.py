"""Result ledger: content-addressed physics digests for cross-run diffing.

A ledger (schema ``raft_tpu.ledger/v1``) is the numeric fingerprint of
one run's physics outputs — small enough to compute on every run and
stable enough to diff across runs: per-case RAO magnitude/phase
summaries per DOF, response means/stds, eigenfrequencies, mean offsets,
drag fixed-point iteration counts, dynamics condition numbers.  Each
entry carries a SHA-256 digest of its canonicalized metrics, and the
ledger carries a digest over the entry digests, so "did anything move?"
is a string compare and "what moved, by how much?" is :func:`diff`.

Writers: ``Model.analyzeCases`` (kept on ``model.last_ledger``, written
as ``<kind>_<run_id>.ledger.json`` next to the manifest when an obs dir
is configured) and ``parallel.sweep.sweep_cases``.  Readers: the
``tools/obsctl.py`` CLI (``diff`` / ``check`` / ``trend``), the bench
self-compare, and the ``tests/test_regression_sentinel.py`` canary
against the golden ledgers under ``tests/golden/``.

Ledger document::

    schema, run_id, kind, created_at, environment, config,
    entries: [{key, metrics: {name: scalar | [scalars]}, digest}],
    digest

:func:`diff` compares two ledgers entry-by-entry, metric-by-metric with
a relative tolerance (per-metric overrides via fnmatch patterns) and
returns a structured report; :func:`compare_manifests` applies the same
machinery to two run manifests (numeric vs wall-time/perf classes).
"""
from __future__ import annotations

import datetime
import fnmatch
import hashlib
import json
import math
import os
import uuid

SCHEMA = "raft_tpu.ledger/v1"

REQUIRED_KEYS = ("schema", "run_id", "kind", "created_at", "environment",
                 "config", "entries", "digest")

#: manifest metric families that legitimately vary between identical
#: runs (compile-event counts depend on the persistent compilation
#: cache; jit cache stats on process history) — skipped by
#: compare_manifests unless the caller passes ignore=()
DEFAULT_MANIFEST_IGNORE = ("raft_jax_*", "raft_jit_cache_*",
                           "raft_device_*", "raft_live_arrays*",
                           "raft_tpu_build_info",
                           # trace-time dispatch counts and executable-
                           # cache events legitimately differ between a
                           # cold run and a warm-started one
                           "raft_solve_dispatch*", "raft_exec_cache_*",
                           # probe-sample arrival counts depend on the
                           # RAFT_TPU_PROBES mode and callback timing,
                           # not on the physics
                           "raft_tpu_probe_*")

#: manifest scalar patterns that measure wall time / throughput — they
#: jitter between identical runs, so they get the looser perf tolerance
PERF_PATTERNS = ("duration_s", "phase:*:total_s", "*_seconds_total",
                 "extra:result:value", "extra:result:vs_baseline",
                 "extra:result:analyze_cases_s_per_case")


def _utcnow() -> str:
    return datetime.datetime.now(datetime.timezone.utc).isoformat()


# ---------------------------------------------------------------------------
# construction
# ---------------------------------------------------------------------------

def _scalar(v):
    """Canonical JSON scalar for a metric value (floats kept full
    precision; numpy scalars unwrapped)."""
    if isinstance(v, bool):
        return int(v)
    if isinstance(v, (int, str)):
        return v
    f = float(v)
    if math.isnan(f):
        return "nan"
    if math.isinf(f):
        return "inf" if f > 0 else "-inf"
    return f


def canonical_metrics(metrics: dict) -> dict:
    """Metrics dict with every value a JSON scalar or flat list of them
    (arrays flattened), keys sorted — the digest input."""
    out = {}
    for k in sorted(metrics):
        v = metrics[k]
        if hasattr(v, "tolist"):
            v = v.tolist()
        if isinstance(v, (list, tuple)):
            flat = []
            for x in v:
                flat.extend(x if isinstance(x, (list, tuple)) else [x])
            out[str(k)] = [_scalar(x) for x in flat]
        else:
            out[str(k)] = _scalar(v)
    return out


def digest_metrics(metrics: dict) -> str:
    """``sha256:<hex>`` of the canonical JSON of ``metrics`` — full
    float precision (repr round-trip), so digest equality means the
    numbers are bitwise-identical."""
    payload = json.dumps(canonical_metrics(metrics), sort_keys=True,
                         separators=(",", ":"))
    return "sha256:" + hashlib.sha256(payload.encode()).hexdigest()


def new_ledger(kind: str, run_id: str = None, config: dict = None,
               environment: dict = None) -> dict:
    return {
        "schema": SCHEMA,
        "run_id": run_id or uuid.uuid4().hex[:12],
        "kind": kind,
        "created_at": _utcnow(),
        "environment": dict(environment or {}),
        "config": dict(config or {}),
        "entries": [],
        "digest": None,
    }


def add_entry(ledger: dict, key: str, metrics: dict) -> dict:
    """Append one content-addressed entry; returns the entry."""
    entry = {"key": str(key), "metrics": canonical_metrics(metrics),
             "digest": digest_metrics(metrics)}
    ledger["entries"].append(entry)
    return entry


def finalize(ledger: dict) -> dict:
    """Stamp the ledger-level digest (over the sorted entry digests)."""
    body = json.dumps(sorted((e["key"], e["digest"])
                             for e in ledger["entries"]),
                      separators=(",", ":"))
    ledger["digest"] = "sha256:" + hashlib.sha256(body.encode()).hexdigest()
    return ledger


def write_ledger(ledger: dict, path: str) -> str:
    if ledger.get("digest") is None:
        finalize(ledger)
    parent = os.path.dirname(os.path.abspath(path))
    os.makedirs(parent, exist_ok=True)
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        json.dump(ledger, f, indent=1)
    os.replace(tmp, path)
    return path


def load_ledger(path: str) -> dict:
    with open(path) as f:
        return json.load(f)


def validate_ledger(doc: dict) -> list[str]:
    """Structural check against the v1 schema; [] == valid."""
    problems = []
    if not isinstance(doc, dict):
        return ["ledger is not an object"]
    if doc.get("schema") != SCHEMA:
        problems.append(f"schema is {doc.get('schema')!r}, expected {SCHEMA}")
    for k in REQUIRED_KEYS:
        if k not in doc:
            problems.append(f"missing key {k!r}")
    if not isinstance(doc.get("entries"), list):
        problems.append("entries is not a list")
        return problems
    seen = set()
    for i, e in enumerate(doc["entries"]):
        if not isinstance(e, dict) or not {"key", "metrics", "digest"} <= set(e):
            problems.append(f"entries[{i}] missing key/metrics/digest")
            continue
        if e["key"] in seen:
            problems.append(f"duplicate entry key {e['key']!r}")
        seen.add(e["key"])
        if digest_metrics(e["metrics"]) != e["digest"]:
            problems.append(f"entries[{i}] ({e['key']!r}) digest mismatch")
    return problems


# ---------------------------------------------------------------------------
# builders for the instrumented entry points
# ---------------------------------------------------------------------------

_CHANS = ("surge", "sway", "heave", "roll", "pitch", "yaw")


def ledger_from_model(model, run_id: str = None) -> dict:
    """Ledger of a completed ``Model.analyzeCases`` run.

    One entry per (case, fowt) with response means/stds and RAO
    magnitude/phase summaries per DOF, one system entry per case (mean
    offsets, statics Newton iterations, dynamics condition number and
    solve residuals, drag fixed-point counts), plus an ``eigen`` entry
    when ``solveEigen`` has run.
    """
    from raft_tpu.obs import manifest as _manifest

    config = {"nCases": len(model.results.get("case_metrics", {})),
              "nFOWT": model.nFOWT, "nw": model.nw, "nDOF": model.nDOF}
    if getattr(model, "mesh", None) is not None:
        # the full mesh topology rides in the ledger config so a
        # partitioned run is distinguishable from a single-device one
        # (the physics entries must still digest identically — the
        # golden gate runs with RAFT_TPU_MESH set to prove it)
        from raft_tpu.parallel import partition
        config["mesh"] = partition.mesh_facts(model.mesh)
    led = new_ledger(
        kind="analyzeCases", run_id=run_id, config=config,
        environment=_manifest.capture_environment(devices=False))
    records = getattr(model, "_case_records", {})
    for iCase in sorted(model.results.get("case_metrics", {})):
        per_case = model.results["case_metrics"][iCase]
        if "failed" in per_case:
            # quarantined case: a structured failure entry stands in for
            # the physics digests (the full record also rides in
            # ledger["extra"]["failed_cases"])
            frec = per_case["failed"]
            add_entry(led, f"case{iCase}/failed", {
                k: v for k, v in sorted(frec.items())
                if isinstance(v, (bool, int, float, str))})
            continue
        rec = records.get(str(iCase), {})
        for ifowt in sorted(k for k in per_case if isinstance(k, int)):
            m = per_case[ifowt]
            metrics = {}
            for ch in _CHANS:
                metrics[f"mean_{ch}"] = m[f"{ch}_avg"]
                metrics[f"std_{ch}"] = m[f"{ch}_std"]
                if f"{ch}_RAO_mag_max" in m:
                    metrics[f"rao_mag_max_{ch}"] = m[f"{ch}_RAO_mag_max"]
                    metrics[f"rao_mag_mean_{ch}"] = m[f"{ch}_RAO_mag_mean"]
                    metrics[f"rao_phase_peak_{ch}"] = m[f"{ch}_RAO_phase_peak"]
            if "Tmoor_avg" in m:
                metrics["tmoor_avg"] = m["Tmoor_avg"]
                metrics["tmoor_std"] = m["Tmoor_std"]
            frec = rec.get(f"fowt{ifowt}", {})
            for k in ("drag_iters", "drag_residual", "drag_converged"):
                if k in frec:
                    metrics[k] = frec[k]
            add_entry(led, f"case{iCase}/fowt{ifowt}", metrics)
        sysm = {}
        offsets = model.results.get("mean_offsets", [])
        if iCase < len(offsets):
            sysm["mean_offset"] = offsets[iCase]
        for k in ("statics_iters", "statics_residual", "cond_max",
                  "dyn_solve_residual"):
            if k in rec:
                sysm[k] = rec[k]
        if sysm:
            add_entry(led, f"case{iCase}/system", sysm)
    if "eigen" in model.results:
        add_entry(led, "eigen",
                  {"fn_hz": model.results["eigen"]["frequencies"]})
    return finalize(led)


def ledger_from_sweep(out: dict, config: dict = None,
                      run_id: str = None) -> dict:
    """Ledger of one ``sweep_cases`` output batch: per-case response
    stds + fixed-point iteration counts, and a batch summary entry."""
    import numpy as np

    from raft_tpu.obs import manifest as _manifest

    led = new_ledger(kind="sweep_cases", run_id=run_id,
                     config=dict(config or {}),
                     environment=_manifest.capture_environment(devices=False))
    std = np.asarray(out["std"])
    iters = np.asarray(out["iters"])
    conv = np.asarray(out["converged"])
    for i in range(std.shape[0]):
        add_entry(led, f"case{i}", {
            "std": std[i], "iters": int(iters[i]),
            "converged": bool(conv[i])})
    add_entry(led, "summary", {
        "ncases": int(std.shape[0]),
        "n_converged": int(conv.sum()),
        "iters_max": int(iters.max(initial=0)),
        "std_norm": float(np.linalg.norm(std))})
    return finalize(led)


# ---------------------------------------------------------------------------
# diffing
# ---------------------------------------------------------------------------

#: residual-class metrics (solver convergence diagnostics:
#: ``statics_residual``, ``dyn_solve_residual``, ``drag_residual``) sit
#: at machine-epsilon magnitudes where a strict relative compare is
#: noise-gating noise — the same converged physics lands at e.g.
#: 4.5638e-7 on one host and 4.5607e-7 on another (a 7e-4 relative
#: "drift" of a quantity whose only contract is "small").  They get a
#: relative tolerance FLOOR instead of the exact ledger tolerance; an
#: explicit per-metric override still wins (callers can pin a residual
#: exactly when they mean to).
RESIDUAL_METRIC_PATTERNS = ("*residual*",)
RESIDUAL_TOL_FLOOR = 1e-2


def _tol_for(metric: str, tol_rel: float, per_metric: dict) -> float:
    for pat, t in (per_metric or {}).items():
        if fnmatch.fnmatch(metric, pat):
            return float(t)
    if any(fnmatch.fnmatch(metric, pat)
           for pat in RESIDUAL_METRIC_PATTERNS):
        return max(tol_rel, RESIDUAL_TOL_FLOOR)
    return tol_rel


def _rel(a, b) -> float:
    if a == b:
        return 0.0
    try:
        fa, fb = float(a), float(b)
    except (TypeError, ValueError):
        return math.inf           # non-numeric mismatch
    if math.isnan(fa) and math.isnan(fb):
        return 0.0
    denom = max(abs(fa), abs(fb))
    if denom == 0.0:
        return 0.0
    if not (math.isfinite(fa) and math.isfinite(fb)):
        return math.inf
    return abs(fa - fb) / denom


def _compare_values(va, vb):
    """Max elementwise relative deviation between two metric values
    (scalar or list); inf on shape/type mismatch."""
    la = va if isinstance(va, list) else [va]
    lb = vb if isinstance(vb, list) else [vb]
    if len(la) != len(lb):
        return math.inf, -1
    worst, worst_i = 0.0, -1
    for i, (a, b) in enumerate(zip(la, lb)):
        r = _rel(a, b)
        if r > worst:
            worst, worst_i = r, i
    return worst, worst_i


def diff(a: dict, b: dict, tol_rel: float = 1e-6,
         per_metric: dict = None, ignore: tuple = ()) -> dict:
    """Compare ledger ``b`` (current) against ``a`` (baseline).

    Returns a report dict: ``regressions`` lists every metric whose max
    elementwise relative deviation exceeds its tolerance (``tol_rel``,
    overridable per metric-name fnmatch pattern via ``per_metric``);
    ``added``/``removed`` list entry/metric keys present on one side
    only (also regressions — a silently vanished output is a drift).
    ``ok`` is True iff nothing regressed.
    """
    ea = {e["key"]: e for e in a.get("entries", [])}
    eb = {e["key"]: e for e in b.get("entries", [])}
    report = {
        "a": a.get("run_id"), "b": b.get("run_id"),
        "kind": (a.get("kind"), b.get("kind")),
        "tol_rel": tol_rel,
        "identical": (a.get("digest") is not None
                      and a.get("digest") == b.get("digest")),
        "added": sorted(set(eb) - set(ea)),
        "removed": sorted(set(ea) - set(eb)),
        "n_compared": 0, "n_entries": len(set(ea) & set(eb)),
        "regressions": [],
    }
    for key in sorted(set(ea) & set(eb)):
        ma, mb = ea[key]["metrics"], eb[key]["metrics"]
        if ea[key]["digest"] == eb[key]["digest"]:
            report["n_compared"] += len(ma)
            continue
        for name in sorted(set(ma) | set(mb)):
            full = f"{key}:{name}"
            if any(fnmatch.fnmatch(full, p) or fnmatch.fnmatch(name, p)
                   for p in ignore):
                continue
            if name not in ma or name not in mb:
                report["regressions"].append({
                    "entry": key, "metric": name,
                    "a": ma.get(name), "b": mb.get(name),
                    "rel": math.inf,
                    "why": "missing in " + ("baseline" if name not in ma
                                            else "current")})
                continue
            report["n_compared"] += 1
            rel, idx = _compare_values(ma[name], mb[name])
            tol = _tol_for(name, tol_rel, per_metric)
            if rel > tol:
                report["regressions"].append({
                    "entry": key, "metric": name, "index": idx,
                    "a": ma[name], "b": mb[name], "rel": rel, "tol": tol})
    report["ok"] = (not report["regressions"] and not report["added"]
                    and not report["removed"])
    return report


def _fmt_val(v):
    if isinstance(v, list):
        head = ", ".join(f"{x:.6g}" if isinstance(x, float) else str(x)
                         for x in v[:4])
        return f"[{head}{', ...' if len(v) > 4 else ''}]"
    if isinstance(v, float):
        return f"{v:.9g}"
    return str(v)


def format_diff(report: dict, max_rows: int = 40) -> str:
    """Human-readable rendering of a :func:`diff` report."""
    lines = [f"ledger diff: {report['a']} -> {report['b']} "
             f"(tol_rel={report['tol_rel']:g})"]
    if report.get("identical"):
        lines.append("  digests identical — nothing moved")
    for key in report["removed"]:
        lines.append(f"  REMOVED entry {key}")
    for key in report["added"]:
        lines.append(f"  ADDED   entry {key}")
    regs = report["regressions"]
    for r in regs[:max_rows]:
        why = r.get("why")
        if why:
            lines.append(f"  REGRESSION {r['entry']}:{r['metric']} — {why}")
        else:
            lines.append(
                f"  REGRESSION {r['entry']}:{r['metric']} "
                f"rel={r['rel']:.3g} (tol {r['tol']:g}): "
                f"{_fmt_val(r['a'])} -> {_fmt_val(r['b'])}")
    if len(regs) > max_rows:
        lines.append(f"  ... and {len(regs) - max_rows} more")
    lines.append(
        f"  {'OK' if report['ok'] else 'REGRESSED'}: "
        f"{len(regs)} regression(s) over {report['n_compared']} compared "
        f"metric(s) in {report['n_entries']} shared entrie(s)")
    return "\n".join(lines)


# ---------------------------------------------------------------------------
# manifest comparison (same engine, perf-aware)
# ---------------------------------------------------------------------------

def _manifest_scalars(doc: dict) -> dict:
    """Flatten a run manifest to comparable scalars.

    Keys: ``status``, ``duration_s``, ``phase:<name>:total_s`` /
    ``:calls``, ``metric:<name>{labels}`` for gauge/counter series and
    histogram count/sum, ``extra:result:*`` numeric leaves.
    """
    out = {"status": doc.get("status")}
    if isinstance(doc.get("duration_s"), (int, float)):
        out["duration_s"] = float(doc["duration_s"])
    for p in doc.get("phases") or []:
        out[f"phase:{p['name']}:total_s"] = float(p["total_s"])
        out[f"phase:{p['name']}:calls"] = int(p["calls"])
    for name, m in (doc.get("metrics") or {}).items():
        for s in m.get("series", []):
            lbl = ",".join(f"{k}={v}" for k, v in
                           sorted(s.get("labels", {}).items()))
            base = f"metric:{name}{{{lbl}}}"
            if m.get("kind") == "histogram":
                out[base + ":count"] = s.get("count")
                out[base + ":sum"] = s.get("sum")
            else:
                out[base] = s.get("value")
    res = (doc.get("extra") or {}).get("result") or {}
    for k, v in res.items():
        if isinstance(v, bool):
            out[f"extra:result:{k}"] = int(v)
        elif isinstance(v, (int, float)):
            out[f"extra:result:{k}"] = v
    return out


def _is_perf(key: str) -> bool:
    return any(fnmatch.fnmatch(key, p) or p in key for p in PERF_PATTERNS)


def compare_manifests(a: dict, b: dict, tol_rel: float = 1e-6,
                      tol_perf: float = 0.5, per_metric: dict = None,
                      ignore: tuple = DEFAULT_MANIFEST_IGNORE) -> dict:
    """Diff two run manifests: numeric facts at ``tol_rel``, wall-time /
    throughput facts at the looser ``tol_perf`` (fractional change —
    0.5 flags a >50% slowdown/speedup).  ``per_metric`` maps fnmatch
    patterns over the flattened keys (``duration_s``,
    ``phase:solve:total_s``, ``metric:raft_...{...}``) to tolerance
    overrides.  Metric families that legitimately vary between
    identical runs are ignored by default.  Returns the same report
    shape as :func:`diff`."""
    sa, sb = _manifest_scalars(a), _manifest_scalars(b)
    report = {
        "a": a.get("run_id"), "b": b.get("run_id"),
        "kind": (a.get("kind"), b.get("kind")),
        "tol_rel": tol_rel, "tol_perf": tol_perf,
        "identical": False,
        "added": [], "removed": [],
        "n_compared": 0, "n_entries": 1,
        "regressions": [],
    }
    keys = set(sa) | set(sb)
    worst_rel = 0.0
    for key in sorted(keys):
        name = key.split("{")[0].removeprefix("metric:")
        if any(fnmatch.fnmatch(name, p) or fnmatch.fnmatch(key, p)
               for p in ignore):
            continue
        if key not in sa or key not in sb:
            (report["removed"] if key not in sb
             else report["added"]).append(key)
            continue
        report["n_compared"] += 1
        va, vb = sa[key], sb[key]
        if key == "status":
            if va != vb:
                report["regressions"].append({
                    "entry": "manifest", "metric": key, "a": va, "b": vb,
                    "rel": math.inf, "tol": 0.0, "why": "status changed"})
            continue
        rel, idx = _compare_values(va, vb)
        worst_rel = max(worst_rel, rel)
        tol = _tol_for(key, tol_perf if _is_perf(key) else tol_rel,
                       per_metric)
        if rel > tol:
            report["regressions"].append({
                "entry": "manifest", "metric": key, "index": idx,
                "a": va, "b": vb, "rel": rel, "tol": tol,
                "class": "perf" if _is_perf(key) else "numeric"})
    # a vanished metric/phase is a drift (same stance as diff()); keys
    # only ADDED by the newer run are fine — new instrumentation must
    # not flag its own introduction
    report["ok"] = not report["regressions"] and not report["removed"]
    report["identical"] = (report["ok"] and not report["added"]
                           and worst_rel == 0.0)
    return report


#: aliases exported through raft_tpu.obs (where ``SCHEMA``/``diff``
#: would collide with the manifest schema / builtins)
LEDGER_SCHEMA = SCHEMA
diff_ledgers = diff


def load_any(path: str) -> tuple[str, dict]:
    """Load ``path`` and classify it: ('ledger'|'manifest', doc)."""
    with open(path) as f:
        doc = json.load(f)
    schema = doc.get("schema", "")
    if schema == SCHEMA:
        return "ledger", doc
    if schema.startswith("raft_tpu.run_manifest/"):
        return "manifest", doc
    raise ValueError(f"{path}: unrecognized schema {schema!r} "
                     "(expected a raft_tpu ledger or run manifest)")
