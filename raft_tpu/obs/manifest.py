"""Structured run manifests: one JSON record per solve/sweep/bench run.

A ``RunManifest`` captures what ran, where, and how it behaved:
environment (JAX version/backend/device count/x64 flag/git SHA), the run
config, per-phase wall times (from the span aggregate), a metrics
snapshot, and — for the bench TPU probe — structured attempt records
(timestamps, timeout, error class) replacing free-text failure strings.

Schema (``raft_tpu.run_manifest/v1``) — every manifest has exactly these
top-level keys; see ``REQUIRED_KEYS`` and ``validate_manifest()``:

    schema, run_id, kind, status, started_at, finished_at, duration_s,
    environment, config, phases, metrics, probe_attempts, extra

Writers: ``Model.analyzeCases``, ``parallel.sweep.sweep_cases``, and
every ``bench.py`` invocation (including the ``tpu_unavailable`` early
exit).  See docs/observability.md for the field-by-field reference.
"""
from __future__ import annotations

import dataclasses
import datetime
import json
import os
import socket
import subprocess
import sys
import uuid

SCHEMA = "raft_tpu.run_manifest/v1"

#: exactly the top-level keys of a serialized v1 manifest
REQUIRED_KEYS = (
    "schema", "run_id", "kind", "status", "started_at", "finished_at",
    "duration_s", "environment", "config", "phases", "metrics",
    "probe_attempts", "extra",
)

_STATUSES = ("running", "ok", "failed", "tpu_unavailable")


def _utcnow() -> str:
    return datetime.datetime.now(datetime.timezone.utc).isoformat()


#: process-lifetime cache of the git probes — every run emits them
#: (environment capture, build-info gauge, ledger), and spawning a git
#: subprocess (plus a full working-tree scan for the dirty flag) per
#: sweep batch is pure overhead for facts that don't change mid-process
_GIT_CACHE: dict = {}


def _git(key: str, argv: list[str]):
    if key in _GIT_CACHE:
        return _GIT_CACHE[key]
    root = os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    out = None
    try:
        r = subprocess.run(["git", "-C", root] + argv,
                           capture_output=True, text=True, timeout=10)
        if r.returncode == 0:
            out = r.stdout
    except Exception:
        pass
    _GIT_CACHE[key] = out
    return out


def git_sha() -> str | None:
    """HEAD SHA of the checkout this package runs from, or None.
    Cached for the process lifetime."""
    out = _git("sha", ["rev-parse", "HEAD"])
    return out.strip() if out is not None else None


def git_dirty() -> bool | None:
    """True when the checkout has uncommitted changes, None when git is
    unavailable.  Cached for the process lifetime."""
    out = _git("dirty", ["status", "--porcelain"])
    return bool(out.strip()) if out is not None else None


def capture_environment(devices: bool = True) -> dict:
    """Environment block: python/host/jax/git facts.

    ``devices=False`` skips everything that would initialize a JAX
    backend — REQUIRED on the bench ``tpu_unavailable`` path, where an
    in-process ``jax.devices()`` can hang forever on the wedged tunnel.
    """
    env = {
        "python": sys.version.split()[0],
        "hostname": socket.gethostname(),
        "pid": os.getpid(),
        "git_sha": git_sha(),
    }
    try:
        import jax
        env["jax_version"] = jax.__version__
        env["x64"] = bool(jax.config.jax_enable_x64)
        if devices:
            ds = jax.devices()
            env["backend"] = jax.default_backend()
            env["device_count"] = len(ds)
            env["devices"] = [str(d) for d in ds[:8]]
        else:
            env["backend"] = None
            env["device_count"] = None
    except Exception as e:                      # pragma: no cover
        env["jax_error"] = f"{type(e).__name__}: {e}"
    return env


@dataclasses.dataclass
class ProbeAttempt:
    """One structured TPU-probe attempt record (bench.py).

    ``attempts`` counts how many identical consecutive tries this
    record stands for — :func:`collapse_probe_attempts` merges runs of
    same-outcome records (the r01–r05 benches logged the same hang
    string 3x each) into one with the combined count and time span.
    """
    index: int
    started_at: str
    finished_at: str | None = None
    timeout_s: float | None = None
    outcome: str | None = None      # ok | timeout | error | cpu-fallback
    error_class: str | None = None  # e.g. TimeoutExpired, CalledProcessError
    message: str | None = None
    attempts: int = 1

    def to_dict(self) -> dict:
        return dataclasses.asdict(self)


#: fields that define probe-attempt identity for collapsing (timestamps
#: and index vary between identical retries; outcome facts must not)
_PROBE_IDENTITY = ("outcome", "error_class", "message", "timeout_s")


def collapse_probe_attempts(attempts: list) -> list[dict]:
    """Collapse identical CONSECUTIVE probe-attempt records into one.

    Merged record: first record's ``index``/``started_at``, last
    record's ``finished_at``, summed ``attempts``.  Non-consecutive or
    differing records are preserved in order — the collapse only
    removes pure retry noise, never reorders the probe history.
    """
    out: list[dict] = []
    for att in attempts:
        att = att.to_dict() if isinstance(att, ProbeAttempt) else dict(att)
        att.setdefault("attempts", 1)
        prev = out[-1] if out else None
        if prev is not None and all(
                prev.get(k) == att.get(k) for k in _PROBE_IDENTITY):
            prev["attempts"] += att["attempts"]
            if att.get("finished_at"):
                prev["finished_at"] = att["finished_at"]
        else:
            out.append(att)
    return out


@dataclasses.dataclass
class RunManifest:
    kind: str
    run_id: str = dataclasses.field(
        default_factory=lambda: uuid.uuid4().hex[:12])
    status: str = "running"
    started_at: str = dataclasses.field(default_factory=_utcnow)
    finished_at: str | None = None
    duration_s: float | None = None
    environment: dict = dataclasses.field(default_factory=dict)
    config: dict = dataclasses.field(default_factory=dict)
    phases: list = dataclasses.field(default_factory=list)
    metrics: dict = dataclasses.field(default_factory=dict)
    probe_attempts: list = dataclasses.field(default_factory=list)
    extra: dict = dataclasses.field(default_factory=dict)

    @classmethod
    def begin(cls, kind: str, config: dict = None,
              devices: bool = True) -> "RunManifest":
        """Start a manifest: stamps run id, start time, environment, and
        a baseline of the span aggregate so ``finish()`` reports phase
        times for THIS run only (the aggregate is process-cumulative).

        When an obs output directory is configured this also fires
        ``obs.begin_run``: a ``status="running"`` manifest stub is
        written (atomically replaced by ``finish_run`` — a killed run
        therefore leaves a discoverable record) and the flight recorder
        opens the run's event file."""
        m = cls(kind=kind, config=dict(config or {}),
                environment=capture_environment(devices=devices))
        from raft_tpu.obs import tracing as _tracing
        m._phase_baseline = _tracing.aggregate()
        # the metrics snapshot embedded at finish is process-cumulative;
        # baseline the probe budget now so the trend store can attribute
        # probe volume to THIS run (trendstore.facts_from_manifest)
        from raft_tpu.obs import metrics as _metrics
        m.extra["probe_events_at_begin"] = _metrics.counter_total(
            "raft_tpu_probe_events_total")
        from raft_tpu import obs as _obs
        _obs.begin_run(m)
        return m

    def add_probe_attempt(self, attempt: ProbeAttempt | dict):
        """Append a probe attempt, collapsing it into the previous
        record when it is an identical consecutive retry.  The attempt
        also streams to the flight recorder as a ``probe_attempt``
        event — bench TPU probes are exactly the in-flight phase an
        operator tails."""
        if isinstance(attempt, ProbeAttempt):
            attempt = attempt.to_dict()
        self.probe_attempts = collapse_probe_attempts(
            self.probe_attempts + [dict(attempt)])
        from raft_tpu.obs import events as _events
        _events.emit("probe_attempt", **dict(attempt))

    def finish(self, status: str = "ok", metrics: dict = None,
               phases: list = None) -> "RunManifest":
        """Stamp the end time and fold in the metrics snapshot and the
        per-phase wall times.  Defaults: the process-wide registry
        (snapshots are cumulative, Prometheus-style) and the span
        aggregate MINUS the baseline captured by ``begin()`` — so
        ``phases`` covers this run only even when several runs share
        the process."""
        if status not in _STATUSES:
            raise ValueError(f"status {status!r} not in {_STATUSES}")
        self.finished_at = _utcnow()
        t0 = datetime.datetime.fromisoformat(self.started_at)
        t1 = datetime.datetime.fromisoformat(self.finished_at)
        self.duration_s = (t1 - t0).total_seconds()
        self.status = status
        if metrics is None:
            from raft_tpu.obs import metrics as _metrics
            if _metrics._JAX_HOOKS.get("mode") == "jit-cache-poll":
                # the fallback compile-telemetry path has no listener to
                # push events — pull one sample so manifests still carry
                # compile counts on jax builds without jax.monitoring
                _metrics.sample_jit_cache()
            metrics = _metrics.snapshot()
        self.metrics = metrics
        if phases is None:
            from raft_tpu.obs import tracing as _tracing
            base = getattr(self, "_phase_baseline", {})
            phases = []
            for name, (tot, calls) in _tracing.aggregate().items():
                tot0, calls0 = base.get(name, (0.0, 0))
                if calls > calls0:
                    phases.append({"name": name, "total_s": tot - tot0,
                                   "calls": calls - calls0})
            phases.sort(key=lambda p: -p["total_s"])
        self.phases = phases
        return self

    def to_dict(self) -> dict:
        d = {"schema": SCHEMA}
        d.update(dataclasses.asdict(self))
        return {k: d[k] for k in REQUIRED_KEYS}

    def write(self, path: str) -> str:
        """Serialize to JSON at ``path``; returns the path."""
        parent = os.path.dirname(os.path.abspath(path))
        os.makedirs(parent, exist_ok=True)
        tmp = path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(self.to_dict(), f, indent=1, default=str)
        os.replace(tmp, path)
        return path


def validate_manifest(doc: dict) -> list[str]:
    """Structural check of a serialized manifest against the v1 schema;
    returns a list of problems (empty == valid)."""
    problems = []
    if not isinstance(doc, dict):
        return ["manifest is not an object"]
    if doc.get("schema") != SCHEMA:
        problems.append(f"schema is {doc.get('schema')!r}, expected {SCHEMA}")
    for k in REQUIRED_KEYS:
        if k not in doc:
            problems.append(f"missing key {k!r}")
    extra_keys = set(doc) - set(REQUIRED_KEYS)
    if extra_keys:
        problems.append(f"unknown top-level keys {sorted(extra_keys)}")
    if doc.get("status") not in _STATUSES:
        problems.append(f"status {doc.get('status')!r} not in {_STATUSES}")
    for k in ("environment", "config", "metrics", "extra"):
        if k in doc and not isinstance(doc[k], dict):
            problems.append(f"{k} is not an object")
    for k in ("phases", "probe_attempts"):
        if k in doc and not isinstance(doc[k], list):
            problems.append(f"{k} is not a list")
    for i, att in enumerate(doc.get("probe_attempts") or []):
        if not isinstance(att, dict):
            problems.append(f"probe_attempts[{i}] is not an object")
            continue
        for k in ("index", "started_at", "outcome"):
            if k not in att:
                problems.append(f"probe_attempts[{i}] missing {k!r}")
    return problems
