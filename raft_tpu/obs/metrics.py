"""Process-wide metrics: counters, gauges, histograms with labels.

Solver-health series the frequency-domain stack actually needs — drag
fixed-point iteration counts/residuals per load case, dynamics-solve
condition numbers, JAX compile events — recorded through one locked
registry and exported two ways:

- ``snapshot()``: a plain-JSON dict (embedded in run manifests);
- ``to_prometheus()``: Prometheus text exposition format (label-value
  escaping, cumulative histogram buckets, ``_sum``/``_count``).

``install_jax_hooks()`` wires JAX compile/retrace telemetry into the
registry via ``jax.monitoring`` listeners when that API exists, falling
back to polling the jit cache-miss counters where it does not.
"""
from __future__ import annotations

import math
import os
import threading

DEFAULT_BUCKETS = (0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5,
                   5.0, 10.0)
#: iteration-count shaped buckets (drag fixed points, Newton loops)
ITER_BUCKETS = (1.0, 2.0, 3.0, 4.0, 6.0, 8.0, 12.0, 16.0, 24.0, 32.0, 50.0)


def _labelkey(labels: dict) -> tuple:
    return tuple(sorted((str(k), str(v)) for k, v in labels.items()))


class _Metric:
    kind = "untyped"

    def __init__(self, name: str, help: str = ""):
        self.name = name
        self.help = help
        self._lock = threading.Lock()
        self._values: dict[tuple, float] = {}

    def _bump(self, labels: dict, amount: float, absolute: bool):
        key = _labelkey(labels)
        with self._lock:
            if absolute:
                self._values[key] = float(amount)
            else:
                self._values[key] = self._values.get(key, 0.0) + float(amount)

    def clear(self):
        """Drop every series of this metric (info-style gauges whose
        label VALUES carry the facts — build info with a per-run
        ``run_id`` — re-record instead of accumulating stale series)."""
        with self._lock:
            self._values.clear()

    def series(self) -> list[dict]:
        with self._lock:
            return [{"labels": dict(k), "value": v}
                    for k, v in sorted(self._values.items())]


class Counter(_Metric):
    kind = "counter"

    def inc(self, amount: float = 1.0, **labels):
        if amount < 0:
            raise ValueError("counters only go up")
        self._bump(labels, amount, absolute=False)


class Gauge(_Metric):
    kind = "gauge"

    def set(self, value: float, **labels):
        self._bump(labels, value, absolute=True)

    def inc(self, amount: float = 1.0, **labels):
        self._bump(labels, amount, absolute=False)


class Histogram(_Metric):
    kind = "histogram"

    def __init__(self, name: str, help: str = "", buckets=DEFAULT_BUCKETS):
        super().__init__(name, help)
        self.buckets = tuple(sorted(float(b) for b in buckets))
        # per label set: [bucket_counts..., +Inf count is implicit via n]
        self._hist: dict[tuple, dict] = {}

    def observe(self, value: float, **labels):
        value = float(value)
        key = _labelkey(labels)
        with self._lock:
            h = self._hist.get(key)
            if h is None:
                h = self._hist[key] = {
                    "counts": [0] * len(self.buckets), "sum": 0.0, "n": 0}
            for i, b in enumerate(self.buckets):
                if value <= b:
                    h["counts"][i] += 1
            h["sum"] += value
            h["n"] += 1

    def observe_many(self, values, **labels):
        for v in values:
            self.observe(v, **labels)

    def series(self) -> list[dict]:
        with self._lock:
            out = []
            for key, h in sorted(self._hist.items()):
                cum = {}
                running = 0
                for i, b in enumerate(self.buckets):
                    # counts[] is already cumulative per bucket boundary
                    running = h["counts"][i]
                    cum[_fmt_float(b)] = running
                cum["+Inf"] = h["n"]
                out.append({"labels": dict(key), "count": h["n"],
                            "sum": h["sum"], "buckets": cum})
            return out


def _fmt_float(v: float) -> str:
    if v == math.inf:
        return "+Inf"
    if float(v).is_integer():
        return str(float(v))
    return repr(float(v))


def _escape_label(v: str) -> str:
    return (str(v).replace("\\", "\\\\").replace('"', '\\"')
            .replace("\n", "\\n"))


def _escape_help(v: str) -> str:
    return str(v).replace("\\", "\\\\").replace("\n", "\\n")


def _labelstr(labels: dict, extra: dict = None) -> str:
    items = dict(labels)
    if extra:
        items.update(extra)
    if not items:
        return ""
    body = ",".join(f'{k}="{_escape_label(v)}"'
                    for k, v in sorted(items.items()))
    return "{" + body + "}"


class MetricsRegistry:
    def __init__(self):
        self._lock = threading.Lock()
        self._metrics: dict[str, _Metric] = {}

    def _get(self, cls, name, help, **kw):
        with self._lock:
            m = self._metrics.get(name)
            if m is None:
                m = self._metrics[name] = cls(name, help, **kw)
            elif not isinstance(m, cls):
                raise TypeError(
                    f"metric {name!r} already registered as {m.kind}")
            return m

    def counter(self, name: str, help: str = "") -> Counter:
        return self._get(Counter, name, help)

    def gauge(self, name: str, help: str = "") -> Gauge:
        return self._get(Gauge, name, help)

    def histogram(self, name: str, help: str = "",
                  buckets=DEFAULT_BUCKETS) -> Histogram:
        return self._get(Histogram, name, help, buckets=buckets)

    def reset(self):
        with self._lock:
            self._metrics.clear()

    def snapshot(self) -> dict:
        """JSON-able {name: {kind, help, series}} of everything recorded."""
        with self._lock:
            metrics = list(self._metrics.values())
        return {m.name: {"kind": m.kind, "help": m.help,
                         "series": m.series()} for m in metrics}

    def to_prometheus(self) -> str:
        """Prometheus text exposition format (version 0.0.4)."""
        with self._lock:
            metrics = list(self._metrics.values())
        lines = []
        for m in metrics:
            if m.help:
                lines.append(f"# HELP {m.name} {_escape_help(m.help)}")
            lines.append(f"# TYPE {m.name} {m.kind}")
            if isinstance(m, Histogram):
                for s in m.series():
                    labels = s["labels"]
                    for le, c in s["buckets"].items():
                        lines.append(
                            f"{m.name}_bucket"
                            f"{_labelstr(labels, {'le': le})} {c}")
                    lines.append(f"{m.name}_sum{_labelstr(labels)} "
                                 f"{_fmt_value(s['sum'])}")
                    lines.append(f"{m.name}_count{_labelstr(labels)} "
                                 f"{s['count']}")
            else:
                for s in m.series():
                    lines.append(f"{m.name}{_labelstr(s['labels'])} "
                                 f"{_fmt_value(s['value'])}")
        return "\n".join(lines) + ("\n" if lines else "")


def _fmt_value(v: float) -> str:
    f = float(v)
    if f.is_integer() and abs(f) < 1e15:
        return str(int(f))
    return repr(f)


#: the process-wide registry every raft_tpu component records into
REGISTRY = MetricsRegistry()


def counter(name: str, help: str = "") -> Counter:
    return REGISTRY.counter(name, help)


def gauge(name: str, help: str = "") -> Gauge:
    return REGISTRY.gauge(name, help)


def histogram(name: str, help: str = "", buckets=DEFAULT_BUCKETS) -> Histogram:
    return REGISTRY.histogram(name, help, buckets=buckets)


def snapshot() -> dict:
    return REGISTRY.snapshot()


def counter_total(name: str) -> float:
    """Summed value across one counter's series (0.0 when unrecorded) —
    the per-run baselining hook for process-cumulative counters."""
    m = REGISTRY.snapshot().get(name) or {}
    return float(sum(s.get("value", 0.0) for s in m.get("series", [])))


def to_prometheus() -> str:
    return REGISTRY.to_prometheus()


def record_build_info(run_id: str = None) -> dict:
    """Info-style ``raft_tpu_build_info`` gauge (value 1, facts as
    labels: git SHA, dirty working tree, package and jax versions, plus
    the PROCESS identity — ``pid``/``hostname`` and, when given, the
    active ``run_id``) so every scraped metrics page / embedded
    manifest snapshot is attributable to a commit AND disambiguable in
    multi-process scrapes (pod-scale runs scrape many workers into one
    Prometheus).  Exactly one series exists at a time: re-recording
    clears the previous one instead of accumulating per-run series.
    Returns the label dict."""
    import socket

    from raft_tpu.obs.manifest import git_dirty, git_sha

    labels = {"git_sha": git_sha() or "unknown",
              "pid": str(os.getpid()),
              "hostname": socket.gethostname()}
    if run_id:
        labels["run_id"] = str(run_id)
    dirty = git_dirty()
    labels["dirty"] = "unknown" if dirty is None else str(dirty).lower()
    try:
        import raft_tpu
        labels["version"] = getattr(raft_tpu, "__version__", "unknown")
    except Exception:                            # pragma: no cover
        labels["version"] = "unknown"
    try:
        import jax
        labels["jax_version"] = jax.__version__
    except Exception:
        labels["jax_version"] = "unavailable"
    g = gauge("raft_tpu_build_info",
              "build/commit identity and process identity of the "
              "running raft_tpu (info-style gauge, always 1)")
    g.clear()
    g.set(1.0, **labels)
    return labels


def exposition(run_id: str = None) -> str:
    """The Prometheus text page with a process-identity header comment
    (pid, hostname, optional run id) ahead of the samples — so a
    multi-process scrape (or a saved page) identifies its producer even
    before the ``raft_tpu_build_info`` sample.  Comment lines that are
    not HELP/TYPE are legal exposition-format noise to every parser."""
    import socket

    head = (f"# raft_tpu exposition pid={os.getpid()} "
            f"hostname={socket.gethostname()}")
    if run_id:
        head += f" run_id={run_id}"
    return head + "\n" + to_prometheus()


def record_solve_dispatch(backend: str, n, batch_elems, fused: bool = False):
    """Count a solve-backend dispatch decision (made at trace time by
    ``ops.linalg``): which kernel (``pallas_fused`` / ``pallas_gj`` /
    ``jnp_gj`` / ``lu``) was chosen for a real-embedded system of size
    ``n``.  Batch size travels as a gauge, not a label, to keep the
    series cardinality bounded."""
    counter("raft_solve_dispatch_total",
            "solve-backend dispatch decisions at trace time, by backend "
            "and real-embedded system size").inc(
        1.0, backend=str(backend), n=str(int(n)),
        fused=str(bool(fused)).lower())
    gauge("raft_solve_dispatch_batch_elems",
          "batch elements of the most recent solve dispatch per backend",
          ).set(float(batch_elems), backend=str(backend))


def record_disk_bytes(component: str, nbytes) -> None:
    """Set the per-component persistence disk gauge
    (``raft_tpu_disk_bytes{component}``: journal / resultstore /
    checkpoint / exec_cache) — the storage-fault ladder's operator
    surface (docs/robustness.md "Preemption & storage").  Components
    are a small fixed vocabulary, so the series cardinality stays
    bounded."""
    gauge("raft_tpu_disk_bytes",
          "bytes held on disk per persistence component (journal / "
          "resultstore / checkpoint / exec_cache)").set(
              float(nbytes), component=str(component))


def record_solve_health(phase: str, residual_max, residual_med,
                        nonfinite_lanes, cond_max=None,
                        iters_max=None) -> None:
    """Publish one batch's solve-health summary (the opt-in
    ``RAFT_TPU_HEALTH=1`` hot-path telemetry): worst/median per-lane
    relative residual ``‖Z·Xi − F‖/‖F‖``, the count of lanes whose
    response went non-finite, and optionally the impedance conditioning
    proxy and drag fixed-point iteration ceiling.  ``phase`` is the
    producing pipeline (``sweep`` / ``serve`` / ``optimize``) — a small
    fixed vocabulary, so series cardinality stays bounded."""
    gauge("raft_tpu_solve_residual_rel",
          "per-batch relative residual of the batched RAO solve "
          "(max/median over lanes; health mode only)").set(
              float(residual_max), phase=str(phase), stat="max")
    gauge("raft_tpu_solve_residual_rel",
          "per-batch relative residual of the batched RAO solve "
          "(max/median over lanes; health mode only)").set(
              float(residual_med), phase=str(phase), stat="median")
    gauge("raft_tpu_solve_nonfinite_lanes",
          "lanes of the last batch whose response was non-finite "
          "(health mode only)").set(
              float(nonfinite_lanes), phase=str(phase))
    if cond_max is not None:
        gauge("raft_tpu_solve_condition_max",
              "max conditioning proxy of the batched impedance over "
              "lanes and frequencies (health mode only)").set(
                  float(cond_max), phase=str(phase))
    if iters_max is not None:
        gauge("raft_tpu_solve_drag_iters_max",
              "max drag fixed-point iterations over the batch "
              "(health mode only)").set(
                  float(iters_max), phase=str(phase))


def record_devprof(facts: dict) -> None:
    """Publish one compiled program's device profile
    (``obs.devprof``): compile seconds, roofline arithmetic intensity,
    buffer bytes and the device-memory watermark delta, all labeled by
    kernel name (one series per AOT program — bounded)."""
    kernel = str(facts.get("kernel", "kernel"))
    if facts.get("compile_s") is not None:
        gauge("raft_tpu_devprof_compile_seconds",
              "wall seconds spent compiling the program (AOT "
              "lower→compile at the exec-cache miss)").set(
                  float(facts["compile_s"]), kernel=kernel)
    if facts.get("arithmetic_intensity") is not None:
        gauge("raft_tpu_devprof_arithmetic_intensity",
              "static-HLO flops / bytes_accessed of the program "
              "(roofline x-axis)").set(
                  float(facts["arithmetic_intensity"]), kernel=kernel)
    for key, help in (("argument_bytes", "argument buffer bytes of the "
                       "compiled program (memory_analysis)"),
                      ("output_bytes", "output buffer bytes of the "
                       "compiled program (memory_analysis)"),
                      ("temp_bytes", "temporary buffer bytes of the "
                       "compiled program (memory_analysis)")):
        if facts.get(key) is not None:
            gauge(f"raft_tpu_devprof_{key}", help).set(
                float(facts[key]), kernel=kernel)
    if facts.get("peak_bytes_delta") is not None:
        gauge("raft_tpu_devprof_peak_bytes_delta",
              "device allocator peak-watermark growth across the "
              "compile (absent on CPU)").set(
                  float(facts["peak_bytes_delta"]), kernel=kernel)


def record_exec_cache_event(event: str):
    """Count a persistent executable-cache event (hit/miss/store/error),
    from ``parallel.exec_cache`` — also streamed to the flight recorder
    so warm-start behavior is visible in a tailed run."""
    counter("raft_exec_cache_events_total",
            "persistent executable cache events (hit / miss / store / "
            "error)").inc(1.0, event=str(event))
    from raft_tpu.obs import events as _events
    _events.emit("exec_cache", event=str(event))


# ---------------------------------------------------------------------------
# JAX compile/retrace telemetry
# ---------------------------------------------------------------------------

_JAX_HOOKS = {"installed": False, "mode": None}
_HOOK_LOCK = threading.Lock()


def install_jax_hooks() -> str:
    """Wire JAX compile/retrace telemetry into the registry (idempotent).

    Preferred: ``jax.monitoring`` event listeners — every recorded event
    (``/jax/core/compile`` etc.) increments
    ``raft_jax_events_total{event=...}`` and duration events accumulate
    into ``raft_jax_event_duration_seconds_total``.  Fallback when that
    API is missing: ``sample_jit_cache()`` polls jit cache hit/miss
    counts into gauges.  Returns the active mode string.
    """
    with _HOOK_LOCK:
        if _JAX_HOOKS["installed"]:
            return _JAX_HOOKS["mode"]
        mode = "unavailable"
        try:
            from jax import monitoring

            # the counters are resolved through the registry on EVERY
            # event (not captured at install time) so telemetry survives
            # a REGISTRY.reset() between runs — the listeners themselves
            # cannot be uninstalled
            def _on_event(event, *a, **kw):
                counter(
                    "raft_jax_events_total",
                    "JAX monitoring events (compiles, retraces) by "
                    "event name").inc(1.0, event=event)

            def _on_duration(event, duration=0.0, *a, **kw):
                try:
                    counter(
                        "raft_jax_event_duration_seconds_total",
                        "Cumulative duration of JAX monitoring duration "
                        "events").inc(float(duration), event=event)
                except (TypeError, ValueError):    # pragma: no cover
                    pass

            monitoring.register_event_listener(_on_event)
            monitoring.register_event_duration_secs_listener(_on_duration)
            mode = "jax.monitoring"
        except Exception:
            mode = "jit-cache-poll"
        _JAX_HOOKS.update(installed=True, mode=mode)
        return mode


def sample_jit_cache() -> dict | None:
    """Poll jit cache hit/miss counters into gauges — the fallback
    compile-telemetry path for JAX builds without ``jax.monitoring``
    (and a cheap on-demand sample anywhere).  Returns the stats dict or
    None when no known cache-info hook exists in this JAX build."""
    try:
        import jax
        info = jax._src.pjit._infer_params_cached.cache_info()  # noqa: SLF001
    except Exception:
        try:
            import jax
            info = jax._src.pjit._create_pjit_jaxpr.cache_info()  # noqa: SLF001
        except Exception:
            return None
    stats = {"hits": int(info.hits), "misses": int(info.misses)}
    gauge("raft_jit_cache_hits",
          "jit cache hits sampled from the pjit lowering cache"
          ).set(stats["hits"])
    gauge("raft_jit_cache_misses",
          "jit cache misses (each miss is a trace+compile)"
          ).set(stats["misses"])
    return stats
