"""On-device probes: the sanctioned host-callback instrumentation channel.

The device-resident solve paths (PR 4) pull results to host only
through the counted ``obs.transfers`` exit points, whose per-case
budget is test-pinned.  That leaves no legal way to *watch* a jitted
solve from the host while it executes — per-iteration fixed-point
residuals, statics Newton trip counts, and per-lane health flags live
and die inside the compiled program.  This module is the one sanctioned
escape: :func:`probe` plants a ``jax.debug.callback`` inside traced
code that streams small diagnostic values to the host **during**
execution, feeding the metrics registry and the flight recorder
(``obs.events``) without touching the pinned transfer budget — probe
traffic is counted in its own ``raft_tpu_probe_events_total`` ledger
instead.

Knob (``raft_tpu._config.probes_mode``): ``RAFT_TPU_PROBES`` =

- ``off`` — :func:`probe` is a trace-time no-op: the compiled program
  is bit-identical to the pre-probe stack and zero probe events exist.
- ``sampled`` (default) — coarse-grained sites compile in: one sample
  per statics Newton solve, per drag fixed-point iteration, per
  adaptive-unroll chunk, per sweep batch (lane flags).
- ``full`` — everything ``sampled`` has plus any site tagged
  ``level="full"`` (reserved for high-rate diagnostics).

The mode is read at *trace* time: functions traced under one mode keep
their instrumentation until retraced (a fresh ``Model`` / process picks
up a changed knob).  Probes never alter numerics — the callback
receives copies and returns nothing — so golden-ledger gates hold with
any mode.

AOT interaction: ``jax.export`` cannot serialize host callbacks, so the
executable-cache entry points (``sweep_cases`` / ``sweep_variants``)
build their cacheable programs inside :func:`suppress` — cached sweeps
are probe-free by construction and one cache entry serves every probe
mode.  The statically enforced twin of this contract is raftlint
RTL001: ``jax.debug.callback`` / ``io_callback`` may appear in
``raft_tpu`` only in this module (``[tool.raftlint.rtl001]
probe-sanctioned``).

Like the rest of ``raft_tpu.obs``, nothing here imports jax at module
scope.
"""
from __future__ import annotations

import threading

_LEVELS = {"off": 0, "sampled": 1, "full": 2}

_LOCAL = threading.local()


def mode() -> str:
    """Active probe mode ("off" | "sampled" | "full")."""
    from raft_tpu import _config
    return _config.probes_mode()


def enabled(level: str = "sampled") -> bool:
    """Trace-time gate: would a probe at ``level`` compile in right
    now?  False inside :func:`suppress` blocks regardless of mode."""
    if getattr(_LOCAL, "suppressed", 0) > 0:
        return False
    return _LEVELS.get(mode(), 0) >= _LEVELS.get(str(level), 1)


class suppress:
    """Context manager that forces probes off for code traced inside it
    — wraps the AOT lower/export of cacheable programs, which
    ``jax.export`` could not serialize with callbacks embedded."""

    def __init__(self, why: str = ""):
        self.why = str(why)

    def __enter__(self):
        _LOCAL.suppressed = getattr(_LOCAL, "suppressed", 0) + 1
        return self

    def __exit__(self, *exc):
        _LOCAL.suppressed = max(0, getattr(_LOCAL, "suppressed", 1) - 1)
        return False


def probe(name: str, level: str = "sampled", **values):
    """Stream ``values`` (scalars or small arrays) out of traced code.

    Call this *inside* jitted / ``lax``-transformed functions; at trace
    time it either compiles to nothing (knob below ``level``) or plants
    an unordered ``jax.debug.callback`` whose host half records the
    sample:

    - ``raft_tpu_probe_events_total{probe}`` counts every arrival (the
      probe channel's own budget — the pinned ``obs.transfers``
      host-transfer budget is untouched);
    - scalar values land in ``raft_tpu_probe_value{probe,field}``;
    - the full sample is appended to the flight recorder as a
      ``probe`` event when one is active.

    The callback is unordered: samples may arrive out of program order
    (the flight recorder's ``seq``/``t`` stamp arrival, not issue).
    Never raises and never changes the computation's values.
    """
    if not enabled(level):
        return
    import jax

    def _sink(**host_values):
        _record(name, host_values)

    try:
        jax.debug.callback(_sink, ordered=False, **values)
    # an unprobeable context (e.g. a transform debug.callback does not
    # support) must degrade to "no sample", never to a failed solve
    except Exception:  # pragma: no cover  # raftlint: disable=RTL004
        pass


def _summarize(v):
    """Host-side payload shaping: scalars pass through, small arrays
    become lists, large arrays become {n, finite, min, max} summaries."""
    import numpy as np

    arr = np.asarray(v)
    if arr.ndim == 0:
        return arr.item()
    if arr.size <= 32:
        return arr.tolist()
    if np.issubdtype(arr.dtype, np.floating):
        finite_mask = np.isfinite(arr)
        finite = arr[finite_mask]
        return {"n": int(arr.size), "finite": int(finite_mask.sum()),
                "min": float(finite.min()) if finite.size else None,
                "max": float(finite.max()) if finite.size else None}
    return {"n": int(arr.size), "finite": int(arr.size),
            "min": float(arr.min()) if arr.size else None,
            "max": float(arr.max()) if arr.size else None}


def _record(name: str, host_values: dict):
    """Host half of the probe channel (runs on callback arrival)."""
    try:
        from raft_tpu.obs import events as _events
        from raft_tpu.obs import metrics as _metrics

        _metrics.counter(
            "raft_tpu_probe_events_total",
            "on-device probe samples streamed through the sanctioned "
            "jax.debug.callback channel, by probe name (the probe "
            "channel's own budget — separate from "
            "raft_tpu_host_transfers_total)").inc(1.0, probe=str(name))
        fields = {}
        for k, v in host_values.items():
            s = _summarize(v)
            fields[k] = s
            if isinstance(s, (int, float)) and not isinstance(s, bool):
                _metrics.gauge(
                    "raft_tpu_probe_value",
                    "most recent scalar value per probe field"
                    ).set(float(s), probe=str(name), field=str(k))
        _events.emit("probe", probe=str(name), values=fields)
    # the probe sink is telemetry: it must never propagate into the
    # runtime's callback machinery (which would poison the solve)
    except Exception:  # pragma: no cover  # raftlint: disable=RTL004
        pass
