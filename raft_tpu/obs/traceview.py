"""Distributed-trace assembly: one request's journey, re-read from disk.

The serving stack propagates a W3C-style trace context
(:class:`raft_tpu.obs.tracing.TraceContext`) from the router through
``SweepService.submit`` into every WAL record the request touches —
``admit`` / ``batch`` / ``ckpt`` / ``complete`` / ``fail`` each carry
the member's ``{"trace_id", "span_id", "parent_id"}`` dict, and a
checkpoint resume on a successor re-journals the inherited context as
a *child* span (same ``trace_id``, fresh ``span_id``, ``parent_id`` =
the dead host's span).  That makes the write-ahead journal itself the
trace store: a trace survives a crash + failover by construction,
with no tracing daemon in the loop.

This module is the read half — ``obsctl trace`` and the failover/
preempt soaks call it to fold one or more journal directories (and
optionally flight-recorder event files) into:

- :func:`assemble` — the span graph of one ``trace_id`` plus its
  connectivity verdict (``orphan_spans``, ``resume_links``,
  ``process_tracks``);
- :func:`chrome_trace` — a Perfetto-loadable Chrome Trace Event
  Format object: one process track per ``(run_id, pid)`` service
  lifetime, ``X`` slices for request spans, ``s``/``f`` flow arrows
  for parent links (the failover handoff renders as an arrow from the
  dead host's slice into the successor's) and batch membership;
- :func:`summary_facts` — the trend-store facts the
  ``trace_orphan_spans <= 0`` SLO rule gates on.

Connectivity verdict: every trace has exactly ONE entitled root — the
original admission (the trace's earliest span), whose ``parent_id``
is the router's (or caller's) un-journaled span.  Any *other* span
whose parent cannot be resolved inside the assembled graph is an
orphan: a break in the propagation chain.  A ``resume_link`` is a
resolved parent edge that crosses a process boundary — the failover
signature.

Pure stdlib + :mod:`raft_tpu.obs.journalio` — jax-free, importable by
``obsctl`` on a host with no accelerator runtime at all.
"""
from __future__ import annotations

import os

from raft_tpu.obs import journalio

#: the serve WAL's on-disk name (mirrors ``serve/journal.py`` — this
#: module deliberately does NOT import the serve package, whose
#: ``__init__`` pulls in jax)
JOURNAL_FILENAME = "serve.journal.jsonl"


# ---------------------------------------------------------------------------
# journal discovery + raw scan
# ---------------------------------------------------------------------------

def _parts(journal_dir: str) -> list[str]:
    """Journal files oldest-first (rotated ``.N`` parts then the live
    file) — the same fold order ``serve.journal.replay`` uses."""
    main = os.path.join(journal_dir, JOURNAL_FILENAME)
    parts = []
    i = 1
    while os.path.exists(f"{main}.{i}"):
        parts.append(f"{main}.{i}")
        i += 1
    parts.reverse()
    if os.path.exists(main):
        parts.append(main)
    return parts


def discover_journal_dirs(root: str) -> list[str]:
    """Every directory under ``root`` (inclusive) holding a serve
    journal, sorted.  Accepts either a journal directory itself or a
    soak tree (``root/primary``, ``root/mirror``,
    ``root/successor/journal``) — a failed-over trace spans several
    journals, and the assembler needs all of them."""
    root = os.path.abspath(root)
    found = set()
    for dirpath, _dirnames, filenames in os.walk(root):
        if any(f == JOURNAL_FILENAME or
               f.startswith(JOURNAL_FILENAME + ".") for f in filenames):
            found.add(dirpath)
    return sorted(found)


def scan(journal_dirs) -> list[tuple[tuple, dict]]:
    """Flatten journal directories into ``[(proc_key, record), ...]``
    in per-directory write order, where ``proc_key`` identifies the
    service lifetime that wrote the record: ``(run_id, pid)`` from the
    most recent ``begin`` header in the stream.

    ``replay()`` cannot do this — it folds ``begin`` and ``batch``
    records away, and a trace needs exactly those: the process
    identity per span and the batch membership arrows.
    """
    out = []
    for d in journal_dirs:
        proc = ("?", 0)
        for path in _parts(d):
            docs, _bad = journalio.read_counted(path, kind="serve")
            for rec in docs:
                if rec.get("type") == "begin":
                    proc = (str(rec.get("run_id", "?")),
                            int(rec.get("pid", 0) or 0))
                    continue
                out.append((proc, rec))
    return out


def trace_ids(journal_dirs) -> list[str]:
    """Distinct trace_ids in admit order across the given journals —
    how the soak/CI gate finds what to assemble without parsing
    provenance out of delivered results."""
    seen = []
    for _proc, rec in scan(journal_dirs):
        if rec.get("type") != "admit":
            continue
        tid = (rec.get("trace") or {}).get("trace_id")
        if tid and tid not in seen:
            seen.append(tid)
    return seen


# ---------------------------------------------------------------------------
# assembly: span graph + connectivity verdict
# ---------------------------------------------------------------------------

def assemble(trace_id: str, journal_dirs, events_paths=()) -> dict:
    """Fold every record carrying ``trace_id`` into a span graph::

        {"trace_id": ..., "spans": {span_id: span}, "batches": [...],
         "instants": [...], "procs": [proc_key, ...],
         "process_tracks": n, "orphan_spans": n, "roots": [span_id],
         "resume_links": n, "open_spans": n, "events_matched": n}

    A *span* is one admitted request on one service lifetime::

        {"span_id", "parent_id", "proc", "seq", "rdigest", "name",
         "t0", "t1" (None while open), "status", "phases"?}

    The primary and its synchronous mirror hold byte-identical records
    from the same writer, so spans key on ``span_id`` and duplicate
    sightings fold into one (earliest ``t0`` / latest ``t1`` win).  A
    successor's re-journaled admit carries a *fresh* child span, so a
    failover contributes a second span on a second process track,
    parented on the first — never a duplicate.
    """
    trace_id = str(trace_id)
    spans: dict[str, dict] = {}
    batches = []
    instants = []
    t_last_by_proc: dict[tuple, float] = {}

    def _span_for(ctx: dict, proc, t: float) -> dict | None:
        sid = (ctx or {}).get("span_id")
        if not sid or (ctx or {}).get("trace_id") != trace_id:
            return None
        sp = spans.get(sid)
        if sp is None:
            sp = spans[sid] = {
                "span_id": sid, "parent_id": ctx.get("parent_id"),
                "proc": proc, "seq": None, "rdigest": None,
                "name": None, "t0": float(t), "t1": None,
                "status": None,
            }
        else:
            sp["t0"] = min(sp["t0"], float(t))
            if sp["parent_id"] is None and ctx.get("parent_id"):
                sp["parent_id"] = ctx.get("parent_id")
        return sp

    for proc, rec in scan(journal_dirs):
        t = float(rec.get("t", 0.0) or 0.0)
        t_last_by_proc[proc] = max(t, t_last_by_proc.get(proc, t))
        rtype = rec.get("type")
        if rtype == "admit":
            sp = _span_for(rec.get("trace"), proc, t)
            if sp is None:
                continue
            sp["seq"] = rec.get("seq")
            sp["rdigest"] = rec.get("rdigest")
            kind = "optimize" if rec.get("opt") else "sweep"
            sp["name"] = f"{kind} seq={rec.get('seq')}"
        elif rtype in ("complete", "fail"):
            sp = _span_for(rec.get("trace"), proc, t)
            if sp is None:
                continue
            sp["t1"] = max(t, sp["t1"] or t)
            sp["status"] = ("ok" if rtype == "complete" else
                            f"fail:{str(rec.get('error', ''))[:60]}")
            if sp["seq"] is None:
                sp["seq"] = rec.get("seq")
            if sp["name"] is None:
                # replayed/deduped completion whose admit lives in a
                # journal we were not given — still a span, still
                # connective, rendered as a point slice
                sp["name"] = f"replayed seq={rec.get('seq')}"
        elif rtype == "ckpt":
            sp = _span_for(rec.get("trace"), proc, t)
            if sp is None:
                continue
            instants.append({"name": f"ckpt step={rec.get('step')}",
                             "proc": proc, "t": t,
                             "span_id": sp["span_id"],
                             "args": {"step": rec.get("step"),
                                      "cdigest": rec.get("cdigest")}})
        elif rtype == "batch":
            traces = rec.get("traces") or []
            seqs = rec.get("seqs") or []
            members = [c.get("span_id") for c in traces
                       if isinstance(c, dict)
                       and c.get("trace_id") == trace_id
                       and c.get("span_id")]
            if members:
                batches.append({"batch_id": rec.get("batch_id"),
                                "proc": proc, "t": t,
                                "mode": rec.get("mode"),
                                "seqs": seqs, "members": members})

    # open spans (journal ends mid-flight — the kill signature) render
    # to the last timestamp their process wrote
    open_spans = 0
    for sp in spans.values():
        if sp["t1"] is None:
            open_spans += 1
            sp["t1"] = t_last_by_proc.get(sp["proc"], sp["t0"])
            sp["status"] = sp["status"] or "open"

    # flight-recorder instants (watchdog/warm-start/ckpt/shed exemplars
    # carry trace_id; batch-scoped events carry a trace_ids list)
    events_matched = 0
    for path in events_paths or ():
        eproc = ("events", 0)
        for e in journalio.read(path):
            if e.get("type") == "begin":
                eproc = (str(e.get("run_id", "events")),
                         int(e.get("pid", 0) or 0))
                continue
            tids = e.get("trace_ids")
            if isinstance(tids, str):
                tids = tids.split(",")
            hit = (e.get("trace_id") == trace_id
                   or (isinstance(tids, (list, tuple))
                       and trace_id in tids))
            if not hit:
                continue
            events_matched += 1
            args = {k: v for k, v in e.items()
                    if k not in ("seq", "t", "type")}
            instants.append({"name": str(e.get("type")), "proc": eproc,
                             "t": float(e.get("t", 0.0) or 0.0),
                             "span_id": None, "args": args})

    procs = sorted({sp["proc"] for sp in spans.values()})
    roots = [sid for sid, sp in spans.items()
             if not sp["parent_id"] or sp["parent_id"] not in spans]
    # the EARLIEST span is entitled to an out-of-WAL parent (the
    # router's / caller's span is never journaled); every other
    # unresolved root is a break in the propagation chain
    earliest = (min(spans.values(),
                    key=lambda s: (s["t0"], s["span_id"]))["span_id"]
                if spans else None)
    orphans = [sid for sid in roots if sid != earliest]
    resume_links = sum(
        1 for sp in spans.values()
        if sp["parent_id"] in spans
        and spans[sp["parent_id"]]["proc"] != sp["proc"])
    return {
        "trace_id": trace_id,
        "spans": spans,
        "batches": batches,
        "instants": instants,
        "procs": procs,
        "process_tracks": len(procs),
        "roots": sorted(roots),
        "orphan_spans": len(orphans),
        "resume_links": resume_links,
        "open_spans": open_spans,
        "events_matched": events_matched,
    }


def summary_facts(assembled: dict) -> dict:
    """The trend-store facts of one assembled trace — what the
    zero-tolerance ``trace_orphan_spans`` SLO rule evaluates."""
    return {
        "trace_spans": len(assembled["spans"]),
        "trace_process_tracks": assembled["process_tracks"],
        "trace_orphan_spans": assembled["orphan_spans"],
        "trace_resume_links": assembled["resume_links"],
        "trace_open_spans": assembled["open_spans"],
    }


# ---------------------------------------------------------------------------
# Chrome Trace Event Format export
# ---------------------------------------------------------------------------

def chrome_trace(assembled: dict) -> dict:
    """Render one assembled trace as a Chrome Trace Event Format
    object (load in Perfetto / ``chrome://tracing``): one process per
    ``(run_id, pid)`` service lifetime, one ``X`` slice per span,
    ``i`` instants for checkpoints and matched flight-recorder events,
    and ``s``/``f`` flow arrows for parent links (the resume arrow
    crosses process tracks) and batch membership."""
    spans = assembled["spans"]
    ts_all = ([sp["t0"] for sp in spans.values()]
              + [i["t"] for i in assembled["instants"]]
              + [b["t"] for b in assembled["batches"]])
    t_min = min(ts_all) if ts_all else 0.0

    def us(t: float) -> float:
        return round((float(t) - t_min) * 1e6, 3)

    procs = list(assembled["procs"])
    for extra in ({i["proc"] for i in assembled["instants"]}
                  | {b["proc"] for b in assembled["batches"]}):
        if extra not in procs:
            procs.append(extra)
    pid_of = {proc: i + 1 for i, proc in enumerate(procs)}

    ev = []
    for proc in procs:
        run_id, ospid = proc
        ev.append({"ph": "M", "name": "process_name", "pid": pid_of[proc],
                   "args": {"name": f"{run_id} (pid {ospid})"}})
    for sp in sorted(spans.values(), key=lambda s: s["t0"]):
        pid = pid_of[sp["proc"]]
        tid = int(sp["seq"] if sp["seq"] is not None else 0)
        dur = max(1.0, us(sp["t1"]) - us(sp["t0"]))
        ev.append({"ph": "X", "name": sp["name"] or sp["span_id"],
                   "cat": "request", "pid": pid, "tid": tid,
                   "ts": us(sp["t0"]), "dur": dur,
                   "args": {"span_id": sp["span_id"],
                            "parent_id": sp["parent_id"],
                            "rdigest": sp["rdigest"],
                            "status": sp["status"]}})
        parent = spans.get(sp["parent_id"] or "")
        if parent is not None:
            # flow arrow parent -> child; the "s" anchor must sit
            # inside the source slice, the "f" (bp=e) inside the
            # destination
            fid = f"link:{sp['span_id']}"
            ppid = pid_of[parent["proc"]]
            ev.append({"ph": "s", "name": "handoff", "cat": "link",
                       "id": fid, "pid": ppid,
                       "tid": int(parent["seq"] or 0),
                       "ts": us(min(parent["t1"], sp["t0"]))})
            ev.append({"ph": "f", "bp": "e", "name": "handoff",
                       "cat": "link", "id": fid, "pid": pid, "tid": tid,
                       "ts": us(sp["t0"]) + 1.0})
    for b in assembled["batches"]:
        pid = pid_of[b["proc"]]
        ev.append({"ph": "i", "name": f"batch {b['batch_id']}",
                   "cat": "batch", "s": "p", "pid": pid, "tid": 0,
                   "ts": us(b["t"]),
                   "args": {"batch_id": b["batch_id"],
                            "mode": b["mode"], "seqs": b["seqs"]}})
        for sid in b["members"]:
            sp = spans.get(sid)
            if sp is None or sp["proc"] != b["proc"]:
                continue
            fid = f"batch:{b['batch_id']}:{sid}"
            ev.append({"ph": "s", "name": "batched", "cat": "batch",
                       "id": fid, "pid": pid,
                       "tid": int(sp["seq"] or 0),
                       "ts": us(max(sp["t0"], min(b["t"], sp["t1"])))})
            ev.append({"ph": "f", "bp": "e", "name": "batched",
                       "cat": "batch", "id": fid, "pid": pid, "tid": 0,
                       "ts": us(b["t"]) + 1.0})
    for i in assembled["instants"]:
        pid = pid_of[i["proc"]]
        sp = spans.get(i["span_id"] or "")
        ev.append({"ph": "i", "name": i["name"], "cat": "event",
                   "s": "t", "pid": pid,
                   "tid": int(sp["seq"] or 0) if sp else 0,
                   "ts": us(i["t"]), "args": i["args"]})
    return {"traceEvents": ev, "displayTimeUnit": "ms",
            "otherData": {"trace_id": assembled["trace_id"],
                          "process_tracks": assembled["process_tracks"],
                          "orphan_spans": assembled["orphan_spans"],
                          "resume_links": assembled["resume_links"]}}
