"""Span-based tracing: nested wall-time spans with attributes.

The process-wide tracer records every finished span into (a) a bounded
event buffer exportable as Chrome-trace/Perfetto JSON and (b) a locked
name -> (total_seconds, calls) aggregate that subsumes the old
``utils.profiling`` flat timing registry (``timed()`` is now a shim over
``span()`` and ``timing_report()`` reads ``aggregate()``).

Usage::

    from raft_tpu import obs

    with obs.span("solveDynamics", case=3) as sp:
        ...
        sp.set(cond_max=1.2e4)          # attach attributes mid-span

    obs.export_chrome_trace("trace.json")   # load in ui.perfetto.dev

Spans nest through a thread-local stack, so concurrent host threads (the
pmapped sweep) each get their own correctly-nested stack while sharing
the global buffer/aggregate under a lock.
"""
from __future__ import annotations

import contextlib
import json
import os
import threading
import time

#: hard cap on buffered span events — a runaway sweep must not OOM the
#: host; past the cap spans still feed the aggregate but drop from the
#: Chrome-trace buffer (`dropped_spans()` reports how many)
MAX_SPANS = 200_000

_LOCK = threading.Lock()
_SPANS: list[dict] = []
_AGG: dict[str, list] = {}          # name -> [total_seconds, calls]
_DROPPED = 0
_T0 = time.perf_counter()           # trace time origin (relative us in export)
_LOCAL = threading.local()
#: optional live event sink fn(kind, payload) — the flight recorder
#: (obs.events) registers here so span open/close stream to disk as
#: they happen; exceptions are swallowed (telemetry never fails a span)
_SINK = None


def set_sink(fn):
    """Install (or clear, with None) the live span-event sink."""
    global _SINK
    _SINK = fn


def _to_sink(kind: str, payload: dict):
    sink = _SINK
    if sink is None:
        return
    try:
        sink(kind, payload)
    # the sink is best-effort telemetry; a failing recorder must never
    # break the span protocol around solver code
    except Exception:  # pragma: no cover  # raftlint: disable=RTL004
        pass


def _stack() -> list:
    st = getattr(_LOCAL, "stack", None)
    if st is None:
        st = _LOCAL.stack = []
    return st


def _jsonable(v):
    """Best-effort JSON-safe conversion for span attributes (numpy and
    jax scalars become Python numbers, everything else falls back to str)."""
    if v is None or isinstance(v, (bool, int, float, str)):
        return v
    try:
        import numpy as np
        if isinstance(v, np.generic):
            return v.item()
    except ImportError:                      # pragma: no cover
        pass
    try:
        return float(v)
    except (TypeError, ValueError):
        return str(v)


class ActiveSpan:
    """Handle yielded by ``span()``: carries the name/attrs and accepts
    late attributes via ``set(**attrs)`` while the span is open."""

    __slots__ = ("name", "attrs", "t0", "depth", "parent")

    def __init__(self, name: str, attrs: dict):
        self.name = name
        self.attrs = {k: _jsonable(v) for k, v in attrs.items()}
        self.t0 = 0.0
        self.depth = 0
        self.parent = None

    def set(self, **attrs):
        for k, v in attrs.items():
            self.attrs[k] = _jsonable(v)
        return self


@contextlib.contextmanager
def span(name: str, **attrs):
    """Open a nested, attributed wall-time span around a code block."""
    global _DROPPED
    sp = ActiveSpan(name, attrs)
    stack = _stack()
    sp.parent = stack[-1].name if stack else None
    sp.depth = len(stack)
    stack.append(sp)
    sp.t0 = time.perf_counter()
    if _SINK is not None:
        _to_sink("span_open", {
            "name": name, "ts": sp.t0 - _T0,
            "tid": threading.get_ident(), "depth": sp.depth,
            "parent": sp.parent, "attrs": dict(sp.attrs)})
    try:
        yield sp
    finally:
        dur = time.perf_counter() - sp.t0
        if stack and stack[-1] is sp:
            stack.pop()
        event = {
            "name": name,
            "ts": sp.t0 - _T0,
            "dur": dur,
            "tid": threading.get_ident(),
            "depth": sp.depth,
            "parent": sp.parent,
            "attrs": dict(sp.attrs),
        }
        with _LOCK:
            entry = _AGG.setdefault(name, [0.0, 0])
            entry[0] += dur
            entry[1] += 1
            if len(_SPANS) < MAX_SPANS:
                _SPANS.append(event)
            else:
                _DROPPED += 1
        _to_sink("span_close", event)


def current_span() -> ActiveSpan | None:
    """The innermost open span on this thread, or None."""
    stack = _stack()
    return stack[-1] if stack else None


def spans() -> list[dict]:
    """Snapshot of the finished-span buffer (oldest first)."""
    with _LOCK:
        return [dict(e) for e in _SPANS]


def dropped_spans() -> int:
    with _LOCK:
        return _DROPPED


def aggregate(reset: bool = False) -> dict:
    """{name: (total_seconds, calls)} across all finished spans."""
    with _LOCK:
        out = {k: tuple(v) for k, v in _AGG.items()}
        if reset:
            _AGG.clear()
    return out


def reset():
    """Clear the span buffer AND the aggregate (open spans unaffected)."""
    global _DROPPED
    with _LOCK:
        _SPANS.clear()
        _AGG.clear()
        _DROPPED = 0


def chrome_trace() -> dict:
    """The finished spans as a Chrome Trace Event Format object
    (``{"traceEvents": [...]}``, "X" complete events, microsecond
    timestamps) — loadable in ui.perfetto.dev or chrome://tracing."""
    pid = os.getpid()
    events = []
    for e in spans():
        events.append({
            "name": e["name"],
            "cat": "raft_tpu",
            "ph": "X",
            "ts": e["ts"] * 1e6,
            "dur": e["dur"] * 1e6,
            "pid": pid,
            "tid": e["tid"],
            "args": e["attrs"],
        })
    return {"traceEvents": events, "displayTimeUnit": "ms"}


def export_chrome_trace(path: str) -> str:
    """Write ``chrome_trace()`` as JSON; returns the path."""
    d = os.path.dirname(os.path.abspath(path))
    os.makedirs(d, exist_ok=True)
    with open(path, "w") as f:
        json.dump(chrome_trace(), f)
    return path


# ---------------------------------------------------------------------------
# distributed trace context (request identity across processes)
# ---------------------------------------------------------------------------

#: HTTP header carrying the context across the router -> replica hop
TRACE_HEADER = "X-Raft-Trace"

_HEX = set("0123456789abcdef")


def _is_hex_id(s, n: int) -> bool:
    return (isinstance(s, str) and len(s) == n and set(s) <= _HEX
            and set(s) != {"0"})


class TraceContext:
    """W3C-traceparent-style request identity: a 128-bit ``trace_id``
    shared by every hop of one request's journey, a 64-bit ``span_id``
    naming the current hop, and the ``parent_id`` of the hop that spawned
    it.  Immutable by convention; derive hops with :meth:`child`.

    The wire form (``to_header`` / ``parse``) is the W3C ``traceparent``
    layout ``00-<trace_id>-<span_id>-01``; a bare ``<trace_id>-<span_id>``
    pair is accepted too.  Anything malformed parses to ``None`` — the
    caller mints a fresh context instead of propagating garbage.

    Allocation-only on the hot path: minting draws 24 random bytes and
    builds three strings; nothing is locked, written, or signalled.
    """

    __slots__ = ("trace_id", "span_id", "parent_id")

    def __init__(self, trace_id: str, span_id: str, parent_id: str = None):
        self.trace_id = trace_id
        self.span_id = span_id
        self.parent_id = parent_id

    @classmethod
    def mint(cls) -> "TraceContext":
        """A fresh root context (new trace_id, no parent)."""
        return cls(os.urandom(16).hex(), os.urandom(8).hex())

    def child(self) -> "TraceContext":
        """The next hop: same trace, fresh span, parented on this one."""
        return TraceContext(self.trace_id, os.urandom(8).hex(),
                            parent_id=self.span_id)

    @classmethod
    def parse(cls, header) -> "TraceContext | None":
        """Parse a ``TRACE_HEADER`` value; None when malformed."""
        if not isinstance(header, str):
            return None
        parts = header.strip().lower().split("-")
        if len(parts) == 4 and parts[0] == "00":    # full traceparent
            parts = parts[1:3]
        if len(parts) != 2:
            return None
        tid, sid = parts
        if not (_is_hex_id(tid, 32) and _is_hex_id(sid, 16)):
            return None
        return cls(tid, sid)

    @classmethod
    def from_header(cls, header) -> "TraceContext":
        """Parse, or mint a fresh root on a missing/malformed header."""
        return cls.parse(header) or cls.mint()

    def to_header(self) -> str:
        return f"00-{self.trace_id}-{self.span_id}-01"

    def as_dict(self) -> dict:
        d = {"trace_id": self.trace_id, "span_id": self.span_id}
        if self.parent_id:
            d["parent_id"] = self.parent_id
        return d

    @classmethod
    def from_dict(cls, d) -> "TraceContext | None":
        """Rehydrate from a WAL/provenance dict; None when not a valid
        serialized context (tolerates foreign keys riding along)."""
        if not isinstance(d, dict):
            return None
        tid, sid = d.get("trace_id"), d.get("span_id")
        if not (_is_hex_id(tid, 32) and _is_hex_id(sid, 16)):
            return None
        pid = d.get("parent_id")
        return cls(tid, sid, parent_id=pid if _is_hex_id(pid, 16) else None)

    def __repr__(self):
        return (f"TraceContext({self.trace_id!r}, {self.span_id!r}, "
                f"parent_id={self.parent_id!r})")

    def __eq__(self, other):
        return (isinstance(other, TraceContext)
                and self.trace_id == other.trace_id
                and self.span_id == other.span_id
                and self.parent_id == other.parent_id)
