"""Host-transfer accounting: device→host pulls as counted, budgeted events.

The device-resident ``analyzeCases`` path (model.py) treats a host pull
the way JAX training stacks do — something that happens only at a small
set of *sanctioned exit points*, each of which goes through
:func:`device_get` here.  Every sanctioned pull is

- counted (events, arrays, bytes) against the innermost active
  accounting *phase* (:func:`phase`, nestable),
- exported to the metrics registry as
  ``raft_tpu_host_transfers_total{phase,what}`` /
  ``raft_tpu_host_transfer_bytes_total{phase}``, and
- available as a process snapshot (:func:`snapshot`) that
  ``Model.analyzeCases`` folds into the run manifest
  (``extra["host_transfers"]``) and the result ledger
  (``ledger["extra"]["host_transfers"]``).

That makes the steady-state per-case host-pull count a *pinned* number:
``tests/test_device_resident.py`` asserts the documented budget (see
docs/performance.md) and any new ``np.asarray`` sneaking onto the hot
path shows up as an uncounted slowdown — or, under :func:`guard`, as a
hard error.

:func:`guard` wraps ``jax.transfer_guard_device_to_host("disallow")``:
inside it, any implicit device→host transfer raises, while
:func:`device_get` remains legal (it re-allows around its own pull).
This is the ``jax.transfer_guard("log")``-style interception with
teeth, used by the budget test to prove the hot path has no unsanctioned
pulls.

Like the rest of ``raft_tpu.obs``, this module never imports jax at
module scope.
"""
from __future__ import annotations

import contextlib
import threading

_LOCK = threading.Lock()
#: per-phase totals: {phase: {"events": int, "arrays": int, "bytes": int}}
_PHASES: dict[str, dict] = {}
#: stack of active phase names (thread-shared: the solve path is
#: host-single-threaded; nested phases label the innermost)
_STACK: list[str] = []

_UNPHASED = "unphased"


def reset():
    """Forget all accumulated transfer accounting (test isolation)."""
    with _LOCK:
        _PHASES.clear()
        del _STACK[:]


@contextlib.contextmanager
def phase(name: str):
    """Attribute sanctioned pulls inside the block to ``name``."""
    with _LOCK:
        _STACK.append(str(name))
    try:
        yield
    finally:
        with _LOCK:
            if _STACK and _STACK[-1] == str(name):
                _STACK.pop()
            elif str(name) in _STACK:          # pragma: no cover
                _STACK.remove(str(name))


def current_phase() -> str:
    with _LOCK:
        return _STACK[-1] if _STACK else _UNPHASED


def _leaf_stats(tree) -> tuple[int, int]:
    """(arrays, bytes) over the jax array leaves of ``tree``."""
    import jax

    arrays = 0
    nbytes = 0
    for leaf in jax.tree_util.tree_leaves(tree):
        arrays += 1
        try:
            nbytes += int(leaf.nbytes)
        except (AttributeError, TypeError):
            pass
    return arrays, nbytes


def device_get(tree, what: str = "", phase: str = None):
    """Sanctioned device→host pull: ``jax.device_get`` counted as ONE
    transfer event against ``phase`` (default: the innermost active
    :func:`phase`).  Legal inside :func:`guard`.  Returns the host
    pytree (numpy leaves)."""
    import jax

    from raft_tpu.obs import metrics as _metrics

    ph = str(phase) if phase is not None else current_phase()
    arrays, nbytes = _leaf_stats(tree)
    with jax.transfer_guard_device_to_host("allow"):
        out = jax.device_get(tree)
    with _LOCK:
        rec = _PHASES.setdefault(
            ph, {"events": 0, "arrays": 0, "bytes": 0})
        rec["events"] += 1
        rec["arrays"] += arrays
        rec["bytes"] += nbytes
    _metrics.counter(
        "raft_tpu_host_transfers_total",
        "sanctioned device->host transfer events on the solve path, "
        "by accounting phase and exit point").inc(
        1.0, phase=ph, what=str(what) or "-")
    _metrics.counter(
        "raft_tpu_host_transfer_bytes_total",
        "bytes pulled device->host through sanctioned exit points"
        ).inc(float(nbytes), phase=ph)
    return out


@contextlib.contextmanager
def guard(mode: str = "disallow"):
    """Trap *unsanctioned* device→host transfers: inside the block any
    implicit transfer (``np.asarray`` on a device array, ``float(x)``,
    iteration) follows ``mode`` (``"disallow"`` raises, ``"log"`` logs —
    jax's transfer-guard semantics), while :func:`device_get` stays
    legal.  Degrades to a no-op on jax builds without the API — and is
    vacuous on the CPU backend, where device memory IS host memory and
    jax never classifies the read as a transfer (the budget there rests
    on the counted events, not the guard)."""
    import jax

    try:
        ctx = jax.transfer_guard_device_to_host(mode)
    except Exception:                                  # pragma: no cover
        ctx = contextlib.nullcontext()
    with ctx:
        yield


def snapshot() -> dict:
    """JSON-able accounting snapshot:
    ``{"total": {...}, "phases": {name: {events, arrays, bytes}}}``."""
    with _LOCK:
        phases = {k: dict(v) for k, v in sorted(_PHASES.items())}
    total = {"events": 0, "arrays": 0, "bytes": 0}
    for rec in phases.values():
        for k in total:
            total[k] += rec[k]
    return {"total": total, "phases": phases}


def delta(before: dict, after: dict) -> dict:
    """Per-phase difference of two :func:`snapshot` dicts — the
    accounting attributable to one run in a process that may have run
    others before it."""
    out = {"total": {}, "phases": {}}
    for ph, rec in after.get("phases", {}).items():
        prev = before.get("phases", {}).get(ph, {})
        d = {k: rec[k] - prev.get(k, 0) for k in rec}
        if any(d.values()):
            out["phases"][ph] = d
    for k in after.get("total", {}):
        out["total"][k] = (after["total"][k]
                           - before.get("total", {}).get(k, 0))
    return out


def counts(phase: str = None) -> dict:
    """One phase's totals (zeros when it never pulled)."""
    with _LOCK:
        rec = _PHASES.get(str(phase) if phase else _UNPHASED)
        return dict(rec) if rec else {"events": 0, "arrays": 0, "bytes": 0}
