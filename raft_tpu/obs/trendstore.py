"""Persistent run-history trend store (SQLite) + declarative SLO rules.

``obsctl trend`` originally re-scanned a directory of JSON artifacts on
every invocation — fine for a dozen bench rounds, useless as the
durable substrate for SLO reporting (ROADMAP item 1 needs
admission/backpressure decisions driven by run history).  This module
replaces that model with one SQLite file every instrumented entry point
appends to: ``obs.finish_run`` folds each finished manifest into a row
(identity columns + a flat JSON ``facts`` blob of the SLO-relevant
scalars extracted by :func:`facts_from_manifest`), and ``obsctl
slo``/``serve`` read it back.

Location: ``RAFT_TPU_TREND_DB`` names the database file explicitly;
otherwise it defaults to ``<obs out_dir>/trend.sqlite`` whenever an obs
output directory is configured (no out dir, no store — same opt-in
stance as every other obs artifact).  ``RAFT_TPU_TREND=0`` disables
appends outright.  Every write is best-effort: a locked or unwritable
database must never take down the run it is recording.

SLO rules are plain JSON (see :data:`DEFAULT_SLO_RULES`)::

    {"name": "warm_s_per_case_p50",     # report label
     "kind": "analyzeCases",            # manifest kind filter
     "fact": "s_per_case",              # facts key (numeric)
     "agg": "p50",                      # p50|p90|mean|max|min|last|sum|
                                        #   count|ratio (ratio needs
                                        #   "denom": other facts key)
     "op": "<=", "threshold": 120.0,    # the gate
     "window": 20,                      # newest N qualifying runs
     "status": "ok"}                    # row status filter (default ok)

:func:`evaluate_slo` runs a rule list over trend rows and returns a
structured report with a single ``ok`` verdict — ``obsctl slo`` turns
that into an exit code for CI.  Rules with no qualifying data are
*skipped*, not failed (a fresh checkout must not fail its first gate),
unless the rule says ``"required": true``.

Stdlib only (sqlite3/json) — never imports jax; safe on a wedged host.
"""
from __future__ import annotations

import json
import math
import os
import sqlite3

SCHEMA_VERSION = 1

_DDL = """
CREATE TABLE IF NOT EXISTS runs (
    run_id      TEXT PRIMARY KEY,
    kind        TEXT,
    status      TEXT,
    started_at  TEXT,
    finished_at TEXT,
    duration_s  REAL,
    git_sha     TEXT,
    hostname    TEXT,
    pid         INTEGER,
    facts       TEXT
);
CREATE INDEX IF NOT EXISTS runs_kind ON runs (kind, started_at);
"""


def enabled() -> bool:
    """Trend-store appends active?  ``RAFT_TPU_TREND=0`` disables."""
    return os.environ.get("RAFT_TPU_TREND", "1").strip() != "0"


def db_path() -> str | None:
    """Active database path: ``RAFT_TPU_TREND_DB``, else
    ``<obs out_dir>/trend.sqlite`` when an obs dir is configured, else
    None (store disabled)."""
    if not enabled():
        return None
    explicit = os.environ.get("RAFT_TPU_TREND_DB")
    if explicit:
        return explicit
    from raft_tpu import obs
    d = obs.out_dir()
    return os.path.join(d, "trend.sqlite") if d else None


# ---------------------------------------------------------------------------
# facts extraction
# ---------------------------------------------------------------------------

def _num(v):
    if isinstance(v, bool):
        return int(v)
    if isinstance(v, (int, float)) and math.isfinite(float(v)):
        return v
    return None


def facts_from_manifest(doc: dict) -> dict:
    """Flatten one run manifest to the scalar facts the SLO rules gate
    on.  Missing structure yields missing facts, never errors — rules
    simply skip runs that lack their fact."""
    facts: dict = {}
    extra = doc.get("extra") or {}
    config = doc.get("config") or {}
    dur = _num(doc.get("duration_s"))
    n_cases = _num(config.get("nCases") if "nCases" in config
                   else config.get("ncases"))
    if n_cases is not None:
        facts["cases_total"] = n_cases
    if dur is not None:
        facts["duration_s"] = dur
        if n_cases:
            facts["s_per_case"] = dur / n_cases
    failed = extra.get("failed_cases")
    if isinstance(failed, list):
        facts["cases_failed"] = len(failed)
    quar = extra.get("quarantine") or {}
    if isinstance(quar.get("quarantined"), list):
        facts["quarantined_lanes"] = len(quar["quarantined"])
    resumed = extra.get("resumed_cases")
    if isinstance(resumed, list):
        facts["cases_resumed"] = len(resumed)
    attempts = (extra.get("recovery") or {}).get("attempts")
    if isinstance(attempts, list):
        facts["recovery_attempts"] = len(attempts)
        facts["recovery_recovered"] = sum(
            1 for a in attempts if a.get("outcome") == "recovered")
    xfers = extra.get("host_transfers") or {}
    total = (xfers.get("total") or {}).get("events")
    if _num(total) is not None:
        facts["transfer_events"] = total
    for ph, per in (xfers.get("per_case") or {}).items():
        if _num(per) is not None:
            facts[f"transfers_per_case_{ph}"] = per
    cache_state = (extra.get("exec_cache") or {}).get("state")
    if cache_state:
        facts["exec_cache_warm"] = int(cache_state == "hit")
    # mesh topology facts (parallel/partition.py): sweep manifests carry
    # them in config["mesh"], partitioned analyzeCases runs too; the
    # ordered-axes string lets `obsctl trend --db` show WHICH 2-D layout
    # a run used, not just how many devices it spanned
    mesh = config.get("mesh") or (extra.get("partition") or {}).get("mesh")
    if isinstance(mesh, dict):
        if _num(mesh.get("devices")) is not None:
            facts["mesh_devices"] = mesh["devices"]
        if mesh.get("topology"):
            facts["mesh"] = str(mesh["topology"])
    res = extra.get("result") or {}
    for k in ("value", "vs_baseline", "analyze_cases_s_per_case"):
        if _num(res.get(k)) is not None:
            facts[f"result_{k}"] = res[k]
    # mixed-precision ladder facts (bench_kernels.py / ops/linalg.py):
    # the promoted-lane ratio is the SLO tripwire against a mixed
    # ladder silently degenerating to all-f64 promotion; the speedup
    # fact only lands on compiled-path rounds (interpret rows omit it)
    solver = extra.get("solver") or {}
    if isinstance(solver, dict):
        if _num(solver.get("promoted_lane_ratio")) is not None:
            facts["solve_promoted_lane_ratio"] = \
                solver["promoted_lane_ratio"]
        if (_num(solver.get("mixed_speedup_vs_f64")) is not None
                and solver.get("timing_meaningful")):
            facts["solve_mixed_speedup_vs_f64"] = \
                solver["mixed_speedup_vs_f64"]
        if solver.get("precision"):
            facts["solve_precision"] = str(solver["precision"])
    # serving-layer facts (raft_tpu/serve): one row per service
    # lifetime, gated by the serve SLO rules below
    serve = extra.get("serve") or {}
    if isinstance(serve, dict):
        for k in ("requests", "admitted", "rejected", "completed",
                  "failed", "quarantined", "retries",
                  "retried_recovered", "deadline_misses", "unhandled",
                  "batches", "abandoned_batches", "n_mode_transitions",
                  "p50_latency_s", "p99_latency_s",
                  # durability facts (serve/journal.py): present only
                  # on journaled / recovered / drained service rows,
                  # so the restart SLO rules skip ordinary runs
                  "journal_errors", "replayed", "recovered_results",
                  "deduped", "replayed_lost_count",
                  "restart_warm_start", "handoff_pending",
                  # tenancy facts (serve/tenancy.py)
                  "tenant_evictions", "tenant_rewarms",
                  # replication facts (serve/replica.py): lag/errors on
                  # every mirrored service row; failover facts only on
                  # a life that recovered from a FOREIGN mirror — the
                  # cross-host SLO rules skip ordinary rows
                  "replication_lag_records", "replication_errors",
                  "failover", "failover_lost_count",
                  # result-tier facts (serve/resultstore.py): present
                  # only on store-enabled service rows — the
                  # corrupt-served / warm-mismatch zero-tolerance SLO
                  # rules skip every store-less run
                  "store_hits", "store_hit_ratio", "read_p50_ms",
                  "read_p99_ms", "coalesced", "store_corrupt",
                  "store_entries", "store_quarantined",
                  "warm_start_seeded", "warm_start_rejected",
                  "warm_start_iter_savings",
                  "warm_start_digest_mismatch"):
            if _num(serve.get(k)) is not None:
                facts[f"serve_{k}"] = serve[k]
        if serve.get("mode"):
            facts["serve_mode"] = str(serve["mode"])
        # preemption-tolerance + storage facts (serve/checkpoint.py)
        # and learned-read-tier facts (serve/surrogate.py): unprefixed
        # names matched exactly by their SLO rules, present only on
        # checkpoint-/surrogate-enabled service rows
        for k in ("ckpt_writes", "ckpt_corrupt", "ckpt_resumes",
                  "ckpt_resumed_from_step", "ckpt_resumed",
                  "ckpt_shed", "store_shed", "disk_journal_bytes",
                  "disk_resultstore_bytes", "disk_checkpoint_bytes",
                  "surrogate_served", "surrogate_escalated",
                  "surrogate_audits", "surrogate_audit_errors",
                  "surrogate_quarantines", "surrogate_hit_ratio",
                  "surrogate_read_p50_ms", "surrogate_read_p99_ms",
                  "surrogate_bound_violation_served_count",
                  "surrogate_quarantine_miss",
                  # quarantine-drill rows (cfg.surrogate_drill): the
                  # intentional served violation trends under its own
                  # name, never the zero-tolerance fact above
                  "surrogate_drill", "surrogate_drill_violations"):
            if _num(serve.get(k)) is not None:
                facts[k] = serve[k]
        # per-request phase breakdown (service summary():
        # phase_<phase>_p50_s / phase_<phase>_p99_s) — the latency
        # decomposition `obsctl slo`/`trend` follow per phase
        for k, v in serve.items():
            if (k.startswith("phase_") and k.endswith("_s")
                    and _num(v) is not None):
                facts[f"serve_{k}"] = v
    # distributed-trace connectivity facts (obs/traceview.py — rows
    # appended by `obsctl trace --trend-db` and the failover soak):
    # unprefixed, gated by the zero-tolerance trace_orphan_spans rule
    trace = extra.get("trace") or {}
    if isinstance(trace, dict):
        for k in ("trace_spans", "trace_process_tracks",
                  "trace_orphan_spans", "trace_resume_links",
                  "trace_open_spans", "trace_count"):
            if _num(trace.get(k)) is not None:
                facts[k] = trace[k]
    # serving-throughput bench facts (bench.py serve): one row per
    # sustained-throughput run, trended by `obsctl trend --db`
    sbench = extra.get("serve_bench") or {}
    if isinstance(sbench, dict):
        for k in ("cases_per_min", "admission_p99_s", "admission_p50_s",
                  "batch_fill_ratio", "arrival_rps", "open_loop_s",
                  # dup-heavy arrival facts (RAFT_BENCH_SERVE_DUP_RATIO)
                  "dup_ratio", "store_hit_ratio", "read_p50_ms",
                  "read_p99_ms", "warm_start_iter_savings",
                  "store_corrupt_served_count",
                  "warm_start_digest_mismatch",
                  # fleet-controller input signals (serve/fleet.py
                  # thresholds are tuned against these trends)
                  "queue_depth_p50", "queue_depth_p99",
                  "quota_pressure"):
            if _num(sbench.get(k)) is not None:
                facts[f"serve_{k}"] = sbench[k]
    # learned-read-tier bench facts (bench.py surrogate): the
    # ground-truth audit row — every surrogate-served answer in the
    # bench is ALSO cold-solved, so the two zero-tolerance facts here
    # are measured against real physics, not the service's sampled
    # audit cadence
    sur = extra.get("surrogate_bench") or {}
    if isinstance(sur, dict):
        for k in ("served", "escalated", "hit_ratio", "read_p50_ms",
                  "read_p99_ms", "speedup_vs_cold", "cold_case_s",
                  "corpus_rows", "bound_rel_max", "quarantines",
                  "audited"):
            if _num(sur.get(k)) is not None:
                facts[f"surrogate_{k}"] = sur[k]
        # unprefixed: named exactly by the zero-tolerance SLO rules
        for k in ("surrogate_bound_violation_served_count",
                  "surrogate_quarantine_miss"):
            if _num(sur.get(k)) is not None:
                facts[k] = sur[k]
    # differentiable co-design facts (parallel/optimize.py +
    # bench.py optimize): descent throughput, the gradient-health
    # ratio (SLO rule: non-finite adjoints must be 0), and the
    # dense-sweep-vs-descent gate facts
    for section in ("optimize", "bench_optimize"):
        opt = extra.get(section) or {}
        if isinstance(opt, dict):
            for k in ("nlanes", "steps", "converged",
                      "grad_nonfinite", "grad_nonfinite_ratio",
                      "f_best", "iters_max", "wall_s",
                      "descents_per_min", "adjoint_s_per_step",
                      "speedup_vs_dense_sweep", "dense_points",
                      "objective_gap", "design_gap_max_spacing",
                      "argmin_match", "converged_lanes",
                      # checkpoint facts (segmented descents): the
                      # bench's segmented-vs-monolithic wall ratio and
                      # the per-run resume/write census
                      "ckpt_overhead_ratio", "checkpoint_every",
                      "resumed_from_step", "ckpt_writes", "segments",
                      "ckpt_segmented_bitwise"):
                if _num(opt.get(k)) is not None:
                    facts[f"optimize_{k}"] = opt[k]
            if opt.get("method"):
                facts["optimize_method"] = str(opt["method"])
            if opt.get("exec_cache"):
                facts["optimize_exec_cache_warm"] = int(
                    opt["exec_cache"] == "hit")
    # farm-axis facts (parallel/sweep.sweep_farm + bench.py farm): the
    # batched N-turbines x M-cases throughput row and its zero-tolerance
    # serial-parity gate (farm_parity_mismatch rule below)
    farm = extra.get("farm_bench") or extra.get("farm") or {}
    if isinstance(farm, dict):
        for k in ("turbine_cases_per_min", "serial_turbine_cases_per_min",
                  "speedup_vs_serial", "wake_iters", "wake_iters_max",
                  "n_turbines", "ncases", "parity_max_rel",
                  "nonfinite_lanes", "wall_s", "serial_lane_s",
                  "build_s"):
            if _num(farm.get(k)) is not None:
                facts[f"farm_{k}"] = farm[k]
        # unprefixed: named exactly by the SLO rule + bench fact
        if _num(farm.get("farm_parity_mismatch")) is not None:
            facts["farm_parity_mismatch"] = farm["farm_parity_mismatch"]
        if farm.get("cache_state"):
            facts["farm_exec_cache_warm"] = int(
                farm["cache_state"] == "hit")
    # preemption chaos soak facts (serve/soak.py run_preempt):
    # ground-truth resume/storage integrity measured against the clean
    # uninterrupted run — the two zero-tolerance rules below gate them
    preempt = extra.get("serve_preempt") or {}
    if isinstance(preempt, dict):
        for k in ("ckpt_resume_digest_mismatch",
                  "storage_corrupt_served_count",
                  "ckpt_resumed_from_step", "ckpt_writes",
                  "ckpt_resumes", "ckpt_corrupt", "checkpoint_every",
                  "preempt_lost", "storage_sheds"):
            if _num(preempt.get(k)) is not None:
                facts[k] = preempt[k]
    # elastic-fleet soak facts (serve/soak.py run_elastic +
    # serve/fleet.py): autoscaling ground truth — the two unprefixed
    # zero-tolerance facts are matched exactly by their SLO rules and
    # exist only on elastic-soak rows, so ordinary runs skip
    fleet = extra.get("fleet") or {}
    if isinstance(fleet, dict):
        for k in ("fleet_scale_loss_count",
                  "fleet_preempt_digest_mismatch",
                  "fleet_scale_ups", "fleet_scale_downs",
                  "fleet_preemptions", "fleet_folds",
                  "fleet_kills_injected", "fleet_handoffs",
                  "fleet_replicas_max", "fleet_ckpt_shed",
                  "fleet_resumed_from_step"):
            if _num(fleet.get(k)) is not None:
                facts[k] = fleet[k]
    # duplicate-storm soak facts (serve/soak.py run_storm): ground-truth
    # integrity counts measured against the clean reference digests
    storm = extra.get("serve_storm") or {}
    if isinstance(storm, dict):
        for k in ("solves", "coalesced", "store_hit_ratio",
                  "read_p50_ms", "read_p99_ms", "store_corrupt_detected",
                  "store_corrupt_served_count", "warm_start_seeded",
                  "warm_start_rejected", "warm_start_iter_savings",
                  "warm_start_digest_mismatch"):
            if _num(storm.get(k)) is not None:
                facts[f"serve_{k}"] = storm[k]
    # batched solve-health facts (parallel/sweep.py health mode; facts
    # exist only on RAFT_TPU_HEALTH=1 rows — default runs skip the two
    # solve-health SLO rules below)
    sh = extra.get("solve_health") or {}
    if isinstance(sh, dict):
        for k in ("residual_rel_max", "residual_rel_median", "cond_max",
                  "nonfinite_lanes", "iters_max", "lanes"):
            if _num(sh.get(k)) is not None:
                facts[f"solve_{k}"] = sh[k]
    # program-level device profile (obs/devprof.py): one fact set per
    # compiled kernel — the roofline/compile-cost series `obsctl
    # regress` trends per program
    dp = extra.get("devprof") or {}
    if isinstance(dp, dict):
        for kernel, kf in sorted(dp.items()):
            if not isinstance(kf, dict):
                continue
            for k in ("compile_s", "flops", "bytes_accessed",
                      "arithmetic_intensity", "argument_bytes",
                      "output_bytes", "temp_bytes", "peak_bytes_delta"):
                if _num(kf.get(k)) is not None:
                    facts[f"devprof_{kernel}_{k}"] = kf[k]
    # probe-channel volume (its own budget, distinct from transfers):
    # the embedded metrics snapshot is process-cumulative, so subtract
    # the baseline RunManifest.begin recorded for THIS run
    probe = (doc.get("metrics") or {}).get("raft_tpu_probe_events_total")
    if probe:
        total = sum(s.get("value", 0) for s in probe.get("series", []))
        base = _num(extra.get("probe_events_at_begin")) or 0
        facts["probe_events"] = max(0.0, total - base)
    return facts


def row_from_manifest(doc: dict) -> dict:
    env = doc.get("environment") or {}
    return {
        "run_id": doc.get("run_id"),
        "kind": doc.get("kind"),
        "status": doc.get("status"),
        "started_at": doc.get("started_at"),
        "finished_at": doc.get("finished_at"),
        "duration_s": _num(doc.get("duration_s")),
        "git_sha": env.get("git_sha"),
        "hostname": env.get("hostname"),
        "pid": env.get("pid"),
        "facts": facts_from_manifest(doc),
    }


# ---------------------------------------------------------------------------
# the store
# ---------------------------------------------------------------------------

_COLS = ("run_id", "kind", "status", "started_at", "finished_at",
         "duration_s", "git_sha", "hostname", "pid", "facts")


class TrendStore:
    """One SQLite run-history file.  Connections are opened per
    operation (short transactions, 5 s busy timeout) so a solver
    appending and an ``obsctl serve`` scraping never deadlock."""

    def __init__(self, path: str):
        self.path = str(path)
        parent = os.path.dirname(os.path.abspath(self.path))
        os.makedirs(parent, exist_ok=True)
        with self._connect() as con:
            con.executescript(_DDL)

    def _connect(self) -> sqlite3.Connection:
        con = sqlite3.connect(self.path, timeout=5.0)
        con.row_factory = sqlite3.Row
        return con

    _INSERT = (f"INSERT OR REPLACE INTO runs ({','.join(_COLS)}) "
               f"VALUES ({','.join('?' * len(_COLS))})")

    @staticmethod
    def _row_values(row: dict) -> list:
        return [row.get(c) if c != "facts"
                else json.dumps(row.get("facts") or {}) for c in _COLS]

    def append(self, manifest_doc: dict) -> dict:
        """Fold one finished manifest into the store (upsert by
        run_id).  Returns the stored row."""
        row = row_from_manifest(manifest_doc)
        with self._connect() as con:
            con.execute(self._INSERT, self._row_values(row))
        return row

    def rows(self, kind: str = None, status: str = None,
             limit: int = None) -> list[dict]:
        """Rows newest-first (by started_at, then rowid)."""
        q = "SELECT * FROM runs"
        clauses, params = [], []
        if kind:
            clauses.append("kind = ?")
            params.append(kind)
        if status:
            clauses.append("status = ?")
            params.append(status)
        if clauses:
            q += " WHERE " + " AND ".join(clauses)
        q += " ORDER BY started_at DESC, rowid DESC"
        if limit:
            q += " LIMIT ?"
            params.append(int(limit))
        with self._connect() as con:
            out = []
            for r in con.execute(q, params):
                d = dict(r)
                try:
                    d["facts"] = json.loads(d.get("facts") or "{}")
                except (TypeError, json.JSONDecodeError):
                    d["facts"] = {}
                out.append(d)
            return out

    def count(self) -> int:
        with self._connect() as con:
            return int(con.execute(
                "SELECT COUNT(*) FROM runs").fetchone()[0])

    def append_rows(self, rows: list[dict]) -> int:
        """Upsert pre-built row dicts (the ``obsctl trend --import``
        backfill path: snapshot-derived history that never had a
        manifest).  Returns rows written."""
        if rows:
            with self._connect() as con:
                con.executemany(self._INSERT,
                                [self._row_values(r) for r in rows])
        return len(rows)

    def ingest(self, paths: list[str]) -> int:
        """Load manifest JSON files and/or JSONL row files (the
        committed trend fixtures) into the store.  Returns rows added.
        Unreadable entries are skipped — ingestion is for operators and
        CI fixtures, not a validation gate."""
        rows = [row for path in paths for row in load_rows(path)]
        if rows:
            with self._connect() as con:
                con.executemany(self._INSERT,
                                [self._row_values(r) for r in rows])
        return len(rows)


def load_rows(path: str) -> list[dict]:
    """Rows from a manifest JSON file or a JSONL fixture of row dicts
    (``{"run_id", "kind", "status", ..., "facts": {...}}``)."""
    try:
        with open(path, encoding="utf-8") as f:
            text = f.read()
    except OSError:
        return []
    text = text.strip()
    if not text:
        return []
    if text.startswith("{") and "\n{" not in text:
        try:
            doc = json.loads(text)
        except json.JSONDecodeError:
            return []
        if str(doc.get("schema", "")).startswith("raft_tpu.run_manifest/"):
            return [row_from_manifest(doc)]
        return [doc] if "run_id" in doc else []
    rows = []
    for line in text.splitlines():
        line = line.strip()
        if not line:
            continue
        try:
            doc = json.loads(line)
        except json.JSONDecodeError:
            continue
        if isinstance(doc, dict) and "run_id" in doc:
            if str(doc.get("schema", "")).startswith(
                    "raft_tpu.run_manifest/"):
                doc = row_from_manifest(doc)
            rows.append(doc)
    return rows


def append_manifest(manifest_doc: dict, path: str = None) -> str | None:
    """Best-effort append of one manifest to the active store; returns
    the db path written, or None when the store is disabled/broken.
    The call ``obs.finish_run`` makes on every finished run."""
    try:
        db = path or db_path()
        if not db:
            return None
        TrendStore(db).append(manifest_doc)
        return db
    # a locked/unwritable trend db must never take down the run that
    # just finished (obs contract)
    except Exception:  # pragma: no cover  # raftlint: disable=RTL004
        return None


# ---------------------------------------------------------------------------
# SLO evaluation
# ---------------------------------------------------------------------------

#: the four gates the ISSUE names, with deliberately loose default
#: thresholds — operators tighten them per deployment via --rules
DEFAULT_SLO_RULES = [
    {"name": "warm_s_per_case_p50", "kind": "analyzeCases",
     "fact": "s_per_case", "agg": "p50", "op": "<=", "threshold": 120.0,
     "window": 20},
    {"name": "recovery_rate", "kind": "analyzeCases",
     "fact": "recovery_recovered", "denom": "recovery_attempts",
     "agg": "ratio", "op": ">=", "threshold": 0.5, "window": 50},
    {"name": "cases_failed_ratio", "kind": "analyzeCases",
     "fact": "cases_failed", "denom": "cases_total", "agg": "ratio",
     "op": "<=", "threshold": 0.25, "window": 50},
    {"name": "transfers_per_case_statics", "kind": "analyzeCases",
     "fact": "transfers_per_case_statics", "agg": "max", "op": "<=",
     "threshold": 1.0, "window": 20},
    {"name": "transfers_per_case_dynamics", "kind": "analyzeCases",
     "fact": "transfers_per_case_dynamics", "agg": "max", "op": "<=",
     "threshold": 4.0, "window": 20},
    # -- serving-layer gates (raft_tpu/serve; skipped when no serve
    # runs exist).  Thresholds match the CI chaos soak's worst case
    # with headroom; operators tighten per deployment via --rules.
    {"name": "serve_admission_reject_ratio", "kind": "serve",
     "fact": "serve_rejected", "denom": "serve_requests",
     "agg": "ratio", "op": "<=", "threshold": 0.75, "window": 20},
    {"name": "serve_retry_success_ratio", "kind": "serve",
     "fact": "serve_retried_recovered", "denom": "serve_retries",
     "agg": "ratio", "op": ">=", "threshold": 0.5, "window": 20},
    {"name": "serve_deadline_miss_count", "kind": "serve",
     "fact": "serve_deadline_misses", "agg": "max", "op": "<=",
     "threshold": 16.0, "window": 20},
    {"name": "serve_unhandled_errors", "kind": "serve",
     "fact": "serve_unhandled", "agg": "max", "op": "<=",
     "threshold": 0.0, "window": 20},
    # -- durability gates (serve/journal.py; skipped when no recovered
    # serve run exists — the facts appear only after a replay).  A
    # replayed request that never reached a terminal state is a silent
    # drop; a recovered service that re-traced instead of warm-starting
    # from the executable cache blew the restart-latency budget.
    {"name": "serve_replayed_lost_count", "kind": "serve",
     "fact": "serve_replayed_lost_count", "agg": "max", "op": "<=",
     "threshold": 0.0, "window": 20},
    {"name": "serve_restart_warm_start", "kind": "serve",
     "fact": "serve_restart_warm_start", "agg": "min", "op": "==",
     "threshold": 1.0, "window": 20},
    # -- replication gates (serve/replica.py; skipped when no mirrored
    # / failed-over serve row exists).  A failover that left a request
    # open lost an accepted request across the host boundary; a mirror
    # more than 64 records behind at summary time has outgrown the
    # synchronous-mirroring contract the zero-loss failover rests on.
    {"name": "serve_failover_lost_count", "kind": "serve",
     "fact": "serve_failover_lost_count", "agg": "max", "op": "<=",
     "threshold": 0.0, "window": 20},
    {"name": "serve_replication_lag_records", "kind": "serve",
     "fact": "serve_replication_lag_records", "agg": "max", "op": "<=",
     "threshold": 64.0, "window": 20},
    # -- result-tier gates (serve/resultstore.py; skipped when no
    # store-enabled row exists).  Both are zero-tolerance tripwires,
    # gated across EVERY kind that measures them (service audit counts,
    # the dup-heavy serve bench's ground-truth duplicate comparison,
    # and the duplicate-storm soak's clean-reference comparison): a
    # corrupt store byte delivered as a result, or a neighbor
    # warm-start that silently changed physics, is never acceptable.
    {"name": "serve_store_corrupt_served_count",
     "fact": "serve_store_corrupt_served_count", "agg": "max",
     "op": "<=", "threshold": 0.0, "window": 20},
    {"name": "serve_warm_start_digest_mismatch",
     "fact": "serve_warm_start_digest_mismatch", "agg": "max",
     "op": "<=", "threshold": 0.0, "window": 20},
    # -- learned-read-tier gates (serve/surrogate.py; facts exist only
    # on surrogate-enabled service rows and the surrogate bench's
    # ground-truth audit — ordinary runs skip).  Both zero-tolerance:
    # a surrogate answer delivered outside its calibrated bound is a
    # wrong number served as physics; a bound violation that did NOT
    # quarantine its bundle is the audit ladder failing silent.
    {"name": "surrogate_bound_violation_served_count",
     "fact": "surrogate_bound_violation_served_count", "agg": "max",
     "op": "<=", "threshold": 0.0, "window": 20},
    {"name": "surrogate_quarantine_miss",
     "fact": "surrogate_quarantine_miss", "agg": "max", "op": "<=",
     "threshold": 0.0, "window": 20},
    # -- preemption-tolerance gates (serve/checkpoint.py; facts exist
    # only on resumed / storage-fault rows — the preempt soak's
    # ground-truth comparison and checkpoint-enabled service
    # summaries — so ordinary runs skip).  Both are zero-tolerance: a
    # resumed descent whose final digest differs from the
    # uninterrupted run means the checkpoint carry lied; a corrupt
    # byte served from any store during a storage-fault wave is never
    # acceptable.
    {"name": "ckpt_resume_digest_mismatch",
     "fact": "ckpt_resume_digest_mismatch", "agg": "max", "op": "<=",
     "threshold": 0.0, "window": 20},
    {"name": "storage_corrupt_served_count",
     "fact": "storage_corrupt_served_count", "agg": "max", "op": "<=",
     "threshold": 0.0, "window": 20},
    # -- elastic-fleet gates (serve/fleet.py + soak.run_elastic; facts
    # exist only on elastic-soak rows — ordinary runs skip).  Both are
    # zero-tolerance: an accepted request lost across a scale-down
    # drain or a preemption fold means the handoff/recover composition
    # dropped work the service acknowledged; a preempted descent that
    # resumed on a survivor with a digest differing from the
    # uninterrupted reference means the fleet's checkpoint carry lied.
    {"name": "fleet_scale_loss_count",
     "fact": "fleet_scale_loss_count", "agg": "max", "op": "<=",
     "threshold": 0.0, "window": 20},
    {"name": "fleet_preempt_digest_mismatch",
     "fact": "fleet_preempt_digest_mismatch", "agg": "max", "op": "<=",
     "threshold": 0.0, "window": 20},
    # -- mixed-precision ladder gate (bench_kernels.py; skipped when no
    # mixed-ladder bench row exists).  A promoted-lane ratio near 1.0
    # means the mixed ladder silently degenerated to an all-f64
    # re-solve — paying the low-width factorization AND the full-width
    # pass on every lane; the bench's well-conditioned hot-path systems
    # should promote (far) under a quarter of their lanes.
    {"name": "solve_promoted_lane_ratio", "kind": "bench_kernels",
     "fact": "solve_promoted_lane_ratio", "agg": "max", "op": "<=",
     "threshold": 0.25, "window": 20},
    # -- differentiable co-design gradient-health gate (parallel/
    # optimize.py; fact present only on optimize/bench_optimize rows —
    # ordinary runs skip).  A single lane whose adjoint goes non-finite
    # is frozen + counted, never fatal; ANY non-zero ratio on a healthy
    # benchmark model means the implicit-diff plumbing regressed.
    {"name": "optimize_grad_nonfinite_ratio",
     "fact": "optimize_grad_nonfinite_ratio", "agg": "max", "op": "<=",
     "threshold": 0.0, "window": 20},
    # -- batched solve-health gates (parallel/sweep.py health mode;
    # facts exist only on RAFT_TPU_HEALTH=1 rows — default runs skip).
    # Zero tolerance on non-finite lanes: a lane whose response went
    # NaN/Inf past the quarantine ladder is never acceptable on a
    # healthy model.  The residual bound is loose against f64 solver
    # accuracy (~1e-15 on OC3) but far below any physically-meaningful
    # drift — a residual above it means the linear solve itself (not
    # the drag model) degraded.
    {"name": "solve_nonfinite_lanes",
     "fact": "solve_nonfinite_lanes", "agg": "max", "op": "<=",
     "threshold": 0.0, "window": 20},
    {"name": "solve_residual_rel_max", "kind": "sweep_cases",
     "fact": "solve_residual_rel_max", "agg": "max", "op": "<=",
     "threshold": 1e-6, "window": 20},
    # -- farm-axis parity gate (bench.py farm; fact present only on
    # bench_farm rows — ordinary runs skip).  Zero-tolerance: a lane of
    # the batched N-turbines x M-cases program whose response std
    # disagrees with the serial per-turbine path beyond solver
    # tolerance means the farm axis changed physics — a faster wrong
    # number is never a result.
    {"name": "farm_parity_mismatch",
     "fact": "farm_parity_mismatch", "agg": "max", "op": "<=",
     "threshold": 0.0, "window": 20},
    # -- distributed-tracing gate (obs/traceview.py; fact present only
    # on rows appended by `obsctl trace --trend-db` / the failover
    # soak — ordinary runs skip).  Zero-tolerance: an orphan span is a
    # request whose trace context broke somewhere between the router,
    # the WAL, and a failover successor — the propagation chain the
    # whole tracing design guarantees by construction.
    {"name": "trace_orphan_spans",
     "fact": "trace_orphan_spans", "agg": "max", "op": "<=",
     "threshold": 0.0, "window": 20},
]

_OPS = {
    "<=": lambda v, t: v <= t,
    "<": lambda v, t: v < t,
    ">=": lambda v, t: v >= t,
    ">": lambda v, t: v > t,
    "==": lambda v, t: v == t,
}


def _percentile(values: list[float], q: float) -> float:
    """Nearest-rank percentile (deterministic, no interpolation)."""
    vs = sorted(values)
    k = max(0, min(len(vs) - 1, math.ceil(q / 100.0 * len(vs)) - 1))
    return vs[k]


def _aggregate(rule: dict, rows: list[dict]):
    """(value, n) of the rule's aggregate over the qualifying rows;
    (None, n) when the aggregate is undefined on this data."""
    fact = rule.get("fact")
    vals = [float(r["facts"][fact]) for r in rows
            if _num(r.get("facts", {}).get(fact)) is not None]
    agg = str(rule.get("agg", "last")).lower()
    if agg == "ratio":
        denom_key = rule.get("denom")
        num = sum(vals)
        den = sum(float(r["facts"][denom_key]) for r in rows
                  if _num(r.get("facts", {}).get(denom_key)) is not None)
        return (None if den == 0 else num / den), len(vals)
    if agg == "count":
        return float(len(vals)), len(vals)
    if not vals:
        return None, 0
    if agg in ("p50", "p90", "p95", "p99"):
        return _percentile(vals, float(agg[1:])), len(vals)
    if agg == "mean":
        return sum(vals) / len(vals), len(vals)
    if agg == "max":
        return max(vals), len(vals)
    if agg == "min":
        return min(vals), len(vals)
    if agg == "sum":
        return sum(vals), len(vals)
    return vals[0], len(vals)          # "last": rows are newest-first


def evaluate_slo(rows: list[dict], rules: list[dict] = None) -> dict:
    """Run ``rules`` (default :data:`DEFAULT_SLO_RULES`) over trend
    rows (as :meth:`TrendStore.rows` returns them, newest first).

    Returns ``{"ok": bool, "results": [{name, value, n, op, threshold,
    ok, skipped}]}``; a rule with no qualifying data is skipped (ok)
    unless it carries ``"required": true``."""
    results = []
    all_ok = True
    for rule in (DEFAULT_SLO_RULES if rules is None else rules):
        sel = [r for r in rows
               if (not rule.get("kind") or r.get("kind") == rule["kind"])
               and r.get("status") == rule.get("status", "ok")]
        window = rule.get("window")
        if window:
            sel = sel[:int(window)]
        value, n = _aggregate(rule, sel)
        res = {"name": rule.get("name", rule.get("fact")),
               "fact": rule.get("fact"), "agg": rule.get("agg"),
               "op": rule.get("op", "<="),
               "threshold": rule.get("threshold"),
               "value": value, "n": n, "skipped": value is None}
        if value is None:
            res["ok"] = not rule.get("required", False)
        else:
            op = _OPS.get(str(rule.get("op", "<=")))
            res["ok"] = bool(op and op(float(value),
                                       float(rule.get("threshold", 0))))
        all_ok = all_ok and res["ok"]
        results.append(res)
    return {"ok": all_ok, "results": results}


# ---------------------------------------------------------------------------
# statistical regression sentinel (obsctl regress)
# ---------------------------------------------------------------------------

#: facts that describe WHAT a row measured rather than how it
#: performed: rows only ever compare against history with the same
#: (kind, fingerprint-facts) identity, and the fingerprint facts
#: themselves are never drift-checked — a topology/precision/metric
#: change starts a NEW baseline instead of tripping the old one.
FINGERPRINT_FACTS = (
    "mesh", "mesh_devices", "solve_precision", "serve_mode",
    "optimize_method", "bench_metric", "cases_total", "nw",
    "optimize_nlanes", "optimize_steps", "n_devices",
    "farm_n_turbines", "farm_ncases",
)

#: bookkeeping facts whose run-to-run movement is expected (cache
#: warmth flips on the first run of a process, resume points depend on
#: where a preemption landed) — excluded from drift checks
_REGRESS_SKIP = (
    "exec_cache_warm", "optimize_exec_cache_warm", "probe_events",
    "resumed_from_step", "ckpt_resumed_from_step",
    "optimize_resumed_from_step",
)


def _regress_fingerprint(row: dict) -> str:
    facts = row.get("facts") or {}
    return json.dumps([(k, facts[k]) for k in FINGERPRINT_FACTS
                       if k in facts], default=str)


def _waived(waivers, kind: str, fact: str) -> bool:
    for w in waivers or []:
        if isinstance(w, str):
            if w == fact or w == f"{kind}:{fact}":
                return True
        elif isinstance(w, dict):
            if (w.get("fact") == fact
                    and w.get("kind") in (None, "", kind)):
                return True
    return False


def evaluate_regression(rows: list[dict], *, min_history: int = 3,
                        nsigma: float = 4.0, rel_floor: float = 0.05,
                        abs_floor: float = 1e-12,
                        waivers: list = None) -> dict:
    """Statistical drift detection over trend rows (newest first, as
    :meth:`TrendStore.rows` returns them) — no hand-set thresholds.

    Rows group by ``(kind, fingerprint)`` where the fingerprint is the
    row's :data:`FINGERPRINT_FACTS` subset (topology / precision /
    batch identity): a number is only ever compared against history
    that measured the same thing.  Within each group the NEWEST row is
    the candidate and the older rows are the baseline; every numeric
    fact of the candidate with at least ``min_history`` baseline
    samples is tested two-sided against a rolling median/MAD noise
    band::

        |x - median| > max(nsigma * 1.4826 * MAD,
                           rel_floor * |median|, abs_floor)

    (1.4826·MAD is the robust sigma estimate; ``rel_floor`` keeps a
    dead-flat baseline — MAD 0 — from flagging sub-percent jitter, and
    ``abs_floor`` absorbs float noise around 0).  ``waivers`` silences
    known-accepted drifts: entries are ``"fact"`` / ``"kind:fact"``
    strings or ``{"kind", "fact"}`` dicts.

    Returns ``{"ok", "regressions": [...], "groups": [...],
    "checked"}``; ``ok`` is False iff any unwaived fact drifted."""
    groups: dict = {}
    order = []
    for r in rows:
        gkey = (r.get("kind"), _regress_fingerprint(r))
        if gkey not in groups:
            order.append(gkey)
        groups.setdefault(gkey, []).append(r)
    regressions, census = [], []
    checked = 0
    for gkey in order:
        kind, fp = gkey
        grows = [r for r in groups[gkey] if r.get("status") == "ok"]
        info = {"kind": kind, "fingerprint": fp, "rows": len(grows),
                "facts_checked": 0}
        if len(grows) < int(min_history) + 1:
            info["skipped"] = "insufficient history"
            census.append(info)
            continue
        cand, base = grows[0], grows[1:]
        info["candidate"] = cand.get("run_id")
        cfacts = cand.get("facts") or {}
        for fact in sorted(cfacts):
            x = _num(cfacts.get(fact))
            if x is None or fact in FINGERPRINT_FACTS \
                    or fact in _REGRESS_SKIP:
                continue
            vals = [float(v) for v in
                    (_num((r.get("facts") or {}).get(fact))
                     for r in base) if v is not None]
            if len(vals) < int(min_history):
                continue
            vs = sorted(vals)
            med = vs[len(vs) // 2] if len(vs) % 2 else \
                0.5 * (vs[len(vs) // 2 - 1] + vs[len(vs) // 2])
            devs = sorted(abs(v - med) for v in vals)
            mad = devs[len(devs) // 2] if len(devs) % 2 else \
                0.5 * (devs[len(devs) // 2 - 1] + devs[len(devs) // 2])
            band = max(float(nsigma) * 1.4826 * mad,
                       float(rel_floor) * abs(med), float(abs_floor))
            info["facts_checked"] += 1
            checked += 1
            if abs(float(x) - med) > band:
                finding = {"kind": kind, "fact": fact,
                           "value": float(x), "median": med,
                           "mad": mad, "band": band, "n": len(vals),
                           "run_id": cand.get("run_id"),
                           "fingerprint": fp,
                           "waived": _waived(waivers, kind, fact)}
                regressions.append(finding)
        census.append(info)
    return {"ok": not any(not f["waived"] for f in regressions),
            "regressions": regressions, "groups": census,
            "checked": checked}


# ---------------------------------------------------------------------------
# live-metrics evaluation (obsctl slo --url against obsctl serve)
# ---------------------------------------------------------------------------

def parse_prometheus(text: str) -> dict:
    """Minimal Prometheus text-exposition parser:
    ``{name: [(labels_dict, value), ...]}`` — enough to gate on the
    pages ``obs.metrics.to_prometheus`` / ``obsctl serve`` produce."""
    import re

    sample = re.compile(
        r"^([A-Za-z_:][A-Za-z0-9_:]*)(?:\{(.*)\})?\s+(-?[\d.eE+-]+|NaN)$")
    label = re.compile(r'([A-Za-z_][A-Za-z0-9_]*)="((?:[^"\\]|\\.)*)"')

    def unescape(v: str) -> str:
        # single pass so escape pairs cannot recombine (the exposition
        # format escapes \ " and newline in label values)
        return re.sub(r"\\(.)",
                      lambda m: {"n": "\n"}.get(m.group(1), m.group(1)),
                      v)

    out: dict = {}
    for line in text.splitlines():
        line = line.strip()
        if not line or line.startswith("#"):
            continue
        m = sample.match(line)
        if not m:
            continue
        name, labelstr, value = m.groups()
        labels = {k: unescape(v)
                  for k, v in label.findall(labelstr or "")}
        try:
            out.setdefault(name, []).append((labels, float(value)))
        except ValueError:                       # pragma: no cover
            continue
    return out


def evaluate_metric_rules(series: dict, rules: list[dict]) -> dict:
    """Gate live scraped metrics: each rule names a ``metric`` (and an
    optional ``labels`` subset to match); ``agg`` sum|max|min|count
    over the matching samples (default sum).  Same report shape as
    :func:`evaluate_slo`."""
    results = []
    all_ok = True
    for rule in rules:
        name = rule.get("metric")
        want = rule.get("labels") or {}
        samples = [v for labels, v in series.get(name, [])
                   if all(labels.get(k) == str(v2)
                          for k, v2 in want.items())]
        agg = str(rule.get("agg", "sum")).lower()
        if not samples:
            value = None
        elif agg == "max":
            value = max(samples)
        elif agg == "min":
            value = min(samples)
        elif agg == "count":
            value = float(len(samples))
        else:
            value = sum(samples)
        res = {"name": rule.get("name", name), "metric": name,
               "op": rule.get("op", ">="),
               "threshold": rule.get("threshold"), "value": value,
               "n": len(samples), "skipped": value is None}
        if value is None:
            res["ok"] = not rule.get("required", False)
        else:
            op = _OPS.get(str(rule.get("op", ">=")))
            res["ok"] = bool(op and op(float(value),
                                       float(rule.get("threshold", 0))))
        all_ok = all_ok and res["ok"]
        results.append(res)
    return {"ok": all_ok, "results": results}
