"""OpenMDAO wrapper: the WEIS integration gate.

TPU-native equivalent of the reference ``RAFT_OMDAO`` / ``RAFT_Group``
(reference: raft/omdao_raft.py:14-831).  The component declares the same
input/output surface as the reference — WEIS drives either implementation
interchangeably — and ``compute`` rebuilds the RAFT design dictionary from
the OpenMDAO inputs (reference: omdao_raft.py:389-686) then runs this
package's :class:`raft_tpu.model.Model`.

OpenMDAO itself is an *optional* dependency (it is an optimization harness,
not part of the physics).  When ``openmdao`` is importable the classes are
real ``om.ExplicitComponent`` / ``om.Group`` subclasses; otherwise a small
API-compatible shim (declare/add_input/add_output/list_outputs/compute)
stands in so the adapter — and everything downstream of its design-dict
rebuild — stays fully usable and testable.
"""
from __future__ import annotations

import copy
import os
from itertools import compress

import numpy as np

#: when truthy (or the RAFT_TPU_DEBUG_OMDAO env var is set), RAFT_OMDAO
#: dumps its options and inputs as yaml next to the output dir before
#: each compute — the reference's WEIS debugging hook
#: (omdao_raft.py:9 DEBUG_OMDAO, :362-386)
DEBUG_OMDAO = bool(os.environ.get("RAFT_TPU_DEBUG_OMDAO", ""))

ndim = 3
ndof = 6

try:  # pragma: no cover - environment dependent
    import openmdao.api as om
    _HAVE_OM = True
except ImportError:
    _HAVE_OM = False


class _OptionsDict(dict):
    """Minimal stand-in for openmdao's OptionsDictionary."""

    def declare(self, name, default=None, **kwargs):
        self.setdefault(name, default)


class _Vector(dict):
    """Key->value store that mimics openmdao vector __getitem__."""


class _ShimComponent:
    """API-compatible stand-in for ``om.ExplicitComponent``.

    Supports the subset the adapter uses: ``options.declare``,
    ``add_input``/``add_discrete_input``/``add_output``,
    ``list_inputs``/``list_outputs`` plus a ``prime``/``run`` driver that
    mirrors ``prob.run_model()`` for a single component.  Always defined —
    ``RAFT_OMDAO_Standalone`` uses it as its driver even when the real
    openmdao is installed.
    """

    def __init__(self, **options):
        self.options = _OptionsDict()
        self.initialize()
        for k, v in options.items():
            self.options[k] = v
        self._inputs = _Vector()
        self._discrete_inputs = _Vector()
        self._outputs = _Vector()
        self._discrete_outputs = _Vector()
        self._is_setup = False

    # --- declaration API ---
    def initialize(self):
        pass

    def setup(self):
        pass

    def add_input(self, name, val=0.0, units=None, desc=''):
        self._inputs[name] = np.array(val, dtype=float) \
            if not np.isscalar(val) else float(val)

    def add_discrete_input(self, name, val=None, desc=''):
        self._discrete_inputs[name] = val

    def add_output(self, name, val=0.0, units=None, desc=''):
        self._outputs[name] = np.array(val, dtype=float) \
            if not np.isscalar(val) else float(val)

    def add_discrete_output(self, name, val=None, desc=''):
        self._discrete_outputs[name] = val

    # --- introspection API (reference uses these in compute) ---
    def list_inputs(self, out_stream=None, all_procs=False):
        return [(k, {'val': v}) for k, v in self._inputs.items()]

    def list_outputs(self, out_stream=None, all_procs=False):
        return [(k, {'val': v}) for k, v in self._outputs.items()]

    # --- driver ---
    def prime(self, inputs=None, discrete_inputs=None):
        """setup() once and overlay the provided input values (no
        compute) — lets callers inspect the merged input vector or call
        ``build_design`` without paying for a model run."""
        if not self._is_setup:
            self.setup()
            self._is_setup = True
        if inputs:
            for k, v in inputs.items():
                # route by declaration, like prob[key] = val in openmdao:
                # a WEIS input dump mixes continuous and discrete keys
                if k in self._inputs:
                    self._inputs[k] = np.asarray(v, dtype=float) \
                        if not np.isscalar(v) else float(v)
                elif k in self._discrete_inputs:
                    self._discrete_inputs[k] = v
                else:
                    raise KeyError(f"unknown input '{k}'")
        if discrete_inputs:
            for k, v in discrete_inputs.items():
                self._discrete_inputs[k] = v
        return self._inputs

    def run(self, inputs=None, discrete_inputs=None):
        """prime() then compute() — mirrors prob.run_model()."""
        self.prime(inputs, discrete_inputs)
        self.compute(self._inputs, self._outputs,
                     self._discrete_inputs, self._discrete_outputs)
        return self._outputs


class _ShimGroup:
    """Stand-in for ``om.Group`` holding promoted subsystems."""

    def __init__(self, **options):
        self.options = _OptionsDict()
        self.initialize()
        for k, v in options.items():
            self.options[k] = v
        self._subsystems = {}

    def initialize(self):
        pass

    def setup(self):
        pass

    def add_subsystem(self, name, comp, promotes=None):
        self._subsystems[name] = comp
        return comp


if _HAVE_OM:  # pragma: no cover - environment dependent
    _ComponentBase = om.ExplicitComponent
    _GroupBase = om.Group
else:
    _ComponentBase = _ShimComponent
    _GroupBase = _ShimGroup


class RAFT_OMDAO(_ComponentBase):
    """RAFT OpenMDAO wrapper (reference: omdao_raft.py:14-810).

    Declares the reference's full input/output surface keyed off the same
    five option dictionaries (modeling/turbine/members/mooring/analysis).
    """

    def initialize(self):
        self.options.declare('modeling_options')
        self.options.declare('turbine_options')
        self.options.declare('mooring_options')
        self.options.declare('member_options')
        self.options.declare('analysis_options')

    # ------------------------------------------------------------------
    # setup: declare inputs/outputs (reference: omdao_raft.py:26-335)
    # ------------------------------------------------------------------
    def setup(self):
        modeling_opt = self.options['modeling_options']
        nfreq = modeling_opt['nfreq']
        n_cases = modeling_opt['n_cases']

        turbine_opt = self.options['turbine_options']
        turbine_npts = turbine_opt['npts']
        n_gain = turbine_opt['PC_GS_n']
        n_span = turbine_opt['n_span']
        n_aoa = turbine_opt['n_aoa']
        n_Re = turbine_opt['n_Re']
        n_tab = turbine_opt['n_tab']
        n_pc = turbine_opt['n_pc']
        n_af = turbine_opt['n_af']
        n_af_span = len(turbine_opt['af_used_names'])

        members_opt = self.options['member_options']
        nmembers = members_opt['nmembers']
        n_ballast_type = members_opt['n_ballast_type']

        mooring_opt = self.options['mooring_options']
        nlines = mooring_opt['nlines']
        nline_types = mooring_opt['nline_types']
        nconnections = mooring_opt['nconnections']

        # ---- turbine / RNA inputs (reference :66-76) ----
        for name in ('turbine_mRNA', 'turbine_IxRNA', 'turbine_IrRNA',
                     'turbine_xCG_RNA', 'turbine_hHub', 'turbine_overhang',
                     'turbine_Fthrust', 'turbine_yaw_stiffness'):
            self.add_input(name, val=0.0)

        # ---- tower (one member; reference :77-104) ----
        self.add_input('turbine_tower_rA', val=np.zeros(ndim))
        self.add_input('turbine_tower_rB', val=np.zeros(ndim))
        self.add_input('turbine_tower_gamma', val=0.0)
        self.add_input('turbine_tower_stations', val=np.zeros(turbine_npts))
        self._add_member_shape_inputs(
            'turbine_tower_', turbine_opt['shape'], turbine_npts,
            turbine_opt['scalar_diameters'], turbine_opt['scalar_thicknesses'],
            turbine_opt['scalar_coefficients'])
        self.add_input('turbine_tower_rho_shell', val=0.0)

        # ---- control (reference :106-113) ----
        self.add_input('rotor_PC_GS_angles', val=np.zeros(n_gain))
        self.add_input('rotor_PC_GS_Kp', val=np.zeros(n_gain))
        self.add_input('rotor_PC_GS_Ki', val=np.zeros(n_gain))
        self.add_input('Fl_Kp', val=0.0)
        self.add_input('rotor_inertia', val=0.0)
        self.add_input('rotor_TC_VS_Kp', val=0.0)
        self.add_input('rotor_TC_VS_Ki', val=0.0)

        # ---- blade & rotor (reference :114-144) ----
        self.add_discrete_input('nBlades', val=3)
        for name in ('tilt', 'precone', 'wind_reference_height', 'hub_radius'):
            self.add_input(name, val=0.0)
        self.add_input('gear_ratio', val=1.0)
        for name in ('blade_r', 'blade_chord', 'blade_theta',
                     'blade_precurve', 'blade_presweep'):
            self.add_input(name, val=np.zeros(n_span))
        for name in ('blade_Rtip', 'blade_precurveTip', 'blade_presweepTip'):
            self.add_input(name, val=0.0)
        self.add_discrete_input('airfoils_name', val=n_af * [""])
        self.add_input('airfoils_position', val=np.zeros(n_af_span))
        self.add_input('airfoils_r_thick', val=np.zeros(n_af))
        self.add_input('airfoils_aoa', val=np.zeros(n_aoa))
        for name in ('airfoils_cl', 'airfoils_cd', 'airfoils_cm'):
            self.add_input(name, val=np.zeros((n_af, n_aoa, n_Re, n_tab)))
        self.add_input('rotor_powercurve_v', val=np.zeros(n_pc))
        self.add_input('rotor_powercurve_omega_rpm', val=np.zeros(n_pc))
        self.add_input('rotor_powercurve_pitch', val=np.zeros(n_pc))
        self.add_input('rho_air', val=1.225)
        self.add_input('rho_water', val=1025.0)
        self.add_input('mu_air', val=1.81e-5)
        self.add_input('shear_exp', val=0.2)
        self.add_input('rated_rotor_speed', val=0.0)

        # ---- platform members (reference :146-225) ----
        for i in range(1, nmembers + 1):
            m_name = f'platform_member{i}_'
            mnpts = members_opt['npts'][i - 1]
            mnpts_lfill = members_opt['npts_lfill'][i - 1]
            mncaps = members_opt['ncaps'][i - 1]
            mnreps = members_opt['nreps'][i - 1]
            self.add_input(m_name + 'heading', val=np.zeros(mnreps))
            self.add_input(m_name + 'rA', val=np.zeros(ndim))
            self.add_input(m_name + 'rB', val=np.zeros(ndim))
            self.add_input(m_name + 's_ghostA', val=0.0)
            self.add_input(m_name + 's_ghostB', val=1.0)
            self.add_input(m_name + 'gamma', val=0.0)
            self.add_input(m_name + 'stations', val=np.zeros(mnpts))
            self._add_member_shape_inputs(
                m_name, members_opt['shape'][i - 1], mnpts,
                members_opt['scalar_diameters'][i - 1],
                members_opt['scalar_thicknesses'][i - 1],
                members_opt['scalar_coefficients'][i - 1])
            self.add_input(m_name + 'rho_shell', val=0.0)
            self.add_input(m_name + 'l_fill', val=np.zeros(mnpts_lfill))
            self.add_input(m_name + 'rho_fill', val=np.zeros(mnpts_lfill))
            self.add_input(m_name + 'cap_stations', val=np.zeros(mncaps))
            self.add_input(m_name + 'cap_t', val=np.zeros(mncaps))
            self.add_input(m_name + 'cap_d_in', val=np.zeros(mncaps))
            self.add_input(m_name + 'ring_spacing', val=0.0)
            self.add_input(m_name + 'ring_t', val=0.0)
            self.add_input(m_name + 'ring_h', val=0.0)

        # ---- mooring (reference :227-248) ----
        self.add_input('mooring_water_depth', val=0.0)
        for i in range(1, nconnections + 1):
            self.add_input(f'mooring_point{i}_location', val=np.zeros(ndim))
        for i in range(1, nlines + 1):
            self.add_input(f'mooring_line{i}_length', val=0.0)
        for i in range(1, nline_types + 1):
            lt_name = f'mooring_line_type{i}_'
            for prop in ('diameter', 'mass_density', 'stiffness',
                         'breaking_load', 'cost', 'transverse_added_mass',
                         'tangential_added_mass', 'transverse_drag',
                         'tangential_drag'):
                self.add_input(lt_name + prop, val=0.0)

        # ---- outputs: properties (reference :250-272) ----
        self.add_output('properties_tower mass', val=0.0)
        self.add_output('properties_tower CG', val=np.zeros(ndim))
        self.add_output('properties_substructure mass', val=0.0)
        self.add_output('properties_substructure CG', val=np.zeros(ndim))
        self.add_output('properties_shell mass', val=0.0)
        self.add_output('properties_ballast mass', val=np.zeros(n_ballast_type))
        self.add_output('properties_ballast densities', val=np.zeros(n_ballast_type))
        self.add_output('properties_total mass', val=0.0)
        self.add_output('properties_total CG', val=np.zeros(ndim))
        self.add_output('properties_roll inertia at subCG', val=np.zeros(ndim))
        self.add_output('properties_pitch inertia at subCG', val=np.zeros(ndim))
        self.add_output('properties_yaw inertia at subCG', val=np.zeros(ndim))
        self.add_output('properties_buoyancy (pgV)', val=0.0)
        self.add_output('properties_center of buoyancy', val=np.zeros(ndim))
        self.add_output('properties_C hydrostatic', val=np.zeros((ndof, ndof)))
        self.add_output('properties_C system', val=np.zeros((ndof, ndof)))
        self.add_output('properties_F_lines0', val=np.zeros(ndof))
        self.add_output('properties_C_lines0', val=np.zeros((ndof, ndof)))
        self.add_output('properties_M support structure', val=np.zeros((ndof, ndof)))
        self.add_output('properties_A support structure', val=np.zeros((ndof, ndof)))
        self.add_output('properties_C support structure', val=np.zeros((ndof, ndof)))

        # ---- outputs: response RAOs (reference :273-283) ----
        self.add_output('response_frequencies', val=np.zeros(nfreq))
        self.add_output('response_wave elevation', val=np.zeros(nfreq))
        for ch in ('surge', 'sway', 'heave', 'pitch', 'roll', 'yaw'):
            self.add_output(f'response_{ch} RAO', val=np.zeros(nfreq))
        self.add_output('response_nacelle acceleration', val=np.zeros(nfreq))

        # ---- outputs: per-case statistics (reference :284-314) ----
        names = ['surge', 'sway', 'heave', 'roll', 'pitch', 'yaw',
                 'AxRNA', 'Mbase', 'omega', 'torque', 'power', 'bPitch',
                 'Tmoor']
        stats = ['avg', 'std', 'max', 'PSD', 'DEL']
        for n in names:
            for s in stats:
                if s == 'DEL' and n not in ['Tmoor', 'Mbase']:
                    continue
                if n == 'Tmoor':
                    myval = np.zeros((n_cases, 2 * nlines)) if s != 'PSD' \
                        else np.zeros((n_cases, 2 * nlines, nfreq))
                else:
                    myval = np.zeros(n_cases) if s != 'PSD' \
                        else np.zeros((n_cases, nfreq))
                self.add_output(f'stats_{n}_{s}', val=myval)
        self.add_output('stats_wind_PSD', val=np.zeros((n_cases, nfreq)))
        self.add_output('stats_wave_PSD', val=np.zeros((n_cases, nfreq)))

        # ---- outputs: natural periods + aggregates (reference :316-335) ----
        self.add_output('rigid_body_periods', val=np.zeros(6))
        for ch in ('surge', 'sway', 'heave', 'roll', 'pitch', 'yaw'):
            self.add_output(f'{ch}_period', val=0.0)
        for name in ('Max_Offset', 'heave_avg', 'Max_PtfmPitch',
                     'Std_PtfmPitch', 'max_nac_accel', 'rotor_overspeed',
                     'max_tower_base'):
            self.add_output(name, val=0.0)
        self.add_output('platform_total_center_of_mass', val=np.zeros(3))
        self.add_output('platform_displacement', val=0.0)
        self.add_output('platform_mass', val=0.0)
        self.add_output('platform_I_total', val=np.zeros(6))

    def _add_member_shape_inputs(self, m_name, shape, npts, scalar_d,
                                 scalar_t, scalar_coeff):
        """d/t/Cd/Ca/CdEnd/CaEnd declarations shared by tower and platform
        members (reference: omdao_raft.py:81-104, 167-214)."""
        if scalar_d:
            self.add_input(m_name + 'd',
                           val=0.0 if shape != 'rect' else [0.0, 0.0])
        elif shape == 'rect':
            self.add_input(m_name + 'd', val=np.zeros((npts, 2)))
        else:
            self.add_input(m_name + 'd', val=np.zeros(npts))
        self.add_input(m_name + 't', val=0.0 if scalar_t else np.zeros(npts))
        if shape == 'circ':
            cval = 0.0 if scalar_coeff else np.zeros(npts)
        else:
            cval = [0.0, 0.0] if scalar_coeff else np.zeros((npts, 2))
        self.add_input(m_name + 'Cd', val=cval)
        self.add_input(m_name + 'Ca', val=copy.deepcopy(cval))
        self.add_input(m_name + 'CdEnd', val=0.0 if scalar_coeff else np.zeros(npts))
        self.add_input(m_name + 'CaEnd', val=0.0 if scalar_coeff else np.zeros(npts))

    # ------------------------------------------------------------------
    # design-dict rebuild (reference: omdao_raft.py:389-686)
    # ------------------------------------------------------------------
    def build_design(self, inputs, discrete_inputs):
        modeling_opt = self.options['modeling_options']
        analysis_options = self.options['analysis_options']
        turbine_opt = self.options['turbine_options']
        members_opt = self.options['member_options']
        mooring_opt = self.options['mooring_options']

        design = {}
        design['type'] = ['input dictionary for RAFT']
        design['name'] = [analysis_options['general']['fname_output']]
        design['comments'] = ['none']

        design['settings'] = {
            'XiStart': float(modeling_opt['xi_start']),
            'min_freq': float(modeling_opt['min_freq']),
            'max_freq': float(modeling_opt['max_freq']),
            'nIter': int(modeling_opt['nIter']),
        }
        design['site'] = {
            'water_depth': float(np.asarray(inputs['mooring_water_depth']).flat[0]),
            'rho_air': float(np.asarray(inputs['rho_air']).flat[0]),
            'rho_water': float(np.asarray(inputs['rho_water']).flat[0]),
            'mu_air': float(np.asarray(inputs['mu_air']).flat[0]),
            'shearExp': float(np.asarray(inputs['shear_exp']).flat[0]),
        }

        # ---- turbine (reference :412-500) ----
        turbine = {}
        for key, iname in (('mRNA', 'turbine_mRNA'), ('IxRNA', 'turbine_IxRNA'),
                           ('IrRNA', 'turbine_IrRNA'),
                           ('xCG_RNA', 'turbine_xCG_RNA'),
                           ('hHub', 'turbine_hHub'),
                           ('overhang', 'turbine_overhang'),
                           ('Fthrust', 'turbine_Fthrust'),
                           ('yaw_stiffness', 'turbine_yaw_stiffness'),
                           ('gear_ratio', 'gear_ratio')):
            turbine[key] = float(np.asarray(inputs[iname]).flat[0])

        tower = {'name': 'tower', 'type': 1}
        rA = np.array(inputs['turbine_tower_rA'], float)
        rB = np.array(inputs['turbine_tower_rB'], float)
        if rA[2] > rB[2]:      # MHK towers come flipped (reference :430-433)
            rA, rB = rB, rA
        tower['rA'] = rA
        tower['rB'] = rB
        tower['shape'] = turbine_opt['shape']
        tower['gamma'] = float(np.asarray(inputs['turbine_tower_gamma']).flat[0])
        tower['stations'] = np.array(inputs['turbine_tower_stations'], float)
        for key, scalar in (('d', turbine_opt['scalar_diameters']),
                            ('t', turbine_opt['scalar_thicknesses'])):
            v = inputs['turbine_tower_' + key]
            tower[key] = float(np.asarray(v).flat[0]) if scalar else np.array(v, float)
        for key in ('Cd', 'Ca', 'CdEnd', 'CaEnd'):
            v = inputs['turbine_tower_' + key]
            tower[key] = float(np.asarray(v).flat[0]) \
                if turbine_opt['scalar_coefficients'] else np.array(v, float)
        tower['rho_shell'] = float(np.asarray(inputs['turbine_tower_rho_shell']).flat[0])
        turbine['tower'] = tower

        turbine['nBlades'] = int(discrete_inputs['nBlades'])
        turbine['shaft_tilt'] = float(np.asarray(inputs['tilt']).flat[0])
        turbine['precone'] = float(np.asarray(inputs['precone']).flat[0])
        turbine['Zhub'] = float(np.asarray(inputs['wind_reference_height']).flat[0])
        turbine['Rhub'] = float(np.asarray(inputs['hub_radius']).flat[0])
        turbine['I_drivetrain'] = float(np.asarray(inputs['rotor_inertia']).flat[0])

        turbine['blade'] = {
            'geometry': np.c_[inputs['blade_r'], inputs['blade_chord'],
                              inputs['blade_theta'], inputs['blade_precurve'],
                              inputs['blade_presweep']],
            'Rtip': float(np.asarray(inputs['blade_Rtip']).flat[0]),
            'precurveTip': float(np.asarray(inputs['blade_precurveTip']).flat[0]),
            'presweepTip': float(np.asarray(inputs['blade_presweepTip']).flat[0]),
            'airfoils': list(zip([float(ap) for ap in inputs['airfoils_position']],
                                 turbine_opt['af_used_names'])),
        }
        n_af = turbine_opt['n_af']
        turbine['airfoils'] = []
        for i in range(n_af):
            turbine['airfoils'].append({
                'name': discrete_inputs['airfoils_name'][i],
                'relative_thickness': float(np.asarray(inputs['airfoils_r_thick'])[i]),
                'data': np.c_[np.rad2deg(np.asarray(inputs['airfoils_aoa'])),
                              np.asarray(inputs['airfoils_cl'])[i, :, 0, 0],
                              np.asarray(inputs['airfoils_cd'])[i, :, 0, 0],
                              np.asarray(inputs['airfoils_cm'])[i, :, 0, 0]],
            })

        turbine['pitch_control'] = {
            'GS_Angles': np.array(inputs['rotor_PC_GS_angles'], float),
            'GS_Kp': np.array(inputs['rotor_PC_GS_Kp'], float),
            'GS_Ki': np.array(inputs['rotor_PC_GS_Ki'], float),
            'Fl_Kp': float(np.asarray(inputs['Fl_Kp']).flat[0]),
        }
        turbine['torque_control'] = {
            'VS_KP': float(np.asarray(inputs['rotor_TC_VS_Kp']).flat[0]),
            'VS_KI': float(np.asarray(inputs['rotor_TC_VS_Ki']).flat[0]),
        }
        turbine['wt_ops'] = {
            'v': np.array(inputs['rotor_powercurve_v'], float),
            'omega_op': np.array(inputs['rotor_powercurve_omega_rpm'], float),
            'pitch_op': np.array(inputs['rotor_powercurve_pitch'], float),
        }
        design['turbine'] = turbine

        # ---- platform members incl. ghost segments (reference :502-640) ----
        design['platform'] = {
            'potModMaster': int(modeling_opt['potential_model_override']),
            'dlsMax': float(modeling_opt['dls_max']),
            # the reference stores this under design['turbine'] only
            # (omdao_raft.py:419) while the model reads it from
            # design['platform'] (raft_fowt.py:194-197) — i.e. WEIS's yaw
            # stiffness is silently dropped there; wire it through here
            'yaw_stiffness': float(np.asarray(
                inputs['turbine_yaw_stiffness']).flat[0]),
        }
        min_freq_BEM = float(modeling_opt['min_freq_BEM'])
        if min_freq_BEM >= modeling_opt['min_freq']:
            min_freq_BEM = modeling_opt['min_freq'] - 1e-7
        design['platform']['min_freq_BEM'] = min_freq_BEM
        nmembers = members_opt['nmembers']
        design['platform']['members'] = []
        for i in range(nmembers):
            m_name = f'platform_member{i+1}_'
            m_shape = members_opt['shape'][i]
            scalar_d = members_opt['scalar_diameters'][i]
            scalar_t = members_opt['scalar_thicknesses'][i]
            scalar_coeff = members_opt['scalar_coefficients'][i]
            mem = {'name': m_name, 'type': i + 2, 'shape': m_shape,
                   'gamma': float(np.asarray(inputs[m_name + 'gamma']).flat[0]),
                   'potMod': members_opt[m_name + 'potMod']}

            # ghost-segment trim: clip stations to [s_ghostA, s_ghostB] and
            # move the physical ends (reference :517-527)
            rA_0 = np.array(inputs[m_name + 'rA'], float)
            rB_0 = np.array(inputs[m_name + 'rB'], float)
            s_ghostA = float(np.asarray(inputs[m_name + 's_ghostA']).flat[0])
            s_ghostB = float(np.asarray(inputs[m_name + 's_ghostB']).flat[0])
            s_0 = np.array(inputs[m_name + 'stations'], float)
            idx = np.logical_and(s_0 >= s_ghostA, s_0 <= s_ghostB)
            s_grid = np.unique(np.r_[s_ghostA, s_0[idx], s_ghostB])
            # NOTE: the reference uses len(idx) (= the untrimmed station
            # count, omdao_raft.py:525) — its Member tolerates a longer 'd'
            # list, this package's parser does not, so use the real grid
            mnpts = len(s_grid)
            mem['rA'] = rA_0 + s_ghostA * (rB_0 - rA_0)
            mem['rB'] = rA_0 + s_ghostB * (rB_0 - rA_0)
            mem['stations'] = s_grid

            if m_shape in ('circ', 'square'):
                if scalar_d:
                    mem['d'] = [float(np.asarray(inputs[m_name + 'd']).flat[0])] * mnpts
                else:
                    mem['d'] = np.interp(s_grid, s_0, np.asarray(inputs[m_name + 'd']))
            else:
                d_in = np.asarray(inputs[m_name + 'd'], float)
                d = np.zeros([len(s_grid), 2])
                if scalar_d:
                    d[:, 0], d[:, 1] = d_in.flat[0], d_in.flat[1]
                else:
                    d[:, 0] = np.interp(s_grid, s_0, d_in[:, 0])
                    d[:, 1] = np.interp(s_grid, s_0, d_in[:, 1])
                mem['d'] = d
            if scalar_t:
                mem['t'] = float(np.asarray(inputs[m_name + 't']).flat[0])
            else:
                mem['t'] = np.interp(s_grid, s_0, np.asarray(inputs[m_name + 't']))

            for key in ('Cd', 'Ca'):
                v = np.asarray(inputs[m_name + key], float)
                if m_shape == 'circ':
                    mem[key] = float(v.flat[0]) if scalar_coeff \
                        else np.interp(s_grid, s_0, v)
                else:
                    c = np.zeros([len(s_grid), 2])
                    if scalar_coeff:
                        c[:, 0], c[:, 1] = v.flat[0], v.flat[1]
                    else:
                        c[:, 0] = np.interp(s_grid, s_0, v[:, 0])
                        c[:, 1] = np.interp(s_grid, s_0, v[:, 1])
                    mem[key] = c
            for key in ('CdEnd', 'CaEnd'):
                v = np.asarray(inputs[m_name + key], float)
                mem[key] = float(v.flat[0]) if scalar_coeff \
                    else np.interp(s_grid, s_0, v)
            mem['rho_shell'] = float(np.asarray(inputs[m_name + 'rho_shell']).flat[0])
            if members_opt['nreps'][i] > 0:
                mem['heading'] = np.array(inputs[m_name + 'heading'], float)
            if members_opt['npts_lfill'][i] > 0:
                mem['l_fill'] = np.array(inputs[m_name + 'l_fill'], float)
                mem['rho_fill'] = np.array(inputs[m_name + 'rho_fill'], float)

            # end caps / bulkheads / ring stiffeners (reference :596-638)
            mncaps = members_opt['ncaps'][i]
            ring_spacing = float(np.asarray(inputs[m_name + 'ring_spacing']).flat[0])
            if mncaps > 0 or ring_spacing > 0:
                s_height = s_grid[-1] - s_grid[0]
                n_stiff = 0 if ring_spacing == 0.0 else \
                    int(np.floor(s_height / ring_spacing))
                s_ring = (np.arange(1, n_stiff + 0.1) - 0.5) * (ring_spacing / s_height)
                d_ring = None
                if len(s_ring):
                    if m_shape != 'rect':
                        d_ring = np.interp(s_ring, s_grid, mem['d'])
                    else:
                        d_ring = np.zeros([len(s_ring), 2])
                        d_ring[:, 0] = np.interp(s_ring, s_grid, mem['d'][:, 0])
                        d_ring[:, 1] = np.interp(s_ring, s_grid, mem['d'][:, 1])
                s_cap_0 = np.asarray(inputs[m_name + 'cap_stations'], float)
                t_cap_0 = np.asarray(inputs[m_name + 'cap_t'], float)
                if len(s_cap_0):
                    idx_cap = np.logical_and(s_cap_0 >= s_ghostA, s_cap_0 <= s_ghostB)
                    s_cap, isort = np.unique(
                        np.r_[s_ghostA, s_cap_0[idx_cap], s_ghostB],
                        return_index=True)
                    t_cap = np.r_[t_cap_0[0], t_cap_0[idx_cap], t_cap_0[-1]][isort]
                    di_cap = np.zeros(s_cap.shape)
                    if s_ghostA > 0.0:
                        s_cap, t_cap, di_cap = s_cap[1:], t_cap[1:], di_cap[1:]
                    if s_ghostB < 1.0:
                        s_cap, t_cap, di_cap = s_cap[:-1], t_cap[:-1], di_cap[:-1]
                else:
                    s_cap = np.zeros(0)
                    t_cap = np.zeros(0)
                    di_cap = np.zeros(0)
                if len(s_ring):
                    s_cap = np.r_[s_ring, s_cap]
                    t_cap = np.r_[float(np.asarray(inputs[m_name + 'ring_t']).flat[0])
                                  * np.ones(n_stiff), t_cap]
                    di_cap = np.r_[d_ring - 2 * float(
                        np.asarray(inputs[m_name + 'ring_h']).flat[0]), di_cap]
                if len(s_cap) > 0:
                    isort = np.argsort(s_cap)
                    mem['cap_stations'] = s_cap[isort]
                    mem['cap_t'] = t_cap[isort]
                    mem['cap_d_in'] = di_cap[isort]
            design['platform']['members'].append(mem)

        # ---- mooring (reference :641-675) ----
        nconnections = mooring_opt['nconnections']
        nlines = mooring_opt['nlines']
        nline_types = mooring_opt['nline_types']
        mooring = {'water_depth': float(np.asarray(
            inputs['mooring_water_depth']).flat[0])}
        mooring['points'] = []
        for i in range(nconnections):
            pt_name = f'mooring_point{i+1}_'
            pt = {'name': mooring_opt[pt_name + 'name'],
                  'type': mooring_opt[pt_name + 'type'],
                  'location': np.array(inputs[pt_name + 'location'], float)}
            if pt['type'].lower() == 'fixed':
                pt['anchor_type'] = 'drag_embedment'
            mooring['points'].append(pt)
        mooring['lines'] = []
        for i in range(nlines):
            ml_name = f'mooring_line{i+1}_'
            mooring['lines'].append({
                'name': f'line{i+1}',
                'endA': mooring_opt[ml_name + 'endA'],
                'endB': mooring_opt[ml_name + 'endB'],
                'type': mooring_opt[ml_name + 'type'],
                'length': float(np.asarray(inputs[ml_name + 'length']).flat[0]),
            })
        mooring['line_types'] = []
        for i in range(nline_types):
            lt_name = f'mooring_line_type{i+1}_'
            lt = {'name': mooring_opt[lt_name + 'name']}
            for prop in ('diameter', 'mass_density', 'stiffness',
                         'breaking_load', 'cost', 'transverse_added_mass',
                         'tangential_added_mass', 'transverse_drag',
                         'tangential_drag'):
                lt[prop] = float(np.asarray(inputs[lt_name + prop]).flat[0])
            mooring['line_types'].append(lt)
        mooring['anchor_types'] = [{
            'name': 'drag_embedment', 'mass': 1e3, 'cost': 1e4,
            'max_vertical_load': 0.0, 'max_lateral_load': 1e5}]
        design['mooring'] = mooring

        # ---- DLC cases: keep spectral-wind rows only (reference :676-686) ----
        turb_ind = modeling_opt['raft_dlcs_keys'].index('turbulence')
        case_mask = [any(tt in str(cd[turb_ind]) for tt in ('NTM', 'ETM', 'EWM'))
                     for cd in modeling_opt['raft_dlcs']]
        design['cases'] = {
            'keys': modeling_opt['raft_dlcs_keys'],
            'data': list(compress(modeling_opt['raft_dlcs'], case_mask)),
        }
        return design, case_mask

    # ------------------------------------------------------------------
    # compute (reference: omdao_raft.py:698-810)
    # ------------------------------------------------------------------
    def _debug_dump(self, inputs, out_dir=None):
        """Dump component options and inputs as yaml for WEIS replay
        (reference omdao_raft.py:362-386 DEBUG_OMDAO block: writes
        weis_options.yaml / weis_inputs.yaml into tests/test_data).
        ``out_dir`` defaults to $RAFT_TPU_DEBUG_OMDAO if it names a
        directory, else the cwd."""
        import yaml as _yaml

        env = os.environ.get("RAFT_TPU_DEBUG_OMDAO", "")
        if out_dir is None:
            out_dir = env if os.path.isdir(env) else "."
        opts = {k: copy.deepcopy(self.options[k])
                for k in ("modeling_options", "turbine_options",
                          "mooring_options", "member_options",
                          "analysis_options") if k in self.options}
        gen = opts.get("analysis_options", {}).get("general")
        if gen and "folder_output" in gen:
            gen["folder_output"] = os.path.split(gen["folder_output"])[-1]

        def _plain(v):
            if isinstance(v, dict):
                return {k: _plain(x) for k, x in v.items()}
            if isinstance(v, (list, tuple)):
                return [_plain(x) for x in v]
            if isinstance(v, np.ndarray):
                return v.tolist()
            if isinstance(v, (np.floating, np.integer)):
                return v.item()
            return v

        with open(os.path.join(out_dir, "weis_options.yaml"), "w") as f:
            _yaml.safe_dump(_plain(opts), f, sort_keys=False)
        try:
            items = {name: meta["val"] for name, meta in
                     self.list_inputs(out_stream=None)}
        # shim component without openmdao: list_inputs can fail in any
        # openmdao-version-specific way; the replay dump then just uses
        # the raw inputs dict
        except Exception:  # raftlint: disable=RTL004
            items = dict(inputs)
        with open(os.path.join(out_dir, "weis_inputs.yaml"), "w") as f:
            _yaml.safe_dump(_plain(items), f, sort_keys=False)

    def compute(self, inputs, outputs, discrete_inputs=None,
                discrete_outputs=None):
        from raft_tpu.model import Model

        modeling_opt = self.options['modeling_options']

        if DEBUG_OMDAO or os.environ.get("RAFT_TPU_DEBUG_OMDAO"):
            self._debug_dump(inputs)

        design, case_mask = self.build_design(inputs, discrete_inputs)

        model = Model(design)
        model.analyzeUnloaded(
            ballast=modeling_opt.get('trim_ballast', 0),
            heave_tol=modeling_opt['heave_tol'])
        model.analyzeCases()
        results = model.calcOutputs()

        # properties pattern-match (reference :750-755)
        for name, _meta in self.list_outputs(out_stream=None, all_procs=True):
            if name.startswith('properties_'):
                key = name.split('properties_')[1]
                if key in results['properties']:
                    val = np.asarray(results['properties'][key], float)
                    outputs[name] = val.reshape(np.shape(outputs[name])) \
                        if np.size(val) == np.size(outputs[name]) else val

        # per-case statistics (reference :766-776)
        names = ['surge', 'sway', 'heave', 'roll', 'pitch', 'yaw',
                 'AxRNA', 'Mbase', 'Tmoor']
        stats = ['avg', 'std', 'max', 'PSD']
        case_mask_arr = np.array(case_mask)
        case_metrics = [cm[0] for cm in results['case_metrics'].values()
                        if 0 in cm]
        for n in names:
            for s in stats:
                iout = f'{n}_{s}'
                stat = np.squeeze(np.array([cm[iout] for cm in case_metrics]))
                full = np.asarray(outputs['stats_' + iout])
                if n == 'Tmoor':
                    stat = np.reshape(stat, (len(case_metrics), -1))
                    ncol = min(stat.shape[-1], full.shape[-1]) \
                        if full.ndim > 1 else stat.shape[-1]
                    if s == 'PSD':
                        stat3 = stat.reshape(len(case_metrics), -1,
                                             model.nw)[:, :ncol, :]
                        full[case_mask_arr, :ncol, :] = stat3
                    else:
                        full[case_mask_arr, :ncol] = stat[:, :ncol]
                else:
                    full[case_mask_arr] = stat
                outputs['stats_' + iout] = full

        # natural periods (reference :786-795)
        fns, _modes = model.solveEigen()
        periods = 1.0 / np.asarray(fns)[:6]
        outputs['rigid_body_periods'] = periods
        for idof, ch in enumerate(('surge', 'sway', 'heave', 'roll',
                                   'pitch', 'yaw')):
            outputs[f'{ch}_period'] = periods[idof]

        # aggregates (reference :797-805)
        def _stat(name):
            return np.asarray(outputs['stats_' + name])[case_mask_arr]

        outputs['Max_Offset'] = float(np.sqrt(
            _stat('surge_max') ** 2 + _stat('sway_max') ** 2).max())
        outputs['heave_avg'] = float(_stat('heave_avg').mean())
        outputs['Max_PtfmPitch'] = float(_stat('pitch_max').max())
        outputs['Std_PtfmPitch'] = float(_stat('pitch_std').mean())
        outputs['max_nac_accel'] = float(np.max([
            np.max(results['case_metrics'][ic][0]['AxRNA_std'])
            for ic in results['case_metrics'] if 0 in results['case_metrics'][ic]]))
        rated = float(np.asarray(inputs['rated_rotor_speed']).flat[0])
        omega_max = np.max([
            np.max(results['case_metrics'][ic][0]['omega_max'])
            for ic in results['case_metrics'] if 0 in results['case_metrics'][ic]])
        outputs['rotor_overspeed'] = (omega_max - rated) / rated if rated else 0.0
        outputs['max_tower_base'] = float(np.max([
            np.max(results['case_metrics'][ic][0]['Mbase_max'])
            for ic in results['case_metrics'] if 0 in results['case_metrics'][ic]]))

        # combined outputs for OpenFAST (reference :807-814)
        stat0 = model._state[0]['statics']
        outputs['platform_displacement'] = float(np.asarray(stat0['V']))
        outputs['platform_total_center_of_mass'] = np.asarray(
            results['properties']['substructure CG'], float)
        outputs['platform_mass'] = float(
            results['properties']['substructure mass'])
        I_total = np.asarray(outputs['platform_I_total'])
        I_total[:3] = np.r_[
            np.atleast_1d(results['properties']['roll inertia at subCG'])[0],
            np.atleast_1d(results['properties']['pitch inertia at subCG'])[0],
            np.atleast_1d(results['properties']['yaw inertia at subCG'])[0]]
        outputs['platform_I_total'] = I_total


class RAFT_Group(_GroupBase):
    """Group wrapper promoting the RAFT component (reference:
    omdao_raft.py:816-831)."""

    def initialize(self):
        self.options.declare('modeling_options')
        self.options.declare('turbine_options')
        self.options.declare('mooring_options')
        self.options.declare('member_options')
        self.options.declare('analysis_options')

    def setup(self):
        self.add_subsystem('raft', RAFT_OMDAO(
            modeling_options=self.options['modeling_options'],
            analysis_options=self.options['analysis_options'],
            turbine_options=self.options['turbine_options'],
            mooring_options=self.options['mooring_options'],
            member_options=self.options['member_options']), promotes=['*'])


class RAFT_OMDAO_Standalone(_ShimComponent):
    """RAFT_OMDAO with the shim driver regardless of whether openmdao is
    installed — the standalone entry for running the WEIS interface without
    an ``om.Problem`` (tests, CLI).  Same declarations/compute as
    RAFT_OMDAO; only the component base differs."""

    initialize = RAFT_OMDAO.initialize
    setup = RAFT_OMDAO.setup
    _add_member_shape_inputs = RAFT_OMDAO._add_member_shape_inputs
    build_design = RAFT_OMDAO.build_design
    compute = RAFT_OMDAO.compute
    _debug_dump = RAFT_OMDAO._debug_dump


# ----------------------------------------------------------------------
# design-dict -> omdao options/inputs (inverse mapping; test + CLI aid)
# ----------------------------------------------------------------------

def omdao_from_design(design: dict, n_aoa=200):
    """Build (options, inputs, discrete_inputs) for :class:`RAFT_OMDAO`
    from a RAFT design dictionary — the inverse of ``build_design``.

    Lets a yaml-defined design be driven through the exact WEIS/OpenMDAO
    interface without WEIS present (and gives tests a closed loop:
    design -> OM inputs -> ``build_design`` -> design).  Airfoil polars are
    resampled onto one shared ``n_aoa``-point angle-of-attack grid, since
    the OM interface stores all polars on a common grid; stations are
    normalized to [0, 1] the way WEIS supplies them (the yaml path allows
    arbitrary monotonic station scales, reference: raft_member.py:71-82).
    """

    def _norm_stations(st):
        st = np.asarray(st, float)
        return (st - st[0]) / (st[-1] - st[0])

    def _norm_stations_of(vals, st):
        st = np.asarray(st, float)
        return (np.asarray(vals, float) - st[0]) / (st[-1] - st[0])

    design = copy.deepcopy(design)
    turbine = design['turbine']
    tower = turbine['tower']
    if isinstance(tower, list):
        tower = tower[0]
    blade = turbine['blade']
    geom = np.asarray(blade['geometry'], float)
    airfoils = turbine['airfoils']
    af_pos = [float(a[0]) for a in blade['airfoils']]
    af_used = [str(a[1]) for a in blade['airfoils']]
    aoa_grid = np.linspace(-np.pi, np.pi, n_aoa)
    n_af = len(airfoils)
    cl = np.zeros((n_af, n_aoa, 1, 1))
    cd = np.zeros((n_af, n_aoa, 1, 1))
    cm = np.zeros((n_af, n_aoa, 1, 1))
    for i, af in enumerate(airfoils):
        data = np.asarray(af['data'], float)
        aoa_rad = np.deg2rad(data[:, 0])
        cl[i, :, 0, 0] = np.interp(aoa_grid, aoa_rad, data[:, 1])
        cd[i, :, 0, 0] = np.interp(aoa_grid, aoa_rad, data[:, 2])
        cm[i, :, 0, 0] = np.interp(aoa_grid, aoa_rad,
                                   data[:, 3] if data.shape[1] > 3
                                   else np.zeros(len(data)))

    settings = design.get('settings', {})
    cases = design['cases']
    site = design['site']
    platform = design['platform']
    members = platform['members']
    mooring = design['mooring']

    tower_d = tower['d']
    tower_scalar_d = np.isscalar(tower_d)
    tower_scalar_t = np.isscalar(tower['t'])
    tower_scalar_c = np.isscalar(tower['Cd'])
    turbine_options = {
        'npts': 1 if tower_scalar_d else len(np.atleast_1d(tower['stations'])),
        'PC_GS_n': len(turbine['pitch_control']['GS_Angles']),
        'n_span': geom.shape[0],
        'n_aoa': n_aoa, 'n_Re': 1, 'n_tab': 1,
        'n_pc': len(turbine['wt_ops']['v']),
        'n_af': n_af,
        'af_used_names': af_used,
        'shape': tower['shape'],
        'scalar_diameters': tower_scalar_d,
        'scalar_thicknesses': tower_scalar_t,
        'scalar_coefficients': tower_scalar_c,
    }

    member_options = {
        'nmembers': len(members),
        'npts': [], 'npts_lfill': [], 'npts_rho_fill': [], 'ncaps': [],
        'nreps': [], 'shape': [], 'scalar_thicknesses': [],
        'scalar_diameters': [], 'scalar_coefficients': [],
        'n_ballast_type': 2,
    }
    for i, mem in enumerate(members):
        member_options['npts'].append(len(np.atleast_1d(mem['stations'])))
        lf = np.atleast_1d(np.asarray(mem.get('l_fill', []), float))
        member_options['npts_lfill'].append(len(lf) if np.any(lf) or len(lf) > 1 else 0)
        member_options['npts_rho_fill'].append(member_options['npts_lfill'][-1])
        member_options['ncaps'].append(len(np.atleast_1d(
            np.asarray(mem.get('cap_stations', []), float))))
        member_options['nreps'].append(len(np.atleast_1d(
            np.asarray(mem.get('heading', []), float)))
            if 'heading' in mem else 0)
        member_options['shape'].append(mem['shape'])
        member_options['scalar_thicknesses'].append(np.isscalar(mem['t']))
        member_options['scalar_diameters'].append(np.isscalar(mem['d']))
        member_options['scalar_coefficients'].append(np.isscalar(mem['Cd']))
        member_options[f'platform_member{i+1}_potMod'] = bool(
            mem.get('potMod', False))

    mooring_options = {
        'nlines': len(mooring['lines']),
        'nline_types': len(mooring['line_types']),
        'nconnections': len(mooring['points']),
    }
    for i, pt in enumerate(mooring['points']):
        mooring_options[f'mooring_point{i+1}_name'] = pt['name']
        mooring_options[f'mooring_point{i+1}_type'] = pt['type']
    for i, ln in enumerate(mooring['lines']):
        mooring_options[f'mooring_line{i+1}_endA'] = ln['endA']
        mooring_options[f'mooring_line{i+1}_endB'] = ln['endB']
        mooring_options[f'mooring_line{i+1}_type'] = ln['type']
    for i, lt in enumerate(mooring['line_types']):
        mooring_options[f'mooring_line_type{i+1}_name'] = lt['name']

    min_freq = float(settings.get('min_freq', 0.01))
    max_freq = float(settings.get('max_freq', 1.0))
    nfreq = len(np.arange(min_freq, max_freq + 0.5 * min_freq, min_freq))
    modeling_options = {
        'nfreq': nfreq,
        'n_cases': len(cases['data']),
        'xi_start': float(settings.get('XiStart', 0.1)),
        'min_freq': min_freq,
        'max_freq': max_freq,
        'nIter': int(settings.get('nIter', 15)),
        'potential_model_override': int(platform.get('potModMaster', 0)),
        'dls_max': float(platform.get('dlsMax', 5.0)),
        'min_freq_BEM': float(platform.get('min_freq_BEM', min_freq - 1e-7)),
        'raft_dlcs_keys': list(cases['keys']),
        'raft_dlcs': [list(row) for row in cases['data']],
        'trim_ballast': 0,
        'heave_tol': 1.0,
        'save_designs': False, 'plot_designs': False,
    }
    analysis_options = {'general': {'fname_output': 'raft_tpu',
                                    'folder_output': '.'}}

    options = dict(modeling_options=modeling_options,
                   turbine_options=turbine_options,
                   member_options=member_options,
                   mooring_options=mooring_options,
                   analysis_options=analysis_options)

    inputs = {
        'turbine_mRNA': turbine['mRNA'], 'turbine_IxRNA': turbine['IxRNA'],
        'turbine_IrRNA': turbine['IrRNA'],
        'turbine_xCG_RNA': turbine['xCG_RNA'],
        'turbine_hHub': turbine['hHub'],
        'turbine_overhang': turbine['overhang'],
        'turbine_Fthrust': float(turbine.get('Fthrust', 0.0)),
        'turbine_yaw_stiffness': float(platform.get('yaw_stiffness', 0.0)),
        'gear_ratio': float(turbine.get('gear_ratio', 1.0)),
        'turbine_tower_rA': np.asarray(tower['rA'], float),
        'turbine_tower_rB': np.asarray(tower['rB'], float),
        'turbine_tower_gamma': float(tower.get('gamma', 0.0)),
        'turbine_tower_stations': _norm_stations(tower['stations']),
        'turbine_tower_d': tower['d'],
        'turbine_tower_t': tower['t'],
        'turbine_tower_Cd': tower['Cd'], 'turbine_tower_Ca': tower['Ca'],
        'turbine_tower_CdEnd': tower['CdEnd'],
        'turbine_tower_CaEnd': tower['CaEnd'],
        'turbine_tower_rho_shell': float(tower['rho_shell']),
        'rotor_PC_GS_angles': np.asarray(
            turbine['pitch_control']['GS_Angles'], float),
        'rotor_PC_GS_Kp': np.asarray(turbine['pitch_control']['GS_Kp'], float),
        'rotor_PC_GS_Ki': np.asarray(turbine['pitch_control']['GS_Ki'], float),
        'Fl_Kp': float(turbine['pitch_control'].get('Fl_Kp', 0.0)),
        'rotor_inertia': float(turbine.get('I_drivetrain', 0.0)),
        'rotor_TC_VS_Kp': float(turbine['torque_control']['VS_KP']),
        'rotor_TC_VS_Ki': float(turbine['torque_control']['VS_KI']),
        'tilt': float(turbine.get('shaft_tilt', 0.0)),
        'precone': float(turbine.get('precone', 0.0)),
        'wind_reference_height': float(turbine['Zhub']),
        'hub_radius': float(turbine['Rhub']),
        'blade_r': geom[:, 0], 'blade_chord': geom[:, 1],
        'blade_theta': geom[:, 2], 'blade_precurve': geom[:, 3],
        'blade_presweep': geom[:, 4],
        'blade_Rtip': float(blade['Rtip']),
        'blade_precurveTip': float(blade.get('precurveTip', 0.0)),
        'blade_presweepTip': float(blade.get('presweepTip', 0.0)),
        'airfoils_position': np.asarray(af_pos, float),
        'airfoils_r_thick': np.asarray(
            [af.get('relative_thickness', 0.2) for af in airfoils], float),
        'airfoils_aoa': aoa_grid,
        'airfoils_cl': cl, 'airfoils_cd': cd, 'airfoils_cm': cm,
        'rotor_powercurve_v': np.asarray(turbine['wt_ops']['v'], float),
        'rotor_powercurve_omega_rpm': np.asarray(
            turbine['wt_ops']['omega_op'], float),
        'rotor_powercurve_pitch': np.asarray(
            turbine['wt_ops']['pitch_op'], float),
        'rho_air': float(site.get('rho_air', 1.225)),
        'rho_water': float(site.get('rho_water', 1025.0)),
        'mu_air': float(site.get('mu_air', 1.81e-5)),
        'shear_exp': float(site.get('shearExp', 0.2)),
        'rated_rotor_speed': float(np.max(turbine['wt_ops']['omega_op'])),
        'mooring_water_depth': float(site['water_depth']),
    }
    for i, mem in enumerate(members):
        m = f'platform_member{i+1}_'
        inputs[m + 'heading'] = np.atleast_1d(np.asarray(
            mem.get('heading', np.zeros(0)), float))
        inputs[m + 'rA'] = np.asarray(mem['rA'], float)
        inputs[m + 'rB'] = np.asarray(mem['rB'], float)
        inputs[m + 's_ghostA'] = 0.0
        inputs[m + 's_ghostB'] = 1.0
        inputs[m + 'gamma'] = float(mem.get('gamma', 0.0))
        inputs[m + 'stations'] = _norm_stations(mem['stations'])
        for key in ('d', 't', 'Cd', 'Ca', 'CdEnd', 'CaEnd'):
            inputs[m + key] = mem[key]
        inputs[m + 'rho_shell'] = float(mem['rho_shell'])
        st = np.asarray(mem['stations'], float)
        st_span = st[-1] - st[0]
        if member_options['npts_lfill'][i] > 0:
            # WEIS passes fill levels in the normalized station scale
            inputs[m + 'l_fill'] = np.atleast_1d(
                np.asarray(mem['l_fill'], float)) / st_span
            inputs[m + 'rho_fill'] = np.atleast_1d(
                np.asarray(mem['rho_fill'], float))
        if member_options['ncaps'][i] > 0:
            inputs[m + 'cap_stations'] = _norm_stations_of(
                np.atleast_1d(np.asarray(mem['cap_stations'], float)), st)
            inputs[m + 'cap_t'] = np.atleast_1d(
                np.asarray(mem['cap_t'], float))
            inputs[m + 'cap_d_in'] = np.atleast_1d(np.asarray(
                mem.get('cap_d_in', np.zeros_like(inputs[m + 'cap_t'])), float))
    for i, pt in enumerate(mooring['points']):
        inputs[f'mooring_point{i+1}_location'] = np.asarray(
            pt['location'], float)
    for i, ln in enumerate(mooring['lines']):
        inputs[f'mooring_line{i+1}_length'] = float(ln['length'])
    for i, lt in enumerate(mooring['line_types']):
        for prop in ('diameter', 'mass_density', 'stiffness', 'breaking_load',
                     'cost', 'transverse_added_mass', 'tangential_added_mass',
                     'transverse_drag', 'tangential_drag'):
            inputs[f'mooring_line_type{i+1}_{prop}'] = float(
                lt.get(prop, 0.0))

    discrete_inputs = {
        'nBlades': int(turbine.get('nBlades', 3)),
        'airfoils_name': [af['name'] for af in airfoils],
    }
    return options, inputs, discrete_inputs
