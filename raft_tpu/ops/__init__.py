from raft_tpu.ops import geometry, spectra, transforms, waves  # noqa: F401
