"""Frustum volume/centroid/inertia primitives for member geometry.

Reference: raft/helpers.py:36-63 (FrustumVCV) and raft/raft_member.py:321-402
(FrustumMOI, RectangularFrustumMOI).  These run at model-build time *and*
inside jitted design sweeps (geometry is a differentiable design variable),
so they are written as pure jnp with circular/rectangular variants split
into separate functions instead of the reference's isinstance branching.
"""
from __future__ import annotations

import jax.numpy as jnp


def frustum_vcv_circ(dA, dB, H):
    """Volume and center-of-volume height of a circular frustum with end
    diameters dA (bottom), dB (top) and height H.  Batched elementwise.
    Returns (V, hc) with hc measured from the dA end."""
    dA, dB, H = jnp.asarray(dA, float), jnp.asarray(dB, float), jnp.asarray(H, float)
    A1 = (jnp.pi / 4) * dA**2
    A2 = (jnp.pi / 4) * dB**2
    Am = (jnp.pi / 4) * dA * dB
    denom = A1 + Am + A2
    V = denom * H / 3.0
    hc = jnp.where(denom > 0, ((A1 + 2 * Am + 3 * A2) / jnp.where(denom > 0, denom, 1.0)) * H / 4.0, 0.0)
    return V, hc


def frustum_vcv_rect(slA, slB, H):
    """Rectangular (pyramidal) frustum volume/centroid; slA, slB are (...,2)
    side-length pairs at the two ends."""
    slA, slB, H = jnp.asarray(slA, float), jnp.asarray(slB, float), jnp.asarray(H, float)
    A1 = slA[..., 0] * slA[..., 1]
    A2 = slB[..., 0] * slB[..., 1]
    Am = jnp.sqrt(A1 * A2)
    denom = A1 + Am + A2
    V = denom * H / 3.0
    hc = jnp.where(denom > 0, ((A1 + 2 * Am + 3 * A2) / jnp.where(denom > 0, denom, 1.0)) * H / 4.0, 0.0)
    return V, hc


def frustum_moi_circ(dA, dB, H, p):
    """Axial (Izz) and transverse (Ixx=Iyy) moments of inertia of a solid
    circular frustum about the center of its *bottom* end, density p.
    Closed-form integrals of r(z) = rA + (rB-rA) z/H (matches reference
    raft/raft_member.py:321-339)."""
    dA, dB, H = jnp.asarray(dA, float), jnp.asarray(dB, float), jnp.asarray(H, float)
    rA, rB = 0.5 * dA, 0.5 * dB
    # cylinder detection must be a RELATIVE tolerance, not ==: derived cap
    # diameters like dB*(dAi/dA) can differ from dAi by 1 ulp, and the
    # tapered closed form divides (rB^5 - rA^5) by (rB - rA) — at
    # ulp-level taper that quotient is catastrophic-cancellation noise
    # (the reference's exact dA==dB check has this bug,
    # raft_member.py:327-336; its OC4semi ring-cap MoI carries ~15% fp
    # noise as a result)
    cyl = jnp.abs(rB - rA) <= 1e-9 * jnp.maximum(jnp.abs(rA), jnp.abs(rB))
    m = jnp.where(H > 0, (rB - rA) / jnp.where(H > 0, H, 1.0), 0.0)
    m = jnp.where(cyl, 0.0, m)
    m_safe = jnp.where(m == 0, 1.0, m)
    Izz_t = (jnp.pi * p / (10.0 * m_safe)) * (rB**5 - rA**5)
    Ixx_t = jnp.pi * p * (
        H**3 / 30.0 * (rA**2 + 3.0 * rA * rB + 6.0 * rB**2)
        + 1.0 / 20.0 / m_safe * (rB**5 - rA**5)
    )
    Izz_cyl = 0.5 * jnp.pi * p * H * rA**4
    Ixx_cyl = jnp.pi * p * H * (rA**4 / 4.0 + (H**2 * rA**2) / 3.0)
    Izz = jnp.where(m == 0, Izz_cyl, Izz_t)
    Ixx = jnp.where(m == 0, Ixx_cyl, Ixx_t)
    return Ixx, Izz


def frustum_moi_rect(slA, slB, H, p):
    """Moments of inertia of a solid rectangular frustum about the center of
    its bottom end; slA/slB are (...,2) side pairs (matches reference
    raft/raft_member.py:341-402 semantics via direct z-integration of the
    linearly-interpolated cross-section)."""
    slA, slB, H = jnp.asarray(slA, float), jnp.asarray(slB, float), jnp.asarray(H, float)
    # cross-section sides a(z), b(z) are linear in z, so the integrands are
    # polynomials of degree <= 5; 8-point Gauss-Legendre (exact to degree 15)
    # integrates them exactly
    xg, wg = _GL8
    z = H[..., None] * xg
    t = jnp.where(H[..., None] > 0, z / jnp.where(H[..., None] > 0, H[..., None], 1.0), 0.0)
    a = slA[..., 0:1] * (1 - t) + slB[..., 0:1] * t
    b = slA[..., 1:2] * (1 - t) + slB[..., 1:2] * t
    w = H[..., None] * wg
    Izz = jnp.sum(w * p * (a * b) * (a**2 + b**2) / 12.0, axis=-1)
    Ixx = jnp.sum(w * p * ((a * b**3) / 12.0 + a * b * z**2), axis=-1)
    Iyy = jnp.sum(w * p * ((b * a**3) / 12.0 + a * b * z**2), axis=-1)
    return Ixx, Iyy, Izz


def _gl8():
    import numpy as np

    x, w = np.polynomial.legendre.leggauss(8)
    return (0.5 * (x + 1.0)), (0.5 * w)


_GL8 = _gl8()
