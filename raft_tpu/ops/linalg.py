"""Linear-algebra helpers for TPU compatibility.

TPU backends implement real LU/triangular solves but NOT complex ones
(jnp.linalg.solve on complex inputs raises UNIMPLEMENTED on TPU).  The
frequency-domain impedance solves Z X = F are complex, so they run through
a real 2n x 2n block embedding

    [Re Z  -Im Z] [Re X]   [Re F]
    [Im Z   Re Z] [Im X] = [Im F]

which is mathematically identical and uses only real kernels, keeping one
code path across CPU/GPU/TPU.

For huge batches of tiny systems (the RAO solve: ~2e5 12x12 real blocks
at 1024 variants x 200 bins), XLA:TPU's LuDecompositionBlock custom-call
is catastrophically slow (~600 ms per solve batch, 80%+ of the whole
variant pipeline as profiled with xprof).  `gauss_jordan_solve` is a
lane-batched, fully unrolled Gauss-Jordan elimination with partial
pivoting whose ops are all elementwise over the batch — ~100x faster for
this shape regime.  It is used automatically for small n with a large
batch; LAPACK/LU handles everything else.

On top of that sits the Pallas twin (ops/pallas/gj_solve.py): the same
algorithm as one VMEM-resident kernel (no HBM round-trip per pivot
step), with the impedance assembly Z = -w^2 M + i w B + C fused into
the kernel's load stage via `impedance_solve` so Z never reaches HBM.
Dispatch is governed by the RAFT_TPU_PALLAS knob (_config.pallas_mode):
"auto" picks it exactly where the jnp Gauss-Jordan would have been
picked, "1" forces it everywhere (interpret mode on CPU — the CI parity
path), "0" disables it.  Every decision is recorded for the run
manifests via `last_dispatch()`.
"""
from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp

from raft_tpu import _config
from raft_tpu.ops import precision as _prec
from raft_tpu.ops.precision import equilibration_eps


def gauss_jordan_solve(A, b, refine: int = 1):
    """Solve A x = b for real A (..., n, n), b (..., n, k) by unrolled
    Gauss-Jordan elimination with partial pivoting, vectorized over the
    (flattened) leading batch.  Intended for small static n (<= ~16) and
    large batches; all operations are elementwise/broadcast over the
    batch axis placed LAST (TPU lane dimension).

    Rows are equilibrated (scaled by 1/max|row|) so partial pivoting is
    meaningful for systems mixing force and moment rows (~1e7 vs ~1e12
    scales in the impedance blocks), and ``refine`` steps of iterative
    refinement (residual re-solve) recover LU-level accuracy."""
    A = jnp.asarray(A)
    b = jnp.asarray(b)
    n = A.shape[-1]
    k = b.shape[-1]
    batch = A.shape[:-2]
    B = int(np.prod(batch)) if batch else 1
    Af = A.reshape(B, n, n)
    bf = b.reshape(B, n, k)
    # row equilibration: D A x = D b with D = 1/max|row| (shared
    # dtype-aware underflow floor — the ladder's single source)
    scale = 1.0 / jnp.maximum(jnp.max(jnp.abs(Af), axis=-1, keepdims=True),
                              equilibration_eps(Af.dtype))
    Af = Af * scale
    bf = bf * scale
    x = _gj_core(Af, bf, n, k)
    for _ in range(refine):
        r = bf - jnp.einsum("bij,bjk->bik", Af, x)
        x = x + _gj_core(Af, r, n, k)
    return x.reshape(*batch, n, k)


def _gj_core(Af, bf, n, k):
    B = Af.shape[0]
    M = jnp.concatenate([Af, bf], axis=-1)
    M = jnp.moveaxis(M, 0, -1)                     # (n, n+k, B)
    rows = jnp.arange(n, dtype=jnp.int32)
    for kk in range(n):                            # static unroll
        col = M[:, kk, :]                          # (n, B)
        mag = jnp.where((rows >= kk)[:, None], jnp.abs(col), -jnp.inf)
        p = jnp.argmax(mag, axis=0)                # (B,) pivot row index
        sel = (rows[:, None] == p[None, :]).astype(M.dtype)      # (n, B)
        ek = (rows == kk).astype(M.dtype)          # (n,)
        pivrow = jnp.sum(sel[:, None, :] * M, axis=0)            # (n+k, B)
        rowk = M[kk, :, :]                         # (n+k, B)
        # swap rows kk <-> p (no-op when p == kk)
        M = (M + ek[:, None, None] * (pivrow - rowk)[None, :, :]
             + sel[:, None, :] * (rowk - pivrow)[None, :, :])
        piv = pivrow[kk, :]                        # (B,)
        rowk_n = pivrow / piv[None, :]
        colk = M[:, kk, :] * (1.0 - ek)[:, None]   # exclude pivot row
        M = M - colk[:, None, :] * rowk_n[None, :, :]
        M = M.at[kk, :, :].set(rowk_n)
    return jnp.moveaxis(M[:, n:, :], -1, 0)        # (B, n, k)


# ---------------------------------------------------------------------------
# mixed-precision ladder (batch-first twin of the in-kernel ladder in
# ops/pallas/gj_solve.py — used by the jnp-GJ and LU backends so every
# RAFT_TPU_PALLAS mode honors RAFT_TPU_PRECISION)
# ---------------------------------------------------------------------------

def _mixed_ladder(A, b, core_low, core_hi, refine, factor_dtype, tol):
    """Equilibrate at the input width, factorize/solve at
    ``factor_dtype`` via ``core_low(Af, rhs_f) -> x_f``, accumulate the
    refinement residual and correction at the input width, then
    promote: lanes whose final max relative residual exceeds ``tol``
    are re-solved at the full width via ``core_hi`` in a second pass in
    which non-promoted lanes are masked to identity systems — and the
    pass is skipped entirely (``lax.cond``) when nothing promoted.

    A (B, n, n), b (B, n, k) batch-first; returns
    (x, {"promoted", "lanes", "resid_max"})."""
    B, n, _ = A.shape
    eps = equilibration_eps(A.dtype)
    scale = 1.0 / jnp.maximum(jnp.max(jnp.abs(A), axis=-1, keepdims=True),
                              eps)
    As = A * scale
    bs = b * scale
    Af = As.astype(factor_dtype)
    x = core_low(Af, bs.astype(factor_dtype)).astype(A.dtype)
    for _ in range(refine):
        r = bs - jnp.einsum("bij,bjk->bik", As, x)
        x = x + core_low(Af, r.astype(factor_dtype)).astype(A.dtype)
    r = bs - jnp.einsum("bij,bjk->bik", As, x)
    rn = (jnp.max(jnp.abs(r), axis=(-2, -1))
          / (jnp.max(jnp.abs(bs), axis=(-2, -1)) + eps))     # (B,)
    mask, promoted = _prec.promotion_mask(rn, tol)

    def _resolve(xm):
        m = mask[:, None, None]
        eye = jnp.broadcast_to(jnp.eye(n, dtype=As.dtype), As.shape)
        xh = core_hi(jnp.where(m, As, eye),
                     jnp.where(m, bs, jnp.zeros((), bs.dtype)))
        return jnp.where(m, xh, xm)

    x = jax.lax.cond(promoted > 0, _resolve, lambda xm: xm, x)
    return x, {"promoted": promoted, "lanes": B,
               "resid_max": jnp.max(rn)}


def _precision_plan(dtype) -> dict:
    """Resolve the ambient ``RAFT_TPU_PRECISION`` request against the
    (real-embedded) solve dtype at trace time.

    Returns the dispatch facts plus the actionable pieces:
    ``mode`` (requested), ``solve_width``/``factor_width`` (resolved
    names), ``factor`` (jnp dtype or None — None means single-width),
    ``cast`` (dtype to force the whole solve to, or None), ``tol``
    (promotion tolerance, mixed only).  A mixed request whose factor
    width is not strictly below the input width degenerates to the
    native solve — recorded, never silent."""
    from raft_tpu import _config

    mode = _config.precision_mode()
    dt = jnp.dtype(dtype)
    plan = {"mode": mode, "solve_width": _prec.width_name(dt),
            "factor": None, "factor_width": None, "cast": None,
            "tol": None}
    if mode == "mixed":
        fd = _prec.factor_dtype(_config.precision_width())
        if _prec.narrows(fd, dt):
            plan.update(factor=fd, factor_width=_prec.width_name(fd),
                        tol=_config.precision_tol())
        else:
            plan["degenerate"] = True
    elif mode == "f32" and dt != jnp.dtype(jnp.float32):
        plan.update(cast=jnp.dtype(jnp.float32), solve_width="f32")
    return plan


def _probe_promoted(stats):
    """Stream the mixed ladder's runtime promotion counts through the
    sanctioned on-device probe channel (metric:
    ``raft_tpu_probe_value{probe="solve_promoted_lanes"}`` + flight
    recorder); trace-time no-op under RAFT_TPU_PROBES=off."""
    try:
        from raft_tpu.obs import probes
        probes.probe("solve_promoted_lanes", promoted=stats["promoted"],
                     lanes=stats["lanes"], resid_max=stats["resid_max"])
    # telemetry emission must never fail a solve (obs layer contract)
    except Exception:  # pragma: no cover  # raftlint: disable=RTL004
        pass


#: above this many systems of size <= _GJ_MAX_N, prefer Gauss-Jordan on TPU
_GJ_MAX_N = 16
_GJ_MIN_BATCH = 4096


def _use_gauss_jordan(n, batch_elems):
    if n > _GJ_MAX_N or batch_elems < _GJ_MIN_BATCH:
        return False
    # any accelerator backend (tpu / axon tunnel / gpu): LAPACK-quality
    # batched LU is only available on cpu, and the TPU LU custom call is
    # the pathological case this kernel replaces
    return jax.default_backend() != "cpu"


def _use_pallas(n, batch_elems):
    """Whether the Pallas VMEM-resident kernel handles this (real
    embedded) shape, per the RAFT_TPU_PALLAS mode: "1" forces it (CI
    runs the kernel under interpret mode on CPU), "0" forbids it, and
    "auto" uses it exactly where the jnp Gauss-Jordan would have been
    picked (accelerator backend, small n, large batch)."""
    from raft_tpu import _config

    mode = _config.pallas_mode()
    if mode == "0":
        return False
    if mode == "1":
        return True
    return _use_gauss_jordan(n, batch_elems)


#: trace-time record of the most recent backend dispatch — the solver
#: fact the run manifests and bench JSON report
_LAST_DISPATCH: dict = {}

#: trace-time flag: the dispatch being recorded is an ADJOINT solve
#: (the backward pass of the implicit-diff custom_vjp below) — folded
#: into ``last_dispatch()`` so manifests/tests can assert that gradient
#: plumbing reuses the forward dispatch ladder instead of growing a
#: second linear-solve implementation.  THREAD-LOCAL: the serve stack
#: traces forward batches (sweep worker) and backward descents
#: (optimize worker) concurrently, and a process-global flag would
#: cross-stamp their dispatch facts.
import threading as _threading

_ADJOINT_TLS = _threading.local()


def _adjoint_active() -> bool:
    return bool(getattr(_ADJOINT_TLS, "active", False))


def last_dispatch() -> dict:
    """Most recent solve-backend dispatch decision (made at trace time):
    ``{"backend", "n", "batch_elems", "fused", "precision",
    "solve_width", "factor_width", "promote_tol"}``.  Empty before any
    solve has been traced in this process."""
    return dict(_LAST_DISPATCH)


def _record_dispatch(backend: str, n, batch_elems, fused: bool = False,
                     plan: dict = None):
    # cleared, not merged: a later single-width dispatch must not keep
    # wearing an earlier mixed dispatch's precision facts
    _LAST_DISPATCH.clear()
    _LAST_DISPATCH.update(backend=backend, n=int(n),
                          batch_elems=int(batch_elems), fused=bool(fused))
    if _adjoint_active():
        _LAST_DISPATCH["adjoint"] = True
    if plan is not None:
        _LAST_DISPATCH.update(
            precision=plan["mode"], solve_width=plan["solve_width"],
            factor_width=plan["factor_width"], promote_tol=plan["tol"])
        if plan.get("degenerate"):
            _LAST_DISPATCH["precision_degenerate"] = True
    try:
        from raft_tpu import obs
        obs.record_solve_dispatch(backend, n, batch_elems, fused=fused)
    # telemetry emission must never fail a solve (obs layer contract)
    except Exception:  # pragma: no cover  # raftlint: disable=RTL004
        pass


def _solve_real_embedded(M, rhs, n2, batch_elems):
    """Dispatch the real-embedded solve M x = rhs per the active
    RAFT_TPU_PALLAS x RAFT_TPU_PRECISION modes; returns x at the input
    width (precision "f32" casts down for the solve and back up)."""
    in_dtype = M.dtype
    plan = _precision_plan(in_dtype)
    if plan["cast"] is not None:
        M = M.astype(plan["cast"])
        rhs = rhs.astype(plan["cast"])
    mixed = plan["factor"] is not None
    k = rhs.shape[-1]
    batch = M.shape[:-2]
    if _use_pallas(n2, batch_elems):
        from raft_tpu.ops.pallas.gj_solve import gj_solve
        _record_dispatch("pallas_gj", n2, batch_elems, plan=plan)
        if mixed:
            x, stats = gj_solve(M, rhs, refine=2, precision="mixed",
                                factor_dtype=plan["factor"],
                                promote_tol=plan["tol"],
                                return_stats=True)
            _probe_promoted(stats)
        else:
            x = gj_solve(M, rhs)
    elif _use_gauss_jordan(n2, batch_elems):
        _record_dispatch("jnp_gj", n2, batch_elems, plan=plan)
        if mixed:
            B = int(np.prod(batch)) if batch else 1
            Mf = M.reshape(B, n2, n2)
            rf = rhs.reshape(B, n2, k)

            def _hi(a, r):
                xh = _gj_core(a, r, n2, k)
                rr = r - jnp.einsum("bij,bjk->bik", a, xh)
                return xh + _gj_core(a, rr, n2, k)

            x, stats = _mixed_ladder(
                Mf, rf, lambda a, r: _gj_core(a, r, n2, k), _hi,
                refine=2, factor_dtype=plan["factor"], tol=plan["tol"])
            _probe_promoted(stats)
            x = x.reshape(*batch, n2, k)
        else:
            x = gauss_jordan_solve(M, rhs)
    else:
        _record_dispatch("lu", n2, batch_elems, plan=plan)
        if mixed:
            B = int(np.prod(batch)) if batch else 1
            # LAPACK LU has no bf16 kernel — the bf16 low rung on this
            # backend runs the jnp Gauss-Jordan core instead (the high
            # rung and the promotion pass stay on LU)
            low = (jnp.linalg.solve
                   if jnp.dtype(plan["factor"]) != jnp.dtype(jnp.bfloat16)
                   else (lambda a, r: _gj_core(a, r, n2, k)))
            x, stats = _mixed_ladder(
                M.reshape(B, n2, n2), rhs.reshape(B, n2, k),
                low, jnp.linalg.solve,
                refine=2, factor_dtype=plan["factor"], tol=plan["tol"])
            _probe_promoted(stats)
            x = x.reshape(*batch, n2, k)
        else:
            x = jnp.linalg.solve(M, rhs)
    return x.astype(in_dtype)


def solve_complex(A, b):
    """Solve A x = b for complex A (..., n, n) and b (..., n) or (..., n, k)
    via the real block embedding (TPU-safe)."""
    A = jnp.asarray(A)
    b = jnp.asarray(b)
    n = A.shape[-1]
    vec = b.ndim == A.ndim - 1
    if vec:
        b = b[..., None]
    Ar, Ai = jnp.real(A), jnp.imag(A)
    M = jnp.concatenate([
        jnp.concatenate([Ar, -Ai], axis=-1),
        jnp.concatenate([Ai, Ar], axis=-1),
    ], axis=-2)
    rhs = jnp.concatenate([jnp.real(b), jnp.imag(b)], axis=-2)
    batch_elems = int(np.prod(A.shape[:-2])) if A.ndim > 2 else 1
    x = _solve_real_embedded(M, rhs, 2 * n, batch_elems)
    out = x[..., :n, :] + 1j * x[..., n:, :]
    return out[..., 0] if vec else out


def _impedance_solve_impl(w, M, B, C, F):
    """Dispatch body of :func:`impedance_solve` (see its docstring).
    Split out so the implicit-diff backward pass below can run the
    ADJOINT solve through the identical Pallas/jnp/LU + precision
    ladder without re-entering the custom_vjp wrapper."""
    # fault-injection seam (trace time): raise@kernel makes this
    # dispatch fail as a typed KernelFailure so the degradation ladder
    # (Pallas -> jnp -> host) is testable on CPU without breaking a
    # real kernel.  Ambient case context is pushed by the case loop.
    from raft_tpu.testing import faults
    faults.maybe_raise("kernel")
    w = jnp.asarray(w)
    M = jnp.asarray(M)
    B = jnp.asarray(B)
    C = jnp.asarray(C)
    F = jnp.asarray(F)
    n = M.shape[-3]
    nw = M.shape[-1]
    batch = M.shape[:-3]
    batch_elems = (int(np.prod(batch)) if batch else 1) * nw
    if _use_pallas(2 * n, batch_elems):
        from raft_tpu.ops.pallas.gj_solve import impedance_gj_solve
        in_dtype = M.dtype
        out_ctype = jnp.result_type(in_dtype, jnp.complex64)
        plan = _precision_plan(in_dtype)
        _record_dispatch("pallas_fused", 2 * n, batch_elems, fused=True,
                         plan=plan)
        if plan["cast"] is not None:
            c32 = jnp.result_type(plan["cast"], jnp.complex64)
            X = impedance_gj_solve(w.astype(plan["cast"]),
                                   M.astype(plan["cast"]),
                                   B.astype(plan["cast"]),
                                   C.astype(plan["cast"]),
                                   F.astype(c32))
            return X.astype(out_ctype)
        if plan["factor"] is not None:
            X, stats = impedance_gj_solve(
                w, M, B, C, F, refine=2, precision="mixed",
                factor_dtype=plan["factor"], promote_tol=plan["tol"],
                return_stats=True)
            _probe_promoted(stats)
            return X
        return impedance_gj_solve(w, M, B, C, F)
    Z = (-w ** 2 * M + 1j * w * B
         + C[..., None]).astype(_config.complex_dtype())
    Xin = solve_complex(jnp.moveaxis(Z, -1, -3), jnp.moveaxis(F, -1, -2))
    return jnp.moveaxis(Xin, -2, -1)


# ---------------------------------------------------------------------------
# implicit differentiation: the impedance solve as a custom_vjp whose
# backward pass is ONE adjoint solve through the same dispatch ladder
# ---------------------------------------------------------------------------

import contextlib as _contextlib


@_contextlib.contextmanager
def _adjoint_scope():
    """Trace-time marker (per thread): dispatches recorded inside are
    adjoint solves (``last_dispatch()["adjoint"] == True``)."""
    prev = _adjoint_active()
    _ADJOINT_TLS.active = True
    try:
        yield
    finally:
        _ADJOINT_TLS.active = prev


def _probe_gate():
    """Probe-suppression context for the custom_vjp fwd/bwd rules —
    jax.custom_vjp cannot carry host-callback effects in its fwd/bwd
    jaxprs, so the differentiated path traces callback-free (the
    primal, non-differentiated path keeps its live probes)."""
    try:
        from raft_tpu.obs import probes
        return probes.suppress("implicit-diff fwd/bwd rule")
    # obs layer must never fail a solve; tracing proceeds un-gated
    except Exception:  # pragma: no cover  # raftlint: disable=RTL004
        return _contextlib.nullcontext()


def _unbroadcast(x, shape):
    """Sum-reduce a cotangent down to the primal's (broadcast-origin)
    shape — the standard transpose of implicit numpy broadcasting."""
    x = jnp.asarray(x)
    if tuple(x.shape) == tuple(shape):
        return x
    extra = x.ndim - len(shape)
    if extra > 0:
        x = jnp.sum(x, axis=tuple(range(extra)))
    axes = tuple(i for i, (a, b) in enumerate(zip(x.shape, shape))
                 if a != b)
    if axes:
        x = jnp.sum(x, axis=axes, keepdims=True)
    return x.reshape(shape)


@jax.custom_vjp
def impedance_solve(w, M, B, C, F):
    """Solve the frequency-domain impedance system

        [-w^2 M + i w B + C] X(w) = F(w)

    over the trailing frequency axis: w (nw,) real, M/B (..., n, n, nw)
    real, C (..., n, n) real, F (..., n, nw) complex -> X (..., n, nw)
    complex.

    Dispatch: the fused Pallas kernel when enabled for the shape (the
    assembly happens in the kernel's VMEM load stage — Z is never
    written to HBM), otherwise the pre-existing assemble-then-
    ``solve_complex`` path, kept bitwise identical to the inline
    assembly the sweep/variant/model callers used to carry.

    Differentiable by construction (``custom_vjp``): the backward pass
    is the implicit-function adjoint — ONE solve with the transposed
    impedance ``Z^T λ = X̄`` dispatched through this very function, so
    the Pallas/jnp/LU rungs AND the mixed-precision ladder apply to
    adjoint solves identically, and ``last_dispatch()`` records
    ``adjoint=True`` for them.  The cotangent algebra (plain-transpose,
    conjugation-free, real parts onto the real inputs) matches JAX's
    native linear-solve VJP to machine precision — pinned by
    ``tests/test_optimize.py``."""
    return _impedance_solve_impl(w, M, B, C, F)


def _impedance_solve_fwd(w, M, B, C, F):
    with _probe_gate():
        X = _impedance_solve_impl(w, M, B, C, F)
    return X, (jnp.asarray(w), jnp.asarray(M), jnp.asarray(B),
               jnp.asarray(C), X)


def _impedance_solve_bwd(res, Xbar):
    w, M, B, C, X = res
    # adjoint system: Z^T λ = X̄ with Z^T = -w² M^T + i w B^T + C^T —
    # i.e. the SAME impedance solve on the transposed blocks, riding
    # the full dispatch ladder (and recorded as an adjoint dispatch)
    with _adjoint_scope(), _probe_gate():
        lam = _impedance_solve_impl(
            w, jnp.swapaxes(M, -3, -2), jnp.swapaxes(B, -3, -2),
            jnp.swapaxes(C, -2, -1), Xbar)
    # Z̄[..., i, j, w] = -λ_i X_j (plain outer product per frequency);
    # real inputs take the real part of their holomorphic chain
    Zbar = -lam[..., :, None, :] * X[..., None, :, :]
    Mbar = _unbroadcast(jnp.real(-w ** 2 * Zbar), jnp.shape(M)
                        ).astype(M.dtype)
    Bbar = _unbroadcast(jnp.real(1j * w * Zbar), jnp.shape(B)
                        ).astype(B.dtype)
    Cbar = _unbroadcast(jnp.real(jnp.sum(Zbar, axis=-1)), jnp.shape(C)
                        ).astype(C.dtype)
    Fbar = lam
    # frequency-grid cotangent: ∂Z/∂w = -2wM + iB per bin, contracted
    # against Z̄ over every non-frequency axis (frequency-sensitivity
    # studies get the true gradient, not a silent zero)
    wbar = _unbroadcast(
        jnp.real(jnp.sum(
            Zbar * (-2.0 * w * M + 1j * B),
            axis=tuple(range(Zbar.ndim - 1)))), jnp.shape(w)
        ).astype(w.dtype)
    return (wbar, Mbar, Bbar, Cbar, Fbar)


impedance_solve.defvjp(_impedance_solve_fwd, _impedance_solve_bwd)


def inv_complex(A):
    """Inverse of complex A (..., n, n) via the real block embedding
    (TPU-safe).  Used to factor the system impedance once and reuse it
    across excitation sources (the reference's Zinv, raft_model.py:
    1038-1040)."""
    A = jnp.asarray(A)
    n = A.shape[-1]
    eye = jnp.broadcast_to(jnp.eye(n, dtype=A.dtype), A.shape)
    return solve_complex(A, eye)
