"""Linear-algebra helpers for TPU compatibility.

TPU backends implement real LU/triangular solves but NOT complex ones
(jnp.linalg.solve on complex inputs raises UNIMPLEMENTED on TPU).  The
frequency-domain impedance solves Z X = F are complex, so they run through
a real 2n x 2n block embedding

    [Re Z  -Im Z] [Re X]   [Re F]
    [Im Z   Re Z] [Im X] = [Im F]

which is mathematically identical and uses only real kernels, keeping one
code path across CPU/GPU/TPU.
"""
from __future__ import annotations

import jax.numpy as jnp


def solve_complex(A, b):
    """Solve A x = b for complex A (..., n, n) and b (..., n) or (..., n, k)
    via the real block embedding (TPU-safe)."""
    A = jnp.asarray(A)
    b = jnp.asarray(b)
    n = A.shape[-1]
    vec = b.ndim == A.ndim - 1
    if vec:
        b = b[..., None]
    Ar, Ai = jnp.real(A), jnp.imag(A)
    M = jnp.concatenate([
        jnp.concatenate([Ar, -Ai], axis=-1),
        jnp.concatenate([Ai, Ar], axis=-1),
    ], axis=-2)
    rhs = jnp.concatenate([jnp.real(b), jnp.imag(b)], axis=-2)
    x = jnp.linalg.solve(M, rhs)
    out = x[..., :n, :] + 1j * x[..., n:, :]
    return out[..., 0] if vec else out


def inv_complex(A):
    """Inverse of complex A (..., n, n) via the real block embedding
    (TPU-safe).  Used to factor the system impedance once and reuse it
    across excitation sources (the reference's Zinv, raft_model.py:
    1038-1040)."""
    A = jnp.asarray(A)
    n = A.shape[-1]
    eye = jnp.broadcast_to(jnp.eye(n, dtype=A.dtype), A.shape)
    return solve_complex(A, eye)
