"""Linear-algebra helpers for TPU compatibility.

TPU backends implement real LU/triangular solves but NOT complex ones
(jnp.linalg.solve on complex inputs raises UNIMPLEMENTED on TPU).  The
frequency-domain impedance solves Z X = F are complex, so they run through
a real 2n x 2n block embedding

    [Re Z  -Im Z] [Re X]   [Re F]
    [Im Z   Re Z] [Im X] = [Im F]

which is mathematically identical and uses only real kernels, keeping one
code path across CPU/GPU/TPU.

For huge batches of tiny systems (the RAO solve: ~2e5 12x12 real blocks
at 1024 variants x 200 bins), XLA:TPU's LuDecompositionBlock custom-call
is catastrophically slow (~600 ms per solve batch, 80%+ of the whole
variant pipeline as profiled with xprof).  `gauss_jordan_solve` is a
lane-batched, fully unrolled Gauss-Jordan elimination with partial
pivoting whose ops are all elementwise over the batch — ~100x faster for
this shape regime.  It is used automatically for small n with a large
batch; LAPACK/LU handles everything else.

On top of that sits the Pallas twin (ops/pallas/gj_solve.py): the same
algorithm as one VMEM-resident kernel (no HBM round-trip per pivot
step), with the impedance assembly Z = -w^2 M + i w B + C fused into
the kernel's load stage via `impedance_solve` so Z never reaches HBM.
Dispatch is governed by the RAFT_TPU_PALLAS knob (_config.pallas_mode):
"auto" picks it exactly where the jnp Gauss-Jordan would have been
picked, "1" forces it everywhere (interpret mode on CPU — the CI parity
path), "0" disables it.  Every decision is recorded for the run
manifests via `last_dispatch()`.
"""
from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp


def gauss_jordan_solve(A, b, refine: int = 1):
    """Solve A x = b for real A (..., n, n), b (..., n, k) by unrolled
    Gauss-Jordan elimination with partial pivoting, vectorized over the
    (flattened) leading batch.  Intended for small static n (<= ~16) and
    large batches; all operations are elementwise/broadcast over the
    batch axis placed LAST (TPU lane dimension).

    Rows are equilibrated (scaled by 1/max|row|) so partial pivoting is
    meaningful for systems mixing force and moment rows (~1e7 vs ~1e12
    scales in the impedance blocks), and ``refine`` steps of iterative
    refinement (residual re-solve) recover LU-level accuracy."""
    A = jnp.asarray(A)
    b = jnp.asarray(b)
    n = A.shape[-1]
    k = b.shape[-1]
    batch = A.shape[:-2]
    B = int(np.prod(batch)) if batch else 1
    Af = A.reshape(B, n, n)
    bf = b.reshape(B, n, k)
    # row equilibration: D A x = D b with D = 1/max|row|
    scale = 1.0 / jnp.maximum(jnp.max(jnp.abs(Af), axis=-1, keepdims=True),
                              1e-300 if Af.dtype == jnp.float64 else 1e-30)
    Af = Af * scale
    bf = bf * scale
    x = _gj_core(Af, bf, n, k)
    for _ in range(refine):
        r = bf - jnp.einsum("bij,bjk->bik", Af, x)
        x = x + _gj_core(Af, r, n, k)
    return x.reshape(*batch, n, k)


def _gj_core(Af, bf, n, k):
    B = Af.shape[0]
    M = jnp.concatenate([Af, bf], axis=-1)
    M = jnp.moveaxis(M, 0, -1)                     # (n, n+k, B)
    rows = jnp.arange(n, dtype=jnp.int32)
    for kk in range(n):                            # static unroll
        col = M[:, kk, :]                          # (n, B)
        mag = jnp.where((rows >= kk)[:, None], jnp.abs(col), -jnp.inf)
        p = jnp.argmax(mag, axis=0)                # (B,) pivot row index
        sel = (rows[:, None] == p[None, :]).astype(M.dtype)      # (n, B)
        ek = (rows == kk).astype(M.dtype)          # (n,)
        pivrow = jnp.sum(sel[:, None, :] * M, axis=0)            # (n+k, B)
        rowk = M[kk, :, :]                         # (n+k, B)
        # swap rows kk <-> p (no-op when p == kk)
        M = (M + ek[:, None, None] * (pivrow - rowk)[None, :, :]
             + sel[:, None, :] * (rowk - pivrow)[None, :, :])
        piv = pivrow[kk, :]                        # (B,)
        rowk_n = pivrow / piv[None, :]
        colk = M[:, kk, :] * (1.0 - ek)[:, None]   # exclude pivot row
        M = M - colk[:, None, :] * rowk_n[None, :, :]
        M = M.at[kk, :, :].set(rowk_n)
    return jnp.moveaxis(M[:, n:, :], -1, 0)        # (B, n, k)


#: above this many systems of size <= _GJ_MAX_N, prefer Gauss-Jordan on TPU
_GJ_MAX_N = 16
_GJ_MIN_BATCH = 4096


def _use_gauss_jordan(n, batch_elems):
    if n > _GJ_MAX_N or batch_elems < _GJ_MIN_BATCH:
        return False
    # any accelerator backend (tpu / axon tunnel / gpu): LAPACK-quality
    # batched LU is only available on cpu, and the TPU LU custom call is
    # the pathological case this kernel replaces
    return jax.default_backend() != "cpu"


def _use_pallas(n, batch_elems):
    """Whether the Pallas VMEM-resident kernel handles this (real
    embedded) shape, per the RAFT_TPU_PALLAS mode: "1" forces it (CI
    runs the kernel under interpret mode on CPU), "0" forbids it, and
    "auto" uses it exactly where the jnp Gauss-Jordan would have been
    picked (accelerator backend, small n, large batch)."""
    from raft_tpu import _config

    mode = _config.pallas_mode()
    if mode == "0":
        return False
    if mode == "1":
        return True
    return _use_gauss_jordan(n, batch_elems)


#: trace-time record of the most recent backend dispatch — the solver
#: fact the run manifests and bench JSON report
_LAST_DISPATCH: dict = {}


def last_dispatch() -> dict:
    """Most recent solve-backend dispatch decision (made at trace time):
    ``{"backend", "n", "batch_elems", "fused"}``.  Empty before any
    solve has been traced in this process."""
    return dict(_LAST_DISPATCH)


def _record_dispatch(backend: str, n, batch_elems, fused: bool = False):
    _LAST_DISPATCH.update(backend=backend, n=int(n),
                          batch_elems=int(batch_elems), fused=bool(fused))
    try:
        from raft_tpu import obs
        obs.record_solve_dispatch(backend, n, batch_elems, fused=fused)
    # telemetry emission must never fail a solve (obs layer contract)
    except Exception:  # pragma: no cover  # raftlint: disable=RTL004
        pass


def solve_complex(A, b):
    """Solve A x = b for complex A (..., n, n) and b (..., n) or (..., n, k)
    via the real block embedding (TPU-safe)."""
    A = jnp.asarray(A)
    b = jnp.asarray(b)
    n = A.shape[-1]
    vec = b.ndim == A.ndim - 1
    if vec:
        b = b[..., None]
    Ar, Ai = jnp.real(A), jnp.imag(A)
    M = jnp.concatenate([
        jnp.concatenate([Ar, -Ai], axis=-1),
        jnp.concatenate([Ai, Ar], axis=-1),
    ], axis=-2)
    rhs = jnp.concatenate([jnp.real(b), jnp.imag(b)], axis=-2)
    batch_elems = int(np.prod(A.shape[:-2])) if A.ndim > 2 else 1
    if _use_pallas(2 * n, batch_elems):
        from raft_tpu.ops.pallas.gj_solve import gj_solve
        _record_dispatch("pallas_gj", 2 * n, batch_elems)
        x = gj_solve(M, rhs)
    elif _use_gauss_jordan(2 * n, batch_elems):
        _record_dispatch("jnp_gj", 2 * n, batch_elems)
        x = gauss_jordan_solve(M, rhs)
    else:
        _record_dispatch("lu", 2 * n, batch_elems)
        x = jnp.linalg.solve(M, rhs)
    out = x[..., :n, :] + 1j * x[..., n:, :]
    return out[..., 0] if vec else out


def impedance_solve(w, M, B, C, F):
    """Solve the frequency-domain impedance system

        [-w^2 M + i w B + C] X(w) = F(w)

    over the trailing frequency axis: w (nw,) real, M/B (..., n, n, nw)
    real, C (..., n, n) real, F (..., n, nw) complex -> X (..., n, nw)
    complex.

    Dispatch: the fused Pallas kernel when enabled for the shape (the
    assembly happens in the kernel's VMEM load stage — Z is never
    written to HBM), otherwise the pre-existing assemble-then-
    ``solve_complex`` path, kept bitwise identical to the inline
    assembly the sweep/variant/model callers used to carry."""
    # fault-injection seam (trace time): raise@kernel makes this
    # dispatch fail as a typed KernelFailure so the degradation ladder
    # (Pallas -> jnp -> host) is testable on CPU without breaking a
    # real kernel.  Ambient case context is pushed by the case loop.
    from raft_tpu.testing import faults
    faults.maybe_raise("kernel")
    w = jnp.asarray(w)
    M = jnp.asarray(M)
    B = jnp.asarray(B)
    C = jnp.asarray(C)
    F = jnp.asarray(F)
    n = M.shape[-3]
    nw = M.shape[-1]
    batch = M.shape[:-3]
    batch_elems = (int(np.prod(batch)) if batch else 1) * nw
    if _use_pallas(2 * n, batch_elems):
        from raft_tpu.ops.pallas.gj_solve import impedance_gj_solve
        _record_dispatch("pallas_fused", 2 * n, batch_elems, fused=True)
        return impedance_gj_solve(w, M, B, C, F)
    Z = (-w ** 2 * M + 1j * w * B + C[..., None]).astype(complex)
    Xin = solve_complex(jnp.moveaxis(Z, -1, -3), jnp.moveaxis(F, -1, -2))
    return jnp.moveaxis(Xin, -2, -1)


def inv_complex(A):
    """Inverse of complex A (..., n, n) via the real block embedding
    (TPU-safe).  Used to factor the system impedance once and reuse it
    across excitation sources (the reference's Zinv, raft_model.py:
    1038-1040)."""
    A = jnp.asarray(A)
    n = A.shape[-1]
    eye = jnp.broadcast_to(jnp.eye(n, dtype=A.dtype), A.shape)
    return solve_complex(A, eye)
