"""TPU Pallas kernels for the frequency-domain hot path.

One kernel family so far: the fused impedance-assembly + batched
real-embedded Gauss-Jordan solve (:mod:`raft_tpu.ops.pallas.gj_solve`)
behind the ``RAFT_TPU_PALLAS`` dispatch knob in :mod:`raft_tpu._config`.
Import is lazy everywhere (``from raft_tpu.ops.pallas import gj_solve``
inside the dispatch branch) so backends without Pallas support never
touch it.
"""
