"""Fused impedance-assembly + batched Gauss-Jordan solve, as Pallas TPU
kernels.

The sweep/variant hot path is ~2e5 independent 6x6 complex solves per
drag-linearization pass (1024 cases x 200 frequency bins), run through
the real 2n x 2n block embedding of ops/linalg.py.  The jnp
``gauss_jordan_solve`` already replaced XLA:TPU's pathological
tiny-matrix LU custom call, but as a graph of ~50 unrolled XLA ops it
round-trips the full (2n, 2n+k, B) augmented block through HBM on every
pivot step, and the impedance

    Z = -w^2 M + i w B + C

is materialized to HBM by the caller before the solve ever sees it.

The kernels here keep each (2n, 2n+k, tile_B) augmented block resident
in VMEM across ALL pivot steps, fuse row equilibration and the
iterative-refinement pass into the same kernel invocation, and (for
:func:`impedance_gj_solve`) fuse the Z assembly into the kernel's load
stage so Z never exists in HBM at all — the kernel reads the real
M/B/C/w/F factors and writes only X.

Batch layout is lane-last, exactly like ``ops.linalg._gj_core``: every
elimination op is elementwise/broadcast over the trailing batch axis
(the TPU lane dimension), so the VPU sees dense (sublane, lane) tiles.
The same kernel body runs under ``interpret=True`` on CPU — that is how
CI proves kernel parity without TPU hardware (``RAFT_TPU_PALLAS=1``).

Numerical behavior matches ``ops.linalg.gauss_jordan_solve``: row
equilibration (1/max|row|), partial pivoting, ``refine`` steps of
residual re-solve.

Mixed-precision ladder (``precision="mixed"``): the elimination runs at
a configurable low width (f32 default, bf16 opt-in) while the
refinement residual ``r = rhs - A x`` and the correction accumulate at
the full input width INSIDE the kernel — classical Carson & Higham
iterative refinement, fused into the same VMEM-resident invocation.
The kernel additionally emits each lane's final relative residual;
lanes above the promotion tolerance are re-solved at the full width in
a second pass where every non-promoted lane is masked to an identity
system, and the whole pass is skipped (``lax.cond``) when no lane
promoted — the common case.  Promoted-lane counts ride back to the
dispatch layer via the returned stats.
"""
from __future__ import annotations

import functools

import numpy as np
import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

from raft_tpu.ops.precision import equilibration_eps, promotion_mask

#: default lane-batch tile: 2 full 128-lane registers per op
DEFAULT_TILE_B = 256


def _default_interpret(interpret):
    """Pallas interpret mode unless explicitly chosen: compiled on
    accelerator backends, interpreted on CPU (identical kernel code)."""
    if interpret is not None:
        return bool(interpret)
    return jax.default_backend() == "cpu"


def _tile(tile_b, B):
    tb = int(tile_b or DEFAULT_TILE_B)
    # small batches: one 128-lane tile is plenty (and the minimum lane
    # granularity); everything else uses the requested tile
    return 128 if B <= 128 else tb


# ---------------------------------------------------------------------------
# kernel bodies (pure functions of VMEM-resident values, lane-last)
# ---------------------------------------------------------------------------

def _gj_elim(A, rhs, n, k):
    """Unrolled Gauss-Jordan elimination with partial pivoting on
    lane-last blocks: A (n, n, tB), rhs (n, k, tB) -> x (n, k, tB).

    Same algorithm (and op order) as ``ops.linalg._gj_core``, with the
    iotas 2-D for Mosaic.  The augmented block M stays a single VMEM
    value across all n pivot steps."""
    tB = A.shape[-1]
    M = jnp.concatenate([A, rhs], axis=1)              # (n, n+k, tB)
    rows1 = jax.lax.broadcasted_iota(jnp.int32, (n, 1), 0)
    rowsB = jax.lax.broadcasted_iota(jnp.int32, (n, tB), 0)
    for kk in range(n):                                # static unroll
        col = M[:, kk, :]                              # (n, tB)
        mag = jnp.where(rows1 >= kk, jnp.abs(col), -jnp.inf)
        p = jnp.argmax(mag, axis=0)                    # (tB,) pivot row
        sel = (rowsB == p[None, :]).astype(M.dtype)    # (n, tB)
        ek = (rows1 == kk).astype(M.dtype)             # (n, 1)
        pivrow = jnp.sum(sel[:, None, :] * M, axis=0)  # (n+k, tB)
        rowk = M[kk, :, :]                             # (n+k, tB)
        # swap rows kk <-> p (no-op when p == kk)
        M = (M + ek[:, :, None] * (pivrow - rowk)[None, :, :]
             + sel[:, None, :] * (rowk - pivrow)[None, :, :])
        piv = pivrow[kk, :]                            # (tB,)
        rowk_n = pivrow / piv[None, :]
        colk = M[:, kk, :] * (1.0 - ek)                # exclude pivot row
        M = M - colk[:, None, :] * rowk_n[None, :, :]
        M = M.at[kk, :, :].set(rowk_n)
    return M[:, n:, :]                                 # (n, k, tB)


def _matmul_bl(A, x):
    """A @ x with the batch on the last axis: (n,n,tB),(n,k,tB)->(n,k,tB).
    Broadcast-sum rather than dot_general — n,k are tiny (<=16) so this
    is a pure VPU op with no layout change."""
    return jnp.sum(A[:, :, None, :] * x[None, :, :, :], axis=1)


def _gj_batchlast(A, rhs, n, k, refine, factor_dtype=None, resid=False):
    """Equilibrate + eliminate + refine, all on VMEM-resident values.

    ``factor_dtype``: when given (and lower than the input width), the
    elimination runs at that width while the residual ``rhs - A x`` and
    the correction accumulate at the input width — the in-kernel mixed
    ladder.  ``resid=True`` additionally returns each lane's final max
    relative residual (tB,), the promotion signal."""
    eps = equilibration_eps(A.dtype)
    scale = 1.0 / jnp.maximum(jnp.max(jnp.abs(A), axis=1, keepdims=True),
                              eps)
    A = A * scale
    rhs = rhs * scale
    if factor_dtype is None or jnp.dtype(factor_dtype) == A.dtype:
        x = _gj_elim(A, rhs, n, k)
        for _ in range(refine):
            r = rhs - _matmul_bl(A, x)
            x = x + _gj_elim(A, r, n, k)
    else:
        # low-width elimination on the FULL-width-equilibrated block;
        # residual + correction stay at the input width
        Af = A.astype(factor_dtype)
        x = _gj_elim(Af, rhs.astype(factor_dtype), n, k).astype(A.dtype)
        for _ in range(refine):
            r = rhs - _matmul_bl(A, x)
            x = x + _gj_elim(Af, r.astype(factor_dtype),
                             n, k).astype(A.dtype)
    if not resid:
        return x, None
    r = rhs - _matmul_bl(A, x)
    den = jnp.max(jnp.abs(rhs), axis=(0, 1)) + eps     # (tB,)
    return x, jnp.max(jnp.abs(r), axis=(0, 1)) / den


def _gj_kernel(a_ref, b_ref, out_ref, *, n, k, refine):
    out_ref[:] = _gj_batchlast(a_ref[:], b_ref[:], n, k, refine)[0]


def _gj_mixed_kernel(a_ref, b_ref, out_ref, res_ref, *, n, k, refine,
                     factor_dtype):
    x, rn = _gj_batchlast(a_ref[:], b_ref[:], n, k, refine,
                          factor_dtype=factor_dtype, resid=True)
    out_ref[:] = x
    res_ref[:] = rn[None, :]


def _assemble_embedding(w_ref, m_ref, b_ref, c_ref, fre_ref, fim_ref, n):
    """VMEM load stage: the real 2n x 2n block embedding of
    Z = -w^2 M + i w B + C and its stacked real rhs."""
    w = w_ref[0, :]                                    # (tB,)
    reZ = c_ref[:] - (w * w)[None, None, :] * m_ref[:]
    imZ = w[None, None, :] * b_ref[:]
    A = jnp.concatenate([
        jnp.concatenate([reZ, -imZ], axis=1),
        jnp.concatenate([imZ, reZ], axis=1),
    ], axis=0)                                         # (2n, 2n, tB)
    rhs = jnp.concatenate([fre_ref[:], fim_ref[:]], axis=0)  # (2n, k, tB)
    return A, rhs


def _impedance_kernel(w_ref, m_ref, b_ref, c_ref, fre_ref, fim_ref,
                      out_ref, *, n, k, refine):
    """Fused load stage: assemble the real block embedding of
    Z = -w^2 M + i w B + C from its real factors, then solve — Z never
    leaves VMEM."""
    A, rhs = _assemble_embedding(w_ref, m_ref, b_ref, c_ref,
                                 fre_ref, fim_ref, n)
    out_ref[:] = _gj_batchlast(A, rhs, 2 * n, k, refine)[0]


def _impedance_mixed_kernel(w_ref, m_ref, b_ref, c_ref, fre_ref, fim_ref,
                            out_ref, res_ref, *, n, k, refine,
                            factor_dtype):
    """The fused assembly with the in-kernel mixed ladder: Z is
    assembled at the full width, eliminated at ``factor_dtype``, and
    refined at the full width — per-lane residuals ride out with X."""
    A, rhs = _assemble_embedding(w_ref, m_ref, b_ref, c_ref,
                                 fre_ref, fim_ref, n)
    x, rn = _gj_batchlast(A, rhs, 2 * n, k, refine,
                          factor_dtype=factor_dtype, resid=True)
    out_ref[:] = x
    res_ref[:] = rn[None, :]


# ---------------------------------------------------------------------------
# public wrappers
# ---------------------------------------------------------------------------

def _pad_lanes(x, pad, fill):
    if pad == 0:
        return x
    tail = jnp.broadcast_to(jnp.asarray(fill, x.dtype)[..., None],
                            x.shape[:-1] + (pad,))
    return jnp.concatenate([x, tail], axis=-1)


def _call_gj(Af, bf, n, k, refine, tB, Bp, interpret):
    """One plain (single-width) kernel launch over the padded lane-last
    blocks; returns x (n, k, Bp)."""
    kern = functools.partial(_gj_kernel, n=n, k=k, refine=int(refine))
    return pl.pallas_call(
        kern,
        grid=(Bp // tB,),
        in_specs=[pl.BlockSpec((n, n, tB), lambda i: (0, 0, i)),
                  pl.BlockSpec((n, k, tB), lambda i: (0, 0, i))],
        out_specs=pl.BlockSpec((n, k, tB), lambda i: (0, 0, i)),
        out_shape=jax.ShapeDtypeStruct((n, k, Bp), Af.dtype),
        interpret=interpret,
    )(Af, bf)


def _call_gj_mixed(Af, bf, n, k, refine, tB, Bp, interpret, factor_dtype):
    """One mixed-ladder kernel launch; returns (x (n, k, Bp),
    per-lane relative residual (Bp,))."""
    kern = functools.partial(_gj_mixed_kernel, n=n, k=k,
                             refine=int(refine),
                             factor_dtype=jnp.dtype(factor_dtype))
    x, rn = pl.pallas_call(
        kern,
        grid=(Bp // tB,),
        in_specs=[pl.BlockSpec((n, n, tB), lambda i: (0, 0, i)),
                  pl.BlockSpec((n, k, tB), lambda i: (0, 0, i))],
        out_specs=[pl.BlockSpec((n, k, tB), lambda i: (0, 0, i)),
                   pl.BlockSpec((1, tB), lambda i: (0, i))],
        out_shape=[jax.ShapeDtypeStruct((n, k, Bp), Af.dtype),
                   jax.ShapeDtypeStruct((1, Bp), Af.dtype)],
        interpret=interpret,
    )(Af, bf)
    return x, rn[0]


def _promote_lanes_gj(Af, bf, x, rn, n, k, refine, tB, Bp, interpret,
                      promote_tol):
    """Per-lane adaptive promotion: lanes whose mixed-ladder residual
    exceeds the tolerance are re-solved at the full input width in a
    second pass in which every NON-promoted lane is masked to an
    identity system (lane-parallel tiles cannot be thinned, so the win
    is skipping the pass entirely — ``lax.cond`` — when nothing
    promoted, the common case).  Returns (x, promoted_count)."""
    mask, promoted = promotion_mask(rn, promote_tol)   # (Bp,), scalar

    def _resolve(xm):
        m = mask[None, None, :]
        eye = jnp.broadcast_to(jnp.eye(n, dtype=Af.dtype)[:, :, None],
                               (n, n, Bp))
        A2 = jnp.where(m, Af, eye)
        b2 = jnp.where(m, bf, jnp.zeros((), bf.dtype))
        xh = _call_gj(A2, b2, n, k, refine, tB, Bp, interpret)
        return jnp.where(m, xh, xm)

    x = jax.lax.cond(promoted > 0, _resolve, lambda xm: xm, x)
    return x, promoted


def gj_solve(A, b, refine: int = 1, tile_b: int = None, interpret=None,
             precision: str = None, factor_dtype=None, promote_tol=None,
             return_stats: bool = False):
    """Pallas batched Gauss-Jordan solve of real A (..., n, n) x = b
    (..., n, k); semantics match ``ops.linalg.gauss_jordan_solve`` (row
    equilibration, partial pivoting, ``refine`` refinement passes).

    The flattened batch is tiled over the grid; each (n, n+k, tile_b)
    augmented block stays VMEM-resident through all pivot steps.
    ``interpret=None`` auto-selects interpret mode on CPU.

    ``precision="mixed"`` runs the in-kernel mixed ladder: elimination
    at ``factor_dtype`` (f32 default), full-width residual/correction,
    and per-lane promotion past ``promote_tol`` (see module docstring).
    ``return_stats=True`` additionally returns
    ``{"promoted", "lanes", "resid_max"}`` (promoted/resid_max are
    traced scalars — jit-safe)."""
    A = jnp.asarray(A)
    b = jnp.asarray(b)
    n = A.shape[-1]
    k = b.shape[-1]
    batch = A.shape[:-2]
    B = int(np.prod(batch)) if batch else 1
    Af = jnp.moveaxis(A.reshape(B, n, n), 0, -1)       # (n, n, B)
    bf = jnp.moveaxis(b.reshape(B, n, k), 0, -1)       # (n, k, B)
    tB = _tile(tile_b, B)
    Bp = -(-B // tB) * tB
    if Bp != B:
        # identity-pad the dead lanes so the elimination stays finite
        pad = Bp - B
        Af = jnp.concatenate(
            [Af, jnp.broadcast_to(jnp.eye(n, dtype=Af.dtype)[:, :, None],
                                  (n, n, pad))], axis=-1)
        bf = _pad_lanes(bf, pad, 0.0)
    interp = _default_interpret(interpret)
    if precision in (None, "native"):
        x = _call_gj(Af, bf, n, k, refine, tB, Bp, interp)
        out = jnp.moveaxis(x[..., :B], -1, 0).reshape(*batch, n, k)
        if not return_stats:
            return out
        return out, {"promoted": jnp.zeros((), jnp.int32), "lanes": B,
                     "resid_max": jnp.zeros((), Af.dtype)}
    if precision != "mixed":
        from raft_tpu import errors
        raise errors.ModelConfigError(
            f"unknown gj_solve precision {precision!r}")
    fd = jnp.dtype(factor_dtype) if factor_dtype is not None \
        else jnp.dtype(jnp.float32)
    tol = float(promote_tol) if promote_tol is not None else 1e-9
    x, rn = _call_gj_mixed(Af, bf, n, k, refine, tB, Bp, interp, fd)
    # pad lanes are identity systems with a zero rhs -> residual 0,
    # never promoted
    x, promoted = _promote_lanes_gj(Af, bf, x, rn, n, k, refine, tB, Bp,
                                    interp, tol)
    out = jnp.moveaxis(x[..., :B], -1, 0).reshape(*batch, n, k)
    if not return_stats:
        return out
    return out, {"promoted": promoted, "lanes": B,
                 "resid_max": jnp.max(rn[:B])}


def impedance_gj_solve(w, M, B, C, F, refine: int = 1, tile_b: int = None,
                       interpret=None, precision: str = None,
                       factor_dtype=None, promote_tol=None,
                       return_stats: bool = False):
    """Solve [-w^2 M + i w B + C] X = F without materializing Z.

    w (nw,) real; M, B (..., n, n, nw) real; C (..., n, n) real;
    F (..., n, nw) complex.  Returns X (..., n, nw) complex.

    The (case, frequency) product is flattened to one lane batch; the
    kernel assembles the real 2n x 2n block embedding of Z in its VMEM
    load stage and runs the equilibrated, partially-pivoted Gauss-Jordan
    elimination with ``refine`` refinement passes in-place.

    ``precision="mixed"`` runs the in-kernel mixed ladder (see
    :func:`gj_solve`); the promotion second pass re-assembles only the
    promoted lanes' systems (non-promoted lanes degrade to the same
    identity system the lane padding uses: M=B=w=F=0, C=I) and is
    skipped entirely when no lane promoted."""
    M = jnp.asarray(M)
    B = jnp.asarray(B)
    C = jnp.asarray(C)
    F = jnp.asarray(F)
    w = jnp.asarray(w, M.dtype)
    n = M.shape[-3]
    nw = M.shape[-1]
    batch = M.shape[:-3]
    nb = int(np.prod(batch)) if batch else 1
    Bt = nb * nw

    def flat_ml(x):
        """(..., n, n, nw) -> (n, n, B) with the (batch, nw) product
        flattened case-major / frequency-minor (the same element order
        as moveaxis(Z, -1, -3).reshape(B, n, n) on the jnp path)."""
        x = jnp.broadcast_to(x, batch + (n, n, nw))
        x = jnp.moveaxis(x, -1, -3).reshape(Bt, n, n)
        return jnp.moveaxis(x, 0, -1)

    Mf = flat_ml(M)
    Bf = flat_ml(B)
    Cf = flat_ml(C[..., None])
    wf = jnp.broadcast_to(w, batch + (nw,)).reshape(1, Bt)
    Ff = jnp.moveaxis(jnp.broadcast_to(F, batch + (n, nw)),
                      -1, -2).reshape(Bt, n, 1)
    Ff = jnp.moveaxis(Ff, 0, -1)                       # (n, 1, B)
    Fre = jnp.real(Ff).astype(M.dtype)
    Fim = jnp.imag(Ff).astype(M.dtype)

    tB = _tile(tile_b, Bt)
    Bp = -(-Bt // tB) * tB
    pad = Bp - Bt
    if pad:
        # dead lanes solve I x = 0: M=B=w=F=0, C=I
        Mf = _pad_lanes(Mf, pad, 0.0)
        Bf = _pad_lanes(Bf, pad, 0.0)
        Cf = jnp.concatenate(
            [Cf, jnp.broadcast_to(jnp.eye(n, dtype=Cf.dtype)[:, :, None],
                                  (n, n, pad))], axis=-1)
        wf = _pad_lanes(wf, pad, 0.0)
        Fre = _pad_lanes(Fre, pad, 0.0)
        Fim = _pad_lanes(Fim, pad, 0.0)

    interp = _default_interpret(interpret)
    spec_nn = pl.BlockSpec((n, n, tB), lambda i: (0, 0, i))
    spec_nk = pl.BlockSpec((n, 1, tB), lambda i: (0, 0, i))
    spec_w = pl.BlockSpec((1, tB), lambda i: (0, i))
    spec_x = pl.BlockSpec((2 * n, 1, tB), lambda i: (0, 0, i))

    def _call_plain(wf_, Mf_, Bf_, Cf_, Fre_, Fim_):
        kern = functools.partial(_impedance_kernel, n=n, k=1,
                                 refine=int(refine))
        return pl.pallas_call(
            kern,
            grid=(Bp // tB,),
            in_specs=[spec_w, spec_nn, spec_nn, spec_nn,
                      spec_nk, spec_nk],
            out_specs=spec_x,
            out_shape=jax.ShapeDtypeStruct((2 * n, 1, Bp), Mf.dtype),
            interpret=interp,
        )(wf_, Mf_, Bf_, Cf_, Fre_, Fim_)

    stats = None
    if precision in (None, "native"):
        x = _call_plain(wf, Mf, Bf, Cf, Fre, Fim)
        if return_stats:
            stats = {"promoted": jnp.zeros((), jnp.int32), "lanes": Bt,
                     "resid_max": jnp.zeros((), Mf.dtype)}
    elif precision == "mixed":
        fd = jnp.dtype(factor_dtype) if factor_dtype is not None \
            else jnp.dtype(jnp.float32)
        tol = float(promote_tol) if promote_tol is not None else 1e-9
        kern = functools.partial(_impedance_mixed_kernel, n=n, k=1,
                                 refine=int(refine), factor_dtype=fd)
        x, rn = pl.pallas_call(
            kern,
            grid=(Bp // tB,),
            in_specs=[spec_w, spec_nn, spec_nn, spec_nn,
                      spec_nk, spec_nk],
            out_specs=[spec_x, pl.BlockSpec((1, tB), lambda i: (0, i))],
            out_shape=[jax.ShapeDtypeStruct((2 * n, 1, Bp), Mf.dtype),
                       jax.ShapeDtypeStruct((1, Bp), Mf.dtype)],
            interpret=interp,
        )(wf, Mf, Bf, Cf, Fre, Fim)
        rn = rn[0]                                     # (Bp,)
        mask, promoted = promotion_mask(rn, tol)

        def _resolve(xm):
            # non-promoted lanes degrade to the identity padding system
            # (M=B=w=F=0, C=I); only the promoted lanes carry physics
            # through the full-width pass
            mnn = mask[None, None, :]
            mnk = mask[None, None, :]
            zero = jnp.zeros((), Mf.dtype)
            eye = jnp.broadcast_to(
                jnp.eye(n, dtype=Cf.dtype)[:, :, None], (n, n, Bp))
            xh = _call_plain(jnp.where(mask[None, :], wf, zero),
                             jnp.where(mnn, Mf, zero),
                             jnp.where(mnn, Bf, zero),
                             jnp.where(mnn, Cf, eye),
                             jnp.where(mnk, Fre, zero),
                             jnp.where(mnk, Fim, zero))
            return jnp.where(mask[None, None, :], xh, xm)

        x = jax.lax.cond(promoted > 0, _resolve, lambda xm: xm, x)
        if return_stats:
            stats = {"promoted": promoted, "lanes": Bt,
                     "resid_max": jnp.max(rn[:Bt])}
    else:
        from raft_tpu import errors
        raise errors.ModelConfigError(
            f"unknown impedance_gj_solve precision {precision!r}")
    x = x[..., :Bt]                                    # (2n, 1, B)
    X = (x[:n, 0, :] + 1j * x[n:, 0, :])               # (n, B) complex
    X = jnp.moveaxis(X, -1, 0).reshape(batch + (nw, n))
    X = jnp.moveaxis(X, -1, -2)                        # (..., n, nw)
    return (X, stats) if return_stats else X
