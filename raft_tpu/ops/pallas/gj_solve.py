"""Fused impedance-assembly + batched Gauss-Jordan solve, as Pallas TPU
kernels.

The sweep/variant hot path is ~2e5 independent 6x6 complex solves per
drag-linearization pass (1024 cases x 200 frequency bins), run through
the real 2n x 2n block embedding of ops/linalg.py.  The jnp
``gauss_jordan_solve`` already replaced XLA:TPU's pathological
tiny-matrix LU custom call, but as a graph of ~50 unrolled XLA ops it
round-trips the full (2n, 2n+k, B) augmented block through HBM on every
pivot step, and the impedance

    Z = -w^2 M + i w B + C

is materialized to HBM by the caller before the solve ever sees it.

The kernels here keep each (2n, 2n+k, tile_B) augmented block resident
in VMEM across ALL pivot steps, fuse row equilibration and the
iterative-refinement pass into the same kernel invocation, and (for
:func:`impedance_gj_solve`) fuse the Z assembly into the kernel's load
stage so Z never exists in HBM at all — the kernel reads the real
M/B/C/w/F factors and writes only X.

Batch layout is lane-last, exactly like ``ops.linalg._gj_core``: every
elimination op is elementwise/broadcast over the trailing batch axis
(the TPU lane dimension), so the VPU sees dense (sublane, lane) tiles.
The same kernel body runs under ``interpret=True`` on CPU — that is how
CI proves kernel parity without TPU hardware (``RAFT_TPU_PALLAS=1``).

Numerical behavior matches ``ops.linalg.gauss_jordan_solve``: row
equilibration (1/max|row|), partial pivoting, ``refine`` steps of
residual re-solve.
"""
from __future__ import annotations

import functools

import numpy as np
import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

#: default lane-batch tile: 2 full 128-lane registers per op
DEFAULT_TILE_B = 256


def _default_interpret(interpret):
    """Pallas interpret mode unless explicitly chosen: compiled on
    accelerator backends, interpreted on CPU (identical kernel code)."""
    if interpret is not None:
        return bool(interpret)
    return jax.default_backend() == "cpu"


def _tile(tile_b, B):
    tb = int(tile_b or DEFAULT_TILE_B)
    # small batches: one 128-lane tile is plenty (and the minimum lane
    # granularity); everything else uses the requested tile
    return 128 if B <= 128 else tb


# ---------------------------------------------------------------------------
# kernel bodies (pure functions of VMEM-resident values, lane-last)
# ---------------------------------------------------------------------------

def _gj_elim(A, rhs, n, k):
    """Unrolled Gauss-Jordan elimination with partial pivoting on
    lane-last blocks: A (n, n, tB), rhs (n, k, tB) -> x (n, k, tB).

    Same algorithm (and op order) as ``ops.linalg._gj_core``, with the
    iotas 2-D for Mosaic.  The augmented block M stays a single VMEM
    value across all n pivot steps."""
    tB = A.shape[-1]
    M = jnp.concatenate([A, rhs], axis=1)              # (n, n+k, tB)
    rows1 = jax.lax.broadcasted_iota(jnp.int32, (n, 1), 0)
    rowsB = jax.lax.broadcasted_iota(jnp.int32, (n, tB), 0)
    for kk in range(n):                                # static unroll
        col = M[:, kk, :]                              # (n, tB)
        mag = jnp.where(rows1 >= kk, jnp.abs(col), -jnp.inf)
        p = jnp.argmax(mag, axis=0)                    # (tB,) pivot row
        sel = (rowsB == p[None, :]).astype(M.dtype)    # (n, tB)
        ek = (rows1 == kk).astype(M.dtype)             # (n, 1)
        pivrow = jnp.sum(sel[:, None, :] * M, axis=0)  # (n+k, tB)
        rowk = M[kk, :, :]                             # (n+k, tB)
        # swap rows kk <-> p (no-op when p == kk)
        M = (M + ek[:, :, None] * (pivrow - rowk)[None, :, :]
             + sel[:, None, :] * (rowk - pivrow)[None, :, :])
        piv = pivrow[kk, :]                            # (tB,)
        rowk_n = pivrow / piv[None, :]
        colk = M[:, kk, :] * (1.0 - ek)                # exclude pivot row
        M = M - colk[:, None, :] * rowk_n[None, :, :]
        M = M.at[kk, :, :].set(rowk_n)
    return M[:, n:, :]                                 # (n, k, tB)


def _matmul_bl(A, x):
    """A @ x with the batch on the last axis: (n,n,tB),(n,k,tB)->(n,k,tB).
    Broadcast-sum rather than dot_general — n,k are tiny (<=16) so this
    is a pure VPU op with no layout change."""
    return jnp.sum(A[:, :, None, :] * x[None, :, :, :], axis=1)


def _gj_batchlast(A, rhs, n, k, refine):
    """Equilibrate + eliminate + refine, all on VMEM-resident values."""
    eps = 1e-300 if A.dtype == jnp.float64 else 1e-30
    scale = 1.0 / jnp.maximum(jnp.max(jnp.abs(A), axis=1, keepdims=True),
                              eps)
    A = A * scale
    rhs = rhs * scale
    x = _gj_elim(A, rhs, n, k)
    for _ in range(refine):
        r = rhs - _matmul_bl(A, x)
        x = x + _gj_elim(A, r, n, k)
    return x


def _gj_kernel(a_ref, b_ref, out_ref, *, n, k, refine):
    out_ref[:] = _gj_batchlast(a_ref[:], b_ref[:], n, k, refine)


def _impedance_kernel(w_ref, m_ref, b_ref, c_ref, fre_ref, fim_ref,
                      out_ref, *, n, k, refine):
    """Fused load stage: assemble the real block embedding of
    Z = -w^2 M + i w B + C from its real factors, then solve — Z never
    leaves VMEM."""
    w = w_ref[0, :]                                    # (tB,)
    reZ = c_ref[:] - (w * w)[None, None, :] * m_ref[:]
    imZ = w[None, None, :] * b_ref[:]
    A = jnp.concatenate([
        jnp.concatenate([reZ, -imZ], axis=1),
        jnp.concatenate([imZ, reZ], axis=1),
    ], axis=0)                                         # (2n, 2n, tB)
    rhs = jnp.concatenate([fre_ref[:], fim_ref[:]], axis=0)  # (2n, k, tB)
    out_ref[:] = _gj_batchlast(A, rhs, 2 * n, k, refine)


# ---------------------------------------------------------------------------
# public wrappers
# ---------------------------------------------------------------------------

def _pad_lanes(x, pad, fill):
    if pad == 0:
        return x
    tail = jnp.broadcast_to(jnp.asarray(fill, x.dtype)[..., None],
                            x.shape[:-1] + (pad,))
    return jnp.concatenate([x, tail], axis=-1)


def gj_solve(A, b, refine: int = 1, tile_b: int = None, interpret=None):
    """Pallas batched Gauss-Jordan solve of real A (..., n, n) x = b
    (..., n, k); semantics match ``ops.linalg.gauss_jordan_solve`` (row
    equilibration, partial pivoting, ``refine`` refinement passes).

    The flattened batch is tiled over the grid; each (n, n+k, tile_b)
    augmented block stays VMEM-resident through all pivot steps.
    ``interpret=None`` auto-selects interpret mode on CPU."""
    A = jnp.asarray(A)
    b = jnp.asarray(b)
    n = A.shape[-1]
    k = b.shape[-1]
    batch = A.shape[:-2]
    B = int(np.prod(batch)) if batch else 1
    Af = jnp.moveaxis(A.reshape(B, n, n), 0, -1)       # (n, n, B)
    bf = jnp.moveaxis(b.reshape(B, n, k), 0, -1)       # (n, k, B)
    tB = _tile(tile_b, B)
    Bp = -(-B // tB) * tB
    if Bp != B:
        # identity-pad the dead lanes so the elimination stays finite
        pad = Bp - B
        Af = jnp.concatenate(
            [Af, jnp.broadcast_to(jnp.eye(n, dtype=Af.dtype)[:, :, None],
                                  (n, n, pad))], axis=-1)
        bf = _pad_lanes(bf, pad, 0.0)
    kern = functools.partial(_gj_kernel, n=n, k=k, refine=int(refine))
    x = pl.pallas_call(
        kern,
        grid=(Bp // tB,),
        in_specs=[pl.BlockSpec((n, n, tB), lambda i: (0, 0, i)),
                  pl.BlockSpec((n, k, tB), lambda i: (0, 0, i))],
        out_specs=pl.BlockSpec((n, k, tB), lambda i: (0, 0, i)),
        out_shape=jax.ShapeDtypeStruct((n, k, Bp), Af.dtype),
        interpret=_default_interpret(interpret),
    )(Af, bf)
    return jnp.moveaxis(x[..., :B], -1, 0).reshape(*batch, n, k)


def impedance_gj_solve(w, M, B, C, F, refine: int = 1, tile_b: int = None,
                       interpret=None):
    """Solve [-w^2 M + i w B + C] X = F without materializing Z.

    w (nw,) real; M, B (..., n, n, nw) real; C (..., n, n) real;
    F (..., n, nw) complex.  Returns X (..., n, nw) complex.

    The (case, frequency) product is flattened to one lane batch; the
    kernel assembles the real 2n x 2n block embedding of Z in its VMEM
    load stage and runs the equilibrated, partially-pivoted Gauss-Jordan
    elimination with ``refine`` refinement passes in-place."""
    M = jnp.asarray(M)
    B = jnp.asarray(B)
    C = jnp.asarray(C)
    F = jnp.asarray(F)
    w = jnp.asarray(w, M.dtype)
    n = M.shape[-3]
    nw = M.shape[-1]
    batch = M.shape[:-3]
    nb = int(np.prod(batch)) if batch else 1
    Bt = nb * nw

    def flat_ml(x):
        """(..., n, n, nw) -> (n, n, B) with the (batch, nw) product
        flattened case-major / frequency-minor (the same element order
        as moveaxis(Z, -1, -3).reshape(B, n, n) on the jnp path)."""
        x = jnp.broadcast_to(x, batch + (n, n, nw))
        x = jnp.moveaxis(x, -1, -3).reshape(Bt, n, n)
        return jnp.moveaxis(x, 0, -1)

    Mf = flat_ml(M)
    Bf = flat_ml(B)
    Cf = flat_ml(C[..., None])
    wf = jnp.broadcast_to(w, batch + (nw,)).reshape(1, Bt)
    Ff = jnp.moveaxis(jnp.broadcast_to(F, batch + (n, nw)),
                      -1, -2).reshape(Bt, n, 1)
    Ff = jnp.moveaxis(Ff, 0, -1)                       # (n, 1, B)
    Fre = jnp.real(Ff).astype(M.dtype)
    Fim = jnp.imag(Ff).astype(M.dtype)

    tB = _tile(tile_b, Bt)
    Bp = -(-Bt // tB) * tB
    pad = Bp - Bt
    if pad:
        # dead lanes solve I x = 0: M=B=w=F=0, C=I
        Mf = _pad_lanes(Mf, pad, 0.0)
        Bf = _pad_lanes(Bf, pad, 0.0)
        Cf = jnp.concatenate(
            [Cf, jnp.broadcast_to(jnp.eye(n, dtype=Cf.dtype)[:, :, None],
                                  (n, n, pad))], axis=-1)
        wf = _pad_lanes(wf, pad, 0.0)
        Fre = _pad_lanes(Fre, pad, 0.0)
        Fim = _pad_lanes(Fim, pad, 0.0)

    kern = functools.partial(_impedance_kernel, n=n, k=1,
                             refine=int(refine))
    spec_nn = pl.BlockSpec((n, n, tB), lambda i: (0, 0, i))
    spec_nk = pl.BlockSpec((n, 1, tB), lambda i: (0, 0, i))
    x = pl.pallas_call(
        kern,
        grid=(Bp // tB,),
        in_specs=[pl.BlockSpec((1, tB), lambda i: (0, i)),
                  spec_nn, spec_nn, spec_nn, spec_nk, spec_nk],
        out_specs=pl.BlockSpec((2 * n, 1, tB), lambda i: (0, 0, i)),
        out_shape=jax.ShapeDtypeStruct((2 * n, 1, Bp), Mf.dtype),
        interpret=_default_interpret(interpret),
    )(wf, Mf, Bf, Cf, Fre, Fim)
    x = x[..., :Bt]                                    # (2n, 1, B)
    X = (x[:n, 0, :] + 1j * x[n:, 0, :])               # (n, B) complex
    X = jnp.moveaxis(X, -1, 0).reshape(batch + (nw, n))
    return jnp.moveaxis(X, -1, -2)                     # (..., n, nw)
