"""Fused QTF pair-grid contraction as a Pallas kernel.

``models/qtf.py:calc_qtf_slender_body`` evaluates the slender-body QTF
on the dense (w1, w2) pair grid as a doubly-vmapped ``pair()`` closure:
every Pinkster/Rainey term materializes its (N, 3, nw2, nw2)-shaped
einsum intermediates to HBM between XLA fusions.  The kernel here tiles
the pair grid instead — grid dimension 0 walks the w1 rows, the w2 axis
rides the TPU lane dimension — and evaluates the ENTIRE per-pair force
assembly (second-order potential, convective/axial-divergence/nabla
accelerations, Rainey body-rotation terms, waterline relative-elevation
terms, Pinkster IV) on VMEM-resident blocks, writing only the (6,)
wrench per pair.  Every frequency field is loaded twice through two
BlockSpecs: a width-1 block at the row index (the "1" side) and a
lane-tile block at the column index (the "2" side).

Precision discipline: all arithmetic happens at the input widths (the
complex fields arrive at ``_config.complex_dtype()``); the kernel
changes memory locality, never numerics — parity vs the vmapped path
is pinned at 1e-6 in tests/test_qtf_kernel.py.

Backend status: the kernel body uses complex arithmetic, which Mosaic
(compiled Pallas-TPU) does not lower yet — the kernel therefore always
runs in interpret mode (the same CI-parity vehicle ``gj_solve`` uses on
CPU), and the ``RAFT_TPU_QTF_KERNEL`` knob keeps the vmapped path the
"auto" default until the real/imag-split Mosaic port lands.  The
blocking layout above is the hardware-shaped part: the real-split port
changes element types, not the tiling.
"""
from __future__ import annotations

import functools

import numpy as np
import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

from raft_tpu import _config
from raft_tpu.ops.waves import wave_pot_2nd_order

#: lane tile over the w2 (column) axis — one full 128-lane register
TILE_P = 128


# ---------------------------------------------------------------------------
# lane-last algebra helpers (trailing axis = w2 lane tile)
# ---------------------------------------------------------------------------

def _cross0(a, b):
    """Cross product along axis 0 of (3, ...) stacks, broadcasting the
    trailing axes (the lane dimension)."""
    return jnp.stack([
        a[1] * b[2] - a[2] * b[1],
        a[2] * b[0] - a[0] * b[2],
        a[0] * b[1] - a[1] * b[0],
    ], axis=0)


def _cross1(a, b):
    """Cross product along axis 1 of (N, 3, ...) node stacks."""
    return jnp.stack([
        a[:, 1] * b[:, 2] - a[:, 2] * b[:, 1],
        a[:, 2] * b[:, 0] - a[:, 0] * b[:, 2],
        a[:, 0] * b[:, 1] - a[:, 1] * b[:, 0],
    ], axis=1)


def _mv(Mt, v):
    """(N,3,3) static matrix times (N,3,L) lane field -> (N,3,L)."""
    return jnp.sum(Mt[:, :, :, None] * v[:, None, :, :], axis=2)


def _mv4(G, v):
    """(N,3,3,L1) lane matrix field times (N,3,L2) -> (N,3,L) with
    L1/L2 broadcasting (the 1-side block is width 1)."""
    return jnp.sum(G * v[:, None, :, :], axis=2)


def _omv(OM, v):
    """(3,3,L1) per-pair rotation matrix times (N,3,L2) -> (N,3,L)."""
    return jnp.sum(OM[None, :, :, :] * v[:, None, :, :], axis=2)


def _skew_l(v):
    """(3, L) lane vector -> (3, 3, L) skew matrices."""
    z = jnp.zeros_like(v[0])
    return jnp.stack([
        jnp.stack([z, -v[2], v[1]], axis=0),
        jnp.stack([v[2], z, -v[0]], axis=0),
        jnp.stack([-v[1], v[0], z], axis=0),
    ], axis=0)


# ---------------------------------------------------------------------------
# kernel body
# ---------------------------------------------------------------------------

def _qtf_pair_kernel(*refs, nm, beta, h, rho, g):
    (w1_ref, wv2_ref, k1_ref, k2_ref,
     xi1_ref, xi2_ref, f11_ref, f12_ref,
     u1_ref, u2_ref, dr1_ref, dr2_ref,
     nv1_ref, nv2_ref, nax1_ref, nax2_ref,
     gu1_ref, gu2_ref, gp1_ref, gp2_ref,
     q_ref, off_ref, pos_ref,
     minert_ref, camat_ref, ptmat_ref, qmat_ref, nsc_ref) = refs[:28]
    if nm:
        wlc1_ref, wlc2_ref, eta1_ref, eta2_ref, \
            wlm_ref, wlg_ref = refs[28:34]
    qre_ref, qim_ref = refs[-2:]

    cdt = xi1_ref.dtype

    w1 = w1_ref[0, :]                                  # (1,)
    wv2 = wv2_ref[0, :]                                # (t,)
    kk1 = k1_ref[0, :]
    kk2 = k2_ref[0, :]
    Xi1 = xi1_ref[:]                                   # (6, 1)
    Xi2 = xi2_ref[:]                                   # (6, t)
    F11 = f11_ref[:]                                   # (6, 1) F1st @ i1
    F12 = f12_ref[:]                                   # (6, t) F1st @ i2
    u1 = u1_ref[:]                                     # (N, 3, 1)
    u2 = u2_ref[:]                                     # (N, 3, t)
    dr1, dr2 = dr1_ref[:], dr2_ref[:]
    nv1, nv2 = nv1_ref[:], nv2_ref[:]
    nax1 = nax1_ref[:]                                 # (N, 1)
    nax2 = nax2_ref[:]                                 # (N, t)
    gu1 = gu1_ref[:]                                   # (N, 3, 3, 1)
    gu2 = gu2_ref[:]                                   # (N, 3, 3, t)
    gp1 = gp1_ref[:]                                   # (N, 3, 1)
    gp2 = gp2_ref[:]
    q = q_ref[:]                                       # (N, 3) real
    offsets = off_ref[:]                               # (N, 3) real
    pos = pos_ref[:]                                   # (N, 3) real
    Minert = minert_ref[:]                             # (N, 3, 3) real
    CaMat = camat_ref[:]
    ptMat = ptmat_ref[:]
    qMat = qmat_ref[:]
    v_i = nsc_ref[:, 0]                                # (N,)
    v_end_ca = nsc_ref[:, 1]
    a_i = nsc_ref[:, 2]
    submerged = nsc_ref[:, 3]

    qc = q.astype(cdt)                                 # (N, 3)
    gdu1 = 1j * w1[None, None, None, :] * gu1
    gdu2 = 1j * wv2[None, None, None, :] * gu2

    # ---- Pinkster IV (reference :1449-1456) ----
    F_rotN = jnp.concatenate([
        0.25 * (_cross0(Xi1[3:], jnp.conj(F12[0:3]))
                + _cross0(jnp.conj(Xi2[3:]), F11[0:3])),
        0.25 * (_cross0(Xi1[3:], jnp.conj(F12[3:]))
                + _cross0(jnp.conj(Xi2[3:]), F11[3:])),
    ])                                                 # (6, t)

    # ---- 2nd-order potential (reference :1541-1544) ----
    # positions broadcast as (N, 1, 3) against the (t,) lane scalars
    acc_2p, p_2nd = wave_pot_2nd_order(
        w1, wv2, kk1, kk2, beta, beta, h, pos[:, None, :], g=g, rho=rho)
    acc_2p = jnp.moveaxis(acc_2p, -1, 1)               # (N, 3, t)
    f_2ndPot = (rho * v_i)[:, None, None] * _mv(Minert, acc_2p)

    # ---- convective acceleration (reference :1546-1548) ----
    conv_acc = 0.25 * (_mv4(gu1, jnp.conj(u2)) + _mv4(jnp.conj(gu2), u1))
    f_conv = (rho * v_i)[:, None, None] * _mv(Minert, conv_acc)

    # ---- Rainey axial divergence (reference :1550-1551) ----
    qq = q[:, :, None, None] * q[:, None, :, None]     # (N,3,3,1)
    dwdz1 = jnp.sum(gu1 * qq, axis=(1, 2))             # (N, 1)
    dwdz2 = jnp.sum(gu2 * qq, axis=(1, 2))             # (N, t)

    def transverse(vec):
        vq = jnp.sum(vec * qc[:, :, None], axis=1)     # (N, L)
        return vec - vq[:, None, :] * qc[:, :, None]

    u1t, u2t = transverse(u1), transverse(u2)
    nv1t, nv2t = transverse(nv1), transverse(nv2)
    axdv = 0.25 * (dwdz1[:, None, :] * jnp.conj(u2t - nv2t)
                   + jnp.conj(dwdz2)[:, None, :] * (u1t - nv1t))
    axdv = transverse(axdv)
    f_axdv = (rho * v_i)[:, None, None] * _mv(CaMat, axdv)

    # ---- body motion in the 1st-order field (reference :1553-1555) ----
    acc_nabla = 0.25 * (_mv4(gdu1, jnp.conj(dr2))
                        + _mv4(jnp.conj(gdu2), dr1))
    f_nabla = (rho * v_i)[:, None, None] * _mv(Minert, acc_nabla)

    # ---- Rainey body-rotation terms (reference :1557-1576) ----
    # transforms.skew is the reference's H-matrix (H(r) x = cross(x, r)
    # = MINUS the standard skew), so the vmapped path's -skew(v) is
    # +_skew_l(v) here
    OM1 = _skew_l(1j * w1[None, :] * Xi1[3:])          # (3, 3, 1)
    OM2 = _skew_l(1j * wv2[None, :] * Xi2[3:])         # (3, 3, t)
    vec1 = nax1[:, None, :] * qc[:, :, None]           # (N, 3, 1)
    vec2 = nax2[:, None, :] * qc[:, :, None]           # (N, 3, t)
    f_rslb = -0.25 * 2.0 * _mv(
        CaMat, _omv(OM1, jnp.conj(vec2)) + _omv(jnp.conj(OM2), vec1))
    f_rslb = (rho * v_i)[:, None, None] * f_rslb

    u1a = u1 - nv1
    u2a = u2 - nv2
    V1 = gu1 + OM1[None, :, :, :]
    V2 = gu2 + OM2[None, :, :, :]
    aux = 0.25 * (_mv4(V1, jnp.conj(_mv(CaMat, u2a)))
                  + _mv4(jnp.conj(V2), _mv(CaMat, u1a)))
    aux = aux - _mv(qMat, aux)
    f_rslb = f_rslb + (rho * v_i)[:, None, None] * aux

    u1at = u1a - _mv(qMat, u1a)
    u2at = u2a - _mv(qMat, u2a)
    aux2 = 0.25 * (_mv(CaMat, _mv4(V1, jnp.conj(u2at)))
                   + _mv(CaMat, _mv4(jnp.conj(V2), u1at)))
    f_rslb = f_rslb - (rho * v_i)[:, None, None] * aux2

    # ---- axial/end effects (reference :1578-1601) ----
    f_2ndPot = f_2ndPot + (a_i[:, None, None] * p_2nd[:, None, :]
                           * qc[:, :, None])
    f_2ndPot = f_2ndPot + (rho * v_end_ca)[:, None, None] * _mv(qMat,
                                                                acc_2p)
    f_conv = f_conv + (rho * v_end_ca)[:, None, None] * _mv(qMat,
                                                            conv_acc)
    f_nabla = f_nabla + (rho * v_end_ca)[:, None, None] * _mv(qMat,
                                                              acc_nabla)
    p_nabla = 0.25 * (jnp.sum(gp1 * jnp.conj(dr2), axis=1)
                      + jnp.sum(jnp.conj(gp2) * dr1, axis=1))  # (N, t)
    f_nabla = f_nabla + (a_i[:, None, None] * p_nabla[:, None, :]
                         * qc[:, :, None])
    p_drop = -2.0 * 0.25 * 0.5 * rho * jnp.sum(
        _mv(ptMat, u1a) * jnp.conj(_mv(CaMat, u2a)), axis=1)   # (N, t)
    f_conv = f_conv + (a_i[:, None, None] * p_drop[:, None, :]
                       * qc[:, :, None])

    # ---- wrench about the PRP, masked to submerged nodes ----
    f_side = ((f_2ndPot + f_conv + f_axdv + f_nabla + f_rslb)
              * submerged[:, None, None])
    mom = _cross1(offsets.astype(cdt)[:, :, None], f_side)
    F_side = jnp.concatenate([jnp.sum(f_side, axis=0),
                              jnp.sum(mom, axis=0)])           # (6, t)

    # ---- waterline relative-elevation terms per crossing member ----
    F_eta = jnp.zeros_like(F_side)
    if nm:
        wlc1 = wlc1_ref[:]                             # (nm, 3, 3, 1)
        wlc2 = wlc2_ref[:]                             # (nm, 3, 3, t)
        eta1 = eta1_ref[:]                             # (nm, 1)
        eta2 = eta2_ref[:]                             # (nm, t)
        wlm = wlm_ref[:]                               # (nm, 2, 3, 3)
        wlg = wlg_ref[:]                               # (nm, 4)
        for im in range(nm):
            udw1, aw1, ge1 = wlc1[im, 0], wlc1[im, 1], wlc1[im, 2]
            udw2, aw2, ge2 = wlc2[im, 0], wlc2[im, 1], wlc2[im, 2]
            er1, er2 = eta1[im], eta2[im]              # (1,), (t,)
            aA = wlg[im, 0]
            off = wlg[im, 1:4].astype(cdt)             # (3,)
            Minert_wl = wlm[im, 0]
            CaMat_wl = wlm[im, 1]
            f_eta = 0.25 * (udw1 * jnp.conj(er2)[None, :]
                            + jnp.conj(udw2) * er1[None, :])
            f_eta = rho * aA * jnp.sum(
                Minert_wl[:, :, None].astype(cdt)
                * f_eta[None, :, :], axis=1)
            a_eta = 0.25 * (aw1 * jnp.conj(er2)[None, :]
                            + jnp.conj(aw2) * er1[None, :])
            f_eta = f_eta - rho * aA * jnp.sum(
                CaMat_wl[:, :, None].astype(cdt)
                * a_eta[None, :, :], axis=1)
            f_eta = f_eta - 0.25 * rho * aA * (
                ge1 * jnp.conj(er2)[None, :]
                + jnp.conj(ge2) * er1[None, :])
            F_eta = F_eta + jnp.concatenate(
                [f_eta, _cross0(off[:, None], f_eta)])

    Q = F_rotN + F_side + F_eta                        # (6, t)
    qre_ref[:] = jnp.real(Q)[None, :, :]
    qim_ref[:] = jnp.imag(Q)[None, :, :]


# ---------------------------------------------------------------------------
# public wrapper
# ---------------------------------------------------------------------------

def qtf_pair_grid(fields: dict, beta, h, rho, g, interpret=None):
    """Evaluate the raw slender-body QTF pair grid (no Kim & Yue
    correction, no Hermitian completion — the caller applies both,
    exactly like the ``rows=`` sharded path) as one Pallas program.

    ``fields`` carries the precomputed frequency/node arrays assembled
    by ``calc_qtf_slender_body`` (see ``_kernel_fields`` there).
    Returns (nw2, nw2, 6) complex.

    ``interpret`` defaults to True on every backend: the body is
    complex-typed (see module docstring) — the knob exists so the
    future Mosaic port can flip the default per backend without an API
    change."""
    w2 = jnp.asarray(fields["w2"])
    nw2 = int(w2.shape[0])
    t = TILE_P
    Bp = -(-nw2 // t) * t
    padf = Bp - nw2
    cdt = _config.complex_dtype()
    rdt = _config.real_dtype()

    def padded(x, fill=0.0):
        """Pad the trailing (frequency) axis to the lane multiple."""
        x = jnp.asarray(x)
        if padf == 0:
            return x
        tail = jnp.broadcast_to(jnp.asarray(fill, x.dtype),
                                x.shape[:-1] + (padf,))
        return jnp.concatenate([x, tail], axis=-1)

    # frequency scalars ride as (1, Bp) rows; dead lanes carry 1.0 so
    # no division in the kernel sees a structural zero (their output
    # is sliced off)
    wrow = padded(w2.astype(rdt)[None, :], 1.0)
    krow = padded(jnp.asarray(fields["k2"], rdt)[None, :], 1.0)
    Xi = padded(jnp.asarray(fields["Xi"], cdt))
    F1st = padded(jnp.asarray(fields["F1st"], cdt))
    u_n = padded(jnp.asarray(fields["u"], cdt))
    dr_n = padded(jnp.asarray(fields["dr"], cdt))
    nodeV = padded(jnp.asarray(fields["nv"], cdt))
    nax = padded(jnp.asarray(fields["nax"], cdt))
    gu = padded(jnp.asarray(fields["gu"], cdt))
    gp = padded(jnp.asarray(fields["gp"], cdt))
    q = jnp.asarray(fields["q"], rdt)
    offsets = jnp.asarray(fields["offsets"], rdt)
    pos = jnp.asarray(fields["pos"], rdt)
    Minert = jnp.asarray(fields["Minert"], rdt)
    CaMat = jnp.asarray(fields["CaMat"], rdt)
    ptMat = jnp.asarray(fields["ptMat"], rdt)
    qMat = jnp.asarray(fields["qMat"], rdt)
    nsc = jnp.asarray(fields["nodescal"], rdt)
    N = int(q.shape[0])

    wl = fields.get("wl")
    nm = 0 if wl is None else int(np.asarray(wl["geo"]).shape[0])

    def s1(*block):
        """1-side spec: width-1 frequency block at the row index."""
        nd = len(block)
        return pl.BlockSpec(tuple(block) + (1,),
                            lambda i, j, nd=nd: (0,) * nd + (i,))

    def s2(*block):
        """2-side spec: lane-tile frequency block at the column tile."""
        nd = len(block)
        return pl.BlockSpec(tuple(block) + (t,),
                            lambda i, j, nd=nd: (0,) * nd + (j,))

    def sfull(*shape):
        nd = len(shape)
        return pl.BlockSpec(tuple(shape),
                            lambda i, j, nd=nd: (0,) * nd)

    inputs = [wrow, wrow, krow, krow,
              Xi, Xi, F1st, F1st,
              u_n, u_n, dr_n, dr_n,
              nodeV, nodeV, nax, nax,
              gu, gu, gp, gp,
              q, offsets, pos,
              Minert, CaMat, ptMat, qMat, nsc]
    in_specs = [s1(1), s2(1), s1(1), s2(1),
                s1(6), s2(6), s1(6), s2(6),
                s1(N, 3), s2(N, 3), s1(N, 3), s2(N, 3),
                s1(N, 3), s2(N, 3), s1(N), s2(N),
                s1(N, 3, 3), s2(N, 3, 3), s1(N, 3), s2(N, 3),
                sfull(N, 3), sfull(N, 3), sfull(N, 3),
                sfull(N, 3, 3), sfull(N, 3, 3), sfull(N, 3, 3),
                sfull(N, 3, 3), sfull(N, 4)]
    if nm:
        wlc = padded(jnp.asarray(wl["c"], cdt))
        eta = padded(jnp.asarray(wl["eta"], cdt))
        inputs += [wlc, wlc, eta, eta,
                   jnp.asarray(wl["mats"], rdt),
                   jnp.asarray(wl["geo"], rdt)]
        in_specs += [s1(nm, 3, 3), s2(nm, 3, 3), s1(nm), s2(nm),
                     sfull(nm, 2, 3, 3), sfull(nm, 4)]

    kern = functools.partial(_qtf_pair_kernel, nm=nm, beta=float(beta),
                             h=float(h), rho=float(rho), g=float(g))
    out_spec = pl.BlockSpec((1, 6, t), lambda i, j: (i, 0, j))
    qre, qim = pl.pallas_call(
        kern,
        grid=(nw2, Bp // t),
        in_specs=in_specs,
        out_specs=[out_spec, out_spec],
        out_shape=[jax.ShapeDtypeStruct((nw2, 6, Bp), rdt),
                   jax.ShapeDtypeStruct((nw2, 6, Bp), rdt)],
        interpret=True if interpret is None else bool(interpret),
    )(*inputs)
    Q = (qre + 1j * qim)[:, :, :nw2]                   # (nw2, 6, nw2)
    return jnp.moveaxis(Q, 1, 2).astype(cdt)           # (nw2, nw2, 6)
