"""Shared helpers for the mixed-precision solve ladder.

One module owns the numeric facts both Gauss-Jordan implementations
(the jnp graph in ``ops/linalg.py`` and the Pallas kernel in
``ops/pallas/gj_solve.py``) must agree on:

- the **equilibration underflow floor**: the row scale ``1/max|row|``
  must never divide by zero (an all-zero row is singular anyway and
  partial pivoting reports it as NaN downstream), and the floor has to
  live BELOW any physical row magnitude while staying representable in
  the width the scale is computed in.  Before the ladder this constant
  was duplicated (and dtype-switched by hand) at both call sites;
  :func:`equilibration_eps` is now the single source.

- the **factorization widths** the ladder can drop to
  (``RAFT_TPU_PRECISION_WIDTH``): f32 is the TPU-native fast path;
  bf16 shares f32's 8-bit exponent (so the same underflow floor
  applies) and is the aggressive rung for pipelines already running
  at f32.

- the **promotion predicate** (:func:`promotion_mask`): which lanes
  the full-width second pass must re-solve.  All three ladder sites
  (the plain and fused Pallas kernels, the batch-first jnp twin) share
  it, so the NaN-safety contract cannot silently diverge between them.

No jax transforms — importable from kernel modules without dragging in
the dispatch layer.
"""
from __future__ import annotations

import jax.numpy as jnp

#: factorization widths the ladder supports, by RAFT_TPU_PRECISION_WIDTH
#: value.  Key insert order is narrow->wide-ish irrelevant; lookup only.
FACTOR_WIDTHS = {
    "f32": jnp.float32,
    "bf16": jnp.bfloat16,
}


def equilibration_eps(dtype) -> float:
    """Underflow floor for the row-equilibration scale ``1/max|row|``.

    float64 has ~1e-308 of normal range: 1e-300 leaves the scale finite
    for any physical row while flooring a numerically-zero one.
    float32 and bfloat16 share the same 8-bit exponent field (min
    normal ~1.2e-38): 1e-30 is the equivalent floor with margin for the
    subsequent multiply."""
    if jnp.dtype(dtype) == jnp.float64:
        return 1e-300
    return 1e-30


def factor_dtype(width: str):
    """Resolve a ``RAFT_TPU_PRECISION_WIDTH`` name to the jnp dtype the
    ladder factorizes in; unknown names fall back to f32 (the
    conservative rung — never silently *wider* than asked)."""
    return FACTOR_WIDTHS.get(str(width).strip().lower(), jnp.float32)


def narrows(factor, solve_dtype) -> bool:
    """True when ``factor`` is a strictly lower width than the solve
    dtype — i.e. the mixed ladder has an actual low rung to drop to.
    (f32 inputs with a requested f32 factor width degenerate to the
    native solve; the dispatch records that fact.)"""
    return jnp.dtype(factor).itemsize < jnp.dtype(solve_dtype).itemsize


def promotion_mask(rn, tol):
    """Per-lane promotion predicate of the mixed ladder: ``(mask,
    promoted_count)`` for a vector of final relative residuals.

    Negated CONVERGED, not ``rn > tol``: a lane whose low-width
    elimination overflowed carries a NaN residual, and ``nan > tol``
    is False — the broken lane must promote, not slip through."""
    mask = ~(rn <= tol)
    return mask, jnp.sum(mask.astype(jnp.int32))


def width_name(dtype) -> str:
    """Short ladder name of a real dtype ("f64" / "f32" / "bf16")."""
    dt = jnp.dtype(dtype)
    if dt == jnp.float64:
        return "f64"
    if dt == jnp.dtype(jnp.bfloat16):
        return "bf16"
    if dt == jnp.float32:
        return "f32"
    return str(dt)
