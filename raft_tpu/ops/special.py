"""Special functions needed by the aero/hydro kernels, implemented in jnp.

The reference uses scipy.special (modstruve/iv in the Kaimal rotor-averaging,
reference: raft/raft_rotor.py:1216-1218; hankel1 in the MacCamy-Fuchs and
Kim&Yue kernels, raft/raft_member.py:1070-1073, 1102-1109).  scipy.special
is not jax-traceable, so the needed combinations are implemented here.

Struve-minus-Bessel differences D_nu(x) = L_nu(x) - I_nu(x) stay O(1) while
L and I grow like e^x/sqrt(x); computing them naively (as the reference
does) loses all precision for x over ~35.  Here D_0 and D_1 come from the
power-series difference (cumulative-product terms) for small x and the DLMF
11.6.2 asymptotic expansion for large x, and the nu=-2 combination used in
the rotor-averaged Kaimal spectrum comes from the exact recurrence
(DLMF 11.4.23 with I-recurrence):  D_{-2} = D_0 - (2/x) D_1 - 2/(pi x).
"""
from __future__ import annotations

import math

import numpy as np
import jax.numpy as jnp

_SERIES_K = 90
_ASYM_K = 10
_SWITCH = 18.0


def _asym_coeffs(nu: float):
    """DLMF 11.6.2: L_nu(z) - I_nu(z) ~ (1/pi) sum_k (-1)^{k+1}
    Gamma(k+1/2)/Gamma(nu+1/2-k) (z/2)^{nu-2k-1}."""
    def gamma_any(x):
        if x > 0:
            return math.gamma(x)
        return math.pi / (math.sin(math.pi * x) * math.gamma(1.0 - x))

    k = np.arange(_ASYM_K)
    c = np.array([(-1.0) ** (kk + 1) * math.gamma(kk + 0.5) / gamma_any(nu + 0.5 - kk)
                  for kk in k]) / math.pi
    p = nu - 2.0 * k - 1.0
    return c, p


_A0_C, _A0_P = _asym_coeffs(0.0)
_A1_C, _A1_P = _asym_coeffs(1.0)


def _series_diff(x, nu: int):
    """L_nu(x) - I_nu(x) by direct summation with cumulative-product terms
    (full f64 precision; used for x < _SWITCH where cancellation is mild)."""
    h = 0.5 * jnp.asarray(x, float)[..., None]
    h2 = h * h
    k = jnp.arange(_SERIES_K, dtype=float)
    # I_nu: t0 = h^nu / Gamma(nu+1); t_{k+1}/t_k = h^2/((k+1)(k+nu+1))
    tI0 = h[..., 0] ** nu / math.gamma(nu + 1.0)
    ratios_I = h2 / ((k[:-1] + 1.0) * (k[:-1] + nu + 1.0))
    tI = tI0[..., None] * jnp.concatenate(
        [jnp.ones_like(h), jnp.cumprod(ratios_I, axis=-1)], axis=-1)
    # L_nu: t0 = h^{nu+1} / (Gamma(3/2) Gamma(nu+3/2));
    # t_{k+1}/t_k = h^2/((k+3/2)(k+nu+3/2))
    tL0 = h[..., 0] ** (nu + 1) / (math.gamma(1.5) * math.gamma(nu + 1.5))
    ratios_L = h2 / ((k[:-1] + 1.5) * (k[:-1] + nu + 1.5))
    tL = tL0[..., None] * jnp.concatenate(
        [jnp.ones_like(h), jnp.cumprod(ratios_L, axis=-1)], axis=-1)
    return jnp.sum(tL - tI, axis=-1)


def _eval_asym(x, coeffs, powers):
    h = 0.5 * jnp.asarray(x, float)[..., None]
    h_safe = jnp.where(h > 0, h, 1.0)
    terms = jnp.asarray(coeffs) * jnp.exp(jnp.asarray(powers) * jnp.log(h_safe))
    return jnp.sum(terms, axis=-1)


def struve_bessel_diff_0(x):
    """D_0(x) = L_0(x) - I_0(x), elementwise, x >= 0."""
    x = jnp.asarray(x, float)
    out = jnp.where(x < _SWITCH, _series_diff(jnp.minimum(x, _SWITCH), 0),
                    _eval_asym(jnp.maximum(x, _SWITCH), _A0_C, _A0_P))
    return jnp.where(x == 0.0, -1.0, out)


def struve_bessel_diff_1(x):
    """D_1(x) = L_1(x) - I_1(x), elementwise, x >= 0.  -> -2/pi at inf."""
    x = jnp.asarray(x, float)
    out = jnp.where(x < _SWITCH, _series_diff(jnp.minimum(x, _SWITCH), 1),
                    _eval_asym(jnp.maximum(x, _SWITCH), _A1_C, _A1_P))
    return jnp.where(x == 0.0, 0.0, out)


def struve_bessel_diff_m2(x):
    """L_{-2}(x) - I_2(x) (= L_{-2} - I_{-2}), elementwise, x > 0, via the
    recurrence D_{-2} = D_0 - (2/x) D_1 - 2/(pi x)."""
    x = jnp.asarray(x, float)
    x_safe = jnp.where(x > 0, x, 1.0)
    out = (struve_bessel_diff_0(x) - (2.0 / x_safe) * struve_bessel_diff_1(x)
           - 2.0 / (jnp.pi * x_safe))
    return jnp.where(x == 0.0, 0.0, out)
