"""Special functions needed by the aero/hydro kernels, implemented in jnp.

The reference uses scipy.special (modstruve/iv in the Kaimal rotor-averaging,
reference: raft/raft_rotor.py:1216-1218; hankel1 in the MacCamy-Fuchs and
Kim&Yue kernels, raft/raft_member.py:1070-1073, 1102-1109).  scipy.special
is not jax-traceable, so the needed combinations are implemented here.

Struve-minus-Bessel differences D_nu(x) = L_nu(x) - I_nu(x) stay O(1) while
L and I grow like e^x/sqrt(x); computing them naively (as the reference
does) loses all precision for x over ~35.  Here D_0 and D_1 come from the
power-series difference (cumulative-product terms) for small x and the DLMF
11.6.2 asymptotic expansion for large x, and the nu=-2 combination used in
the rotor-averaged Kaimal spectrum comes from the exact recurrence
(DLMF 11.4.23 with I-recurrence):  D_{-2} = D_0 - (2/x) D_1 - 2/(pi x).
"""
from __future__ import annotations

import math

import numpy as np
import jax.numpy as jnp

_SERIES_K = 90
_ASYM_K = 10
_SWITCH = 18.0


def _asym_coeffs(nu: float):
    """DLMF 11.6.2: L_nu(z) - I_nu(z) ~ (1/pi) sum_k (-1)^{k+1}
    Gamma(k+1/2)/Gamma(nu+1/2-k) (z/2)^{nu-2k-1}."""
    def gamma_any(x):
        if x > 0:
            return math.gamma(x)
        return math.pi / (math.sin(math.pi * x) * math.gamma(1.0 - x))

    k = np.arange(_ASYM_K)
    c = np.array([(-1.0) ** (kk + 1) * math.gamma(kk + 0.5) / gamma_any(nu + 0.5 - kk)
                  for kk in k]) / math.pi
    p = nu - 2.0 * k - 1.0
    return c, p


_A0_C, _A0_P = _asym_coeffs(0.0)
_A1_C, _A1_P = _asym_coeffs(1.0)


def _series_diff(x, nu: int):
    """L_nu(x) - I_nu(x) by direct summation with cumulative-product terms
    (full f64 precision; used for x < _SWITCH where cancellation is mild)."""
    h = 0.5 * jnp.asarray(x, float)[..., None]
    h2 = h * h
    k = jnp.arange(_SERIES_K, dtype=float)
    # I_nu: t0 = h^nu / Gamma(nu+1); t_{k+1}/t_k = h^2/((k+1)(k+nu+1))
    tI0 = h[..., 0] ** nu / math.gamma(nu + 1.0)
    ratios_I = h2 / ((k[:-1] + 1.0) * (k[:-1] + nu + 1.0))
    tI = tI0[..., None] * jnp.concatenate(
        [jnp.ones_like(h), jnp.cumprod(ratios_I, axis=-1)], axis=-1)
    # L_nu: t0 = h^{nu+1} / (Gamma(3/2) Gamma(nu+3/2));
    # t_{k+1}/t_k = h^2/((k+3/2)(k+nu+3/2))
    tL0 = h[..., 0] ** (nu + 1) / (math.gamma(1.5) * math.gamma(nu + 1.5))
    ratios_L = h2 / ((k[:-1] + 1.5) * (k[:-1] + nu + 1.5))
    tL = tL0[..., None] * jnp.concatenate(
        [jnp.ones_like(h), jnp.cumprod(ratios_L, axis=-1)], axis=-1)
    return jnp.sum(tL - tI, axis=-1)


def _eval_asym(x, coeffs, powers):
    h = 0.5 * jnp.asarray(x, float)[..., None]
    h_safe = jnp.where(h > 0, h, 1.0)
    terms = jnp.asarray(coeffs) * jnp.exp(jnp.asarray(powers) * jnp.log(h_safe))
    return jnp.sum(terms, axis=-1)


def struve_bessel_diff_0(x):
    """D_0(x) = L_0(x) - I_0(x), elementwise, x >= 0."""
    x = jnp.asarray(x, float)
    out = jnp.where(x < _SWITCH, _series_diff(jnp.minimum(x, _SWITCH), 0),
                    _eval_asym(jnp.maximum(x, _SWITCH), _A0_C, _A0_P))
    return jnp.where(x == 0.0, -1.0, out)


def struve_bessel_diff_1(x):
    """D_1(x) = L_1(x) - I_1(x), elementwise, x >= 0.  -> -2/pi at inf."""
    x = jnp.asarray(x, float)
    out = jnp.where(x < _SWITCH, _series_diff(jnp.minimum(x, _SWITCH), 1),
                    _eval_asym(jnp.maximum(x, _SWITCH), _A1_C, _A1_P))
    return jnp.where(x == 0.0, 0.0, out)


def struve_bessel_diff_m2(x):
    """L_{-2}(x) - I_2(x) (= L_{-2} - I_{-2}), elementwise, x > 0, via the
    recurrence D_{-2} = D_0 - (2/x) D_1 - 2/(pi x)."""
    x = jnp.asarray(x, float)
    x_safe = jnp.where(x > 0, x, 1.0)
    out = (struve_bessel_diff_0(x) - (2.0 / x_safe) * struve_bessel_diff_1(x)
           - 2.0 / (jnp.pi * x_safe))
    return jnp.where(x == 0.0, 0.0, out)


# --------------------------------------------------------------------------
# Bessel Y / Hankel functions (MacCamy-Fuchs + Kim&Yue kernels; the
# reference calls scipy.special.hankel1, raft_member.py:1070-1073, 1102-1109)
# --------------------------------------------------------------------------
# J0/J1/Y0/Y1 use the Abramowitz & Stegun 9.4 rational/amplitude-phase
# approximations (|eps| < ~1.6e-8 — ample for the MCF/K&Y physics); higher
# orders: J_n from jax.scipy.special.bessel_jn (stable downward recurrence,
# machine precision), Y_n by upward recurrence (stable for Y).

def _poly(t, coeffs):
    out = jnp.zeros_like(t) + coeffs[0]
    for c in coeffs[1:]:
        out = out * t + c
    return out


def bessel_j0(x):
    x = jnp.abs(jnp.asarray(x, float))
    t = (x / 3.0) ** 2
    small = _poly(t, [0.0002100, -0.0039444, 0.0444479, -0.3163866,
                      1.2656208, -2.2499997, 1.0])
    z = 3.0 / jnp.where(x > 3.0, x, 3.0)
    f0 = _poly(z, [0.00014476, -0.00072805, 0.00137237, -0.00009512,
                   -0.00552740, -0.00000077, 0.79788456])
    th0 = x + _poly(z, [0.00013558, -0.00029333, -0.00054125, 0.00262573,
                        -0.00003954, -0.04166397, -0.78539816])
    big = f0 * jnp.cos(th0) / jnp.sqrt(jnp.where(x > 0, x, 1.0))
    return jnp.where(x <= 3.0, small, big)


def bessel_j1(x):
    x = jnp.asarray(x, float)
    ax = jnp.abs(x)
    t = (ax / 3.0) ** 2
    small = ax * _poly(t, [0.00001109, -0.00031761, 0.00443319, -0.03954289,
                           0.21093573, -0.56249985, 0.5])
    z = 3.0 / jnp.where(ax > 3.0, ax, 3.0)
    f1 = _poly(z, [-0.00020033, 0.00113653, -0.00249511, 0.00017105,
                   0.01659667, 0.00000156, 0.79788456])
    th1 = ax + _poly(z, [-0.00029166, 0.00079824, 0.00074348, -0.00637879,
                         0.00005650, 0.12499612, -2.35619449])
    big = f1 * jnp.cos(th1) / jnp.sqrt(jnp.where(ax > 0, ax, 1.0))
    return jnp.sign(x) * jnp.where(ax <= 3.0, small, big)


def bessel_y0(x):
    """Y_0(x), x > 0 (A&S 9.4.2/9.4.3)."""
    x = jnp.asarray(x, float)
    x_safe = jnp.where(x > 0, x, 1.0)
    t = (x / 3.0) ** 2
    small = (2.0 / jnp.pi) * jnp.log(0.5 * x_safe) * bessel_j0(x) + _poly(
        t, [-0.00024846, 0.00427916, -0.04261214, 0.25300117,
            -0.74350384, 0.60559366, 0.36746691])
    z = 3.0 / jnp.where(x > 3.0, x, 3.0)
    f0 = _poly(z, [0.00014476, -0.00072805, 0.00137237, -0.00009512,
                   -0.00552740, -0.00000077, 0.79788456])
    th0 = x + _poly(z, [0.00013558, -0.00029333, -0.00054125, 0.00262573,
                        -0.00003954, -0.04166397, -0.78539816])
    big = f0 * jnp.sin(th0) / jnp.sqrt(x_safe)
    return jnp.where(x <= 3.0, small, big)


def bessel_y1(x):
    """Y_1(x), x > 0 (A&S 9.4.5/9.4.6)."""
    x = jnp.asarray(x, float)
    x_safe = jnp.where(x > 0, x, 1.0)
    t = (x / 3.0) ** 2
    small = ((2.0 / jnp.pi) * x * jnp.log(0.5 * x_safe) * bessel_j1(x)
             + _poly(t, [0.0027873, -0.0400976, 0.3123951, -1.3164827,
                         2.1682709, 0.2212091, -0.6366198])) / x_safe
    z = 3.0 / jnp.where(x > 3.0, x, 3.0)
    f1 = _poly(z, [-0.00020033, 0.00113653, -0.00249511, 0.00017105,
                   0.01659667, 0.00000156, 0.79788456])
    th1 = x + _poly(z, [-0.00029166, 0.00079824, 0.00074348, -0.00637879,
                        0.00005650, 0.12499612, -2.35619449])
    big = f1 * jnp.sin(th1) / jnp.sqrt(x_safe)
    return jnp.where(x <= 3.0, small, big)


def _bessel_jn_miller(x, nmax: int):
    """J_n(x) for n = 0..nmax by Miller's normalized downward recurrence —
    overflow-safe in f32 (jax.scipy.special.bessel_jn NaNs without x64,
    which is exactly the TPU throughput mode bench.py runs in).  Accuracy
    is set by the A&S j0/j1 normalization (~1e-7)."""
    x = jnp.asarray(x, float)
    x_safe = jnp.where(x > 0, x, 1.0)
    start = nmax + 26          # > x + ~15 for the x <= ~15 range used here
    big = 1e18
    b_np1 = jnp.zeros_like(x_safe)
    b_n = jnp.full_like(x_safe, 1e-25)
    rows = {}
    for n in range(start, -1, -1):
        if n <= nmax:
            rows[n] = b_n
        b_nm1 = (2.0 * n / x_safe) * b_n - b_np1
        b_np1, b_n = b_n, b_nm1
        # renormalize before f32 overflow; rescales all collected rows too
        scale = jnp.where(jnp.abs(b_n) > big, 1.0 / big, 1.0)
        b_n = b_n * scale
        b_np1 = b_np1 * scale
        rows = {k: v * scale for k, v in rows.items()}
    b0 = rows[0]
    b1 = rows[1] if nmax >= 1 else b0
    j0, j1 = bessel_j0(x), bessel_j1(x)
    # normalize against whichever of J0/J1 is away from a zero
    use0 = jnp.abs(j0) > 0.05
    denom = jnp.where(use0, b0, jnp.where(jnp.abs(b1) > 0, b1, 1.0))
    ratio = jnp.where(use0, j0, j1) / jnp.where(denom == 0, 1.0, denom)
    return jnp.stack([rows[n] * ratio for n in range(nmax + 1)])


def hankel1_all(x, nmax: int):
    """H^(1)_n(x) = J_n(x) + i Y_n(x) for n = 0..nmax, x > 0 real.

    Returns (nmax+1, ...) complex.  J_n via jax.scipy.special.bessel_jn
    under x64 (machine precision) or the f32-safe Miller recurrence
    otherwise; Y_n by the (stable upward) recurrence
    Y_{n+1} = (2n/x) Y_n - Y_{n-1}.
    """
    import jax

    x = jnp.asarray(x, float)
    flat = x.reshape(-1)
    if jax.config.jax_enable_x64:
        from jax.scipy.special import bessel_jn
        J = bessel_jn(flat, v=nmax)                 # (nmax+1, nx)
    else:
        J = _bessel_jn_miller(flat, nmax)
    x_safe = jnp.where(flat > 0, flat, 1.0)
    # clamp the (rapidly growing) Y magnitudes below the dtype overflow so
    # downstream differences/products stay NaN-free; consumers treat huge
    # |H| via guarded reciprocals (1/|H| -> 0), which is the correct limit
    cap = 1e300 if jax.config.jax_enable_x64 else 1e18
    Ys = [bessel_y0(flat), bessel_y1(flat)]
    for n in range(1, nmax):
        Ys.append(jnp.clip((2.0 * n / x_safe) * Ys[n] - Ys[n - 1],
                           -cap, cap))
    Y = jnp.stack(Ys[:nmax + 1])
    H = (J + 1j * Y).reshape((nmax + 1,) + x.shape)
    return H


def hankel1p_all(x, nmax: int):
    """Derivatives H^(1)'_n(x) for n = 0..nmax: 0.5 (H_{n-1} - H_{n+1}),
    with H_{-1} = -H_1 (so H'_0 = -H_1)."""
    H = hankel1_all(x, nmax + 1)              # orders 0 .. nmax+1
    lower = jnp.concatenate([-H[1][None], H[:nmax]])   # H_{n-1}, n=0..nmax
    upper = H[1:nmax + 2]                              # H_{n+1}, n=0..nmax
    return 0.5 * (lower - upper)
