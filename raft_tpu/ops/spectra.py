"""Wave/wind spectra and response-statistics ops.

Reference: raft/helpers.py:581-684 (getRMS, getPSD, JONSWAP, getRAO).  All
batched over leading axes; JONSWAP's IEC-61400-3 auto-gamma branch is
reproduced exactly.
"""
from __future__ import annotations

import jax.numpy as jnp


def jonswap_gamma(Hs, Tp):
    """IEC 61400-3 recommended peak-shape parameter (reference:
    raft/helpers.py:636-643)."""
    Hs = jnp.asarray(Hs, dtype=float)
    Tp = jnp.asarray(Tp, dtype=float)
    ratio = Tp / jnp.sqrt(Hs)
    mid = jnp.exp(5.75 - 1.15 * ratio)
    return jnp.where(ratio <= 3.6, 5.0, jnp.where(ratio >= 5.0, 1.0, mid))


def jonswap(ws, Hs, Tp, gamma=None):
    """One-sided JONSWAP/PM wave PSD [m^2/(rad/s)] at frequencies ws [rad/s]
    (reference: raft/helpers.py:606-663; formula per FAST v7 / IEC 61400-3).

    ws, Hs, Tp broadcast, enabling a vmapped sea-state axis.  gamma=None
    selects the IEC auto-gamma; gamma=1 gives Pierson-Moskowitz.
    """
    ws = jnp.asarray(ws, dtype=float)
    Hs = jnp.asarray(Hs, dtype=float)
    Tp = jnp.asarray(Tp, dtype=float)
    # gamma=None or gamma=0 both select IEC auto-gamma (the reference's
    # `if not Gamma:` treats 0 as the auto sentinel, and design yamls use it)
    if gamma is None or (jnp.ndim(gamma) == 0 and not isinstance(gamma, jnp.ndarray)
                         and not gamma):
        g = jonswap_gamma(Hs, Tp)
    else:
        g = jnp.asarray(gamma, dtype=float)
    f = 0.5 / jnp.pi * ws
    fpOvrf4 = (Tp * f) ** (-4.0)
    C = 1.0 - 0.287 * jnp.log(g)
    sigma = jnp.where(f <= 1.0 / Tp, 0.07, 0.09)
    alpha = jnp.exp(-0.5 * ((f * Tp - 1.0) / sigma) ** 2)
    return (
        0.5 / jnp.pi * C * 0.3125 * Hs * Hs * fpOvrf4 / f
        * jnp.exp(-1.25 * fpOvrf4) * g**alpha
    )


def get_rms(xi, axis=None):
    """sigma = sqrt(0.5 * sum |xi|^2) over all (or given) axes (reference:
    raft/helpers.py:581-587)."""
    return jnp.sqrt(0.5 * jnp.sum(jnp.abs(xi) ** 2, axis=axis))


def get_psd(xi, dw, source_axis=None):
    """PSD = 0.5 |xi|^2 / dw, summed over an excitation-source axis if given
    (reference: raft/helpers.py:590-603)."""
    psd = 0.5 * jnp.abs(xi) ** 2 / dw
    if source_axis is not None:
        psd = jnp.sum(psd, axis=source_axis)
    return psd


def get_rao(Xi, zeta, eps=1e-6):
    """Response amplitude operator Xi/zeta with a zero-amplitude guard
    (reference: raft/helpers.py:665-684).  zeta: (nw,) along Xi's last axis."""
    zeta = jnp.asarray(zeta)
    ok = jnp.abs(zeta) > eps
    safe = jnp.where(ok, zeta, 1.0)
    return jnp.where(ok, Xi / safe, 0.0)
