"""Rigid-body frame transforms as batched jnp ops.

Covers the reference's transform kernel set (reference: raft/helpers.py:314-579
— SmallRotate, VecVecTrans, getH, rotationMatrix, translateForce3to6DOF,
transformForce, translateMatrix3to6DOF, translateMatrix6to6DOF, rotateMatrix3,
rotateMatrix6, RotFrm2Vect).  All functions here are pure, shape-polymorphic
over leading batch axes where noted, and jit/vmap-safe.  Matrix layouts use
the Sadeghi & Incecik 6-DOF block convention  [[m, J], [J^T, I]].
"""
from __future__ import annotations

import jax.numpy as jnp


def small_rotate(r, th):
    """First-order (small-angle) displacement of point ``r`` under rotation ``th``.

    r: (..., 3) real;  th: (..., 3) possibly complex rotation amplitudes.
    Returns cross(th, r) elementwise (reference: raft/helpers.py:314-326).
    """
    r = jnp.asarray(r)
    th = jnp.asarray(th)
    return jnp.stack(
        [
            -th[..., 2] * r[..., 1] + th[..., 1] * r[..., 2],
            th[..., 2] * r[..., 0] - th[..., 0] * r[..., 2],
            -th[..., 1] * r[..., 0] + th[..., 0] * r[..., 1],
        ],
        axis=-1,
    )


def vec_vec_trans(v):
    """Outer product v v^T for (...,3) vectors -> (...,3,3)."""
    v = jnp.asarray(v)
    return v[..., :, None] * v[..., None, :]


def skew(r):
    """Alternator ("H") matrix: H(r) @ x == cross(x, r) in the reference's
    sign convention (reference: raft/helpers.py:346-355).  r: (...,3)."""
    r = jnp.asarray(r)
    z = jnp.zeros_like(r[..., 0])
    return jnp.stack(
        [
            jnp.stack([z, r[..., 2], -r[..., 1]], axis=-1),
            jnp.stack([-r[..., 2], z, r[..., 0]], axis=-1),
            jnp.stack([r[..., 1], -r[..., 0], z], axis=-1),
        ],
        axis=-2,
    )


def rotation_matrix(x3, x2, x1):
    """Intrinsic z-y-x (Tait-Bryan) rotation matrix; args are the roll(x3),
    pitch(x2), yaw(x1) angles in radians, matching the reference's argument
    order (reference: raft/helpers.py:357-384).  Scalar or batched."""
    x3, x2, x1 = jnp.asarray(x3), jnp.asarray(x2), jnp.asarray(x1)
    s1, c1 = jnp.sin(x1), jnp.cos(x1)
    s2, c2 = jnp.sin(x2), jnp.cos(x2)
    s3, c3 = jnp.sin(x3), jnp.cos(x3)
    row0 = jnp.stack([c1 * c2, c1 * s2 * s3 - c3 * s1, s1 * s3 + c1 * c3 * s2], axis=-1)
    row1 = jnp.stack([c2 * s1, c1 * c3 + s1 * s2 * s3, c3 * s1 * s2 - c1 * s3], axis=-1)
    row2 = jnp.stack([-s2, c2 * s3, c2 * c3], axis=-1)
    return jnp.stack([row0, row1, row2], axis=-2)


def translate_force_3to6(F, r):
    """Force (...,3) acting at point r (...,3) -> 6-DOF wrench (...,6) about
    the origin (reference: raft/helpers.py:386-401)."""
    F = jnp.asarray(F)
    r = jnp.asarray(r)
    m = jnp.cross(jnp.broadcast_to(r, F.shape).astype(F.dtype), F)
    return jnp.concatenate([F, m], axis=-1)


def transform_force(f, offset=None, rotmat=None):
    """Rotate a 3- or 6-wrench by ``rotmat`` then shift its point of action by
    ``offset`` (reference: raft/helpers.py:404-451)."""
    f = jnp.asarray(f)
    if f.shape[-1] == 3:
        f = jnp.concatenate([f, jnp.zeros_like(f)], axis=-1)
    F, M = f[..., :3], f[..., 3:]
    if rotmat is not None:
        F = jnp.einsum("...ij,...j->...i", rotmat, F)
        M = jnp.einsum("...ij,...j->...i", rotmat, M)
    if offset is not None:
        offset = jnp.asarray(offset)
        M = M + jnp.cross(jnp.broadcast_to(offset, F.shape).astype(F.dtype), F)
    return jnp.concatenate([F, M], axis=-1)


def translate_matrix_3to6(M, r):
    """3x3 mass matrix about its CG -> 6x6 about a point offset by r
    (parallel-axis; reference: raft/helpers.py:455-478).  M: (...,3,3),
    r: (...,3) -> (...,6,6)."""
    M = jnp.asarray(M)
    H = skew(r).astype(M.dtype)
    MH = M @ H
    top = jnp.concatenate([M, MH], axis=-1)
    bot = jnp.concatenate([jnp.swapaxes(MH, -1, -2), H @ M @ jnp.swapaxes(H, -1, -2)], axis=-1)
    return jnp.concatenate([top, bot], axis=-2)


def translate_matrix_6to6(M, r):
    """6x6 mass/inertia matrix translated to a new reference point; r points
    from the new reference to the current one (reference:
    raft/helpers.py:481-503)."""
    M = jnp.asarray(M)
    H = skew(r).astype(M.dtype)
    Ht = jnp.swapaxes(H, -1, -2)
    m = M[..., :3, :3]
    J = M[..., :3, 3:]
    I = M[..., 3:, 3:]
    Jp = m @ H + J
    Ip = H @ m @ Ht + jnp.swapaxes(J, -1, -2) @ H + Ht @ J + I
    top = jnp.concatenate([m, Jp], axis=-1)
    bot = jnp.concatenate([jnp.swapaxes(Jp, -1, -2), Ip], axis=-1)
    return jnp.concatenate([top, bot], axis=-2)


def rotate_matrix_3(M, R):
    """Congruence rotation R M R^T (reference: raft/helpers.py:545-558)."""
    return R @ M @ jnp.swapaxes(R, -1, -2)


def rotate_matrix_6(M, R):
    """Blockwise rotation of a 6x6 tensor (reference: raft/helpers.py:507-542).
    Note the reference symmetrizes the off-diagonal block (lower = upper^T)
    rather than rotating it independently; we reproduce that.
    M: (...,6,6), R: (...,3,3)."""
    Rt = jnp.swapaxes(R, -1, -2)
    m = R @ M[..., :3, :3] @ Rt
    J = R @ M[..., :3, 3:] @ Rt
    I = R @ M[..., 3:, 3:] @ Rt
    top = jnp.concatenate([m, J], axis=-1)
    bot = jnp.concatenate([jnp.swapaxes(J, -1, -2), I], axis=-1)
    return jnp.concatenate([top, bot], axis=-2)


def rot_frm_2_vect(A, B):
    """Rodrigues rotation matrix taking direction A to direction B; identity
    when they are (anti)parallel (reference: raft/helpers.py:561-579)."""
    A = jnp.asarray(A, dtype=float)
    B = jnp.asarray(B, dtype=float)
    A = A / jnp.linalg.norm(A, axis=-1, keepdims=True)
    B = B / jnp.linalg.norm(B, axis=-1, keepdims=True)
    v = jnp.cross(A, B)
    v2 = jnp.sum(v * v, axis=-1)
    ssc = -skew(v)  # reference's ssc is skew-symmetric cross-product matrix of v
    dotAB = jnp.sum(A * B, axis=-1)
    # guard the v2==0 division; result replaced by identity below
    safe_v2 = jnp.where(v2 == 0.0, 1.0, v2)
    R = (
        jnp.eye(3)
        + ssc
        + (ssc @ ssc) * ((1.0 - dotAB) / safe_v2)[..., None, None]
    )
    return jnp.where((v2 == 0.0)[..., None, None], jnp.eye(3), R)
