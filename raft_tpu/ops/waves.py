"""Linear (Airy) wave kinematics and second-order wave terms as batched jnp ops.

TPU-first reimplementation of the reference wave kernel set (reference:
raft/helpers.py:66-310 — getKinematics, getWaveKin, getWaveKin_grad_u1,
getWaveKin_grad_dudt, getWaveKin_grad_pres1st, getWaveKin_axdivAcc,
getWaveKin_pot2ndOrd, waveNumber).  The reference evaluates these in
per-frequency / per-node Python loops; here every function is fully
vectorized over frequency and broadcastable over node/heading batch axes so
the whole excitation field is computed as one fused XLA program.

Conventions follow the reference: wave heading ``beta`` is in *radians* for
the first-order kinematics and in *degrees* for the gradient/second-order
kernels (the reference mixes conventions; see each docstring).  z is positive
up with the free surface at z=0; nodes above the surface produce zeros.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

_G_DEFAULT = 9.81

# same deep-water switch threshold as the reference (raft/helpers.py:133)
_KH_DEEP = 89.4


def wave_number(w, h, g=_G_DEFAULT, tol=1e-3):
    """Solve the linear dispersion relation w^2 = g k tanh(k h) for k.

    Replicates the reference's fixed-point iteration *including its early
    stopping* (reference: raft/helpers.py:295-310): each frequency iterates
    k <- w^2/(g tanh(k h)) from the deep-water seed until the relative change
    drops below ``tol``, independently per element (converged elements are
    frozen so results match the serial reference bit-for-bit up to fp
    reassociation).

    w: (...,) rad/s (w=0 returns k=0);  h: scalar depth [m].
    """
    w = jnp.asarray(w, dtype=float)
    w2g = w * w / g
    k1 = w2g  # deep-water seed
    k2 = w2g / jnp.tanh(jnp.maximum(k1, 1e-300) * h)
    done = jnp.abs(k2 - k1) / jnp.maximum(k1, 1e-300) <= tol

    def cond(state):
        _, _, done = state
        return ~jnp.all(done)

    def body(state):
        k1, k2, done = state
        k1n = jnp.where(done, k1, k2)
        k2n = jnp.where(done, k2, w2g / jnp.tanh(jnp.maximum(k1n, 1e-300) * h))
        done_n = done | (jnp.abs(k2n - k1n) / jnp.maximum(k1n, 1e-300) <= tol)
        return k1n, k2n, done_n

    _, k2, _ = lax.while_loop(cond, body, (k1, k2, done))
    return jnp.where(w == 0.0, 0.0, k2)


def _depth_ratios(k, z, h):
    """The three hyperbolic depth-attenuation ratios, numerically safe.

    Returns (sinh(k(z+h))/sinh(kh), cosh(k(z+h))/sinh(kh),
    cosh(k(z+h))/cosh(kh)) with the reference's deep-water switchover at
    k h > 89.4 (reference: raft/helpers.py:126-140).  Shapes broadcast.
    """
    kh = k * h
    kh_safe = jnp.minimum(kh, _KH_DEEP)  # keep cosh/sinh finite in dead branch
    kzh = jnp.minimum(k * (z + h), _KH_DEEP)
    shallow_s = jnp.sinh(kzh) / jnp.sinh(kh_safe)
    shallow_c = jnp.cosh(kzh) / jnp.sinh(kh_safe)
    shallow_cc = jnp.cosh(kzh) / jnp.cosh(kh_safe)
    deep = jnp.exp(k * z)
    deep_cc = deep + jnp.exp(-k * (z + 2.0 * h))
    use_deep = kh > _KH_DEEP
    s_ratio = jnp.where(use_deep, deep, shallow_s)
    c_ratio = jnp.where(use_deep, deep, shallow_c)
    cc_ratio = jnp.where(use_deep, deep_cc, shallow_cc)
    # k == 0 limit as in the reference (raft/helpers.py:128-132)
    s_ratio = jnp.where(k == 0.0, 1.0, s_ratio)
    c_ratio = jnp.where(k == 0.0, 99999.0, c_ratio)
    cc_ratio = jnp.where(k == 0.0, 99999.0, cc_ratio)
    return s_ratio, c_ratio, cc_ratio


def wave_kinematics(zeta0, beta, w, k, h, r, rho=1025.0, g=_G_DEFAULT):
    """First-order wave kinematics at point(s) r from an elevation spectrum.

    Vectorized equivalent of the reference's per-frequency loop (reference:
    raft/helpers.py:105-154).

    Parameters
    ----------
    zeta0 : (nw,) complex wave elevation amplitudes at the origin
    beta : wave heading [rad] (scalar)
    w, k : (nw,) frequencies [rad/s] and wave numbers [1/m]
    h : water depth [m]
    r : (..., 3) node position(s); any leading batch shape
    Returns (u, ud, pDyn): velocities (...,3,nw), accelerations (...,3,nw),
    dynamic pressure (...,nw); zero above the waterline.
    """
    zeta0 = jnp.asarray(zeta0)
    r = jnp.asarray(r, dtype=float)
    batch = r.shape[:-1]
    x, y, z = r[..., 0], r[..., 1], r[..., 2]
    cosb, sinb = jnp.cos(beta), jnp.sin(beta)
    # phase shift to node location: (..., nw)
    phase = jnp.exp(-1j * k * (cosb * x + sinb * y)[..., None])
    zeta = zeta0 * phase
    s_r, c_r, cc_r = _depth_ratios(k, z[..., None], h)
    wet = (z <= 0.0)[..., None]
    u = jnp.stack(
        [
            w * zeta * c_r * cosb,
            w * zeta * c_r * sinb,
            1j * w * zeta * s_r,
        ],
        axis=len(batch),
    )
    u = jnp.where(wet[..., None, :], u, 0.0)
    ud = 1j * w * u
    pDyn = jnp.where(wet, rho * g * zeta * cc_r, 0.0)
    return u, ud, pDyn


def kinematics_from_motion(r, Xi, w):
    """Node displacement/velocity/acceleration amplitudes from 6-DOF platform
    motion Xi (6, nw) at offset r (...,3) from the PRP (reference:
    raft/helpers.py:66-101).  Returns (dr, v, a), each (...,3,nw)."""
    Xi = jnp.asarray(Xi)
    r = jnp.asarray(r, dtype=float)
    trans = Xi[..., :3, :]  # (...,3,nw)
    rot = Xi[..., 3:, :]
    # small-angle cross term: cross(th, r) per frequency
    rx = r[..., :, None]
    disp_rot = jnp.stack(
        [
            -rot[..., 2, :] * rx[..., 1, :] + rot[..., 1, :] * rx[..., 2, :],
            rot[..., 2, :] * rx[..., 0, :] - rot[..., 0, :] * rx[..., 2, :],
            -rot[..., 1, :] * rx[..., 0, :] + rot[..., 0, :] * rx[..., 1, :],
        ],
        axis=-2,
    )
    dr = trans + disp_rot
    v = 1j * w * dr
    a = 1j * w * v
    return dr, v, a


def _grad_ratios_deg(k, z, h, denom_sinh=True):
    """Depth ratios for the gradient kernels, which use a k*h >= 10 deep-water
    switch (reference: raft/helpers.py:168-175, 213-220)."""
    kh = k * h
    kh_safe = jnp.minimum(kh, _KH_DEEP)
    kzh = jnp.minimum(k * (z + h), _KH_DEEP)
    den = jnp.sinh(kh_safe) if denom_sinh else jnp.cosh(kh_safe)
    shallow_xy = jnp.cosh(kzh) / den
    shallow_z = jnp.sinh(kzh) / den
    deep = jnp.exp(k * z)
    use_deep = kh >= 10.0
    return jnp.where(use_deep, deep, shallow_xy), jnp.where(use_deep, deep, shallow_z)


def wave_vel_gradient(w, k, beta, h, r):
    """Spatial gradient matrix of first-order wave velocity, (...,3,3).

    Reference: raft/helpers.py:157-195, with ``beta`` in RADIANS used
    consistently for both the directional factors and the phase.  (The
    reference's QTF engine passes radians into a kernel that applies
    deg2rad to them for the direction factors only — a mixed-units
    inconsistency that vanishes at beta=0, the only heading its examples
    exercise.  We use one convention throughout instead.)
    """
    r = jnp.asarray(r, dtype=float)
    x, y, z = r[..., 0], r[..., 1], r[..., 2]
    cosB, sinB = jnp.cos(beta), jnp.sin(beta)
    khz_xy, khz_z = _grad_ratios_deg(k, z, h, denom_sinh=True)
    phase = jnp.exp(-1j * (k * (cosB * x + sinB * y)))
    aux_x = w * cosB * phase
    aux_y = w * sinB * phase
    aux_z = 1j * w * phase
    zero = jnp.zeros_like(phase)
    g00 = -1j * aux_x * khz_xy * k * cosB
    g01 = -1j * aux_x * khz_xy * k * sinB
    g02 = aux_x * k * khz_z
    g11 = -1j * aux_y * khz_xy * k * sinB
    g12 = aux_y * k * khz_z
    g22 = aux_z * k * khz_xy
    # the velocity-gradient tensor of an irrotational field is symmetric:
    # dw/dx = du/dz (g02) and dw/dy = dv/dz (g12).  (The reference instead
    # fills grad[2][1] with du/dy — a copy-paste quirk, raft/helpers.py:192
    # — which is zero at beta=0, the only heading its examples use.)
    grad = jnp.stack(
        [
            jnp.stack([g00, g01, g02], axis=-1),
            jnp.stack([g01, g11, g12], axis=-1),
            jnp.stack([g02, g12, g22], axis=-1),
        ],
        axis=-2,
    )
    active = ((z <= 0.0) & (k > 0.0))[..., None, None]
    return jnp.where(active, grad, jnp.zeros_like(zero)[..., None, None])


def wave_acc_gradient(w, k, beta, h, r):
    """Gradient of first-order wave acceleration (reference:
    raft/helpers.py:198-199).  ``beta`` in radians."""
    return 1j * w * wave_vel_gradient(w, k, beta, h, r)


def wave_pres1st_gradient(k, beta, h, r, rho=1025.0, g=_G_DEFAULT):
    """Gradient of first-order dynamic pressure, (...,3) (reference:
    raft/helpers.py:202-225).  ``beta`` in radians (see wave_vel_gradient
    on the reference's mixed-units convention)."""
    r = jnp.asarray(r, dtype=float)
    x, y, z = r[..., 0], r[..., 1], r[..., 2]
    cosB, sinB = jnp.cos(beta), jnp.sin(beta)
    khz_xy, khz_z = _grad_ratios_deg(k, z, h, denom_sinh=False)
    phase = jnp.exp(-1j * (k * (cosB * x + sinB * y)))
    gx = rho * g * khz_xy * phase * (-1j * k * cosB)
    gy = rho * g * khz_xy * phase * (-1j * k * sinB)
    gz = rho * g * khz_z * phase * k
    grad = jnp.stack([gx, gy, gz], axis=-1)
    active = ((z <= 0.0) & (k > 0.0))[..., None]
    return jnp.where(active, grad, 0.0)


def wave_pot_2nd_order(w1, w2, k1, k2, beta1, beta2, h, r,
                       g=_G_DEFAULT, rho=1025.0):
    """Acceleration and pressure from the difference-frequency second-order
    potential for a bichromatic pair (reference: raft/helpers.py:254-291).
    ``beta1``/``beta2`` in radians.

    All of w1,w2,k1,k2 broadcast; r is (...,3).  Returns (acc (...,3), p).
    Zero when w1==w2 (no mean-drift contribution from the 2nd-order
    potential), above water, or at k<=0.
    """
    r = jnp.asarray(r, dtype=float)
    z = r[..., 2]
    dkx = k1 * jnp.cos(beta1) - k2 * jnp.cos(beta2)
    dky = k1 * jnp.sin(beta1) - k2 * jnp.sin(beta2)
    nk = jnp.sqrt(dkx * dkx + dky * dky)
    dw = w1 - w2
    # gamma factors; guard divisions (dead values masked at the end)
    th1, th2, thn = jnp.tanh(k1 * h), jnp.tanh(k2 * h), jnp.tanh(nk * h)
    den12 = dw * dw / g - nk * thn
    den12 = jnp.where(den12 == 0.0, 1.0, den12)
    g12 = (-1j * g / (2 * w1)) * ((k1**2) * (1 - th1**2) - 2 * k1 * k2 * (1 + th1 * th2)) / den12
    g21 = (-1j * g / (2 * w2)) * ((k2**2) * (1 - th2**2) - 2 * k2 * k1 * (1 + th2 * th1)) / den12
    aux = 0.5 * (g21 + jnp.conj(g12))
    nkh = jnp.minimum(nk * h, _KH_DEEP)
    nkzh = jnp.minimum(nk * (z + h), _KH_DEEP)
    khz_xy = jnp.cosh(nkzh) / jnp.cosh(nkh)
    khz_z = jnp.sinh(nkzh) / jnp.cosh(nkh)
    phase = jnp.exp(-1j * (dkx * r[..., 0] + dky * r[..., 1]))
    ax = aux * khz_xy * phase * dw * dkx
    ay = aux * khz_xy * phase * dw * dky
    az = aux * khz_z * phase * 1j * dw * nk
    p = aux * khz_xy * phase * (-1j) * rho * dw
    acc = jnp.stack([ax, ay, az], axis=-1)
    active = (z <= 0.0) & (k1 > 0.0) & (k2 > 0.0) & (w1 != w2)
    acc = jnp.where(active[..., None], acc, 0.0)
    p = jnp.where(active, p, 0.0)
    return acc, p
