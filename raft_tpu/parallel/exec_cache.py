"""Persistent executable cache for the AOT-compiled sweep programs.

``sweep_cases`` / ``sweep_variants`` trace, lower and compile one large
batched program per (model, batch-shape, dtype, mesh) combination — tens
of seconds of host work that is bitwise-identical across runs of the
same model.  This module serializes the exported program (via
``jax.export``) keyed by a content digest of the model pytree (computed
with the PR-2 ledger digest machinery) plus the shape/dtype/environment
facts and — for sharded programs — the FULL ordered mesh topology
(axis names + sizes + process span, ``partition.mesh_facts``) and the
partition-rule fingerprint (``partition.rules_fingerprint``): a
``(2,4)`` ``(cases,freq)`` program is never served for a ``(2,4)``
``(variants,cases)`` request, and editing a partition rule invalidates
every program it shaped.  A warm-start process skips the
``sweep_lower`` and ``sweep_compile`` phases entirely; the XLA compile
that remains inside
the deserialized call is served by JAX's persistent compilation cache
(enabled in ``_config.py``).

Opt-in: set ``RAFT_TPU_EXEC_CACHE=1`` (cache under
``~/.cache/raft_tpu/executables``) or point ``RAFT_TPU_EXEC_CACHE_DIR``
at a directory; ``RAFT_TPU_EXEC_CACHE=0`` forces it off.  Every lookup/
store outcome is counted in-process (:func:`stats`), recorded in the
``raft_exec_cache_events_total`` Prometheus counter, and embedded in the
entry point's run manifest (``extra["exec_cache"]``).

Keys include the git SHA (+dirty flag), jax version, backend, and x64
flag, so a code change invalidates the cache rather than serving a stale
executable.  Failures are never fatal — any error falls back to the
normal lower/compile path and is counted as ``error``.
"""
from __future__ import annotations

import dataclasses
import json
import os
import threading

import numpy as np

from raft_tpu.obs.ledger import digest_metrics

_LOCK = threading.Lock()
_STATS = {"hits": 0, "misses": 0, "stores": 0, "errors": 0, "corrupts": 0}

#: in-process memo of deserialized executables (key -> exe) for callers
#: that re-enter the same program many times per process — the serving
#: loop's warm path (raft_tpu/serve) deserializes ONCE and then every
#: batch is a pure ``exe.call``.  Opt-in per load (``memo=True``):
#: sweep_cases keeps the plain read-validate-deserialize path so the
#: corrupt-entry machinery stays exercised per call.  Bounded FIFO.
_MEMO_LOCK = threading.Lock()
_MEMO: dict[str, object] = {}
_MEMO_MAX = 8


def reset_memo():
    """Drop every memoized executable (test isolation)."""
    with _MEMO_LOCK:
        _MEMO.clear()

#: failure types a deserialized-executable call can legitimately raise
#: (deserialization drift past the key, XLA runtime errors incl.
#: jaxlib's XlaRuntimeError — a RuntimeError subclass — and truncated
#: payloads); anything outside this tuple is a bug and must propagate.
#: Single source of truth for every cached-``exe.call`` except clause
#: (sweep_cases, sweep_variants).
CALL_ERRORS = (RuntimeError, ValueError, TypeError, KeyError, OSError)


def enabled() -> bool:
    """Cache active?  ``RAFT_TPU_EXEC_CACHE`` 1/0 wins; default: on iff
    ``RAFT_TPU_EXEC_CACHE_DIR`` names a directory."""
    v = os.environ.get("RAFT_TPU_EXEC_CACHE", "auto").strip().lower()
    if v in ("0", "off", "false"):
        return False
    if v in ("1", "on", "true"):
        return True
    return bool(os.environ.get("RAFT_TPU_EXEC_CACHE_DIR"))


def cache_dir() -> str:
    return (os.environ.get("RAFT_TPU_EXEC_CACHE_DIR")
            or os.path.join(os.path.expanduser("~"), ".cache", "raft_tpu",
                            "executables"))


#: NamedTuple node types already registered with ``jax.export``'s
#: PyTreeDef serde registry (re-registration raises, so memoized here)
_EXPORT_TYPES: set = set()


def register_export_types(tree) -> int:
    """Register every NamedTuple pytree node type reachable in ``tree``
    for ``jax.export`` PyTreeDef (de)serialization, idempotently.

    ``jax.export`` refuses to serialize a program whose example args
    contain an unregistered container type — optax optimizer states
    (``ScaleByAdamState`` & co) being the canonical offenders, which
    silently demoted every optimize-program store to an ``error`` and
    every warm-process descent to a full recompile.  The serialized
    name is derived from the type's module + qualname, so the store-ing
    and load-ing processes agree without coordination.  Returns the
    number of newly registered types; never raises (an unregisterable
    type just falls through to export's own error, counted as usual)."""
    from jax import export as jexport

    new = 0

    def _walk(node):
        nonlocal new
        t = type(node)
        if isinstance(node, tuple) and hasattr(t, "_fields"):
            with _LOCK:
                fresh = t not in _EXPORT_TYPES
                if fresh:
                    _EXPORT_TYPES.add(t)
            if fresh:
                try:
                    jexport.register_namedtuple_serialization(
                        t, serialized_name=(
                            f"{t.__module__}.{t.__qualname__}"))
                    new += 1
                # already registered elsewhere (same name): fine
                except Exception:  # raftlint: disable=RTL004
                    pass
        if isinstance(node, (list, tuple)):
            for c in node:
                _walk(c)
        elif isinstance(node, dict):
            for c in node.values():
                _walk(c)

    _walk(tree)
    return new


def stats() -> dict:
    with _LOCK:
        return dict(_STATS)


def reset_stats():
    with _LOCK:
        for k in _STATS:
            _STATS[k] = 0


def _count(event: str):
    key = event + ("es" if event.endswith("s") else "s")
    with _LOCK:
        _STATS[key] = _STATS.get(key, 0) + 1
    try:
        from raft_tpu import obs
        obs.record_exec_cache_event(event)
    # metric emission must never fail the cache layer (obs contract)
    except Exception:  # pragma: no cover  # raftlint: disable=RTL004
        pass


# ---------------------------------------------------------------------------
# content digests and keys
# ---------------------------------------------------------------------------

def _flatten(obj, path, out):
    """Recursive walk of a model object into {path: scalar|1-D array}
    for the ledger digest machinery — arrays by value, dataclasses by
    field, callables by qualified name (never by repr, which would embed
    a memory address and break digest stability)."""
    if obj is None or isinstance(obj, (bool, int, float, str)):
        out[path] = "None" if obj is None else obj
    elif callable(obj) and not hasattr(obj, "__array__"):
        out[path] = f"callable:{getattr(obj, '__qualname__', type(obj).__name__)}"
    elif dataclasses.is_dataclass(obj) and not isinstance(obj, type):
        for f in dataclasses.fields(obj):
            _flatten(getattr(obj, f.name), f"{path}.{f.name}", out)
    elif isinstance(obj, dict):
        for k in sorted(obj, key=str):
            _flatten(obj[k], f"{path}[{k}]", out)
    elif isinstance(obj, (list, tuple)):
        for i, v in enumerate(obj):
            _flatten(v, f"{path}[{i}]", out)
    elif hasattr(obj, "__array__"):
        arr = np.asarray(obj)
        out[path] = arr.ravel()
        out[path + ".meta"] = f"{arr.shape}:{arr.dtype}"
    else:
        out[path] = f"{type(obj).__module__}.{type(obj).__qualname__}"


def model_digest(obj) -> str:
    """Content digest of a model pytree (FOWTModel, theta dict, ...):
    ``sha256:<hex>`` over every array leaf by value — the ledger-style
    content address that keys the executable cache."""
    flat: dict = {}
    _flatten(obj, "", flat)
    return digest_metrics(flat)


def layout_digest(xy, D=None) -> str:
    """Short content digest of a farm layout — the (n,2) turbine
    positions (plus rotor diameters when given), rounded to the
    millimeter so host float noise can't fork cache identities.  Carried
    in farm exec-cache keys and salted into farm serve rdigests."""
    # f64 on purpose: the digest must not fork with the precision mode
    xy = np.round(np.asarray(xy, dtype=np.float64), 3)  # raftlint: disable=RTL003
    flat: dict = {"xy": xy}
    if D is not None:
        flat["D"] = np.round(np.asarray(D, dtype=np.float64), 3)  # raftlint: disable=RTL003
    return digest_metrics(flat)[7:][:16]


def _env_facts() -> dict:
    import jax

    import raft_tpu
    from raft_tpu import _config
    from raft_tpu.obs.manifest import git_dirty, git_sha

    sha = git_sha() or "unknown"
    if git_dirty():
        sha += "+dirty"
    return {"jax": jax.__version__,
            "backend": jax.default_backend(),
            "x64": bool(jax.config.jax_enable_x64),
            # the solve path is baked into the exported program — an
            # executable traced under one RAFT_TPU_PALLAS mode must not
            # be served under another
            "pallas": _config.pallas_mode(),
            # the precision ladder is likewise baked in at trace time: a
            # mixed-ladder program must never be served for an f64
            # request (nor across factor widths / promotion tolerances)
            "precision": _config.precision_mode(),
            "precision_width": _config.precision_width(),
            "precision_tol": _config.precision_tol(),
            "raft": getattr(raft_tpu, "__version__", "unknown"),
            "git": sha}


def make_key(**facts) -> str:
    """Cache key: sha256 over the canonical JSON of the caller's facts
    (model digest, nw, batch shape, dtypes, mesh shape, solver config)
    merged with the environment facts (git SHA, jax version, backend,
    x64) that must invalidate stale executables."""
    payload = {"env": _env_facts(), **facts}
    return digest_metrics({"key": json.dumps(payload, sort_keys=True,
                                             default=str)})[7:][:32]


# ---------------------------------------------------------------------------
# load / store
# ---------------------------------------------------------------------------

def _paths(key: str) -> tuple[str, str]:
    d = cache_dir()
    return os.path.join(d, key + ".bin"), os.path.join(d, key + ".json")


def _purge(key: str):
    """Delete a corrupt entry's artifact pair (never raises)."""
    for path in _paths(key):
        try:
            os.remove(path)
        except OSError:
            pass


_PRIMED = False


def _prime_custom_calls():
    """Force-register the CPU LAPACK custom-call targets before any
    deserialized executable runs.

    jaxlib registers its CPU solver custom calls lazily, on the first
    in-process *lowering* of a linalg op.  A warm-start process that
    only ever calls a deserialized export never lowers one, and the
    program's ``lapack_*gesv``-style custom call hits an unregistered
    target — a hard SIGSEGV at ``exe.call`` (observed with jax 0.4.37
    on CPU: the identical call succeeds after any in-process
    ``jit(jnp.linalg.solve)``).  One tiny real+complex solve per
    process closes the hole for every cached program."""
    global _PRIMED
    if _PRIMED:
        return
    try:
        import jax
        import jax.numpy as jnp

        solve = jax.jit(jnp.linalg.solve)
        for dt in (float, complex):
            jax.block_until_ready(solve(jnp.eye(3, dtype=dt),
                                        jnp.ones(3, dtype=dt)))
    # priming is a best-effort safety net — a backend without these
    # ops must not turn every cache load into a failure
    except Exception:  # raftlint: disable=RTL004
        pass
    _PRIMED = True


def load(key: str, memo: bool = False):
    """Deserialize the cached executable for ``key``; None on miss.

    Entries are validated BEFORE deserialization against the size and
    content digest recorded in the meta sidecar at store time — a
    truncated/bit-rotted entry is deleted and counted as ``corrupt``
    (one more miss next time, never a runtime error at ``exe.call``).
    Deserialization failures of a digest-valid entry (e.g. a jax
    version change that slipped past the key) still count as ``error``
    and also purge the entry.

    ``memo=True`` additionally consults/feeds the in-process executable
    memo: a repeat load of the same key returns the already-deserialized
    program without touching disk (counted as a ``hit``) — the serving
    loop's warm path."""
    import hashlib

    from jax import export as jexport

    from raft_tpu.testing import faults

    if memo:
        with _MEMO_LOCK:
            exe = _MEMO.get(key)
        if exe is not None:
            _count("hit")
            return exe
    bin_path, _ = _paths(key)
    try:
        with open(bin_path, "rb") as f:
            data = f.read()
    except OSError:
        _count("miss")
        return None
    data = faults.corrupt_bytes("exec_cache", data)
    meta = load_meta(key) or {}
    want_bytes = meta.get("bytes")
    want_digest = meta.get("sha256")
    if ((want_bytes is not None and want_bytes != len(data))
            or (want_digest is not None
                and want_digest != hashlib.sha256(data).hexdigest())):
        _count("corrupt")
        _purge(key)
        return None
    _prime_custom_calls()
    try:
        exe = jexport.deserialize(bytearray(data))
    # jax.export deserialization raises arbitrary types on drifted/
    # corrupt payloads; delete-and-miss IS the documented recovery
    # (errors.CacheCorruption) — strictness lives at the caller
    except Exception:  # raftlint: disable=RTL004
        _count("error")
        _purge(key)
        return None
    _count("hit")
    if memo:
        with _MEMO_LOCK:
            if len(_MEMO) >= _MEMO_MAX:
                _MEMO.pop(next(iter(_MEMO)))
            _MEMO[key] = exe
    return exe


def store(fn_jitted, args, key: str, meta: dict = None) -> str | None:
    """Export ``fn_jitted`` at ``args`` and persist it (plus a JSON meta
    sidecar) under ``key``.  Returns the written path, or None when the
    export/serialize/write failed (never raises).

    ``jax.export.export`` re-traces/lowers the program the caller just
    lowered for compilation; jax's internal jaxpr/lowering caches
    absorb most of that (measured ~1.4 s store vs ~4 s first lower on
    the coarse OC3 sweep), and it only runs on the miss path, inside
    the caller's ``*_cache_store`` span where it stays visible.

    The meta sidecar records the payload size and sha256 so ``load``
    can reject a truncated/corrupt entry before deserializing it."""
    import hashlib

    from jax import export as jexport

    bin_path, meta_path = _paths(key)
    try:
        from raft_tpu.testing import faults
        if faults.fire_info("exec_cache", action="enospc") is not None:
            import errno as _errno
            raise OSError(_errno.ENOSPC, "injected ENOSPC (fault)")
        exported = jexport.export(fn_jitted)(*args)
        data = bytes(exported.serialize())
        os.makedirs(cache_dir(), exist_ok=True)
        tmp = bin_path + ".tmp"
        with open(tmp, "wb") as f:
            f.write(data)
        os.replace(tmp, bin_path)
        doc = {"key": key, "bytes": len(data),
               "sha256": hashlib.sha256(data).hexdigest(), **(meta or {})}
        tmp = meta_path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(doc, f, indent=1, default=str)
        os.replace(tmp, meta_path)
    # the store is best-effort: an unwritable/full cache dir must not
    # take down the solve that just compiled successfully.  A PROVEN
    # full disk additionally emits the storage_degraded signal the
    # ENOSPC dashboards key on — the cache never sheds (every store is
    # already optional), it just becomes visible
    except Exception as e:  # raftlint: disable=RTL004
        _count("error")
        try:
            from raft_tpu.serve.checkpoint import is_enospc
            if is_enospc(e):
                from raft_tpu import obs
                obs.events.emit("storage_degraded",
                                component="exec_cache")
        except Exception:  # pragma: no cover  # raftlint: disable=RTL004
            pass
        return None
    _count("store")
    return bin_path


def load_meta(key: str) -> dict | None:
    """The JSON meta sidecar written next to a stored executable."""
    _, meta_path = _paths(key)
    try:
        with open(meta_path) as f:
            return json.load(f)
    except (OSError, json.JSONDecodeError):
        return None
